"""TPU tensor-search engine: vmapped BFS over a frontier of packed states.

This is the component the whole rebuild points at (SURVEY §0, §8,
BASELINE.json): the reference's explicit-state model checker
(framework/tst/.../search/Search.java:405-505 — one thread pops one state,
clones one node, runs one reflective handler) becomes a data-parallel XLA
program:

  frontier [N, ...]  --(enumerate events x vmapped transition)-->
  successors [N*E, ...] --(canonicalise + 128-bit fingerprint)-->
  dedup (device sort-unique prefilter + device-resident visited hash
  table, dslabs_tpu/tpu/visited.py) --> frontier'

The whole wave cycle — expand, in-chunk sort-unique, visited-table
insert, frontier compaction — stays on device: the carry (visited table
+ frontier) rides ``jax.jit(..., donate_argnums=0)`` so the table is
updated in place, per-wave host transfers are SCALARS only (counters +
flag counts; never ``[N, 4]`` fingerprint pulls), and the loop is
double-buffered (wave k+1 dispatches before wave k's scalars are read).
The original host-side ``sorted_member`` loop survives as
:meth:`TensorSearch.run_host` — the parity oracle for tests and the
trace-recording path (per-level event spills are host-side by nature).

Checker semantics reproduced exactly (SURVEY §7):
  * the network is a SET of fixed-width message records, kept in canonical
    sorted order (Java hashes unordered sets; canonical order makes equal
    states hash equal — SURVEY §8.1 "canonicalization matters");
    delivery never removes a message (SearchState.java:300);
  * per-node timer queues keep insertion order; a timer is deliverable iff
    no earlier-queued timer t' has t.min >= t'.max (TimerQueue.java:66-105),
    computed as a vectorised prefix-min; firing removes the timer;
  * dedup happens on successor generation, pre-check (Search.java:485);
    equivalence keys on (node lanes, network set, timer queues, exception
    lane) via a 128-bit fingerprint (hash compaction; collision odds
    ~n^2 / 2^128);
  * guard failures in a tensor twin set a terminal per-state exception code
    that participates in equivalence (SearchState.java:594-596, SURVEY
    §8.4.7) and ends the search with EXCEPTION_THROWN (checkState order:
    exception strictly first, Search.java:162-231).

All device arithmetic is int32/uint32 — TPUs have no native int64 and the
round-1 bench crashed inside the x64-emulated fingerprint path.  The two
64-bit fingerprints live on device as paired uint32 lanes `[N, 4]`
(a_hi, a_lo, b_hi, b_lo); only host-side NumPy packs them into uint64 for
the sorted visited set.  Capacity overflow (network set or timer queue) is
counted on device and surfaced as a loud ``CapacityOverflow`` error rather
than silently corrupting state counts (SURVEY §8.4.2).

The engine is protocol-agnostic: a :class:`TensorProtocol` supplies packed
node-state lanes and a pure ``step(state, event)`` transition; the engine
owns event enumeration, network-set insertion, canonicalisation,
fingerprinting, dedup, predicate checks, and frontier compaction.  Multi-
chip scaling shards the frontier over a mesh and exchanges successor
fingerprints by hash ownership (see ``dslabs_tpu/tpu/sharded.py``).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dslabs_tpu.tpu import visited as visited_mod

__all__ = ["TensorProtocol", "TensorState", "TensorSearch", "SearchOutcome",
           "CapacityOverflow", "SENTINEL", "drop_pending_messages",
           "device_get"]


def _visited_warn() -> float:
    from dslabs_tpu.tpu.spill import visited_warn_threshold

    return visited_warn_threshold()


def device_get(x) -> np.ndarray:
    """The device->host readback funnel for the device-resident run loop.

    Every transfer the wave loop performs goes through here so tests can
    instrument it (monkeypatch) and assert the per-wave transfer
    contract: scalars/short stat vectors only — never state rows or
    ``[N, 4]`` fingerprint batches."""
    return np.asarray(x)

# Empty slots in the network / timer arrays hold SENTINEL in every lane, so
# they sort after every real record and hash consistently.
SENTINEL = np.int32(2 ** 31 - 1)


def drop_pending_messages(state: dict) -> dict:
    """The staged-search ``dropPendingMessages`` analog
    (SearchState.java:534-561): a copy of the state with an empty network
    (timers survive, so retry timers re-drive the protocol)."""
    return {**state, "net": jnp.full_like(jnp.asarray(state["net"]),
                                          SENTINEL)}


class CapacityOverflow(RuntimeError):
    """A fixed-capacity structure (network set / timer queue) overflowed.

    The reference's structures are unbounded; the tensor twin's are sized
    per protocol.  Overflow would silently corrupt verdicts and state
    counts, so the engine counts drops on device and aborts loudly
    (SURVEY §8.4.2 "fail loudly on bound overflow")."""


# --------------------------------------------------------------------- state

class TensorState(Dict[str, jnp.ndarray]):
    """A batch of packed search states (struct-of-arrays pytree):

    nodes  [N, NW]            int32 — all nodes' packed protocol fields
    net    [N, NET_CAP, MW]   int32 — canonical-sorted message set
    timers [N, NN, T_CAP, TW] int32 — per-node timer queues, insertion order
                                      (lane 0 = tag, lane 1 = min, lane 2 =
                                      max, rest payload)
    exc    [N]                int32 — terminal exception code (0 = none)
    """


@dataclasses.dataclass(frozen=True)
class TensorProtocol:
    """Contract a tensorised protocol twin fulfils.

    The transition functions operate on ONE state (the engine vmaps them):

    ``step_message(nodes, msg) -> (nodes', sends, new_timers[, exc])``
    ``step_timer(nodes, node_idx, timer) -> (nodes', sends, new_timers[, exc])``

    where ``sends`` is ``[MAX_SENDS, MW]`` with invalid rows = SENTINEL,
    ``new_timers`` is ``[MAX_SETS, 1 + TW]`` (leading lane = target node
    index, SENTINEL rows invalid), and the optional trailing ``exc`` is an
    int32 exception code (0 = none) — the tensor analog of a handler
    throwing (SearchState.java:218-222).
    """

    name: str
    n_nodes: int
    node_width: int
    msg_width: int
    timer_width: int
    net_cap: int
    timer_cap: int
    max_sends: int
    max_sets: int
    init_nodes: Callable[[], np.ndarray]
    init_messages: Callable[[], np.ndarray]   # [k, MW] initial network
    init_timers: Callable[[], np.ndarray]     # [k, 1 + TW] initial timer sets
    step_message: Callable
    step_timer: Callable
    # message -> destination node index (for delivery gating); jax fn
    msg_dest: Callable
    # state-level predicates: dict name -> vmapped-able fn(state_slice)->bool
    invariants: Dict[str, Callable] = dataclasses.field(default_factory=dict)
    goals: Dict[str, Callable] = dataclasses.field(default_factory=dict)
    prunes: Dict[str, Callable] = dataclasses.field(default_factory=dict)
    # optional masks: deliver_message(msg)->bool, deliver_timer(node)->bool
    deliver_message: Optional[Callable] = None
    deliver_timer: Optional[Callable] = None
    # RUNTIME-mask variants: fn(msg, marr)->bool / fn(node, tarr)->bool
    # where marr/tarr are device arrays passed per run (TensorSearch
    # .set_runtime_masks), NOT trace-time constants.  The harness search
    # backend uses these so every staged phase of a lab test (different
    # partitions/timer gating, same protocol shape) shares ONE compiled
    # expand program instead of recompiling per mask (settings gate
    # events, never shapes — SURVEY §7.7).  Applied in _event_tables
    # (the single validity source for the expand pipeline).
    deliver_message_rt: Optional[Callable] = None
    deliver_timer_rt: Optional[Callable] = None
    # Max SIMULTANEOUS valid send rows any single transition can emit.
    # ``max_sends`` is the static row budget summed over all (mutually
    # exclusive) handler branches; the live count is far smaller (lab3:
    # 29 rows budgeted, <= ~12 ever valid at once).  When set, the engine
    # compacts sends to this width before the set-insert merge — the
    # merge is O(S x CAP) so this directly shrinks the hot loop.  Too
    # small a value is a loud CapacityOverflow, never silent truncation.
    max_live_sends: Optional[int] = None
    # optional object-twin decoders for trace reconstruction
    # (tpu/trace.py): decode_message(np_record) -> (from_addr, to_addr,
    # Message); decode_timer(node_idx, np_record) -> (to_addr, Timer,
    # min_ms, max_ms).  Addresses follow the twin's parity-test naming.
    decode_message: Optional[Callable] = None
    decode_timer: Optional[Callable] = None
    # Declared per-lane value domains (ISSUE 15, tpu/packing.py):
    # {"nodes": [...], "msg": [...], "timer": [...], "exc": (lo, hi)}
    # with (lo, hi) or None per lane — the input to the bit-packed
    # frontier encoding.  None (hand twins) derives the identity
    # descriptor: the packed path is a traced no-op.
    lane_domains: Optional[dict] = None
    # Symmetry groups (ISSUE 15, tpu/symmetry.py SymmetrySpec):
    # permutation tables over node ids/lanes for the opt-in
    # canonicalize-before-fingerprint pass.  None = no groups.
    symmetry: Optional[object] = None
    # Checkable fault scenarios (ISSUE 19, tpu/faults.py FaultLanes):
    # the compiled fault-model descriptor — partition/crash/drop/dup
    # event segment layout, controller lane offsets, deliverability
    # tables.  None = no fault model; every engine addition is gated
    # at trace time on this, so fault-free specs lower to the
    # byte-identical pre-fault program.
    fault: Optional[object] = None


@dataclasses.dataclass
class SearchOutcome:
    end_condition: str               # GOAL_FOUND / INVARIANT_VIOLATED /
                                     # EXCEPTION_THROWN / SPACE_EXHAUSTED /
                                     # CAPACITY_EXHAUSTED / DEPTH_EXHAUSTED /
                                     # TIME_EXHAUSTED
    states_explored: int
    unique_states: int
    depth: int
    elapsed_secs: float
    violating_state: Optional[dict] = None
    goal_state: Optional[dict] = None
    predicate_name: Optional[str] = None
    exception_code: int = 0
    trace: Optional[list] = None     # [(parent event id, ...)] — see trace.py
    dropped: int = 0                 # beam-truncation drops (strict=False)
    # Trace-mode exhaust verdicts carry a few deepest-state traces so the
    # caller can re-check value-level invariants (which collapse to
    # constant-true lane predicates on the twin) on replayed OBJECT
    # states before trusting the exhaustion (ADVICE r4).
    samples: Optional[list] = None   # [root-first event-id list, ...]
    # Visited-table overflow: keys whose probe exhausted (table
    # effectively full) were treated as FRESH — sound (the state may be
    # re-explored; nothing is ever silently dropped) but the unique
    # count can then over-report re-explorations.  Strict engines raise
    # instead; beam runs report the count here (ISSUE 1 contract).
    visited_overflow: int = 0
    # Recovery accounting (tpu/supervisor.py, docs/resilience.md): every
    # degradation the supervisor absorbed on the way to this verdict is
    # visible here — never a silent partial verdict.
    retries: int = 0                 # transient-dispatch retries absorbed
    failovers: int = 0               # ladder rungs abandoned before this one
    resumed_from_depth: int = 0      # checkpoint depth resumed from (0=root)
    engine: Optional[str] = None     # ladder rung that produced the verdict
    # Process-isolation accounting (tpu/warden.py): children the warden
    # spawned beyond the first on the way to this verdict, and
    # dispatches SIGKILLed mid-flight after heartbeat silence.  Zero in
    # in-process mode.
    child_restarts: int = 0
    killed_dispatches: int = 0
    # In-process watchdog leak accounting: watchdog-abandoned daemon
    # threads STILL BLOCKED when the verdict landed (each one pins a
    # wedged XLA dispatch; process isolation is the leak-free mode).
    abandoned_threads: int = 0
    # Structured per-level throughput records from the sharded driver
    # (dicts of depth / chunks / wall / explored / unique /
    # next_frontier) — the bench emits them as its throughput series;
    # DSLABS_LEVEL_TIMING pretty-prints the same records live.
    levels: Optional[list] = None
    # Wall seconds spent in explicit AOT compilation (the construction-
    # time .lower().compile() warm-up) — reported SEPARATELY from
    # elapsed_secs so compile cost never pollutes states/min, and so a
    # warm persistent compile cache (tpu/compile_cache.py) is visible
    # as this number dropping to near-zero on the second run.
    compile_secs: float = 0.0
    # Swarm-explorer accounting (tpu/swarm.py, docs/swarm.md).  A
    # random walker RESTARTS (root/frontier re-seed) on dead ends,
    # prunes, its depth bound, or — the loud bugfix of the old silent
    # rollout behaviour — a capacity-truncated step; the truncated-step
    # count is swarm_overflow (strict swarms raise instead, matching
    # the visited-overflow contract), and the total restart count is
    # walker_restarts.  ``swarm`` carries the fleet's throughput stats
    # (walkers/sec, unique-states/min, deepest depth) for the bench.
    walker_restarts: int = 0
    swarm_overflow: int = 0
    swarm: Optional[dict] = None
    # The verified counterexample (tpu/swarm.py ``Witness``): minimized
    # event trace + replay-verification flags.  Populated by swarm /
    # rollout violations before the verdict is returned — no tensor
    # verdict ships an unminimized or unreplayed trace.
    witness: Optional[object] = None
    # Portfolio-mode cancellation marker (tpu/supervisor.py): this
    # outcome was cut short because the OTHER portfolio lane already
    # landed a terminal verdict — never a standalone verdict.
    cancelled: bool = False
    # Host-RAM spill-tier accounting (tpu/spill.py, docs/capacity.md):
    # keys evicted from the device visited table to the host tier,
    # re-discoveries the level-boundary refilter removed (each one a
    # corrected duplicate count), and frontier rows that took the
    # host-spool detour instead of being dropped.  All zero when the
    # spill tier never engaged.
    spilled_keys: int = 0
    host_tier_hits: int = 0
    respilled_frontier: int = 0
    # Elastic-mesh resilience accounting (ISSUE 9, tpu/supervisor.py,
    # docs/resilience.md): the mesh width (device count) of the rung
    # that produced this verdict, how many times the degraded-mesh
    # ladder halved the mesh (``mesh_shrunk`` events), and how many
    # in-place knob-shrink re-levels OOM-classified failures were
    # answered with (``knobs_shrunk`` events) instead of burning a
    # rung.  None/0 outside the supervisor.
    mesh_width: Optional[int] = None
    mesh_shrinks: int = 0
    knob_retries: int = 0
    # Causal-trace identity (ISSUE 13, tpu/tracing.py): the trace this
    # verdict belongs to, stamped from the attached telemetry
    # recorder's context at span emission — how a service verdict, its
    # COSTS.jsonl record, and its flight log stay joinable after the
    # run dir is pruned.  None outside any trace.
    trace_id: Optional[str] = None
    # Batched job lanes (ISSUE 14, tpu/lanes.py): the lane index this
    # verdict ran in, the batch width (L), and this lane's fraction of
    # the batch's shared device-seconds (every dispatch's wall split
    # evenly across the lanes resident at that level — the shares of a
    # batch sum to 1.0, so lane billing never double-charges a
    # dispatch).  None/unset outside a lane batch.
    lane: Optional[int] = None
    lane_width: Optional[int] = None
    lane_share: Optional[float] = None
    # Capacity round 2 (ISSUE 15, tpu/packing.py / tpu/symmetry.py):
    # HBM bytes per stored frontier row under the engine's encoding
    # (packed when the spec declares domains), the unpacked reference,
    # their ratio (the capacity multiplier at fixed HBM), and the
    # symmetry-quotient accounting — the canonicalize pass's
    # permutation count (0 = reduction off; unique_states is then the
    # CANONICAL orbit count, strictly <= the raw count).
    bytes_per_state: Optional[int] = None
    bytes_per_state_unpacked: Optional[int] = None
    pack_ratio: Optional[float] = None
    symmetry_perms: int = 0
    # Async spill drain (ISSUE 15c, tpu/spill.py): host ms inside
    # drain jobs vs ms the driver actually blocked waiting for them —
    # the gap is drain work overlapped with device compute.
    spill_drain_ms: int = 0
    spill_wait_ms: int = 0
    # Checkable fault scenarios (ISSUE 19, tpu/faults.py): valid fault
    # events EXPLORED (counted over successor states, like
    # states_explored) split by family — partition cut/heal, crash +
    # restart, message drops, dup tags — and their total.  All zero
    # when the spec declares no fault model.
    fault_events: int = 0
    partition_events: int = 0
    crash_events: int = 0
    drop_events: int = 0
    dup_events: int = 0

    @property
    def dropped_states(self) -> int:
        """Beam-truncation drop COUNT under its roadmap name (ISSUE 6
        satellite: surfaced everywhere, never a boolean) — the same
        number as ``dropped``; the alias exists so bench JSON, docs,
        and the DSLABS_DROPPED_WARN threshold all speak one name."""
        return self.dropped


# ----------------------------------------------------------------- hashing

def _mix32(x: jnp.ndarray, seed: jnp.ndarray) -> jnp.ndarray:
    """Add-shift-xor mixer over int32 lanes (vectorised, uint32 only).

    Jenkins one-at-a-time-style avalanche: NO per-element integer
    multiplies — uint32 multiplies at (pairs x lanes) scale measured ~6x
    slower than shift/add/xor lanes on the TPU VPU (round-2 profile).
    The only multiply is on the [1, L] positional seed row."""
    x = x.astype(jnp.uint32) ^ (seed.astype(jnp.uint32)
                                * jnp.uint32(0x9E3779B9))
    x = x + (x << 10)
    x = x ^ (x >> 6)
    x = x + (x << 3)
    x = x ^ (x >> 11)
    x = x + (x << 15)
    x = x ^ (x >> 7)
    return x


def _fingerprint32(flat: jnp.ndarray, seed: int,
                   sum_fn=None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """64-bit fingerprint of [N, L] int32 rows as a (hi, lo) uint32 pair.

    Sequential-free: each lane is mixed with its position and a seed, then
    lanes are combined with addition and a final avalanche (order within the
    row still matters via the positional term).  No int64 anywhere — TPU
    native dtypes only.

    ``sum_fn`` overrides the uint32 lane reduction: the Pallas kernel
    (tpu/kernels.py) passes a bit-identical int32-bitcast sum because
    Mosaic cannot reduce over unsigned ints — keeping the mixing sequence
    and constants defined in exactly one place."""
    if sum_fn is None:
        def sum_fn(x):
            return jnp.sum(x, axis=1, dtype=jnp.uint32)
    _, l = flat.shape
    pos = jnp.arange(l, dtype=jnp.uint32)[None, :] + jnp.uint32(seed * 0x1000193)
    h = _mix32(flat, pos)
    lo = sum_fn(h)
    hi = sum_fn(_mix32(h, pos + jnp.uint32(0x27D4EB2F)))
    return hi, lo


def row_fingerprints(flat: jnp.ndarray) -> jnp.ndarray:
    """[N, L] int32 rows -> [N, 4] uint32 (a_hi, a_lo, b_hi, b_lo): two
    independent 64-bit fingerprints = one 128-bit equivalence key."""
    a_hi, a_lo = _fingerprint32(flat, 1)
    b_hi, b_lo = _fingerprint32(flat, 2)
    return jnp.stack([a_hi, a_lo, b_hi, b_lo], axis=1)


def flatten_state(state: dict) -> jnp.ndarray:
    """[N]-batch state pytree -> [N, L] int32 rows (the hash preimage).
    The exception lane participates — exception states are equivalence-
    distinct from normal ones (SearchState.java:594-596)."""
    n = state["nodes"].shape[0]
    return jnp.concatenate([
        state["nodes"].reshape(n, -1),
        state["net"].reshape(n, -1),
        state["timers"].reshape(n, -1),
        state["exc"].reshape(n, 1),
    ], axis=1)


def state_fingerprints(state: dict) -> jnp.ndarray:
    """[N]-batch -> [N, 4] uint32 128-bit equivalence keys.  Defaults to
    the jnp path, which XLA fuses into the expand program (measured ~2x
    faster end-to-end than the VMEM-tiled Pallas kernel in
    tpu/kernels.py, which is opt-in via DSLABS_PALLAS_FP=1)."""
    from dslabs_tpu.tpu.kernels import fingerprint_rows

    return fingerprint_rows(flatten_state(state))


def host_keys(fp: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """[N, 4] uint32 device fingerprints -> host (h1, h2) uint64 arrays.
    x64 lives only here, in host NumPy (TPUs emulate int64; round 1's
    global ``jax_enable_x64`` crashed the TPU worker)."""
    fp = np.asarray(fp, dtype=np.uint64)
    h1 = (fp[:, 0] << np.uint64(32)) | fp[:, 1]
    h2 = (fp[:, 2] << np.uint64(32)) | fp[:, 3]
    return h1, h2


def _keys_to_rows(visited: Tuple[np.ndarray, np.ndarray]) -> np.ndarray:
    """Inverse of :func:`host_keys`: host (h1, h2) uint64 arrays ->
    [K, 4] uint32 device-format key rows (the unified checkpoint's
    visited_keys layout, tpu/checkpoint.py)."""
    h1, h2 = visited
    rows = np.empty((len(h1), 4), np.uint32)
    rows[:, 0] = (h1 >> np.uint64(32)).astype(np.uint32)
    rows[:, 1] = (h1 & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    rows[:, 2] = (h2 >> np.uint64(32)).astype(np.uint32)
    rows[:, 3] = (h2 & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    return rows


def sorted_member(vh1: np.ndarray, vh2: np.ndarray,
                  h1: np.ndarray, h2: np.ndarray) -> np.ndarray:
    """Membership of query keys (h1, h2) in a visited set sorted by
    (h1, h2).  Scans forward over the full run of equal h1 (not a fixed
    2-slot probe), so >=3-way 64-bit collisions cannot cause re-exploration
    (round-1 advisor finding)."""
    seen = np.zeros(len(h1), dtype=bool)
    if not len(vh1):
        return seen
    pos = np.searchsorted(vh1, h1, side="left")
    off = 0
    while True:
        q = pos + off
        inb = q < len(vh1)
        qc = np.where(inb, q, 0)
        eq1 = inb & (vh1[qc] == h1)
        if not eq1.any():
            return seen
        seen |= eq1 & (vh2[qc] == h2)
        off += 1


# ------------------------------------------------------------ net/timer ops

def _row_less(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Lexicographic ``a < b`` over the trailing lane axis (broadcasts).
    Pure compare/select lanes — no integer multiplies: uint32-multiply
    hashing at (state x event x row) scale measured ~6x slower than these
    raw-lane compares on TPU (round-2 bisection)."""
    eq = a == b
    # first_diff[l] = lanes 0..l-1 all equal and lane l differs
    prefix_eq = jnp.cumprod(eq, axis=-1, dtype=jnp.int32).astype(bool)
    prefix_excl = jnp.concatenate([
        jnp.ones_like(prefix_eq[..., :1]), prefix_eq[..., :-1]], axis=-1)
    return jnp.any(~eq & prefix_excl & (a < b), axis=-1)


def canonicalize_net(net: jnp.ndarray) -> jnp.ndarray:
    """Sort the message set into canonical (raw-lane lexicographic) order
    and collapse duplicates.

    [CAP, MW] -> [CAP, MW]; empty rows are all-SENTINEL and sort last
    (SENTINEL is int32 max and occupied rows always have lane 0 !=
    SENTINEL).  Cold path: used for batch-1 initial states only — the hot
    loop's set-insertion (:func:`insert_messages`) is a sort-free merge
    that preserves this order."""
    cap = net.shape[0]
    empty = net[:, 0] == SENTINEL
    # lexsort: LAST key is primary — empty rows always sort to the back.
    keys = tuple(net[:, lane] for lane in range(net.shape[1] - 1, -1, -1))
    order = jnp.lexsort(keys + (empty,))
    net_s = net[order]
    empty_s = empty[order]
    dup = jnp.zeros(cap, dtype=bool).at[1:].set(
        jnp.all(net_s[1:] == net_s[:-1], axis=1) & ~empty_s[1:])
    keep = ~dup & ~empty_s
    pos = jnp.cumsum(keep) - 1
    out = jnp.full((cap + 1, net.shape[1]), SENTINEL, net.dtype)
    out = out.at[jnp.where(keep, pos, cap)].set(net_s)
    return out[:cap]


def compact_rows(rows: jnp.ndarray,
                 budget: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Compact occupied rows (lane 0 != SENTINEL) of [R, W] into the first
    ``budget`` slots of a [budget, W] output, preserving order; returns
    ``(out, overflow)`` where overflow counts occupied rows beyond the
    budget (callers treat nonzero as fatal — a dropped row would corrupt
    the successor state, never a beam-style truncation).

    One-hot select-reduce over the [budget, R] grid — static indexing
    only (a pos-indexed scatter per pair is the slow dynamic path)."""
    occ = rows[:, 0] != SENTINEL
    pos = jnp.cumsum(occ) - 1
    hit = occ[None, :] & (pos[None, :] == jnp.arange(budget)[:, None])
    out = jnp.sum(jnp.where(hit[:, :, None], rows[None, :, :], 0), axis=1)
    out = jnp.where(jnp.any(hit, axis=1)[:, None], out, SENTINEL)
    overflow = jnp.sum(occ & (pos >= budget)).astype(jnp.int32)
    return out, overflow


def insert_messages(net: jnp.ndarray,
                    sends: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Set-insert up to S records into the canonical network.

    Sort-free merge: ``net`` is always in canonical form (occupied rows
    first, raw-lane ascending — every state enters the engine through
    :func:`canonicalize_net` or this function), so inserting S small
    ``sends`` needs only O(S x CAP) lexicographic comparisons to compute
    each row's merged rank.  The round-2 profile showed a
    sort-per-(state,event) version was 82% of the whole expand program;
    round 3 replaced the remaining O(CAP^2) one-hot placement of net rows
    with S+1 STATIC shifted slices: net row j lands at j + shift_j where
    shift_j = #valid sends below it <= S, so out[k] selects among
    net[k-c] for c in 0..S — an O(CAP x S) select chain with no dynamic
    indexing.  Callers compact ``sends`` to the protocol's
    ``max_live_sends`` first, which is what makes S genuinely small.

    Returns ``(net', overflow)`` where overflow counts distinct occupied
    records that did not fit back into capacity — the caller surfaces any
    nonzero count as a CapacityOverflow (never a silent truncation)."""
    cap = net.shape[0]
    s = sends.shape[0]
    w = net.shape[1]
    net_occ = net[:, 0] != SENTINEL                       # [cap]
    send_occ = sends[:, 0] != SENTINEL                    # [s]
    sn_less = _row_less(sends[:, None, :], net[None, :, :])  # send_i < net_j
    sn_eq = jnp.all(sends[:, None, :] == net[None, :, :], axis=-1)
    dup_net = jnp.any(sn_eq & net_occ[None, :], axis=1)   # [s]
    ss_eq = jnp.all(sends[:, None, :] == sends[None, :, :], axis=-1)
    earlier = jnp.tril(jnp.ones((s, s), bool), k=-1)      # j < i
    earlier_dup = jnp.any(ss_eq & earlier & send_occ[None, :], axis=1)
    valid = send_occ & ~dup_net & ~earlier_dup            # [s]

    # Merged rank of each valid send: occupied net rows strictly below it
    # plus valid sends strictly below it (ties impossible after dedup —
    # tie-break among equal-key sends never fires, but keep the j<i term
    # for full determinism anyway).
    net_below = jnp.sum((~sn_less & ~sn_eq) & net_occ[None, :], axis=1)
    ss_less = _row_less(sends[:, None, :], sends[None, :, :])  # [s,s] i<j?
    sends_below = jnp.sum(
        (ss_less.T | (ss_eq & earlier)) & valid[None, :], axis=1)
    dst_send = net_below + sends_below                    # [s]

    # Net row j lands at j + shift_j (valid sends below push it right);
    # place via S+1 static shifted slices: out[k] = net[k-c] when
    # shift[k-c] == c and net[k-c] occupied.
    shift = jnp.sum(sn_less & valid[:, None], axis=0)      # [cap]
    pad_rows = jnp.full((s, w), SENTINEL, net.dtype)
    pnet = jnp.concatenate([pad_rows, net])                # [s+cap, w]
    pshift = jnp.concatenate([jnp.full((s,), -1, shift.dtype), shift])
    pocc = jnp.concatenate([jnp.zeros((s,), bool), net_occ])
    out = jnp.zeros((cap, w), net.dtype)
    any_hit = jnp.zeros((cap,), bool)
    for c in range(s + 1):
        lo = s - c
        hit = (pshift[lo:lo + cap] == c) & pocc[lo:lo + cap]
        out = out + jnp.where(hit[:, None], pnet[lo:lo + cap], 0)
        any_hit = any_hit | hit
    # Send placement: [cap, s] one-hot select-reduce (S is small).
    k = jnp.arange(cap)
    hit_send = valid[None, :] & (dst_send[None, :] == k[:, None])  # [cap,s]
    # Masked select-reduce, not an int32 einsum: integer-multiply
    # dot_general lowers to slow VPU loops, while where+sum fuses.
    out = out + jnp.sum(
        jnp.where(hit_send[:, :, None], sends[None, :, :], 0), axis=1)
    any_hit = any_hit | jnp.any(hit_send, axis=1)
    out = jnp.where(any_hit[:, None], out, SENTINEL)
    total = (jnp.sum(net_occ) + jnp.sum(valid)).astype(jnp.int32)
    overflow = jnp.maximum(total - cap, 0).astype(jnp.int32)
    return out, overflow


def compact_rows_batched(rowsT: jnp.ndarray,
                         budget: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Batched, TRANSPOSED :func:`compact_rows`: ``rowsT`` is
    [R, W, P] (pairs on the MINOR axis — full 128-lane VPU utilisation;
    the per-pair vmapped form left 7/8 of every vector op idle, the
    round-3 measured pathology) -> ([budget, W, P], overflow [P])."""
    r, w, pp = rowsT.shape
    occ = rowsT[:, 0, :] != SENTINEL                 # [R, P]
    pos = jnp.cumsum(occ, axis=0) - 1                # [R, P]
    outs = []
    hits = []
    for b in range(budget):
        hit = occ & (pos == b)                       # [R, P]
        outs.append(jnp.sum(jnp.where(hit[:, None, :], rowsT, 0), axis=0))
        hits.append(jnp.any(hit, axis=0))
    out = jnp.stack(outs)                            # [budget, W, P]
    has = jnp.stack(hits)                            # [budget, P]
    out = jnp.where(has[:, None, :], out, SENTINEL)
    overflow = jnp.sum(occ & (pos >= budget), axis=0).astype(jnp.int32)
    return out, overflow


def insert_messages_batched(netT: jnp.ndarray, sendsT: jnp.ndarray
                            ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Batched, TRANSPOSED :func:`insert_messages`: ``netT`` [CAP, MW, P]
    (canonical per pair), ``sendsT`` [S, MW, P] (compacted) ->
    (merged [CAP, MW, P], overflow [P]).

    Same math as the per-pair form — lexicographic ranks, S+1 static
    shifted slices for net placement, one-hot send placement — but every
    op is [CAP, P] or [S, S, P] with pairs riding the minor (lane) axis.
    Measured on the v5e: the per-pair form's [S, CAP, MW]-shaped compare
    ran ~30x slower than this layout purely from lane waste (MW = 8 of
    128 lanes)."""
    cap, mw, pp = netT.shape
    s = sendsT.shape[0]
    net_occ = netT[:, 0, :] != SENTINEL              # [CAP, P]
    send_occ = sendsT[:, 0, :] != SENTINEL           # [S, P]

    # send_i vs net_j lexicographic, one send at a time: [CAP, P] lanes.
    sn_less_l, sn_eq_l = [], []
    for si in range(s):
        lt = jnp.zeros((cap, pp), bool)
        eqp = jnp.ones((cap, pp), bool)
        for l in range(mw):
            nv = netT[:, l, :]
            sv = sendsT[si, l, :][None, :]
            lt = lt | (eqp & (sv < nv))
            eqp = eqp & (sv == nv)
        sn_less_l.append(lt)
        sn_eq_l.append(eqp)
    sn_less = jnp.stack(sn_less_l)                   # [S, CAP, P]
    sn_eq = jnp.stack(sn_eq_l)
    dup_net = jnp.any(sn_eq & net_occ[None], axis=1)  # [S, P]

    # send_i vs send_j lexicographic: [S, S, P].
    lt = jnp.zeros((s, s, pp), bool)
    eqp = jnp.ones((s, s, pp), bool)
    for l in range(mw):
        a = sendsT[:, None, l, :]
        b = sendsT[None, :, l, :]
        lt = lt | (eqp & (a < b))
        eqp = eqp & (a == b)
    ss_less, ss_eq = lt, eqp
    earlier = jnp.tril(jnp.ones((s, s), bool), k=-1)[:, :, None]
    earlier_dup = jnp.any(ss_eq & earlier & send_occ[None, :, :], axis=1)
    valid = send_occ & ~dup_net & ~earlier_dup       # [S, P]

    net_below = jnp.sum(~sn_less & ~sn_eq & net_occ[None], axis=1)
    sends_below = jnp.sum(
        (jnp.swapaxes(ss_less, 0, 1) | (ss_eq & earlier))
        & valid[None, :, :], axis=1)
    dst_send = net_below + sends_below               # [S, P]
    shift = jnp.sum(sn_less & valid[:, None, :], axis=0)   # [CAP, P]

    pad_rows = jnp.full((s, mw, pp), SENTINEL, netT.dtype)
    pnet = jnp.concatenate([pad_rows, netT])         # [S+CAP, MW, P]
    pshift = jnp.concatenate([jnp.full((s, pp), -1, shift.dtype), shift])
    pocc = jnp.concatenate([jnp.zeros((s, pp), bool), net_occ])
    out = jnp.zeros((cap, mw, pp), netT.dtype)
    any_hit = jnp.zeros((cap, pp), bool)
    for c in range(s + 1):
        lo = s - c
        hit = (pshift[lo:lo + cap] == c) & pocc[lo:lo + cap]   # [CAP, P]
        out = out + jnp.where(hit[:, None, :], pnet[lo:lo + cap], 0)
        any_hit = any_hit | hit
    k = jnp.arange(cap)[:, None]
    for si in range(s):
        hit = valid[si][None, :] & (dst_send[si][None, :] == k)
        out = out + jnp.where(hit[:, None, :], sendsT[si][None], 0)
        any_hit = any_hit | hit
    out = jnp.where(any_hit[:, None, :], out, SENTINEL)
    total = (jnp.sum(net_occ, axis=0) + jnp.sum(valid, axis=0)
             ).astype(jnp.int32)
    overflow = jnp.maximum(total - cap, 0).astype(jnp.int32)
    return out, overflow


def timer_deliverable_mask(queue: jnp.ndarray) -> jnp.ndarray:
    """[T_CAP, TW] -> [T_CAP] bool: the TimerQueue partial order
    (TimerQueue.java:66-105).  Lane 1 = min, lane 2 = max; empty rows are
    SENTINEL.  deliverable[i] = occupied[i] and min[i] < min(max[j] for
    occupied j < i) (strictly: NOT exists earlier t' with t.min >= t'.max)."""
    occupied = queue[:, 0] != SENTINEL
    maxes = jnp.where(occupied, queue[:, 2], SENTINEL)
    prefix_min = jnp.concatenate([
        jnp.array([SENTINEL], dtype=maxes.dtype),
        jax.lax.cummin(maxes)[:-1]])
    return occupied & (queue[:, 1] < prefix_min)


def remove_timer(queue: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """Remove the timer at position idx, shifting later entries left
    (insertion order is semantic — it drives the partial order).
    Static shift-select: the shifted copy is a constant-offset slice, the
    blend a positional mask — no dynamic gather."""
    cap = queue.shape[0]
    pos = jnp.arange(cap)
    shifted = jnp.concatenate([
        queue[1:], jnp.full((1, queue.shape[1]), SENTINEL, queue.dtype)])
    return jnp.where((pos >= idx)[:, None], shifted, queue)


def append_timers(timers: jnp.ndarray,
                  new_timers: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Append [MAX_SETS, 1+TW] records (lane 0 = node idx) to the per-node
    queues [NN, T_CAP, TW], preserving insertion order.  Returns
    ``(timers', dropped)`` — a full queue drops the append (insertion order
    is semantic, clobbering would corrupt the partial order) and the drop
    count is surfaced loudly by the engine.

    Occupied rows form a prefix of each queue (appends land at the count,
    removals shift left), so every append's slot is computable up front:
    queue occupancy + number of earlier appends to the same node.  The
    writes land via a one-hot 0/1 einsum over the (node, slot) grid —
    static indexing only (dynamic scatters under the engine's flat vmap
    lowered to ~1 GB/s code on TPU, the round-2 bottleneck; distinct
    records land on distinct slots, so the products sum exactly)."""
    nn, cap, tw = timers.shape
    s = new_timers.shape[0]
    node = new_timers[:, 0]
    valid = node != SENTINEL
    node_c = jnp.where(valid, node, 0).astype(jnp.int32).clip(0, nn - 1)
    counts = jnp.sum(timers[:, :, 0] != SENTINEL, axis=1)   # [NN]
    earlier_same = (jnp.tril(jnp.ones((s, s), bool), k=-1)
                    & (node[None, :] == node[:, None]) & valid[None, :])
    offset = jnp.sum(earlier_same, axis=1)
    # counts[node_c] as a one-hot sum (static): [s, nn] @ [nn]
    node_oh = jnp.arange(nn)[None, :] == node_c[:, None]    # [s, nn]
    slot = jnp.sum(node_oh * counts[None, :], axis=1) + offset
    ok = valid & (slot < cap)
    dropped = jnp.sum(valid & ~ok).astype(jnp.int32)
    slot_oh = jnp.arange(cap)[None, :] == slot[:, None]     # [s, cap]
    write = (node_oh[:, :, None] & slot_oh[:, None, :]
             & ok[:, None, None])                           # [s, nn, cap]
    # Masked select-reduce, not an int32 einsum (see insert_messages).
    contrib = jnp.sum(
        jnp.where(write[:, :, :, None], new_timers[:, None, None, 1:], 0),
        axis=0)                                             # [nn, cap, tw]
    hit = jnp.any(write, axis=0)                            # [nn, cap]
    return jnp.where(hit[:, :, None], contrib, timers), dropped


def _normalize_step(out):
    """Protocol step fns may return 3-tuple (no exception lane) or 4-tuple
    with a trailing int32 exception code."""
    if len(out) == 3:
        nodes2, sends, new_t = out
        return nodes2, sends, new_t, jnp.int32(0)
    nodes2, sends, new_t, exc = out
    return nodes2, sends, new_t, jnp.asarray(exc, jnp.int32)


# ------------------------------------------------------------------- engine

class TensorSearch:
    """Single-device BFS driver.  One jitted program expands a frontier
    chunk into successors (vmapped transition + canonicalisation +
    128-bit fingerprints + in-chunk sort-unique + predicate flags); the
    host loop handles level accounting, visited merging, and termination."""

    def __init__(self, protocol: TensorProtocol,
                 frontier_cap: int = 1 << 16,
                 chunk: int = 1 << 12,
                 max_depth: Optional[int] = None,
                 max_secs: Optional[float] = None,
                 record_trace: bool = False,
                 in_chunk_dedup: bool = True,
                 ev_budget: Optional[int] = None,
                 visited_cap: int = 1 << 20,
                 strict: bool = True,
                 use_host_visited: bool = False,
                 checkpoint_path: Optional[str] = None,
                 checkpoint_every: int = 0,
                 spill=None,
                 telemetry=None,
                 packed: Optional[bool] = None,
                 symmetry: Optional[bool] = None):
        self.p = protocol
        # Unified telemetry (tpu/telemetry.py): when attached — here or
        # via ``Telemetry.attach(search)`` — every ``_dispatch`` call
        # becomes a flight-recorder span and the per-level fused-stats
        # scalars feed the metrics registry.  Strictly host-side: zero
        # extra device dispatches or transfers (the overhead-guard
        # test pins this).
        self._telemetry = telemetry
        # Host-RAM spill tier (tpu/spill.py, docs/capacity.md): when
        # enabled, a full visited table EVICTS to a host fingerprint
        # set (and would-be frontier drops take a host spool detour)
        # instead of raising CapacityOverflow — strict searches stay
        # exact, just slower.  ``spill`` is False/None (off; env
        # DSLABS_SPILL=1 flips the default), True, or a
        # spill.SpillConfig.  Off by default: the overflow contract
        # (strict raises) is load-bearing for existing callers; the
        # supervisor's capacity ladder opts in on their behalf.
        from dslabs_tpu.tpu import spill as spill_mod

        if spill is None:
            spill = spill_mod.spill_env_default()
        if isinstance(spill, spill_mod.SpillConfig):
            self._spill = spill_mod.SpillManager(spill)
        elif spill:
            self._spill = spill_mod.SpillManager()
        else:
            self._spill = None
        if self._spill is not None and record_trace:
            raise ValueError(
                "spill + record_trace is unsupported (trace spills are "
                "host-side already; run the trace pass uncapped)")
        # Unified checkpoint/resume (tpu/checkpoint.py): every
        # ``checkpoint_every`` completed waves the live search state —
        # occupied frontier rows + occupied visited-table lines +
        # counters + depth — is snapshotted host-side and drained to
        # ``checkpoint_path`` (atomic .npz) by a background thread;
        # ``run(resume=True)`` continues a killed search from the last
        # dump with identical verdict and unique count.  The dump format
        # is ENGINE-AGNOSTIC — the device-resident wave loop, the host
        # parity loop, and the sharded driver all read the same file
        # (the supervisor's failover ladder depends on that).  0 = off.
        self.checkpoint_path = checkpoint_path
        self.checkpoint_every = checkpoint_every
        self._resumed_from_depth = 0
        # Persistent XLA compile cache (tpu/compile_cache.py): the
        # DSLABS_COMPILE_CACHE knob, defaulting to a compile_cache/
        # dir beside the checkpoint when one is configured — so the
        # second run of any config pays near-zero compile.
        from dslabs_tpu.tpu import compile_cache

        compile_cache.setup_for_checkpoint(checkpoint_path)
        self.frontier_cap = frontier_cap
        self.chunk = chunk
        self.max_depth = max_depth
        self.max_secs = max_secs
        self.record_trace = record_trace
        # Device-resident dedup (run()): capacity of the open-addressing
        # visited table (power of two; ~16 bytes/slot) and the overflow
        # policy — strict raises on a table-full (unique counts must be
        # exact), non-strict degrades to treat-as-fresh and reports the
        # count via SearchOutcome.visited_overflow.  use_host_visited
        # forces the legacy host sorted_member loop (the parity oracle).
        visited_mod.check_cap(visited_cap)
        self.visited_cap = visited_cap
        self.strict = strict
        self.use_host_visited = use_host_visited
        # Occupancy-compacted event enumeration: expand only each state's
        # VALID events (occupied messages + deliverable timers), packed
        # into per-KIND pair-slot tables — message pairs run only the
        # message machinery and timer pairs only the timer machinery (the
        # round-2 select-both design computed BOTH branches for every
        # pair).  ``ev_budget``: None = full grid per kind (always safe);
        # int b = message slots capped at b, timer slots full; tuple
        # (bm, bt) caps both (bench protocol: (40, 8) vs the 64+30
        # grid; measured mean ~30 valid events at depth 16).  A state
        # with more valid events than a budget overflows LOUDLY (base
        # engine: CapacityOverflow; sharded strict: same; sharded beam:
        # counted in SearchOutcome.dropped — coverage truncation, same
        # class as a frontier-cap drop).
        tgrid = protocol.n_nodes * protocol.timer_cap
        if ev_budget is None:
            bm, bt = protocol.net_cap, tgrid
        elif isinstance(ev_budget, tuple):
            bm, bt = ev_budget
        else:
            bm, bt = ev_budget, tgrid
        self._ev_msg = min(bm, protocol.net_cap)
        self._ev_tmr = min(bt, tgrid)
        # Fault event segment (ISSUE 19, tpu/faults.py): always the FULL
        # fault grid — fault grids are small (2 + 2*crashable + the
        # drop/dup slots), never budget-windowed, so re-step spill
        # passes (ev_pass > 0) see an empty fault table via the
        # _compact_ids offset logic rather than a shifted window.
        self._ev_flt = (protocol.fault.n_events
                        if protocol.fault is not None else 0)
        self._ev_slots = self._ev_msg + self._ev_tmr + self._ev_flt
        # When False, _expand_chunk marks every valid successor unique and
        # dedup is entirely the caller's job — only meaningful for drivers
        # with their own dedup authority (the sharded engine's owner-side
        # hash table); the base run() loop REQUIRES the prefilter.
        self._in_chunk_dedup = in_chunk_dedup
        # Flat-row layout: states travel as [*, lanes] int32 rows (nodes
        # ++ net ++ timers ++ exc) everywhere past initial_state() — the
        # round-3 bisect showed the expand is HBM-bound, and the old
        # dict-of-pieces representation materialised every successor
        # twice (once as the pytree, once flattened for hashing).
        p = protocol
        self._off = (p.node_width,
                     p.node_width + p.net_cap * p.msg_width,
                     p.node_width + p.net_cap * p.msg_width
                     + p.n_nodes * p.timer_cap * p.timer_width)
        self.lanes = self._off[2] + 1
        # Bit-packed frontier encoding (ISSUE 15a, tpu/packing.py): ON
        # by default — protocols with no declared domains derive the
        # IDENTITY descriptor (self._pk stays None, traced programs
        # unchanged), so only spec-compiled twins with bounds actually
        # pack.  The device wave loop stores cur/nxt (and the spill
        # spool + checkpoints) at ``self.plane`` words/row; handlers,
        # predicates, and fingerprints always see the unpacked int32
        # view, decoded in-register at expand time — bit-identical
        # unique/explored/verdict to the unpacked path by construction.
        from dslabs_tpu.tpu import packing as packing_mod

        if packed is None:
            packed = os.environ.get(
                "DSLABS_PACKED", "1").strip().lower() not in (
                "0", "off", "false", "no")
        pk = (packing_mod.derive_packing(protocol, self.lanes)
              if packed else None)
        self._pk = None if (pk is None or pk.identity) else pk
        self.plane = (self._pk.words if self._pk is not None
                      else self.lanes)
        # Symmetry reduction (ISSUE 15b, tpu/symmetry.py): OPT-IN and
        # default OFF — canonical unique counts differ from raw counts
        # by design, so the pinned lab counts stay untouched unless a
        # caller asks.  When on, every fingerprint site (expand, root,
        # spill keys — and through _expand_chunk, the sharded
        # owner-hash) hashes the canonical orbit representative.
        if symmetry is None:
            symmetry = os.environ.get(
                "DSLABS_SYMMETRY", "").strip().lower() in (
                "1", "on", "true", "yes")
        if symmetry:
            if protocol.symmetry is None:
                raise ValueError(
                    f"{protocol.name}: symmetry=True but the protocol "
                    "declares no symmetry groups (ProtocolSpec("
                    "symmetry=...))")
            from dslabs_tpu.tpu.symmetry import build_canonicalizer

            self._canon = build_canonicalizer(protocol, self._off)
        else:
            self._canon = None
        # Per-level (parent row, event id) spill for trace reconstruction
        # (SURVEY §8.1; SearchState.java:361-474). Populated by run() when
        # record_trace is set; consumed by tpu/trace.py.
        self._levels: List[dict] = []
        # Fault-event counters accumulated per run (ISSUE 19): numpy
        # [4] = partition / crash / drop / dup valid successor events
        # (counted like states_explored); stamped onto the outcome by
        # _stamp_faults.  Always zeros when protocol.fault is None.
        self._fault_counts = np.zeros((4,), np.int64)
        self._expand = jax.jit(self._expand_chunk)
        # Terminal-flag order = checkState order (Search.java:162-231):
        # exception strictly first, then invariants, then goals.  Shared
        # by the device-resident wave loop and the sharded driver.
        self._flag_names = (["exc"]
                            + [f"inv:{n}" for n in protocol.invariants]
                            + [f"goal:{n}" for n in protocol.goals])
        # Jitted device-loop programs, keyed by frontier-buffer capacity
        # (the buffer grows geometrically on overflow — see _run_device).
        self._dev_progs: Dict[int, tuple] = {}
        # Soundness sanitizer (ISSUE 10): DSLABS_SANITIZE=1 statically
        # audits this engine's dispatch-site programs at build time.
        # Subclasses call _maybe_sanitize at the END of their own
        # __init__ (their programs are not built yet here).
        if type(self) is TensorSearch:
            self._maybe_sanitize()

    # ------------------------------------------------------------- plumbing

    def _maybe_sanitize(self) -> None:
        """DSLABS_SANITIZE build-time hook (dslabs_tpu/analysis): off
        means off — one env read, zero imports, zero dispatches (the
        overhead-guard test pins it).  On, the jaxpr auditor lowers
        every site program and records findings as telemetry events."""
        if os.environ.get("DSLABS_SANITIZE", "").strip().lower() in (
                "", "0", "off", "false", "no"):
            return
        from dslabs_tpu.analysis.jaxpr_audit import sanitize_engine

        sanitize_engine(self)

    def dispatch_site_programs(self) -> Dict[str, dict]:
        """The site-program registry for the sanitizer's jaxpr auditor
        (ISSUE 10): every lowered program this engine dispatches
        through :meth:`_dispatch`, keyed by its dispatch tag (the same
        tags telemetry.DISPATCH_SITES enumerates), with example
        abstract args, the declared donation, and a ``builder`` that
        re-derives the program for the retrace-hazard check.  Pure
        host work: programs are jit-wrapped (already cached) and args
        are ShapeDtypeStructs — nothing here traces, compiles, or
        touches a device."""
        C = self.chunk
        cap = -(-self.frontier_cap // C) * C        # run()'s user_cap
        step, promote, init = self._dev_programs(cap)
        row_sds = jax.ShapeDtypeStruct((1, self.lanes), jnp.int32)
        carry_sds = jax.eval_shape(init, row_sds)
        rt = getattr(self, "_rt_masks", None)
        sites = {
            "device.init": dict(
                fn=init, args=(row_sds,), donate=(), multi=False,
                builder=lambda: jax.jit(self._build_dev_init(cap))),
            "device.step": dict(
                fn=step, args=(carry_sds, rt), donate=(0,),
                multi=False,
                builder=lambda: jax.jit(self._build_dev_step(cap),
                                        donate_argnums=0)),
            "device.promote": dict(
                fn=promote, args=(carry_sds,), donate=(0,),
                multi=False,
                builder=lambda: jax.jit(self._build_dev_promote(cap),
                                        donate_argnums=0)),
        }
        if self._spill is not None:
            progs = self._spill_progs(cap)
            sites["device.spill_drain"] = dict(
                fn=progs["reset"], args=(carry_sds,), donate=(0,),
                multi=False, builder=None)
            sites["device.spill_evict"] = dict(
                fn=progs["evict"], args=(carry_sds,), donate=(0,),
                multi=False, builder=None)
        # The bucket-probe kernel (ISSUE 12): the ACTIVE
        # visited.insert variant (Pallas/jnp per DSLABS_VISITED_PALLAS)
        # standalone over one wave's successor batch, so the auditor
        # and profiler cover the kernel itself.
        sites["visited.insert"] = visited_mod.dispatch_site_program(
            self.visited_cap, C * self._num_events())
        # Capacity round 2 (ISSUE 15): the pack/unpack codecs and the
        # symmetry canonicalize pass are fused INTO the step programs
        # above, but register standalone too (like visited.insert) so
        # the jaxpr auditor (J0-J5) and the profiler's hot-site table
        # cover the codec lowerings themselves.
        if self._pk is not None:
            pk = self._pk
            rows_sds = jax.ShapeDtypeStruct((C, self.lanes), jnp.int32)
            packed_sds = jax.ShapeDtypeStruct((C, self.plane),
                                              jnp.int32)
            sites["packing.pack"] = dict(
                fn=jax.jit(pk.pack_jnp), args=(rows_sds,), donate=(),
                multi=False, builder=lambda: jax.jit(pk.pack_jnp))
            sites["packing.unpack"] = dict(
                fn=jax.jit(pk.unpack_jnp), args=(packed_sds,),
                donate=(), multi=False,
                builder=lambda: jax.jit(pk.unpack_jnp))
        if self._canon is not None:
            rows_sds = jax.ShapeDtypeStruct((C, self.lanes), jnp.int32)
            sites["symmetry.canonicalize"] = dict(
                fn=jax.jit(self._canon), args=(rows_sds,), donate=(),
                multi=False, builder=lambda: jax.jit(self._canon))
        return sites

    def _dispatch(self, tag: str, fn, *args):
        """THE device-dispatch boundary: every hot-loop dispatch and
        blocking readback in this engine (and the sharded subclass)
        funnels through here.  With no hook installed it is a plain
        call; the search supervisor (tpu/supervisor.py) installs its
        retry/watchdog/fault-injection boundary as ``_dispatch_hook``.
        Tags are ``"<engine>.<site>"`` — the engine half keys the
        supervisor's fault plan and per-rung counters.  An attached
        telemetry recorder (tpu/telemetry.py) wraps the WHOLE chain —
        hook included — so every dispatch becomes one structured span
        with zero extra device work."""
        hook = getattr(self, "_dispatch_hook", None)
        tel = getattr(self, "_telemetry", None)
        if tel is not None:
            return tel.record_dispatch(self, tag, hook, fn, *args)
        if hook is None:
            return fn(*args)
        return hook(tag, fn, *args)

    def lane_signature(self) -> Optional[str]:
        """The batched-lane packing key (ISSUE 14, tpu/lanes.py): two
        searches may share a lane-stacked program iff this string
        matches — the checkpoint config fingerprint (protocol lane
        widths + strict) plus every knob that shapes the compiled
        step/promote programs.  ``None`` means the engine is not
        lane-packable (the sharded subclass opts out — its superstep
        is already a whole-mesh program)."""
        from dslabs_tpu.tpu import checkpoint as ckpt_mod

        return "|".join([
            ckpt_mod.config_fingerprint(self.p, self.strict,
                                        self.record_trace),
            f"chunk={self.chunk}", f"fcap={self.frontier_cap}",
            f"vcap={self.visited_cap}",
            f"ev={self._ev_msg},{self._ev_tmr}",
            f"enc={self._frontier_encoding()}",
            f"sym={self.p.symmetry.n_perms if self._canon is not None else 0}"])

    def _cancelled(self) -> bool:
        """Portfolio-lane cancellation (tpu/supervisor.py portfolio
        mode): when the OTHER lane lands a terminal verdict first, the
        supervisor sets this event and every run loop returns a
        TIME_EXHAUSTED-shaped outcome (marked ``cancelled``) at its
        next boundary instead of burning the rest of its budget."""
        ev = getattr(self, "_cancel_event", None)
        return ev is not None and ev.is_set()

    # -------------------------------------------------------- checkpointing

    def _ckpt_fingerprint(self) -> str:
        """The config identity a dump must share to be resumable here
        (engine-agnostic by design — see tpu/checkpoint.py).  The
        symmetry-reduction flag participates: canonical unique counts
        describe the QUOTIENT space, so a reduced dump must never
        silently resume an unreduced search (or vice versa)."""
        from dslabs_tpu.tpu import checkpoint as ckpt_mod

        return ckpt_mod.config_fingerprint(
            self.p, self.strict, self.record_trace,
            symmetry=(self.p.symmetry.n_perms
                      if self._canon is not None else 0))

    def has_resumable_checkpoint(self) -> bool:
        """Existence + fingerprint check WITHOUT loading the arrays."""
        from dslabs_tpu.tpu import checkpoint as ckpt_mod

        if not self.checkpoint_path:
            return False
        fp = ckpt_mod.peek_fingerprint(self.checkpoint_path)
        return fp is not None and fp == self._ckpt_fingerprint()

    def _load_ckpt(self):
        """Load + verify the dump; ``None`` when no file exists, a loud
        CheckpointMismatch when it belongs to a different config.  The
        returned checkpoint's frontier is ALWAYS normalized to raw
        (unpacked) rows — packed dumps decode here (loudly when the
        live engine itself is unpacked), so every consumer (device
        carry, host loop, spill spool, lanes) converts from one
        canonical form."""
        from dslabs_tpu.tpu import checkpoint as ckpt_mod

        if not self.checkpoint_path:
            return None
        ck = ckpt_mod.load(self.checkpoint_path, self._ckpt_fingerprint())
        if ck is not None:
            self._resumed_from_depth = ck.depth
            self._normalize_ckpt_frontier(ck)
        return ck

    def _normalize_ckpt_frontier(self, ck) -> None:
        """Decode a dump's frontier rows to raw int32 lanes per its
        ``frontier_encoding`` marker (ISSUE 15a).  Cross-encoding
        resume is a LOUD conversion; an encoding this protocol cannot
        derive (foreign domain declarations) is a loud refusal —
        never a silent reinterpretation of packed bytes as lanes."""
        from dslabs_tpu.tpu import checkpoint as ckpt_mod
        from dslabs_tpu.tpu import packing as packing_mod

        enc = "raw"
        if ck.extra and "frontier_encoding" in ck.extra:
            enc = np.asarray(ck.extra["frontier_encoding"]
                             ).item()
            if isinstance(enc, bytes):
                enc = enc.decode()
            ck.extra = {k: v for k, v in ck.extra.items()
                        if k != "frontier_encoding"} or None
        if enc == "raw":
            if len(ck.frontier) and ck.frontier.shape[1] != self.lanes:
                raise ckpt_mod.CheckpointMismatch(
                    f"checkpoint frontier rows are "
                    f"{ck.frontier.shape[1]} lanes wide, this "
                    f"protocol's are {self.lanes} — foreign dump")
            return
        pk = self._pk or packing_mod.derive_packing(self.p, self.lanes)
        if pk.identity or pk.signature() != enc:
            raise ckpt_mod.CheckpointMismatch(
                f"refusing to resume packed checkpoint: frontier "
                f"encoding {enc!r} does not match this protocol's "
                f"derived descriptor "
                f"{pk.signature() if not pk.identity else 'raw'!r} "
                "(domain declarations changed, or the dump belongs to "
                "a different spec) — delete the file or restore the "
                "declarations")
        if self._pk is None:
            import warnings

            warnings.warn(
                f"{self.p.name}: resuming a PACKED checkpoint "
                f"({enc}) on an unpacked engine — converting the "
                f"frontier rows (loud by contract, never silent)",
                RuntimeWarning, stacklevel=3)
        # Delta-lane dumps (ISSUE 18 leg (b)) carry the level base the
        # rows were packed against; a delta descriptor without one is
        # a corrupt/foreign dump, refused loudly.
        base = None
        if ck.extra and "pack_base" in ck.extra:
            base = np.asarray(ck.extra["pack_base"],
                              np.int32).reshape(-1)
            ck.extra = {k: v for k, v in ck.extra.items()
                        if k != "pack_base"} or None
        if pk.has_delta and base is None:
            raise ckpt_mod.CheckpointMismatch(
                f"packed checkpoint {enc!r} uses delta lanes but "
                "carries no pack_base vector — corrupt or foreign "
                "dump, refusing to guess a bias")
        ck.frontier = pk.unpack_np(ck.frontier, base) \
            if len(ck.frontier) \
            else np.zeros((0, self.lanes), np.int32)

    @property
    def _ckpt_writer(self):
        from dslabs_tpu.tpu import checkpoint as ckpt_mod

        w = getattr(self, "_ckpt_writer_obj", None)
        if w is None:
            w = self._ckpt_writer_obj = ckpt_mod.AsyncCheckpointWriter()
        return w

    def _kick_ckpt(self, frontier: np.ndarray, visited_keys: np.ndarray,
                   depth: int, explored: int, elapsed: float,
                   vis_over: int = 0) -> None:
        """Queue one async atomic dump (skip-if-busy, never a queue);
        arrays must already be host copies.  Frontier rows are in the
        engine's NATIVE encoding (packed when the spec declares
        domains) — the marker rides the dump so any engine can
        convert on resume (loud, never silent)."""
        from dslabs_tpu.tpu import checkpoint as ckpt_mod

        extra = None
        if self._pk is not None:
            extra = {"frontier_encoding": np.bytes_(
                self._frontier_encoding().encode())}
        ck = ckpt_mod.SearchCheckpoint(
            fingerprint=self._ckpt_fingerprint(), depth=depth,
            explored=explored, elapsed=elapsed, frontier=frontier,
            visited_keys=visited_keys, vis_over=vis_over, extra=extra)
        self._ckpt_writer.kick(
            lambda: ckpt_mod.save(self.checkpoint_path, ck))

    def initial_state(self) -> dict:
        p = self.p
        nodes = jnp.asarray(p.init_nodes(), jnp.int32)[None]
        net = jnp.full((1, p.net_cap, p.msg_width), SENTINEL, jnp.int32)
        init_msgs = np.asarray(p.init_messages(), np.int32).reshape(-1, p.msg_width)
        if init_msgs.shape[0]:
            pad = np.full((p.net_cap - init_msgs.shape[0], p.msg_width),
                          SENTINEL, np.int32)
            net = jnp.asarray(np.concatenate([init_msgs, pad]))[None]
            net = jax.vmap(canonicalize_net)(net)
        timers = jnp.full((1, p.n_nodes, p.timer_cap, p.timer_width),
                          SENTINEL, jnp.int32)
        init_tmrs = np.asarray(p.init_timers(), np.int32)
        if init_tmrs.size:
            timers, dropped = jax.vmap(append_timers)(
                timers, jnp.asarray(init_tmrs, jnp.int32)[None])
            if int(dropped.sum()):
                raise CapacityOverflow(
                    f"{self.p.name}: initial timers overflow timer_cap="
                    f"{p.timer_cap}")
        return {"nodes": nodes, "net": net, "timers": timers,
                "exc": jnp.zeros((1,), jnp.int32)}

    @staticmethod
    def _grid_events(p: TensorProtocol) -> int:
        return p.net_cap + p.n_nodes * p.timer_cap

    def unflatten_rows(self, rows) -> dict:
        """[N, lanes] rows -> batched state pytree (the inverse of
        :func:`flatten_state`); slices/reshapes only, no copies."""
        p = self.p
        o0, o1, o2 = self._off
        n = rows.shape[0]
        return {
            "nodes": rows[:, :o0],
            "net": rows[:, o0:o1].reshape(n, p.net_cap, p.msg_width),
            "timers": rows[:, o1:o2].reshape(
                n, p.n_nodes, p.timer_cap, p.timer_width),
            "exc": rows[:, o2],
        }

    def _slice_state(self, row) -> dict:
        """[lanes] row -> ONE unbatched state dict (views)."""
        p = self.p
        o0, o1, o2 = self._off
        return {
            "nodes": row[:o0],
            "net": row[o0:o1].reshape(p.net_cap, p.msg_width),
            "timers": row[o1:o2].reshape(
                p.n_nodes, p.timer_cap, p.timer_width),
            "exc": row[o2],
        }

    def _num_events(self) -> int:
        """Pair slots per state in the expand program (the successor-row
        stride): the compacted budget when ev_budget is set, else the full
        event grid."""
        return self._ev_slots

    # ------------------------------------------ packing / symmetry

    def _canon_rows(self, rows):
        """Symmetry hash-step hook: the canonical orbit representative
        of each row when the reduction is on, the rows themselves
        otherwise.  ONLY fingerprints flow through here — stored
        states stay the real reachable states."""
        return rows if self._canon is None else self._canon(rows)

    def _canonical_root_fp(self, state):
        """[1, 4] fingerprints of a batch-1 state pytree through the
        SAME canonicalize-then-hash step the expand programs use."""
        from dslabs_tpu.tpu.kernels import fingerprint_rows

        return fingerprint_rows(self._canon_rows(flatten_state(state)))

    def _pack_rows(self, rows):
        """[N, lanes] -> [N, plane] native frontier-storage encoding."""
        return rows if self._pk is None else self._pk.pack_jnp(rows)

    def _unpack_rows(self, rows):
        return rows if self._pk is None else self._pk.unpack_jnp(rows)

    def _frontier_encoding(self) -> str:
        """The marker dumped with every checkpoint's frontier rows."""
        return "raw" if self._pk is None else self._pk.signature()

    def _stamp_capacity(self, out: "SearchOutcome") -> "SearchOutcome":
        """Attach the capacity-round-2 accounting every verdict
        carries (bench/STATUS render it; telemetry compare guards
        bytes_per_state)."""
        out.bytes_per_state = (self._pk.bytes_per_state
                               if self._pk is not None
                               else self.lanes * 4)
        out.bytes_per_state_unpacked = self.lanes * 4
        out.pack_ratio = round(
            out.bytes_per_state_unpacked / max(out.bytes_per_state, 1),
            3)
        out.symmetry_perms = (self.p.symmetry.n_perms
                              if self._canon is not None else 0)
        return out

    # -------------------------------------------- fault plane (ISSUE 19)
    #
    # Every method below is reached only under a trace-time
    # ``p.fault is not None`` guard: a fault-free spec lowers to the
    # byte-identical pre-fault program.  All picks are one-hot /
    # static-index, matching the step kinds' discipline.

    def _fault_down_vec(self, nodes: jnp.ndarray) -> jnp.ndarray:
        """[NN] int32 down flags of ONE state's node vector (0 for
        non-crashable nodes) — a static gather over the controller's
        ``down_*`` lanes."""
        fl = self.p.fault
        z = jnp.zeros((), jnp.int32)
        return jnp.stack([nodes[int(off)] if int(off) >= 0 else z
                          for off in fl.down_off])

    def _fault_msg_ok(self, nodes: jnp.ndarray,
                      msg: jnp.ndarray) -> jnp.ndarray:
        """Deliverability of ONE message row under ONE state's fault
        lanes: blocked while a cut separates frm/to's partition blocks,
        or while the DESTINATION is down (in-flight messages from a
        node that later crashed stay deliverable — they already left).
        Blocked messages stay in the network set, deliverable again
        after HEAL/RESTART; only the DROP event removes them."""
        fl = self.p.fault
        ok = jnp.asarray(True)
        nid = jnp.arange(fl.n_nodes)
        oh_f = nid == msg[1]
        oh_t = nid == msg[2]
        if fl.has_partition:
            blk = jnp.asarray(fl.block_id)
            bf = jnp.sum(oh_f * blk)
            bt = jnp.sum(oh_t * blk)
            cross = (bf >= 0) & (bt >= 0) & (bf != bt)
            ok = ok & ~((nodes[fl.pcut_off] > 0) & cross)
        if fl.n_crashable:
            ok = ok & (jnp.sum(oh_t * self._fault_down_vec(nodes)) == 0)
        return ok

    def _flt_step(self, row: jnp.ndarray, f_idx: jnp.ndarray):
        """Expand ONE state row by ONE fault event (index into the
        fault segment of the grid) -> (successor row, valid, over).
        Fault steps run no handlers and send nothing — they flip
        controller lanes, wipe volatile fields (CRASH) or remove one
        network row (DROP); ``over`` is always 0."""
        p = self.p
        fl = p.fault
        s = self._slice_state(row)
        nodes, net = s["nodes"], s["net"]
        ok = jnp.asarray(False)
        nodes2 = nodes
        net2 = net
        if fl.has_partition:
            is_cut = f_idx == fl.seg_cut
            is_heal = f_idx == fl.seg_heal
            pcut, eras = nodes[fl.pcut_off], nodes[fl.eras_off]
            ok = ok | (is_cut & (pcut == 0)
                       & (eras < fl.model.partition.max_eras)) \
                    | (is_heal & (pcut > 0))
            nodes2 = nodes2.at[fl.pcut_off].set(
                jnp.where(is_cut, 1,
                          jnp.where(is_heal, 0, nodes2[fl.pcut_off])))
            nodes2 = nodes2.at[fl.eras_off].add(
                jnp.where(is_cut, 1, 0))
        for k in range(fl.n_crashable):
            n = int(fl.crash_nodes[k])
            off = int(fl.down_off[n])
            is_c = f_idx == fl.seg_crash + k
            is_r = f_idx == fl.seg_restart + k
            down_n = nodes[off]
            ok = ok | (is_c & (down_n == 0)
                       & (nodes[fl.crashes_off]
                          < fl.model.crash.max_crashes)) \
                    | (is_r & (down_n > 0))
            # Volatile wipe back to declared inits; durable lanes (and
            # every other node's lanes) keep their values.
            nodes2 = jnp.where(is_c & jnp.asarray(fl.wipe[k]),
                               jnp.asarray(fl.init_vec), nodes2)
            nodes2 = nodes2.at[off].set(
                jnp.where(is_c, 1, jnp.where(is_r, 0, nodes2[off])))
            nodes2 = nodes2.at[fl.crashes_off].add(
                jnp.where(is_c, 1, 0))
        if fl.model.max_drops > 0:
            in_drop = (f_idx >= fl.seg_drop) \
                & (f_idx < fl.seg_drop + p.net_cap)
            slot = (f_idx - fl.seg_drop).clip(0, p.net_cap - 1)
            s_oh = jnp.arange(p.net_cap) == slot
            occ = jnp.sum(s_oh * (net[:, 0] != SENTINEL)) > 0
            ok = ok | (in_drop & occ
                       & (nodes[fl.drops_off] < fl.model.max_drops))
            # Static shift-left removal keeps the network set's
            # canonical sorted prefix (same pattern as remove_timer).
            net2 = jnp.where(in_drop, remove_timer(net, slot), net2)
            nodes2 = nodes2.at[fl.drops_off].add(
                jnp.where(in_drop, 1, 0))
        if fl.model.max_dups > 0:
            in_dup = f_idx >= fl.seg_dup
            slot = (f_idx - fl.seg_dup).clip(0, p.net_cap - 1)
            s_oh = jnp.arange(p.net_cap) == slot
            occ = jnp.sum(s_oh * (net[:, 0] != SENTINEL)) > 0
            # Set-semantics delivery never consumes, so a duplicate is
            # behaviorally subsumed; the explicit event binds the dup
            # budget and names the slot in witness traces.
            ok = ok | (in_dup & occ
                       & (nodes[fl.dups_off] < fl.model.max_dups))
            nodes2 = nodes2.at[fl.dups_off].add(
                jnp.where(in_dup, 1, 0))
        row2 = jnp.concatenate([
            nodes2.astype(jnp.int32), net2.reshape(-1),
            s["timers"].reshape(-1), jnp.zeros((1,), jnp.int32)])
        return row2, ok, jnp.int32(0)

    def _fault_event_grid(self, chunk_state: dict) -> jnp.ndarray:
        """[C, n_fault_events] validity grid over the fault segment —
        the fault-side analog of the msg/timer tables in
        :meth:`_event_tables`; validity conditions mirror
        :meth:`_flt_step`'s ``ok`` exactly."""
        p = self.p
        fl = p.fault
        nodesC = chunk_state["nodes"]
        c = nodesC.shape[0]
        cols = []
        if fl.has_partition:
            pcut = nodesC[:, fl.pcut_off]
            eras = nodesC[:, fl.eras_off]
            cols.append(((pcut == 0)
                         & (eras < fl.model.partition.max_eras))[:, None])
            cols.append((pcut > 0)[:, None])
        if fl.n_crashable:
            downs = jnp.stack(
                [nodesC[:, int(fl.down_off[int(n)])] > 0
                 for n in fl.crash_nodes], axis=1)       # [C, nc]
            budget = (nodesC[:, fl.crashes_off]
                      < fl.model.crash.max_crashes)[:, None]
            cols.append(~downs & budget)
            cols.append(downs)
        occ = chunk_state["net"][:, :, 0] != SENTINEL    # [C, net_cap]
        if fl.model.max_drops > 0:
            cols.append(occ & (nodesC[:, fl.drops_off]
                               < fl.model.max_drops)[:, None])
        if fl.model.max_dups > 0:
            cols.append(occ & (nodesC[:, fl.dups_off]
                               < fl.model.max_dups)[:, None])
        return (jnp.concatenate(cols, axis=1) if cols
                else jnp.zeros((c, 0), bool))

    def _fault_chunk_counts(self, event_ids, valids) -> jnp.ndarray:
        """[4] int32 partition/crash/drop/dup VALID successor events in
        one expanded chunk (traced; the device wave loop sums it into
        the carry).  ``event_ids`` [C, B] grid ids, ``valids`` [C*B]."""
        fl = self.p.fault
        base = self.p.net_cap + self.p.n_nodes * self.p.timer_cap
        ev = event_ids.reshape(-1)
        ok = valids & (ev >= base)
        f = ev - base

        def cnt(m):
            return jnp.sum(ok & m).astype(jnp.int32)

        return jnp.stack([
            cnt(f < fl.seg_crash),
            cnt((f >= fl.seg_crash) & (f < fl.seg_drop)),
            cnt((f >= fl.seg_drop) & (f < fl.seg_dup)),
            cnt(f >= fl.seg_dup)])

    def _accum_fault_counts(self, event_ids, valids) -> None:
        """Host-loop twin of :meth:`_fault_chunk_counts`: accumulate
        one chunk's fault-family counts into ``self._fault_counts``
        (numpy, no device work)."""
        fl = self.p.fault
        base = self.p.net_cap + self.p.n_nodes * self.p.timer_cap
        ev = np.asarray(event_ids).reshape(-1)
        ok = np.asarray(valids).reshape(-1) & (ev >= base)
        f = ev - base
        self._fault_counts[0] += int(np.sum(ok & (f < fl.seg_crash)))
        self._fault_counts[1] += int(np.sum(
            ok & (f >= fl.seg_crash) & (f < fl.seg_drop)))
        self._fault_counts[2] += int(np.sum(
            ok & (f >= fl.seg_drop) & (f < fl.seg_dup)))
        self._fault_counts[3] += int(np.sum(ok & (f >= fl.seg_dup)))

    def _stamp_faults(self, out: "SearchOutcome") -> "SearchOutcome":
        """Stamp the run's accumulated fault-event counters onto the
        outcome (zeros when no fault model is declared)."""
        fc = self._fault_counts
        out.partition_events = int(fc[0])
        out.crash_events = int(fc[1])
        out.drop_events = int(fc[2])
        out.dup_events = int(fc[3])
        out.fault_events = int(fc.sum())
        return out

    def _fault_block(self) -> dict:
        """The schema-pinned ``faults`` telemetry block (STATUS.json /
        level records — docs/scenarios.md): cumulative fault-event
        counts by family for the current run."""
        fc = self._fault_counts
        return {"partition_events": int(fc[0]),
                "crash_events": int(fc[1]),
                "drop_events": int(fc[2]),
                "dup_events": int(fc[3]),
                "fault_events": int(fc.sum())}

    def _msg_step_raw(self, row: jnp.ndarray, net_slot: jnp.ndarray):
        """Handler half of a message step (no network merge): ONE state
        row + net slot -> (nodes', sends, timers', exc, ok, t_over).
        All event picks are one-hot 0/1 sums — static indexing only
        (per-pair dynamic gathers materialise at ~1 GB/s under the flat
        vmap on TPU)."""
        p = self.p
        s = self._slice_state(row)
        nodes, net, timers = s["nodes"], s["net"], s["timers"]
        moh = jnp.arange(p.net_cap) == net_slot.clip(0, p.net_cap - 1)
        msg = jnp.sum(moh[:, None] * net, axis=0)
        ok = msg[0] != SENTINEL
        if p.deliver_message is not None:
            ok = ok & p.deliver_message(msg)
        if p.fault is not None:
            ok = ok & self._fault_msg_ok(nodes, msg)
        nodes2, sends, new_t, exc = _normalize_step(
            p.step_message(nodes, msg))
        timers2, t_over = append_timers(timers, new_t)
        return nodes2, sends, timers2, exc, ok, t_over

    def _tmr_step_raw(self, row: jnp.ndarray, t_idx: jnp.ndarray):
        """Handler half of a timer step (no network merge): timer grid
        index t_idx = node * timer_cap + queue slot."""
        p = self.p
        s = self._slice_state(row)
        nodes, net, timers = s["nodes"], s["net"], s["timers"]
        t_node = t_idx // p.timer_cap
        t_slot = t_idx % p.timer_cap
        n_oh = jnp.arange(p.n_nodes) == t_node               # [NN]
        s_oh = jnp.arange(p.timer_cap) == t_slot             # [T_CAP]
        queue = jnp.sum(n_oh[:, None, None] * timers, axis=0)
        ok = jnp.sum(timer_deliverable_mask(queue) * s_oh) > 0
        if p.deliver_timer is not None:
            ok = ok & p.deliver_timer(t_node)
        if p.fault is not None and p.fault.n_crashable:
            # A down node's timers are masked, not cleared — they fire
            # only after restart (a recovered node's stale timers).
            ok = ok & (jnp.sum(n_oh * self._fault_down_vec(nodes)) == 0)
        timer = jnp.sum(s_oh[:, None] * queue, axis=0)
        nodes2, sends, new_t, exc = _normalize_step(
            p.step_timer(nodes, t_node, timer))
        # Firing consumes the timer (SearchState.java:357); the updated
        # queue lands via the node one-hot, never a dynamic scatter.
        fired_q = remove_timer(queue, t_slot)
        timers1 = jnp.where(n_oh[:, None, None], fired_q[None], timers)
        timers2, t_over = append_timers(timers1, new_t)
        return nodes2, sends, timers2, exc, ok, t_over

    def _finish_row(self, net, nodes2, sends, timers2, exc, ok, t_over):
        """Per-pair merge tail (the batched expand uses the TRANSPOSED
        tail in _batched_tail; this form remains for _step_one)."""
        p = self.p
        send_over = jnp.int32(0)
        if (p.max_live_sends is not None
                and p.max_live_sends < p.max_sends):
            sends, send_over = compact_rows(sends, p.max_live_sends)
        net2, net_over = insert_messages(net, sends)
        over = (net_over + t_over + send_over) * ok.astype(jnp.int32)
        row = jnp.concatenate([
            nodes2.astype(jnp.int32), net2.reshape(-1),
            timers2.reshape(-1),
            jnp.asarray(exc, jnp.int32).reshape(1)])
        return row, ok, over
        # An exception-state successor is frozen at the throwing
        # transition: sends/new timers from the faulting handler are
        # still applied (the reference captures the throwable after the
        # hooks ran, SearchState.java:218-222), but the state is terminal
        # (run() ends).

    def _msg_step(self, row: jnp.ndarray, net_slot: jnp.ndarray):
        """ONE state row x message slot -> (successor row, valid, over)."""
        s = self._slice_state(row)
        nodes2, sends, timers2, exc, ok, t_over = self._msg_step_raw(
            row, net_slot)
        return self._finish_row(s["net"], nodes2, sends, timers2, exc,
                                ok, t_over)

    def _tmr_step(self, row: jnp.ndarray, t_idx: jnp.ndarray):
        """ONE state row x timer grid index -> (successor row, valid,
        over)."""
        s = self._slice_state(row)
        nodes2, sends, timers2, exc, ok, t_over = self._tmr_step_raw(
            row, t_idx)
        return self._finish_row(s["net"], nodes2, sends, timers2, exc,
                                ok, t_over)

    def _batched_tail(self, chunk_rows, c, b, nodes2, sendsP, timersP,
                      excP, okP, toverP):
        """Batched TRANSPOSED merge tail: pairs ride the minor axis so
        the set-insert's compare/select ops use all 128 VPU lanes (the
        vmapped per-pair tail used MW = 8 of them — measured ~30x slower
        on the v5e).  The parent network is broadcast from the CHUNK
        rows ([CAP, MW, C] -> [CAP, MW, C*B]) instead of being
        materialised per pair."""
        p = self.p
        pp = c * b
        live = (p.max_live_sends
                if (p.max_live_sends is not None
                    and p.max_live_sends < p.max_sends) else None)
        sendsT = jnp.transpose(sendsP, (1, 2, 0))        # [S, MW, P]
        send_over = jnp.zeros((pp,), jnp.int32)
        if live is not None:
            sendsT, send_over = compact_rows_batched(sendsT, live)
        o0, o1, _ = self._off
        net_rows = chunk_rows[:, o0:o1].reshape(c, p.net_cap,
                                                p.msg_width)
        netT = jnp.transpose(net_rows, (1, 2, 0))        # [CAP, MW, C]
        netT = jnp.broadcast_to(
            netT[:, :, :, None],
            (p.net_cap, p.msg_width, c, b)).reshape(
            p.net_cap, p.msg_width, pp)
        outT, net_over = insert_messages_batched(netT, sendsT)
        net_flat = jnp.transpose(outT, (2, 0, 1)).reshape(pp, -1)
        rows = jnp.concatenate([
            nodes2.astype(jnp.int32), net_flat,
            timersP.reshape(pp, -1),
            excP.astype(jnp.int32).reshape(pp, 1)], axis=1)
        over = (net_over + send_over + toverP) * okP.astype(jnp.int32)
        return rows, over

    def _step_one(self, row: jnp.ndarray, event_idx: jnp.ndarray):
        """Expand ONE state row by ONE grid event id -> (successor row,
        valid, over).  Select-both compatibility wrapper over the split
        kinds — the expand pipeline uses the split grids; this remains
        for trace replay (tpu/trace.py) and external callers."""
        p = self.p
        is_msg = event_idx < p.net_cap
        m = self._msg_step(row, event_idx)
        t = self._tmr_step(row, jnp.maximum(event_idx - p.net_cap, 0))
        out = jax.tree.map(lambda a, b: jnp.where(is_msg, a, b), m, t)
        if p.fault is not None and self._ev_flt:
            tgrid = p.n_nodes * p.timer_cap
            is_flt = event_idx >= p.net_cap + tgrid
            f = self._flt_step(
                row, jnp.maximum(event_idx - p.net_cap - tgrid, 0))
            out = jax.tree.map(
                lambda a, b: jnp.where(is_flt, b, a), out, f)
        return out

    @staticmethod
    def _compact_ids(valid_ev: jnp.ndarray, budget: int, offset=0):
        """[C, G] validity grid -> ([C, budget] compacted indices into G
        (-1 = empty slot), remaining scalar).  One-hot select-reduce over
        the [C, budget, G] cube — static indexing; per-CHUNK, not
        per-pair.

        ``offset`` (static int or traced scalar) selects the event WINDOW
        [offset, offset + budget) by valid-event rank: the spill
        mechanism re-steps a chunk with the next window when
        ``remaining`` (valid events at rank >= offset + budget) is
        nonzero, so a budget smaller than the worst-case event count
        truncates nothing — it just costs extra passes on the rare
        over-budget chunk (the round-3 drop-or-abort became round 4's
        count-then-respill)."""
        c, g = valid_ev.shape
        if budget >= g:
            # Window 0 covers every rank (remaining always 0) — but the
            # OTHER event kind may still spill the chunk, so later passes
            # must present an empty table here or the full-grid kind's
            # events would be re-expanded (and re-counted) every pass.
            ids = jnp.broadcast_to(jnp.arange(g, dtype=jnp.int32), (c, g))
            first = jnp.asarray(offset, jnp.int32) == 0
            return jnp.where(valid_ev & first, ids, -1), jnp.int32(0)
        pos = jnp.cumsum(valid_ev, axis=1) - 1
        hit = valid_ev[:, None, :] & (
            pos[:, None, :] == jnp.arange(budget)[None, :, None] + offset)
        ids = jnp.sum(jnp.where(hit, jnp.arange(g, dtype=jnp.int32)
                                [None, None, :], 0), axis=2)
        ids = jnp.where(jnp.any(hit, axis=2), ids, -1)
        remaining = jnp.sum(valid_ev
                            & (pos >= budget + offset)).astype(jnp.int32)
        return ids, remaining

    def set_runtime_masks(self, marr, tarr) -> None:
        """Install per-run delivery masks (device arrays consumed by the
        protocol's deliver_*_rt fns).  They ride the jitted programs as
        ARGUMENTS, so changing masks never recompiles."""
        import jax.numpy as jnp

        self._rt_masks = (jnp.asarray(marr), jnp.asarray(tarr))

    def _event_tables(self, chunk_rows: jnp.ndarray,
                      chunk_valid: jnp.ndarray, ev_pass=0, masks=None):
        """[C, lanes] chunk -> (msg_ids [C, Bm] net-slot indices, tmr_ids
        [C, Bt] timer grid indices, flt_ids [C, Bf] fault-segment
        indices (``None`` when no fault model), ev_remaining): each
        state's VALID events (occupied network rows + deliverable
        timers, masked by the protocol's deliver_* settings AND the
        fault deliverability mask — exactly the predicates the step
        kinds re-check — plus enabled fault events) packed into
        per-kind pair slots.  ``ev_pass`` selects the budget WINDOW
        (pass w covers valid-event ranks [w*budget, (w+1)*budget) of
        each kind); ``ev_remaining`` counts valid events past the
        current window — spill drivers re-step the chunk at the next
        window until it reaches zero, so a finite budget never
        truncates coverage.  The fault segment is never windowed
        (budget = its full grid), so pass 0 covers it entirely and
        later passes present an empty fault table."""
        p = self.p
        c = chunk_valid.shape[0]
        chunk_state = self.unflatten_rows(chunk_rows)
        msg_ok = chunk_state["net"][:, :, 0] != SENTINEL   # [C, net_cap]
        if p.deliver_message is not None:
            msg_ok = msg_ok & jax.vmap(jax.vmap(p.deliver_message))(
                chunk_state["net"])
        if p.deliver_message_rt is not None and masks is not None:
            marr = masks[0]
            msg_ok = msg_ok & jax.vmap(jax.vmap(
                lambda m: p.deliver_message_rt(m, marr)))(
                chunk_state["net"])
        tmask = jax.vmap(jax.vmap(timer_deliverable_mask))(
            chunk_state["timers"])                         # [C, NN, T_CAP]
        if p.deliver_timer is not None:
            dt = jax.vmap(p.deliver_timer)(jnp.arange(p.n_nodes))
            tmask = tmask & dt[None, :, None]
        if p.deliver_timer_rt is not None and masks is not None:
            tarr = masks[1]
            dt = jax.vmap(lambda nd: p.deliver_timer_rt(nd, tarr))(
                jnp.arange(p.n_nodes))
            tmask = tmask & dt[None, :, None]
        flt_ids = None
        if p.fault is not None:
            fl = p.fault
            nodesC = chunk_state["nodes"]
            nid = jnp.arange(fl.n_nodes)
            net = chunk_state["net"]
            if fl.has_partition:
                # Cross-block messages are blocked while the cut is up
                # (block ids resolved by one-hot over the static table;
                # -1 = unpartitioned node, never blocked).
                blk = jnp.asarray(fl.block_id)
                bf_ = jnp.sum((net[:, :, 1, None] == nid) * blk, axis=2)
                bt_ = jnp.sum((net[:, :, 2, None] == nid) * blk, axis=2)
                cross = (bf_ >= 0) & (bt_ >= 0) & (bf_ != bt_)
                pcut = nodesC[:, fl.pcut_off] > 0
                msg_ok = msg_ok & ~(pcut[:, None] & cross)
            if fl.n_crashable:
                z = jnp.zeros((c,), jnp.int32)
                down = jnp.stack(
                    [nodesC[:, int(off)] if int(off) >= 0 else z
                     for off in fl.down_off], axis=1)     # [C, NN]
                dest_down = jnp.sum(
                    (net[:, :, 2, None] == nid) * down[:, None, :],
                    axis=2)
                msg_ok = msg_ok & (dest_down == 0)
                tmask = tmask & (down == 0)[:, :, None]
            flt_ids, _f_rem = self._compact_ids(
                self._fault_event_grid(chunk_state)
                & chunk_valid[:, None], self._ev_flt,
                ev_pass * self._ev_flt)
        msg_ids, m_rem = self._compact_ids(
            msg_ok & chunk_valid[:, None], self._ev_msg,
            ev_pass * self._ev_msg)
        tmr_ids, t_rem = self._compact_ids(
            tmask.reshape(c, -1) & chunk_valid[:, None], self._ev_tmr,
            ev_pass * self._ev_tmr)
        return msg_ids, tmr_ids, flt_ids, m_rem + t_rem

    def _expand_chunk(self, chunk_rows: jnp.ndarray,
                      chunk_valid: jnp.ndarray, ev_pass=0, masks=None,
                      dedup: Optional[bool] = None):
        """[C, lanes] chunk rows -> successor rows + fingerprints + masks
        + flags.

        Returns (rows [C*B, lanes], valids [C*B], fp [C*B, 4] uint32,
        unique [C*B] in-chunk-first-occurrence mask, overflow scalar,
        ev_remaining scalar (valid events past this pass's window — see
        :meth:`_event_tables`), event_ids [C, B], flags dict) — all
        device arrays; no host sync inside.  B = Bm + Bt, message pair
        slots first per state (successor row = chunk_row * B + slot, the
        arithmetic run()/_reconstruct and the sharded driver use)."""
        p = self.p
        bm, bt = self._ev_msg, self._ev_tmr
        bf = self._ev_flt
        has_flt = p.fault is not None and bf > 0
        c = chunk_valid.shape[0]
        # Dev bisect hook (tools/profile_sharded2.py): expand-internal
        # stages.  Each truncation returns dummy outputs whose shapes
        # match the contract, folding the live stage outputs into the
        # overflow scalar so XLA cannot DCE the work under test.
        stop = getattr(self, "_stop_after", None)

        def _cut(*live):
            b = bm + bt + bf
            acc = jnp.int32(0)
            for x in live:
                acc = acc + jnp.sum(x).astype(jnp.int32)
            return (jnp.zeros((c * b, self.lanes), jnp.int32),
                    jnp.zeros((c * b,), bool),
                    jnp.zeros((c * b, 4), jnp.uint32),
                    jnp.zeros((c * b,), bool), acc, jnp.int32(0),
                    jnp.zeros((c, b), jnp.int32),
                    {f"{kind}:{name}": jnp.zeros((c * b,), bool)
                     for kind, preds in (("inv", p.invariants),
                                         ("goal", p.goals),
                                         ("prune", p.prunes))
                     for name in preds})

        msg_ids, tmr_ids, flt_ids, ev_drops = self._event_tables(
            chunk_rows, chunk_valid, ev_pass, masks)
        if stop == "events":
            return _cut(msg_ids, tmr_ids)
        # TWO flat vmaps — one per event kind, each running only its own
        # machinery (the round-2 select-both design ran BOTH handlers for
        # every pair).  Flat, not nested: a nested
        # vmap-over-events-inside-vmap-over-states compiles the protocol
        # twins' traced-index gathers/scatters into a pathologically slow
        # two-batch-dim scatter path on TPU (~100x); flattening keeps
        # every scatter on the fast single-batch-dim lowering.  The
        # per-state repeat is a broadcast (XLA fuses it into the reads).
        # Only the HANDLER half is vmapped; the network merge runs as
        # ONE batched transposed program per kind (_batched_tail).
        rep_m = jnp.repeat(chunk_rows, bm, axis=0)
        (nodes_m, sends_m, timers_m, exc_m, ok_m,
         tover_m) = jax.vmap(self._msg_step_raw)(
            rep_m, jnp.maximum(msg_ids, 0).reshape(-1))
        rep_t = jnp.repeat(chunk_rows, bt, axis=0)
        (nodes_t, sends_t, timers_t, exc_t, ok_t,
         tover_t) = jax.vmap(self._tmr_step_raw)(
            rep_t, jnp.maximum(tmr_ids, 0).reshape(-1))
        if stop == "handlers":
            return _cut(nodes_m, sends_m, timers_m, ok_m,
                        nodes_t, sends_t, timers_t, ok_t)
        rows_m, over_m = self._batched_tail(
            chunk_rows, c, bm, nodes_m, sends_m, timers_m, exc_m, ok_m,
            tover_m)
        val_m = ok_m & (msg_ids >= 0).reshape(-1)
        rows_t, over_t = self._batched_tail(
            chunk_rows, c, bt, nodes_t, sends_t, timers_t, exc_t, ok_t,
            tover_t)
        val_t = ok_t & (tmr_ids >= 0).reshape(-1)
        if stop == "tail":
            return _cut(rows_m, rows_t)
        # Fault segment (ISSUE 19): no handlers, no sends — _flt_step
        # returns full successor rows directly, so the pairs skip the
        # batched merge tail entirely.
        if has_flt:
            rep_f = jnp.repeat(chunk_rows, bf, axis=0)
            rows_f, ok_f, over_f = jax.vmap(self._flt_step)(
                rep_f, jnp.maximum(flt_ids, 0).reshape(-1))
            val_f = ok_f & (flt_ids >= 0).reshape(-1)

        widths = [bm, bt] + ([bf] if has_flt else [])

        def _inter(*parts):
            return jnp.concatenate(
                [x.reshape((c, w) + x.shape[1:])
                 for x, w in zip(parts, widths)],
                axis=1).reshape((c * sum(widths),) + parts[0].shape[1:])

        if has_flt:
            rows = _inter(rows_m, rows_t, rows_f)
            valids = _inter(val_m, val_t, val_f)
            overs = _inter(over_m, over_t, over_f)
        else:
            rows = _inter(rows_m, rows_t)
            valids = _inter(val_m, val_t)
            overs = _inter(over_m, over_t)
        # Grid event ids for trace spills: timer table entries are
        # net_cap + t_idx in the flat grid numbering; fault entries
        # follow at net_cap + NN*T_CAP + f_idx.
        ev_segs = [msg_ids,
                   jnp.where(tmr_ids >= 0, p.net_cap + tmr_ids, -1)]
        if has_flt:
            tgrid = p.n_nodes * p.timer_cap
            ev_segs.append(jnp.where(flt_ids >= 0,
                                     p.net_cap + tgrid + flt_ids, -1))
        event_ids = jnp.concatenate(ev_segs, axis=1)       # [C, B]
        overflow = jnp.sum(overs * valids.astype(jnp.int32))
        # Symmetry hash step (ISSUE 15b): fingerprints — and through
        # them the sharded owner-hash — key on the canonical orbit
        # representative; the stored rows stay the real states.
        fp = row_fingerprints(self._canon_rows(rows))
        if stop == "fp":
            return _cut(fp, valids)

        if self._in_chunk_dedup if dedup is None else dedup:
            # In-chunk sort-unique on device: first occurrence of each
            # 128-bit key among valid rows (invalid rows sort last and are
            # never unique).  Cuts host dedup work before any readback.
            inv = ~valids
            order = jnp.lexsort((fp[:, 3], fp[:, 2], fp[:, 1], fp[:, 0],
                                 inv))
            fps = fp[order]
            vs = valids[order]
            first = jnp.ones(fps.shape[0], bool).at[1:].set(
                jnp.any(fps[1:] != fps[:-1], axis=1))
            unique = jnp.zeros_like(vs).at[order].set(first & vs)
        else:
            # Sharded path: the owner-side hash table (and its in-batch
            # key sort) is the dedup authority — the prefilter sort here
            # is redundant work; routing buckets are sized for the full
            # successor count.
            unique = valids

        flags = {}
        succ_states = self.unflatten_rows(rows)    # views for predicates
        for kind, preds in (("inv", p.invariants), ("goal", p.goals),
                            ("prune", p.prunes)):
            for name, fn in preds.items():
                flags[f"{kind}:{name}"] = jax.vmap(fn)(succ_states) & valids
        return (rows, valids, fp, unique, overflow, ev_drops, event_ids,
                flags)

    # ----------------------------------------------------------------- run

    def _check_initial(self, state, t0) -> Optional[SearchOutcome]:
        import time
        p = self.p
        for kind, preds in (("inv", p.invariants), ("goal", p.goals)):
            for name, fn in preds.items():
                hit = bool(jax.vmap(fn)(state)[0])
                if kind == "inv" and not hit:
                    return SearchOutcome("INVARIANT_VIOLATED", 1, 1, 0,
                                         time.time() - t0,
                                         violating_state=state,
                                         predicate_name=name)
                if kind == "goal" and hit:
                    return SearchOutcome("GOAL_FOUND", 1, 1, 0,
                                         time.time() - t0,
                                         goal_state=state,
                                         predicate_name=name)
        return None

    def _terminal_outcome(self, rows, np_valids, np_exc, flags,
                          explored, visited_n, depth, t0,
                          level_base_row: int = 0):
        """checkState order: exception -> invariant -> goal
        (Search.java:162-231).  Returns a SearchOutcome or None."""
        import time

        def slice_state(idx):
            return jax.tree.map(
                np.asarray,
                self.unflatten_rows(np.asarray(rows[idx:idx + 1])))

        exc_hit = np_valids & (np_exc != 0)
        if exc_hit.any():
            idx = int(np.nonzero(exc_hit)[0][0])
            return SearchOutcome(
                "EXCEPTION_THROWN", explored, visited_n, depth,
                time.time() - t0, violating_state=slice_state(idx),
                exception_code=int(np_exc[idx]),
                trace=self._reconstruct(level_base_row + idx))
        for kind in ("inv", "goal"):
            for name, f in flags.items():
                if not name.startswith(kind + ":"):
                    continue
                fa = np.asarray(f)
                pname = name.split(":", 1)[1]
                if kind == "inv" and not fa[np_valids].all():
                    idx = int(np.nonzero(np_valids & ~fa)[0][0])
                    return SearchOutcome(
                        "INVARIANT_VIOLATED", explored, visited_n, depth,
                        time.time() - t0, violating_state=slice_state(idx),
                        predicate_name=pname,
                        trace=self._reconstruct(level_base_row + idx))
                if kind == "goal" and fa[np_valids].any():
                    idx = int(np.nonzero(np_valids & fa)[0][0])
                    return SearchOutcome(
                        "GOAL_FOUND", explored, visited_n, depth,
                        time.time() - t0, goal_state=slice_state(idx),
                        predicate_name=pname,
                        trace=self._reconstruct(level_base_row + idx))
        return None

    def _reconstruct(self, row: int) -> Optional[list]:
        """Walk the per-level (parent, event) spill back from a successor
        row of the current level to the initial state -> [event ids] root
        first (SearchState.java:361-371's parent chain, tensorised)."""
        if not self.record_trace or not self._levels:
            return None
        ne = self._num_events()
        events = []
        for lvl in reversed(self._levels):
            parent_chunk_row = row // ne
            if isinstance(lvl["event_ids"], list):
                lvl["event_ids"] = np.concatenate(lvl["event_ids"], axis=0)
            # The pair slot is a compacted rank when ev_budget is set; the
            # level's spilled event table maps it back to the GRID event
            # id (what tpu/trace.py decodes).
            events.append(int(lvl["event_ids"][parent_chunk_row, row % ne]))
            # Map the in-level parent row back through the previous level's
            # kept-state compaction.
            row = int(lvl["parent_rows"][parent_chunk_row])
        events.reverse()
        return events

    def random_rollouts(self, n_walkers: int = 256,
                        n_steps: int = 64, seed: int = 0,
                        initial: Optional[dict] = None,
                        max_secs: Optional[float] = None) -> SearchOutcome:
        """RandomDFS-style DEEP probes: ``n_walkers`` parallel random
        walks of up to ``n_steps`` events each — a walker reaches depth
        d in O(d) steps where BFS must exhaust every shallower level
        first (RandomDFS.java via SURVEY §2.4).

        Since ISSUE 5 this is a thin single-device client of the swarm
        explorer (tpu/swarm.py ``SwarmSearch``) — ONE walker
        implementation, so the probe gains the swarm's shared-table
        dedup, loud overflow-restart accounting (the old loop restarted
        capacity-truncated walkers silently), and the witness pipeline:
        a violation's trace is minimized and replay-verified before the
        verdict returns (``SearchOutcome.witness``).  Verdict
        vocabulary is unchanged: INVARIANT_VIOLATED / EXCEPTION_THROWN
        with a root-first event trace (the tpu/trace.py contract), else
        TIME_EXHAUSTED — exhaustive verdicts stay BFS-only."""
        from dslabs_tpu.tpu.sharded import make_mesh
        from dslabs_tpu.tpu.swarm import SwarmSearch

        sw = SwarmSearch(
            self.p, mesh=make_mesh(1), walkers_per_device=n_walkers,
            max_steps=n_steps, seed=seed, max_secs=max_secs,
            visited_cap=min(self.visited_cap, 1 << 18),
            ev_budget=(self._ev_msg, self._ev_tmr))
        rt = getattr(self, "_rt_masks", None)
        if rt is not None:
            sw.set_runtime_masks(*rt)
        # The probe inherits this engine's supervision boundary (the
        # backend installs transient retry on the engine, and the probe
        # must ride the same seam).
        hook = getattr(self, "_dispatch_hook", None)
        if hook is not None:
            sw._dispatch_hook = hook
        tel = getattr(self, "_telemetry", None)
        if tel is not None:
            sw._telemetry = tel
        out = sw.run(initial=initial, check_initial=False)
        # Expose the walk root for tpu/trace.py replay on THIS engine
        # too (decode_trace reads search._trace_root off whichever
        # search object the caller holds).
        self._trace_root = sw._trace_root
        return out

    def run(self, check_initial: bool = True,
            initial: Optional[dict] = None,
            resume: bool = False) -> SearchOutcome:
        """Run the BFS.  ``initial`` (a batch-1 state pytree, e.g. a prior
        outcome's ``goal_state``) starts the search from an arbitrary
        state — the staged-search pattern (PaxosTest.java:886-1096):
        extract a goal state, change the settings masks
        (``dataclasses.replace(protocol, deliver_message=...)``), and
        search onward from it.  ``resume=True`` continues from
        ``checkpoint_path`` if a fingerprint-matching dump exists (a
        killed search restarts at its last checkpointed level with
        identical final verdict and unique count).

        Dispatch: the device-resident wave loop (:meth:`_run_device` —
        visited table + frontier as donated device buffers, scalar-only
        per-wave host transfers) unless trace recording or
        ``use_host_visited`` demand the legacy host-dedup loop
        (:meth:`run_host`, the parity oracle — trace mode spills
        per-level event tables to the host by design)."""
        tel = getattr(self, "_telemetry", None)
        if tel is not None and self._spill is not None:
            # Spill evict/reinject operations surface as telemetry
            # events (tpu/spill.py) — host bookkeeping only.
            self._spill.telemetry = tel
        if self.record_trace or self.use_host_visited:
            out = self.run_host(check_initial, initial, resume=resume)
            eng = "host"
        else:
            out = self._run_device(check_initial, initial,
                                   resume=resume)
            eng = "device"
        self._stamp_capacity(out)
        self._stamp_faults(out)
        if tel is not None:
            # Trace stamp at span emission (ISSUE 13): the verdict
            # carries the recorder's causal-trace identity — a host
            # string copy, never a device transfer.
            if out.trace_id is None:
                out.trace_id = tel.trace_id
            tel.on_outcome(out, engine=eng)
        return out

    def run_host(self, check_initial: bool = True,
                 initial: Optional[dict] = None,
                 resume: bool = False) -> SearchOutcome:
        """The legacy host-dedup BFS: device expand + in-chunk sort-unique,
        host ``sorted_member`` visited membership.  Kept as (a) the parity
        oracle the device-table loop is tested against and (b) the trace-
        recording path (per-level (parent, event) spills are host-side).
        Same contract as :meth:`run`."""
        import time
        t0 = time.time()
        state = (jax.tree.map(jnp.asarray, initial) if initial is not None
                 else self.initial_state())
        # The root this run's trace event-ids are relative to (staged
        # searches start from arbitrary states; tpu/trace.py replays from
        # here, not from the protocol's initial state).
        self._trace_root = jax.tree.map(np.asarray, state)
        ck = self._load_ckpt() if resume else None
        if ck is not None and self.record_trace:
            raise ValueError(
                "resume + record_trace is unsupported on the host loop "
                "(per-level trace spills cannot be rebuilt from a "
                "checkpoint); rerun without record_trace")
        self._levels = []
        self._host_prev_explored = 0
        self._fault_counts[:] = 0
        if ck is not None:
            # Resume at the checkpointed level boundary: the visited SET
            # comes back from the dumped 128-bit keys, the frontier from
            # the dumped live rows; clocks continue from the dump.
            t0 = time.time() - ck.elapsed
            h1, h2 = host_keys(ck.visited_keys)
            order = np.lexsort((h2, h1))
            visited = (h1[order], h2[order])
            self._host_visited = visited
            explored = ck.explored
            depth = ck.depth
            frontier = jnp.asarray(ck.frontier)
            frontier_n = len(ck.frontier)
            parent_rows = np.full(max(frontier_n, 1), -1, dtype=np.int64)
        else:
            fp0 = np.asarray(self._canonical_root_fp(state))
            visited = host_keys(fp0)
            # Diagnostic stash: the parity tests compare this loop's
            # exact visited SET against the device table's keys.
            self._host_visited = visited
            explored = 0
            depth = 0

            if check_initial:
                out = self._check_initial(state, t0)
                if out is not None:
                    return out

            frontier = flatten_state(state)          # [1, lanes] rows
            # parent_rows[i] = the global successor row (in the PREVIOUS
            # level's enumeration) that produced frontier state i; for
            # the root level it is -1.  Used by _reconstruct.
            parent_rows = np.array([-1], dtype=np.int64)
            frontier_n = 1
        while frontier_n > 0:
            if self.max_depth is not None and depth >= self.max_depth:
                return SearchOutcome("DEPTH_EXHAUSTED", explored,
                                     len(visited[0]), depth,
                                     time.time() - t0)
            if (self.max_secs is not None
                    and time.time() - t0 > self.max_secs) \
                    or self._cancelled():
                return SearchOutcome("TIME_EXHAUSTED", explored,
                                     len(visited[0]), depth,
                                     time.time() - t0,
                                     cancelled=self._cancelled())
            depth += 1
            # Live depth for supervision heartbeats (the dispatch
            # observer reads it — tpu/supervisor.py, tpu/warden.py).
            self._current_depth = depth
            t_lvl = time.time()
            if self.record_trace:
                self._levels.append({"parent_rows": parent_rows,
                                     "event_ids": []})
            # ---- expand all chunks (device), collect level arrays (host)
            lvl_states: List[np.ndarray] = []
            lvl_keys: List[Tuple[np.ndarray, np.ndarray]] = []
            lvl_pruned: List[np.ndarray] = []
            lvl_rows: List[np.ndarray] = []
            ne = self._num_events()
            for start in range(0, frontier_n, self.chunk):
                end = min(start + self.chunk, frontier_n)
                c = end - start
                pad = self.chunk - c
                chunk_rows = (jnp.concatenate(
                    [frontier[start:end],
                     jnp.repeat(frontier[:1], pad, axis=0)], axis=0)
                    if pad else frontier[start:end])
                chunk_valid = jnp.concatenate(
                    [jnp.ones(c, bool), jnp.zeros(pad, bool)])
                rt = getattr(self, "_rt_masks", None)
                (rows_d, valids, fp, unique, overflow, ev_drops, event_ids,
                 flags) = (self._dispatch("host.expand", self._expand,
                                          chunk_rows, chunk_valid, 0, rt)
                           if rt is not None
                           else self._dispatch("host.expand", self._expand,
                                               chunk_rows, chunk_valid))
                if int(overflow):
                    raise CapacityOverflow(
                        f"{self.p.name}: net_cap={self.p.net_cap}, "
                        f"timer_cap={self.p.timer_cap}, or max_live_sends="
                        f"{self.p.max_live_sends} overflowed at depth "
                        f"{depth} ({int(overflow)} drops); raise the caps")
                if int(ev_drops):
                    raise CapacityOverflow(
                        f"{self.p.name}: ev_budget={self._ev_slots} < "
                        f"valid events of some state at depth {depth} "
                        f"({int(ev_drops)} skipped); raise the budget")
                if self.record_trace:
                    self._levels[-1]["event_ids"].append(
                        np.asarray(event_ids))
                np_valids = np.asarray(valids)
                explored += int(np_valids.sum())
                if self.p.fault is not None:
                    self._accum_fault_counts(event_ids, np_valids)
                np_exc = np.asarray(rows_d[:, -1])
                out = self._terminal_outcome(
                    rows_d, np_valids, np_exc, flags, explored,
                    len(visited[0]), depth, t0,
                    level_base_row=start * ne)
                if out is not None:
                    return out

                pruned = np.zeros(len(np_valids), dtype=bool)
                for name, f in flags.items():
                    if name.startswith("prune:"):
                        pruned |= np.asarray(f)
                # Exception states are terminal even when the search
                # continues past them (none here: exceptions end the run).
                keep = np.asarray(unique)
                if keep.any():
                    h1, h2 = host_keys(np.asarray(fp))
                    idxs = np.nonzero(keep)[0]
                    lvl_keys.append((h1[idxs], h2[idxs]))
                    lvl_pruned.append(pruned[idxs])
                    lvl_rows.append(idxs + start * ne)
                    lvl_states.append(np.asarray(rows_d)[idxs])

            if not lvl_keys:
                return SearchOutcome("SPACE_EXHAUSTED", explored,
                                     len(visited[0]), depth,
                                     time.time() - t0)

            # ---- one level-wide dedup (sort-unique + visited membership)
            h1 = np.concatenate([k[0] for k in lvl_keys])
            h2 = np.concatenate([k[1] for k in lvl_keys])
            pruned = np.concatenate(lvl_pruned)
            rows = np.concatenate(lvl_rows)
            order = np.lexsort((h2, h1))
            h1s, h2s = h1[order], h2[order]
            first = np.ones(len(order), dtype=bool)
            first[1:] = (h1s[1:] != h1s[:-1]) | (h2s[1:] != h2s[:-1])
            unique_mask = np.zeros(len(order), dtype=bool)
            unique_mask[order] = first
            fresh = unique_mask & ~sorted_member(visited[0], visited[1],
                                                 h1, h2)

            # ---- merge visited (sorted-merge, stays sorted by (h1, h2))
            if fresh.any():
                nk = np.nonzero(fresh)[0]
                no = np.lexsort((h2[nk], h1[nk]))
                mh1 = np.concatenate([visited[0], h1[nk][no]])
                mh2 = np.concatenate([visited[1], h2[nk][no]])
                mo = np.lexsort((mh2, mh1))
                visited = (mh1[mo], mh2[mo])
                self._host_visited = visited

            expand = fresh & ~pruned
            if not expand.any():
                return SearchOutcome("SPACE_EXHAUSTED", explored,
                                     len(visited[0]), depth,
                                     time.time() - t0)

            keep_idx = np.nonzero(expand)[0]
            tel = getattr(self, "_telemetry", None)
            if tel is not None:
                from dslabs_tpu.tpu import telemetry as tel_mod

                delta = [explored - getattr(self, "_host_prev_explored",
                                            0)]
                self._host_prev_explored = explored
                lvl_rec = {
                    "depth": depth,
                    "wall": round(time.time() - t_lvl, 4),
                    "explored": explored,
                    "unique": int(len(visited[0])),
                    "next_frontier": int(len(keep_idx)),
                    "per_device": {
                        "explored": delta,
                        "frontier": [int(len(keep_idx))],
                        "load_factor": [0.0], "drops": [0]},
                    "skew": {"explored": tel_mod.skew_metrics(delta)}}
                if self.p.fault is not None:
                    lvl_rec["faults"] = self._fault_block()
                tel.on_level("host", lvl_rec)
            # lvl_states rows align 1:1 with h1/h2/rows concatenation.
            all_rows = (np.concatenate(lvl_states, axis=0)
                        if len(lvl_states) > 1 else lvl_states[0])
            nf = all_rows[keep_idx]
            parent_rows = rows[keep_idx]
            frontier_n = len(nf)
            if frontier_n > self.frontier_cap:
                return SearchOutcome("CAPACITY_EXHAUSTED", explored,
                                     len(visited[0]), depth,
                                     time.time() - t0)
            frontier = jnp.asarray(nf)
            if (self.checkpoint_path and self.checkpoint_every
                    and depth % self.checkpoint_every == 0
                    and not self.record_trace):
                # Everything is already host-side here, so the dump is a
                # plain synchronous atomic write (the device loops use
                # the async drain instead — their readback is the cost).
                from dslabs_tpu.tpu import checkpoint as ckpt_mod

                ckpt_mod.save(self.checkpoint_path, ckpt_mod.SearchCheckpoint(
                    fingerprint=self._ckpt_fingerprint(), depth=depth,
                    explored=explored, elapsed=time.time() - t0,
                    frontier=nf,
                    visited_keys=_keys_to_rows(visited)))

        return SearchOutcome("SPACE_EXHAUSTED", explored, len(visited[0]),
                             depth, 0.0)

    # ------------------------------------------- device-resident wave loop

    def _build_dev_step(self, cap: int):
        """One wave step over frontier chunk ``j``: expand -> in-chunk
        sort-unique -> visited-table insert -> frontier-compact append,
        all on device.  The carry is DONATED (run() jits with
        donate_argnums=0), so the table and frontier update in place
        instead of reallocating per wave."""
        p = self.p
        C = self.chunk
        lanes = self.lanes
        plane = self.plane
        pk = self._pk
        # Spill mode (tpu/spill.py): a chunk that would overflow the
        # frontier buffer or leave table keys unresolved ABORTS — every
        # carry entry (the visited table included) reverts to its
        # pre-chunk state and an abort code rides the f_drop stats slot
        # (bit 0 = frontier full, bit 1 = table full).  The host drains
        # nxt to the spool / evicts the table to the host tier, then
        # re-dispatches the SAME chunk against exactly the state it
        # first saw — nothing is ever dropped or double-counted.
        spill_on = self._spill is not None

        def step(carry, masks):
            cur, cur_n = carry["cur"], carry["cur_n"][0]
            j = carry["j"][0]
            start = j * C
            # The frontier buffers hold PACKED rows (ISSUE 15a): the
            # chunk decodes in-register right here — handlers, flags,
            # and fingerprints all operate on the int32 view.
            rows_chunk = jax.lax.dynamic_slice(cur, (start, 0),
                                               (C, plane))
            rows_chunk = self._unpack_rows(rows_chunk)
            valid = (start + jnp.arange(C)) < cur_n
            ev_pass = carry["evp"][0]
            # dedup=False: the visited table below is the dedup
            # authority and resolves in-batch duplicates natively (the
            # per-bucket reservation admits exactly one copy), so the
            # in-chunk sort-unique prefilter is redundant work here —
            # same ~60% chunk-step saving the sharded single-device
            # path measured.  run_host keeps the prefilter (its host
            # merge requires batch-unique keys).
            (rows, valids, fp, unique, overflow, ev_rem, _event_ids,
             flags) = self._expand_chunk(rows_chunk, valid, ev_pass,
                                         masks, dedup=False)
            # Event-window spill (round-4 semantics): valid events past
            # this pass's window re-step the SAME chunk at the next
            # window before j advances — a finite ev_budget costs extra
            # passes, never coverage.
            spill = ev_rem > 0
            j_next = carry["j"] + jnp.where(spill, 0, 1)
            evp_next = jnp.where(spill, carry["evp"] + 1, 0)

            # ---- terminal flags, checkState order (exception first);
            # first-hit successor row kept per flag.
            hit_list = [valids & (rows[:, -1] != 0)]
            for n in p.invariants:
                hit_list.append(valids & ~flags[f"inv:{n}"])
            for n in p.goals:
                hit_list.append(flags[f"goal:{n}"])
            hits = jnp.stack(hit_list)                   # [nf, C*B]
            cnts = jnp.sum(hits, axis=1).astype(jnp.int32)
            idxs = jnp.argmax(hits, axis=1)
            fresh_flag = (carry["flag_cnt"] == 0) & (cnts > 0)
            flag_rows = jnp.where(fresh_flag[:, None], rows[idxs],
                                  carry["flag_rows"])

            pruned = rows[:, -1] != 0        # exception states terminal
            for n in p.prunes:
                pruned = pruned | flags[f"prune:{n}"]

            # ---- device-table dedup (the authority): in-chunk firsts go
            # through the shared open-addressing table; unresolved keys
            # (probe exhausted = table effectively full) are treated as
            # FRESH — sound, may re-explore, never a silent drop — and
            # counted into vis_over (fatal in strict mode at the sync).
            table, inserted, unresolved = visited_mod.insert(
                carry["visited"], fp, unique)
            fresh = inserted | unresolved

            # ---- frontier-compact append of fresh, un-pruned successors
            # Spill mode appends pruned-but-fresh rows TOO: every fresh
            # insert must reach the host refilter so a post-eviction
            # re-discovery of a pruned state is charged to dup_epoch
            # (the drain recomputes the prune mask host-side and drops
            # the rows before they can be re-expanded).
            sel = fresh if spill_on else fresh & ~pruned
            spos = jnp.cumsum(sel) - 1
            nxt_n = carry["nxt_n"][0]
            sdst = jnp.where(sel & (nxt_n + spos < cap), nxt_n + spos, cap)
            # Successors re-encode to the packed storage form before
            # the frontier append.  A live value OUTSIDE its declared
            # domain is counted into the overflow scalar — a wrong
            # spec bound is a loud CapacityOverflow, never silent
            # state corruption.
            if pk is not None:
                rows_store, pack_bad = pk.pack_jnp(rows, count_bad=True)
                overflow = overflow + jnp.sum(
                    pack_bad * sel.astype(jnp.int32))
            else:
                rows_store = rows
            nxt = carry["nxt"].at[sdst].set(rows_store)
            n_sel = jnp.sum(sel).astype(jnp.int32)
            f_drop = jnp.maximum(nxt_n + n_sel - cap, 0)
            n_sel = n_sel - f_drop

            out = {
                "cur": cur, "cur_n": carry["cur_n"],
                "j": j_next, "evp": evp_next,
                "nxt": nxt, "nxt_n": carry["nxt_n"].at[0].add(n_sel),
                "visited": table,
                "vis_n": carry["vis_n"].at[0].add(
                    jnp.sum(inserted).astype(jnp.int32)),
                "explored": carry["explored"].at[0].add(
                    jnp.sum(valids).astype(jnp.int32)),
                "overflow": carry["overflow"].at[0].add(overflow),
                "vis_over": carry["vis_over"].at[0].add(
                    jnp.sum(unresolved).astype(jnp.int32)),
                "f_drop": carry["f_drop"].at[0].add(f_drop),
                "flag_cnt": carry["flag_cnt"] + cnts,
                "flag_rows": flag_rows,
            }
            has_flt = p.fault is not None and self._ev_flt > 0
            if has_flt:
                # Fault-family event counters (ISSUE 19): cumulative
                # like "explored", computed from the event-id table the
                # fault-free program discards — no extra readback, one
                # extra stats lane per family.
                out["fault_cnt"] = carry["fault_cnt"] \
                    + self._fault_chunk_counts(_event_ids, valids)
            if spill_on:
                tbl_full = jnp.any(unresolved)
                front_full = (nxt_n + jnp.sum(sel).astype(jnp.int32)
                              ) > cap
                abort = tbl_full | front_full
                code = (front_full.astype(jnp.int32)
                        + 2 * tbl_full.astype(jnp.int32))
                for k in ("j", "evp", "nxt", "nxt_n", "visited",
                          "vis_n", "explored", "overflow", "vis_over",
                          "flag_cnt", "flag_rows") \
                        + (("fault_cnt",) if has_flt else ()):
                    out[k] = jnp.where(abort, carry[k], out[k])
                out["f_drop"] = jnp.where(abort, code[None],
                                          out["f_drop"])
            # The per-wave scalar stats ride along with every step (the
            # ONLY recurring device->host transfer of the device loop:
            # [explored, overflow, vis_over, f_drop, vis_n, nxt_n, j] ++
            # flag counts ++ (fault model only) fault-family counts) —
            # computed in-program so the sync needs no separate
            # dispatch, and only the LAST chunk's vector of a wave is
            # actually pulled to the host.
            stats = jnp.concatenate([
                jnp.asarray([out["explored"][0], out["overflow"][0],
                             out["vis_over"][0], out["f_drop"][0],
                             out["vis_n"][0], out["nxt_n"][0],
                             out["j"][0]], jnp.int32),
                out["flag_cnt"].astype(jnp.int32)]
                + ([out["fault_cnt"]] if has_flt else []))
            return out, stats

        return step

    def _build_dev_promote(self, cap: int):
        """Between-wave frontier promotion (nxt -> cur), donated like the
        step so the buffers swap in place."""
        plane = self.plane

        def promote(carry):
            out = dict(carry)
            out["cur"] = carry["nxt"][:cap]
            out["cur_n"] = carry["nxt_n"]
            out["nxt"] = jnp.zeros((cap + 1, plane), jnp.int32)
            out["nxt_n"] = jnp.zeros((1,), jnp.int32)
            out["j"] = jnp.zeros((1,), jnp.int32)
            out["evp"] = jnp.zeros((1,), jnp.int32)
            return out

        return promote

    def _build_dev_init(self, cap: int):
        """Carry built ON DEVICE inside one jitted program: only the root
        row crosses the host boundary (UNPACKED — the build packs it
        for storage); the root key is inserted through the same shared
        table code the waves use."""
        lanes = self.lanes
        plane = self.plane
        V = self.visited_cap
        nf = len(self._flag_names)

        def build(row0):
            from dslabs_tpu.tpu.kernels import fingerprint_rows

            fp0 = fingerprint_rows(self._canon_rows(row0))   # [1, 4]
            row0s = self._pack_rows(row0)
            table, _, _ = visited_mod.insert(
                visited_mod.empty_table(V), fp0, jnp.ones((1,), bool))
            out = {
                "cur": jnp.zeros((cap, plane), jnp.int32).at[0].set(
                    row0s[0]),
                "cur_n": jnp.ones((1,), jnp.int32),
                "j": jnp.zeros((1,), jnp.int32),
                "evp": jnp.zeros((1,), jnp.int32),
                "nxt": jnp.zeros((cap + 1, plane), jnp.int32),
                "nxt_n": jnp.zeros((1,), jnp.int32),
                "visited": table,
                "vis_n": jnp.ones((1,), jnp.int32),
                "explored": jnp.zeros((1,), jnp.int32),
                "overflow": jnp.zeros((1,), jnp.int32),
                "vis_over": jnp.zeros((1,), jnp.int32),
                "f_drop": jnp.zeros((1,), jnp.int32),
                "flag_cnt": jnp.zeros((nf,), jnp.int32),
                "flag_rows": jnp.zeros((nf, lanes), jnp.int32),
            }
            if self.p.fault is not None and self._ev_flt > 0:
                out["fault_cnt"] = jnp.zeros((4,), jnp.int32)
            return out

        return build

    def _dev_programs(self, cap: int):
        progs = self._dev_progs.get(cap)
        if progs is None:
            progs = (jax.jit(self._build_dev_step(cap), donate_argnums=0),
                     jax.jit(self._build_dev_promote(cap),
                             donate_argnums=0),
                     jax.jit(self._build_dev_init(cap)))
            self._dev_progs[cap] = progs
        return progs

    def _dev_terminal(self, carry, flag_counts, explored, vis_n, depth,
                      t0, vis_over) -> SearchOutcome:
        """Resolve the first terminal flag (checkState order).  The flag
        rows are the one non-scalar readback of the device loop — paid
        once per RUN, only when a terminal state actually fired."""
        import time

        rows = self._dispatch("device.flags", device_get,
                              carry["flag_rows"])
        for fi, fname in enumerate(self._flag_names):
            if flag_counts[fi] <= 0:
                continue
            st = jax.tree.map(np.asarray,
                              self.unflatten_rows(rows[fi][None]))
            elapsed = time.time() - t0
            if fname == "exc":
                return SearchOutcome(
                    "EXCEPTION_THROWN", explored, vis_n, depth, elapsed,
                    violating_state=st, exception_code=int(st["exc"][0]),
                    visited_overflow=vis_over)
            kind, pname = fname.split(":", 1)
            if kind == "inv":
                return SearchOutcome(
                    "INVARIANT_VIOLATED", explored, vis_n, depth, elapsed,
                    violating_state=st, predicate_name=pname,
                    visited_overflow=vis_over)
            return SearchOutcome(
                "GOAL_FOUND", explored, vis_n, depth, elapsed,
                goal_state=st, predicate_name=pname,
                visited_overflow=vis_over)
        raise AssertionError("flag counts fired without a flag name")

    def _run_device(self, check_initial: bool = True,
                    initial: Optional[dict] = None,
                    resume: bool = False) -> SearchOutcome:
        """The device-resident BFS.  Frontier + visited table live in
        device buffers donated through every wave; host transfers are the
        per-wave stats scalars.  The frontier buffer starts small and
        grows geometrically on overflow (deterministic restart — same
        verdict, amortised cost), up to ``frontier_cap``; overflowing AT
        the cap is the legacy CAPACITY_EXHAUSTED."""
        import time

        t0 = time.time()
        state = (jax.tree.map(jnp.asarray, initial) if initial is not None
                 else self.initial_state())
        self._trace_root = jax.tree.map(np.asarray, state)
        self._fault_counts[:] = 0
        ck = self._load_ckpt() if resume else None
        if ck is not None:
            t0 = time.time() - ck.elapsed
        elif check_initial:
            out = self._check_initial(state, t0)
            if out is not None:
                return out
        C = self.chunk
        user_cap = -(-self.frontier_cap // C) * C
        if self._spill is not None:
            # Spill mode skips the geometric buffer growth (a drain to
            # the host spool replaces every would-be drop, so the only
            # reason to grow is a single chunk's successors exceeding
            # the buffer — which growth cannot amortise anyway) and
            # runs its own per-chunk-synced wave loop.
            try:
                return self._device_attempt_spill(state, user_cap, t0,
                                                  ck)
            finally:
                w = getattr(self, "_ckpt_writer_obj", None)
                if w is not None:
                    w.join()
        # Start the frontier buffer SMALL (2k rows): the per-wave promote
        # zero+copy scales with the buffer, and most searches never need
        # more; the ones that do pay one bounded deterministic restart
        # per x8 growth rung.  A resumed frontier sets the floor.
        cap = min(user_cap, -(-max(C, 1 << 11) // C) * C)
        if ck is not None:
            cap = min(user_cap,
                      max(cap, -(-max(len(ck.frontier), 1) // C) * C))
        try:
            while True:
                # Growth restarts re-seed from the CHECKPOINT when one
                # was loaded (the dump is a consistent level boundary;
                # restarting there is deterministic and cheaper than
                # from the root).
                out = self._device_attempt(state, cap, user_cap, t0, ck)
                if out is not None:
                    return out
                cap = min(cap * 8, user_cap)
        finally:
            w = getattr(self, "_ckpt_writer_obj", None)
            if w is not None:
                # An async dump still draining must land before the
                # caller sees the outcome (kill-resume depends on it).
                w.join()

    def _carry_from_ckpt(self, ck, cap: int):
        """Rebuild the device carry from a unified checkpoint
        (tpu/checkpoint.py): frontier rows pad back to the buffer, the
        visited table is rebuilt by RE-INSERTING the dumped keys (layout
        is engine-local; the key SET is the semantic content), and the
        never-dumped accumulators come back empty — exactly their state
        at a wave boundary."""
        lanes = self.lanes
        plane = self.plane
        V = self.visited_cap
        nf = len(self._flag_names)
        n = len(ck.frontier)
        cur = np.zeros((cap, plane), np.int32)
        if n:
            # ck.frontier is normalized-raw (_load_ckpt); re-encode to
            # the engine's native packed storage.
            cur[:n] = (self._pk.pack_np(ck.frontier)
                       if self._pk is not None else ck.frontier)
        table, n_ins, n_unres = visited_mod.build_table(
            V, ck.visited_keys)
        if n_unres:
            raise CapacityOverflow(
                f"{self.p.name}: visited_cap={V} too small to rebuild "
                f"the checkpoint's visited set ({n_unres} of "
                f"{len(ck.visited_keys)} keys unresolved); raise "
                "visited_cap")
        carry = {
            "cur": jnp.asarray(cur),
            "cur_n": jnp.asarray([n], jnp.int32),
            "j": jnp.zeros((1,), jnp.int32),
            "evp": jnp.zeros((1,), jnp.int32),
            "nxt": jnp.zeros((cap + 1, plane), jnp.int32),
            "nxt_n": jnp.zeros((1,), jnp.int32),
            "visited": table,
            "vis_n": jnp.asarray([n_ins], jnp.int32),
            "explored": jnp.asarray([ck.explored], jnp.int32),
            "overflow": jnp.zeros((1,), jnp.int32),
            "vis_over": jnp.asarray([ck.vis_over], jnp.int32),
            "f_drop": jnp.zeros((1,), jnp.int32),
            "flag_cnt": jnp.zeros((nf,), jnp.int32),
            "flag_rows": jnp.zeros((nf, lanes), jnp.int32),
        }
        if self.p.fault is not None and self._ev_flt > 0:
            # Fault counters are per-PROCESS accounting (like retries):
            # a resumed run counts fault events from the resume point.
            carry["fault_cnt"] = jnp.zeros((4,), jnp.int32)
        return carry

    def _write_dev_ckpt(self, carry, depth: int, explored: int,
                        vis_over: int, nxt_n: int,
                        elapsed: float) -> None:
        """Snapshot the wave-boundary carry into the unified checkpoint:
        the occupied frontier prefix + the occupied visited-table lines
        + counters — never the empty accumulators or buffer padding."""
        if nxt_n:
            frontier = np.asarray(carry["cur"][:nxt_n])
        else:
            frontier = np.zeros((0, self.plane), np.int32)
        table = np.asarray(carry["visited"])[:-1]
        occ = ~(table == visited_mod.MAXU32).all(axis=1)
        self._kick_ckpt(frontier, table[occ], depth, explored, elapsed,
                        vis_over)

    def _device_attempt(self, state, cap: int, user_cap: int,
                        t0, ck=None) -> Optional[SearchOutcome]:
        """One run at a fixed frontier-buffer capacity; None = frontier
        overflowed below the user cap (caller grows and restarts).
        ``ck`` (a loaded SearchCheckpoint) seeds the carry from a dump
        instead of the root."""
        import time

        p = self.p
        C = self.chunk
        step, promote, init = self._dev_programs(cap)
        rt = getattr(self, "_rt_masks", None)
        if ck is not None:
            carry = self._carry_from_ckpt(ck, cap)
            if not len(ck.frontier):
                # A dump saved after the final wave: the search already
                # ended; report the finished verdict from the counters.
                return SearchOutcome(
                    "SPACE_EXHAUSTED", ck.explored,
                    len(ck.visited_keys), ck.depth, time.time() - t0,
                    visited_overflow=ck.vis_over)
        else:
            carry = self._dispatch("device.init", init,
                                   flatten_state(state))
        sdev = None        # stats vector of the latest dispatched step
        # With a finite ev_budget a chunk can spill extra window passes,
        # holding j back — then the sync must watch j and re-dispatch,
        # which precludes the pre-sync speculative dispatch below.
        spill = (self._ev_msg < p.net_cap
                 or self._ev_tmr < p.n_nodes * p.timer_cap)
        if ck is not None:
            depth = ck.depth
            n_chunks = max(1, -(-len(ck.frontier) // C))
            last = (ck.explored, len(ck.visited_keys), ck.vis_over)
        else:
            depth = 0
            n_chunks = 1
            last = (0, 1, 0)   # (explored, unique, vis_over) at last sync
        spec = 0           # chunks of the current wave already dispatched
        while True:
            if (self.max_secs is not None
                    and time.time() - t0 > self.max_secs) \
                    or self._cancelled():
                return SearchOutcome(
                    "TIME_EXHAUSTED", last[0], last[1], depth,
                    time.time() - t0, visited_overflow=last[2],
                    cancelled=self._cancelled())
            if self.max_depth is not None and depth >= self.max_depth:
                return SearchOutcome(
                    "DEPTH_EXHAUSTED", last[0], last[1], depth,
                    time.time() - t0, visited_overflow=last[2])
            depth += 1
            # Live depth for supervision heartbeats (tpu/warden.py).
            self._current_depth = depth
            t_wave = time.time()
            # A checkpoint-due wave skips the speculative next-wave
            # dispatch: the snapshot must see the carry at a clean wave
            # boundary, not mid-way through wave depth+1.
            ckpt_due = bool(self.checkpoint_path and self.checkpoint_every
                            and depth % self.checkpoint_every == 0)
            for _ in range(n_chunks - spec):
                carry, sdev = self._dispatch("device.step", step,
                                             carry, rt)
            if spill:
                while True:
                    s = self._dispatch("device.sync", device_get, sdev)
                    if int(s[6]) >= n_chunks:
                        break
                    for _ in range(n_chunks - int(s[6])):
                        carry, sdev = self._dispatch("device.step", step,
                                                     carry, rt)
                carry = self._dispatch("device.promote", promote, carry)
                spec = 0
            else:
                # Double-buffering: the next wave's promotion AND its
                # first chunk dispatch BEFORE this wave's scalars are
                # read, so host bookkeeping overlaps device compute.  A
                # terminal/empty wave makes the speculative chunk a
                # no-op (flags keep first-hit; empty frontier expands
                # nothing) — the readback below still reports wave k.
                # Single-chunk waves skip the speculation: the chunk
                # would BE the whole next wave, and on termination it is
                # a full expand wasted (the measured 20% overhead on
                # small search spaces).  When the wave's last chunk WAS
                # last wave's speculative dispatch (n_chunks == spec),
                # its stats vector is already in hand.
                wave_stats = sdev
                carry = self._dispatch("device.promote", promote, carry)
                if n_chunks > 1 and not ckpt_due:
                    carry, sdev = self._dispatch("device.step", step,
                                                 carry, rt)
                    spec = 1
                else:
                    spec = 0
                s = self._dispatch("device.sync", device_get, wave_stats)
            (explored, overflow, vis_over, f_drop, vis_n,
             nxt_n) = (int(x) for x in s[:6])
            nf = len(self._flag_names)
            flag_counts = np.asarray(s[7:7 + nf])
            if self.p.fault is not None and self._ev_flt > 0:
                # Cumulative from the carry — overwrite, never add.
                self._fault_counts[:] = np.asarray(
                    s[7 + nf:7 + nf + 4])
            if overflow:
                raise CapacityOverflow(
                    f"{p.name}: net_cap={p.net_cap}, timer_cap="
                    f"{p.timer_cap}, or max_live_sends={p.max_live_sends} "
                    f"overflowed at depth {depth} ({overflow} drops); "
                    "raise the caps")
            # Early-warning instrumentation (ISSUE 6 satellite): table
            # pressure is visible BEFORE the overflow contract fires.
            limit = (3 * self.visited_cap // 4 if self.strict
                     else self.visited_cap)
            if (not getattr(self, "_warned_visited", False)
                    and vis_n >= int(_visited_warn() * limit)):
                self._warned_visited = True
                import warnings

                warnings.warn(
                    f"{p.name}: visited table at {vis_n}/"
                    f"{self.visited_cap} at depth {depth} — capacity "
                    "pressure; raise visited_cap or enable the spill "
                    "tier (spill=True / DSLABS_SPILL=1) before this "
                    "becomes CapacityOverflow",
                    RuntimeWarning, stacklevel=2)
            if vis_over and self.strict:
                raise CapacityOverflow(
                    f"{p.name}: visited table full at depth {depth} "
                    f"({vis_over} unresolved keys, cap "
                    f"{self.visited_cap}); raise visited_cap or run "
                    "strict=False for sound treat-as-fresh degradation")
            if self.strict and vis_n > 3 * self.visited_cap // 4:
                raise CapacityOverflow(
                    f"{p.name}: visited table > 75% full "
                    f"({vis_n}/{self.visited_cap}) at depth {depth}; "
                    "raise visited_cap")
            prev_explored = last[0]
            last = (explored, vis_n, vis_over)
            tel = getattr(self, "_telemetry", None)
            if tel is not None:
                from dslabs_tpu.tpu import telemetry as tel_mod

                # Fed from the wave's fused stats vector — scalars this
                # loop just read anyway (zero extra transfers).  The
                # per-device lanes are length-1 on the single-device
                # engine but keep the mesh-scope record shape uniform
                # (report heatmap / STATUS.json / skew gauges).
                delta = [explored - prev_explored]
                lvl_rec = {
                    "depth": depth,
                    "wall": round(time.time() - t_wave, 4),
                    "explored": explored, "unique": vis_n,
                    "next_frontier": int(nxt_n),
                    "load_factor": round(vis_n / self.visited_cap, 4),
                    "per_device": {
                        "explored": delta, "frontier": [int(nxt_n)],
                        "load_factor": [round(vis_n / self.visited_cap,
                                              4)],
                        "drops": [0]},
                    "skew": {"explored": tel_mod.skew_metrics(delta)}}
                if self.p.fault is not None:
                    lvl_rec["faults"] = self._fault_block()
                tel.on_level("device", lvl_rec)
            self._last_dev_carry = carry
            if flag_counts.any():
                return self._dev_terminal(carry, flag_counts, explored,
                                          vis_n, depth, t0, vis_over)
            if f_drop:
                if cap < user_cap:
                    return None            # grow the buffer and restart
                return SearchOutcome(
                    "CAPACITY_EXHAUSTED", explored, vis_n, depth,
                    time.time() - t0, visited_overflow=vis_over)
            if ckpt_due:
                # Carry is at a clean wave boundary (spec == 0): cur is
                # wave depth+1's frontier, counters are cumulative.
                # Host copies happen HERE (before the next wave donates
                # the buffers); the file write drains asynchronously.
                self._write_dev_ckpt(carry, depth, explored, vis_over,
                                     nxt_n, time.time() - t0)
            if nxt_n == 0:
                return SearchOutcome(
                    "SPACE_EXHAUSTED", explored, vis_n, depth,
                    time.time() - t0, visited_overflow=vis_over)
            n_chunks = -(-nxt_n // C)

    # ----------------------------------------- host-RAM spill tier mode
    #
    # The capacity-laddered variant of the device loop (ISSUE 6,
    # tpu/spill.py, docs/capacity.md).  Same wave cycle, three changes:
    # the step program ABORTS (wholesale revert + code on the f_drop
    # stats slot) instead of dropping frontier rows or leaving table
    # keys unresolved; the host answers an abort by draining nxt to the
    # frontier spool and/or bulk-evicting the visited table to the host
    # fingerprint tier; and once the tier is live, each level boundary
    # re-filters the would-be frontier against it (one batched
    # readback + corrected promote mask — never per-state sync), so
    # "table full" means "slower, still exact" instead of
    # CapacityOverflow.  Syncs are per chunk (no speculation): spill
    # mode is the degraded-capacity gear, correctness over latency.
    # Every host round-trip goes through the _dispatch seam
    # (device.spill_drain / spill_evict / spill_reinject tags), so
    # supervisor retry/watchdog/FaultPlan and the warden's heartbeat
    # cover the spill path like any other dispatch.

    def _spill_progs(self, cap: int) -> dict:
        cache = getattr(self, "_spill_prog_cache", None)
        if cache is None:
            cache = self._spill_prog_cache = {}
        progs = cache.get(cap)
        if progs is not None:
            return progs
        lanes = self.lanes
        V = self.visited_cap

        def reset(carry):
            out = dict(carry)
            out["nxt"] = jnp.zeros((cap + 1, self.plane), jnp.int32)
            out["nxt_n"] = jnp.zeros((1,), jnp.int32)
            out["f_drop"] = jnp.zeros((1,), jnp.int32)
            return out

        def evict(carry):
            out = dict(carry)
            out["visited"] = visited_mod.empty_table(V)
            out["vis_n"] = jnp.zeros((1,), jnp.int32)
            out["f_drop"] = jnp.zeros((1,), jnp.int32)
            return out

        progs = {"reset": jax.jit(reset, donate_argnums=0),
                 "evict": jax.jit(evict, donate_argnums=0),
                 "inject": {}, "fp": {}, "prune": {}}
        cache[cap] = progs
        return progs

    @staticmethod
    def _pow2_bucket(n: int, cap: int) -> int:
        m = 1
        while m < max(n, 1):
            m <<= 1
        return min(m, cap)

    def _spill_keys_of(self, rows: np.ndarray, cap: int) -> np.ndarray:
        """Fingerprints of host rows (UNPACKED lanes) via the SAME
        device fp program the engines hash with — canonicalize pass
        included, so spill-tier keys match the expand keys bit-exactly
        (jitted per pow2 row bucket so compiles stay O(log cap))."""
        from dslabs_tpu.tpu.kernels import fingerprint_rows

        n = len(rows)
        if not n:
            return np.zeros((0, 4), np.uint32)
        m = self._pow2_bucket(n, max(cap, n))
        progs = self._spill_progs(cap)
        fn = progs["fp"].get(m)
        if fn is None:
            fn = progs["fp"][m] = jax.jit(
                lambda r: fingerprint_rows(self._canon_rows(r)))
        pad = np.zeros((m, rows.shape[1]), np.int32)
        pad[:n] = rows
        return np.asarray(fn(jnp.asarray(pad)))[:n]

    def _spill_keep_mask(self, rows: np.ndarray, cap: int) -> np.ndarray:
        """Exception/prune mask recomputed on drained rows (spill mode
        appends pruned-but-fresh rows so they reach the refilter; they
        must not be re-expanded)."""
        rows = np.asarray(rows)
        keep = rows[:, -1] == 0
        if self.p.prunes and len(rows):
            n = len(rows)
            m = self._pow2_bucket(n, max(cap, n))
            progs = self._spill_progs(cap)
            fn = progs["prune"].get(m)
            if fn is None:
                preds = list(self.p.prunes.values())

                def pruned_of(r):
                    st = self.unflatten_rows(r)
                    acc = jnp.zeros((r.shape[0],), bool)
                    for f in preds:
                        acc = acc | jax.vmap(f)(st)
                    return acc

                fn = progs["prune"][m] = jax.jit(pruned_of)
            pad = np.zeros((m, rows.shape[1]), np.int32)
            pad[:n] = rows
            keep &= ~np.asarray(fn(jnp.asarray(pad)))[:n]
        return keep

    def _spill_drain(self, carry, nxt_n: int, cap: int):
        """Mid-level or boundary drain: read nxt's occupied prefix back
        (ONE batched readback of PACKED rows + their canonical keys),
        reset nxt on device, and hand the host half — refilter against
        the tier, drop exception/pruned rows, spool the keepers — to
        the spill manager's drain queue.  With the async gear (ISSUE
        15c, default on) the device re-dispatches the aborted chunk
        IMMEDIATELY after the reset while the host answers the drain
        in the background; ordering through the single worker keeps
        the refilter-before-next-eviction invariant, so counts stay
        exact."""
        sp = self._spill
        pk = self._pk

        def fetch():
            rows = np.asarray(carry["nxt"])[:nxt_n]
            rows_u = pk.unpack_np(rows) if pk is not None else rows
            return rows, self._spill_keys_of(rows_u, cap)

        if nxt_n:
            rows, keys = self._dispatch("device.spill_drain", fetch)

            def host_half():
                kept = sp.refilter(rows, keys)
                if len(kept):
                    ku = (pk.unpack_np(kept) if pk is not None
                          else kept)
                    kept = kept[self._spill_keep_mask(ku, cap)]
                sp.spool(kept)

            sp.submit_drain(host_half)
        return self._dispatch("device.spill_drain",
                              self._spill_progs(cap)["reset"], carry)

    def _spill_evict_dev(self, carry, cap: int):
        """Bulk eviction: occupied table lines -> host tier, table and
        vis_n restart empty (a fresh epoch).  The tier absorb rides
        the same ordered drain queue as the refilters — every drained
        batch is refiltered against the PRE-eviction tier (the
        exactness invariant, docs/capacity.md)."""
        sp = self._spill

        def fetch():
            return visited_mod.host_occupied(
                np.asarray(carry["visited"]))

        occ = self._dispatch("device.spill_evict", fetch)
        sp.submit_drain(lambda: sp.evict(occ), evict=True)
        return self._dispatch("device.spill_evict",
                              self._spill_progs(cap)["evict"], carry)

    def _spill_inject(self, carry, rows: np.ndarray, cap: int):
        """(Re-)inject a host frontier segment (native packed rows) as
        the live cur — the deferred re-expansion wave, at unchanged
        BFS depth."""
        n = len(rows)
        m = self._pow2_bucket(n, cap)
        plane = self.plane
        progs = self._spill_progs(cap)
        fn = progs["inject"].get(m)
        if fn is None:
            def inject(c, seg, nn):
                out = dict(c)
                out["cur"] = jnp.zeros((cap, plane),
                                       jnp.int32).at[:m].set(seg)
                out["cur_n"] = nn
                out["j"] = jnp.zeros((1,), jnp.int32)
                out["evp"] = jnp.zeros((1,), jnp.int32)
                return out

            fn = progs["inject"][m] = jax.jit(inject, donate_argnums=0)
        pad = np.zeros((m, plane), np.int32)
        pad[:n] = rows
        carry = self._dispatch("device.spill_reinject", fn, carry,
                               jnp.asarray(pad),
                               jnp.asarray([n], jnp.int32))
        return carry, n

    def _spill_wave(self, carry, step, rt, cap: int, n_cur: int):
        """Expand the injected frontier completely: per-chunk dispatch
        + sync, answering abort codes (bit 0 frontier full -> drain;
        bit 1 table full -> drain then evict) by re-dispatching the
        same chunk against the recovered capacity."""
        C = self.chunk
        sp = self._spill
        n_chunks = max(1, -(-n_cur // C))
        while True:
            carry, sdev = self._dispatch("device.step", step, carry, rt)
            s = self._dispatch("device.sync", device_get, sdev)
            code = int(s[3])
            vis_n, nxt_n = int(s[4]), int(s[5])
            if code:
                if (code & 1) and nxt_n == 0:
                    raise CapacityOverflow(
                        f"{self.p.name}: one chunk's fresh successors "
                        f"exceed frontier_cap={cap} even with spill; "
                        f"lower chunk ({C}) or raise frontier_cap")
                if (code & 2) and vis_n == 0:
                    raise CapacityOverflow(
                        f"{self.p.name}: one chunk's unique successors "
                        f"exceed visited_cap={self.visited_cap} even "
                        f"from an empty table; lower chunk ({C}) or "
                        "raise visited_cap")
                carry = self._spill_drain(carry, nxt_n, cap)
                if code & 2:
                    carry = self._spill_evict_dev(carry, cap)
                continue
            if int(s[6]) >= n_chunks:
                # The wave's final sync must stay accurate (the caller
                # derives the exact unique count from its vis_n), so
                # end-of-wave eviction is the BOUNDARY's job.
                return carry, s
            # Proactive mid-wave high-water eviction keeps aborts rare:
            # drain whatever nxt holds (pre-eviction refilter order),
            # then evict, then continue the wave on a fresh epoch.
            if sp.should_evict(vis_n, self.visited_cap):
                carry = self._spill_drain(carry, nxt_n, cap)
                carry = self._spill_evict_dev(carry, cap)

    def _spill_ckpt(self, carry, depth: int, explored: int,
                    elapsed: float) -> None:
        """Synchronous unified dump at a spill-mode level boundary:
        ``visited_keys`` = device table ∪ host tier (exact-deduped, so
        the resumer's unique base is len(keys)); ``frontier`` = every
        spooled segment of the level about to run; spill counters ride
        ``extra__spill_stats``.  CRC + .prev rotation come free from
        tpu/checkpoint.py — kill-mid-spill resume is bit-exact."""
        from dslabs_tpu.tpu import checkpoint as ckpt_mod

        sp = self._spill
        occ = visited_mod.host_occupied(np.asarray(carry["visited"]))
        extra = sp.checkpoint_extra()
        if self._pk is not None:
            # Spool segments are stored in the native packed encoding;
            # the marker rides the dump for loud cross-resume.
            extra["frontier_encoding"] = np.bytes_(
                self._frontier_encoding().encode())
        ckpt_mod.save(self.checkpoint_path, ckpt_mod.SearchCheckpoint(
            fingerprint=self._ckpt_fingerprint(), depth=depth,
            explored=explored, elapsed=elapsed,
            frontier=sp.spool_cur.concat(self.plane),
            visited_keys=sp.checkpoint_keys(occ),
            extra=extra))

    def _spill_carry_from_ckpt(self, ck, cap: int):
        """Spill-mode resume: ALL dumped keys load into the host tier,
        the device table restarts empty (a fresh epoch — the refilter
        makes that exact), and the dumped frontier spools in cap-sized
        segments with the first injected as cur."""
        sp = self._spill
        sp.restore(ck.visited_keys, ck.extra)
        rows = np.asarray(ck.frontier, np.int32)
        if self._pk is not None:
            # The loader normalized the dump to raw lanes; the spool
            # holds the engine's native packed rows.
            rows = self._pk.pack_np(rows) if len(rows) else \
                np.zeros((0, self.plane), np.int32)
        for i in range(0, len(rows), cap):
            sp.spool_cur.push(rows[i:i + cap])
        lanes = self.lanes
        plane = self.plane
        nf = len(self._flag_names)
        carry = {
            "cur": jnp.zeros((cap, plane), jnp.int32),
            "cur_n": jnp.zeros((1,), jnp.int32),
            "j": jnp.zeros((1,), jnp.int32),
            "evp": jnp.zeros((1,), jnp.int32),
            "nxt": jnp.zeros((cap + 1, plane), jnp.int32),
            "nxt_n": jnp.zeros((1,), jnp.int32),
            "visited": visited_mod.empty_table(self.visited_cap),
            "vis_n": jnp.zeros((1,), jnp.int32),
            "explored": jnp.asarray([ck.explored], jnp.int32),
            "overflow": jnp.zeros((1,), jnp.int32),
            "vis_over": jnp.zeros((1,), jnp.int32),
            "f_drop": jnp.zeros((1,), jnp.int32),
            "flag_cnt": jnp.zeros((nf,), jnp.int32),
            "flag_rows": jnp.zeros((nf, lanes), jnp.int32),
        }
        if self.p.fault is not None and self._ev_flt > 0:
            carry["fault_cnt"] = jnp.zeros((4,), jnp.int32)
        seg = sp.spool_cur.pop()
        return self._spill_inject(carry, seg, cap)

    def _device_attempt_spill(self, state, cap: int, t0,
                              ck=None) -> SearchOutcome:
        """The spill-mode device BFS (structure mirrors
        _device_attempt; see the section comment above)."""
        import time

        from dslabs_tpu.tpu import spill as spill_mod

        p = self.p
        sp = self._spill
        step, promote, init = self._dev_programs(cap)
        rt = getattr(self, "_rt_masks", None)
        warn_at = spill_mod.visited_warn_threshold()
        if ck is not None:
            if not len(ck.frontier):
                out = SearchOutcome(
                    "SPACE_EXHAUSTED", ck.explored,
                    len(ck.visited_keys), ck.depth, time.time() - t0,
                    visited_overflow=ck.vis_over)
                sp.attach(out)
                return out
            carry, n_cur = self._spill_carry_from_ckpt(ck, cap)
            depth = ck.depth
            explored = ck.explored
            unique = sp.unique(0)
        else:
            # Fresh start: run N must not see run N-1's tier/spool
            # (the warm-up-then-measure reuse pattern) — restore()
            # handles the resume case above.
            sp.reset_run()
            carry = self._dispatch("device.init", init,
                                   flatten_state(state))
            depth = 0
            n_cur = 1
            explored, unique = 0, 1
        while True:
            if (self.max_secs is not None
                    and time.time() - t0 > self.max_secs) \
                    or self._cancelled():
                out = SearchOutcome(
                    "TIME_EXHAUSTED", explored, unique, depth,
                    time.time() - t0, cancelled=self._cancelled())
                sp.attach(out)
                return out
            if self.max_depth is not None and depth >= self.max_depth:
                out = SearchOutcome("DEPTH_EXHAUSTED", explored, unique,
                                    depth, time.time() - t0)
                sp.attach(out)
                return out
            depth += 1
            self._current_depth = depth
            t_lvl = time.time()
            # ---- expand the level: cur, then every spooled segment of
            # the same level as deferred re-expansion waves.
            while True:
                carry, s = self._spill_wave(carry, step, rt, cap, n_cur)
                explored, overflow = int(s[0]), int(s[1])
                vis_over, vis_n, nxt_n = int(s[2]), int(s[4]), int(s[5])
                nf = len(self._flag_names)
                flag_counts = np.asarray(s[7:7 + nf])
                if self.p.fault is not None and self._ev_flt > 0:
                    self._fault_counts[:] = np.asarray(
                        s[7 + nf:7 + nf + 4])
                if overflow:
                    raise CapacityOverflow(
                        f"{p.name}: net_cap={p.net_cap}, timer_cap="
                        f"{p.timer_cap}, or max_live_sends="
                        f"{p.max_live_sends} overflowed at depth "
                        f"{depth} ({overflow} drops); raise the caps")
                if vis_over:
                    raise AssertionError(
                        "spill mode committed unresolved keys (abort "
                        "contract violated)")
                unique = sp.unique(vis_n)
                if flag_counts.any():
                    out = self._dev_terminal(carry, flag_counts,
                                             explored, unique, depth,
                                             t0, 0)
                    sp.attach(out)
                    return out
                load = vis_n / self.visited_cap
                if load >= warn_at and not getattr(
                        self, "_warned_visited", False):
                    self._warned_visited = True
                    import warnings

                    warnings.warn(
                        f"{p.name}: visited table at "
                        f"{load:.0%} of visited_cap="
                        f"{self.visited_cap} at depth {depth} — "
                        "capacity pressure; the spill tier will evict "
                        f"at {sp.config.high_water:.0%}",
                        RuntimeWarning, stacklevel=2)
                seg = sp.pop_current()
                if seg is None:
                    break
                carry, n_cur = self._spill_inject(carry, seg, cap)
            tel = getattr(self, "_telemetry", None)
            if tel is not None:
                from dslabs_tpu.tpu import telemetry as tel_mod

                # Per-level record WITH the spill-overlap wall split
                # (ISSUE 15c satellite): drain_wall = host seconds in
                # drain jobs this level, drain_wait = seconds the
                # driver actually blocked — their gap is host work
                # hidden behind device compute, so the host drain wall
                # is no longer additive with the chunk wall.
                delta = [explored - getattr(self, "_spill_prev_explored",
                                            0)]
                self._spill_prev_explored = explored
                lvl_rec = {
                    "depth": depth,
                    "wall": round(time.time() - t_lvl, 4),
                    "explored": explored, "unique": unique,
                    "next_frontier": int(nxt_n),
                    "load_factor": round(vis_n / self.visited_cap, 4),
                    "spill": sp.level_walls(),
                    "per_device": {
                        "explored": delta, "frontier": [int(nxt_n)],
                        "load_factor": [round(vis_n / self.visited_cap,
                                              4)],
                        "drops": [0]},
                    "skew": {"explored": tel_mod.skew_metrics(delta)}}
                if self.p.fault is not None:
                    lvl_rec["faults"] = self._fault_block()
                tel.on_level("device", lvl_rec)
            # ---- level boundary.  Fast path until the tier/spool is
            # live: the plain on-device promote.
            if not (sp.active
                    or sp.should_evict(vis_n, self.visited_cap)):
                if nxt_n == 0:
                    out = SearchOutcome(
                        "SPACE_EXHAUSTED", explored, unique, depth,
                        time.time() - t0)
                    sp.attach(out)
                    return out
                carry = self._dispatch("device.promote", promote, carry)
                n_cur = nxt_n
                if (self.checkpoint_path and self.checkpoint_every
                        and depth % self.checkpoint_every == 0):
                    self._write_dev_ckpt(carry, depth, explored, 0,
                                         nxt_n, time.time() - t0)
                continue
            # Slow exact path: drain nxt through the refilter, evict at
            # high water (AFTER the drain — the refilter must run
            # against the pre-eviction tier), swap spools, re-inject.
            carry = self._spill_drain(carry, nxt_n, cap)
            if sp.should_evict(vis_n, self.visited_cap):
                carry = self._spill_evict_dev(carry, cap)
                vis_n = 0
            unique = sp.unique(vis_n)
            sp.advance_level()
            if not sp.spool_cur.segments:
                out = SearchOutcome("SPACE_EXHAUSTED", explored, unique,
                                    depth, time.time() - t0)
                sp.attach(out)
                return out
            if (self.checkpoint_path and self.checkpoint_every
                    and depth % self.checkpoint_every == 0):
                self._spill_ckpt(carry, depth, explored,
                                 time.time() - t0)
            seg = sp.spool_cur.pop()
            carry, n_cur = self._spill_inject(carry, seg, cap)
