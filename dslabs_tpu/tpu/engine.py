"""TPU tensor-search engine: vmapped BFS over a frontier of packed states.

This is the component the whole rebuild points at (SURVEY §0, §8,
BASELINE.json): the reference's explicit-state model checker
(framework/tst/.../search/Search.java:405-505 — one thread pops one state,
clones one node, runs one reflective handler) becomes a data-parallel XLA
program:

  frontier [N, ...]  --(enumerate events x vmapped transition)-->
  successors [N*E, ...] --(canonicalise + 128-bit fingerprint)-->
  dedup (sort-unique + sorted-visited membership) --> next frontier

Checker semantics reproduced exactly (SURVEY §7):
  * the network is a SET of fixed-width message records, kept in canonical
    sorted order (Java hashes unordered sets; canonical order makes equal
    states hash equal — SURVEY §8.1 "canonicalization matters");
    delivery never removes a message (SearchState.java:300);
  * per-node timer queues keep insertion order; a timer is deliverable iff
    no earlier-queued timer t' has t.min >= t'.max (TimerQueue.java:66-105),
    computed as a vectorised prefix-min; firing removes the timer;
  * dedup happens on successor generation, pre-check (Search.java:485);
    equivalence keys on (node lanes, network set, timer queues) via a
    128-bit fingerprint (hash compaction; collision odds ~n^2 / 2^128).

The engine is protocol-agnostic: a :class:`TensorProtocol` supplies packed
node-state lanes and a pure ``step(state, event)`` transition; the engine
owns event enumeration, network-set insertion, canonicalisation,
fingerprinting, dedup, predicate checks, and frontier compaction.  Multi-
chip scaling shards the frontier over a mesh and exchanges successor
fingerprints by hash ownership (see ``dslabs_tpu/tpu/sharded.py``).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable, Dict, List, Optional, Tuple

import jax

# 64-bit fingerprints need x64 lanes (TPU emulates int64; the fingerprint
# arithmetic is a tiny fraction of the level step).
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

__all__ = ["TensorProtocol", "TensorState", "TensorSearch", "SearchOutcome",
           "SENTINEL"]

# Empty slots in the network / timer arrays hold SENTINEL in every lane, so
# they sort after every real record and hash consistently.
SENTINEL = np.int32(2 ** 31 - 1)


# --------------------------------------------------------------------- state

class TensorState(Dict[str, jnp.ndarray]):
    """A batch of packed search states (struct-of-arrays pytree):

    nodes  [N, NW]            int32 — all nodes' packed protocol fields
    net    [N, NET_CAP, MW]   int32 — canonical-sorted message set
    timers [N, NN, T_CAP, TW] int32 — per-node timer queues, insertion order
                                      (lane 0 = tag, lane 1 = min, lane 2 =
                                      max, rest payload)
    """


@dataclasses.dataclass(frozen=True)
class TensorProtocol:
    """Contract a tensorised protocol twin fulfils.

    The transition functions operate on ONE state (the engine vmaps them):

    ``step_message(nodes, msg) -> (nodes', sends, new_timers)``
    ``step_timer(nodes, node_idx, timer) -> (nodes', sends, new_timers)``

    where ``sends`` is ``[MAX_SENDS, MW]`` with invalid rows = SENTINEL and
    ``new_timers`` is ``[MAX_SETS, 1 + TW]`` (leading lane = target node
    index, SENTINEL rows invalid).
    """

    name: str
    n_nodes: int
    node_width: int
    msg_width: int
    timer_width: int
    net_cap: int
    timer_cap: int
    max_sends: int
    max_sets: int
    init_nodes: Callable[[], np.ndarray]
    init_messages: Callable[[], np.ndarray]   # [k, MW] initial network
    init_timers: Callable[[], np.ndarray]     # [k, 1 + TW] initial timer sets
    step_message: Callable
    step_timer: Callable
    # message -> destination node index (for delivery gating); jax fn
    msg_dest: Callable
    # state-level predicates: dict name -> vmapped-able fn(state_slice)->bool
    invariants: Dict[str, Callable] = dataclasses.field(default_factory=dict)
    goals: Dict[str, Callable] = dataclasses.field(default_factory=dict)
    prunes: Dict[str, Callable] = dataclasses.field(default_factory=dict)
    # optional masks: deliver_message(msg)->bool, deliver_timer(node)->bool
    deliver_message: Optional[Callable] = None
    deliver_timer: Optional[Callable] = None


@dataclasses.dataclass
class SearchOutcome:
    end_condition: str               # GOAL_FOUND / INVARIANT_VIOLATED /
                                     # SPACE_EXHAUSTED / CAPACITY_EXHAUSTED /
                                     # DEPTH_EXHAUSTED
    states_explored: int
    unique_states: int
    depth: int
    elapsed_secs: float
    violating_state: Optional[dict] = None
    goal_state: Optional[dict] = None
    predicate_name: Optional[str] = None


# ----------------------------------------------------------------- hashing

def _mix32(x: jnp.ndarray, seed: jnp.ndarray) -> jnp.ndarray:
    """xorshift-multiply mixer over int32 lanes (vectorised)."""
    x = x.astype(jnp.uint32) ^ (seed.astype(jnp.uint32) * jnp.uint32(0x9E3779B9))
    x = (x ^ (x >> 16)) * jnp.uint32(0x85EBCA6B)
    x = (x ^ (x >> 13)) * jnp.uint32(0xC2B2AE35)
    return x ^ (x >> 16)


def _fingerprint(flat: jnp.ndarray, seed: int) -> jnp.ndarray:
    """64-bit fingerprint of [N, L] int32 rows -> [N] int64.

    Sequential-free: each lane is mixed with its position and a seed, then
    lanes are combined with addition and a final avalanche (order within the
    row still matters via the positional term)."""
    n, l = flat.shape
    pos = jnp.arange(l, dtype=jnp.uint32)[None, :] + jnp.uint32(seed * 0x1000193)
    h = _mix32(flat, pos)
    lo = jnp.sum(h, axis=1, dtype=jnp.uint32)
    hi = jnp.sum(_mix32(h, pos + jnp.uint32(0x27D4EB2F)), axis=1,
                 dtype=jnp.uint32)
    return (hi.astype(jnp.int64) << 32) | lo.astype(jnp.int64)


def state_fingerprints(state: dict) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Two independent 64-bit fingerprints per state (128-bit key)."""
    n = state["nodes"].shape[0]
    flat = jnp.concatenate([
        state["nodes"].reshape(n, -1),
        state["net"].reshape(n, -1),
        state["timers"].reshape(n, -1),
    ], axis=1)
    return _fingerprint(flat, 1), _fingerprint(flat, 2)


# ------------------------------------------------------------ net/timer ops

def canonicalize_net(net: jnp.ndarray) -> jnp.ndarray:
    """Sort the message set into canonical order and collapse duplicates.

    [CAP, MW] -> [CAP, MW]; empty rows are all-SENTINEL and sort last.
    Records are ordered by their packed fingerprint (any total order works
    for canonicalisation as long as it is content-determined)."""
    cap, mw = net.shape

    def keys(rows):
        empty = rows[:, 0] == SENTINEL
        return empty, _fingerprint(rows, 3), _fingerprint(rows, 4)

    empty, key1, key2 = keys(net)
    # lexsort: LAST key is primary — empty rows always sort to the back.
    order = jnp.lexsort((key2, key1, empty))
    net = net[order]
    key1, key2, empty = key1[order], key2[order], empty[order]
    dup = jnp.zeros(cap, dtype=bool).at[1:].set(
        (key1[1:] == key1[:-1]) & (key2[1:] == key2[:-1]) & ~empty[1:])
    net = jnp.where(dup[:, None], SENTINEL, net)
    # One more sort pushes the duplicate-cleared rows to the back.
    empty, key1, key2 = keys(net)
    order = jnp.lexsort((key2, key1, empty))
    return net[order]


def insert_messages(net: jnp.ndarray, sends: jnp.ndarray) -> jnp.ndarray:
    """Set-insert up to MAX_SENDS records into the canonical network.

    Concatenate, canonicalise (dedup), and truncate back to capacity.  A
    genuine overflow would silently drop the largest-keyed record; protocols
    size NET_CAP so this cannot happen within the searched depth."""
    cap = net.shape[0]
    combined = jnp.concatenate([net, sends], axis=0)
    return canonicalize_net(combined)[:cap]


def timer_deliverable_mask(queue: jnp.ndarray) -> jnp.ndarray:
    """[T_CAP, TW] -> [T_CAP] bool: the TimerQueue partial order
    (TimerQueue.java:66-105).  Lane 1 = min, lane 2 = max; empty rows are
    SENTINEL.  deliverable[i] = occupied[i] and min[i] < min(max[j] for
    occupied j < i) (strictly: NOT exists earlier t' with t.min >= t'.max)."""
    occupied = queue[:, 0] != SENTINEL
    maxes = jnp.where(occupied, queue[:, 2], SENTINEL)
    prefix_min = jnp.concatenate([
        jnp.array([SENTINEL], dtype=maxes.dtype),
        jax.lax.cummin(maxes)[:-1]])
    return occupied & (queue[:, 1] < prefix_min)


def remove_timer(queue: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """Remove the timer at position idx, shifting later entries left
    (insertion order is semantic — it drives the partial order)."""
    cap = queue.shape[0]
    pos = jnp.arange(cap)
    src = jnp.where(pos >= idx, pos + 1, pos).clip(0, cap - 1)
    shifted = queue[src]
    shifted = shifted.at[cap - 1].set(SENTINEL)
    return jnp.where((pos >= idx)[:, None], shifted, queue)


def append_timers(timers: jnp.ndarray, new_timers: jnp.ndarray) -> jnp.ndarray:
    """Append [MAX_SETS, 1+TW] records (lane 0 = node idx) to the per-node
    queues [NN, T_CAP, TW], preserving insertion order."""
    nn, cap, tw = timers.shape

    def one_append(tmrs, rec):
        node = rec[0]
        # A full queue DROPS the append rather than clobbering the last
        # slot — insertion order is semantic.  Protocols must size
        # timer_cap for the searched depth (as with NET_CAP overflow).
        def body(t):
            q = t[node]
            count = jnp.sum(q[:, 0] != SENTINEL)
            has_room = count < cap
            q = q.at[count.clip(0, cap - 1)].set(
                jnp.where(has_room, rec[1:], q[count.clip(0, cap - 1)]))
            return t.at[node].set(q)
        return jax.lax.cond(rec[0] != SENTINEL, body, lambda t: t, tmrs), None

    timers, _ = jax.lax.scan(one_append, timers, new_timers)
    return timers


# ------------------------------------------------------------------- engine

class TensorSearch:
    """Single-device BFS driver.  One jitted program expands a frontier
    chunk into successors; the host loop handles level accounting, visited
    merging, and termination."""

    def __init__(self, protocol: TensorProtocol,
                 frontier_cap: int = 1 << 16,
                 chunk: int = 1 << 12,
                 max_depth: Optional[int] = None,
                 max_secs: Optional[float] = None):
        self.p = protocol
        self.frontier_cap = frontier_cap
        self.chunk = chunk
        self.max_depth = max_depth
        self.max_secs = max_secs
        self._expand = jax.jit(self._expand_chunk)

    # ------------------------------------------------------------- plumbing

    def initial_state(self) -> dict:
        p = self.p
        nodes = jnp.asarray(p.init_nodes(), jnp.int32)[None]
        net = jnp.full((1, p.net_cap, p.msg_width), SENTINEL, jnp.int32)
        init_msgs = np.asarray(p.init_messages(), np.int32).reshape(-1, p.msg_width)
        if init_msgs.shape[0]:
            pad = np.full((p.net_cap - init_msgs.shape[0], p.msg_width),
                          SENTINEL, np.int32)
            net = jnp.asarray(np.concatenate([init_msgs, pad]))[None]
            net = jax.vmap(canonicalize_net)(net)
        timers = jnp.full((1, p.n_nodes, p.timer_cap, p.timer_width),
                          SENTINEL, jnp.int32)
        init_tmrs = np.asarray(p.init_timers(), np.int32)
        if init_tmrs.size:
            timers = jax.vmap(append_timers)(
                timers, jnp.asarray(init_tmrs, jnp.int32)[None])
        return {"nodes": nodes, "net": net, "timers": timers}

    def _num_events(self) -> int:
        return self.p.net_cap + self.p.n_nodes * self.p.timer_cap

    def _step_one(self, state_slice: dict, event_idx: jnp.ndarray):
        """Expand ONE state by ONE event index -> (successor, valid)."""
        p = self.p
        nodes, net, timers = (state_slice["nodes"], state_slice["net"],
                              state_slice["timers"])
        is_msg = event_idx < p.net_cap

        def deliver_message():
            msg = net[event_idx.clip(0, p.net_cap - 1)]
            occupied = msg[0] != SENTINEL
            ok = occupied
            if p.deliver_message is not None:
                ok = ok & p.deliver_message(msg)
            nodes2, sends, new_timers = p.step_message(nodes, msg)
            return nodes2, sends, new_timers, None, ok

        def deliver_timer():
            t_idx = event_idx - p.net_cap
            node = t_idx // p.timer_cap
            slot = t_idx % p.timer_cap
            queue = timers[node]
            ok = timer_deliverable_mask(queue)[slot]
            if p.deliver_timer is not None:
                ok = ok & p.deliver_timer(node)
            timer = queue[slot]
            nodes2, sends, new_timers = p.step_timer(nodes, node, timer)
            return nodes2, sends, new_timers, (node, slot), ok

        m_nodes, m_sends, m_set, _, m_ok = deliver_message()
        t_nodes, t_sends, t_set, (t_node, t_slot), t_ok = deliver_timer()

        nodes2 = jnp.where(is_msg, m_nodes, t_nodes)
        sends = jnp.where(is_msg, m_sends, t_sends)
        new_t = jnp.where(is_msg, m_set, t_set)
        valid = jnp.where(is_msg, m_ok, t_ok)

        net2 = insert_messages(net, sends)
        timers2 = timers
        # Firing consumes the timer (SearchState.java:357).
        fired_q = remove_timer(timers[t_node], t_slot)
        timers2 = jnp.where(is_msg, timers2,
                            timers2.at[t_node].set(fired_q))
        timers2 = append_timers(timers2, new_t)
        return {"nodes": nodes2, "net": net2, "timers": timers2}, valid

    def _expand_chunk(self, chunk_state: dict, chunk_valid: jnp.ndarray):
        """[C]-state chunk -> all successors + fingerprints + flags."""
        p = self.p
        ne = self._num_events()
        ev = jnp.arange(ne)

        def per_state(slice_, v):
            succ, valid = jax.vmap(
                lambda e: self._step_one(slice_, e))(ev)
            return succ, valid & v

        succs, valids = jax.vmap(per_state)(chunk_state, chunk_valid)
        flat = jax.tree.map(
            lambda x: x.reshape((-1,) + x.shape[2:]), succs)
        valids = valids.reshape(-1)
        h1, h2 = state_fingerprints(flat)
        h1 = jnp.where(valids, h1, jnp.int64(2 ** 62))
        flags = {}
        for kind, preds in (("inv", p.invariants), ("goal", p.goals),
                            ("prune", p.prunes)):
            for name, fn in preds.items():
                flags[f"{kind}:{name}"] = jax.vmap(fn)(flat) & valids
        return flat, valids, h1, h2, flags

    # ----------------------------------------------------------------- run

    def run(self, check_initial: bool = True) -> SearchOutcome:
        import time
        t0 = time.time()
        p = self.p
        state = self.initial_state()
        h1, h2 = state_fingerprints(state)
        visited = (np.asarray(h1), np.asarray(h2))
        explored = 0
        depth = 0

        if check_initial:
            for kind, preds in (("inv", p.invariants), ("goal", p.goals)):
                for name, fn in preds.items():
                    hit = bool(jax.vmap(fn)(state)[0])
                    if kind == "inv" and not hit:
                        return SearchOutcome("INVARIANT_VIOLATED", 1, 1, 0,
                                             time.time() - t0,
                                             predicate_name=name)
                    if kind == "goal" and hit:
                        return SearchOutcome("GOAL_FOUND", 1, 1, 0,
                                             time.time() - t0,
                                             goal_state=state,
                                             predicate_name=name)

        frontier = state
        frontier_n = 1
        while frontier_n > 0:
            if self.max_depth is not None and depth >= self.max_depth:
                return SearchOutcome("DEPTH_EXHAUSTED", explored,
                                     len(visited[0]), depth,
                                     time.time() - t0)
            if self.max_secs is not None and time.time() - t0 > self.max_secs:
                return SearchOutcome("TIME_EXHAUSTED", explored,
                                     len(visited[0]), depth,
                                     time.time() - t0)
            depth += 1
            new_states: List[dict] = []
            new_keys: List[Tuple[np.ndarray, np.ndarray]] = []
            outcome = None
            for start in range(0, frontier_n, self.chunk):
                end = min(start + self.chunk, frontier_n)
                c = end - start
                pad = self.chunk - c
                chunk_state = jax.tree.map(
                    lambda x: jnp.concatenate(
                        [x[start:end],
                         jnp.repeat(x[:1], pad, axis=0)], axis=0)
                    if pad else x[start:end], frontier)
                chunk_valid = jnp.concatenate(
                    [jnp.ones(c, bool), jnp.zeros(pad, bool)])
                flat, valids, h1, h2, flags = self._expand(
                    chunk_state, chunk_valid)
                explored += int(jnp.sum(valids))

                # Terminal checks in checkState order: invariants strictly
                # before goals (Search.java:162-231) — jit canonicalises
                # dict outputs to sorted key order, so order explicitly.
                np_valids = np.asarray(valids)
                for kind in ("inv", "goal"):
                    for name, f in flags.items():
                        if not name.startswith(kind + ":"):
                            continue
                        fa = np.asarray(f)
                        pname = name.split(":", 1)[1]
                        if kind == "inv" and not fa[np_valids].all():
                            idx = int(np.nonzero(np_valids & ~fa)[0][0])
                            bad = jax.tree.map(lambda x: x[idx:idx + 1], flat)
                            return SearchOutcome(
                                "INVARIANT_VIOLATED", explored,
                                len(visited[0]), depth, time.time() - t0,
                                violating_state=bad, predicate_name=pname)
                        if kind == "goal" and fa[np_valids].any():
                            idx = int(np.nonzero(np_valids & fa)[0][0])
                            good = jax.tree.map(lambda x: x[idx:idx + 1], flat)
                            return SearchOutcome(
                                "GOAL_FOUND", explored, len(visited[0]),
                                depth, time.time() - t0, goal_state=good,
                                predicate_name=pname)

                pruned = np.zeros(len(np_valids), dtype=bool)
                for name, f in flags.items():
                    if name.startswith("prune:"):
                        pruned |= np.asarray(f)

                # Dedup: in-chunk sort-unique, then against visited.  Pruned
                # states count as discovered (dedup happens on generation,
                # Search.java:485) but are not expanded.
                h1n, h2n = np.asarray(h1), np.asarray(h2)
                keep = np.array(np_valids)  # writable copy
                order = np.lexsort((h2n, h1n))
                h1s, h2s = h1n[order], h2n[order]
                first = np.ones(len(order), dtype=bool)
                first[1:] = (h1s[1:] != h1s[:-1]) | (h2s[1:] != h2s[:-1])
                unique_mask = np.zeros(len(order), dtype=bool)
                unique_mask[order] = first
                keep &= unique_mask
                # Membership against visited + already-collected this level.
                vh1, vh2 = visited
                pos = np.searchsorted(vh1, h1n)
                seen = np.zeros(len(h1n), dtype=bool)
                for off in range(2):
                    q = (pos + off).clip(0, max(len(vh1) - 1, 0))
                    if len(vh1):
                        seen |= (vh1[q] == h1n) & (vh2[q] == h2n)
                for kh1, kh2 in new_keys:
                    kpos = np.searchsorted(kh1, h1n)
                    for off in range(2):
                        q = (kpos + off).clip(0, max(len(kh1) - 1, 0))
                        if len(kh1):
                            seen |= (kh1[q] == h1n) & (kh2[q] == h2n)
                keep &= ~seen
                if keep.any():
                    kidxs = np.nonzero(keep)[0]
                    ko = np.lexsort((h2n[kidxs], h1n[kidxs]))
                    new_keys.append((h1n[kidxs][ko], h2n[kidxs][ko]))
                expand = keep & ~pruned
                if expand.any():
                    idxs = np.nonzero(expand)[0]
                    new_states.append(jax.tree.map(
                        lambda x: np.asarray(x)[idxs], flat))

            if new_keys:
                all_h1 = np.concatenate([k[0] for k in new_keys])
                all_h2 = np.concatenate([k[1] for k in new_keys])
                mh1 = np.concatenate([visited[0], all_h1])
                mh2 = np.concatenate([visited[1], all_h2])
                mo = np.lexsort((mh2, mh1))
                visited = (mh1[mo], mh2[mo])

            if not new_states:
                return SearchOutcome("SPACE_EXHAUSTED", explored,
                                     len(visited[0]), depth,
                                     time.time() - t0)

            nf = jax.tree.map(
                lambda *xs: np.concatenate(xs, axis=0),
                *new_states) if len(new_states) > 1 else new_states[0]
            frontier_n = len(nf["nodes"])
            if frontier_n > self.frontier_cap:
                return SearchOutcome("CAPACITY_EXHAUSTED", explored,
                                     len(visited[0]), depth,
                                     time.time() - t0)
            frontier = jax.tree.map(jnp.asarray, nf)

        return SearchOutcome("SPACE_EXHAUSTED", explored, len(visited[0]),
                             depth, 0.0)
