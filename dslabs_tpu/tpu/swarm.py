"""Device-sharded swarm explorer: diversified random-walk fleets.

The checking power of the reference comes from a BFS + RandomDFS
*portfolio* (SURVEY §2.4): BFS proves shallow exhaustiveness, random
deep probes hit the deep-narrow violations BFS cannot reach inside a
budget.  This module is the accelerator-native second half of that
portfolio, in the spirit of swarm verification (Holzmann & Joshi,
*Swarm Verification Techniques*): a fleet of DIVERSIFIED random walkers
runs as ONE ``shard_map`` program across the device mesh, and every
witness it produces is minimized and independently replay-verified
before the verdict is returned.

Architecture
============

* **One fused superstep per round.**  Each device owns a block of
  ``walkers_per_device`` walkers (state rows + depths + per-walker
  event histories).  A round is a single dispatched ``shard_map``
  program whose ``lax.while_loop`` runs up to ``steps_per_round`` walk
  steps — event-table build, one random event pick per walker, one
  vmapped transition, invariant/goal/exception flags, visited-table
  insert, restart resolution — and stops EARLY when any device raises a
  terminal flag (the first-hit stop is a ``psum``'d flag count in the
  loop condition, so the whole fleet halts within one step of the first
  hit).  Host involvement per round is one dispatch + one scalar stats
  readback, through the same ``_dispatch`` seam as the BFS drivers — so
  supervisor retry/watchdog/FaultPlan, warden process isolation, and
  the persistent compile cache all apply unchanged.

* **Diversification axes** (what makes a swarm beat N copies of one
  walker): every walker gets (1) its own PRNG stream (per-device key,
  per-walker categorical picks), (2) its own DEPTH BOUND from a
  schedule spanning ``[min_steps, max_steps]`` — short-leash walkers
  resample shallow prefixes while long-leash walkers commit deep, and
  (3) its own event-pick TEMPERATURE and message/timer affinity — cold
  walkers follow their kind bias almost deterministically, hot walkers
  pick uniformly, so the fleet covers timer-storm and message-storm
  schedules that a uniform picker visits exponentially rarely.

* **Shared dedup** through the one open-addressing table implementation
  (tpu/visited.py): every advanced successor inserts its 128-bit
  fingerprint into the device's table, so fleets do not re-count each
  other's states (``unique_states`` is fresh inserts, never the walked
  count) and BFS coverage can be pre-seeded (below).  An optional
  ``revisit_patience`` restarts a walker whose last N steps all landed
  on already-visited states — restart steering away from covered
  territory.  A full table degrades exactly like the BFS engines
  (visited.py contract): unresolved keys count as fresh, surfaced on
  ``SearchOutcome.visited_overflow`` (strict swarms raise).

* **Frontier seeding** (the BFS+swarm hybrid): ``frontier_seed`` names
  a mid-BFS unified checkpoint (tpu/checkpoint.py); walkers then
  restart from the dumped FRONTIER rows instead of the root, and the
  dump's visited keys pre-seed every device's table — the swarm probes
  strictly PAST the exhaustively-proven region.  Witness traces are
  recorded relative to the walker's seed state (the staged-search
  ``initial=`` contract; ``_trace_root`` is set per hit).

* **Witness pipeline.**  A violation's root-first event trace comes
  straight from the walker's recorded history (no re-derivation), then
  :func:`minimize_event_trace` shrinks it to a fixpoint (the
  TraceMinimizer.java:32-109 discipline, executed in tensor space with
  one fused replay program per candidate) and :func:`replay_events`
  re-applies the minimized trace from the seed state, asserting every
  event applies and the predicate result reproduces.  The verdict is
  returned only with a verified :class:`Witness` attached
  (``SearchOutcome.witness``) — never an unminimized or unreplayed
  trace.  The object-level double-check (search/minimize.py +
  search/replay.py on the replayed object twin) rides in the search
  backend (tpu/backend.py) where an object root exists.

* **Rounds checkpoint/resume** like BFS levels: the walker rows,
  depths, histories, PRNG keys, seed pool, and table keys dump into the
  unified checkpoint format (``SearchCheckpoint.extra``), so a killed
  swarm resumes mid-flight with an IDENTICAL continuation (the PRNG
  state is part of the dump) — supervisor failover semantics unchanged.

Env knobs (docs/swarm.md): DSLABS_SWARM_WALKERS, DSLABS_SWARM_STEPS,
DSLABS_SWARM_ROUND, DSLABS_SWARM_PATIENCE, DSLABS_SWARM_RESTART_WARN,
DSLABS_SWARM_OVERFLOW_WARN.
"""

from __future__ import annotations

import dataclasses
import os
import time
import warnings
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from dslabs_tpu.tpu import checkpoint as ckpt_mod
from dslabs_tpu.tpu import visited as visited_mod
from dslabs_tpu.tpu.engine import (CapacityOverflow, SearchOutcome,
                                   TensorProtocol, TensorSearch,
                                   device_get, flatten_state,
                                   row_fingerprints)

__all__ = ["SwarmSearch", "Witness", "minimize_event_trace",
           "replay_events"]

# Warn thresholds for the loud-degradation counters (satellite of
# ISSUE 5: the old rollout probe restarted capacity-truncated walkers
# SILENTLY).  Any overflow restart is worth a warning by default;
# ordinary restarts are the walkers' job, so that bar is high.
RESTART_WARN = int(os.environ.get("DSLABS_SWARM_RESTART_WARN",
                                  str(1 << 20)))
OVERFLOW_WARN = int(os.environ.get("DSLABS_SWARM_OVERFLOW_WARN", "0"))

_TERMINAL = ("INVARIANT_VIOLATED", "EXCEPTION_THROWN", "GOAL_FOUND")


# ------------------------------------------------------------- witnesses

@dataclasses.dataclass
class Witness:
    """A minimized, replay-verified counterexample (or goal trace).

    ``trace`` is the minimized root-first grid-event-id list (the
    tpu/trace.py contract, relative to the walk's seed state);
    ``raw_trace`` is the walker's original history.  ``replay_verified``
    is True iff re-applying ``trace`` from the seed state applied every
    event and reproduced the predicate result — swarm verdicts refuse
    to ship otherwise."""

    end_condition: str
    predicate_name: Optional[str]
    exception_code: int
    raw_trace: List[int]
    trace: List[int]
    minimized: bool
    replay_verified: bool
    minimize_passes: int = 0
    # Set by the search backend when the object-level pipeline
    # (search/minimize.py + search/replay.py) also confirmed the
    # witness on the replayed object twin.
    object_verified: Optional[bool] = None

    def __len__(self) -> int:
        return len(self.trace)


def _replay_prog(search: TensorSearch, length: int):
    """One fused replay program for padded event lists of ``length``:
    a ``lax.scan`` of ``_step_one`` where ``ev < 0`` rows are inert
    padding and the first inapplicable/overflowed event FREEZES the
    state (TraceMinimizer.java:95-108 ``applyEvents`` semantics — later
    events are not applied).  Returns ``(final_row, applied[L])``.
    Cached per padded length (lengths are padded to powers of two so
    the program count stays O(log L))."""
    cache = getattr(search, "_swarm_replay_progs", None)
    if cache is None:
        cache = search._swarm_replay_progs = {}
    fn = cache.get(length)
    if fn is not None:
        return fn

    def prog(row0, evs):
        def step(carry, ev):
            row, alive = carry
            do = alive & (ev >= 0)
            succ, ok, over = search._step_one(row, jnp.maximum(ev, 0))
            good = do & ok & (over == 0)
            row2 = jnp.where(good, succ, row)
            alive2 = jnp.where(ev >= 0, alive & good, alive)
            return (row2, alive2), good

        (row, _alive), applied = jax.lax.scan(
            step, (row0, jnp.bool_(True)), evs)
        return row, applied

    fn = cache[length] = jax.jit(prog)
    return fn


def _pad_len(n: int) -> int:
    length = 8
    while length < n:
        length <<= 1
    return length


def replay_events(search: TensorSearch, root_row: np.ndarray,
                  events: List[int]) -> Tuple[np.ndarray, int]:
    """Replay ``events`` (grid event ids, root-first) from ``root_row``
    ([lanes] int32).  Returns ``(final_row, n_applied)`` where
    ``n_applied`` counts the applied prefix — application stops at the
    first undeliverable/overflowed event, like the reference
    minimizer's ``applyEvents``.  Replay is UNMASKED by design: the
    reference minimizer replays under default settings (all delivery
    permitted, search/minimize.py module docstring), and runtime masks
    gate validity, never the transition."""
    L = _pad_len(max(len(events), 1))
    evs = np.full((L,), -1, np.int32)
    evs[:len(events)] = np.asarray(events, np.int32)
    row, applied = _replay_prog(search, L)(
        jnp.asarray(root_row, jnp.int32), jnp.asarray(evs))
    applied = np.asarray(applied)[:len(events)]
    n_applied = int(applied.sum()) if applied.all() else \
        int(np.argmin(applied))
    return np.asarray(row), n_applied


def _verdict_check(search: TensorSearch, end_condition: str,
                   predicate_name: Optional[str], exception_code: int):
    """-> fn(final_row) -> bool: does this state reproduce the verdict
    (same-truth-value / same-exception-code discipline of
    search/minimize.py)?"""
    p = search.p

    def check(row: np.ndarray) -> bool:
        st = search.unflatten_rows(jnp.asarray(row, jnp.int32)[None])
        if end_condition == "EXCEPTION_THROWN":
            return int(np.asarray(st["exc"])[0]) == exception_code
        preds = (p.invariants if end_condition == "INVARIANT_VIOLATED"
                 else p.goals)
        holds = bool(np.asarray(jax.vmap(preds[predicate_name])(st))[0])
        return (not holds if end_condition == "INVARIANT_VIOLATED"
                else holds)

    return check


def minimize_event_trace(search: TensorSearch, root_row: np.ndarray,
                         events: List[int], check,
                         max_passes: int = 6) -> Tuple[List[int], int]:
    """Shrink an event trace to a (bounded) fixpoint: for each event,
    try replaying the trace WITHOUT it; keep the deletion when the end
    state still reproduces the predicate result (``check``) — the
    TraceMinimizer.java:33-61 loop, executed in tensor space with one
    fused replay dispatch per candidate.  ``max_passes`` bounds the
    fixpoint (each pass is O(L) replays); random-walk traces converge
    in 2-3 passes in practice.  Returns ``(minimized, passes_run)``."""
    events = list(events)
    passes = 0
    changed = True
    while changed and passes < max_passes:
        changed = False
        passes += 1
        i = 0
        while i < len(events):
            cand = events[:i] + events[i + 1:]
            row, _n = replay_events(search, root_row, cand)
            if check(row):
                events = cand
                changed = True
            else:
                i += 1
    return events, passes


def build_witness(search: TensorSearch, root_row: np.ndarray,
                  raw_trace: List[int], end_condition: str,
                  predicate_name: Optional[str], exception_code: int,
                  minimize: bool = True,
                  verify: bool = True) -> Witness:
    """The swarm witness pipeline: minimize (optional) then
    replay-verify.  A failed verification is a LOUD RuntimeError — a
    swarm verdict never ships a trace that does not independently
    reproduce its predicate result."""
    check = _verdict_check(search, end_condition, predicate_name,
                           exception_code)
    trace, passes = (minimize_event_trace(search, root_row, raw_trace,
                                          check)
                     if minimize else (list(raw_trace), 0))
    verified = False
    if verify:
        row, n_applied = replay_events(search, root_row, trace)
        if n_applied < len(trace):
            # check() accepted a prefix mid-minimization; the dangling
            # suffix is dead weight — trim and re-verify.
            trace = trace[:n_applied]
            row, n_applied = replay_events(search, root_row, trace)
        verified = n_applied == len(trace) and check(row)
        if not verified:
            raise RuntimeError(
                f"swarm witness failed replay verification "
                f"({end_condition}, predicate={predicate_name!r}, "
                f"{n_applied}/{len(trace)} events applied) — walker "
                "history or transition replay is corrupt (engine bug)")
    return Witness(end_condition=end_condition,
                   predicate_name=predicate_name,
                   exception_code=exception_code,
                   raw_trace=list(raw_trace), trace=trace,
                   minimized=minimize, replay_verified=verified,
                   minimize_passes=passes)


# ------------------------------------------------------------ the swarm

class SwarmSearch(TensorSearch):
    """Diversified random-walk fleets over a device mesh (module
    docstring).  ``run()`` returns the standard :class:`SearchOutcome`:
    INVARIANT_VIOLATED / EXCEPTION_THROWN / GOAL_FOUND with a verified
    :class:`Witness`, else TIME_EXHAUSTED with the fleet statistics on
    ``outcome.swarm`` — exhaustive verdicts remain BFS-only by design.
    """

    def __init__(self, protocol: TensorProtocol, mesh=None,
                 walkers_per_device: Optional[int] = None,
                 max_steps: Optional[int] = None,
                 min_steps: Optional[int] = None,
                 steps_per_round: Optional[int] = None,
                 max_rounds: Optional[int] = None,
                 max_secs: Optional[float] = None,
                 seed: int = 0,
                 temperature: Tuple[float, float] = (0.25, 4.0),
                 kind_affinity: float = 2.0,
                 revisit_patience: Optional[int] = None,
                 visited_cap: int = 1 << 18,
                 strict: bool = False,
                 ev_budget=None,
                 frontier_seed: Optional[str] = None,
                 checkpoint_path: Optional[str] = None,
                 checkpoint_every: int = 0,
                 minimize: bool = True,
                 replay_verify: bool = True):
        if mesh is None:
            from dslabs_tpu.tpu.sharded import make_mesh

            mesh = make_mesh(len(jax.devices()))
        self.mesh = mesh
        self.axis = mesh.axis_names[0]
        self.n_devices = int(mesh.devices.size)
        self.walkers = int(walkers_per_device
                           or os.environ.get("DSLABS_SWARM_WALKERS", 128))
        self.max_steps = int(max_steps
                             or os.environ.get("DSLABS_SWARM_STEPS", 96))
        self.min_steps = int(min_steps if min_steps is not None
                             else max(4, self.max_steps // 4))
        self.steps_per_round = int(
            steps_per_round or os.environ.get("DSLABS_SWARM_ROUND", 64))
        self.max_rounds = max_rounds
        self.seed = int(seed)
        self.temperature = (float(temperature[0]), float(temperature[1]))
        self.kind_affinity = float(kind_affinity)
        # Restart steering: a walker whose last ``patience`` steps all
        # landed on already-visited states restarts (it is re-treading
        # covered territory).  <= 0 disables — the safe default: from a
        # root INSIDE a large covered region, a small patience would
        # fence walkers below the fresh frontier.  Enable alongside
        # frontier seeding, where restarts land PAST the covered region.
        if revisit_patience is None:
            revisit_patience = int(os.environ.get(
                "DSLABS_SWARM_PATIENCE", "0"))
        self.revisit_patience = int(revisit_patience)
        self.frontier_seed = frontier_seed
        self.minimize = minimize
        self.replay_verify = replay_verify
        super().__init__(protocol, frontier_cap=max(self.walkers, 2),
                         chunk=self.walkers, max_secs=max_secs,
                         ev_budget=ev_budget, visited_cap=visited_cap,
                         strict=strict,
                         checkpoint_path=checkpoint_path,
                         checkpoint_every=checkpoint_every)
        self._round = jax.jit(self._build_round(), donate_argnums=0)
        self.compile_secs = 0.0
        # Watchdog granularity (tpu/supervisor.py): one round dispatch
        # legitimately runs up to steps_per_round walk steps.
        self._dispatch_deadline_scales = {
            "round": float(max(1, self.steps_per_round))}
        # Soundness sanitizer (ISSUE 10): audit the fused round program
        # when DSLABS_SANITIZE is on (base __init__ skips subclasses).
        self._maybe_sanitize()

    def dispatch_site_programs(self):
        """Sanitizer site registry (ISSUE 10; base-class docstring):
        the ONE hot swarm program — the fused round superstep.  Unlike
        the BFS engines the round's carry shapes live on device (the
        init shard_map builds them), so this runs the real swarm.init
        once and abstracts its result; the audit itself still only
        lowers."""
        carry = self._init_carry(self.initial_state())

        def _sds(x):
            return jax.ShapeDtypeStruct(
                x.shape, x.dtype, sharding=getattr(x, "sharding", None))

        carry_sds = jax.tree.map(_sds, carry)
        b = jnp.asarray(self.steps_per_round, jnp.int32)
        rt = getattr(self, "_rt_masks", None)
        args = ((carry_sds, b, rt) if rt is not None
                else (carry_sds, b))
        return {
            "swarm.round": dict(
                fn=self._round, args=args, donate=(0,), multi=True,
                builder=lambda: jax.jit(self._build_round(),
                                        donate_argnums=0)),
        }

    # --------------------------------------------------- diversification

    def _schedules(self):
        """Host-built per-walker diversification arrays over the WHOLE
        fleet (D * K walkers): depth bounds, temperatures, kind
        affinities.  Deterministic functions of the config — never
        checkpointed, always regenerated."""
        n = self.n_devices * self.walkers
        bounds = np.linspace(self.min_steps, self.max_steps, n)
        bounds = np.ceil(bounds).astype(np.int32).clip(1, self.max_steps)
        t_lo, t_hi = self.temperature
        temps = np.geomspace(max(t_lo, 1e-3), max(t_hi, 1e-3),
                             n).astype(np.float32)
        # Affinity alternates sign across the fleet so half the walkers
        # chase timer-heavy schedules and half message-heavy ones, at
        # every temperature rung.
        affin = np.where(np.arange(n) % 2 == 0, 1.0, -1.0)
        affin = (affin * self.kind_affinity).astype(np.float32)
        return bounds, temps, affin

    def _dev_keys(self) -> np.ndarray:
        """[D, 2] uint32 per-device PRNG keys (fold_in by device)."""
        base = jax.random.PRNGKey(self.seed)
        return np.stack([np.asarray(jax.random.fold_in(base, d))
                         for d in range(self.n_devices)]).astype(
            np.uint32)

    # -------------------------------------------------------- seed pool

    def _seed_pool(self, state) -> Tuple[np.ndarray, np.ndarray,
                                         np.ndarray]:
        """-> (seeds [D, P, lanes], seeds_n [D], preseed_keys [M, 4]).

        Root mode: every device's pool is the one root row, no
        pre-seeded keys.  Frontier mode (``frontier_seed`` = a BFS
        checkpoint path): the dumped frontier rows split contiguously
        across devices (distinct seeds per device = another
        diversification axis) and the dump's visited keys pre-seed
        EVERY device's table (tables are device-local; replication
        maximizes sharing)."""
        root = np.asarray(flatten_state(state))[0]
        D = self.n_devices
        if not self.frontier_seed:
            seeds = np.broadcast_to(root, (D, 1, self.lanes)).copy()
            return seeds, np.ones((D,), np.int32), np.zeros((0, 4),
                                                            np.uint32)
        ck = self._load_bfs_seed(self.frontier_seed)
        rows = ck.frontier
        if not len(rows):
            rows = root[None]
        per = max(1, -(-len(rows) // D))
        seeds = np.zeros((D, per, self.lanes), np.int32)
        seeds_n = np.zeros((D,), np.int32)
        for d in range(D):
            part = rows[d * per:(d + 1) * per]
            if not len(part):
                # A device with no frontier share falls back to the
                # root (never an empty pool).
                part = root[None]
            seeds[d, :len(part)] = part
            seeds_n[d] = len(part)
        return seeds, seeds_n, np.asarray(ck.visited_keys, np.uint32)

    def _load_bfs_seed(self, path: str):
        """Load a BFS dump for frontier seeding.  The dump may have
        been written by a strict or beam, trace-recording or plain
        search — any fingerprint whose PROTOCOL half matches ours is a
        sound seed (we only consume frontier rows + visited keys)."""
        last = None
        for strict in (True, False):
            for rt in (False, True):
                fp = ckpt_mod.config_fingerprint(self.p, strict, rt)
                try:
                    ck = ckpt_mod.load(path, fp)
                except ckpt_mod.CheckpointMismatch as e:
                    last = e
                    continue
                if ck is not None:
                    return ck
        if last is not None:
            raise last
        raise FileNotFoundError(
            f"frontier_seed: no BFS checkpoint at {path}")

    # ------------------------------------------------------ the programs

    def _carry_specs(self):
        ax = self.axis
        keys = ["rows", "depths", "hists", "streak", "seed_idx",
                "bounds", "temps", "affin", "key", "seeds", "seeds_n",
                "visited", "explored", "fresh", "revisit", "restarts",
                "over", "vis_over", "deepest",
                "hit_cnt", "hit_rows", "hit_hist", "hit_depth",
                "hit_seed"]
        return {k: P(ax) for k in keys}

    def _build_walk_step(self):
        """One walk step for this device's K walkers (runs INSIDE the
        round's shard_map/while_loop)."""
        p = self.p
        K = self.walkers
        S = self.max_steps
        patience = self.revisit_patience

        def walk(c, masks=None):
            rows, depths, hists = c["rows"], c["depths"], c["hists"]
            key, sub, sub2 = jax.random.split(c["key"][0], 3)
            msg_ids, tmr_ids, flt_ids, _rem = self._event_tables(
                rows, jnp.ones((K,), bool), masks=masks)
            segs = [msg_ids,
                    jnp.where(tmr_ids >= 0, tmr_ids + p.net_cap, -1)]
            if flt_ids is not None:
                tgrid = p.n_nodes * p.timer_cap
                segs.append(jnp.where(
                    flt_ids >= 0, flt_ids + p.net_cap + tgrid, -1))
            ids = jnp.concatenate(segs, axis=1)              # [K, B]
            ok = ids >= 0
            # Diversified pick: kind-affinity bias over valid events,
            # scaled by each walker's temperature (cold = committed to
            # its bias, hot = uniform), resolved by one categorical
            # draw per walker.
            is_tmr = (jnp.arange(ids.shape[1])
                      >= self._ev_msg)[None, :]               # [1, B]
            bias = (c["affin"][:, None]
                    * jnp.where(is_tmr, 1.0, -1.0)
                    / c["temps"][:, None])
            logits = jnp.where(ok, bias, -jnp.inf)
            pick = jax.random.categorical(sub, logits, axis=-1)  # [K]
            ev = jnp.take_along_axis(ids, pick[:, None], axis=1)[:, 0]
            any_ok = ok.any(axis=1)
            ev = jnp.where(any_ok, ev, 0)
            succ, s_ok, s_over = jax.vmap(self._step_one)(rows, ev)
            # A capacity-overflowed successor is TRUNCATED — checking
            # predicates on it would be unsound.  The walker restarts,
            # and the truncation is COUNTED (c["over"]) — the old
            # rollout probe's silent-restart bug, fixed.
            over = any_ok & s_ok & (s_over != 0)
            advance = any_ok & s_ok & ~over
            sstate = self.unflatten_rows(succ)

            # Terminal flags, checkState order (exception -> invariant
            # -> goal; shared _flag_names layout with the BFS drivers).
            hit_list = [advance & (sstate["exc"] != 0)]
            for n in p.invariants:
                hit_list.append(advance
                                & ~jax.vmap(p.invariants[n])(sstate))
            for n in p.goals:
                hit_list.append(advance & jax.vmap(p.goals[n])(sstate))
            hits = jnp.stack(hit_list)                        # [nf, K]
            pruned = jnp.zeros((K,), bool)
            for fn in p.prunes.values():
                pruned = pruned | jax.vmap(fn)(sstate)

            # History records the event BEFORE restart resolution: a
            # violating successor's trace must include its final edge.
            hists2 = jnp.where(
                (jnp.arange(S)[None, :] == depths[:, None])
                & advance[:, None], ev[:, None], hists)
            depths2 = depths + advance.astype(jnp.int32)

            # Shared dedup: fingerprints of advanced successors insert
            # into this device's table (visited.py contract: unresolved
            # = table full = treated as fresh, counted).
            fp = row_fingerprints(succ)
            table, ins, unres = visited_mod.insert(
                c["visited"], fp, advance)
            revisit = advance & ~ins & ~unres
            streak2 = jnp.where(revisit, c["streak"] + 1,
                                jnp.zeros_like(c["streak"]))
            if patience > 0:
                rv_restart = streak2 >= patience
            else:
                rv_restart = jnp.zeros((K,), bool)

            # First-hit capture per flag (one walker's full history),
            # taken from the PRE-restart arrays.
            cnts = jnp.sum(hits, axis=1).astype(jnp.int32)
            idxs = jnp.argmax(hits, axis=1)
            freshf = (c["hit_cnt"] == 0) & (cnts > 0)
            hit_rows = jnp.where(freshf[:, None], succ[idxs],
                                 c["hit_rows"])
            hit_hist = jnp.where(freshf[:, None], hists2[idxs],
                                 c["hit_hist"])
            hit_depth = jnp.where(freshf, depths2[idxs], c["hit_depth"])
            hit_seed = jnp.where(freshf, c["seed_idx"][idxs],
                                 c["hit_seed"])

            # Restarts: dead end / truncated step / prune / depth bound
            # / revisit patience -> re-seed from the pool.
            restart = (~advance | pruned | (depths2 >= c["bounds"])
                       | rv_restart)
            nsd = jnp.maximum(c["seeds_n"][0], 1)
            ridx = jax.random.randint(sub2, (K,), 0, nsd)
            new_rows = c["seeds"][ridx]
            rows2 = jnp.where(restart[:, None], new_rows, succ)
            depths3 = jnp.where(restart, 0, depths2)
            hists3 = jnp.where(restart[:, None], -1, hists2)
            streak3 = jnp.where(restart, 0, streak2)
            seed_idx2 = jnp.where(restart, ridx, c["seed_idx"])

            def bump(name, val):
                return c[name].at[0].add(val.astype(jnp.int32))

            return {
                "rows": rows2, "depths": depths3, "hists": hists3,
                "streak": streak3, "seed_idx": seed_idx2,
                "bounds": c["bounds"], "temps": c["temps"],
                "affin": c["affin"], "key": key[None],
                "seeds": c["seeds"], "seeds_n": c["seeds_n"],
                "visited": table,
                "explored": bump("explored", jnp.sum(advance)),
                "fresh": bump("fresh", jnp.sum(ins)),
                "revisit": bump("revisit", jnp.sum(revisit)),
                "restarts": bump("restarts", jnp.sum(restart)),
                "over": bump("over", jnp.sum(over)),
                "vis_over": bump("vis_over", jnp.sum(unres)),
                "deepest": c["deepest"].at[0].max(
                    jnp.max(depths2).astype(jnp.int32)),
                "hit_cnt": c["hit_cnt"] + cnts,
                "hit_rows": hit_rows, "hit_hist": hit_hist,
                "hit_depth": hit_depth, "hit_seed": hit_seed,
            }

        return walk

    def _build_round(self):
        """The fused ROUND superstep: up to ``budget`` walk steps in one
        ``lax.while_loop``, stopping early when ANY device's flag count
        goes nonzero (psum'd first-hit stop).  Returns (carry', stats)
        with the psum'd scalar stats in-program, so host involvement
        per round is one dispatch."""
        walk = self._build_walk_step()
        ax = self.axis

        def stats_local(c, k):
            def ps(x):
                return jax.lax.psum(x, ax)

            core = jnp.stack([
                ps(c["explored"][0]), ps(c["fresh"][0]),
                ps(c["revisit"][0]), ps(c["restarts"][0]),
                ps(c["over"][0]), ps(c["vis_over"][0]),
                jax.lax.pmax(c["deepest"][0], ax), k,
            ]).astype(jnp.int32)
            # Per-device stats lanes (ISSUE 8): the pre-psum per-device
            # scalars ride the SAME readback, LAST so every absolute
            # index parse stays valid — [explored×D, fresh×D,
            # restarts×D, deepest×D], one all_gather in the fused round
            # program, zero extra dispatches or transfers.
            per_dev = jnp.stack([c["explored"][0], c["fresh"][0],
                                 c["restarts"][0], c["deepest"][0]])
            return jnp.concatenate([
                core, ps(c["hit_cnt"]).astype(jnp.int32),
                jax.lax.all_gather(per_dev, ax).T.reshape(-1)
                .astype(jnp.int32)])

        def round_local(carry, budget, masks=None):
            def cond(st):
                c, k = st
                hit = jnp.sum(c["hit_cnt"])
                return (k < budget) & (jax.lax.psum(hit, ax) == 0)

            def body(st):
                c, k = st
                return walk(c, masks), k + 1

            carry, k = jax.lax.while_loop(cond, body,
                                          (carry, jnp.int32(0)))
            return carry, stats_local(carry, k)

        spec = self._carry_specs()
        if (self.p.deliver_message_rt is not None
                or self.p.deliver_timer_rt is not None):
            return shard_map(
                lambda c, b, m: round_local(c, b, m), mesh=self.mesh,
                in_specs=(spec, P(), (P(), P())),
                out_specs=(spec, P()), check_rep=False)
        return shard_map(
            lambda c, b: round_local(c, b), mesh=self.mesh,
            in_specs=(spec, P()), out_specs=(spec, P()),
            check_rep=False)

    def _round_call(self, carry, budget: int):
        """Dispatch one round through the supervisor seam; the
        dispatched callable blocks on the scalar stats readback so the
        watchdog bounds the fused round."""
        b = jnp.asarray(budget, jnp.int32)
        rt = getattr(self, "_rt_masks", None)

        def run(c, bb, *masks):
            c2, stats = (self._round(c, bb, masks[0]) if masks
                         else self._round(c, bb))
            return c2, device_get(stats)

        if rt is not None:
            return self._dispatch("swarm.round", run, carry, b, rt)
        return self._dispatch("swarm.round", run, carry, b)

    # ------------------------------------------------------------- carry

    def _init_carry(self, state):
        """Build the fleet carry: host-side small arrays + one jitted
        shard_map finisher that builds each device's table (pre-seeded
        when frontier seeding is on) and places walkers round-robin
        over the seed pool."""
        D, K, S, V = (self.n_devices, self.walkers, self.max_steps,
                      self.visited_cap)
        lanes = self.lanes
        nf = len(self._flag_names)
        seeds, seeds_n, pre_keys = self._seed_pool(state)
        pool = seeds.shape[1]
        bounds, temps, affin = self._schedules()
        m = len(pre_keys)
        # Pre-seed keys replicate to every device's table.
        pk = np.zeros((D, max(m, 1), 4), np.uint32)
        pv = np.zeros((D, max(m, 1)), bool)
        if m:
            pk[:] = pre_keys[None]
            pv[:] = True
        shard = NamedSharding(self.mesh, P(self.axis))
        dev_in = {k: jax.device_put(v, shard) for k, v in {
            "seeds": seeds.reshape(D * pool, lanes),
            "seeds_n": seeds_n,
            "bounds": bounds, "temps": temps, "affin": affin,
            "key": self._dev_keys(),
            "pkeys": pk.reshape(-1, 4), "pval": pv.reshape(-1),
        }.items()}

        def local(s):
            table, ins, unres = visited_mod.insert(
                visited_mod.empty_table(V), s["pkeys"], s["pval"])
            nsd = jnp.maximum(s["seeds_n"][0], 1)
            idx0 = (jnp.arange(K, dtype=jnp.int32) % nsd)
            out = {
                "rows": s["seeds"][idx0],
                "depths": jnp.zeros((K,), jnp.int32),
                "hists": jnp.full((K, S), -1, jnp.int32),
                "streak": jnp.zeros((K,), jnp.int32),
                "seed_idx": idx0,
                "bounds": s["bounds"], "temps": s["temps"],
                "affin": s["affin"], "key": s["key"],
                "seeds": s["seeds"], "seeds_n": s["seeds_n"],
                "visited": table,
                "explored": jnp.zeros((1,), jnp.int32),
                "fresh": jnp.zeros((1,), jnp.int32),
                "revisit": jnp.zeros((1,), jnp.int32),
                "restarts": jnp.zeros((1,), jnp.int32),
                "over": jnp.zeros((1,), jnp.int32),
                "vis_over": jnp.zeros((1,), jnp.int32),
                "deepest": jnp.zeros((1,), jnp.int32),
                "hit_cnt": jnp.zeros((nf,), jnp.int32),
                "hit_rows": jnp.zeros((nf, lanes), jnp.int32),
                "hit_hist": jnp.full((nf, S), -1, jnp.int32),
                "hit_depth": jnp.zeros((nf,), jnp.int32),
                "hit_seed": jnp.zeros((nf,), jnp.int32),
            }
            return out, jnp.sum(unres).astype(jnp.int32)[None]

        ax = self.axis
        in_spec = {k: P(ax) for k in dev_in}
        fn = jax.jit(shard_map(local, mesh=self.mesh,
                               in_specs=(in_spec,),
                               out_specs=(self._carry_specs(), P(ax)),
                               check_rep=False))

        def build(inputs):
            carry, unres = fn(inputs)
            return carry, device_get(unres)

        carry, unres = self._dispatch("swarm.init", build, dev_in)
        n_unres = int(np.asarray(unres).sum())
        if n_unres:
            raise CapacityOverflow(
                f"{self.p.name}: visited_cap={V}/device too small to "
                f"pre-seed {m} BFS keys ({n_unres} unresolved); raise "
                "visited_cap")
        return carry

    # ------------------------------------------------------- checkpoints

    def _ckpt_fingerprint(self) -> str:
        """Swarm dumps are their own config family: a BFS engine must
        never resume one (and vice versa).  The history length (S) and
        the PRNG seed are part of the identity, but the mesh width (D)
        and per-device walker count (K) are deliberately EXCLUDED
        (ISSUE 9 satellite — the old ``D/K`` pin made every swarm dump
        unresumable after any mesh-width change): on load the walker
        rows, histories, PRNG keys, and per-device table key groups
        REDISTRIBUTE across whatever fleet resumes them
        (:meth:`_redistribute_swarm`); an unchanged-width resume takes
        the bit-exact passthrough path.  ``CheckpointMismatch`` is
        reserved for genuine protocol/strictness/seed mismatches."""
        base = ckpt_mod.config_fingerprint(self.p, self.strict, False)
        return f"swarm:{base}:S{self.max_steps}:seed{self.seed}"

    def _save_swarm_ckpt(self, carry, rounds: int, elapsed: float
                         ) -> None:
        """Host copies at the round boundary (before the next round's
        dispatch donates the buffers), file write drained async — the
        engine checkpoint discipline."""
        D, K, S, V = (self.n_devices, self.walkers, self.max_steps,
                      self.visited_cap)
        vis = np.asarray(carry["visited"]).reshape(D, V + 1, 4)[:, :-1]
        occ = ~(vis == visited_mod.MAXU32).all(axis=2)
        vdev = occ.sum(axis=1).astype(np.int64)
        keys = vis[occ]
        extra = {
            "depths": np.asarray(carry["depths"]),
            "hists": np.asarray(carry["hists"]),
            "streak": np.asarray(carry["streak"]),
            "seed_idx": np.asarray(carry["seed_idx"]),
            "key": np.asarray(carry["key"]),
            "seeds": np.asarray(carry["seeds"]),
            "seeds_n": np.asarray(carry["seeds_n"]),
            "vdev": vdev,
            "counters": np.stack([
                np.asarray(carry[k]).reshape(-1)
                for k in ("explored", "fresh", "revisit", "restarts",
                          "over", "vis_over", "deepest")]),
        }
        ck = ckpt_mod.SearchCheckpoint(
            fingerprint=self._ckpt_fingerprint(), depth=rounds,
            explored=int(np.asarray(carry["explored"]).sum()),
            elapsed=elapsed,
            frontier=np.asarray(carry["rows"]),
            visited_keys=keys,
            vis_over=int(np.asarray(carry["vis_over"]).sum()),
            extra=extra)
        self._ckpt_writer.kick(
            lambda: ckpt_mod.save(self.checkpoint_path, ck))

    def _redistribute_swarm(self, ck, x):
        """Cross-mesh-width resume (ISSUE 9 satellite): rewrite a dump
        written by a (D', K') fleet into this search's (D, K) shapes.

        Walker rows / depths / histories / streaks tile (or truncate)
        onto the new fleet size; the seed pool's live rows re-split
        into contiguous per-device shares; per-device PRNG keys map
        ``new[d] = old[d % D']`` (fresh streams per device either way);
        per-device visited key groups merge round-robin (duplicate keys
        across old device-local tables resolve in the insert); counters
        re-aggregate onto device 0 (sums — max for ``deepest`` — so
        psum/pmax stats stay exact).  The continuation is sound, not
        bit-exact — bit-exactness is reserved for the unchanged-width
        passthrough path."""
        import warnings

        D, K = self.n_devices, self.walkers
        vdev_old = np.asarray(x["vdev"], np.int64)
        d_old = max(len(vdev_old), 1)
        rows_old = np.asarray(ck.frontier, np.int32)
        n_old = max(len(rows_old), 1)
        m = D * K
        if len(rows_old) != m:
            warnings.warn(
                f"{self.p.name}: swarm resume redistributes "
                f"{len(rows_old)} walkers from a {d_old}-device dump "
                f"onto {D}x{K}={m} walker slots "
                f"({'tiling' if m > len(rows_old) else 'truncating'})",
                RuntimeWarning, stacklevel=3)
        idx = np.arange(m) % n_old
        x = dict(x)
        x["depths"] = np.asarray(x["depths"], np.int32)[idx]
        x["hists"] = np.asarray(x["hists"], np.int32)[idx]
        x["streak"] = np.asarray(x["streak"], np.int32)[idx]
        # PRNG keys: one per device, reused round-robin.
        key_old = np.asarray(x["key"], np.uint32).reshape(d_old, -1)
        x["key"] = key_old[np.arange(D) % d_old]
        # Seed pool: gather every device's live prefix, ceil-split into
        # contiguous per-device shares (the _seed_pool discipline).
        seeds_old = np.asarray(x["seeds"], np.int32)
        sn_old = np.asarray(x["seeds_n"], np.int32).reshape(-1)
        p_old = max(seeds_old.shape[0] // d_old, 1)
        live = [seeds_old[d * p_old:d * p_old + int(sn_old[d])]
                for d in range(d_old) if int(sn_old[d]) > 0]
        live = (np.concatenate(live) if live else rows_old[:1])
        per = max(1, -(-len(live) // D))
        seeds = np.zeros((D, per, self.lanes), np.int32)
        seeds_n = np.zeros((D,), np.int32)
        for d in range(D):
            part = live[d * per:(d + 1) * per]
            if not len(part):
                part = live[:1]     # never an empty pool
            seeds[d, :len(part)] = part
            seeds_n[d] = len(part)
        x["seeds"] = seeds.reshape(D * per, self.lanes)
        x["seeds_n"] = seeds_n
        # seed_idx references the per-device pool — clamp each walker's
        # index into its new device's pool size.
        sidx = np.asarray(x["seed_idx"], np.int32)[idx]
        owner = np.arange(m) // K
        x["seed_idx"] = np.minimum(sidx, seeds_n[owner] - 1).clip(0)
        # Per-device key groups merge round-robin onto the new width.
        offs = np.concatenate([[0], np.cumsum(vdev_old)]).astype(int)
        groups = [ck.visited_keys[offs[d]:offs[d + 1]]
                  for d in range(len(vdev_old))]
        merged = [[] for _ in range(D)]
        for g, keys in enumerate(groups):
            merged[g % D].append(keys)
        new_groups = [(np.concatenate(gs) if gs
                       else np.zeros((0, 4), np.uint32))
                      for gs in merged]
        x["vdev"] = np.asarray([len(g) for g in new_groups], np.int64)
        visited_keys = (np.concatenate(new_groups) if len(ck.visited_keys)
                        else ck.visited_keys)
        # Counters: per-device partials re-aggregate onto device 0 —
        # the stats psum (pmax for deepest) reads identical totals.
        c_old = np.asarray(x["counters"], np.int64).reshape(7, d_old)
        totals = c_old.sum(axis=1)
        totals[6] = c_old[6].max(initial=0)
        c_new = np.zeros((7, D), np.int64)
        c_new[:, 0] = totals
        x["counters"] = c_new
        import dataclasses as _dc

        return _dc.replace(ck, frontier=rows_old[idx],
                           visited_keys=visited_keys), x

    def _load_swarm_ckpt(self):
        """-> (carry, rounds, elapsed) or None.  Rebuilds the full
        fleet carry — walker rows/depths/histories, PRNG keys, seed
        pool, per-device tables re-inserted from the dumped key groups
        — so an unchanged-width continuation is bit-exact (the
        resume-parity test); a dump from a DIFFERENT mesh width or
        walker count redistributes first (:meth:`_redistribute_swarm`)."""
        ck = self._load_ckpt()
        if ck is None:
            return None
        if ck.extra is None:
            raise ckpt_mod.CheckpointCorrupt(
                f"{self.checkpoint_path}: swarm checkpoint has no "
                "extra__ walker arrays")
        D, K, S, V = (self.n_devices, self.walkers, self.max_steps,
                      self.visited_cap)
        lanes = self.lanes
        nf = len(self._flag_names)
        x = ck.extra
        if (len(np.asarray(x["vdev"]).reshape(-1)) != D
                or len(ck.frontier) != D * K):
            ck, x = self._redistribute_swarm(ck, x)
        vdev = np.asarray(x["vdev"], np.int64)
        kmax = int(max(vdev.max(initial=0), 1))
        kbuf = np.zeros((D, kmax, 4), np.uint32)
        kval = np.zeros((D, kmax), bool)
        off = 0
        for d in range(D):
            n = int(vdev[d])
            kbuf[d, :n] = ck.visited_keys[off:off + n]
            kval[d, :n] = True
            off += n
        counters = np.asarray(x["counters"], np.int32)
        shard = NamedSharding(self.mesh, P(self.axis))
        bounds, temps, affin = self._schedules()
        dev_in = {k: jax.device_put(v, shard) for k, v in {
            "rows": np.asarray(ck.frontier, np.int32),
            "depths": np.asarray(x["depths"], np.int32),
            "hists": np.asarray(x["hists"], np.int32),
            "streak": np.asarray(x["streak"], np.int32),
            "seed_idx": np.asarray(x["seed_idx"], np.int32),
            "key": np.asarray(x["key"], np.uint32),
            "seeds": np.asarray(x["seeds"], np.int32),
            "seeds_n": np.asarray(x["seeds_n"], np.int32),
            "bounds": bounds, "temps": temps, "affin": affin,
            "pkeys": kbuf.reshape(-1, 4), "pval": kval.reshape(-1),
            "counters": counters.T.copy(),          # [D, 7]
        }.items()}

        def local(s):
            table, ins, unres = visited_mod.insert(
                visited_mod.empty_table(V), s["pkeys"], s["pval"])
            cnt = s["counters"][0]
            out = {
                "rows": s["rows"], "depths": s["depths"],
                "hists": s["hists"], "streak": s["streak"],
                "seed_idx": s["seed_idx"],
                "bounds": s["bounds"], "temps": s["temps"],
                "affin": s["affin"], "key": s["key"],
                "seeds": s["seeds"], "seeds_n": s["seeds_n"],
                "visited": table,
                "explored": cnt[0][None], "fresh": cnt[1][None],
                "revisit": cnt[2][None], "restarts": cnt[3][None],
                "over": cnt[4][None], "vis_over": cnt[5][None],
                "deepest": cnt[6][None],
                "hit_cnt": jnp.zeros((nf,), jnp.int32),
                "hit_rows": jnp.zeros((nf, lanes), jnp.int32),
                "hit_hist": jnp.full((nf, S), -1, jnp.int32),
                "hit_depth": jnp.zeros((nf,), jnp.int32),
                "hit_seed": jnp.zeros((nf,), jnp.int32),
            }
            return out, jnp.sum(unres).astype(jnp.int32)[None]

        ax = self.axis
        in_spec = {k: P(ax) for k in dev_in}
        fn = jax.jit(shard_map(local, mesh=self.mesh,
                               in_specs=(in_spec,),
                               out_specs=(self._carry_specs(), P(ax)),
                               check_rep=False))
        with self.mesh:
            carry, unres = fn(dev_in)
        if int(np.asarray(unres).sum()):
            raise CapacityOverflow(
                f"{self.p.name}: visited_cap={V}/device too small to "
                "rebuild the swarm checkpoint's table; raise "
                "visited_cap")
        return carry, ck.depth, ck.elapsed

    # --------------------------------------------------------------- run

    def run(self, check_initial: bool = True,
            initial: Optional[dict] = None,
            resume: bool = False) -> SearchOutcome:
        """Run the swarm to a verdict.  ``initial`` (a batch-1 state
        pytree) roots the walk at an arbitrary state (the staged-search
        contract); ``resume=True`` continues from ``checkpoint_path``
        bit-exactly.  Compile time is excluded from the wall budget
        (the reference charges neither JIT nor class loading to
        maxTime) and reported on ``outcome.compile_secs``."""
        state = (jax.tree.map(jnp.asarray, initial)
                 if initial is not None else self.initial_state())
        self._trace_root = jax.tree.map(np.asarray, state)
        t0 = time.time()
        if check_initial:
            out = self._check_initial(state, t0)
            if out is not None:
                return out
        try:
            with self.mesh:
                return self._run_rounds(state, resume)
        finally:
            w = getattr(self, "_ckpt_writer_obj", None)
            if w is not None:
                w.join()

    def _run_rounds(self, state, resume: bool) -> SearchOutcome:
        resumed = (self._load_swarm_ckpt()
                   if resume and self.checkpoint_path else None)
        if resumed is not None:
            carry, rounds, prev_elapsed = resumed
            self._resumed_from_depth = rounds
        else:
            carry = self._init_carry(state)
            rounds, prev_elapsed = 0, 0.0
        # Warm-up: a zero-step round compiles the fused program OUTSIDE
        # the wall budget; the persistent compile cache makes the
        # second construction near-free.
        t_c = time.time()
        carry, _ = self._round_call(carry, 0)
        self.compile_secs += time.time() - t_c
        tel = getattr(self, "_telemetry", None)
        if tel is not None:
            # Compile as a first-class trace node (ISSUE 13) — an
            # event, not a span, so span/dispatch parity holds.
            tel.event("compile", engine="swarm",
                      secs=round(time.time() - t_c, 4), aot=True)
        t0 = time.time() - prev_elapsed
        stats = None
        self._pd_prev_explored = [0] * self.n_devices
        while True:
            cancelled = self._cancelled()
            timed_out = (self.max_secs is not None
                         and time.time() - t0 > self.max_secs)
            round_cap = (self.max_rounds is not None
                         and rounds >= self.max_rounds)
            if cancelled or timed_out or round_cap:
                return self._exhaust_outcome(stats, rounds, t0,
                                             cancelled)
            rounds += 1
            # Live "depth" for supervision heartbeats = round count.
            self._current_depth = rounds
            t_round = time.time()
            carry, stats = self._round_call(carry,
                                            self.steps_per_round)
            stats = np.asarray(stats)
            tel = getattr(self, "_telemetry", None)
            if tel is not None:
                from dslabs_tpu.tpu import telemetry as tel_mod

                # Fed from the round's fused stats vector — the same
                # scalars this loop reads anyway (zero extra syncs).
                rec = {
                    "depth": rounds,
                    "wall": round(time.time() - t_round, 4),
                    "explored": int(stats[0]), "unique": int(stats[1]),
                    "next_frontier": 0, "deepest": int(stats[6]),
                    "restarts": int(stats[3])}
                # Per-device lanes off the SAME readback (the 4D tail
                # stats_local appends): walker-work share per device is
                # the per-round explored delta.
                D = self.n_devices
                pd = [int(x) for x in stats[len(stats) - 4 * D:]]
                prev = getattr(self, "_pd_prev_explored", [0] * D)
                delta = [e - p for e, p in zip(pd[:D], prev)]
                self._pd_prev_explored = pd[:D]
                rec["per_device"] = {
                    "explored": delta, "unique": pd[D:2 * D],
                    "restarts": pd[2 * D:3 * D],
                    "deepest": pd[3 * D:]}
                rec["skew"] = {
                    "explored": tel_mod.skew_metrics(delta),
                    "unique": tel_mod.skew_metrics(pd[D:2 * D])}
                hbm = tel_mod.device_memory_stats(
                    self.mesh.devices.flat)
                if hbm is not None:
                    rec["hbm_peak"] = hbm
                tel.on_level("swarm", rec)
            vis_over = int(stats[5])
            over = int(stats[4])
            # Early-warning instrumentation (ISSUE 6 satellite): the
            # swarm shares the BFS visited table, so operators must
            # see fill pressure BEFORE the overflow contract fires
            # (strict raise / treat-as-fresh revisit inflation).
            from dslabs_tpu.tpu.spill import visited_warn_threshold

            fill = int(stats[1]) / (self.n_devices * self.visited_cap)
            if (fill >= visited_warn_threshold()
                    and not getattr(self, "_warned_visited", False)):
                self._warned_visited = True
                warnings.warn(
                    f"{self.p.name}: swarm visited table ~{fill:.0%} "
                    f"full ({int(stats[1])} fresh inserts vs "
                    f"{self.n_devices}x{self.visited_cap} slots) at "
                    f"round {rounds} — capacity pressure; raise "
                    "visited_cap before overflow degrades dedup",
                    RuntimeWarning, stacklevel=2)
            # Terminal flags BEFORE the strict capacity guards: a
            # violation found this round is a valid verdict even if
            # the table filled alongside it (the _sync_checks order).
            nf = len(self._flag_names)
            if stats[8:8 + nf].any():
                return self._resolve_hit(carry, stats, rounds, t0)
            if self.strict and vis_over:
                raise CapacityOverflow(
                    f"{self.p.name}: swarm visited table full "
                    f"({vis_over} unresolved keys, cap "
                    f"{self.visited_cap}/device); raise visited_cap "
                    "or run strict=False")
            if self.strict and over:
                raise CapacityOverflow(
                    f"{self.p.name}: {over} walker steps truncated by "
                    "net/timer caps (strict swarm); raise the caps")
            if (self.checkpoint_path and self.checkpoint_every
                    and rounds % self.checkpoint_every == 0):
                self._save_swarm_ckpt(carry, rounds, time.time() - t0)

    def _stats_dict(self, stats, rounds: int, elapsed: float) -> dict:
        (explored, fresh, revisit, restarts, over, vis_over,
         deepest, _steps) = (int(x) for x in stats[:8])
        el = max(elapsed, 1e-9)
        return {
            "walkers": self.n_devices * self.walkers,
            "rounds": rounds, "explored": explored, "unique": fresh,
            "revisits": revisit, "restarts": restarts,
            "overflow_restarts": over, "vis_over": vis_over,
            "deepest": deepest,
            "walkers_per_sec": round(explored / el, 1),
            "unique_per_min": round(fresh / el * 60.0, 1),
        }

    def _finish_outcome(self, out: SearchOutcome,
                        sd: dict) -> SearchOutcome:
        out.swarm = sd
        out.walker_restarts = sd["restarts"]
        out.swarm_overflow = sd["overflow_restarts"]
        out.visited_overflow = sd["vis_over"]
        out.compile_secs = round(self.compile_secs, 3)
        out.resumed_from_depth = getattr(self, "_resumed_from_depth", 0)
        if out.swarm_overflow > OVERFLOW_WARN:
            warnings.warn(
                f"{self.p.name}: {out.swarm_overflow} walker steps "
                "were capacity-truncated and restarted (net/timer caps "
                "too small for the walked region) — deep coverage is "
                "degraded; raise the caps or run a strict swarm",
                RuntimeWarning, stacklevel=3)
        if out.walker_restarts > RESTART_WARN:
            warnings.warn(
                f"{self.p.name}: {out.walker_restarts} walker restarts "
                "(> DSLABS_SWARM_RESTART_WARN) — walkers are churning; "
                "raise max_steps or seed from a deeper frontier",
                RuntimeWarning, stacklevel=3)
        tel = getattr(self, "_telemetry", None)
        if tel is not None:
            # Trace stamp at span emission (ISSUE 13): host-side only.
            if out.trace_id is None:
                out.trace_id = tel.trace_id
            tel.on_outcome(out, engine="swarm")
        return out

    def _exhaust_outcome(self, stats, rounds: int, t0,
                         cancelled: bool) -> SearchOutcome:
        elapsed = time.time() - t0
        if stats is None:
            stats = np.zeros((8 + len(self._flag_names),), np.int64)
        sd = self._stats_dict(stats, rounds, elapsed)
        out = SearchOutcome(
            "TIME_EXHAUSTED", sd["explored"], sd["unique"],
            sd["deepest"], elapsed, cancelled=cancelled)
        return self._finish_outcome(out, sd)

    def _resolve_hit(self, carry, stats, rounds: int,
                     t0) -> SearchOutcome:
        """First-hit resolution: ONE readback of the capture arrays,
        checkState flag order, then the witness pipeline (minimize +
        replay-verify) before the verdict is returned."""
        D, K, S = self.n_devices, self.walkers, self.max_steps
        nf = len(self._flag_names)
        data = self._dispatch(
            "swarm.flags", device_get_tree,
            {k: carry[k] for k in ("hit_cnt", "hit_rows", "hit_hist",
                                   "hit_depth", "hit_seed", "seeds",
                                   "seeds_n")})
        cnts = data["hit_cnt"].reshape(D, nf)
        rows = data["hit_rows"].reshape(D, nf, self.lanes)
        hist = data["hit_hist"].reshape(D, nf, S)
        depth = data["hit_depth"].reshape(D, nf)
        seed_i = data["hit_seed"].reshape(D, nf)
        pool = data["seeds"].reshape(D, -1, self.lanes)
        elapsed = time.time() - t0
        sd = self._stats_dict(stats, rounds, elapsed)
        for fi, fname in enumerate(self._flag_names):
            devs = np.nonzero(cnts[:, fi])[0]
            if not len(devs):
                continue
            d = int(devs[0])
            raw = [int(e) for e in hist[d, fi][:int(depth[d, fi])]]
            seed_row = pool[d, int(seed_i[d, fi])]
            # The walk root this witness replays from (tpu/trace.py
            # contract): the walker's seed state — the run root for
            # root-started fleets, a frontier row under seeding.
            self._trace_root = jax.tree.map(
                np.asarray, self.unflatten_rows(seed_row[None]))
            st = jax.tree.map(np.asarray,
                              self.unflatten_rows(rows[d, fi][None]))
            if fname == "exc":
                end, pname = "EXCEPTION_THROWN", None
                code = int(st["exc"][0])
            else:
                kind, pname = fname.split(":", 1)
                end = ("INVARIANT_VIOLATED" if kind == "inv"
                       else "GOAL_FOUND")
                code = 0
            wit = build_witness(self, seed_row, raw, end, pname, code,
                                minimize=self.minimize,
                                verify=self.replay_verify)
            out = SearchOutcome(
                end, sd["explored"], sd["unique"],
                int(depth[d, fi]), elapsed,
                violating_state=(st if end != "GOAL_FOUND" else None),
                goal_state=(st if end == "GOAL_FOUND" else None),
                predicate_name=pname, exception_code=code,
                trace=wit.trace, witness=wit)
            return self._finish_outcome(out, sd)
        raise AssertionError("swarm hit counts fired without a flag")


def device_get_tree(tree):
    """Readback funnel for pytrees (mirrors engine.device_get, which
    tests monkeypatch to audit transfer sizes)."""
    return jax.tree.map(device_get, tree)
