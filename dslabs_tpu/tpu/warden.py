"""Process-isolated dispatch warden: hang-proof failover supervision.

The in-process supervisor (tpu/supervisor.py) retries, watchdogs, and
fails over — but a truly wedged XLA runtime cannot be interrupted from
Python: the watchdog can only ABANDON the dispatch by leaking a blocked
daemon thread, and a hard runtime wedge takes the whole process down
with it (the BENCH_r01/r04/r05 failure class: raw tracebacks, rc=124
with no JSON, a 300 s preflight hang starving the CPU fallback).  This
module is the layer that makes every in-process resilience feature hold
against those failures, the same way elastic-training supervisors
restart a worker stuck in a hung collective:

* **Spawned child per rung.**  :class:`Warden` runs the
  accelerator-facing search loop in a child process
  (``python -m dslabs_tpu.tpu.warden``), supervised over a pipe.  The
  child rebuilds the protocol from a ``"module:callable"`` factory spec
  (live protocol objects hold closures that cannot cross a spawn
  boundary) and runs a single-rung :class:`SearchSupervisor` — the
  in-child retry/backoff/fault machinery is unchanged.
* **Heartbeats from the dispatch seam.**  The child installs a dispatch
  observer at the existing ``TensorSearch._dispatch`` boundary and
  emits one JSON line per dispatch attempt: tag, dispatch index, live
  BFS depth, and the last DURABLE checkpoint depth
  (``checkpoint.peek_depth``).  Every heartbeat announces its own
  silence budget (``grace``): compile-inclusive for the first dispatch
  at a tag, deadline-scale-stretched for fused supersteps, idle-sized
  between dispatches.
* **SIGKILL, not abandonment.**  A child silent past its announced
  grace (+ slack) is SIGKILLed and REAPED — no leaked thread, no
  zombie, no runtime state left racing device work.  The death is
  classified from the exit code + last heartbeat
  (:func:`classify_death`): ``wedge`` (warden kill after silence),
  ``oom`` (unprompted SIGKILL — the kernel OOM killer / an external
  kill), ``crash`` (other signal or abrupt exit), ``failed`` (the child
  reported a classified in-child failure and exited cleanly).
* **Failover + durable resume.**  After a death the warden spawns the
  next rung's child (``sharded -> device -> host``), which resumes from
  the unified PR-2 checkpoint (tpu/checkpoint.py) — now torn-write-safe
  via content checksums and ``.prev`` rotation, so even a SIGKILL that
  lands mid-dump costs one checkpoint interval, never the run.  The
  LAST rung's child is forced onto the CPU runtime
  (``JAX_PLATFORMS=cpu`` in the child env + a config re-pin against
  plugin-pinned platforms) so a verdict lands even when the accelerator
  runtime itself is the thing that is broken.
* **Identical verdict semantics.**  ``SearchSupervisor(
  process_isolation=True)`` rides this class; outcomes keep the full
  recovery accounting (``retries`` / ``failovers`` /
  ``resumed_from_depth``) plus ``child_restarts`` and
  ``killed_dispatches``.

:class:`LineWatch` is the shared child-stream monitor: bench.py's
phase subprocesses ride it so a wedged preflight is killed at heartbeat
silence (seconds) instead of the full phase budget (minutes), keeping
the CPU fallback inside the global deadline.

Exercised by the deterministic kill/hang/crash matrix in
tests/test_warden.py (``make fault-smoke``) — injected via the
``fault`` spec field, on CPU, no broken hardware required.
"""

from __future__ import annotations

import dataclasses
import json
import os
import queue
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from dslabs_tpu.tpu import checkpoint as ckpt_mod
from dslabs_tpu.tpu.supervisor import (CHILD_RC_FAILED, EngineFailure,
                                       RetryPolicy, SupervisorExhausted,
                                       classify_child_death)

__all__ = ["Warden", "LineWatch", "classify_death", "outcome_to_dict",
           "outcome_from_dict", "CHILD_RC_FAILED"]

# The repo root (…/dslabs_tpu/tpu/warden.py -> three levels up): child
# processes get it on PYTHONPATH so ``-m dslabs_tpu.tpu.warden``
# resolves regardless of the parent's cwd.
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def classify_death(exitcode: Optional[int],
                   killed_by_warden: bool,
                   stderr_markers=()) -> str:
    """The exit-code taxonomy (pinned by tests/test_warden.py and the
    table-driven test in tests/test_service.py) — a thin alias of the
    SHARED :func:`~dslabs_tpu.tpu.supervisor.classify_child_death`, so
    the warden's failover, the elastic ladder's ``classify_oom``, and
    the service scheduler's retry policy agree on one vocabulary:

    * ``wedge``  — the warden SIGKILLed the child after heartbeat
      silence (a hung dispatch / wedged runtime);
    * ``oom``    — an UNPROMPTED SIGKILL (kernel OOM killer / external
      ``kill -9``), or an abrupt death whose stderr tail carries an
      OOM marker (MemoryError traceback, RESOURCE_EXHAUSTED, …);
    * ``failed`` — the child exited :data:`CHILD_RC_FAILED` after
      reporting a classified in-child failure over the pipe;
    * ``crash``  — anything else: another signal (SIGSEGV, SIGBUS, …)
      or an abrupt nonzero exit with no report.
    """
    return classify_child_death(exitcode, killed_by_warden,
                                stderr_markers)


# ---------------------------------------------------------- serialization

_SCALAR_FIELDS = (
    "end_condition", "states_explored", "unique_states", "depth",
    "elapsed_secs", "predicate_name", "exception_code", "trace",
    "dropped", "samples", "visited_overflow", "retries", "failovers",
    "resumed_from_depth", "engine", "levels", "compile_secs",
    "child_restarts", "killed_dispatches", "abandoned_threads",
    "mesh_width", "mesh_shrinks", "knob_retries", "trace_id",
    "lane", "lane_width", "lane_share",
    "fault_events", "partition_events", "crash_events",
    "drop_events", "dup_events")


def outcome_to_dict(out) -> dict:
    """``SearchOutcome`` -> a JSON-serialisable dict (the pipe format).
    Batch-1 terminal states become nested int lists; everything else in
    the outcome is already plain data."""
    import numpy as np

    def _state(s):
        if s is None:
            return None
        return {k: np.asarray(v).tolist() for k, v in s.items()}

    d = {f: getattr(out, f) for f in _SCALAR_FIELDS}
    d["violating_state"] = _state(out.violating_state)
    d["goal_state"] = _state(out.goal_state)
    return d


def outcome_from_dict(d: dict):
    """Inverse of :func:`outcome_to_dict` (parent side of the pipe)."""
    import numpy as np

    from dslabs_tpu.tpu.engine import SearchOutcome

    def _state(s):
        if s is None:
            return None
        return {k: np.asarray(v, np.int32) for k, v in s.items()}

    out = SearchOutcome(
        end_condition=d["end_condition"],
        states_explored=d["states_explored"],
        unique_states=d["unique_states"],
        depth=d["depth"], elapsed_secs=d["elapsed_secs"])
    for f in _SCALAR_FIELDS:
        setattr(out, f, d.get(f, getattr(out, f)))
    out.violating_state = _state(d.get("violating_state"))
    out.goal_state = _state(d.get("goal_state"))
    return out


# ------------------------------------------------------------- line watch

class LineWatch:
    """Watch a child process's text stream line by line, tracking
    last-activity time, so a caller can enforce BOTH a total budget and
    a heartbeat-silence budget (the warden-probe contract bench.py's
    phase subprocesses ride).  The reader thread forwards each line to
    ``on_line`` and keeps a short tail for attributable errors."""

    def __init__(self, proc: subprocess.Popen, stream, on_line=None):
        self.proc = proc
        self.last_activity = time.time()
        self.tail: List[str] = []
        self._on_line = on_line
        self._thread = threading.Thread(target=self._drain,
                                        args=(stream,), daemon=True)
        self._thread.start()

    def _drain(self, stream) -> None:
        for line in stream:
            self.last_activity = time.time()
            self.tail.append(line.rstrip()[:300])
            del self.tail[:-5]
            if self._on_line is not None:
                self._on_line(line)

    def silence(self) -> float:
        return time.time() - self.last_activity

    def kill(self) -> None:
        try:
            self.proc.kill()
        except OSError:
            pass
        self.proc.wait()

    def wait(self, timeout: float,
             silence: Optional[float] = None) -> Tuple[str, Optional[int]]:
        """Wait for exit within ``timeout`` total seconds, killing the
        child if its stream goes quiet for ``silence`` seconds.
        Returns ``("ok", returncode)``, ``("silence", None)``, or
        ``("total", None)`` — the child is dead in every case."""
        deadline = time.time() + timeout
        while True:
            try:
                rc = self.proc.wait(timeout=0.25)
                self._thread.join(timeout=5.0)
                return "ok", rc
            except subprocess.TimeoutExpired:
                pass
            if time.time() >= deadline:
                self.kill()
                return "total", None
            if silence is not None and self.silence() > silence:
                self.kill()
                return "silence", None


# ----------------------------------------------------------------- warden

@dataclasses.dataclass
class ChildDeath:
    """One reaped child: what rung died, how, and what it last said."""

    rung: str
    kind: str                   # classify_death vocabulary
    exitcode: Optional[int]
    detail: str
    last_hb: Optional[dict] = None


class Warden:
    """Parent half of the process-isolation layer: spawn one child per
    failover rung, enforce heartbeat deadlines with SIGKILL, classify
    deaths, and resume the next rung from the durable checkpoint.

    ``fault`` injects a deterministic child-side fault for the CI
    matrix: ``{"kind": "hang"|"die"|"exit"|"raise", "at": k}`` fires at
    dispatch index ``k`` of the FIRST rung it matches (optional
    ``"engine"`` restricts the rung; optional ``"spawns": [0, 1]``
    targets spawn indices instead — how the elastic SIGKILL matrix
    kills the 8-wide and 4-wide children but spares the 2-wide one) —
    a hang blocks the dispatch (the
    warden must kill), ``die`` is SIGKILL-self (an external/OOM kill),
    ``exit`` is an abrupt ``os._exit``, ``raise`` a fatal in-child
    error reported over the pipe."""

    def __init__(self, factory: str,
                 factory_kwargs: Optional[dict] = None,
                 transform: Optional[str] = None,
                 ladder: Tuple[str, ...] = ("sharded", "device", "host"),
                 policy: Optional[RetryPolicy] = None,
                 checkpoint_path: Optional[str] = None,
                 checkpoint_every: int = 0,
                 strict: bool = True,
                 max_depth: Optional[int] = None,
                 max_secs: Optional[float] = None,
                 chunk: int = 1 << 10,
                 frontier_cap: int = 1 << 14,
                 visited_cap: int = 1 << 20,
                 ev_budget=None,
                 aot_warmup: bool = False,
                 boot_grace: float = 240.0,
                 first_grace: Optional[float] = None,
                 steady_grace: float = 120.0,
                 idle_grace: float = 300.0,
                 grace_slack: float = 5.0,
                 fault: Optional[dict] = None,
                 env: Optional[dict] = None,
                 extra_sys_path: Optional[List[str]] = None,
                 telemetry=None,
                 elastic: bool = False,
                 trace_id: Optional[str] = None,
                 parent_span: Optional[str] = None):
        # Unified telemetry (tpu/telemetry.py): child heartbeats from
        # the pipe protocol are re-emitted as parent-side telemetry
        # events, so the flight log shows the child's dispatch-level
        # liveness even though the child is a separate process.
        self.telemetry = telemetry
        self.factory = factory
        self.factory_kwargs = factory_kwargs or {}
        self.transform = transform
        self.ladder = tuple(ladder)
        self.policy = policy or RetryPolicy()
        self.checkpoint_path = checkpoint_path
        self.checkpoint_every = checkpoint_every
        self.strict = strict
        self.max_depth = max_depth
        self.max_secs = max_secs
        self.chunk = chunk
        self.frontier_cap = frontier_cap
        self.visited_cap = visited_cap
        self.ev_budget = ev_budget
        self.aot_warmup = aot_warmup
        # Grace ladder: boot (spawn + imports + jax init), first
        # dispatch per tag (XLA compile), steady dispatch, idle (host
        # work between dispatches).  The CHILD announces the applicable
        # grace on every heartbeat; the parent enforces announced grace
        # + slack, so policy lives in one place.
        self.boot_grace = boot_grace
        self.first_grace = (boot_grace if first_grace is None
                            else first_grace)
        self.steady_grace = steady_grace
        self.idle_grace = idle_grace
        self.grace_slack = grace_slack
        self.fault = fault
        self.env = env or {}
        self.extra_sys_path = list(extra_sys_path or [])
        # Elastic degraded-mesh ladder (ISSUE 9): expand the "sharded"
        # rung into width rungs sharded(D) -> ... -> sharded(2); each
        # width runs in its own child on a rebuilt smaller mesh,
        # resuming the unified checkpoint re-sharded to the new owner
        # map (tpu/supervisor.py expand_ladder — one expansion rule for
        # both modes).
        self.elastic = bool(elastic)
        # Causal-trace propagation (ISSUE 13, tpu/tracing.py): every
        # child gets DSLABS_TRACE_ID/DSLABS_PARENT_SPAN in its env, so
        # its run-dir telemetry recorder stamps the whole flight log
        # into the submitting trace's causal tree.  Defaults inherit
        # this process's own trace context — a warden inside a traced
        # service forwards the trace with no extra plumbing.
        from dslabs_tpu.tpu import tracing as tracing_mod

        env_trace, env_parent = tracing_mod.current_trace()
        self.trace_id = trace_id or env_trace
        self.parent_span = parent_span or env_parent
        self.mesh_shrinks = 0
        self.failures: List[EngineFailure] = []
        self.deaths: List[ChildDeath] = []
        self.killed_dispatches = 0
        # Platform the winning child actually ran on (the host rung's
        # forced-CPU contract is asserted against this).
        self.last_platform: Optional[str] = None

    # ------------------------------------------------------------- child io

    def _spec(self, rung: str, resume: bool,
              width: Optional[int] = None) -> dict:
        return {
            # Degraded-mesh rung width (None = the child's full device
            # set): the child builds make_mesh(width) for its sharded
            # supervisor.
            "mesh_width": width,
            "factory": self.factory,
            "factory_kwargs": self.factory_kwargs,
            "transform": self.transform,
            "rung": rung,
            "resume": resume,
            "strict": self.strict,
            "max_depth": self.max_depth,
            "max_secs": self.max_secs,
            "chunk": self.chunk,
            "frontier_cap": self.frontier_cap,
            "visited_cap": self.visited_cap,
            "ev_budget": (list(self.ev_budget)
                          if isinstance(self.ev_budget, tuple)
                          else self.ev_budget),
            "aot_warmup": self.aot_warmup,
            "checkpoint_path": self.checkpoint_path,
            "checkpoint_every": self.checkpoint_every,
            "policy": dataclasses.asdict(self.policy),
            "grace": {"boot": self.boot_grace, "first": self.first_grace,
                      "steady": self.steady_grace,
                      "idle": self.idle_grace},
            # The last rung runs with the CPU runtime forced: when the
            # accelerator runtime itself is the broken part, the final
            # rung must not touch it.
            "force_cpu": rung == self.ladder[-1],
            "fault": self.fault,
            "spawn_index": len(self.deaths),
        }

    def _child_env(self, spec: dict) -> dict:
        env = dict(os.environ)
        paths = [_REPO_ROOT] + self.extra_sys_path
        if env.get("PYTHONPATH"):
            paths.append(env["PYTHONPATH"])
        env["PYTHONPATH"] = os.pathsep.join(paths)
        env["DSLABS_WARDEN_CHILD"] = "1"
        if spec["force_cpu"]:
            env["JAX_PLATFORMS"] = "cpu"
        env.update(self.env)
        # Trace propagation AFTER self.env so explicit warden-level
        # trace identity wins over whatever a caller's env carried.
        from dslabs_tpu.tpu import tracing as tracing_mod

        env.update(tracing_mod.child_trace_env(self.trace_id,
                                               self.parent_span))
        return env

    def _run_child(self, rung: str, resume: bool,
                   width: Optional[int] = None) -> dict:
        """Spawn + supervise ONE rung child.  Returns the child's
        ``result`` message, or a death dict
        ``{"t": "death", "kind", "detail", "exitcode", "last_hb"}``."""
        spec = self._spec(rung, resume, width)
        proc = subprocess.Popen(
            [sys.executable, "-m", "dslabs_tpu.tpu.warden"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True,
            env=self._child_env(spec))

        def _tee(line):
            # stderr passes straight through (live heartbeats in the
            # driver tail) while LineWatch keeps the last lines — the
            # tail feeds the UNIFIED death taxonomy so an abrupt exit
            # with a MemoryError traceback classifies "oom", not
            # "crash" (supervisor.classify_child_death).
            sys.stderr.write(line)
            sys.stderr.flush()

        err_watch = LineWatch(proc, proc.stderr, on_line=_tee)
        try:
            proc.stdin.write(json.dumps(spec))
            proc.stdin.close()
        except BrokenPipeError:
            pass

        msgs: "queue.Queue[dict]" = queue.Queue()

        def _read():
            for line in proc.stdout:
                try:
                    msgs.put(json.loads(line))
                except ValueError:
                    continue          # stray child output, not protocol
            msgs.put({"t": "eof"})

        threading.Thread(target=_read, daemon=True).start()

        grace = self.boot_grace
        last_hb: Optional[dict] = None
        while True:
            try:
                msg = msgs.get(timeout=grace + self.grace_slack)
            except queue.Empty:
                # Heartbeat silence past the announced grace: the child
                # is wedged.  SIGKILL — the one interruption a hung XLA
                # runtime cannot ignore — and reap.
                try:
                    proc.kill()
                except OSError:
                    pass
                proc.wait()
                in_dispatch = (last_hb is not None
                               and last_hb.get("phase") == "start")
                if in_dispatch:
                    self.killed_dispatches += 1
                where = (f"dispatch {last_hb.get('tag')!r} "
                         f"(index {last_hb.get('n')}, depth "
                         f"{last_hb.get('depth')})" if in_dispatch
                         else "boot/idle")
                return {"t": "death", "kind": "wedge",
                        "exitcode": proc.returncode, "last_hb": last_hb,
                        "detail": (f"child silent > {grace:.1f}s in "
                                   f"{where}; SIGKILLed and reaped")}
            t = msg.get("t")
            if t == "hb":
                last_hb = msg
                grace = float(msg.get("grace", self.steady_grace))
                if self.telemetry is not None:
                    self.telemetry.event(
                        "heartbeat", rung=rung,
                        phase=msg.get("phase"), tag=msg.get("tag"),
                        n=msg.get("n"), depth=msg.get("depth"),
                        ckpt_depth=msg.get("ckpt_depth"),
                        grace=msg.get("grace"))
                continue
            if t == "result":
                proc.wait()
                return msg
            if t == "err":
                # The child reported a classified failure and will exit
                # CHILD_RC_FAILED; give it a moment, then reap.
                try:
                    rc = proc.wait(timeout=30.0)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    rc = proc.wait()
                return {"t": "death",
                        "kind": classify_death(rc, False,
                                               err_watch.tail),
                        "exitcode": rc, "last_hb": last_hb,
                        "detail": msg.get("error", "child failure")}
            if t == "eof":
                rc = proc.wait()
                kind = classify_death(rc, False, err_watch.tail)
                return {"t": "death", "kind": kind, "exitcode": rc,
                        "last_hb": last_hb,
                        "detail": (f"child exited rc={rc} without a "
                                   f"result (classified {kind}; last "
                                   f"heartbeat: {last_hb}; stderr "
                                   f"tail: {err_watch.tail[-2:]})")}

    # ----------------------------------------------------------------- run

    def run(self, resume: bool = False):
        """Run the ladder to a verdict, one supervised child per rung.
        Failover rungs always resume from the durable checkpoint when a
        matching dump exists (the in-child supervisor verifies the
        fingerprint).  Raises :class:`SupervisorExhausted` with the
        per-rung failure chain when every rung's child dies."""
        self.failures = []
        self.deaths = []
        self.killed_dispatches = 0
        self.mesh_shrinks = 0
        if self.elastic:
            import jax

            from dslabs_tpu.tpu.supervisor import expand_ladder

            specs = expand_ladder(self.ladder, len(jax.devices()), True)
            full_width = len(jax.devices())
        else:
            specs = [(r, None) for r in self.ladder]
            full_width = None
        spawned = 0
        prev_width = None
        for i, (rung, width) in enumerate(specs):
            eff = None
            if rung == "sharded" and self.elastic:
                eff = width or full_width
                if prev_width is not None and eff < prev_width:
                    self.mesh_shrinks += 1
                    if self.telemetry is not None:
                        self.telemetry.event("mesh_shrunk",
                                             from_width=prev_width,
                                             to_width=eff)
                prev_width = eff
            res = self._run_child(rung, resume=(resume or i > 0),
                                  width=eff)
            spawned += 1
            if res.get("t") == "result":
                out = outcome_from_dict(res["outcome"])
                self.last_platform = res.get("platform")
                out.engine = rung
                out.failovers = len(self.failures)
                out.child_restarts = spawned - 1
                out.killed_dispatches = self.killed_dispatches
                out.mesh_shrinks = self.mesh_shrinks
                if out.mesh_width is None and eff is not None:
                    out.mesh_width = eff
                return out
            death = ChildDeath(rung=rung, kind=res["kind"],
                               exitcode=res.get("exitcode"),
                               detail=res["detail"],
                               last_hb=res.get("last_hb"))
            self.deaths.append(death)
            if self.telemetry is not None:
                self.telemetry.event(
                    "child_death", rung=rung, kind=death.kind,
                    exitcode=death.exitcode,
                    detail=death.detail[:200])
            self.failures.append(EngineFailure(
                rung, death.kind, RuntimeError(death.detail)))
        raise SupervisorExhausted(self.failures)


# ------------------------------------------------------------ child half

def _resolve(ref: str):
    """``"module:callable"`` -> the callable (child-side import)."""
    import importlib

    mod, _, name = ref.partition(":")
    obj = importlib.import_module(mod)
    for part in name.split("."):
        obj = getattr(obj, part)
    return obj


def _send(obj: dict) -> None:
    sys.stdout.write(json.dumps(obj) + "\n")
    sys.stdout.flush()


def _child_main() -> int:
    spec = json.load(sys.stdin)
    g = spec.get("grace") or {}
    boot_g = float(g.get("boot", 240.0))
    first_g = float(g.get("first", boot_g))
    steady_g = float(g.get("steady", 120.0))
    idle_g = float(g.get("idle", 300.0))
    _send({"t": "hb", "phase": "boot", "stage": "spawned",
           "grace": boot_g})
    if spec.get("force_cpu"):
        # The env var alone is not enough on machines with an
        # accelerator plugin that re-pins platforms at site init
        # (tests/conftest.py measured this) — re-pin via config too.
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
    from dslabs_tpu.tpu.supervisor import (RetryPolicy, SearchSupervisor,
                                           SupervisorExhausted)

    proto = _resolve(spec["factory"])(**(spec.get("factory_kwargs")
                                         or {}))
    if spec.get("transform"):
        proto = _resolve(spec["transform"])(proto)
    _send({"t": "hb", "phase": "boot", "stage": "protocol",
           "grace": boot_g})

    policy = RetryPolicy(**(spec.get("policy") or {}))
    ev = spec.get("ev_budget")
    if isinstance(ev, list):
        ev = tuple(ev)
    ckpt_path = spec.get("checkpoint_path")
    fault = spec.get("fault")
    rung = spec["rung"]
    if fault is not None:
        if fault.get("spawns") is not None:
            # Explicit spawn targeting (the elastic SIGKILL matrix:
            # kill the 8-wide AND the 4-wide child, let the 2-wide
            # finish) — overrides the engine/first-child scoping, which
            # cannot distinguish same-named width rungs.
            if int(spec.get("spawn_index", 0)) not in fault["spawns"]:
                fault = None
        elif fault.get("engine") is not None:
            if fault["engine"] != rung:
                fault = None
        elif int(spec.get("spawn_index", 0)) > 0:
            # Un-scoped faults fire on the FIRST child only — otherwise
            # the same injected death would chase the run down every
            # rung of the ladder.
            fault = None
    seen_tags = set()
    st = {"ckpt_depth": None}
    sup_ref: Dict[str, object] = {}

    def observer(phase, tag, idx, depth):
        if phase == "start":
            first = tag not in seen_tags
            seen_tags.add(tag)
            scale = 1.0
            b = sup_ref.get("sup") and sup_ref["sup"].boundary
            if b is not None:
                scale = b._deadline_scale(tag)
            grace = first_g if first else steady_g * max(scale, 1.0)
            _send({"t": "hb", "phase": "start", "tag": tag, "n": idx,
                   "depth": depth, "ckpt_depth": st["ckpt_depth"],
                   "grace": grace})
            if fault is not None:
                kind = fault.get("kind")
                at = int(fault.get("at", 0))
                # Process-death kinds arm at index ``at`` and fire on
                # the first armed dispatch; with ``after_ckpt`` they
                # additionally wait until a DURABLE checkpoint has been
                # observed on disk (peek_depth above), so resume-parity
                # tests are deterministic instead of racing the async
                # dump drain.  ``raise`` keeps exact-index semantics (a
                # repeated raise would just exhaust retries).
                due = (idx >= at if kind in ("die", "exit", "hang")
                       else idx == at)
                if due and fault.get("after_ckpt") and (
                        st["ckpt_depth"] is None):
                    due = False
                if due:
                    if kind == "die":
                        os.kill(os.getpid(), signal.SIGKILL)
                    elif kind == "exit":
                        os._exit(int(fault.get("rc", 86)))
                    elif kind == "hang":
                        # An UNINTERRUPTIBLE block, as a wedged runtime
                        # would be — only the parent's SIGKILL ends it.
                        time.sleep(float(fault.get("secs", 3600.0)))
                    elif kind == "raise":
                        raise RuntimeError(
                            f"injected warden child fault [{tag} "
                            f"dispatch {idx}]")
        else:
            if ckpt_path and tag.rsplit(".", 1)[-1] in ("promote",
                                                        "expand"):
                d = ckpt_mod.peek_depth(ckpt_path)
                if d is not None:
                    st["ckpt_depth"] = d
            _send({"t": "hb", "phase": "done", "tag": tag, "n": idx,
                   "depth": depth, "ckpt_depth": st["ckpt_depth"],
                   "grace": idle_g})

    # A checkpointed child gets a run-dir telemetry recorder of its
    # own (flight.jsonl + STATUS.json beside the dump): `telemetry
    # watch <run-dir>` then renders the CHILD's live depth/rate/skew
    # from the directory alone — the parent's heartbeat re-emission
    # covers liveness, this covers progress.  Never fatal: a child on
    # a read-only dir just runs unrecorded.
    child_tel = None
    if ckpt_path:
        try:
            from dslabs_tpu.tpu.telemetry import Telemetry

            child_tel = Telemetry.for_checkpoint(
                ckpt_path, engine_hint=f"warden-child:{rung}")
        except Exception:  # noqa: BLE001 — observability is optional
            child_tel = None
    # Degraded-mesh rung: the child rebuilds the SMALLER mesh and its
    # in-child supervisor resumes the unified checkpoint re-sharded to
    # the new owner map (tpu/checkpoint.py carries everything needed).
    mesh = None
    width = spec.get("mesh_width")
    if width and rung == "sharded":
        from dslabs_tpu.tpu.sharded import make_mesh

        mesh = make_mesh(int(width))
    sup = SearchSupervisor(
        proto, ladder=(rung,), policy=policy, mesh=mesh,
        checkpoint_path=ckpt_path,
        checkpoint_every=spec.get("checkpoint_every", 0),
        strict=spec.get("strict", True),
        max_depth=spec.get("max_depth"),
        max_secs=spec.get("max_secs"),
        chunk=spec.get("chunk", 1 << 10),
        frontier_cap=spec.get("frontier_cap", 1 << 14),
        visited_cap=spec.get("visited_cap", 1 << 20),
        ev_budget=ev, aot_warmup=spec.get("aot_warmup", False),
        dispatch_observer=observer, telemetry=child_tel)
    sup_ref["sup"] = sup
    try:
        out = sup.run(resume=bool(spec.get("resume")))
    except BaseException as e:  # noqa: BLE001 — reported over the pipe
        kind = "failed"
        if isinstance(e, SupervisorExhausted) and e.failures:
            kind = e.failures[-1].kind
        _send({"t": "err", "kind": kind,
               "error": f"{type(e).__name__}: {e}"[:500]})
        return CHILD_RC_FAILED
    finally:
        if child_tel is not None:
            child_tel.close()
    import jax

    _send({"t": "result", "outcome": outcome_to_dict(out),
           "platform": jax.devices()[0].platform})
    return 0


if __name__ == "__main__":
    sys.exit(_child_main())
