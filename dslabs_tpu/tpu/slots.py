"""Multi-instance slot arrays for the protocol spec layer (ISSUE 20,
ROADMAP #1).

Replicated protocols are arrays of near-identical state machines: lab3
multi-Paxos keeps per-SLOT log entries and vote bitmaps, lab4 keeps
per-group Paxos blocks and per-transaction 2PC votes.  The hand twins
lowered these by hand — ``LOG + 4*(slot-1) + j`` offset arithmetic
repeated in the twin, the adapter, and the predicates, three copies
that had to drift together.  A :class:`Slots` declaration replaces
that: a named block of ``n`` logical instances, each carrying the same
small record of bounded int fields, lowered mechanically to one
``{block}.{field}`` array Field per record field (struct-of-arrays —
each record field keeps its OWN packing domain, which is where the
lab3/lab4 bit-packing win comes from: a 1-bit ``chosen`` flag no
longer shares a lane encoding with a 20-bit packed command).

Slot access from handlers goes through the Ctx slot ops
(``ctx.slot_get/slot_put`` in tpu/compiler.py, delegating here): a
STATIC index outside the declared range is a loud compile-gate
``SpecError`` (never a silent zero from the one-hot mux); a traced
index lowers to the engine's one-hot select, exactly the hand-twin
discipline.  ``clear_upto`` is the slot-windowed garbage bound: the
lab3 twin's log GC — "slots at or below the collective floor reset to
their cleared value" — as one declaration-driven lowering instead of
per-field hand loops.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

__all__ = ["SlotField", "Slots", "expand_slots", "slot_lane"]


@dataclasses.dataclass(frozen=True)
class SlotField:
    """One field of a slot record.  ``init`` is an int or a callable
    ``(instance_index, slot_index) -> int`` (slot_index is LOGICAL,
    i.e. already offset by the block's ``base``).  ``clear`` is the
    value :func:`Slots.clear_upto` resets the field to — the garbage-
    collected representation, which must itself sit inside the
    declared domain."""

    name: str
    init: object = 0
    lo: int = 0
    hi: Optional[int] = None
    delta: Optional[int] = None
    clear: int = 0


@dataclasses.dataclass(frozen=True)
class Slots:
    """``n`` logical instances of a record of :class:`SlotField`s,
    indexed ``base .. base + n - 1`` (lab3 slot numbers are 1-based;
    declaring ``base=1`` keeps handler arithmetic in protocol terms).
    Appears inside ``NodeKind.fields``; the spec expands it at
    construction via :func:`expand_slots` and remembers the block for
    Ctx slot ops, fingerprinting, and conformance."""

    name: str
    n: int
    fields: Tuple[SlotField, ...]
    base: int = 0

    def field(self, name: str) -> SlotField:
        for f in self.fields:
            if f.name == name:
                return f
        raise KeyError(name)

    def lane(self, field: str) -> str:
        return slot_lane(self.name, field)


def slot_lane(block: str, field: str) -> str:
    """The lowered Field name one slot-record field occupies."""
    return f"{block}.{field}"


def _field_init(sf: SlotField, n: int):
    """Lower a SlotField init to the compiler Field init form (int, or
    per-instance callable returning the full [n] list)."""
    if callable(sf.init):
        def init(i, _sf=sf, _n=n):
            return [int(_sf.init(i, s)) for s in range(_n)]
        return init
    return sf.init


def expand_slots(block: "Slots", compiler_field_cls) -> list:
    """Lower one Slots block to its struct-of-arrays compiler Fields
    (one array Field per record field, size ``n``, the record field's
    own domain).  ``compiler_field_cls`` is ``compiler.Field`` — passed
    in to keep this module import-light (the compiler imports us)."""
    from dslabs_tpu.tpu.compiler import SpecError

    if block.n <= 0:
        raise SpecError(
            f"Slots block {block.name!r} declares {block.n} instances "
            f"— an empty slot array has no lanes to lower",
            field=block.name, code="C4")
    if not block.fields:
        raise SpecError(
            f"Slots block {block.name!r} declares no fields",
            field=block.name, code="C4")
    out = []
    for sf in block.fields:
        if sf.hi is not None and not (sf.lo <= sf.clear <= sf.hi):
            raise SpecError(
                f"Slots block {block.name!r} field {sf.name!r}: clear "
                f"value {sf.clear} outside declared domain "
                f"[{sf.lo}, {sf.hi}]", field=sf.name, code="C4")
        out.append(compiler_field_cls(
            name=slot_lane(block.name, sf.name), size=block.n,
            init=_field_init(sf, block.n), lo=sf.lo, hi=sf.hi,
            delta=sf.delta))
    return out
