"""Bit-packed frontier encoding (ISSUE 15 leg (a), ROADMAP #4a).

HBM bytes-per-state is the binding constraint on frontier width
everywhere: every protocol lane is stored as a full int32 even though
the spec already declares tiny enum/counter domains (a lab1 message tag
is one of two values; a ballot flag is a bit).  This module derives a
**packing descriptor** from the compiled spec's declared domains
(``TensorProtocol.lane_domains``, emitted by ``ProtocolSpec.compile()``
— enum tag cardinalities, node-index ranges, counter budgets) and
provides fused ``pack``/``unpack`` device functions so the frontier
SoA, the spill spool segments, and checkpoint rows are stored packed
while the expand/check handlers keep operating on the existing int32
view, decoded in-register at expand time.

Semantics are BIT-EXACT by construction: fingerprints, predicates, and
handlers all run on the unpacked int32 rows — packing is purely a
storage encoding, so the unique/explored/verdict trajectory of a packed
search is identical to the unpacked one (pinned by
tests/test_packing.py).

Descriptor model (``LanePacking``):

* every flat state lane (nodes ++ net ++ timers ++ exc, the
  ``flatten_state`` order) gets a ``(word, shift, width, lo, sentinel)``
  entry: the 32-bit word it lives in, its bit offset, its bit width,
  its domain bias, and whether the lane can hold the engine's SENTINEL
  (net/timer lanes — empty rows are all-SENTINEL);
* a bounded lane ``[lo, hi]`` encodes ``v - lo`` in
  ``ceil(log2(hi - lo + 1 [+ 1 sentinel code]))`` bits; SENTINEL maps
  to the all-ones code of the lane (which the domain can never reach —
  the width derivation reserves it);
* an unbounded lane (``None`` domain — hand twins declare nothing)
  stays a raw 32-bit word, SENTINEL passes through untouched;
* a **delta lane** (``("delta", bits)`` domain, from
  ``Field(delta=bits)`` — ISSUE 18 leg (b)) is an unbounded
  monotone-ish counter (view numbers, liveness ticks) packed as
  ``v - base`` in ``bits`` bits, where ``base`` is a per-lane int32
  the CALLER carries (the sharded engine tracks the per-level minimum
  and re-bases at promote).  Delta lanes are opt-in
  (``derive_packing(..., delta=True)``) because the base plumbing is
  an engine contract; with ``delta=False`` (the single-device default)
  a delta domain derives as raw, so both engines agree on the static
  part of the layout.  A value outside the ``[base, base + window)``
  wire window counts as out-of-domain — loud, never silent;
* lanes are laid out first-fit in declaration order and never straddle
  a word boundary, so pack/unpack are shift+mask on one word each.

A protocol with no declared domains derives the **identity** descriptor
(``words == lanes``, pack/unpack return their input), which is how the
packed path ships ON by default without touching the hand twins'
lowered programs: identity packing traces to the identical jaxpr.

Packing never guesses: a live value OUTSIDE its declared domain is
counted by ``pack_jnp(..., count_bad=True)`` and surfaced by the engine
as a loud :class:`~dslabs_tpu.tpu.engine.CapacityOverflow` — a wrong
bound is a crash with a name, never silent state corruption.
"""

from __future__ import annotations

import dataclasses
import zlib
from typing import List, Optional, Tuple

import numpy as np

__all__ = ["LanePacking", "derive_packing", "RAW_WIDTH"]

RAW_WIDTH = 32

# Engine SENTINEL (duplicated to keep this module import-light; pinned
# equal by tests/test_packing.py).
_SENTINEL = np.int32(2 ** 31 - 1)


def _width_for(lo: int, hi: int, sentinel: bool) -> int:
    """Bit width for domain [lo, hi] (+1 reserved all-ones sentinel
    code when the lane can hold SENTINEL)."""
    span = hi - lo + 1
    codes = span + (1 if sentinel else 0)
    w = max(1, int(codes - 1).bit_length())
    # Sentinel lanes need the all-ones code strictly above the domain:
    # 2^w - 1 >= span, guaranteed by bit_length(codes - 1) with the +1.
    return w


@dataclasses.dataclass(frozen=True)
class LanePacking:
    """Per-lane packing descriptor for one protocol's flat state rows.

    Arrays are all length ``lanes`` (np int64/bool constants baked into
    the traced programs): ``word``/``shift``/``width`` place each lane,
    ``lo`` is the domain bias, ``sent`` marks SENTINEL-capable lanes,
    ``raw`` marks 32-bit passthrough lanes, ``dlt`` marks
    delta-from-base lanes (bias supplied at pack/unpack time via the
    ``base`` vector instead of the static ``lo``)."""

    lanes: int
    words: int
    word: np.ndarray
    shift: np.ndarray
    width: np.ndarray
    lo: np.ndarray
    sent: np.ndarray
    raw: np.ndarray
    dlt: Optional[np.ndarray] = None

    def __post_init__(self):
        if self.dlt is None:
            object.__setattr__(self, "dlt",
                               np.zeros(self.lanes, bool))

    # ------------------------------------------------------------ meta

    @property
    def identity(self) -> bool:
        """True when packing is a no-op (every lane raw, one word per
        lane) — the hand-twin default; callers skip the wrap entirely."""
        return self.words == self.lanes and bool(self.raw.all())

    @property
    def has_delta(self) -> bool:
        """True when any lane is delta-from-base encoded — pack/unpack
        then REQUIRE the ``base`` vector (a missing base is a loud
        ValueError, never a silent zero-bias decode)."""
        return bool(self.dlt.any())

    @property
    def delta_lanes(self) -> np.ndarray:
        """Flat lane indices of the delta-encoded lanes, in order."""
        return np.nonzero(self.dlt)[0]

    @property
    def bytes_per_state(self) -> int:
        """Packed bytes per stored frontier row."""
        return int(self.words) * 4

    @property
    def bytes_per_state_unpacked(self) -> int:
        return int(self.lanes) * 4

    @property
    def pack_ratio(self) -> float:
        """unpacked/packed bytes — >= 1.0; the capacity multiplier on
        frontier_cap/visited-spool width at fixed HBM."""
        return self.bytes_per_state_unpacked / max(self.bytes_per_state,
                                                   1)

    def signature(self) -> str:
        """Stable identity of the ENCODING (not the protocol): two
        descriptors with equal signatures produce byte-identical packed
        rows.  Rides checkpoints as the ``frontier_encoding`` marker."""
        if self.identity:
            return "raw"
        parts = [
            np.asarray([self.lanes, self.words], np.int64),
            self.word.astype(np.int64), self.shift.astype(np.int64),
            self.width.astype(np.int64), self.lo.astype(np.int64),
            self.sent.astype(np.int64), self.raw.astype(np.int64),
        ]
        # Delta lanes extend the blob ONLY when present, so every
        # pre-existing (static-domain) descriptor keeps its signature
        # and old checkpoints keep resuming.
        if self.has_delta:
            parts.append(self.dlt.astype(np.int64))
        blob = np.concatenate(parts).tobytes()
        return f"packed:{self.words}w:{zlib.crc32(blob) & 0xFFFFFFFF:08x}"

    def descriptor(self) -> dict:
        """The reportable packing descriptor (bench / STATUS.json):
        lane -> word/offset/width plus the headline byte counts."""
        return {
            "lanes": int(self.lanes),
            "words": int(self.words),
            "bytes_per_state": self.bytes_per_state,
            "bytes_per_state_unpacked": self.bytes_per_state_unpacked,
            "pack_ratio": round(self.pack_ratio, 3),
            "signature": self.signature(),
            "lane_bits": [int(w) for w in self.width],
            "delta_lanes": int(self.dlt.sum()),
        }

    # ----------------------------------------------- word/lane ranges

    def _word_ranges(self) -> List[Tuple[int, int, int]]:
        """[(word, lane_start, lane_end)] — lanes are assigned to words
        contiguously in order, so each word covers one lane slice."""
        out = []
        for w in range(self.words):
            idx = np.nonzero(self.word == w)[0]
            out.append((w, int(idx[0]), int(idx[-1]) + 1))
        return out

    # ------------------------------------------------------- jnp path

    def _require_base(self, base):
        if self.has_delta and base is None:
            raise ValueError(
                "packing descriptor has delta lanes but no base vector "
                "was supplied — the caller must carry the per-level "
                "base (see ISSUE 18 leg (b))")

    def _lo_eff_jnp(self, base):
        """Effective per-lane bias: the static ``lo`` except on delta
        lanes, where the caller's ``base`` vector [lanes] supplies it."""
        import jax.numpy as jnp

        lo = jnp.asarray(self.lo, jnp.int32)
        if not self.has_delta:
            return lo
        return jnp.where(jnp.asarray(self.dlt),
                         jnp.asarray(base, jnp.int32).reshape(-1), lo)

    def pack_jnp(self, rows, base=None, count_bad: bool = False):
        """[N, lanes] int32 -> [N, words] int32 (device).  With
        ``count_bad``, also returns an int32 [N] vector counting each
        row's values OUTSIDE their declared domain (callers mask to
        live rows and raise loudly — a wrong bound must never silently
        corrupt a stored state).  ``base`` is the [lanes] int32 bias
        vector, required iff the descriptor has delta lanes."""
        import jax.numpy as jnp

        self._require_base(base)
        if self.identity:
            return ((rows, jnp.zeros((rows.shape[0],), jnp.int32))
                    if count_bad else rows)
        lo = self._lo_eff_jnp(base)
        raw = jnp.asarray(self.raw)
        sent = jnp.asarray(self.sent)
        shift = jnp.asarray(self.shift, jnp.uint32)
        mask = jnp.asarray(
            ((np.uint64(1) << self.width.astype(np.uint64)) - 1
             ).astype(np.uint32))
        is_sent = rows == _SENTINEL
        enc = (rows.astype(jnp.uint32) - lo.astype(jnp.uint32)) & mask
        enc = jnp.where(raw[None, :], rows.astype(jnp.uint32), enc)
        enc = jnp.where((sent & ~raw)[None, :] & is_sent, mask[None, :],
                        enc)
        shifted = enc << shift[None, :]
        cols = []
        for _w, s, e in self._word_ranges():
            cols.append(jnp.sum(shifted[:, s:e].astype(jnp.uint32),
                                axis=1, dtype=jnp.uint32))
        packed = jnp.stack(cols, axis=1).astype(jnp.int32)
        if not count_bad:
            return packed
        # Out-of-domain detection on bounded lanes: value not SENTINEL
        # and (v - lo) has bits above the lane width, or collides with
        # the reserved sentinel code.
        span = (rows.astype(jnp.uint32) - lo.astype(jnp.uint32))
        over = span > mask[None, :]
        hit_sent = sent[None, :] & (span == mask[None, :])
        bad = (~raw)[None, :] & ~is_sent & (over | hit_sent)
        return packed, jnp.sum(bad, axis=1).astype(jnp.int32)

    def unpack_jnp(self, packed, base=None):
        """[N, words] int32 -> [N, lanes] int32 (device; exact inverse
        of :meth:`pack_jnp` on in-domain rows — with the SAME ``base``
        the rows were packed against)."""
        import jax.numpy as jnp

        self._require_base(base)
        if self.identity:
            return packed
        pu = packed.astype(jnp.uint32)
        parts = []
        for w, s, e in self._word_ranges():
            sh = jnp.asarray(self.shift[s:e], jnp.uint32)
            mk = jnp.asarray(
                ((np.uint64(1) << self.width[s:e].astype(np.uint64)) - 1
                 ).astype(np.uint32))
            parts.append((pu[:, w:w + 1] >> sh[None, :]) & mk[None, :])
        bits = jnp.concatenate(parts, axis=1)
        lo = self._lo_eff_jnp(base)
        raw = jnp.asarray(self.raw)
        sent = jnp.asarray(self.sent)
        mask = jnp.asarray(
            ((np.uint64(1) << self.width.astype(np.uint64)) - 1
             ).astype(np.uint32))
        val = bits.astype(jnp.int32) + lo[None, :]
        val = jnp.where(raw[None, :], bits.astype(jnp.int32), val)
        return jnp.where((sent & ~raw)[None, :] & (bits == mask[None, :]),
                         _SENTINEL, val)

    # ------------------------------------------------------ host path

    def _lo_eff_np(self, base) -> np.ndarray:
        if not self.has_delta:
            return self.lo
        return np.where(self.dlt,
                        np.asarray(base, np.int64).reshape(-1), self.lo)

    def pack_np(self, rows: np.ndarray, base=None) -> np.ndarray:
        """Host-side mirror of :meth:`pack_jnp` (exact same bits)."""
        self._require_base(base)
        rows = np.asarray(rows, np.int32).reshape(-1, self.lanes)
        if self.identity:
            return rows
        lo_eff = self._lo_eff_np(base)
        mask = ((np.uint64(1) << self.width.astype(np.uint64)) - 1
                ).astype(np.uint32)
        is_sent = rows == _SENTINEL
        enc = ((rows.astype(np.uint32)
                - lo_eff.astype(np.uint32)) & mask)
        enc = np.where(self.raw[None, :], rows.astype(np.uint32), enc)
        enc = np.where((self.sent & ~self.raw)[None, :] & is_sent,
                       mask[None, :], enc)
        shifted = enc << self.shift.astype(np.uint32)[None, :]
        out = np.zeros((len(rows), self.words), np.uint32)
        for w, s, e in self._word_ranges():
            out[:, w] = shifted[:, s:e].sum(axis=1, dtype=np.uint32)
        return out.astype(np.int32)

    def unpack_np(self, packed: np.ndarray, base=None) -> np.ndarray:
        self._require_base(base)
        packed = np.asarray(packed, np.int32).reshape(-1, self.words)
        if self.identity:
            return packed
        pu = packed.astype(np.uint32)
        bits = np.zeros((len(packed), self.lanes), np.uint32)
        for w, s, e in self._word_ranges():
            mk = ((np.uint64(1) << self.width[s:e].astype(np.uint64)) - 1
                  ).astype(np.uint32)
            bits[:, s:e] = ((pu[:, w:w + 1]
                             >> self.shift[s:e].astype(np.uint32)[None, :])
                            & mk[None, :])
        mask = ((np.uint64(1) << self.width.astype(np.uint64)) - 1
                ).astype(np.uint32)
        val = (bits.astype(np.int64)
               + self._lo_eff_np(base).astype(np.int64)).astype(np.int32)
        val = np.where(self.raw[None, :], bits.astype(np.int32), val)
        return np.where((self.sent & ~self.raw)[None, :]
                        & (bits == mask[None, :]), _SENTINEL, val)


def _flat_domains(protocol) -> Tuple[List[Optional[Tuple[int, int]]],
                                     List[bool]]:
    """Expand ``protocol.lane_domains`` to per-flat-lane (domain,
    sentinel-capable) in ``flatten_state`` order: nodes ++ net ++
    timers ++ exc."""
    p = protocol
    ld = getattr(p, "lane_domains", None) or {}
    nodes = list(ld.get("nodes") or [None] * p.node_width)
    msg = list(ld.get("msg") or [None] * p.msg_width)
    tmr = list(ld.get("timer") or [None] * p.timer_width)
    exc = ld.get("exc")
    if len(nodes) != p.node_width or len(msg) != p.msg_width \
            or len(tmr) != p.timer_width:
        raise ValueError(
            f"{p.name}: lane_domains shape mismatch "
            f"(nodes {len(nodes)}/{p.node_width}, msg "
            f"{len(msg)}/{p.msg_width}, timer {len(tmr)}/"
            f"{p.timer_width})")
    doms: List[Optional[Tuple[int, int]]] = []
    sent: List[bool] = []
    doms += nodes
    sent += [False] * p.node_width
    for _ in range(p.net_cap):
        doms += msg
        sent += [True] * p.msg_width
    for _ in range(p.n_nodes * p.timer_cap):
        doms += tmr
        sent += [True] * p.timer_width
    doms.append(exc)
    sent.append(False)
    return doms, sent


def derive_packing(protocol, lanes: int,
                   delta: bool = False) -> LanePacking:
    """Derive the packing descriptor for one protocol's flat rows.
    ``lanes`` is the engine's flat row width (cross-checked).  No
    declared domains -> the identity descriptor.

    ``delta`` opts into the delta-from-base lanes (ISSUE 18 leg (b)):
    a ``("delta", bits)`` domain packs ``v - base`` in ``bits`` bits
    with a caller-carried base vector.  With ``delta=False`` (the
    single-device engine) delta domains derive as raw 32-bit lanes —
    correct, just uncompressed — so a spec annotated for the mesh
    still runs unchanged on one chip."""
    doms, sent_caps = _flat_domains(protocol)
    if len(doms) != lanes:
        raise ValueError(
            f"{protocol.name}: domain expansion produced {len(doms)} "
            f"lanes, engine rows have {lanes}")
    word = np.zeros(lanes, np.int64)
    shift = np.zeros(lanes, np.int64)
    width = np.zeros(lanes, np.int64)
    lo = np.zeros(lanes, np.int64)
    sent = np.zeros(lanes, bool)
    raw = np.zeros(lanes, bool)
    dlt = np.zeros(lanes, bool)
    cur_word, cur_bits = 0, 0
    for i, (dom, s_cap) in enumerate(zip(doms, sent_caps)):
        is_dlt = False
        if dom is None:
            w, is_raw, lo_i = RAW_WIDTH, True, 0
        elif isinstance(dom, tuple) and len(dom) and dom[0] == "delta":
            bits = int(dom[1])
            if bits < 1:
                raise ValueError(
                    f"{protocol.name}: lane {i} delta width {bits} "
                    "must be >= 1 bit")
            is_dlt = delta and bits < RAW_WIDTH
            if is_dlt:
                w, is_raw, lo_i = bits, False, 0
            else:
                w, is_raw, lo_i = RAW_WIDTH, True, 0
        else:
            lo_i, hi_i = int(dom[0]), int(dom[1])
            if hi_i < lo_i:
                raise ValueError(
                    f"{protocol.name}: lane {i} domain [{lo_i}, {hi_i}] "
                    "is empty (hi < lo)")
            w = _width_for(lo_i, hi_i, s_cap)
            is_raw = w >= RAW_WIDTH or hi_i >= int(_SENTINEL)
            if is_raw:
                w, lo_i = RAW_WIDTH, 0
        if cur_bits + w > 32:
            cur_word += 1
            cur_bits = 0
        word[i] = cur_word
        shift[i] = cur_bits
        width[i] = w
        lo[i] = lo_i
        sent[i] = s_cap and not is_raw
        raw[i] = is_raw
        dlt[i] = is_dlt
        cur_bits += w
    return LanePacking(lanes=lanes, words=int(cur_word + 1), word=word,
                       shift=shift, width=width, lo=lo, sent=sent,
                       raw=raw, dlt=dlt)
