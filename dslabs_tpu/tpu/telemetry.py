"""Unified telemetry: dispatch-span flight recorder, metrics, reports.

Before this module every subsystem emitted its own ad-hoc signals —
SearchOutcome counters, warden heartbeat lines, bench JSON fragments,
``DSLABS_LEVEL_TIMING`` records — and a wedged run left almost nothing
behind (BENCH_r05 died in preflight with one scraped stderr line to
explain a 300-second hang).  This is the one observability substrate
they all feed, built on the paper's discipline that **every signal must
come from scalar readbacks already paid for**: the recorder never adds
a device dispatch and never reads anything off the device beyond the
fused stats vector the engines already sync (enforced by the
overhead-guard test in tests/test_telemetry.py).

Pieces:

* **Dispatch spans.**  :meth:`Telemetry.attach` hooks the existing
  ``TensorSearch._dispatch`` seam — the one choke point every hot-loop
  device dispatch already funnels through (tpu/supervisor.py).  Each
  dispatch becomes a structured span (engine, site, per-engine index,
  live BFS depth, wall seconds, retries absorbed by the supervisor
  boundary, watchdog deadline-scale, outcome) appended to a bounded
  in-memory ring and — when a ``flight_log`` is configured — streamed
  as JSONL to the **flight-recorder file** beside the checkpoint
  (tpu/checkpoint.py ``default_flight_log``).  The file is opened
  line-buffered append-only and every dispatch writes a begin marker
  BEFORE the device call, so a SIGKILL'd or wedged run leaves a
  readable trail whose torn tail names the in-flight dispatch —
  exactly what the BENCH_r05 shape lacked.

* **Metrics registry.**  Counters / gauges / histograms fed from the
  host scalars the run already holds: per-level fused-stats records
  (all three engines + the swarm's rounds), spill/overflow counters,
  supervisor retry/failover/rung events, and warden heartbeats
  re-emitted from the child→parent JSON protocol.  ``summary()`` is
  the JSON block bench phases attach to their output.

* **Profiler windows.**  ``DSLABS_PROFILE=<dir>`` wraps the first
  ``DSLABS_PROFILE_STEPS`` post-warmup hot-loop dispatches (the first
  dispatch at each site pays the XLA compile and is skipped) in
  ``jax.profiler.trace`` — an opt-in deep dive that rides the same
  seam, zero cost when the knob is unset.

* **Run reports.**  ``python -m dslabs_tpu.tpu.telemetry report
  <run-dir-or-flight-log>`` renders the flight log alone into per-level
  throughput series, per-site dispatch-latency percentiles, the
  retry/failover/heartbeat timeline, spill and overflow counts, the
  compile-vs-search wall split, and the in-flight dispatch of a torn
  tail.  docs/observability.md documents the span model and the
  "diagnosing a wedge" recipe rides it (docs/resilience.md).

Thread-safe (the portfolio runs two lanes against one recorder); pure
host-side Python + stdlib — importing this module never imports jax.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

__all__ = ["Telemetry", "MetricsRegistry", "Counter", "Gauge",
           "Histogram", "read_flight", "tail_records", "build_report",
           "render_report", "render_sites", "main"]

# Hot-loop sites whose steady-state dispatches are worth a profiler
# capture (the compile-paying first dispatch at a site is skipped).
_PROFILE_SITES = ("superstep", "step", "round", "expand")


# ------------------------------------------------------------- registry

class Counter:
    """Monotonic count (events, dispatches, retries)."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0

    def inc(self, v: int = 1) -> None:
        self.value += v


class Gauge:
    """Last-written scalar (depth, table load, outcome counters)."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0

    def set(self, v) -> None:
        self.value = v


class Histogram:
    """Bounded sample store with percentile readout (span latencies).
    Keeps the most recent ``cap`` observations — a run report wants
    the distribution, not an unbounded host array."""

    __slots__ = ("values", "count", "total", "cap")

    def __init__(self, cap: int = 4096):
        self.values: deque = deque(maxlen=cap)
        self.count = 0
        self.total = 0.0
        self.cap = cap

    def observe(self, v: float) -> None:
        self.values.append(float(v))
        self.count += 1
        self.total += float(v)

    def percentile(self, q: float) -> float:
        if not self.values:
            return 0.0
        vs = sorted(self.values)
        i = min(len(vs) - 1, max(0, int(round(q * (len(vs) - 1)))))
        return vs[i]

    def snapshot(self) -> dict:
        return {"count": self.count,
                "total": round(self.total, 6),
                "p50": round(self.percentile(0.50), 6),
                "p90": round(self.percentile(0.90), 6),
                "p99": round(self.percentile(0.99), 6),
                "max": round(max(self.values, default=0.0), 6)}


class MetricsRegistry:
    """Create-on-touch named metrics; ``snapshot()`` is plain JSON."""

    def __init__(self):
        self.counters: Dict[str, Counter] = {}
        self.gauges: Dict[str, Gauge] = {}
        self.histograms: Dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        c = self.counters.get(name)
        if c is None:
            c = self.counters[name] = Counter()
        return c

    def gauge(self, name: str) -> Gauge:
        g = self.gauges.get(name)
        if g is None:
            g = self.gauges[name] = Gauge()
        return g

    def histogram(self, name: str) -> Histogram:
        h = self.histograms.get(name)
        if h is None:
            h = self.histograms[name] = Histogram()
        return h

    def snapshot(self) -> dict:
        return {
            "counters": {k: c.value for k, c in self.counters.items()},
            "gauges": {k: g.value for k, g in self.gauges.items()},
            "histograms": {k: h.snapshot()
                           for k, h in self.histograms.items()},
        }


# ------------------------------------------------------ profiler window

class _ProfileWindow:
    """Opt-in ``jax.profiler.trace`` capture of the first K post-warmup
    hot-loop dispatches (DSLABS_PROFILE=<dir>, DSLABS_PROFILE_STEPS).
    The first dispatch at each site pays the XLA compile and is never
    captured (a compile trace drowns the steady-state picture).  All
    failures degrade to "window off" — profiling must never take a
    search down."""

    def __init__(self):
        self.dir = os.environ.get("DSLABS_PROFILE") or None
        try:
            self.steps = int(os.environ.get("DSLABS_PROFILE_STEPS",
                                            "4"))
        except ValueError:
            self.steps = 4
        self.active = False
        self.done = self.dir is None
        self._left = 0
        self._seen: Dict[str, int] = {}

    def on_start(self, site: str) -> None:
        if self.done or self.active or site not in _PROFILE_SITES:
            return
        n = self._seen.get(site, 0)
        self._seen[site] = n + 1
        if n == 0:
            return                     # compile-paying warm-up dispatch
        try:
            import jax

            jax.profiler.start_trace(self.dir)
            self.active = True
            self._left = self.steps
        except Exception:
            self.done = True

    def on_done(self, site: str) -> None:
        if not self.active or site not in _PROFILE_SITES:
            return
        self._left -= 1
        if self._left <= 0:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception:
                pass
            self.active = False
            self.done = True


# ------------------------------------------------------------- recorder

class Telemetry:
    """The per-run recorder.  ``attach(search)`` routes the search's
    ``_dispatch`` seam through :meth:`record_dispatch`; engines feed
    per-level fused-stats records via :meth:`on_level` and final
    outcomes via :meth:`on_outcome`; the supervisor/warden feed
    recovery events via :meth:`event`.  Everything lands in the ring
    buffer, the metrics registry, and (when configured) the JSONL
    flight-recorder file."""

    def __init__(self, flight_log: Optional[str] = None,
                 ring: Optional[int] = None,
                 engine_hint: Optional[str] = None):
        if ring is None:
            try:
                ring = int(os.environ.get("DSLABS_TELEMETRY_RING",
                                          "512"))
            except ValueError:
                ring = 512
        self.ring: deque = deque(maxlen=ring)
        self.registry = MetricsRegistry()
        self.levels: List[dict] = []
        self.events: deque = deque(maxlen=512)
        self.flight_log = flight_log
        self.engine_hint = engine_hint
        self._counts: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._profile = _ProfileWindow()
        self._t0 = time.time()
        self._fh = None
        if flight_log:
            d = os.path.dirname(os.path.abspath(flight_log))
            os.makedirs(d, exist_ok=True)
            # Line-buffered append: each record hits the OS on its own
            # write, so a SIGKILL leaves complete lines (the reader
            # tolerates one torn tail line).
            self._fh = open(flight_log, "a", buffering=1)
        self._write({"t": "meta", "started": round(self._t0, 3),
                     "pid": os.getpid(), "hint": engine_hint})

    @classmethod
    def for_checkpoint(cls, checkpoint_path: str, **kw) -> "Telemetry":
        """The run-dir convention: flight log beside the dump
        (tpu/checkpoint.py ``default_flight_log``)."""
        from dslabs_tpu.tpu import checkpoint as ckpt_mod

        kw.setdefault("flight_log",
                      ckpt_mod.default_flight_log(checkpoint_path))
        return cls(**kw)

    # ----------------------------------------------------------- plumbing

    def _ts(self) -> float:
        return round(time.time() - self._t0, 4)

    def _write(self, rec: dict) -> None:
        if self._fh is None:
            return
        try:
            self._fh.write(json.dumps(rec) + "\n")
        except (OSError, ValueError):
            self._fh = None           # disk gone / closed: record in RAM only

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None

    def attach(self, search):
        """Route ``search``'s dispatches through this recorder (the
        engine's ``_dispatch`` checks ``_telemetry``).  Returns the
        search for chaining."""
        search._telemetry = self
        return search

    # ----------------------------------------------------------- dispatch

    def record_dispatch(self, search, tag: str, hook, fn, *args):
        """THE span source: called by ``TensorSearch._dispatch`` for
        every hot-loop device dispatch.  Wraps the existing hook chain
        (supervisor boundary included) — never an extra device call,
        never a readback; everything recorded is a host scalar the
        dispatch already produced."""
        engine, _, site = tag.partition(".")
        with self._lock:
            idx = self._counts.get(engine, 0)
            self._counts[engine] = idx + 1
        depth = int(getattr(search, "_current_depth", 0) or 0)
        boundary = getattr(search, "_dispatch_boundary", None)
        r0 = boundary.retries if boundary is not None else 0
        scales = getattr(search, "_dispatch_deadline_scales", None) or {}
        scale = float(scales.get(site, 1.0))
        start = {"t": "dispatch", "ts": self._ts(), "tag": tag,
                 "i": idx, "depth": depth}
        with self._lock:
            self._write(start)
        self._profile.on_start(site)
        t0 = time.time()
        outcome = "ok"
        try:
            if hook is None:
                return fn(*args)
            return hook(tag, fn, *args)
        except BaseException as e:  # noqa: BLE001 — recorded, re-raised
            outcome = type(e).__name__
            raise
        finally:
            wall = time.time() - t0
            self._profile.on_done(site)
            retries = ((boundary.retries - r0)
                       if boundary is not None else 0)
            span = {"t": "span", "ts": self._ts(), "tag": tag,
                    "engine": engine, "site": site, "i": idx,
                    "depth": depth, "wall": round(wall, 6),
                    "retries": retries, "scale": scale,
                    "outcome": outcome}
            with self._lock:
                self.ring.append(span)
                self._write(span)
                self.registry.counter(f"dispatches.{engine}").inc()
                self.registry.histogram(f"dispatch_secs.{tag}").observe(
                    wall)
                if retries:
                    self.registry.counter("retries").inc(retries)
                if outcome != "ok":
                    self.registry.counter(
                        f"dispatch_errors.{outcome}").inc()

    @contextlib.contextmanager
    def span(self, tag: str, **fields):
        """Manual span for host-side work that is not a device dispatch
        (bench preflight, the profiling tools' timed blocks).  Same
        record shape, same registry feeds."""
        engine, _, site = tag.partition(".")
        with self._lock:
            idx = self._counts.get(engine, 0)
            self._counts[engine] = idx + 1
            self._write({"t": "dispatch", "ts": self._ts(), "tag": tag,
                         "i": idx, "depth": 0})
        t0 = time.time()
        outcome = "ok"
        try:
            yield self
        except BaseException as e:  # noqa: BLE001 — recorded, re-raised
            outcome = type(e).__name__
            raise
        finally:
            wall = time.time() - t0
            span = {"t": "span", "ts": self._ts(), "tag": tag,
                    "engine": engine, "site": site, "i": idx,
                    "depth": 0, "wall": round(wall, 6), "retries": 0,
                    "scale": 1.0, "outcome": outcome, **fields}
            with self._lock:
                self.ring.append(span)
                self._write(span)
                self.registry.counter(f"dispatches.{engine}").inc()
                self.registry.histogram(f"dispatch_secs.{tag}").observe(
                    wall)

    # -------------------------------------------------------- other feeds

    def event(self, kind: str, **fields) -> None:
        """Recovery/operational event (supervisor retry/failover/rung,
        warden heartbeat/child_death, spill evict/reinject, …)."""
        rec = {"t": "event", "ts": self._ts(), "kind": kind, **fields}
        with self._lock:
            self.events.append(rec)
            self._write(rec)
            self.registry.counter(f"events.{kind}").inc()

    def on_level(self, engine: str, record: dict) -> None:
        """One completed BFS level / wave / swarm round, described by
        the host scalars of the fused stats readback the engine already
        paid for (depth, wall, explored, unique, next_frontier, …)."""
        rec = {"t": "level", "ts": self._ts(), "engine": engine,
               **record}
        with self._lock:
            self.levels.append(rec)
            self._write(rec)
            self.registry.counter(f"levels.{engine}").inc()
            self.registry.gauge(f"depth.{engine}").set(
                record.get("depth", 0))
            self.registry.gauge(f"explored.{engine}").set(
                record.get("explored", 0))
            self.registry.gauge(f"unique.{engine}").set(
                record.get("unique", 0))
            if record.get("wall") is not None:
                self.registry.histogram(f"level_secs.{engine}").observe(
                    float(record["wall"]))
            if record.get("load_factor") is not None:
                self.registry.gauge(f"load_factor.{engine}").set(
                    record["load_factor"])

    # Outcome scalars worth a gauge + the outcome record (all plain
    # host ints the verdict already carries).
    _OUTCOME_FIELDS = (
        "states_explored", "unique_states", "depth", "retries",
        "failovers", "resumed_from_depth", "visited_overflow",
        "dropped", "spilled_keys", "host_tier_hits",
        "respilled_frontier", "walker_restarts", "swarm_overflow",
        "child_restarts", "killed_dispatches", "abandoned_threads")

    def on_outcome(self, out, engine: Optional[str] = None) -> None:
        """Ingest a SearchOutcome's accounting: one ``outcome`` record
        plus gauges for every counter (spill, overflow, recovery)."""
        eng = engine or getattr(out, "engine", None) or "search"
        rec = {"t": "outcome", "ts": self._ts(), "engine": eng,
               "end_condition": out.end_condition,
               "elapsed_secs": round(float(out.elapsed_secs), 4),
               "compile_secs": round(float(out.compile_secs), 4)}
        with self._lock:
            for f in self._OUTCOME_FIELDS:
                v = int(getattr(out, f, 0) or 0)
                rec[f] = v
                if v:
                    self.registry.gauge(f"outcome.{f}").set(v)
            self.registry.gauge("outcome.compile_secs").set(
                rec["compile_secs"])
            self._write(rec)
            self.events.append(rec)

    # ------------------------------------------------------------ summary

    def summary(self) -> dict:
        """The compact JSON block bench phases attach to their output:
        span totals, per-site latency snapshots, event counts, and the
        flight-log path for the deep dive."""
        with self._lock:
            sites = {name[len("dispatch_secs."):]: h.snapshot()
                     for name, h in
                     self.registry.histograms.items()
                     if name.startswith("dispatch_secs.")}
            events = {name[len("events."):]: c.value
                      for name, c in self.registry.counters.items()
                      if name.startswith("events.")}
            return {
                "spans": sum(self._counts.values()),
                "dispatches": dict(self._counts),
                "sites": sites,
                "events": events,
                "levels": len(self.levels),
                "flight_log": self.flight_log,
            }


# ------------------------------------------------------- flight reading

def read_flight(path: str) -> List[dict]:
    """Parse a flight-recorder JSONL file, tolerating ONE torn tail
    line (the signature of a SIGKILL mid-write).  A torn line anywhere
    else raises — the file is corrupt, not merely truncated."""
    records: List[dict] = []
    with open(path) as f:
        lines = f.read().splitlines()
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            records.append(json.loads(line))
        except ValueError:
            if i == len(lines) - 1:
                break                     # torn tail: expected crash shape
            raise
    return records


def tail_records(path: Optional[str], n: int = 6,
                 kinds=("dispatch", "span", "event")) -> List[dict]:
    """The last ``n`` span/dispatch/event records of a flight log —
    the wedge-diagnostics payload bench.py attaches to a phase error.
    Never raises: diagnostics must not mask the error they describe."""
    if not path:
        return []
    try:
        recs = [r for r in read_flight(path) if r.get("t") in kinds]
    except Exception:
        return []
    return recs[-n:]


# --------------------------------------------------------------- report

def _resolve_flight(path: str) -> str:
    """Accept a flight log OR a run directory (the checkpoint's dir):
    a directory resolves to its ``flight.jsonl`` or the newest
    ``*.flight.jsonl`` inside it."""
    if os.path.isdir(path):
        cand = os.path.join(path, "flight.jsonl")
        if os.path.exists(cand):
            return cand
        logs = sorted(
            (os.path.join(path, f) for f in os.listdir(path)
             if f.endswith(".flight.jsonl") or f.endswith(".jsonl")),
            key=lambda p: os.path.getmtime(p))
        if logs:
            return logs[-1]
        raise FileNotFoundError(f"no flight log (*.jsonl) in {path}")
    return path


def build_report(records: List[dict]) -> dict:
    """Aggregate a flight log's records into the run-report structure
    (everything the renderer needs, derived from the log alone)."""
    spans = [r for r in records if r.get("t") == "span"]
    levels = [r for r in records if r.get("t") == "level"]
    events = [r for r in records if r.get("t") == "event"]
    outcomes = [r for r in records if r.get("t") == "outcome"]
    meta = next((r for r in records if r.get("t") == "meta"), None)

    sites: Dict[str, Histogram] = {}
    first_wall: Dict[str, float] = {}
    for s in spans:
        h = sites.setdefault(s["tag"], Histogram())
        h.observe(s.get("wall", 0.0))
        first_wall.setdefault(s["tag"], float(s.get("wall", 0.0)))
    total_wall = sum(float(s.get("wall", 0.0)) for s in spans)
    compile_wall = sum(first_wall.values())

    # Per-level throughput series: explored is cumulative, so the rate
    # uses the delta against the previous record of the same engine.
    series: Dict[str, List[dict]] = {}
    prev: Dict[str, int] = {}
    for lv in levels:
        eng = lv.get("engine", "?")
        d = int(lv.get("explored", 0)) - prev.get(eng, 0)
        prev[eng] = int(lv.get("explored", 0))
        wall = float(lv.get("wall", 0.0)) or 1e-9
        series.setdefault(eng, []).append(dict(lv, delta_explored=d,
                                               rate=round(d / wall, 1)))

    # Recovery timeline: events plus retry-absorbing spans, time-sorted.
    timeline = sorted(
        (events
         + [s for s in spans if s.get("retries")]
         + [s for s in spans if s.get("outcome") not in (None, "ok")]),
        key=lambda r: r.get("ts", 0.0))

    # In-flight dispatch: a begin marker with no matching span means
    # the process died (or is wedged) inside that device call.
    open_dispatch = None
    done = {(s["tag"], s["i"]) for s in spans}
    for r in records:
        if r.get("t") == "dispatch" and (r["tag"], r["i"]) not in done:
            open_dispatch = r
    counts = {}
    for o in outcomes:
        for k in ("spilled_keys", "host_tier_hits", "respilled_frontier",
                  "visited_overflow", "dropped", "retries", "failovers",
                  "walker_restarts", "swarm_overflow"):
            if o.get(k):
                counts[k] = counts.get(k, 0) + int(o[k])
    return {"meta": meta, "n_spans": len(spans),
            "sites": {t: h.snapshot() for t, h in sites.items()},
            "series": series, "timeline": timeline,
            "outcomes": outcomes, "counts": counts,
            "total_wall": round(total_wall, 3),
            "compile_wall": round(compile_wall, 3),
            "in_flight": open_dispatch}


def render_report(report: dict, source: str = "") -> str:
    """The human-readable run report (pinned sections: the golden test
    asserts these headers — keep them stable)."""
    out: List[str] = []
    out.append(f"== dslabs run report: {source or 'flight log'} ==")
    meta = report.get("meta") or {}
    if meta:
        out.append(f"meta: pid {meta.get('pid')} "
                   f"hint={meta.get('hint')}")
    out.append(
        f"spans: {report['n_spans']} dispatches across "
        f"{len(report['sites'])} sites; device wall "
        f"{report['total_wall']:.3f}s "
        f"(first-dispatch/compile {report['compile_wall']:.3f}s, "
        f"steady {report['total_wall'] - report['compile_wall']:.3f}s)")

    out.append("")
    out.append("-- dispatch latency by site --")
    out.append(f"{'site':34s} {'n':>6s} {'p50ms':>9s} {'p90ms':>9s} "
               f"{'p99ms':>9s} {'maxms':>9s} {'total_s':>9s}")
    for tag in sorted(report["sites"]):
        s = report["sites"][tag]
        out.append(f"{tag:34s} {s['count']:6d} {s['p50']*1e3:9.2f} "
                   f"{s['p90']*1e3:9.2f} {s['p99']*1e3:9.2f} "
                   f"{s['max']*1e3:9.2f} {s['total']:9.3f}")

    out.append("")
    out.append("-- per-level throughput --")
    if not report["series"]:
        out.append("(no level records)")
    for eng in sorted(report["series"]):
        out.append(f"[engine {eng}]")
        out.append(f"{'depth':>6s} {'wall_s':>8s} {'explored':>10s} "
                   f"{'unique':>10s} {'next':>10s} {'states/s':>10s}")
        for lv in report["series"][eng]:
            out.append(
                f"{lv.get('depth', 0):6d} {lv.get('wall', 0.0):8.3f} "
                f"{lv.get('explored', 0):10d} "
                f"{lv.get('unique', 0):10d} "
                f"{lv.get('next_frontier', 0):10d} "
                f"{lv.get('rate', 0.0):10.1f}")

    out.append("")
    out.append("-- recovery timeline --")
    if not report["timeline"]:
        out.append("(no retries, failovers, or events)")
    for r in report["timeline"][-40:]:
        if r.get("t") == "event":
            extra = {k: v for k, v in r.items()
                     if k not in ("t", "ts", "kind")}
            out.append(f"+{r.get('ts', 0.0):8.2f}s event "
                       f"{r['kind']} {extra}")
        else:
            out.append(f"+{r.get('ts', 0.0):8.2f}s span {r['tag']} "
                       f"i={r['i']} retries={r.get('retries', 0)} "
                       f"outcome={r.get('outcome')}")

    out.append("")
    out.append("-- spill / overflow / recovery counts --")
    if report["counts"]:
        out.append(" ".join(f"{k}={v}"
                            for k, v in sorted(report["counts"].items())))
    else:
        out.append("(all zero)")
    for o in report["outcomes"]:
        out.append(
            f"outcome: {o.get('end_condition')} engine="
            f"{o.get('engine')} depth={o.get('depth')} "
            f"unique={o.get('unique_states')} "
            f"explored={o.get('states_explored')} "
            f"elapsed={o.get('elapsed_secs')}s "
            f"compile={o.get('compile_secs')}s")

    if report["in_flight"] is not None:
        r = report["in_flight"]
        out.append("")
        out.append(f"!! in-flight at EOF: {r['tag']} i={r['i']} "
                   f"depth={r.get('depth')} — the run died or wedged "
                   "inside this dispatch")
    return "\n".join(out)


def render_sites(summary: dict) -> str:
    """The per-site latency table of a :meth:`Telemetry.summary` —
    the shared renderer the profiling tools (tools/profile_*.py) print
    instead of hand-rolled timing scaffolds.  Columns match the report
    CLI's dispatch-latency section."""
    out = [f"{'site':40s} {'n':>6s} {'p50ms':>9s} {'p90ms':>9s} "
           f"{'maxms':>9s} {'total_s':>9s}"]
    for tag in sorted(summary.get("sites", {})):
        s = summary["sites"][tag]
        out.append(f"{tag:40s} {s['count']:6d} {s['p50']*1e3:9.2f} "
                   f"{s['p90']*1e3:9.2f} {s['max']*1e3:9.2f} "
                   f"{s['total']:9.3f}")
    return "\n".join(out)


def main(argv: Optional[List[str]] = None) -> int:
    import sys

    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] != "report" or len(argv) < 2:
        print("usage: python -m dslabs_tpu.tpu.telemetry report "
              "<run-dir-or-flight-log>", file=sys.stderr)
        return 2
    path = _resolve_flight(argv[1])
    report = build_report(read_flight(path))
    print(render_report(report, source=path))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
