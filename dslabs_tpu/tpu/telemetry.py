"""Unified telemetry: dispatch-span flight recorder, metrics, reports.

Before this module every subsystem emitted its own ad-hoc signals —
SearchOutcome counters, warden heartbeat lines, bench JSON fragments,
``DSLABS_LEVEL_TIMING`` records — and a wedged run left almost nothing
behind (BENCH_r05 died in preflight with one scraped stderr line to
explain a 300-second hang).  This is the one observability substrate
they all feed, built on the paper's discipline that **every signal must
come from scalar readbacks already paid for**: the recorder never adds
a device dispatch and never reads anything off the device beyond the
fused stats vector the engines already sync (enforced by the
overhead-guard test in tests/test_telemetry.py).

Pieces:

* **Dispatch spans.**  :meth:`Telemetry.attach` hooks the existing
  ``TensorSearch._dispatch`` seam — the one choke point every hot-loop
  device dispatch already funnels through (tpu/supervisor.py).  Each
  dispatch becomes a structured span (engine, site, per-engine index,
  live BFS depth, wall seconds, retries absorbed by the supervisor
  boundary, watchdog deadline-scale, outcome) appended to a bounded
  in-memory ring and — when a ``flight_log`` is configured — streamed
  as JSONL to the **flight-recorder file** beside the checkpoint
  (tpu/checkpoint.py ``default_flight_log``).  The file is opened
  line-buffered append-only and every dispatch writes a begin marker
  BEFORE the device call, so a SIGKILL'd or wedged run leaves a
  readable trail whose torn tail names the in-flight dispatch —
  exactly what the BENCH_r05 shape lacked.

* **Metrics registry.**  Counters / gauges / histograms fed from the
  host scalars the run already holds: per-level fused-stats records
  (all three engines + the swarm's rounds), spill/overflow counters,
  supervisor retry/failover/rung events, and warden heartbeats
  re-emitted from the child→parent JSON protocol.  ``summary()`` is
  the JSON block bench phases attach to their output.

* **Profiler windows.**  ``DSLABS_PROFILE=<dir>`` wraps the first
  ``DSLABS_PROFILE_STEPS`` post-warmup hot-loop dispatches (the first
  dispatch at each site pays the XLA compile and is skipped) in
  ``jax.profiler.trace`` — an opt-in deep dive that rides the same
  seam, zero cost when the knob is unset.

* **Run reports.**  ``python -m dslabs_tpu.tpu.telemetry report
  <run-dir-or-flight-log>`` renders the flight log alone into per-level
  throughput series, per-site dispatch-latency percentiles, the
  retry/failover/heartbeat timeline, spill and overflow counts, the
  compile-vs-search wall split, and the in-flight dispatch of a torn
  tail.  ``report --json`` emits the same structure machine-readable
  (one schema shared with the grading scripts and the ledger compare
  path; pinned by test).  docs/observability.md documents the span
  model and the "diagnosing a wedge" recipe rides it
  (docs/resilience.md).

* **Per-device skew (mesh scope).**  The sharded / swarm engines keep
  their pre-``psum`` per-device scalars in the SAME fused stats
  readback (frontier occupancy, visited-table load, states expanded,
  capacity drops — see sharded.py ``stats_local``), so per-level
  records carry ``per_device`` lanes and :func:`skew_metrics`
  (max/mean imbalance + coefficient of variation) at zero added
  transfers.  ``on_level`` feeds them to the registry and warns past
  ``DSLABS_SKEW_WARN``; the report CLI renders a per-device ×
  per-level heatmap.  These are the numbers the owner-hashed
  ``all_to_all`` design (ROADMAP #1) is decided on.

* **Live run monitor.**  A recorder with a run dir atomically rewrites
  ``STATUS.json`` (depth, rate, skew, spill tier, last span, current
  rung/lane, in-flight dispatch) at level/event boundaries —
  ``python -m dslabs_tpu.tpu.telemetry watch <run-dir>`` tails it plus
  the flight log to render a live terminal view of ANY run, including
  a warden child or a bench phase in another process, and survives
  the run being SIGKILLed mid-level (atomic replace = never torn;
  the flight tail names the in-flight dispatch).

* **Cross-run bench ledger.**  bench.py appends each run's last-line
  JSON to ``BENCH_HISTORY.jsonl`` (:func:`append_ledger`);
  ``telemetry compare <ledger>`` diffs the latest run against the
  best prior run per phase and flags regressions past
  ``DSLABS_BENCH_REGRESS_PCT`` — the BENCH_r0N trajectory as a
  queryable artifact instead of loose files.

Thread-safe (the portfolio runs two lanes against one recorder); pure
host-side Python + stdlib — importing this module never imports jax.
"""

from __future__ import annotations

import contextlib
import json
import math
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

__all__ = ["Telemetry", "MetricsRegistry", "Counter", "Gauge",
           "Histogram", "read_flight", "tail_records", "build_report",
           "render_report", "render_sites", "skew_metrics",
           "device_memory_stats", "default_status_path", "load_status",
           "render_watch", "watch_frame", "append_ledger",
           "read_ledger", "compare_ledger", "render_compare",
           "DISPATCH_SITES", "main"]

# THE canonical dispatch-site registry (ISSUE 10): every tag the
# engines route through ``TensorSearch._dispatch``, with the static
# contract each site's lowered program is audited against by the
# soundness sanitizer (dslabs_tpu/analysis/jaxpr_audit.py — the same
# enumeration feeds the profiler-site selection below and the
# sanitizer's coverage check, so a new dispatch site that skips this
# table is a loud J0 finding, not silent audit rot).
#
#   hot      — steady-state dispatches worth a profiler capture
#   donated  — the program's carry is declared jit(donate_argnums=0);
#              the auditor verifies the lowering kept the aliasing
#   multi    — cross-device collectives are EXPECTED (mesh programs);
#              False means any collective is a J4 finding
#   program  — the tag dispatches a lowered device program (False =
#              a bare readback / host helper; nothing to audit)
DISPATCH_SITES = {
    "device.init":           dict(hot=False, donated=False, multi=False,
                                  program=True),
    "device.step":           dict(hot=True, donated=True, multi=False,
                                  program=True),
    "device.promote":        dict(hot=False, donated=True, multi=False,
                                  program=True),
    "device.sync":           dict(hot=False, donated=False, multi=False,
                                  program=False),
    "device.flags":          dict(hot=False, donated=False, multi=False,
                                  program=False),
    "device.spill_drain":    dict(hot=False, donated=True, multi=False,
                                  program=True),
    "device.spill_evict":    dict(hot=False, donated=True, multi=False,
                                  program=True),
    "device.spill_reinject": dict(hot=False, donated=True, multi=False,
                                  program=False),
    "sharded.superstep":     dict(hot=True, donated=True, multi=True,
                                  program=True),
    "sharded.step":          dict(hot=True, donated=True, multi=True,
                                  program=True),
    "sharded.promote":       dict(hot=False, donated=True, multi=True,
                                  program=True),
    "sharded.init":          dict(hot=False, donated=False, multi=True,
                                  program=True),
    "sharded.sync":          dict(hot=False, donated=False, multi=False,
                                  program=True),
    "sharded.spill_drain":   dict(hot=False, donated=True, multi=True,
                                  program=True),
    "sharded.spill_evict":   dict(hot=False, donated=True, multi=True,
                                  program=True),
    "sharded.spill_reinject": dict(hot=False, donated=True, multi=True,
                                   program=False),
    # Boundary work stealing (ISSUE 18 leg (c)): one extra all_to_all
    # at a level boundary moving packed frontier rows per a host-built
    # donation plan — dispatched only when the skew gate trips (or at
    # the depth-1 root fanout), never in the per-chunk hot loop.
    "sharded.steal":         dict(hot=False, donated=True, multi=True,
                                  program=True),
    "swarm.round":           dict(hot=True, donated=True, multi=True,
                                  program=True),
    "swarm.init":            dict(hot=False, donated=False, multi=True,
                                  program=False),
    "swarm.flags":           dict(hot=False, donated=False, multi=True,
                                  program=False),
    "host.expand":           dict(hot=True, donated=False, multi=False,
                                  program=False),
    # The visited-table bucket-probe kernel (ISSUE 12): Pallas on TPU
    # (interpret mode off-TPU), jnp oracle otherwise — inlined into
    # every expanding dispatch, and audited/profiled standalone
    # through this site (visited.dispatch_site_program).
    "visited.insert":        dict(hot=True, donated=True, multi=False,
                                  program=True),
    # Batched job lanes (ISSUE 14, tpu/lanes.py): the lane superstep
    # is THE multi-tenant hot path — one dispatch per level advances
    # every resident lane — with the masked promote, the one-hot
    # swap-in/restore splices, and the vmapped root initializer
    # around it.  All single-device programs (J4 applies); the
    # superstep/promote/inject carries are donated (J3 applies).
    "lanes.init":            dict(hot=False, donated=False, multi=False,
                                  program=True),
    "lanes.superstep":       dict(hot=True, donated=True, multi=False,
                                  program=True),
    "lanes.promote":         dict(hot=False, donated=True, multi=False,
                                  program=True),
    "lanes.inject":          dict(hot=False, donated=True, multi=False,
                                  program=True),
    "lanes.restore":         dict(hot=False, donated=True, multi=False,
                                  program=True),
    "lanes.sync":            dict(hot=False, donated=False, multi=False,
                                  program=False),
    "lanes.flags":           dict(hot=False, donated=False, multi=False,
                                  program=False),
    # Capacity round 2 (ISSUE 15): the bit-packed frontier codec
    # (tpu/packing.py) and the symmetry canonicalize pass
    # (tpu/symmetry.py) are FUSED into device.step / host.expand — no
    # standalone dispatch in the hot loop — but each registers a
    # canonical standalone program (like visited.insert) so the jaxpr
    # auditor (J0-J5) and profiler cover the codec lowerings
    # themselves.  Registered only by engines whose descriptor is
    # non-identity / whose reduction is on.
    "packing.pack":          dict(hot=False, donated=False, multi=False,
                                  program=True),
    "packing.unpack":        dict(hot=False, donated=False, multi=False,
                                  program=True),
    "symmetry.canonicalize": dict(hot=False, donated=False, multi=False,
                                  program=True),
}

# Hot-loop sites whose steady-state dispatches are worth a profiler
# capture (the compile-paying first dispatch at a site is skipped) —
# derived from the registry so the two views cannot drift.
_PROFILE_SITES = tuple(sorted({t.split(".", 1)[1]
                               for t, m in DISPATCH_SITES.items()
                               if m["hot"]}))


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def skew_metrics(values) -> dict:
    """Shard-skew summary of one per-device lane: the slowest-device
    ratio (``imbalance`` = max/mean — 1.0 is a perfectly balanced
    mesh, D is one device doing all the work) and the coefficient of
    variation.  Pure host math over scalars the level sync already
    read; shared by the engines (per-level records), ``on_level``
    (registry + warning), and the report heatmap."""
    vals = [float(v) for v in values]
    n = len(vals)
    if not n:
        return {"max": 0, "mean": 0.0, "imbalance": 1.0, "cv": 0.0}
    mean = sum(vals) / n
    mx = max(vals)
    if mean <= 0:
        return {"max": mx, "mean": round(mean, 3),
                "imbalance": 1.0, "cv": 0.0}
    var = sum((v - mean) ** 2 for v in vals) / n
    return {"max": mx, "mean": round(mean, 3),
            "imbalance": round(mx / mean, 4),
            "cv": round(math.sqrt(var) / mean, 4)}


def device_memory_stats(devices) -> Optional[List[int]]:
    """Per-device HBM high-water (``peak_bytes_in_use``), polled
    host-side via the runtime's memory stats — never a device
    dispatch.  ``None`` when the backend does not report (CPU meshes):
    callers simply omit the lane."""
    out = []
    for d in devices:
        try:
            ms = d.memory_stats() or {}
        except Exception:  # noqa: BLE001 — absence of stats is normal
            return None
        out.append(int(ms.get("peak_bytes_in_use",
                              ms.get("bytes_in_use", 0))))
    return out if any(out) else None


def default_status_path(flight_log: Optional[str]) -> Optional[str]:
    """The live-monitor file that pairs with a flight log: the run-dir
    convention is ``STATUS.json`` beside ``flight.jsonl``
    (checkpoint.run_dir_layout); a named phase log
    (``<phase>.flight.jsonl``, the bench layout) gets
    ``<phase>.STATUS.json`` so concurrent phases in one dir never
    clobber each other."""
    if not flight_log:
        return None
    d = os.path.dirname(os.path.abspath(flight_log))
    base = os.path.basename(flight_log)
    if base == "flight.jsonl":
        return os.path.join(d, "STATUS.json")
    for suffix in (".flight.jsonl", ".jsonl"):
        if base.endswith(suffix):
            return os.path.join(d, base[:-len(suffix)] + ".STATUS.json")
    return os.path.join(d, base + ".STATUS.json")


# ------------------------------------------------------------- registry

class Counter:
    """Monotonic count (events, dispatches, retries)."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0

    def inc(self, v: int = 1) -> None:
        self.value += v


class Gauge:
    """Last-written scalar (depth, table load, outcome counters)."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0

    def set(self, v) -> None:
        self.value = v


class Histogram:
    """Bounded sample store with percentile readout (span latencies).
    Keeps the most recent ``cap`` observations — a run report wants
    the distribution, not an unbounded host array."""

    __slots__ = ("values", "count", "total", "cap")

    def __init__(self, cap: int = 4096):
        self.values: deque = deque(maxlen=cap)
        self.count = 0
        self.total = 0.0
        self.cap = cap

    def observe(self, v: float) -> None:
        self.values.append(float(v))
        self.count += 1
        self.total += float(v)

    def percentile(self, q: float) -> float:
        if not self.values:
            return 0.0
        vs = sorted(self.values)
        i = min(len(vs) - 1, max(0, int(round(q * (len(vs) - 1)))))
        return vs[i]

    def snapshot(self) -> dict:
        return {"count": self.count,
                "total": round(self.total, 6),
                "p50": round(self.percentile(0.50), 6),
                "p90": round(self.percentile(0.90), 6),
                "p99": round(self.percentile(0.99), 6),
                "max": round(max(self.values, default=0.0), 6)}


class MetricsRegistry:
    """Create-on-touch named metrics; ``snapshot()`` is plain JSON."""

    def __init__(self):
        self.counters: Dict[str, Counter] = {}
        self.gauges: Dict[str, Gauge] = {}
        self.histograms: Dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        c = self.counters.get(name)
        if c is None:
            c = self.counters[name] = Counter()
        return c

    def gauge(self, name: str) -> Gauge:
        g = self.gauges.get(name)
        if g is None:
            g = self.gauges[name] = Gauge()
        return g

    def histogram(self, name: str) -> Histogram:
        h = self.histograms.get(name)
        if h is None:
            h = self.histograms[name] = Histogram()
        return h

    def snapshot(self) -> dict:
        return {
            "counters": {k: c.value for k, c in self.counters.items()},
            "gauges": {k: g.value for k, g in self.gauges.items()},
            "histograms": {k: h.snapshot()
                           for k, h in self.histograms.items()},
        }


# ------------------------------------------------------ profiler window

class _ProfileWindow:
    """Opt-in ``jax.profiler.trace`` capture of the first K post-warmup
    hot-loop dispatches (DSLABS_PROFILE=<dir>, DSLABS_PROFILE_STEPS).
    The first dispatch at each site pays the XLA compile and is never
    captured (a compile trace drowns the steady-state picture).  All
    failures degrade to "window off" — profiling must never take a
    search down."""

    def __init__(self):
        self.dir = os.environ.get("DSLABS_PROFILE") or None
        try:
            self.steps = int(os.environ.get("DSLABS_PROFILE_STEPS",
                                            "4"))
        except ValueError:
            self.steps = 4
        self.active = False
        self.done = self.dir is None
        self._left = 0
        self._seen: Dict[str, int] = {}

    def on_start(self, site: str) -> None:
        if self.done or self.active or site not in _PROFILE_SITES:
            return
        n = self._seen.get(site, 0)
        self._seen[site] = n + 1
        if n == 0:
            return                     # compile-paying warm-up dispatch
        try:
            import jax

            jax.profiler.start_trace(self.dir)
            self.active = True
            self._left = self.steps
        except Exception:
            self.done = True

    def on_done(self, site: str) -> None:
        if not self.active or site not in _PROFILE_SITES:
            return
        self._left -= 1
        if self._left <= 0:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception:
                pass
            self.active = False
            self.done = True


# ------------------------------------------------------------- recorder

class Telemetry:
    """The per-run recorder.  ``attach(search)`` routes the search's
    ``_dispatch`` seam through :meth:`record_dispatch`; engines feed
    per-level fused-stats records via :meth:`on_level` and final
    outcomes via :meth:`on_outcome`; the supervisor/warden feed
    recovery events via :meth:`event`.  Everything lands in the ring
    buffer, the metrics registry, and (when configured) the JSONL
    flight-recorder file."""

    def __init__(self, flight_log: Optional[str] = None,
                 ring: Optional[int] = None,
                 engine_hint: Optional[str] = None,
                 status_path: Optional[str] = None,
                 trace_id: Optional[str] = None,
                 parent_span: Optional[str] = None):
        # Causal-trace context (ISSUE 13, tpu/tracing.py): inherited
        # from env when not given explicitly — the service sets
        # DSLABS_TRACE_ID/DSLABS_PARENT_SPAN on every warden launch and
        # the warden forwards them to its children, so a child's
        # recorder stamps the whole flight log into the submit's causal
        # tree without any new plumbing at the engines.
        from dslabs_tpu.tpu import tracing as tracing_mod

        env_trace, env_parent = tracing_mod.current_trace()
        self.trace_id = trace_id or env_trace
        self.parent_span = parent_span or env_parent
        self.span_id = tracing_mod.new_span_id()
        if ring is None:
            try:
                ring = int(os.environ.get("DSLABS_TELEMETRY_RING",
                                          "512"))
            except ValueError:
                ring = 512
        self.ring: deque = deque(maxlen=ring)
        self.registry = MetricsRegistry()
        self.levels: List[dict] = []
        self.events: deque = deque(maxlen=512)
        self.flight_log = flight_log
        self.engine_hint = engine_hint
        self._counts: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._profile = _ProfileWindow()
        self._t0 = time.time()
        self._fh = None
        self.flight_error: Optional[str] = None
        # Live-monitor state (STATUS.json): the last level/event/outcome
        # scalars, atomically rewritten so ``telemetry watch`` in any
        # other process can render this run.  Derived from the flight
        # log's location unless given explicitly; None = monitor off.
        self.status_path = (status_path
                            or default_status_path(flight_log))
        self._status_secs = _env_float("DSLABS_STATUS_SECS", 1.0)
        self._status_last = 0.0
        self._status: Dict[str, object] = {}
        self._prev_explored: Dict[str, int] = {}
        # Rate accounting (ISSUE 13 satellite): the cumulative rate is
        # explored / summed level wall over the WHOLE run; the sliding
        # window keeps the last DSLABS_RATE_WINDOW (explored-delta,
        # wall) pairs so a long run's STATUS shows current speed, not
        # the average over an hour of history.  Per engine — a
        # failover rung restarts its own series.
        try:
            self._rate_window_n = max(1, int(os.environ.get(
                "DSLABS_RATE_WINDOW", "8") or 8))
        except ValueError:
            self._rate_window_n = 8
        self._level_wall: Dict[str, float] = {}
        self._rate_window: Dict[str, deque] = {}
        self._open_dispatch: Optional[dict] = None
        self._warned_skew = False
        if flight_log:
            # Line-buffered append: each record hits the OS on its own
            # write, so a SIGKILL leaves complete lines (the reader
            # tolerates one torn tail line).  An unwritable location
            # (read-only FS — the bench fallback case) degrades to
            # RAM-only recording, never takes the run down.
            try:
                d = os.path.dirname(os.path.abspath(flight_log))
                os.makedirs(d, exist_ok=True)
                self._fh = open(flight_log, "a", buffering=1)
            except OSError as e:
                self.flight_error = f"{type(e).__name__}: {e}"
                self.flight_log = None
                self.status_path = status_path  # only if explicit
        self._write({"t": "meta", "started": round(self._t0, 3),
                     "pid": os.getpid(), "hint": engine_hint,
                     "trace_id": self.trace_id,
                     "parent_span": self.parent_span,
                     "span_id": self.span_id})

    @classmethod
    def for_checkpoint(cls, checkpoint_path: str, **kw) -> "Telemetry":
        """The run-dir convention: flight log beside the dump
        (tpu/checkpoint.py ``default_flight_log``)."""
        from dslabs_tpu.tpu import checkpoint as ckpt_mod

        kw.setdefault("flight_log",
                      ckpt_mod.default_flight_log(checkpoint_path))
        return cls(**kw)

    # ----------------------------------------------------------- plumbing

    def _ts(self) -> float:
        return round(time.time() - self._t0, 4)

    def _write(self, rec: dict) -> None:
        if self._fh is None:
            return
        try:
            self._fh.write(json.dumps(rec) + "\n")
        except (OSError, ValueError):
            self._fh = None           # disk gone / closed: record in RAM only

    def _write_status(self, force: bool = False) -> None:
        """Atomically rewrite STATUS.json (tmp + ``os.replace``, so a
        reader — or a SIGKILL — never sees a torn file).  Called with
        ``self._lock`` held, from the feeds the run already makes:
        level boundaries, recovery events, outcomes, and (throttled by
        ``DSLABS_STATUS_SECS``) dispatch begin markers.  Pure host
        file IO — never a device dispatch or readback; failures
        disable the monitor, never the run."""
        if self.status_path is None:
            return
        now = time.time()
        if not force and now - self._status_last < self._status_secs:
            return
        self._status_last = now
        last_span = self.ring[-1] if self.ring else None
        st = {
            "t": "status", "pid": os.getpid(),
            "hint": self.engine_hint,
            "updated": round(now, 3),
            "uptime": round(now - self._t0, 1),
            "spans": sum(self._counts.values()),
            "levels": len(self.levels),
            "last_span": last_span,
            "in_flight": self._open_dispatch,
            "flight_log": self.flight_log,
            # Live mesh width (ISSUE 9): how many devices the current
            # rung is actually running on — fed by per-device level
            # lanes and mesh_shrunk/rung events, so `telemetry watch`
            # shows a degraded mesh the moment it shrinks.  Always
            # present (schema-pinned); None until the first feed.
            "mesh_width": None,
            # Live skew aggregate (ISSUE 18 satellite): running
            # imbalance_max/mean/cv over the per-level explored lanes
            # — the rebalance health of the CURRENT run, visible in
            # `telemetry watch` instead of only in bench phase JSON.
            # Always present (schema-pinned); None until a sharded
            # level reports per-device lanes.
            "skew_agg": None,
            # Causal-trace identity (ISSUE 13): STATUS.json carries the
            # same trace context as the flight log, so a live monitor
            # frame is linkable to the submit that caused the run.
            # Always present (schema-pinned); None outside a trace.
            "trace_id": self.trace_id,
            "parent_span": self.parent_span,
            "span_id": self.span_id,
            **self._status,
        }
        tmp = self.status_path + ".tmp"
        try:
            with open(tmp, "w") as f:
                f.write(json.dumps(st))
            os.replace(tmp, self.status_path)
        except OSError:
            self.status_path = None

    def close(self) -> None:
        with self._lock:
            self._write_status(force=True)
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None

    def attach(self, search):
        """Route ``search``'s dispatches through this recorder (the
        engine's ``_dispatch`` checks ``_telemetry``).  Returns the
        search for chaining."""
        search._telemetry = self
        return search

    # ----------------------------------------------------------- dispatch

    def record_dispatch(self, search, tag: str, hook, fn, *args):
        """THE span source: called by ``TensorSearch._dispatch`` for
        every hot-loop device dispatch.  Wraps the existing hook chain
        (supervisor boundary included) — never an extra device call,
        never a readback; everything recorded is a host scalar the
        dispatch already produced."""
        engine, _, site = tag.partition(".")
        with self._lock:
            idx = self._counts.get(engine, 0)
            self._counts[engine] = idx + 1
        depth = int(getattr(search, "_current_depth", 0) or 0)
        boundary = getattr(search, "_dispatch_boundary", None)
        r0 = boundary.retries if boundary is not None else 0
        scales = getattr(search, "_dispatch_deadline_scales", None) or {}
        scale = float(scales.get(site, 1.0))
        start = {"t": "dispatch", "ts": self._ts(), "tag": tag,
                 "i": idx, "depth": depth}
        if self.trace_id:
            start["trace"] = self.trace_id
        with self._lock:
            self._write(start)
            self._open_dispatch = start
            self._write_status()
        self._profile.on_start(site)
        t0 = time.time()
        outcome = "ok"
        try:
            if hook is None:
                return fn(*args)
            return hook(tag, fn, *args)
        except BaseException as e:  # noqa: BLE001 — recorded, re-raised
            outcome = type(e).__name__
            raise
        finally:
            wall = time.time() - t0
            self._profile.on_done(site)
            retries = ((boundary.retries - r0)
                       if boundary is not None else 0)
            span = {"t": "span", "ts": self._ts(), "tag": tag,
                    "engine": engine, "site": site, "i": idx,
                    "depth": depth, "wall": round(wall, 6),
                    "retries": retries, "scale": scale,
                    "outcome": outcome}
            if self.trace_id:
                span["trace"] = self.trace_id
            with self._lock:
                self.ring.append(span)
                self._write(span)
                self._open_dispatch = None
                self.registry.counter(f"dispatches.{engine}").inc()
                self.registry.histogram(f"dispatch_secs.{tag}").observe(
                    wall)
                if retries:
                    self.registry.counter("retries").inc(retries)
                if outcome != "ok":
                    self.registry.counter(
                        f"dispatch_errors.{outcome}").inc()

    @contextlib.contextmanager
    def span(self, tag: str, **fields):
        """Manual span for host-side work that is not a device dispatch
        (bench preflight, the profiling tools' timed blocks).  Same
        record shape, same registry feeds."""
        engine, _, site = tag.partition(".")
        with self._lock:
            idx = self._counts.get(engine, 0)
            self._counts[engine] = idx + 1
            start = {"t": "dispatch", "ts": self._ts(), "tag": tag,
                     "i": idx, "depth": 0}
            self._write(start)
            self._open_dispatch = start
            self._write_status()
        t0 = time.time()
        outcome = "ok"
        try:
            yield self
        except BaseException as e:  # noqa: BLE001 — recorded, re-raised
            outcome = type(e).__name__
            raise
        finally:
            wall = time.time() - t0
            span = {"t": "span", "ts": self._ts(), "tag": tag,
                    "engine": engine, "site": site, "i": idx,
                    "depth": 0, "wall": round(wall, 6), "retries": 0,
                    "scale": 1.0, "outcome": outcome, **fields}
            if self.trace_id:
                span["trace"] = self.trace_id
            with self._lock:
                self.ring.append(span)
                self._write(span)
                self._open_dispatch = None
                self.registry.counter(f"dispatches.{engine}").inc()
                self.registry.histogram(f"dispatch_secs.{tag}").observe(
                    wall)

    # -------------------------------------------------------- other feeds

    def event(self, kind: str, **fields) -> None:
        """Recovery/operational event (supervisor retry/failover/rung,
        warden heartbeat/child_death, spill evict/reinject, …)."""
        rec = {"t": "event", "ts": self._ts(), "kind": kind, **fields}
        if self.trace_id:
            rec.setdefault("trace", self.trace_id)
        with self._lock:
            self.events.append(rec)
            self._write(rec)
            self.registry.counter(f"events.{kind}").inc()
            # Live-monitor feeds: the current ladder rung / portfolio
            # lane and the spill tier's size ride STATUS.json so the
            # watch view shows where a run IS, not just how fast.
            if kind in ("rung", "capacity_retry"):
                self._status["rung"] = {k: v for k, v in rec.items()
                                        if k not in ("t", "ts")}
                if fields.get("width"):
                    self._status["mesh_width"] = fields["width"]
                self._write_status(force=True)
            elif kind in ("mesh_shrunk", "knobs_shrunk"):
                # Elastic-ladder degradations (ISSUE 9): the live
                # monitor shows the CURRENT width and the last
                # resilience action, not just that a rung changed.
                self._status["resilience"] = {
                    k: v for k, v in rec.items() if k not in ("t", "ts")}
                if fields.get("to_width"):
                    self._status["mesh_width"] = fields["to_width"]
                self._write_status(force=True)
            elif kind in ("lane", "lane_winner", "failover",
                          "child_death"):
                self._status["lane"] = {k: v for k, v in rec.items()
                                        if k not in ("t", "ts")}
                self._write_status(force=True)
            elif kind.startswith("spill"):
                self._status["spill"] = {k: v for k, v in rec.items()
                                         if k not in ("t", "ts")}
                self._write_status()
            elif kind == "steal":
                # Boundary work-stealing (ISSUE 18c) fires AFTER the
                # level feed, so the running skew aggregate picks the
                # rebalance up here rather than from on_level.
                agg = self._status.get("skew_agg") or {
                    "imbalance_max": 1.0, "imbalance_mean": 0.0,
                    "cv_max": 0.0, "levels": 0}
                agg["steal_events"] = agg.get("steal_events", 0) + 1
                agg["stolen_rows"] = (agg.get("stolen_rows", 0)
                                      + int(fields.get("moved", 0)))
                if fields.get("imbalance_after") is not None:
                    agg["imbalance_post_steal"] = float(
                        fields["imbalance_after"])
                self._status["skew_agg"] = agg
                self._write_status(force=True)
            else:
                self._write_status()

    def on_level(self, engine: str, record: dict) -> None:
        """One completed BFS level / wave / swarm round, described by
        the host scalars of the fused stats readback the engine already
        paid for (depth, wall, explored, unique, next_frontier, …)."""
        rec = {"t": "level", "ts": self._ts(), "engine": engine,
               **record}
        skew = rec.get("skew")
        with self._lock:
            self.levels.append(rec)
            self._write(rec)
            self.registry.counter(f"levels.{engine}").inc()
            self.registry.gauge(f"depth.{engine}").set(
                record.get("depth", 0))
            self.registry.gauge(f"explored.{engine}").set(
                record.get("explored", 0))
            self.registry.gauge(f"unique.{engine}").set(
                record.get("unique", 0))
            if record.get("wall") is not None:
                self.registry.histogram(f"level_secs.{engine}").observe(
                    float(record["wall"]))
            if record.get("load_factor") is not None:
                self.registry.gauge(f"load_factor.{engine}").set(
                    record["load_factor"])
            # Mesh-scope skew feeds (per-device lanes already in the
            # record — the engines read them off the SAME fused stats
            # vector, zero added transfers).
            if skew:
                work = skew.get("explored") or next(iter(skew.values()))
                self.registry.gauge(f"skew.{engine}").set(
                    work.get("imbalance", 1.0))
                self.registry.gauge(f"skew_cv.{engine}").set(
                    work.get("cv", 0.0))
                self.registry.histogram(
                    f"skew_imbalance.{engine}").observe(
                    float(work.get("imbalance", 1.0)))
                # Running skew aggregate (ISSUE 18 satellite): the live
                # monitor's one-glance answer to "is this run
                # imbalanced" — worst and mean per-level imbalance over
                # the explored lanes, plus the worst cv, schema-pinned
                # as STATUS.json's ``skew_agg`` block.
                agg = self._status.get("skew_agg") or {
                    "imbalance_max": 1.0, "imbalance_mean": 0.0,
                    "cv_max": 0.0, "levels": 0}
                n = agg["levels"]
                imb = float(work.get("imbalance", 1.0))
                agg["imbalance_max"] = max(agg["imbalance_max"], imb)
                agg["imbalance_mean"] = round(
                    (agg["imbalance_mean"] * n + imb) / (n + 1), 3)
                agg["cv_max"] = max(
                    agg["cv_max"], round(float(work.get("cv", 0.0)), 3))
                agg["imbalance_max"] = round(agg["imbalance_max"], 3)
                agg["levels"] = n + 1
                self._status["skew_agg"] = agg
            # Live monitor: cumulative rate over the whole run PLUS a
            # sliding-window rate over the last N level records (the
            # satellite fix: one number for billing-grade averages,
            # one for "how fast is it going RIGHT NOW").
            explored = int(record.get("explored", 0) or 0)
            delta = explored - self._prev_explored.get(engine, 0)
            self._prev_explored[engine] = explored
            wall = float(record.get("wall", 0.0) or 0.0)
            wall_total = self._level_wall.get(engine, 0.0) + wall
            self._level_wall[engine] = wall_total
            win = self._rate_window.get(engine)
            if win is None:
                win = self._rate_window[engine] = deque(
                    maxlen=self._rate_window_n)
            win.append((delta, wall))
            win_d = sum(d for d, _ in win)
            win_w = sum(w for _, w in win)
            pd = record.get("per_device") or {}
            if pd.get("explored"):
                # The per-device lanes ARE the live mesh width — a
                # degraded rung's level records carry fewer lanes.
                self._status["mesh_width"] = len(pd["explored"])
            if record.get("lanes") is not None:
                # Batched-child monitor block (ISSUE 14, tpu/lanes.py):
                # per-lane job/depth/explored, schema-pinned so
                # `telemetry watch` renders every resident lane of one
                # lane-batch process.
                self._status["lanes"] = record["lanes"]
            if record.get("spill") is not None:
                # Async-drain wall split (ISSUE 15c): per-level host
                # drain seconds vs blocked seconds — the live monitor
                # shows how much of the spill detour is hidden behind
                # device compute.
                self._status["drain"] = record["spill"]
            if record.get("faults") is not None:
                # Fault-scenario block (ISSUE 19): cumulative fault
                # events by family, schema-pinned so `telemetry watch`
                # shows how much of the run is fault interleavings.
                self._status["faults"] = record["faults"]
                for k, v in record["faults"].items():
                    self.registry.gauge(f"faults.{k}").set(int(v))
            self._status.update({
                "engine": engine,
                "depth": record.get("depth", 0),
                "explored": explored,
                "unique": record.get("unique", 0),
                "rate_per_min": round(explored / wall_total * 60.0, 1)
                if wall_total > 0 else None,
                "rate_per_min_window": round(win_d / win_w * 60.0, 1)
                if win_w > 0 else None,
                "level_wall": wall,
                "load_factor": record.get("load_factor"),
                "skew": skew,
                "per_device": record.get("per_device"),
            })
            self._write_status(force=True)
        if skew:
            work = skew.get("explored") or next(iter(skew.values()))
            warn_at = _env_float("DSLABS_SKEW_WARN", 3.0)
            if (not self._warned_skew
                    and len(record.get("per_device", {})
                            .get("explored", ())) > 1
                    and work.get("mean", 0.0) >= 64
                    and work.get("imbalance", 1.0) >= warn_at):
                self._warned_skew = True
                import warnings

                warnings.warn(
                    f"shard skew: slowest-device imbalance "
                    f"{work['imbalance']:.2f}x (cv {work['cv']:.2f}) "
                    f"at depth {record.get('depth')} on engine "
                    f"{engine} (>= DSLABS_SKEW_WARN={warn_at}) — the "
                    "mesh is load-imbalanced; see the per-device "
                    "heatmap in `telemetry report` and "
                    "docs/observability.md",
                    RuntimeWarning, stacklevel=3)

    # Outcome scalars worth a gauge + the outcome record (all plain
    # host ints the verdict already carries).
    _OUTCOME_FIELDS = (
        "states_explored", "unique_states", "depth", "retries",
        "failovers", "resumed_from_depth", "visited_overflow",
        "dropped", "spilled_keys", "host_tier_hits",
        "respilled_frontier", "walker_restarts", "swarm_overflow",
        "child_restarts", "killed_dispatches", "abandoned_threads",
        "mesh_width", "mesh_shrinks", "knob_retries",
        "fault_events", "partition_events", "crash_events",
        "drop_events", "dup_events")

    def on_outcome(self, out, engine: Optional[str] = None) -> None:
        """Ingest a SearchOutcome's accounting: one ``outcome`` record
        plus gauges for every counter (spill, overflow, recovery) and
        the capacity-round-2 block (bytes_per_state / pack_ratio /
        symmetry_perms — ISSUE 15, schema-pinned in STATUS.json)."""
        eng = engine or getattr(out, "engine", None) or "search"
        rec = {"t": "outcome", "ts": self._ts(), "engine": eng,
               "end_condition": out.end_condition,
               "elapsed_secs": round(float(out.elapsed_secs), 4),
               "compile_secs": round(float(out.compile_secs), 4)}
        trace = getattr(out, "trace_id", None) or self.trace_id
        if trace:
            rec["trace"] = trace
        with self._lock:
            for f in self._OUTCOME_FIELDS:
                v = int(getattr(out, f, 0) or 0)
                rec[f] = v
                if v:
                    self.registry.gauge(f"outcome.{f}").set(v)
            self.registry.gauge("outcome.compile_secs").set(
                rec["compile_secs"])
            bps = getattr(out, "bytes_per_state", None)
            if bps:
                cap_block = {
                    "bytes_per_state": int(bps),
                    "bytes_per_state_unpacked": int(
                        getattr(out, "bytes_per_state_unpacked", 0)
                        or 0),
                    "pack_ratio": float(
                        getattr(out, "pack_ratio", 1.0) or 1.0),
                    "symmetry_perms": int(
                        getattr(out, "symmetry_perms", 0) or 0)}
                rec["capacity"] = cap_block
                self._status["capacity"] = cap_block
                self.registry.gauge("capacity.bytes_per_state").set(
                    cap_block["bytes_per_state"])
                self.registry.gauge("capacity.pack_ratio").set(
                    cap_block["pack_ratio"])
                if cap_block["symmetry_perms"]:
                    self.registry.gauge(
                        "capacity.symmetry_perms").set(
                        cap_block["symmetry_perms"])
            if int(getattr(out, "fault_events", 0) or 0):
                # Fault-scenario block (ISSUE 19): same schema as the
                # engines' per-level ``faults`` record.
                flt_block = {
                    k: int(getattr(out, k, 0) or 0)
                    for k in ("partition_events", "crash_events",
                              "drop_events", "dup_events",
                              "fault_events")}
                rec["faults"] = flt_block
                self._status["faults"] = flt_block
                for k, v in flt_block.items():
                    self.registry.gauge(f"faults.{k}").set(v)
            self._write(rec)
            self.events.append(rec)
            self._status["end_condition"] = out.end_condition
            self._write_status(force=True)

    # ------------------------------------------------------------ summary

    def summary(self) -> dict:
        """The compact JSON block bench phases attach to their output:
        span totals, per-site latency snapshots, event counts, and the
        flight-log path for the deep dive."""
        with self._lock:
            sites = {name[len("dispatch_secs."):]: h.snapshot()
                     for name, h in
                     self.registry.histograms.items()
                     if name.startswith("dispatch_secs.")}
            events = {name[len("events."):]: c.value
                      for name, c in self.registry.counters.items()
                      if name.startswith("events.")}
            out = {
                "spans": sum(self._counts.values()),
                "dispatches": dict(self._counts),
                "sites": sites,
                "events": events,
                "levels": len(self.levels),
                "flight_log": self.flight_log,
            }
            if self.status_path:
                out["status"] = self.status_path
            if self.flight_error:
                out["flight_error"] = self.flight_error
            sk = self._status.get("skew")
            if sk:
                out["skew"] = sk
            return out


# ------------------------------------------------------- flight reading

def read_flight(path: str) -> List[dict]:
    """Parse a flight-recorder JSONL file, tolerating ONE torn tail
    line (the signature of a SIGKILL mid-write).  A torn line anywhere
    else raises — the file is corrupt, not merely truncated."""
    records: List[dict] = []
    with open(path) as f:
        lines = f.read().splitlines()
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            records.append(json.loads(line))
        except ValueError:
            if i == len(lines) - 1:
                break                     # torn tail: expected crash shape
            raise
    return records


def tail_records(path: Optional[str], n: int = 6,
                 kinds=("dispatch", "span", "event")) -> List[dict]:
    """The last ``n`` span/dispatch/event records of a flight log —
    the wedge-diagnostics payload bench.py attaches to a phase error.
    Never raises: diagnostics must not mask the error they describe."""
    if not path:
        return []
    try:
        recs = [r for r in read_flight(path) if r.get("t") in kinds]
    except Exception:
        return []
    return recs[-n:]


# --------------------------------------------------------------- report

def _resolve_flight(path: str) -> str:
    """Accept a flight log OR a run directory (the checkpoint's dir):
    a directory resolves to its ``flight.jsonl`` or the newest
    ``*.flight.jsonl`` inside it."""
    if os.path.isdir(path):
        cand = os.path.join(path, "flight.jsonl")
        if os.path.exists(cand):
            return cand
        logs = sorted(
            (os.path.join(path, f) for f in os.listdir(path)
             if f.endswith(".flight.jsonl") or f.endswith(".jsonl")),
            key=lambda p: os.path.getmtime(p))
        if logs:
            return logs[-1]
        raise FileNotFoundError(f"no flight log (*.jsonl) in {path}")
    return path


def build_report(records: List[dict]) -> dict:
    """Aggregate a flight log's records into the run-report structure
    (everything the renderer needs, derived from the log alone)."""
    spans = [r for r in records if r.get("t") == "span"]
    levels = [r for r in records if r.get("t") == "level"]
    events = [r for r in records if r.get("t") == "event"]
    outcomes = [r for r in records if r.get("t") == "outcome"]
    meta = next((r for r in records if r.get("t") == "meta"), None)

    sites: Dict[str, Histogram] = {}
    first_wall: Dict[str, float] = {}
    for s in spans:
        h = sites.setdefault(s["tag"], Histogram())
        h.observe(s.get("wall", 0.0))
        first_wall.setdefault(s["tag"], float(s.get("wall", 0.0)))
    total_wall = sum(float(s.get("wall", 0.0)) for s in spans)
    compile_wall = sum(first_wall.values())

    # Per-level throughput series: explored is cumulative, so the rate
    # uses the delta against the previous record of the same engine.
    series: Dict[str, List[dict]] = {}
    prev: Dict[str, int] = {}
    for lv in levels:
        eng = lv.get("engine", "?")
        d = int(lv.get("explored", 0)) - prev.get(eng, 0)
        prev[eng] = int(lv.get("explored", 0))
        wall = float(lv.get("wall", 0.0)) or 1e-9
        series.setdefault(eng, []).append(dict(lv, delta_explored=d,
                                               rate=round(d / wall, 1)))

    # Recovery timeline: events plus retry-absorbing spans, time-sorted.
    timeline = sorted(
        (events
         + [s for s in spans if s.get("retries")]
         + [s for s in spans if s.get("outcome") not in (None, "ok")]),
        key=lambda r: r.get("ts", 0.0))

    # In-flight dispatch: a begin marker with no matching span means
    # the process died (or is wedged) inside that device call.
    open_dispatch = None
    done = {(s["tag"], s["i"]) for s in spans}
    for r in records:
        if r.get("t") == "dispatch" and (r["tag"], r["i"]) not in done:
            open_dispatch = r
    counts = {}
    for o in outcomes:
        for k in ("spilled_keys", "host_tier_hits", "respilled_frontier",
                  "visited_overflow", "dropped", "retries", "failovers",
                  "walker_restarts", "swarm_overflow", "mesh_shrinks",
                  "knob_retries"):
            if o.get(k):
                counts[k] = counts.get(k, 0) + int(o[k])
    # Capacity round 2 (ISSUE 15): the last outcome's packing /
    # symmetry block, plus the summed per-level drain-overlap walls.
    capacity = next((o["capacity"] for o in reversed(outcomes)
                     if o.get("capacity")), None)
    # Fault scenarios (ISSUE 19): the last outcome's fault-family block.
    faults = next((o["faults"] for o in reversed(outcomes)
                   if o.get("faults")), None)
    drain = {}
    for lv in levels:
        sp = lv.get("spill")
        if isinstance(sp, dict):
            for k, v in sp.items():
                try:
                    drain[k] = round(drain.get(k, 0.0) + float(v), 4)
                except (TypeError, ValueError):
                    pass
    return {"meta": meta, "n_spans": len(spans),
            "sites": {t: h.snapshot() for t, h in sites.items()},
            "series": series, "timeline": timeline,
            "outcomes": outcomes, "counts": counts,
            "capacity": capacity, "faults": faults,
            "drain": drain or None,
            "total_wall": round(total_wall, 3),
            "compile_wall": round(compile_wall, 3),
            "in_flight": open_dispatch}


def render_report(report: dict, source: str = "") -> str:
    """The human-readable run report (pinned sections: the golden test
    asserts these headers — keep them stable)."""
    out: List[str] = []
    out.append(f"== dslabs run report: {source or 'flight log'} ==")
    meta = report.get("meta") or {}
    if meta:
        out.append(f"meta: pid {meta.get('pid')} "
                   f"hint={meta.get('hint')}")
    out.append(
        f"spans: {report['n_spans']} dispatches across "
        f"{len(report['sites'])} sites; device wall "
        f"{report['total_wall']:.3f}s "
        f"(first-dispatch/compile {report['compile_wall']:.3f}s, "
        f"steady {report['total_wall'] - report['compile_wall']:.3f}s)")

    out.append("")
    out.append("-- dispatch latency by site --")
    out.append(f"{'site':34s} {'n':>6s} {'p50ms':>9s} {'p90ms':>9s} "
               f"{'p99ms':>9s} {'maxms':>9s} {'total_s':>9s}")
    for tag in sorted(report["sites"]):
        s = report["sites"][tag]
        out.append(f"{tag:34s} {s['count']:6d} {s['p50']*1e3:9.2f} "
                   f"{s['p90']*1e3:9.2f} {s['p99']*1e3:9.2f} "
                   f"{s['max']*1e3:9.2f} {s['total']:9.3f}")

    out.append("")
    out.append("-- per-level throughput --")
    if not report["series"]:
        out.append("(no level records)")
    for eng in sorted(report["series"]):
        out.append(f"[engine {eng}]")
        out.append(f"{'depth':>6s} {'wall_s':>8s} {'explored':>10s} "
                   f"{'unique':>10s} {'next':>10s} {'states/s':>10s}")
        for lv in report["series"][eng]:
            out.append(
                f"{lv.get('depth', 0):6d} {lv.get('wall', 0.0):8.3f} "
                f"{lv.get('explored', 0):10d} "
                f"{lv.get('unique', 0):10d} "
                f"{lv.get('next_frontier', 0):10d} "
                f"{lv.get('rate', 0.0):10.1f}")

    # Per-device × per-level heatmap (mesh scope): only rendered when
    # the level records carry per_device lanes (sharded/swarm engines).
    # Rows start with 'd' — the throughput rows above are the only
    # digit-leading rows, which the golden test counts.
    heat_engines = [e for e in sorted(report["series"])
                    if any(lv.get("per_device")
                           for lv in report["series"][e])]
    if heat_engines:
        ramp = " .:-=+*#%@"
        out.append("")
        out.append("-- per-device skew (explored share per level) --")
        for eng in heat_engines:
            lvs = [lv for lv in report["series"][eng]
                   if lv.get("per_device")]
            n_dev = max(len(lv["per_device"].get("explored", ()))
                        for lv in lvs)
            out.append(f"[engine {eng}] devices 0..{n_dev - 1}; "
                       "each cell = device share of the level's "
                       "expanded states")
            for lv in lvs:
                lane = lv["per_device"].get("explored", [])
                mx = max(max(lane, default=0), 1)
                cells = "".join(
                    ramp[min(len(ramp) - 1,
                             int(round(v / mx * (len(ramp) - 1))))]
                    for v in lane)
                sk = (lv.get("skew") or {}).get("explored", {})
                out.append(
                    f"d{lv.get('depth', 0):4d} |{cells}| "
                    f"imb={sk.get('imbalance', 1.0):5.2f} "
                    f"cv={sk.get('cv', 0.0):5.2f}")
            hbms = [lv for lv in lvs if lv.get("hbm_peak")]
            if hbms:
                peak = hbms[-1]["hbm_peak"]
                out.append("hbm peak bytes/device: "
                           + " ".join(f"{b:.2e}" for b in peak))

    out.append("")
    out.append("-- recovery timeline --")
    if not report["timeline"]:
        out.append("(no retries, failovers, or events)")
    for r in report["timeline"][-40:]:
        if r.get("t") == "event":
            extra = {k: v for k, v in r.items()
                     if k not in ("t", "ts", "kind")}
            out.append(f"+{r.get('ts', 0.0):8.2f}s event "
                       f"{r['kind']} {extra}")
        else:
            out.append(f"+{r.get('ts', 0.0):8.2f}s span {r['tag']} "
                       f"i={r['i']} retries={r.get('retries', 0)} "
                       f"outcome={r.get('outcome')}")

    out.append("")
    out.append("-- spill / overflow / recovery counts --")
    if report["counts"]:
        out.append(" ".join(f"{k}={v}"
                            for k, v in sorted(report["counts"].items())))
    else:
        out.append("(all zero)")
    if report.get("capacity"):
        out.append("capacity: " + " ".join(
            f"{k}={v}" for k, v in sorted(report["capacity"].items())))
    if report.get("faults"):
        out.append("faults: " + " ".join(
            f"{k}={v}" for k, v in sorted(report["faults"].items())))
    if report.get("drain"):
        out.append("drain overlap: " + " ".join(
            f"{k}={v}" for k, v in sorted(report["drain"].items())))
    for o in report["outcomes"]:
        out.append(
            f"outcome: {o.get('end_condition')} engine="
            f"{o.get('engine')} depth={o.get('depth')} "
            f"unique={o.get('unique_states')} "
            f"explored={o.get('states_explored')} "
            f"elapsed={o.get('elapsed_secs')}s "
            f"compile={o.get('compile_secs')}s")

    if report["in_flight"] is not None:
        r = report["in_flight"]
        out.append("")
        out.append(f"!! in-flight at EOF: {r['tag']} i={r['i']} "
                   f"depth={r.get('depth')} — the run died or wedged "
                   "inside this dispatch")
    return "\n".join(out)


def render_sites(summary: dict) -> str:
    """The per-site latency table of a :meth:`Telemetry.summary` —
    the shared renderer the profiling tools (tools/profile_*.py) print
    instead of hand-rolled timing scaffolds.  Columns match the report
    CLI's dispatch-latency section."""
    out = [f"{'site':40s} {'n':>6s} {'p50ms':>9s} {'p90ms':>9s} "
           f"{'maxms':>9s} {'total_s':>9s}"]
    for tag in sorted(summary.get("sites", {})):
        s = summary["sites"][tag]
        out.append(f"{tag:40s} {s['count']:6d} {s['p50']*1e3:9.2f} "
                   f"{s['p90']*1e3:9.2f} {s['max']*1e3:9.2f} "
                   f"{s['total']:9.3f}")
    return "\n".join(out)


# ----------------------------------------------------- live run monitor

def _resolve_status(path: str) -> Optional[str]:
    """STATUS.json for a run dir (or a direct path): ``STATUS.json``
    first (the checkpoint run-dir convention), else the newest
    ``*.STATUS.json`` (the bench per-phase convention)."""
    if os.path.isdir(path):
        cand = os.path.join(path, "STATUS.json")
        if os.path.exists(cand):
            return cand
        stats = sorted(
            (os.path.join(path, f) for f in os.listdir(path)
             if f.endswith("STATUS.json")),
            key=lambda p: os.path.getmtime(p))
        return stats[-1] if stats else None
    return path if path.endswith(".json") else None


def load_status(path: Optional[str]) -> Optional[dict]:
    """Read a STATUS.json; never raises (the writer's atomic replace
    means a well-formed file or nothing, but the run dir may predate
    the monitor entirely)."""
    if not path:
        return None
    try:
        with open(path) as f:
            return json.loads(f.read())
    except (OSError, ValueError):
        return None


def watch_frame(path: str, now: Optional[float] = None) -> dict:
    """One machine-readable live-monitor frame (``watch --json``, the
    satellite's scripting hook): the STATUS snapshot, the staleness
    verdict (the same >15 s rule the human view flags), and the
    in-flight dispatch derived from the flight tail's begin markers.
    Torn/absent artifacts are never fatal — every field degrades to
    None."""
    from dslabs_tpu.tpu import tracing as tracing_mod

    now = time.time() if now is None else now
    st = load_status(_resolve_status(path))
    age = (now - float(st.get("updated", now))) if st else None
    open_d = None
    try:
        recs, _ = tracing_mod.read_flight_lax(_resolve_flight(path))
    except (OSError, ValueError, FileNotFoundError):
        recs = []
    segs = tracing_mod.segment_flight(recs)
    if segs:
        # Only the LAST segment's open dispatch is live state — an
        # earlier child's kill point belongs to the trace assembler.
        open_d = segs[-1]["in_flight"]
    return {
        "t": "watch", "source": path,
        "status": st,
        "age_secs": round(age, 1) if age is not None else None,
        "stale": bool(st) and age is not None and age > 15,
        "finished": bool(st and st.get("end_condition")),
        "in_flight": open_d,
        "trace_id": (st or {}).get("trace_id"),
    }


def render_watch(path: str, now: Optional[float] = None) -> str:
    """One frame of the live monitor, from the run dir ALONE: the
    atomic STATUS.json (depth / rate / skew / spill / rung) plus the
    flight log's tail (last span; the in-flight dispatch of a torn
    tail — a SIGKILLed run stays attributable)."""
    now = time.time() if now is None else now
    out: List[str] = [f"== dslabs live monitor: {path} =="]
    st = load_status(_resolve_status(path))
    if st is None:
        out.append("(no STATUS.json yet — run predates the monitor, "
                   "or died before its first level)")
    else:
        age = now - float(st.get("updated", now))
        stale = " !! STALE (run dead or wedged?)" if age > 15 else ""
        out.append(f"status: pid {st.get('pid')} "
                   f"hint={st.get('hint')} "
                   f"updated {age:.1f}s ago{stale}")
        rate = st.get("rate_per_min")
        win = st.get("rate_per_min_window")
        out.append(
            f"engine {st.get('engine', '?')}  "
            f"depth {st.get('depth', 0)}  "
            f"unique {st.get('unique', 0)}  "
            f"explored {st.get('explored', 0)}  "
            f"rate {rate if rate is not None else '?'} states/min "
            f"(window {win if win is not None else '?'})")
        if st.get("trace_id"):
            out.append(f"trace: {st['trace_id']} "
                       f"(parent span {st.get('parent_span') or '-'})")
        if st.get("mesh_width"):
            out.append(f"mesh width: {st['mesh_width']} device(s)")
        if st.get("resilience"):
            out.append("resilience: " + " ".join(
                f"{k}={v}" for k, v in sorted(st["resilience"].items())))
        sk = st.get("skew") or {}
        if sk:
            parts = [f"{lane} imb={m.get('imbalance', 1.0):.2f} "
                     f"cv={m.get('cv', 0.0):.2f}"
                     for lane, m in sorted(sk.items())]
            out.append("skew: " + " | ".join(parts))
        if st.get("skew_agg"):
            out.append("skew agg: " + " ".join(
                f"{k}={v}" for k, v in sorted(st["skew_agg"].items())))
        pd = st.get("per_device") or {}
        if pd.get("frontier") is not None:
            out.append("per-device frontier: "
                       + " ".join(str(v) for v in pd["frontier"]))
        if st.get("load_factor") is not None:
            out.append(f"visited load factor: {st['load_factor']}")
        if st.get("spill"):
            out.append("spill: " + " ".join(
                f"{k}={v}" for k, v in sorted(st["spill"].items())))
        if st.get("drain"):
            out.append("drain: " + " ".join(
                f"{k}={v}" for k, v in sorted(st["drain"].items())))
        if st.get("capacity"):
            out.append("capacity: " + " ".join(
                f"{k}={v}" for k, v in sorted(st["capacity"].items())))
        if st.get("faults"):
            out.append("faults: " + " ".join(
                f"{k}={v}" for k, v in sorted(st["faults"].items())))
        if st.get("rung"):
            out.append("rung: " + " ".join(
                f"{k}={v}" for k, v in sorted(st["rung"].items())))
        if st.get("lane"):
            out.append("lane: " + " ".join(
                f"{k}={v}" for k, v in sorted(st["lane"].items())))
        if st.get("lanes"):
            # A lane-batch child (tpu/lanes.py): one line per resident
            # lane — the batched equivalent of the per-device lanes.
            for lrec in st["lanes"]:
                out.append(
                    f"job lane {lrec.get('lane')}: "
                    f"{lrec.get('job_id')} depth {lrec.get('depth')} "
                    f"unique {lrec.get('unique')} "
                    f"explored {lrec.get('explored')} "
                    f"frontier {lrec.get('frontier')}")
        ls = st.get("last_span")
        if ls:
            out.append(f"last span: {ls.get('tag')} i={ls.get('i')} "
                       f"depth={ls.get('depth')} "
                       f"{ls.get('outcome')} {ls.get('wall', 0.0)}s")
        if st.get("end_condition"):
            out.append(f"end: {st['end_condition']}")
    # The flight tail is the authority on an unclosed dispatch: the
    # STATUS snapshot may predate the wedge, but the begin marker
    # cannot (it is written BEFORE the device call).
    try:
        recs = read_flight(_resolve_flight(path))
    except (OSError, ValueError):
        recs = []
    if recs:
        done = {(s["tag"], s["i"]) for s in recs
                if s.get("t") == "span"}
        open_d = None
        for r in recs:
            if (r.get("t") == "dispatch"
                    and (r["tag"], r["i"]) not in done):
                open_d = r
        if open_d is not None:
            out.append(f"!! in-flight: {open_d['tag']} "
                       f"i={open_d['i']} depth={open_d.get('depth')} "
                       "— the run is inside (or died inside) this "
                       "dispatch")
    return "\n".join(out)


# ---------------------------------------------------- cross-run ledger

def append_ledger(path: str, record: dict) -> Optional[str]:
    """Append one run's record to a JSONL bench ledger.  Never raises
    (the ledger is an artifact, not a dependency); returns the path on
    success, None on failure."""
    try:
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "a") as f:
            f.write(json.dumps(record) + "\n")
        return path
    except (OSError, ValueError, TypeError):
        return None


def read_ledger(path: str) -> List[dict]:
    """Ledger reader — same torn-tail tolerance as the flight log (a
    run killed mid-append leaves one torn line, not a dead ledger)."""
    return read_flight(path)


# The bench phases a ledger compare diffs ("headline" is the last-line
# JSON's top-level value — the number the BENCH_r0N trajectory tracks).
_LEDGER_PHASES = ("headline", "mesh", "strict", "beam", "swarm",
                  "spill", "capacity2", "service", "lanes", "memo",
                  "scenarios", "labs", "cpu_fallback")

# Resilience counters the ledger tracks beside the rates (ISSUE 9):
# a bench run that suddenly needs mesh shrinks / knob re-levels /
# failovers to land its number is a regression even at equal states/min.
_RESILIENCE_COUNTERS = ("mesh_shrinks", "knob_retries", "failovers")

# Sanitizer counters off the bench JSON's ``sanitizer`` block
# (ISSUE 10): a run whose soundness-sanitizer findings INCREASE over
# the best (fewest-findings) prior run regressed static correctness —
# flagged with the same rc-1 severity as a rate regression.
_SANITIZER_COUNTERS = ("findings", "conformance", "jaxpr")


def _sanitizer_value(rec: dict, counter: str) -> Optional[int]:
    s = rec.get("sanitizer")
    if not isinstance(s, dict) or counter not in s:
        return None
    try:
        return int(s[counter])
    except (TypeError, ValueError):
        return None


def _counter_value(rec: dict, counter: str) -> Optional[int]:
    v = rec.get(counter)
    if v is None:
        return None
    try:
        return int(v)
    except (TypeError, ValueError):
        return None


def _phase_value(rec: dict, phase: str) -> Optional[float]:
    if phase == "headline":
        v = rec.get("value")
    else:
        p = rec.get(phase)
        v = p.get("value") if isinstance(p, dict) else None
    try:
        v = float(v)
    except (TypeError, ValueError):
        return None
    return v if v > 0 else None


def compare_ledger(records: List[dict],
                   threshold: Optional[float] = None) -> dict:
    """Diff the LATEST run against the BEST prior run per phase.
    ``threshold`` is the tolerated fractional slowdown
    (DSLABS_BENCH_REGRESS_PCT, default 0.25 = flag anything >25%
    below the best prior rate — states/min is noisy on shared boxes,
    and the best-prior baseline already biases toward flagging)."""
    if threshold is None:
        threshold = _env_float("DSLABS_BENCH_REGRESS_PCT", 0.25)
    runs = [r for r in records if isinstance(r, dict)
            and ("value" in r or r.get("t") == "bench")]
    cmp = {"runs": len(runs), "threshold_pct": round(threshold * 100, 1),
           "phases": {}, "regressions": [], "improvements": []}
    if len(runs) < 2:
        cmp["note"] = "need >= 2 runs to compare"
        return cmp
    latest, prior = runs[-1], runs[:-1]
    for phase in _LEDGER_PHASES:
        lv = _phase_value(latest, phase)
        priors = [v for v in (_phase_value(r, phase) for r in prior)
                  if v is not None]
        if lv is None or not priors:
            continue
        best = max(priors)
        delta = (lv - best) / best
        entry = {"phase": phase, "latest": round(lv, 1),
                 "best_prior": round(best, 1),
                 "delta_pct": round(delta * 100, 1)}
        cmp["phases"][phase] = entry
        if delta < -threshold:
            cmp["regressions"].append(entry)
        elif delta > threshold:
            cmp["improvements"].append(entry)
    # Headline mesh-width regression (ISSUE 12): the headline number
    # is only comparable at equal (or wider) mesh width — a run that
    # silently fell back to a narrower mesh (elastic re-level, wedged
    # devices, lost XLA_FLAGS) must NOT compare as a headline win even
    # if its states/min happens to be higher.  Width rides the
    # last-line JSON as top-level ``mesh_width`` (bench._set_headline).
    cmp["mesh_width"] = {}

    def _width(rec) -> Optional[int]:
        try:
            w = int(rec.get("mesh_width"))
        except (TypeError, ValueError):
            return None
        return w if w > 0 else None

    lw = _width(latest)
    priors_w = [w for w in (_width(r) for r in prior) if w is not None]
    if lw is not None and priors_w:
        best_w = max(priors_w)
        entry = {"phase": "headline:mesh_width", "latest": lw,
                 "best_prior": best_w,
                 "delta_pct": round((lw - best_w) / best_w * 100, 1)}
        cmp["mesh_width"]["mesh_width"] = entry
        if lw < best_w:
            cmp["regressions"].append(entry)
    # Resilience regressions: the latest run needed MORE degradation
    # (mesh shrinks / knob re-levels / failovers) than any prior run —
    # flagged alongside the rate regressions (same rc).
    cmp["resilience"] = {}
    for counter in _RESILIENCE_COUNTERS:
        lv = _counter_value(latest, counter)
        if lv is None:
            continue
        priors = [v for v in (_counter_value(r, counter) for r in prior)
                  if v is not None]
        worst = max(priors) if priors else 0
        entry = {"phase": f"resilience:{counter}", "latest": lv,
                 "best_prior": worst,
                 "delta_pct": 0.0}
        cmp["resilience"][counter] = entry
        if lv > worst:
            cmp["regressions"].append(entry)
    # Sanitizer regressions (ISSUE 10): the latest run's soundness
    # findings vs the BEST (fewest) prior — any increase is a
    # regression; waived findings never count (they are documented
    # exceptions, not drift).
    cmp["sanitizer"] = {}
    for counter in _SANITIZER_COUNTERS:
        lv = _sanitizer_value(latest, counter)
        if lv is None:
            continue
        priors = [v for v in (_sanitizer_value(r, counter)
                              for r in prior) if v is not None]
        if not priors:
            continue
        best = min(priors)
        entry = {"phase": f"sanitizer:{counter}", "latest": lv,
                 "best_prior": best, "delta_pct": 0.0}
        cmp["sanitizer"][counter] = entry
        if lv > best:
            cmp["regressions"].append(entry)
    # Fairness regressions (ISSUE 11): the service phase's fairness
    # index (max/mean verdicts-per-tenant-budget; 1.0 = perfectly
    # fair) vs the BEST (lowest) prior — a rise past the threshold
    # means one tenant converted shared budget into verdicts at a
    # neighbor's expense, a regression even at equal aggregate rate.
    cmp["fairness"] = {}

    def _fair(rec):
        s = rec.get("service")
        if not isinstance(s, dict):
            return None
        try:
            v = float(s.get("fairness_index"))
        except (TypeError, ValueError):
            return None
        return v if v > 0 else None

    lv = _fair(latest)
    priors_f = [v for v in (_fair(r) for r in prior) if v is not None]
    if lv is not None and priors_f:
        best = min(priors_f)
        entry = {"phase": "service:fairness_index",
                 "latest": round(lv, 4), "best_prior": round(best, 4),
                 "delta_pct": round((lv - best) / best * 100, 1)}
        cmp["fairness"]["fairness_index"] = entry
        if lv > best * (1.0 + threshold):
            cmp["regressions"].append(entry)
    # Per-phase compile-time creep (ISSUE 13 satellite): each phase's
    # measured compile_secs vs the BEST (fastest) prior — compile
    # regressions are invisible in states/min (the measured window
    # excludes them by design), so they get their own guard with the
    # same threshold / rc-1 discipline.  Sub-second bests are skipped:
    # a warm-cache 0.2s -> 0.5s jitter is noise, not creep.
    cmp["compile"] = {}

    def _compile_value(rec, phase) -> Optional[float]:
        p = rec.get(phase)
        if not isinstance(p, dict):
            return None
        try:
            v = float(p.get("compile_secs"))
        except (TypeError, ValueError):
            return None
        return v if v >= 0 else None

    floor = _env_float("DSLABS_COMPILE_REGRESS_FLOOR", 1.0)
    for phase in _LEDGER_PHASES:
        lv = _compile_value(latest, phase)
        if lv is None:
            continue
        priors_c = [v for v in (_compile_value(r, phase)
                                for r in prior) if v is not None]
        if not priors_c:
            continue
        best = min(priors_c)
        entry = {"phase": f"compile:{phase}", "latest": round(lv, 1),
                 "best_prior": round(best, 1),
                 "delta_pct": round((lv - best) / best * 100, 1)
                 if best > 0 else 0.0}
        cmp["compile"][phase] = entry
        if (lv > max(best, floor) * (1.0 + threshold)
                and lv - best > floor):
            cmp["regressions"].append(entry)
    # Cost-per-unique-state creep (ISSUE 13): the service phase's
    # aggregate device-seconds per unique state vs the BEST (cheapest)
    # prior — a tenant's billed budget buying fewer states is a
    # regression even when verdicts/min holds (e.g. retries burning
    # device time the verdict count hides).
    cmp["cost"] = {}

    def _cost(rec):
        s = rec.get("service")
        if not isinstance(s, dict):
            return None
        try:
            v = float(s.get("cost_per_unique"))
        except (TypeError, ValueError):
            return None
        return v if v > 0 else None

    lv = _cost(latest)
    priors_k = [v for v in (_cost(r) for r in prior) if v is not None]
    if lv is not None and priors_k:
        best = min(priors_k)
        entry = {"phase": "service:cost_per_unique",
                 "latest": lv, "best_prior": best,
                 "delta_pct": round((lv - best) / best * 100, 1)}
        cmp["cost"]["cost_per_unique"] = entry
        if lv > best * (1.0 + threshold):
            cmp["regressions"].append(entry)
    # Batched-lane amortisation guards (ISSUE 14, tpu/lanes.py).
    # dispatches-per-job is THE number continuous batching exists to
    # shrink: a rise past the threshold over the best (fewest) prior
    # means jobs stopped sharing dispatch streams — a regression even
    # at equal verdicts/min.  Lane occupancy (mean resident lanes per
    # level of the lanes phase) dropping past the threshold means the
    # packer stopped filling lanes — same severity.
    cmp["lanes"] = {}

    def _dpj(rec):
        for block in ("lanes", "service"):
            s = rec.get(block)
            if isinstance(s, dict):
                try:
                    v = float(s.get("dispatches_per_job"))
                except (TypeError, ValueError):
                    continue
                if v > 0:
                    return v
        return None

    lv = _dpj(latest)
    priors_d = [v for v in (_dpj(r) for r in prior) if v is not None]
    if lv is not None and priors_d:
        best = min(priors_d)
        entry = {"phase": "service:dispatches_per_job",
                 "latest": round(lv, 2), "best_prior": round(best, 2),
                 "delta_pct": round((lv - best) / best * 100, 1)
                 if best > 0 else 0.0}
        cmp["lanes"]["dispatches_per_job"] = entry
        if lv > best * (1.0 + threshold):
            cmp["regressions"].append(entry)

    def _occ(rec):
        s = rec.get("lanes")
        if not isinstance(s, dict):
            return None
        try:
            v = float(s.get("occupancy"))
        except (TypeError, ValueError):
            return None
        return v if v > 0 else None

    lv = _occ(latest)
    priors_o = [v for v in (_occ(r) for r in prior) if v is not None]
    if lv is not None and priors_o:
        best = max(priors_o)
        entry = {"phase": "lanes:occupancy",
                 "latest": round(lv, 3), "best_prior": round(best, 3),
                 "delta_pct": round((lv - best) / best * 100, 1)}
        cmp["lanes"]["occupancy"] = entry
        if lv < best * (1.0 - threshold):
            cmp["regressions"].append(entry)
    # Capacity-round-2 guard (ISSUE 15): HBM bytes per stored frontier
    # state on the capacity2 phase vs the BEST (smallest) prior — a
    # rise past the threshold means the packed encoding regressed
    # (domain declarations lost, codec disabled), shrinking
    # frontier/visited capacity at fixed HBM even when states/min
    # holds.  Same rc-1 severity as a rate regression.
    cmp["capacity"] = {}

    def _bps(rec):
        s = rec.get("capacity2")
        if not isinstance(s, dict):
            return None
        try:
            v = float(s.get("bytes_per_state"))
        except (TypeError, ValueError):
            return None
        return v if v > 0 else None

    lv = _bps(latest)
    priors_b = [v for v in (_bps(r) for r in prior) if v is not None]
    if lv is not None and priors_b:
        best = min(priors_b)
        entry = {"phase": "capacity:bytes_per_state",
                 "latest": round(lv, 1), "best_prior": round(best, 1),
                 "delta_pct": round((lv - best) / best * 100, 1)}
        cmp["capacity"]["bytes_per_state"] = entry
        if lv > best * (1.0 + threshold):
            cmp["regressions"].append(entry)
    # Cross-job memoization guard (ISSUE 16, service/memo.py): the
    # memo phase's hit_rate vs the BEST (highest) prior — a drop past
    # the threshold means identical resubmits stopped reusing verdicts
    # (fingerprint churn, store invalidation bug), the throughput
    # multiplier silently lost even at equal cold-run states/min.
    # device_secs_saved is tracked beside it (rendered, not guarded:
    # its magnitude scales with workload, the RATE is the invariant).
    cmp["memo"] = {}

    def _hit_rate(rec):
        s = rec.get("memo")
        if not isinstance(s, dict):
            return None
        try:
            v = float(s.get("hit_rate"))
        except (TypeError, ValueError):
            return None
        return v if v >= 0 else None

    lv = _hit_rate(latest)
    priors_h = [v for v in (_hit_rate(r) for r in prior)
                if v is not None]
    if lv is not None and priors_h:
        best = max(priors_h)
        entry = {"phase": "memo:hit_rate",
                 "latest": round(lv, 3), "best_prior": round(best, 3),
                 "delta_pct": round((lv - best) / best * 100, 1)
                 if best > 0 else 0.0}
        cmp["memo"]["hit_rate"] = entry
        if lv < best * (1.0 - threshold):
            cmp["regressions"].append(entry)

    def _saved(rec):
        for block in ("memo", "service"):
            s = rec.get(block)
            if isinstance(s, dict):
                try:
                    v = float(s.get("device_secs_saved"))
                except (TypeError, ValueError):
                    continue
                if v >= 0:
                    return v
        return None

    lv = _saved(latest)
    priors_s = [v for v in (_saved(r) for r in prior) if v is not None]
    if lv is not None and priors_s:
        best = max(priors_s)
        cmp["memo"]["device_secs_saved"] = {
            "phase": "service:device_secs_saved",
            "latest": round(lv, 3), "best_prior": round(best, 3),
            "delta_pct": round((lv - best) / best * 100, 1)
            if best > 0 else 0.0}
    # Packed-wire mesh guards (ISSUE 18): two invariants the wire
    # refactor exists to hold.  wire_bytes_per_state is the ICI
    # payload row width on the mesh phase vs the BEST (smallest)
    # prior — a rise means the exchange fell back to raw rows (codec
    # disabled, identity descriptor) even when states/min holds.
    # imbalance_max is the worst post-steal per-level frontier
    # imbalance vs the BEST (lowest) prior — a rise means the stealing
    # pass stopped levelling the shards.  Both rc-1 on regression.
    cmp["mesh"] = {}

    def _wire(rec):
        s = rec.get("mesh")
        if not isinstance(s, dict):
            return None
        w = s.get("wire")
        if not isinstance(w, dict):
            return None
        try:
            v = float(w.get("wire_bytes_per_state"))
        except (TypeError, ValueError):
            return None
        return v if v > 0 else None

    lv = _wire(latest)
    priors_w = [v for v in (_wire(r) for r in prior) if v is not None]
    if lv is not None and priors_w:
        best = min(priors_w)
        entry = {"phase": "mesh:wire_bytes_per_state",
                 "latest": round(lv, 1), "best_prior": round(best, 1),
                 "delta_pct": round((lv - best) / best * 100, 1)}
        cmp["mesh"]["wire_bytes_per_state"] = entry
        if lv > best * (1.0 + threshold):
            cmp["regressions"].append(entry)

    def _imb(rec):
        s = rec.get("mesh")
        if not isinstance(s, dict):
            return None
        try:
            v = float(s.get("imbalance_max"))
        except (TypeError, ValueError):
            return None
        return v if v >= 1.0 else None

    lv = _imb(latest)
    priors_i = [v for v in (_imb(r) for r in prior) if v is not None]
    if lv is not None and priors_i:
        best = min(priors_i)
        entry = {"phase": "mesh:imbalance_max",
                 "latest": round(lv, 2), "best_prior": round(best, 2),
                 "delta_pct": round((lv - best) / best * 100, 1)
                 if best > 0 else 0.0}
        cmp["mesh"]["imbalance_max"] = entry
        if lv > best * (1.0 + threshold):
            cmp["regressions"].append(entry)
    # Fault-scenario parity guard (ISSUE 19, bench --scenarios):
    # verdict_parity is BINARY — 1 means the zero-budget FaultModel
    # landed the exact fault-free verdict/explored/unique on both
    # engines (the overhead-guard invariant scenarios ride on); 0 is a
    # soundness break, flagged regardless of threshold or priors.
    cmp["scenarios"] = {}

    def _parity(rec):
        s = rec.get("scenarios")
        if not isinstance(s, dict) or "verdict_parity" not in s:
            return None
        try:
            return int(s["verdict_parity"])
        except (TypeError, ValueError):
            return None

    lv = _parity(latest)
    priors_p = [v for v in (_parity(r) for r in prior) if v is not None]
    if lv is not None:
        best = max(priors_p) if priors_p else 1
        entry = {"phase": "scenarios:verdict_parity", "latest": lv,
                 "best_prior": best, "delta_pct": 0.0}
        cmp["scenarios"]["verdict_parity"] = entry
        if lv < 1:
            cmp["regressions"].append(entry)
    # Generated-labs packing guard (ISSUE 20, bench --labs): summed
    # packed bytes per stored state across the ProtocolSpec-compiled
    # lab3/lab4 protocols vs the BEST (smallest) prior — a rise past
    # the threshold means the spec-declared Field/Slots domains
    # stopped reaching the bit-packer (declarations dropped in a
    # refactor, identity descriptor re-derived), silently shrinking
    # frontier capacity at fixed HBM.  Same rc-1 severity as a rate
    # regression.
    cmp["labs"] = {}

    def _labs_bps(rec):
        s = rec.get("labs")
        if not isinstance(s, dict):
            return None
        try:
            v = float(s.get("bytes_per_state"))
        except (TypeError, ValueError):
            return None
        return v if v > 0 else None

    lv = _labs_bps(latest)
    priors_lb = [v for v in (_labs_bps(r) for r in prior)
                 if v is not None]
    if lv is not None and priors_lb:
        best = min(priors_lb)
        entry = {"phase": "labs:bytes_per_state",
                 "latest": round(lv, 1), "best_prior": round(best, 1),
                 "delta_pct": round((lv - best) / best * 100, 1)}
        cmp["labs"]["bytes_per_state"] = entry
        if lv > best * (1.0 + threshold):
            cmp["regressions"].append(entry)
    return cmp


def render_compare(cmp: dict, source: str = "") -> str:
    out = [f"== bench ledger compare: {source or 'ledger'} "
           f"({cmp['runs']} runs, threshold "
           f"{cmp['threshold_pct']:.0f}%) =="]
    if cmp.get("note"):
        out.append(cmp["note"])
        return "\n".join(out)
    out.append(f"{'phase':14s} {'latest':>12s} {'best_prior':>12s} "
               f"{'delta':>8s}")
    for phase in _LEDGER_PHASES:
        e = cmp["phases"].get(phase)
        if e is None:
            continue
        out.append(f"{phase:14s} {e['latest']:12.1f} "
                   f"{e['best_prior']:12.1f} {e['delta_pct']:+7.1f}%")
    for c, e in sorted(cmp.get("mesh_width", {}).items()):
        out.append(f"headline {c:16s} latest={e['latest']} "
                   f"prior_widest={e['best_prior']}")
    for c, e in sorted(cmp.get("resilience", {}).items()):
        out.append(f"resilience {c:14s} latest={e['latest']} "
                   f"prior_worst={e['best_prior']}")
    for c, e in sorted(cmp.get("sanitizer", {}).items()):
        out.append(f"sanitizer {c:15s} latest={e['latest']} "
                   f"prior_best={e['best_prior']}")
    for c, e in sorted(cmp.get("fairness", {}).items()):
        out.append(f"fairness {c:16s} latest={e['latest']} "
                   f"prior_best={e['best_prior']} "
                   f"({e['delta_pct']:+.1f}%)")
    for c, e in sorted(cmp.get("compile", {}).items()):
        out.append(f"compile {c:17s} latest={e['latest']}s "
                   f"prior_best={e['best_prior']}s "
                   f"({e['delta_pct']:+.1f}%)")
    for c, e in sorted(cmp.get("cost", {}).items()):
        out.append(f"cost {c:20s} latest={e['latest']} "
                   f"prior_best={e['best_prior']} "
                   f"({e['delta_pct']:+.1f}%)")
    for c, e in sorted(cmp.get("lanes", {}).items()):
        out.append(f"lanes {c:19s} latest={e['latest']} "
                   f"prior_best={e['best_prior']} "
                   f"({e['delta_pct']:+.1f}%)")
    for c, e in sorted(cmp.get("capacity", {}).items()):
        out.append(f"capacity {c:16s} latest={e['latest']} "
                   f"prior_best={e['best_prior']} "
                   f"({e['delta_pct']:+.1f}%)")
    for c, e in sorted(cmp.get("memo", {}).items()):
        out.append(f"memo {c:20s} latest={e['latest']} "
                   f"prior_best={e['best_prior']} "
                   f"({e['delta_pct']:+.1f}%)")
    for c, e in sorted(cmp.get("mesh", {}).items()):
        out.append(f"mesh {c:20s} latest={e['latest']} "
                   f"prior_best={e['best_prior']} "
                   f"({e['delta_pct']:+.1f}%)")
    for c, e in sorted(cmp.get("scenarios", {}).items()):
        out.append(f"scenarios {c:15s} latest={e['latest']} "
                   f"prior_best={e['best_prior']}")
    for c, e in sorted(cmp.get("labs", {}).items()):
        out.append(f"labs {c:20s} latest={e['latest']} "
                   f"prior_best={e['best_prior']} "
                   f"({e['delta_pct']:+.1f}%)")
    for e in cmp["regressions"]:
        out.append(f"REGRESSION: phase={e['phase']} "
                   f"latest={e['latest']} vs best={e['best_prior']} "
                   f"({e['delta_pct']:+.1f}%)")
    for e in cmp["improvements"]:
        out.append(f"improvement: phase={e['phase']} "
                   f"({e['delta_pct']:+.1f}%)")
    if not cmp["regressions"]:
        out.append("parity: no phase regressed past the threshold")
    return "\n".join(out)


# ------------------------------------------------------------------ CLI

_USAGE = """usage: python -m dslabs_tpu.tpu.telemetry <command> ...

  report  <run-dir-or-flight-log> [--json]   render a run report
  watch   <run-dir> [--interval S] [--once] [--json]
                                             live monitor of any run
  trace   <run-dir|server-dir> [--job ID] [--json] [--perfetto F]
                                             assemble the causal trace
  compare <ledger.jsonl> [--threshold F]     diff latest vs best prior
"""


def main(argv: Optional[List[str]] = None) -> int:
    import sys

    argv = list(sys.argv[1:] if argv is None else argv)
    if len(argv) < 2 or argv[0] not in ("report", "watch", "compare",
                                        "trace"):
        print(_USAGE, file=sys.stderr)
        return 2
    cmd, path = argv[0], argv[1]
    flags = argv[2:]

    if cmd == "trace":
        # The causal-trace assembler (ISSUE 13) lives in tpu/tracing.py
        # — journal + SERVER_STATUS + per-job flight logs, from disk
        # alone, rendered or exported as Perfetto trace-event JSON.
        from dslabs_tpu.tpu import tracing as tracing_mod

        return tracing_mod.main([path] + flags)

    if cmd == "report":
        flight = _resolve_flight(path)
        report = build_report(read_flight(flight))
        if "--json" in flags:
            # The machine-readable schema (pinned by test): the same
            # sections the renderer draws, one structure shared with
            # grading scripts and the ledger compare path.
            print(json.dumps(dict(report, source=flight)))
        else:
            print(render_report(report, source=flight))
        return 0

    if cmd == "compare":
        threshold = None
        if "--threshold" in flags:
            threshold = float(flags[flags.index("--threshold") + 1])
        cmp = compare_ledger(read_ledger(path), threshold)
        print(render_compare(cmp, source=path))
        return 1 if cmp["regressions"] else 0

    # watch: redraw until interrupted (--once = one frame, for smoke
    # tests and scripts; --json = one machine-readable frame with the
    # staleness verdict, the satellite's scripting hook).  Reads only
    # the run dir — the run itself can be any process, a warden child
    # or a bench phase included.
    if "--json" in flags:
        print(json.dumps(watch_frame(path)))
        return 0
    interval = 2.0
    if "--interval" in flags:
        interval = float(flags[flags.index("--interval") + 1])
    once = "--once" in flags
    try:
        while True:
            frame = render_watch(path)
            if not once:
                print("\x1b[2J\x1b[H", end="")
            print(frame, flush=True)
            if once:
                return 0
            time.sleep(interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    raise SystemExit(main())
