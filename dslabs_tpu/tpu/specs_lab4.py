"""Generated lab 4 twins: the four hand protocols
(tpu/protocols/{shardmaster_join,shardstore,shardstore_multi,
shardstore_tx}.py, now tests/fixtures/hand_twins/) rebuilt as
:class:`~dslabs_tpu.tpu.compiler.ProtocolSpec` values on the
replicated-protocol layer (ISSUE 20).

Composition is the point of this module: the sharded store is not one
monolithic handler set but a stack of sub-state machines —

* a RECONFIGURATION EPOCH fragment (config number, outgoing/incoming
  handoff flags, the ShardMove/ShardMoveAck exchange),
* a PER-GROUP PAXOS fragment (ballots, slot log, P2b vote bitmaps,
  election/heartbeat — the multi-server Part-3 shape),
* a 2PC VOTE fragment (per-transaction participant locks + the
  coordinator's vote/ack ledgers, TxPrepare..TxAck),

each declared once as a :class:`~dslabs_tpu.tpu.compiler.Fragment` and
composed onto the node kinds that carry it.  Slot-shaped state
(replicated log, vote ledgers, per-transaction records) declares
:class:`~dslabs_tpu.tpu.slots.Slots` blocks; group majorities declare
:class:`~dslabs_tpu.tpu.quorum.QuorumCount`.

Parity contract (same as specs_lab3): handlers mirror the hand twins
handler-for-handler, message/timer RECORDS are bijective to the hand
rows (the compiler's [tag, frm, to, fields...] header adds lanes that
are pure functions of the hand payload — sender and destination are
determined by tag + payload in every lab4 exchange), and node state is
a bijective lane permutation — so the pinned unique-state counts are
exactly preserved, while every lane now declares its packing domain
(the hand twins ran the identity codec).
"""

from __future__ import annotations

from typing import List

import jax.numpy as jnp

from dslabs_tpu.tpu.compiler import (Field, Fragment, MessageType,
                                     NodeKind, ProtocolSpec, TimerType)
from dslabs_tpu.tpu.quorum import QuorumCount
from dslabs_tpu.tpu.slots import SlotField, Slots

__all__ = [
    "make_join_spec", "make_join_protocol",
    "make_shardstore_spec", "make_shardstore_protocol",
    "make_shardstore_tx_spec", "make_shardstore_tx_protocol",
    "make_shardstore_multi_spec", "make_shardstore_multi_protocol",
    "make_shardstore_crash_spec",
    "JOIN_REQ", "JOIN_REP",
    "JOIN_T_CLIENT", "JOIN_T_ELECTION", "JOIN_T_HEARTBEAT",
    "QRY", "QREP", "SSREQ", "SSREP", "WG", "SM", "SMACK", "JREQ",
    "JREP", "T_CLIENT", "T_QUERY", "T_ELECTION", "T_HEARTBEAT",
    "CLIENT_MS", "QUERY_MS", "ELECTION_MIN", "ELECTION_MAX",
    "HEARTBEAT_MS",
]

CLIENT_MS = 100     # shardstore.py CLIENT_RETRY_MILLIS
QUERY_MS = 50       # shardstore.py QUERY_MILLIS
ELECTION_MIN, ELECTION_MAX = 150, 300   # paxos.py
HEARTBEAT_MS = 50

# Wire tags, for the harness adapters (tpu/adapters/shardstore.py).
# The join twin is its own enum space; the store twins share the first
# seven tags (the tx twin appends TXP..TXA in its own factory).
JOIN_REQ, JOIN_REP = 0, 1
JOIN_T_CLIENT, JOIN_T_ELECTION, JOIN_T_HEARTBEAT = 1, 2, 3
QRY, QREP, SSREQ, SSREP, WG, SM, SMACK, JREQ, JREP = range(9)
T_CLIENT, T_QUERY, T_ELECTION, T_HEARTBEAT = 1, 2, 3, 4


# ===================================================================
# join phase (hand twin: shardmaster_join.py)
# ===================================================================

def make_join_spec(n_joins: int = 1, net_cap: int = 12,
                   timer_cap: int = 4) -> ProtocolSpec:
    """Lab 4's JOIN phase: one shard master (lone PaxosServer running
    ShardMaster) + the config controller driving ``n_joins`` sequential
    Join commands, store servers partitioned away.  See the hand
    twin's docstring (tests/fixtures/hand_twins/shardmaster_join.py)
    for the collapse argument; the state is [mc, amo, heard] on the
    master and the controller's workload index."""
    W = n_joins

    master = NodeKind("master", 1, (
        Field("mc", hi=W),          # decided-slot count (<= W joins)
        Field("amo", hi=W),         # controller AMO high-water mark
        Field("heard", hi=1),       # heard_from_leader
    ))
    ctl = NodeKind("ctl", 1, (
        Field("k", init=1, lo=0, hi=W + 1),))

    messages = [
        MessageType("Request", ("seq",), bounds={"seq": (1, W)}),
        MessageType("Reply", ("seq",), bounds={"seq": (1, W)}),
    ]
    timers = [
        TimerType("Client", ("k",), min_ms=CLIENT_MS, max_ms=CLIENT_MS,
                  bounds={"k": (1, W)}),
        TimerType("Election", (), min_ms=ELECTION_MIN,
                  max_ms=ELECTION_MAX),
        TimerType("Heartbeat", (), min_ms=HEARTBEAT_MS,
                  max_ms=HEARTBEAT_MS),
    ]

    spec = ProtocolSpec(
        name=f"shardmaster-join-w{W}",
        nodes=[master, ctl], messages=messages, timers=timers,
        net_cap=net_cap, timer_cap=timer_cap)

    @spec.on("master", "Request")
    def m_request(ctx, p):
        seq = p["seq"]
        last = ctx.get("amo")
        fresh = seq > last
        ctx.put("amo", seq, when=fresh)
        ctx.put("mc", ctx.get("mc") + 1, when=fresh)
        ctx.put("heard", 1, when=fresh)
        # reply for fresh or exactly-cached seq (AMO re-reply)
        ctx.send("Reply", to=1, when=seq >= last, seq=seq)

    @spec.on("ctl", "Reply")
    def c_reply(ctx, p):
        k = ctx.get("k")
        match = (p["seq"] == k) & (k <= W)
        k2 = jnp.where(match, k + 1, k)
        ctx.put("k", k2)
        has_next = match & (k2 <= W)
        ctx.send("Request", to=0, when=has_next, seq=k2)
        ctx.set_timer("Client", when=has_next, k=k2)

    @spec.on_timer("master", "Election")
    def m_election(ctx, p):
        # Lone master is its own decided leader: only heard resets.
        ctx.put("heard", 0)
        ctx.set_timer("Election")

    @spec.on_timer("master", "Heartbeat")
    def m_heartbeat(ctx, p):
        ctx.set_timer("Heartbeat")       # no peers, nothing in flight

    @spec.on_timer("ctl", "Client")
    def c_timer(ctx, p):
        k = ctx.get("k")
        live = (p["k"] == k) & (k <= W)
        ctx.send("Request", to=0, when=live, seq=k)
        ctx.set_timer("Client", when=live, k=k)

    spec.initial_messages.append(("Request", 1, 0, {"seq": 1}))
    spec.initial_timers.append(("Election", 0, {}))
    spec.initial_timers.append(("Heartbeat", 0, {}))
    spec.initial_timers.append(("Client", 1, {"k": 1}))

    def clients_done(view):
        return view.get("ctl", 0, "k") == W + 1

    spec.goals["CLIENTS_DONE"] = clients_done
    return spec


def make_join_protocol(n_joins: int, net_cap: int = 12,
                       timer_cap: int = 4):
    """Drop-in replacement for the deleted hand twin's factory."""
    return make_join_spec(n_joins, net_cap, timer_cap).compile()


# ===================================================================
# Part 1 store (hand twin: shardstore.py) — G groups of ONE server
# ===================================================================

def _reconfig_fragment(NC: int, N_CFG: int, Ws: List[int], G: int):
    """The reconfiguration-epoch sub-machine carried by every store
    server: config number, outgoing/incoming handoff flags, the
    per-client snapshot AMO vector, and the ShardMove/ShardMoveAck
    exchange that walks a handoff to completion.  Handlers close over
    the shape statics; the config-install trigger itself lives on the
    including spec (it needs the QueryReply routing)."""
    maxW = max(Ws)
    frag = Fragment(
        "reconfig",
        fields=(
            Field("scfg", hi=N_CFG),
            Field("out", hi=1), Field("in", hi=1),
            Field("osamo", size=NC, hi=maxW, index_group="client"),
        ),
        messages=(
            MessageType("ShardMove",
                        ("g",) + tuple(f"s{c + 1}" for c in range(NC)),
                        bounds={"g": (2, 2)}
                        | {f"s{c + 1}": (0, Ws[c]) for c in range(NC)}),
            MessageType("ShardMoveAck", ("g",), bounds={"g": (1, 1)}),
        ))

    @frag.on("ShardMove")
    def s_shard_move(ctx, p):
        # Group 2 proposes InstallShards when at the final config with
        # the shards still incoming; re-acks when already installed;
        # ignores when behind (shardstore.py handle_ShardMove).
        if G == 1 or ctx.node_index() != 2:
            return
        at_final = ctx.get("scfg") == N_CFG
        inst = at_final & (ctx.get("in") == 1)
        reack = at_final & (ctx.get("in") == 0)
        ctx.put("scnt", ctx.get("scnt") + 1, when=inst)
        ctx.put("sh", 1, when=inst)
        for c in range(NC):    # AMO merge: per-client max with snapshot
            samo = ctx.get_at("samo", c)
            ctx.put_at("samo", c, jnp.maximum(samo, p[f"s{c + 1}"]),
                       when=inst)
        ctx.put("in", 0, when=inst)
        ctx.send("ShardMoveAck", to=1, when=inst | reack, g=1)

    @frag.on("ShardMoveAck")
    def s_shard_move_ack(ctx, p):
        # Group 1 proposes MoveDone while the handoff is outstanding.
        if G == 1 or ctx.node_index() != 1:
            return
        fin = ctx.get("out") == 1
        ctx.put("scnt", ctx.get("scnt") + 1, when=fin)
        ctx.put("sh", 1, when=fin)
        ctx.put("out", 0, when=fin)

    return frag


def make_shardstore_spec(groups_of=(1, 1), net_cap: int = 48,
                         timer_cap: int = 6,
                         model_master_timers: bool = False,
                         model_ctl: bool = False,
                         fault=None) -> ProtocolSpec:
    """``groups_of``: per-client, per-command owning group (1-based)
    under the FINAL config; a flat int list means one client.  See the
    hand twin's docstring (tests/fixtures/hand_twins/shardstore.py)
    for the one-server-group collapse argument and the config-walk /
    handoff model; every handler below mirrors it line by line."""
    if groups_of and isinstance(groups_of[0], int):
        groups_of = [list(groups_of)]
    per_client: List[List[int]] = [list(g) for g in groups_of]
    NC = len(per_client)
    Ws = [len(g) for g in per_client]
    G = max(max(g) for g in per_client)
    assert all(min(g) >= 1 for g in per_client)
    assert G <= 2, "3+-group configs need multi-hop handoff modelling"
    N_CFG = G                       # one config per staged Join
    maxW = max(Ws)
    CLI0 = G + 1                    # first client node index
    CCA = 1 + G + NC                # controller (model_ctl only)

    def grp_of(c, k):
        """Traced (client, workload index) -> owning group under the
        final config (static where-chain)."""
        out = jnp.asarray(per_client[0][0], jnp.int32)
        for cs in range(NC):
            for kk in range(1, Ws[cs] + 1):
                if (cs, kk) == (0, 1):
                    continue
                out = jnp.where((c == cs) & (k == kk),
                                per_client[cs][kk - 1], out)
        return out

    def served_kind(arg):
        # shardmaster.py Query: arg < 0 or >= len -> latest config.
        latest = N_CFG - 1
        return jnp.where((arg < 0) | (arg >= N_CFG), latest,
                         arg).astype(jnp.int32)

    def cfg_mine(g, cfg_idx, c, k):
        """Does group g own command (c, k)'s shard under configs[
        cfg_idx] (0-based)?  cfg0 assigns everything to group 1; the
        final config follows groups_of."""
        under_final = grp_of(c, k) == g
        if g == 1:
            return jnp.where(cfg_idx == 0, True, under_final)
        return jnp.where(cfg_idx == 0, False, under_final)

    master = NodeKind("master", 1, (
        Field("mc", init=G),        # G decided Joins at the seam
        Field("heard", init=1, hi=1),
        Field("amoc", size=NC, index_group="client"),
        Field("amos", size=G, index_group="server"),
    ))
    server = NodeKind("server", G, (
        Field("scnt"), Field("sh", hi=1), Field("sq"),
        Field("samo", size=NC, hi=maxW, index_group="client"),
    ))
    client = NodeKind("client", NC, (
        Field("k", init=1, hi=maxW + 1),
        Field("cfg", hi=1),
        Field("cq", init=2),
    ))
    nodes = [master, server, client]
    if model_ctl:
        # The controller's only mutable state is its (engine-modelled)
        # timer queue — a node kind with no lanes.
        nodes.append(NodeKind("ctl", 1, ()))

    messages = [
        MessageType("Query", ("src", "seq", "arg"),
                    bounds={"src": (0, NC + G - 1),
                            "arg": (-1, N_CFG)}),
        MessageType("QueryReply", ("dst", "seq", "kind"),
                    bounds={"dst": (0, NC + G - 1),
                            "kind": (0, N_CFG - 1)}),
        MessageType("ShardStoreRequest", ("c", "k"),
                    bounds={"c": (0, NC - 1), "k": (1, maxW)}),
        MessageType("ShardStoreReply", ("c", "k"),
                    bounds={"c": (0, NC - 1), "k": (1, maxW)}),
        MessageType("WrongGroup", ("c", "k"),
                    bounds={"c": (0, NC - 1), "k": (1, maxW)}),
    ]
    timers = [
        TimerType("Client", ("k",), min_ms=CLIENT_MS, max_ms=CLIENT_MS,
                  bounds={"k": (1, max(maxW, G) if model_ctl
                                else maxW)}),
        TimerType("Query", (), min_ms=QUERY_MS, max_ms=QUERY_MS),
        TimerType("Election", (), min_ms=ELECTION_MIN,
                  max_ms=ELECTION_MAX),
        TimerType("Heartbeat", (), min_ms=HEARTBEAT_MS,
                  max_ms=HEARTBEAT_MS),
    ]

    spec = ProtocolSpec(
        name=f"shardstore-g{G}-c{NC}-w{sum(Ws)}",
        nodes=nodes, messages=messages, timers=timers,
        net_cap=net_cap, timer_cap=timer_cap, fault=fault)
    spec.include("server", _reconfig_fragment(NC, N_CFG, Ws, G))
    spec.include("master", Fragment(
        "join-debris",
        messages=(MessageType("JoinRequest", ("j",),
                              bounds={"j": (1, G)}),
                  MessageType("JoinReply", ("j",),
                              bounds={"j": (1, G)}))))

    # ----------------------------------------------- message handlers

    @spec.on("master", "Query")
    def m_query(ctx, p):
        # paxos.py handle_PaxosRequest; n=1: fresh commands decide +
        # execute + GC inline.  Sources: clients 0..NC-1, servers
        # NC..NC+G-1 (out-of-range halves of the AMO pair are one-hot
        # no-ops).
        src, seq, arg = p["src"], p["seq"], p["arg"]
        last = jnp.where(src < NC, ctx.get_at("amoc", src),
                         ctx.get_at("amos", src - NC))
        fresh = seq > last
        ctx.put_at("amoc", src, seq, when=fresh)
        ctx.put_at("amos", src - NC, seq, when=fresh)
        ctx.put("mc", ctx.get("mc") + 1, when=fresh)
        # A fresh proposal's self-delivered P2a sets heard_from_leader.
        ctx.put("heard", 1, when=fresh)
        ctx.send("QueryReply",
                 to=jnp.where(src < NC, CLI0 + src, src - NC + 1),
                 when=seq >= last, dst=src, seq=seq,
                 kind=served_kind(arg))

    @spec.on("master", "JoinRequest")
    def m_join_debris(ctx, p):
        # model_ctl join-phase debris: REQ(G) re-replies the cached
        # result — an identical row the network set dedupes.
        ctx.send("JoinReply", to=CCA, when=p["j"] == G, j=G)

    @spec.on("server", "QueryReply")
    def s_query_reply(ctx, p):
        # Propose NewConfig iff the carried config is exactly
        # _next_config_num() and reconfig is done; installing the FINAL
        # config starts the handoff (g1 loses, g2 gains).
        g = ctx.node_index()
        kind = p["kind"]
        scfg = ctx.get("scfg")
        done = (ctx.get("out") == 0) & (ctx.get("in") == 0)
        install = (kind == scfg) & (scfg < N_CFG) & done
        if G > 1:
            is_final = install & (scfg == N_CFG - 1)
            if g == 1:
                ctx.put("out", 1, when=is_final)
                for c in range(NC):
                    ctx.put_at("osamo", c, ctx.get_at("samo", c),
                               when=is_final)
                # leader installs -> _send_moves inline
                ctx.send("ShardMove", to=2, when=is_final, g=2,
                         **{f"s{c + 1}": ctx.get_at("samo", c)
                            for c in range(NC)})
            else:
                ctx.put("in", 1, when=is_final)
        ctx.put("scfg", scfg + 1, when=install)
        ctx.put("scnt", ctx.get("scnt") + 1, when=install)
        ctx.put("sh", 1, when=install)

    @spec.on("server", "ShardStoreRequest")
    def s_ssreq(ctx, p):
        # ALWAYS proposes (relay-mode chosen entries are not deduped)
        # -> count+1, heard; execution gated by config coverage and
        # ownership (shardstore.py _execute_client_command).  Routing
        # already delivered this to grp_of(c, k).
        g = ctx.node_index()
        cc, kk = p["c"], p["k"]
        ctx.put("scnt", ctx.get("scnt") + 1)
        ctx.put("sh", 1)
        scfg = ctx.get("scfg")
        has_cfg = scfg >= 1
        mine = cfg_mine(g, (scfg - 1).clip(0, N_CFG - 1), cc, kk) \
            & has_cfg
        # wrong group: current config exists but shard is not mine
        ctx.send("WrongGroup", to=CLI0 + cc, when=has_cfg & ~mine,
                 c=cc, k=kk)
        # mine but still incoming -> silent (client retries); only
        # group 2 ever gains shards
        if g == 2 and G > 1:
            owned = mine & (ctx.get("in") == 0)
        else:
            owned = mine
        samo = ctx.get_at("samo", cc)
        ctx.put_at("samo", cc, kk, when=owned & (kk > samo))
        ctx.send("ShardStoreReply", to=CLI0 + cc,
                 when=owned & (kk >= samo), c=cc, k=kk)

    @spec.on("client", "QueryReply")
    def c_query_reply(ctx, p):
        # Adopt the (always latest) config if newer, then send the
        # pending command.
        c = ctx.node_index() - CLI0
        k = ctx.get("k")
        adopt = ctx.get("cfg") == 0
        ctx.put("cfg", 1, when=adopt)
        ctx.send("ShardStoreRequest", to=grp_of(c, k),
                 when=adopt & (k <= Ws[c]), c=c, k=k)

    @spec.on("client", "ShardStoreReply")
    def c_ssrep(ctx, p):
        c = ctx.node_index() - CLI0
        k = ctx.get("k")
        match = (p["c"] == c) & (p["k"] == k) & (k <= Ws[c])
        k2 = jnp.where(match, k + 1, k)
        ctx.put("k", k2)
        has_next = match & (k2 <= Ws[c])
        ctx.send("ShardStoreRequest", to=grp_of(c, k2), when=has_next,
                 c=c, k=k2)
        ctx.set_timer("Client", when=has_next, k=k2)

    @spec.on("client", "WrongGroup")
    def c_wrong_group(ctx, p):
        c = ctx.node_index() - CLI0
        k = ctx.get("k")
        is_wg = (p["c"] == c) & (p["k"] == k) & (k <= Ws[c])
        cq = ctx.get("cq")
        ctx.put("cq", cq + 1, when=is_wg)
        ctx.send("Query", to=0, when=is_wg, src=c, seq=cq + 1, arg=-1)

    # ------------------------------------------------- timer handlers

    @spec.on_timer("client", "Client")
    def c_timer(ctx, p):
        # Re-query (+1 more query when there is no config yet —
        # _send_pending falls back to _query_config) and re-send the
        # pending command.  The hand twin's single state-dependent row
        # is two complementary guarded sends here — same network set.
        c = ctx.node_index() - CLI0
        k = ctx.get("k")
        live = (p["k"] == k) & (k <= Ws[c])
        cq = ctx.get("cq")
        has_cfg = ctx.get("cfg") == 1
        ctx.put("cq", jnp.where(has_cfg, cq + 1, cq + 2), when=live)
        ctx.send("Query", to=0, when=live, src=c, seq=cq + 1, arg=-1)
        ctx.send("ShardStoreRequest", to=grp_of(c, k),
                 when=live & has_cfg, c=c, k=k)
        ctx.send("Query", to=0, when=live & ~has_cfg, src=c,
                 seq=cq + 2, arg=-1)
        ctx.set_timer("Client", when=live, k=k)

    @spec.on_timer("server", "Query")
    def s_query_timer(ctx, p):
        # The query itself is gated on _reconfig_done; _send_moves
        # always runs (re-sends the stored ShardMove while a handoff
        # is pending).
        g = ctx.node_index()
        done = (ctx.get("out") == 0) & (ctx.get("in") == 0)
        sq = ctx.get("sq")
        ctx.put("sq", sq + 1, when=done)
        ctx.send("Query", to=0, when=done, src=NC + g - 1, seq=sq + 1,
                 arg=ctx.get("scfg"))
        if g == 1 and G > 1:
            ctx.send("ShardMove", to=2, when=ctx.get("out") == 1, g=2,
                     **{f"s{c + 1}": ctx.get_at("osamo", c)
                        for c in range(NC)})
        ctx.set_timer("Query")

    @spec.on_timer("server", "Election")
    def s_election(ctx, p):
        # Lone server is its own decided leader; only heard resets.
        ctx.put("sh", 0)
        ctx.set_timer("Election")

    @spec.on_timer("server", "Heartbeat")
    def s_heartbeat(ctx, p):
        ctx.set_timer("Heartbeat")     # no peers, nothing in flight

    if model_master_timers:
        @spec.on_timer("master", "Election")
        def m_election(ctx, p):
            ctx.put("heard", 0)
            ctx.set_timer("Election")

        @spec.on_timer("master", "Heartbeat")
        def m_heartbeat(ctx, p):
            ctx.set_timer("Heartbeat")

    # The controller's stale ClientTimers (model_ctl) have NO handler:
    # delivery only consumes the timer — the state change IS the pop.

    # -------------------------------------------- initials/predicates

    for c in range(NC):
        for s in (1, 2):
            # init() queries once; send_command with no config falls
            # back to _query_config and queries AGAIN.
            spec.initial_messages.append(
                ("Query", CLI0 + c, 0, {"src": c, "seq": s, "arg": -1}))
    if model_ctl:
        for j in range(1, G + 1):
            spec.initial_messages.append(
                ("JoinRequest", CCA, 0, {"j": j}))
            spec.initial_messages.append(
                ("JoinReply", 0, CCA, {"j": j}))
    if model_master_timers:
        spec.initial_timers.append(("Election", 0, {}))
        spec.initial_timers.append(("Heartbeat", 0, {}))
    if model_ctl:
        for j in range(1, G + 1):
            spec.initial_timers.append(("Client", CCA, {"k": j}))
    for g in range(1, G + 1):
        # ShardStoreServer.init: paxos.init (Election, then the
        # immediate self-election arms Heartbeat), then QueryTimer.
        spec.initial_timers.append(("Election", g, {}))
        spec.initial_timers.append(("Heartbeat", g, {}))
        spec.initial_timers.append(("Query", g, {}))
    for c in range(NC):
        spec.initial_timers.append(("Client", CLI0 + c, {"k": 1}))

    def clients_done(view):
        done = jnp.asarray(True)
        for c in range(NC):
            done = done & (view.get("client", c, "k") == Ws[c] + 1)
        return done

    spec.goals["CLIENTS_DONE"] = clients_done
    return spec


def make_shardstore_protocol(groups_of, net_cap: int = 48,
                             timer_cap: int = 6,
                             model_master_timers: bool = False,
                             model_ctl: bool = False, fault=None):
    """Drop-in replacement for the deleted hand twin's factory: same
    signature, same protocol name, same searched state space."""
    return make_shardstore_spec(
        groups_of, net_cap, timer_cap, model_master_timers,
        model_ctl, fault=fault).compile()


def make_shardstore_crash_spec(groups_of=(1, 1), net_cap: int = 48,
                               timer_cap: int = 6) -> ProtocolSpec:
    """The generated part-1 shardstore under a crash-recovery
    scenario (ISSUE 19 model events on the ISSUE 20 spec layer): any
    server group may crash once and restart.  The per-client ``samo``
    at-most-once table is DURABLE — it survives the crash — while the
    config walk (scnt/sh/sq) is volatile and resets to inits on
    restart, so a recovered group must re-learn its config from the
    master; the exactly-once obligation holds across the crash."""
    from dslabs_tpu.tpu.faults import Crash, FaultModel

    fm = FaultModel(crash=Crash(durable={"server": ("samo",)},
                                max_crashes=1))
    spec = make_shardstore_spec(list(groups_of), net_cap, timer_cap,
                                fault=fm)
    spec.name += "-crash"
    return spec


# ===================================================================
# Part 2 transactions (hand twin: shardstore_tx.py) — 2PC over the
# two-group store: the reconfig fragment above + a 2PC vote fragment
# ===================================================================

def _twopc_fragment(W: int, CLIENT: int):
    """The 2PC sub-machine carried by both store groups: the
    per-transaction PARTICIPANT record (promised round, vote, applied
    flag) and key lock on every server, plus the COORDINATOR's vote and
    ack ledgers (constant-zero lanes on group 2 — a bijection-safe
    uniform layout).  Group 1 doubles as coordinator, so fragment
    handlers branch on ``ctx.node_index()`` exactly like the hand
    twin's node blocks."""
    frag = Fragment(
        "twopc",
        fields=(
            Field("lock", hi=W),
            Slots("ptx", W, base=1, fields=(
                SlotField("rnd"), SlotField("ok", hi=1),
                SlotField("done", hi=1))),
            Slots("coord", W, base=1, fields=(
                SlotField("lrnd"), SlotField("rnd"),
                SlotField("v1", hi=2), SlotField("v2", hi=2),
                SlotField("dec", hi=2),
                SlotField("a1", hi=1), SlotField("a2", hi=1))),
        ),
        messages=(
            MessageType("TxPrepare", ("t", "rnd", "g"),
                        bounds={"t": (1, W), "g": (1, 2)}),
            MessageType("TxVote", ("t", "rnd", "v"),
                        bounds={"t": (1, W), "v": (2, 5)}),
            MessageType("TxDecision", ("t", "rnd", "d"),
                        bounds={"t": (1, W), "d": (2, 5)}),
            MessageType("TxAck", ("t", "rnd", "g"),
                        bounds={"t": (1, W), "g": (1, 2)}),
        ))

    @frag.on("TxPrepare")
    def s_tx_prepare(ctx, p):
        # Participant path (handle_TxPrepare): immediate yes for an
        # already-applied txn, no under cfg0, else the promise/lock
        # dance — supersede an older round, refuse a held lock, group 2
        # refuses while shards are incoming.
        g = ctx.node_index()
        ctx.put("scnt", ctx.get("scnt") + 1)
        ctx.put("sh", 1)
        scfg = ctx.get("scfg")
        for t in range(1, W + 1):
            h = p["t"] == t
            rnd = p["rnd"]
            dn = ctx.slot_get("ptx", "done", t) == 1
            ctx.send("TxVote", to=1, when=h & (scfg >= 1) & dn,
                     t=t, rnd=rnd, v=2 * g + 1)
            ctx.send("TxVote", to=1, when=h & (scfg == 1) & ~dn,
                     t=t, rnd=rnd, v=2 * g)
            m = h & (scfg == 2) & ~dn
            prnd = ctx.slot_get("ptx", "rnd", t)
            stale = prnd > rnd
            supersede = (prnd > 0) & (prnd < rnd)
            ctx.put("lock", 0,
                    when=m & supersede & (ctx.get("lock") == t))
            fresh = (prnd == 0) | supersede
            lock2 = ctx.get("lock")          # RE-READ after release
            conflict = (lock2 != 0) & (lock2 != t)
            owned = (ctx.get("in") == 0) if g == 2 \
                else jnp.asarray(True)
            ok = fresh & ~conflict & owned
            ctx.put("lock", t, when=m & ok)
            ctx.slot_put("ptx", "rnd", t, rnd, when=m & fresh)
            ctx.slot_put("ptx", "ok", t, ok.astype(jnp.int32),
                         when=m & fresh)
            # vote from the STORED record (fresh writes land first)
            ctx.send("TxVote", to=1, when=m & ~stale, t=t,
                     rnd=ctx.slot_get("ptx", "rnd", t),
                     v=2 * g + ctx.slot_get("ptx", "ok", t))

    @frag.on("TxVote")
    def s_tx_vote(ctx, p):
        # Coordinator path: record the vote, decide on both-in, reply
        # to the client on commit, broadcast the decision.
        if ctx.node_index() != 1:
            return
        ctx.put("scnt", ctx.get("scnt") + 1)
        ctx.put("sh", 1)
        for t in range(1, W + 1):
            h = p["t"] == t
            rnd = p["rnd"]
            fg, okv = p["v"] // 2, p["v"] % 2
            live = h & (ctx.slot_get("coord", "rnd", t) == rnd) \
                & (rnd > 0) & (ctx.slot_get("coord", "dec", t) == 0)
            vval = jnp.where(okv == 1, 1, 2)
            ctx.slot_put("coord", "v1", t, vval, when=live & (fg == 1))
            ctx.slot_put("coord", "v2", t, vval, when=live & (fg == 2))
            v1 = ctx.slot_get("coord", "v1", t)   # RE-READ
            v2 = ctx.slot_get("coord", "v2", t)
            dec_abort = live & ((v1 == 2) | (v2 == 2))
            dec_commit = live & (v1 == 1) & (v2 == 1)
            ctx.slot_put("coord", "dec", t, 2, when=dec_abort)
            ctx.slot_put("coord", "dec", t, 1, when=dec_commit)
            ctx.put_at("samo", 0, t,
                       when=dec_commit & (ctx.get_at("samo", 0) < t))
            ctx.send("ShardStoreReply", to=CLIENT, when=dec_commit,
                     k=t)
            decided = dec_abort | dec_commit
            cbit = dec_commit.astype(jnp.int32)
            ctx.send("TxDecision", to=1, when=decided, t=t, rnd=rnd,
                     d=2 + cbit)
            ctx.send("TxDecision", to=2, when=decided, t=t, rnd=rnd,
                     d=4 + cbit)

    @frag.on("TxDecision")
    def s_tx_decision(ctx, p):
        # Participant applies a commit it voted for, releases the
        # lock + promise; the coordinator half additionally clears an
        # ABORT ledger early (commit ledgers wait for both acks).
        g = ctx.node_index()
        ctx.put("scnt", ctx.get("scnt") + 1)
        ctx.put("sh", 1)
        commit = p["d"] % 2 == 1
        for t in range(1, W + 1):
            h = p["t"] == t
            rnd = p["rnd"]
            pmatch = h & (ctx.slot_get("ptx", "rnd", t) == rnd) \
                & (rnd > 0)
            ctx.slot_put("ptx", "done", t, 1,
                         when=pmatch & commit
                         & (ctx.slot_get("ptx", "ok", t) == 1))
            ctx.put("lock", 0, when=pmatch & (ctx.get("lock") == t))
            ctx.slot_put("ptx", "rnd", t, 0, when=pmatch)
            ctx.slot_put("ptx", "ok", t, 0, when=pmatch)
            if g == 1:
                clear = h & ~commit \
                    & (ctx.slot_get("coord", "dec", t) == 2) \
                    & (ctx.slot_get("coord", "rnd", t) == rnd)
                for f in ("rnd", "v1", "v2", "dec", "a1", "a2"):
                    ctx.slot_put("coord", f, t, 0, when=clear)
            ctx.send("TxAck", to=1, when=h & (ctx.get("scfg") >= 1),
                     t=t, rnd=rnd, g=g)

    @frag.on("TxAck")
    def s_tx_ack(ctx, p):
        # Coordinator: second ack retires the ledger (LRND persists —
        # it is the round generator).
        if ctx.node_index() != 1:
            return
        ctx.put("scnt", ctx.get("scnt") + 1)
        ctx.put("sh", 1)
        fg = p["g"]
        for t in range(1, W + 1):
            h = p["t"] == t
            rnd = p["rnd"]
            live = h & (ctx.slot_get("coord", "rnd", t) == rnd) \
                & (rnd > 0)
            ctx.slot_put("coord", "a1", t, 1, when=live & (fg == 1))
            ctx.slot_put("coord", "a2", t, 1, when=live & (fg == 2))
            full = live & (ctx.slot_get("coord", "a1", t) == 1) \
                & (ctx.slot_get("coord", "a2", t) == 1)   # RE-READ
            for f in ("rnd", "v1", "v2", "dec", "a1", "a2"):
                ctx.slot_put("coord", f, t, 0, when=full)

    return frag


def make_shardstore_tx_spec(n_tx: int = 1, net_cap: int = 48,
                            timer_cap: int = 6) -> ProtocolSpec:
    """Lab 4 part 2: every client command is a 2-shard transaction
    (one key per group under the final config), group 1 coordinating
    2PC across both groups.  See the hand twin's docstring
    (tests/fixtures/hand_twins/shardstore_tx.py) for the collapse and
    alphabet arguments; handlers mirror it block for block.  The
    reconfiguration epoch is the SAME fragment part 1 composes in; the
    2PC records are the new ``twopc`` fragment."""
    W, G, N_CFG = n_tx, 2, 2
    CLIENT = 3

    master = NodeKind("master", 1, (
        Field("mc", init=G),
        Field("amoc", size=1, index_group="client"),
        Field("amos", size=G, index_group="group"),
    ))
    group = NodeKind("group", G, (
        Field("scnt"), Field("sh", hi=1), Field("sq"),
        Field("samo", size=1, hi=W, index_group="client"),
    ))
    client = NodeKind("client", 1, (
        Field("k", init=1, hi=W + 1),
        Field("cfg", hi=1),
        Field("cq", init=2),
    ))

    messages = [
        MessageType("Query", ("src", "seq", "arg"),
                    bounds={"src": (0, G), "arg": (-1, N_CFG)}),
        MessageType("QueryReply", ("dst", "seq", "kind"),
                    bounds={"dst": (0, G), "kind": (0, N_CFG - 1)}),
        MessageType("ShardStoreRequest", ("k",), bounds={"k": (1, W)}),
        MessageType("ShardStoreReply", ("k",), bounds={"k": (1, W)}),
        MessageType("WrongGroup", ("k",), bounds={"k": (1, W)}),
    ]
    timers = [
        TimerType("Client", ("k",), min_ms=CLIENT_MS, max_ms=CLIENT_MS,
                  bounds={"k": (1, W)}),
        TimerType("Query", (), min_ms=QUERY_MS, max_ms=QUERY_MS),
        TimerType("Election", (), min_ms=ELECTION_MIN,
                  max_ms=ELECTION_MAX),
        TimerType("Heartbeat", (), min_ms=HEARTBEAT_MS,
                  max_ms=HEARTBEAT_MS),
    ]

    spec = ProtocolSpec(
        name=f"shardstore-tx-g{G}-w{W}",
        nodes=[master, group, client], messages=messages,
        timers=timers, net_cap=net_cap, timer_cap=timer_cap,
        max_live_sends=6)
    spec.include("group", _reconfig_fragment(1, N_CFG, [W], G))
    spec.include("group", _twopc_fragment(W, CLIENT))

    def reconfig_done(ctx, g):
        # _reconfig_done: no handoff in flight AND no 2PC state held
        # (locks, promises; the coordinator also drains its ledgers).
        done = (ctx.get("out") == 0) & (ctx.get("in") == 0) \
            & (ctx.get("lock") == 0)
        for t in range(1, W + 1):
            done = done & (ctx.slot_get("ptx", "rnd", t) == 0)
            if g == 1:
                done = done & (ctx.slot_get("coord", "rnd", t) == 0)
        return done

    # ----------------------------------------------- message handlers

    @spec.on("master", "Query")
    def m_query(ctx, p):
        # Collapsed lone-master paxos: NO heard lane here — the part-2
        # harness never partitions the master, so heard_from_leader is
        # constant (the hand twin dropped it too).
        src, seq, arg = p["src"], p["seq"], p["arg"]
        last = jnp.where(src == 0, ctx.get_at("amoc", 0),
                         ctx.get_at("amos", src - 1))
        fresh = seq > last
        ctx.put_at("amoc", 0, seq, when=fresh & (src == 0))
        ctx.put_at("amos", src - 1, seq, when=fresh)
        ctx.put("mc", ctx.get("mc") + 1, when=fresh)
        served = jnp.where((arg < 0) | (arg >= N_CFG), N_CFG - 1,
                           arg).astype(jnp.int32)
        ctx.send("QueryReply", to=jnp.where(src == 0, CLIENT, src),
                 when=seq >= last, dst=src, seq=seq, kind=served)

    @spec.on("group", "QueryReply")
    def s_query_reply(ctx, p):
        g = ctx.node_index()
        kind = p["kind"]
        scfg = ctx.get("scfg")
        install = (kind == scfg) & (scfg < N_CFG) \
            & reconfig_done(ctx, g)
        is_final = install & (scfg == N_CFG - 1)
        if g == 1:
            ctx.put("out", 1, when=is_final)
            ctx.put_at("osamo", 0, ctx.get_at("samo", 0),
                       when=is_final)
            ctx.send("ShardMove", to=2, when=is_final, g=2,
                     s1=ctx.get_at("samo", 0))
        else:
            ctx.put("in", 1, when=is_final)
        ctx.put("scfg", scfg + 1, when=install)
        ctx.put("scnt", ctx.get("scnt") + 1, when=install)
        ctx.put("sh", 1, when=install)

    @spec.on("group", "ShardStoreRequest")
    def s_ssreq(ctx, p):
        # Only the coordinator (group 1) receives client requests.
        # cfg1: direct single-group execute.  cfg2: answer from cache
        # or start a 2PC round (one per txn in flight).
        if ctx.node_index() != 1:
            return
        kk = p["k"]
        ctx.put("scnt", ctx.get("scnt") + 1)
        ctx.put("sh", 1)
        scfg = ctx.get("scfg")
        samo = ctx.get_at("samo", 0)
        direct = scfg == 1
        ctx.put_at("samo", 0, kk, when=direct & (kk > samo))
        ctx.send("ShardStoreReply", to=CLIENT,
                 when=direct & (kk >= samo), k=kk)
        co = scfg == 2
        cached = co & (samo >= kk)
        ctx.send("ShardStoreReply", to=CLIENT,
                 when=cached & (kk == samo), k=kk)
        in_prog = ctx.slot_get("coord", "rnd", kk) > 0
        start = co & ~cached & ~in_prog
        for t in range(1, W + 1):
            here = start & (kk == t)
            rnd = ctx.slot_get("coord", "lrnd", t) + 1
            ctx.slot_put("coord", "lrnd", t, rnd, when=here)
            ctx.slot_put("coord", "rnd", t, rnd, when=here)
            for f in ("v1", "v2", "dec", "a1", "a2"):
                ctx.slot_put("coord", f, t, 0, when=here)
            ctx.send("TxPrepare", to=1, when=here, t=t, rnd=rnd, g=1)
            ctx.send("TxPrepare", to=2, when=here, t=t, rnd=rnd, g=2)

    @spec.on("client", "QueryReply")
    def c_query_reply(ctx, p):
        k = ctx.get("k")
        adopt = ctx.get("cfg") == 0
        ctx.put("cfg", 1, when=adopt)
        ctx.send("ShardStoreRequest", to=1, when=adopt & (k <= W),
                 k=k)

    @spec.on("client", "ShardStoreReply")
    def c_ssrep(ctx, p):
        k = ctx.get("k")
        match = (p["k"] == k) & (k <= W)
        k2 = jnp.where(match, k + 1, k)
        ctx.put("k", k2)
        has_next = match & (k2 <= W)
        ctx.send("ShardStoreRequest", to=1, when=has_next, k=k2)
        ctx.set_timer("Client", when=has_next, k=k2)

    @spec.on("client", "WrongGroup")
    def c_wrong_group(ctx, p):
        # Unreachable in this workload (nothing sends WrongGroup); the
        # handler mirrors the hand twin's parity stub.
        k = ctx.get("k")
        is_wg = (p["k"] == k) & (k <= W)
        cq = ctx.get("cq")
        ctx.put("cq", cq + 1, when=is_wg)
        ctx.send("Query", to=0, when=is_wg, src=0, seq=cq + 1, arg=-1)

    # ------------------------------------------------- timer handlers

    @spec.on_timer("client", "Client")
    def c_timer(ctx, p):
        k = ctx.get("k")
        live = (p["k"] == k) & (k <= W)
        cq = ctx.get("cq")
        has_cfg = ctx.get("cfg") == 1
        ctx.put("cq", jnp.where(has_cfg, cq + 1, cq + 2), when=live)
        ctx.send("Query", to=0, when=live, src=0, seq=cq + 1, arg=-1)
        ctx.send("ShardStoreRequest", to=1, when=live & has_cfg, k=k)
        ctx.send("Query", to=0, when=live & ~has_cfg, src=0,
                 seq=cq + 2, arg=-1)
        ctx.set_timer("Client", when=live, k=k)

    @spec.on_timer("group", "Query")
    def s_query_timer(ctx, p):
        g = ctx.node_index()
        ask = reconfig_done(ctx, g)
        sq = ctx.get("sq")
        ctx.put("sq", sq + 1, when=ask)
        ctx.send("Query", to=0, when=ask, src=g, seq=sq + 1,
                 arg=ctx.get("scfg"))
        if g == 1:
            ctx.send("ShardMove", to=2, when=ctx.get("out") == 1, g=2,
                     s1=ctx.get_at("osamo", 0))
        ctx.set_timer("Query")

    @spec.on_timer("group", "Election")
    def s_election(ctx, p):
        ctx.put("sh", 0)
        ctx.set_timer("Election")

    @spec.on_timer("group", "Heartbeat")
    def s_heartbeat(ctx, p):
        ctx.set_timer("Heartbeat")

    # -------------------------------------------- initials/predicates

    for s in (1, 2):
        spec.initial_messages.append(
            ("Query", CLIENT, 0, {"src": 0, "seq": s, "arg": -1}))
    for g in (1, 2):
        spec.initial_timers.append(("Election", g, {}))
        spec.initial_timers.append(("Heartbeat", g, {}))
        spec.initial_timers.append(("Query", g, {}))
    spec.initial_timers.append(("Client", CLIENT, {"k": 1}))

    def clients_done(view):
        return view.get("client", 0, "k") == W + 1

    def multi_gets_match(view):
        # A replied txn t is committed on the coordinator (samo >= t).
        ok = jnp.asarray(True)
        for t in range(1, W + 1):
            replied = view.get("client", 0, "k") > t
            ok = ok & (~replied
                       | (view.get("group", 0, "samo") >= t))
        return ok

    spec.goals["CLIENTS_DONE"] = clients_done
    spec.invariants["MULTI_GETS_MATCH"] = multi_gets_match
    return spec


def make_shardstore_tx_protocol(n_tx: int = 1, net_cap: int = 48,
                                timer_cap: int = 6):
    """Drop-in replacement for the deleted hand twin's factory."""
    return make_shardstore_tx_spec(n_tx, net_cap,
                                   timer_cap).compile()


# ===================================================================
# Part 3 multi-server groups (hand twin: shardstore_multi.py) — the
# per-group Paxos fragment composed onto two replica-group kinds
# ===================================================================

BALLOT_HI = (1 << 12) - 1       # paxos ballots: round*n + idx, 12 bits


def _staged_configs(G: int, n: int, num_shards: int):
    """Run the OBJECT ShardMaster on the staged Join sequence; return
    per-config per-group shard bitmasks (bit s-1 = shard s)."""
    from dslabs_tpu.core.address import LocalAddress
    from dslabs_tpu.labs.shardedstore.shardmaster import Join, Query, \
        ShardMaster

    sm = ShardMaster(num_shards)
    for g in range(1, G + 1):
        sm.execute(Join(g, tuple(
            LocalAddress(f"server{g}-{i}") for i in range(1, n + 1))))
    out = []
    for j in range(G):
        cfg = sm.execute(Query(j))
        masks = {}
        for gid, (_, shards) in cfg.group_info:
            m = 0
            for s in shards:
                m |= 1 << (s - 1)
            masks[gid] = m
        out.append(masks)
    return out


def _gpaxos_fragment(kind: str, base: int, n: int, S: int,
                     cmd_hi: int, exec_effect):
    """The multi-server replicated-log sub-machine carried by ONE
    replica-group kind: ballots, slot log, raw P1b votes, P2b vote
    bitmaps, executed/cleared/gc frontiers — the lab 3 twin's lane
    discipline minus the AMO layer.  Chosen commands execute through
    the ``exec_effect`` callback the including spec supplies (the
    shardstore effect switch), which is what makes the SAME fragment
    body serve both groups: composition carries the consensus machine,
    the spec carries the state-machine-specific effects.

    ``base`` is the group's first GLOBAL node index; quorum reads go
    through the spec-declared QuorumCount named after the kind."""
    e_hi = 3 + (BALLOT_HI << 2) + (cmd_hi << 14)
    bal = (0, BALLOT_HI)
    vote_fields = [SlotField("have", hi=1)]
    for s in range(1, S + 1):
        vote_fields += [SlotField(f"ex{s}", hi=1),
                        SlotField(f"lb{s}", hi=BALLOT_HI),
                        SlotField(f"cmd{s}", hi=cmd_hi),
                        SlotField(f"ch{s}", hi=1)]
    votes = Slots("votes", n, fields=tuple(vote_fields))
    frag = Fragment(
        "gpaxos",
        fields=(
            Field("b", hi=BALLOT_HI), Field("ld", hi=1),
            Field("hd", hi=1), Field("si", init=1, lo=1, hi=S + 1),
            Field("ex", hi=S), Field("cl", hi=S), Field("gc", hi=S),
            Field("pm", hi=(1 << n) - 1),
            Field("peer", size=n, hi=S, index_group=kind),
            Slots("p2bv", S, base=1,
                  fields=(SlotField("v", hi=(1 << n) - 1),)),
            Slots("log", S, base=1, fields=(
                SlotField("ex", hi=1), SlotField("lb", hi=BALLOT_HI),
                SlotField("cmd", hi=cmd_hi), SlotField("ch", hi=1))),
            votes,
        ),
        messages=(
            MessageType("PaxosRequest", ("cmd",),
                        bounds={"cmd": (0, cmd_hi)}),
            MessageType("P1a", ("b",), bounds={"b": bal}),
            MessageType("P1b",
                        ("b",) + tuple(f"e{s}"
                                       for s in range(1, S + 1)),
                        bounds={"b": bal} | {f"e{s}": (0, e_hi)
                                             for s in range(1, S + 1)}),
            MessageType("P2a", ("b", "slot", "cmd"),
                        bounds={"b": bal, "slot": (1, S),
                                "cmd": (0, cmd_hi)}),
            MessageType("P2b", ("b", "slot"),
                        bounds={"b": bal, "slot": (1, S)}),
            MessageType("Heartbeat", ("b", "commit", "gc"),
                        bounds={"b": bal, "commit": (0, S),
                                "gc": (0, S)}),
            MessageType("HeartbeatReply", ("b", "exec"),
                        bounds={"b": bal, "exec": (0, S)}),
        ),
        timers=(
            TimerType("Election", (), min_ms=ELECTION_MIN,
                      max_ms=ELECTION_MAX),
            TimerType("Heartbeat", ("b",), min_ms=HEARTBEAT_MS,
                      max_ms=HEARTBEAT_MS, bounds={"b": bal}),
        ))

    def local(ctx):
        return ctx.node_index() - base

    def pack_entry(ex, lb, cmd, ch):
        return ex | (ch << 1) | (lb << 2) | (cmd << 14)

    def unpack_entry(v):
        return v & 1, (v >> 2) & 0xFFF, v >> 14, (v >> 1) & 1

    def log_get(ctx, slot):
        return (ctx.slot_get("log", "ex", slot),
                ctx.slot_get("log", "lb", slot),
                ctx.slot_get("log", "cmd", slot),
                ctx.slot_get("log", "ch", slot))

    def log_set(ctx, slot, ex, lb, cmd, ch, when=True):
        ctx.slot_put("log", "ex", slot, ex, when=when)
        ctx.slot_put("log", "lb", slot, lb, when=when)
        ctx.slot_put("log", "cmd", slot, cmd, when=when)
        ctx.slot_put("log", "ch", slot, ch, when=when)

    def gc_to(ctx, through, when):
        do = when & (through > ctx.get("cl"))
        ctx.slot_clear_upto("log", through + 1, when=do)
        ctx.put("cl", through, when=do)

    def maybe_gc(ctx, when):
        have_all = ctx.get("pm") == (1 << n) - 1
        peers = ctx.get("peer")
        floor = peers[0]
        for t in range(1, n):
            floor = jnp.minimum(floor, peers[t])
        do = when & have_all & (floor > ctx.get("gc"))
        ctx.put("gc", floor, when=do)
        gc_to(ctx, ctx.get("gc"), do)

    def exec_chain(ctx):
        """_execute_chosen: advance ex through contiguous chosen
        slots, driving the spec's effect per slot; the leader tracks
        its own peer_executed and may GC."""
        for _ in range(S):
            nxt = ctx.get("ex") + 1
            e_ex, _lb, e_cmd, e_ch = log_get(ctx, nxt)
            run = (nxt <= S) & (e_ex == 1) & (e_ch == 1)
            exec_effect(ctx.cond(run), e_cmd)
            ctx.put("ex", nxt, when=run)
        i = local(ctx)
        is_leader = (ctx.get("ld") == 1) & (ctx.get("b") % n == i)
        ctx.put_at("peer", i, ctx.get("ex"), when=is_leader)
        maybe_gc(ctx, is_leader)

    def send_p2a(ctx, slot):
        """Broadcast P2a for log[slot] + inline self-accept/vote."""
        i = local(ctx)
        _ex, _lb, cmd0, _ch = log_get(ctx, slot)
        ballot = ctx.get("b")
        for t in range(n):
            if t != i:
                ctx.send("P2a", to=base + t, b=ballot, slot=slot,
                         cmd=cmd0)
        e_ex, _lb2, e_cmd, e_ch = log_get(ctx, slot)
        write = (slot > ctx.get("cl")) & ~((e_ex == 1) & (e_ch == 1))
        log_set(ctx, slot, 1, ballot, e_cmd, 0, when=write)
        ctx.put("hd", 1)
        v_ex, v_lb, _c, v_ch = log_get(ctx, slot)
        ok = (v_ex == 1) & (v_ch == 0) & (v_lb == ballot)
        ctx.slot_put("p2bv", "v", slot,
                     ctx.slot_get("p2bv", "v", slot) | (1 << i),
                     when=ok)

    def heartbeat_sends(ctx):
        i = local(ctx)
        for t in range(n):
            if t != i:
                ctx.send("Heartbeat", to=base + t, b=ctx.get("b"),
                         commit=ctx.get("ex"), gc=ctx.get("gc"))

    def propose(ctx, cmd, when):
        """Leader proposal with the relay dedup rule: an equal
        in-flight unchosen entry absorbs the request."""
        dup = jnp.asarray(False)
        for s in range(1, S + 1):
            e_ex, _lb, e_cmd, e_ch = log_get(ctx, s)
            dup = dup | ((e_ex == 1) & (e_ch == 0) & (e_cmd == cmd))
        slot = ctx.get("si")
        do = when & ~dup & (slot <= S)
        dctx = ctx.cond(do)
        log_set(dctx, slot, 1, ctx.get("b"), cmd, 0)
        ctx.put("si", slot + 1, when=do)
        send_p2a(dctx, slot)

    def handle_request(ctx, cmd, when, injected):
        """_propose: the leader proposes; a parent-injected request
        forwards ONCE to the believed leader; a peer's forward is
        never re-forwarded."""
        i = local(ctx)
        b = ctx.get("b")
        is_leader = (ctx.get("ld") == 1) & (b % n == i)
        propose(ctx, cmd, when & is_leader)
        if injected:
            believed = b % n
            fwd = when & ~is_leader & (believed != i)
            for t in range(n):
                if t != i:
                    ctx.send("PaxosRequest", to=base + t,
                             when=fwd & (believed == t), cmd=cmd)

    def p1b_win(ctx):
        """Phase-1 victory; ctx is refined to the win condition."""
        i = local(ctx)
        ballot = ctx.get("b")
        ctx.put("ld", 1)
        ctx.put("p2bv.v", 0)
        ctx.put("pm", 1 << i)
        ctx.put("peer",
                jnp.where(jnp.arange(n) == i, ctx.get("ex"), 0))
        for s in range(1, S + 1):
            a_ex = jnp.zeros((), jnp.int32)
            a_b = jnp.full((), -1, jnp.int32)
            a_c = jnp.zeros((), jnp.int32)
            a_ch = jnp.zeros((), jnp.int32)
            for t in range(n):
                have = ctx.slot_get("votes", "have", t)
                ex = ctx.slot_get("votes", f"ex{s}", t)
                vb = ctx.slot_get("votes", f"lb{s}", t)
                vc = ctx.slot_get("votes", f"cmd{s}", t)
                vch = ctx.slot_get("votes", f"ch{s}", t)
                valid = (have == 1) & (ex == 1)
                take = valid & ((vch == 1) & (a_ch == 0)
                                | (a_ch == 0) & ((a_ex == 0)
                                                 | (vb > a_b)))
                a_b = jnp.where(take, vb, a_b)
                a_c = jnp.where(take, vc, a_c)
                a_ch = jnp.where(take, jnp.maximum(a_ch, vch), a_ch)
                a_ex = jnp.where(take, 1, a_ex)
            m_ex, _lb, _c, m_ch = log_get(ctx, s)
            adopt = (a_ex == 1) & (s > ctx.get("cl")) \
                & ~((m_ex == 1) & (m_ch == 1))
            log_set(ctx, s, 1, ballot, a_c, a_ch, when=adopt)
        top = ctx.get("cl")
        for s in range(1, S + 1):
            top = jnp.where(ctx.slot_get("log", "ex", s) == 1, s, top)
        for s in range(1, S + 1):
            in_span = (s > ctx.get("ex")) & (s <= top)
            log_set(ctx, s, 1, ballot, 0, 0,
                    when=in_span
                    & (ctx.slot_get("log", "ex", s) == 0))
            reprop = in_span & (ctx.slot_get("log", "ch", s) == 0)
            send_p2a(ctx.cond(reprop), s)
        ctx.put("si", top + 1)
        exec_chain(ctx)
        ctx.set_timer("Heartbeat", b=ballot)
        heartbeat_sends(ctx)

    # ------------------------------------------------ paxos handlers

    @frag.on("PaxosRequest")
    def srv_preq(ctx, p):
        handle_request(ctx, p["cmd"], jnp.asarray(True),
                       injected=False)

    @frag.on("P1a")
    def srv_p1a(ctx, p):
        mb, frm = p["b"], p["_from"]
        adopt = mb > ctx.get("b")
        ctx.put("b", mb, when=adopt)
        ctx.put("ld", 0, when=adopt)
        ctx.send("P1b", to=frm, when=mb == ctx.get("b"),
                 b=ctx.get("b"),
                 **{f"e{s}": pack_entry(*log_get(ctx, s))
                    for s in range(1, S + 1)})

    @frag.on("P1b")
    def srv_p1b(ctx, p):
        i = local(ctx)
        vb = p["b"]
        frm_i = (p["_from"] - base).clip(0, n - 1)
        accept_vote = (vb == ctx.get("b")) \
            & (ctx.get("b") % n == i) & (ctx.get("ld") == 0)
        ctx.slot_put("votes", "have", frm_i, 1, when=accept_vote)
        for s in range(1, S + 1):
            ex, lb, cmd, ch = unpack_entry(p[f"e{s}"])
            ctx.slot_put("votes", f"ex{s}", frm_i, ex,
                         when=accept_vote)
            ctx.slot_put("votes", f"lb{s}", frm_i, lb,
                         when=accept_vote)
            ctx.slot_put("votes", f"cmd{s}", frm_i, cmd,
                         when=accept_vote)
            ctx.slot_put("votes", f"ch{s}", frm_i, ch,
                         when=accept_vote)
        q = ctx.quorum(kind)
        win = accept_vote & q.met(ctx.get("votes.have"))
        p1b_win(ctx.cond(win))

    @frag.on("P2a")
    def srv_p2a(ctx, p):
        ab, aslot, acmd = p["b"], p["slot"], p["cmd"]
        ok = ab >= ctx.get("b")
        ctx.put("ld", 0, when=ok & (ab > ctx.get("b")))
        ctx.put("b", ab, when=ok)
        ctx.put("hd", 1, when=ok)
        e_ex, _lb, _c, e_ch = log_get(ctx, aslot)
        write = ok & (aslot > ctx.get("cl")) \
            & ~((e_ex == 1) & (e_ch == 1))
        log_set(ctx, aslot, 1, ab, acmd, 0, when=write)
        ctx.send("P2b", to=p["_from"], when=ok, b=ab, slot=aslot)

    @frag.on("P2b")
    def srv_p2b(ctx, p):
        i = local(ctx)
        bb, bslot = p["b"], p["slot"]
        frm_i = (p["_from"] - base).clip(0, n - 1)
        lead_ok = (bb == ctx.get("b")) & (ctx.get("ld") == 1) \
            & (ctx.get("b") % n == i)
        e_ex, e_lb, e_cmd, e_ch = log_get(ctx, bslot)
        count_ok = lead_ok & (e_ex == 1) & (e_ch == 0) & (e_lb == bb)
        vmask = ctx.slot_get("p2bv", "v", bslot)
        vmask2 = jnp.where(count_ok, vmask | (1 << frm_i), vmask)
        q = ctx.quorum(kind)
        chosen_now = count_ok & q.met_bits(vmask2)
        ctx.slot_put("p2bv", "v", bslot,
                     jnp.where(chosen_now, 0, vmask2), when=count_ok)
        log_set(ctx, bslot, 1, e_lb, e_cmd, 1, when=chosen_now)
        exec_chain(ctx.cond(chosen_now))

    @frag.on("Heartbeat")
    def srv_heartbeat(ctx, p):
        hb_b, hb_commit, hb_gc = p["b"], p["commit"], p["gc"]
        ok = hb_b >= ctx.get("b")
        ctx.put("ld", 0, when=ok & (hb_b > ctx.get("b")))
        ctx.put("b", hb_b, when=ok)
        ctx.put("hd", 1, when=ok)
        gc_to(ctx, hb_gc, ok)
        # NO catchup exchange in this lab's alphabet (the object
        # harness runs small windows; decisions re-arrive via P2a).
        ctx.send("HeartbeatReply", to=p["_from"], when=ok,
                 b=ctx.get("b"), exec=ctx.get("ex"))

    @frag.on("HeartbeatReply")
    def srv_heartbeat_reply(ctx, p):
        i = local(ctx)
        frm_i = (p["_from"] - base).clip(0, n - 1)
        ok = (p["b"] == ctx.get("b")) & (ctx.get("ld") == 1) \
            & (ctx.get("b") % n == i)
        pcur = ctx.get_at("peer", frm_i)
        ctx.put_at("peer", frm_i, jnp.maximum(pcur, p["exec"]),
                   when=ok)
        ctx.put("pm", ctx.get("pm") | (1 << frm_i), when=ok)
        maybe_gc(ctx, ok)

    @frag.on_timer("Election")
    def srv_election(ctx, p):
        i = local(ctx)
        b = ctx.get("b")
        is_leader = (ctx.get("ld") == 1) & (b % n == i)
        elect = ~is_leader & (ctx.get("hd") == 0)
        new_ballot = (b // n + 1) * n + i
        ctx.put("b", new_ballot, when=elect)
        ctx.put("ld", 0, when=elect)
        for sf in votes.fields:
            ctx.put(votes.lane(sf.name), 0, when=elect)
        for t in range(n):
            if t != i:
                ctx.send("P1a", to=base + t, when=elect, b=new_ballot)
        # Self-promise: own vote with own log.
        ectx = ctx.cond(elect)
        ectx.slot_put("votes", "have", i, 1)
        for s in range(1, S + 1):
            e_ex, e_lb, e_cmd, e_ch = log_get(ectx, s)
            ectx.slot_put("votes", f"ex{s}", i, e_ex)
            ectx.slot_put("votes", f"lb{s}", i, e_lb)
            ectx.slot_put("votes", f"cmd{s}", i, e_cmd)
            ectx.slot_put("votes", f"ch{s}", i, e_ch)
        ctx.put("hd", 0)
        ctx.set_timer("Election")

    @frag.on_timer("Heartbeat")
    def srv_heartbeat_timer(ctx, p):
        i = local(ctx)
        live = (p["b"] == ctx.get("b")) & (ctx.get("ld") == 1) \
            & (ctx.get("b") % n == i)
        heartbeat_sends(ctx.cond(live))
        for s in range(1, S + 1):
            inflight = live & (s > ctx.get("ex")) \
                & (s < ctx.get("si")) \
                & (ctx.slot_get("log", "ex", s) == 1) \
                & (ctx.slot_get("log", "ch", s) == 0)
            send_p2a(ctx.cond(inflight), s)
        ctx.set_timer("Heartbeat", when=live, b=p["b"])

    return frag, handle_request


def make_shardstore_multi_spec(n_groups: int = 2, n: int = 3,
                               num_shards: int = 10, w: int = 1,
                               net_cap: int = 48,
                               timer_cap: int = 6) -> ProtocolSpec:
    """Lab 4 with MULTI-SERVER replica groups: G groups of n
    Paxos-replicated ShardStoreServers, one frozen shard master, one
    client.  Each group kind composes the ``gpaxos`` fragment; chosen
    commands drive the shardstore effect switch the spec supplies.
    See the hand twin's docstring (tests/fixtures/hand_twins/
    shardstore_multi.py) for the command alphabet and the G == 2
    scope bound; handlers mirror it block for block."""
    from dslabs_tpu.labs.shardedstore.shardstore import key_to_shard

    G, NC, W = n_groups, 1, w
    assert G == 2, "scope bound: one handoff edge (hand twin docstring)"
    S = 2 + W + 2
    CFG = _staged_configs(G, n, num_shards)
    NCMD = NC * W
    CMD_NC0 = NCMD + 1
    CMD_IS0 = CMD_NC0 + G
    CMD_MD = CMD_IS0 + NC * W + 1
    N_CMDS = CMD_MD + 1
    cmd_hi = N_CMDS - 1
    put_shard = [key_to_shard(f"key-{k}", num_shards)
                 for k in range(1, W + 1)]
    put_mask = [1 << (s - 1) for s in put_shard]
    MOVE_MASK = CFG[0][1] & ~CFG[1][1]
    SHMASK = (1 << num_shards) - 1
    CLIENT = 1 + G * n

    def srv(g, i):
        return 1 + g * n + i            # g, i 0-based

    def group_mask(g, cfg_idx):
        vals = jnp.asarray([CFG[j].get(g + 1, 0) for j in range(G)],
                           jnp.int32)
        oh = jnp.arange(G) == cfg_idx
        return jnp.sum(jnp.where(oh, vals, 0))

    master = NodeKind("master", 1, (
        Field("mc", init=G),
        Field("mamo", size=1 + G * n),
    ))
    gkinds = [NodeKind(f"g{g + 1}", n, (
        Field("scfg", hi=G),
        Field("own", hi=SHMASK), Field("inc", hi=SHMASK),
        Field("outf", hi=1), Field("osamo", hi=W),
        Field("samo", hi=W), Field("qseq"),
    )) for g in range(G)]
    client = NodeKind("client", 1, (
        Field("k", init=1, hi=W + 1),
        Field("cfg", hi=G),
        Field("cq", init=2),
    ))

    messages = [
        MessageType("Query", ("seq", "arg"), bounds={"arg": (-1, G)}),
        MessageType("QueryReply", ("seq", "kind"),
                    bounds={"kind": (0, G - 1)}),
        MessageType("ShardStoreRequest", ("k",), bounds={"k": (1, W)}),
        MessageType("ShardStoreReply", ("k",), bounds={"k": (1, W)}),
        MessageType("WrongGroup", ("k",), bounds={"k": (1, W)}),
    ]
    timers = [
        TimerType("Election", (), min_ms=ELECTION_MIN,
                  max_ms=ELECTION_MAX),
        TimerType("Heartbeat", ("b",), min_ms=HEARTBEAT_MS,
                  max_ms=HEARTBEAT_MS, bounds={"b": (0, BALLOT_HI)}),
        TimerType("Query", (), min_ms=QUERY_MS, max_ms=QUERY_MS),
        TimerType("Client", ("k",), min_ms=CLIENT_MS, max_ms=CLIENT_MS,
                  bounds={"k": (1, W)}),
    ]

    spec = ProtocolSpec(
        name=f"shardstore-multi-g{G}x{n}-w{W}",
        nodes=[master] + gkinds + [client],
        messages=messages, timers=timers,
        net_cap=net_cap, timer_cap=timer_cap,
        quorums=tuple(QuorumCount(f"g{g + 1}", over=f"g{g + 1}",
                                  threshold="majority")
                      for g in range(G)),
        max_live_sends=32)

    # ---- per-group effect switch + fragment composition -------------

    def make_group(gi):
        kname = f"g{gi + 1}"
        base = 1 + gi * n

        def reconfig_done(ctx):
            return (ctx.get("inc") == 0) & (ctx.get("outf") == 0)

        def exec_effect(ctx, cmd):
            """handle_PaxosDecision's switch for one executed command;
            ctx is refined to the exec condition."""
            i = ctx.node_index() - base
            is_leader = (ctx.get("ld") == 1) & (ctx.get("b") % n == i)

            # NewConfig(j) (_apply_new_config)
            j = cmd - CMD_NC0
            nc_ok = (cmd >= CMD_NC0) & (cmd < CMD_NC0 + G) \
                & (j == ctx.get("scfg")) & reconfig_done(ctx)
            mine_new = group_mask(gi, j)
            first = ctx.get("scfg") == 0
            own = ctx.get("own")
            lost = own & ~mine_new
            gained = mine_new & ~own
            ctx.put("own", jnp.where(first, mine_new, own & ~lost),
                    when=nc_ok)
            ctx.put("inc", gained, when=nc_ok & ~first)
            has_out = nc_ok & ~first & (lost != 0)
            ctx.put("outf", 1, when=has_out)
            ctx.put("osamo", ctx.get("samo"), when=has_out)
            ctx.put("scfg", j + 1, when=nc_ok)
            if gi == 0:
                # executing leader: _send_moves (only edge: g1 -> g2)
                move = has_out & is_leader
                for t in range(n):
                    ctx.send("ShardMove", to=srv(1, t), when=move,
                             g=1, v=ctx.get("samo"))

            # client command (_execute_client_command)
            cl_ok = (cmd >= 1) & (cmd <= NCMD)
            have_cfg = ctx.get("scfg") > 0
            cmask = jnp.sum(jnp.where(
                jnp.arange(W) == (cmd - 1) % W,
                jnp.asarray(put_mask, jnp.int32), 0))
            mine = group_mask(gi, ctx.get("scfg") - 1)
            in_mine = (cmask & mine) == cmask
            wrong = cl_ok & have_cfg & ~in_mine
            ctx.send("WrongGroup", to=CLIENT, when=wrong,
                     k=(cmd - 1) % W + 1)
            owned_now = (cmask & ctx.get("own")) == cmask
            do = cl_ok & have_cfg & in_mine & owned_now
            seq = (cmd - 1) % W + 1
            ctx.put("samo", jnp.maximum(ctx.get("samo"), seq),
                    when=do)
            ctx.send("ShardStoreReply", to=CLIENT, when=do, k=seq)

            # InstallShards (_apply_install); only g2 receives it
            if gi == 1:
                v = cmd - CMD_IS0
                is_ok = (cmd >= CMD_IS0) \
                    & (cmd < CMD_IS0 + NC * W + 1) \
                    & (ctx.get("scfg") == 2) \
                    & ((MOVE_MASK & ctx.get("inc")) == MOVE_MASK)
                ctx.put("own", ctx.get("own") | MOVE_MASK, when=is_ok)
                ctx.put("inc", ctx.get("inc") & ~MOVE_MASK,
                        when=is_ok)
                ctx.put("samo", jnp.maximum(ctx.get("samo"), v),
                        when=is_ok)
                ack = is_ok & is_leader
                for t in range(n):
                    ctx.send("ShardMoveAck", to=srv(0, t), when=ack,
                             g=1)

            # MoveDone
            ctx.put("outf", 0, when=cmd == CMD_MD)

        frag, handle_request = _gpaxos_fragment(
            kname, base, n, S, cmd_hi, exec_effect)
        spec.include(kname, frag)

        # ---- store-layer wiring (QueryReply/SSREQ/SM/SMACK inject
        # commands into the group log; QueryTimer is leader-gated)

        @spec.on(kname, "QueryReply")
        def s_query_reply(ctx, p):
            want = (p["kind"] == ctx.get("scfg")) & reconfig_done(ctx)
            handle_request(ctx, CMD_NC0 + p["kind"], want,
                           injected=True)

        @spec.on(kname, "ShardStoreRequest")
        def s_ssreq(ctx, p):
            handle_request(ctx, p["k"], jnp.asarray(True),
                           injected=True)

        if gi == 1:
            @spec.on(kname, "ShardMove")
            def s_shard_move(ctx, p):
                sm_ok = ctx.get("scfg") == 2
                handle_request(ctx, CMD_IS0 + p["v"], sm_ok,
                               injected=True)
        else:
            @spec.on(kname, "ShardMoveAck")
            def s_shard_move_ack(ctx, p):
                sa_ok = ctx.get("outf") == 1
                handle_request(ctx, CMD_MD, sa_ok, injected=True)

        @spec.on_timer(kname, "Query")
        def s_query_timer(ctx, p):
            i = ctx.node_index() - base
            is_leader = (ctx.get("ld") == 1) \
                & (ctx.get("b") % n == i)
            q_ok = is_leader & (reconfig_done(ctx)
                                | (ctx.get("scfg") == 0))
            ctx.put("qseq", ctx.get("qseq") + 1, when=q_ok)
            ctx.send("Query", to=0, when=q_ok, seq=ctx.get("qseq"),
                     arg=ctx.get("scfg"))
            if gi == 0:
                resend = is_leader & (ctx.get("outf") == 1) \
                    & (ctx.get("scfg") == 2)
                for t in range(n):
                    ctx.send("ShardMove", to=srv(1, t), when=resend,
                             g=1, v=ctx.get("osamo"))
            ctx.set_timer("Query")

    for gi in range(G):
        make_group(gi)

    # the handoff WIRE types merge last so the tag order matches the
    # hand twin's enum (SM, SMACK after the paxos tags)
    spec.include("g1", Fragment("handoff-wire", messages=(
        MessageType("ShardMove", ("g", "v"),
                    bounds={"g": (1, 1), "v": (0, NC * W)}),
        MessageType("ShardMoveAck", ("g",), bounds={"g": (1, 1)}),
    )))

    # ---------------- master (collapsed lone ShardMaster paxos)

    @spec.on("master", "Query")
    def m_query(ctx, p):
        frm = p["_from"]
        qseq, arg = p["seq"], p["arg"]
        idx = jnp.where(frm == CLIENT, 0, frm)
        cur = ctx.get_at("mamo", idx)
        fresh = qseq > cur
        ctx.put("mc", ctx.get("mc") + 1, when=fresh)
        ctx.put_at("mamo", idx, qseq, when=fresh)
        kind = jnp.where((arg < 0) | (arg >= G), G - 1,
                         arg).astype(jnp.int32)
        ctx.send("QueryReply", to=frm, when=qseq >= cur, seq=qseq,
                 kind=kind)

    # ---------------- client (ShardStoreClient)

    def client_send_pending(ctx, cond):
        """_send_pending: broadcast SSREQ(k) to every server of the
        owning group under the client's known config."""
        k = ctx.get("k")
        kmask = jnp.sum(jnp.where(jnp.arange(W) == (k - 1) % W,
                                  jnp.asarray(put_mask, jnp.int32), 0))
        ccfg = ctx.get("cfg")
        for g in range(G):
            gm = group_mask(g, ccfg - 1)
            owns = (kmask & gm) == kmask
            for i in range(n):
                ctx.send("ShardStoreRequest", to=srv(g, i),
                         when=cond & owns & (ccfg > 0), k=k)

    @spec.on("client", "QueryReply")
    def c_query_reply(ctx, p):
        newer = p["kind"] + 1 > ctx.get("cfg")
        ctx.put("cfg", p["kind"] + 1, when=newer)
        client_send_pending(ctx, newer & (ctx.get("k") <= W))

    @spec.on("client", "ShardStoreReply")
    def c_ssrep(ctx, p):
        k = ctx.get("k")
        match = (p["k"] == k) & (k <= W)
        ctx.put("k", k + 1, when=match)

    @spec.on("client", "WrongGroup")
    def c_wrong_group(ctx, p):
        k = ctx.get("k")
        is_wg = (p["k"] == k) & (k <= W)
        cq = ctx.get("cq")
        ctx.put("cq", cq + 1, when=is_wg)
        ctx.send("Query", to=0, when=is_wg, seq=cq + 1, arg=-1)

    @spec.on_timer("client", "Client")
    def c_timer(ctx, p):
        k = ctx.get("k")
        live = (p["k"] == k) & (k <= W)
        cq = ctx.get("cq")
        ctx.put("cq", cq + 1, when=live)
        ctx.send("Query", to=0, when=live, seq=cq + 1, arg=-1)
        no_cfg = ctx.get("cfg") == 0
        ctx.put("cq", ctx.get("cq") + 1, when=live & no_cfg)
        ctx.send("Query", to=0, when=live & no_cfg, seq=cq + 2,
                 arg=-1)
        client_send_pending(ctx, live & ~no_cfg)
        ctx.set_timer("Client", when=live, k=k)

    # -------------------------------------------- initials/predicates

    for s in (1, 2):
        spec.initial_messages.append(
            ("Query", CLIENT, 0, {"seq": s, "arg": -1}))
    for g in range(G):
        for i in range(n):
            # server init: paxos Election, then QueryTimer (the first
            # heartbeat arms on phase-1 victory).
            spec.initial_timers.append(("Election", srv(g, i), {}))
            spec.initial_timers.append(("Query", srv(g, i), {}))
    spec.initial_timers.append(("Client", CLIENT, {"k": 1}))

    def clients_done(view):
        return view.get("client", 0, "k") == W + 1

    spec.goals["CLIENTS_DONE"] = clients_done
    return spec


def make_shardstore_multi_protocol(n_groups: int = 2, n: int = 3,
                                   num_shards: int = 10, w: int = 1,
                                   net_cap: int = 48,
                                   timer_cap: int = 6):
    """Drop-in replacement for the deleted hand twin's factory."""
    return make_shardstore_multi_spec(
        n_groups, n, num_shards, w, net_cap, timer_cap).compile()
