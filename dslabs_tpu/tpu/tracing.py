"""End-to-end causal tracing + per-tenant cost accounting (ISSUE 13).

The service (dslabs_tpu/service/) runs every job as a warden child with
its own run dir, and the telemetry layer records spans per process —
but before this module no artifact connected them: a tenant's "why was
my verdict slow?" required hand-correlating the journal queue,
SERVER_STATUS.json, the warden's heartbeat pipe, and each child's
flight.jsonl.  This module is the missing connective tissue, in two
halves:

* **Trace/span-ID discipline.**  ``submit`` mints a :func:`mint_trace_id`
  that the journal queue persists on the job record, the scheduler
  stamps onto every journal event, and the warden passes to children
  via env (``DSLABS_TRACE_ID`` / ``DSLABS_PARENT_SPAN``).  The
  telemetry recorder (tpu/telemetry.py) picks the pair up from env, so
  every flight-recorder span and STATUS.json carries the trace — and
  because the flight recorder's begin markers land BEFORE each device
  call, the causal tree survives SIGKILL: a child killed mid-level
  leaves its in-flight dispatch attributable from disk alone.

* **The trace assembler** (:func:`assemble`, CLI ``python -m
  dslabs_tpu.tpu.telemetry trace``): stitches the journal +
  SERVER_STATUS + per-job flight logs FROM DISK ALONE into one causal
  tree per job — submit -> queue-wait -> admission -> per-attempt
  warden children -> compile -> per-level search -> verdict, with
  knob-shrink / mesh-shrink re-levels and the in-flight dispatch of a
  torn tail as first-class nodes — rendered as a timeline
  (:func:`render_trace`) or exported as Chrome/Perfetto trace-event
  JSON (:func:`to_perfetto`).

* **The cost meter** (:class:`CostMeter`): per-tenant cost accounting
  fed from the span/level records the runs already wrote — device
  seconds by dispatch site, dispatch counts, states explored/unique,
  the compile-vs-search wall split, retries/failovers burned — at ZERO
  added device dispatches (everything is host-side file reading of
  artifacts that already exist; the overhead-guard test pins it).
  Records append to ``COSTS.jsonl`` beside the journal (line-buffered,
  torn-tail-tolerant — the flight-recorder discipline) and surface in
  SERVER_STATUS.json per-tenant ledgers, the bench ``--service``
  phase, and ``telemetry compare`` (cost-per-unique-state regression
  flagging).

Pure host-side Python + stdlib — importing this module never imports
jax; the telemetry module is imported lazily (it is the lower layer).
"""

from __future__ import annotations

import binascii
import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

__all__ = ["TRACE_ENV", "PARENT_ENV", "COSTS_NAME", "mint_trace_id",
           "new_span_id", "current_trace", "child_trace_env",
           "attempt_span_id", "read_flight_lax", "segment_flight",
           "load_json_tolerant", "CostMeter", "assemble",
           "render_trace", "to_perfetto", "main"]

# The propagation contract (docs/observability.md): the service sets
# both on every warden launch, the warden forwards them to its
# children, and Telemetry reads them at construction — one env pair
# threads the whole process tree.
TRACE_ENV = "DSLABS_TRACE_ID"
PARENT_ENV = "DSLABS_PARENT_SPAN"

# Per-server append-only cost ledger, beside the journal (the name is
# also the run-dir-layout "costs" entry — tpu/checkpoint.py).
COSTS_NAME = "COSTS.jsonl"


def mint_trace_id() -> str:
    """A fresh 16-hex-char trace id (random, host-side — ids only need
    to be unique within a service root, not globally)."""
    return binascii.hexlify(os.urandom(8)).decode()


def new_span_id() -> str:
    """A fresh 8-hex-char span id (one per recorder / child run)."""
    return binascii.hexlify(os.urandom(4)).decode()


def current_trace(env: Optional[dict] = None) -> Tuple[Optional[str],
                                                       Optional[str]]:
    """The (trace_id, parent_span) this process inherited via env, or
    (None, None) outside any trace."""
    e = os.environ if env is None else env
    return (e.get(TRACE_ENV) or None, e.get(PARENT_ENV) or None)


def child_trace_env(trace_id: Optional[str],
                    parent_span: Optional[str]) -> dict:
    """The env additions that thread a trace into a child process."""
    env = {}
    if trace_id:
        env[TRACE_ENV] = trace_id
    if parent_span:
        env[PARENT_ENV] = parent_span
    return env


def attempt_span_id(job_id: str, attempt: int) -> str:
    """The DETERMINISTIC span id of one scheduler attempt — derivable
    from the journal's ``start`` record alone, so the assembler can
    link a child's ``meta.parent_span`` back to the attempt that
    spawned it without any extra journal field."""
    return f"{job_id}:a{int(attempt)}"


# ------------------------------------------------------ tolerant readers

def read_flight_lax(path: str) -> Tuple[List[dict], int]:
    """Parse a JSONL artifact SKIPPING unparsable lines instead of
    raising on a mid-file torn line.  The strict reader
    (telemetry.read_flight) is right for single-writer logs; a
    per-JOB flight log is appended to by EVERY child of every attempt,
    so a SIGKILL'd first child can leave its torn line mid-file with a
    second child's records after it.  Returns ``(records, n_torn)`` —
    the torn count stays attributable in the assembled trace."""
    records: List[dict] = []
    torn = 0
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except OSError:
        return [], 0
    for line in lines:
        if not line.strip():
            continue
        try:
            records.append(json.loads(line))
        except ValueError:
            torn += 1
    return records, torn


def load_json_tolerant(path: Optional[str]) -> Optional[dict]:
    """Read one JSON file tolerating a mid-write snapshot (the
    tmp+replace race: a reader can open the path between the open and
    the replace, or catch a half-written ``.tmp`` handed to it
    directly).  Never raises — None means "no usable snapshot"."""
    if not path:
        return None
    try:
        with open(path) as f:
            data = f.read()
    except OSError:
        return None
    try:
        out = json.loads(data)
    except ValueError:
        return None
    return out if isinstance(out, dict) else None


def segment_flight(records: List[dict]) -> List[dict]:
    """Split one per-job flight log into CHILD SEGMENTS at its ``meta``
    records (every recorder writes one at construction).  Per-engine
    dispatch indices restart in every child, so span/begin matching is
    only meaningful within a segment.  Each segment carries its own
    in-flight dispatch: a begin marker with no matching span means the
    child died (or is wedged) inside that device call."""
    segments: List[dict] = []
    cur: Optional[dict] = None
    for rec in records:
        if rec.get("t") == "meta":
            cur = {"meta": rec, "records": []}
            segments.append(cur)
            continue
        if cur is None:                  # pre-meta stray (old log): bucket
            cur = {"meta": {}, "records": []}
            segments.append(cur)
        cur["records"].append(rec)
    for seg in segments:
        spans = [r for r in seg["records"] if r.get("t") == "span"]
        done = {(s.get("tag"), s.get("i")) for s in spans}
        open_d = None
        for r in seg["records"]:
            if (r.get("t") == "dispatch"
                    and (r.get("tag"), r.get("i")) not in done):
                open_d = r
        seg["spans"] = spans
        seg["in_flight"] = open_d
    return segments


# ------------------------------------------------------------ cost meter

def _blank_tenant() -> dict:
    return {"jobs": 0, "completed": 0, "failed": 0, "explored": 0,
            "unique": 0, "device_secs": 0.0, "dispatches": 0,
            "compile_secs": 0.0, "search_secs": 0.0, "retries": 0,
            "failovers": 0, "budget_spent": 0.0,
            "cost_per_unique": None, "dispatches_per_job": None}


class CostMeter:
    """The per-tenant cost ledger.  :meth:`charge` turns one finished
    job (its verdict dict + its run dir's flight log) into an
    append-only ``COSTS.jsonl`` record and the in-memory per-tenant
    aggregate; everything it reads already exists on disk or in the
    verdict — zero added device dispatches, zero added transfers.

    A restarted server replays the existing ledger at construction, so
    per-tenant totals survive the process the same way the journal
    does.  Thread-safe (drain workers charge concurrently); the
    append is line-buffered (one write per record — a SIGKILL leaves
    at most one torn tail line, which the reader skips)."""

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self._lock = threading.Lock()
        self._fh = None
        self.error: Optional[str] = None
        self.records: List[dict] = []
        if path and os.path.exists(path):
            self.records, _ = read_flight_lax(path)
            self.records = [r for r in self.records
                            if r.get("t") == "cost"]
        if path:
            try:
                d = os.path.dirname(os.path.abspath(path))
                os.makedirs(d, exist_ok=True)
                self._fh = open(path, "a", buffering=1)
            except OSError as e:
                # Read-only root: RAM-only accounting, attributable —
                # the telemetry degradation convention.
                self.error = f"{type(e).__name__}: {e}"
                self._fh = None

    # ------------------------------------------------------------- charge

    @staticmethod
    def flight_costs(flight_log: Optional[str]) -> dict:
        """Device-time accounting off one run dir's flight log:
        per-site device seconds, dispatch count, absorbed retries, and
        the compile-vs-search wall split (explicit AOT compile from
        the engines' ``compile`` events + outcome records; implicit
        first-dispatch compile from the first span per site per child
        segment).  Pure file reading — the spans were already paid
        for."""
        out = {"device_secs": 0.0, "device_secs_by_site": {},
               "dispatches": 0, "retries": 0, "aot_compile_secs": 0.0,
               "first_dispatch_secs": 0.0, "compile_secs": 0.0,
               "search_secs": 0.0, "levels": 0, "torn_lines": 0}
        if not flight_log:
            return out
        records, torn = read_flight_lax(flight_log)
        out["torn_lines"] = torn
        for seg in segment_flight(records):
            first_seen = set()
            for r in seg["records"]:
                t = r.get("t")
                if t == "span":
                    wall = float(r.get("wall", 0.0) or 0.0)
                    tag = r.get("tag", "?")
                    out["device_secs"] += wall
                    out["device_secs_by_site"][tag] = round(
                        out["device_secs_by_site"].get(tag, 0.0) + wall,
                        6)
                    out["dispatches"] += 1
                    out["retries"] += int(r.get("retries", 0) or 0)
                    if tag not in first_seen:
                        first_seen.add(tag)
                        out["first_dispatch_secs"] += wall
                elif t == "level":
                    out["levels"] += 1
                elif t == "outcome":
                    out["aot_compile_secs"] += float(
                        r.get("compile_secs", 0.0) or 0.0)
                elif (t == "event" and r.get("kind") == "compile"):
                    # The engines' explicit AOT warm-up events — only
                    # counted when no outcome record carried the same
                    # seconds (a completed child reports both).
                    pass
        out["device_secs"] = round(out["device_secs"], 6)
        out["first_dispatch_secs"] = round(out["first_dispatch_secs"], 6)
        out["aot_compile_secs"] = round(out["aot_compile_secs"], 6)
        out["compile_secs"] = round(
            out["aot_compile_secs"] + out["first_dispatch_secs"], 6)
        out["search_secs"] = round(
            max(0.0, out["device_secs"] - out["first_dispatch_secs"]), 6)
        return out

    def charge(self, verdict: dict,
               flight_log: Optional[str] = None) -> dict:
        """Account one finished job.  ``verdict`` is the structured
        result ``CheckServer.run_job`` returns (done OR failed); the
        explored/unique/depth counters are copied EXACTLY from it, so
        per-tenant ledger sums always agree with the jobs'
        SearchOutcome counters (pinned by test).

        A lane-batch job (ISSUE 14, tpu/lanes.py) carries
        ``lane_share`` — its fraction of the batch's SHARED dispatch
        stream (shares of a batch sum to 1.0) — and ``flight_log`` is
        the batch's: the device-time numbers are scaled by the share
        so a shared dispatch is billed exactly once across the batch,
        and per-tenant bills DROP as batching improves."""
        fc = self.flight_costs(flight_log)
        share = verdict.get("lane_share")
        if share is not None:
            share = max(0.0, min(1.0, float(share)))
            for k in ("device_secs", "compile_secs", "search_secs",
                      "first_dispatch_secs", "aot_compile_secs"):
                fc[k] = round(fc[k] * share, 6)
            fc["dispatches"] = round(fc["dispatches"] * share, 3)
            fc["device_secs_by_site"] = {
                t: round(v * share, 6)
                for t, v in fc["device_secs_by_site"].items()}
        rec = {
            "t": "cost", "ts": round(time.time(), 3),
            "job_id": verdict.get("job_id"),
            "tenant": verdict.get("tenant"),
            "trace_id": verdict.get("trace_id"),
            "status": verdict.get("status"),
            "end": verdict.get("end"),
            "explored": int(verdict.get("explored", 0) or 0),
            "unique": int(verdict.get("unique", 0) or 0),
            "depth": int(verdict.get("depth", 0) or 0),
            "attempts": int(verdict.get("attempts", 1) or 1),
            "failovers": len(verdict.get("deaths") or ()),
            "budget_units": float(verdict.get("budget_units", 0.0)
                                  or 0.0),
            "elapsed_secs": float(verdict.get("elapsed_secs", 0.0)
                                  or 0.0),
            **{k: fc[k] for k in (
                "device_secs", "device_secs_by_site", "dispatches",
                "retries", "compile_secs", "search_secs", "levels")},
        }
        if share is not None:
            rec["lane_share"] = share
            rec["lanes"] = verdict.get("lanes")
        rec["cost_per_unique"] = (
            round(rec["device_secs"] / rec["unique"], 9)
            if rec["unique"] > 0 else None)
        with self._lock:
            self.records.append(rec)
            if self._fh is not None:
                try:
                    self._fh.write(json.dumps(rec) + "\n")
                except (OSError, ValueError) as e:
                    self.error = f"{type(e).__name__}: {e}"
                    self._fh = None
        return rec

    # ---------------------------------------------------------- summaries

    def tenant_summary(self) -> Dict[str, dict]:
        """Per-tenant ledger totals (the SERVER_STATUS.json ``costs``
        block): explored/unique sums, device seconds, dispatch count,
        compile-vs-search split, retries/failovers burned, and
        cost-per-unique-state (device seconds per unique state — the
        number ``telemetry compare`` tracks)."""
        with self._lock:
            records = list(self.records)
        return aggregate_costs(records)

    def totals(self) -> dict:
        """Cross-tenant totals + the headline ``cost_per_unique``."""
        per = self.tenant_summary()
        out = _blank_tenant()
        for s in per.values():
            for k in out:
                if k in ("cost_per_unique", "dispatches_per_job"):
                    continue
                out[k] = out[k] + s[k]
        out["cost_per_unique"] = (
            round(out["device_secs"] / out["unique"], 9)
            if out["unique"] > 0 else None)
        out["dispatches_per_job"] = (
            round(out["dispatches"] / out["jobs"], 3)
            if out["jobs"] > 0 else None)
        for k in ("device_secs", "compile_secs", "search_secs",
                  "budget_spent"):
            out[k] = round(out[k], 6)
        return out

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None


def aggregate_costs(records: List[dict]) -> Dict[str, dict]:
    """Fold cost records (e.g. a ``COSTS.jsonl`` read back with
    :func:`read_flight_lax`) into per-tenant totals."""
    out: Dict[str, dict] = {}
    for r in records:
        if r.get("t") != "cost":
            continue
        s = out.setdefault(str(r.get("tenant")), _blank_tenant())
        s["jobs"] += 1
        s["completed"] += 1 if r.get("status") == "done" else 0
        s["failed"] += 1 if r.get("status") != "done" else 0
        s["explored"] += int(r.get("explored", 0) or 0)
        s["unique"] += int(r.get("unique", 0) or 0)
        s["device_secs"] = round(
            s["device_secs"] + float(r.get("device_secs", 0.0) or 0.0),
            6)
        # Lane-batch records carry share-scaled FRACTIONAL dispatch
        # counts (tpu/lanes.py) — keep the float, the per-job mean is
        # the amortisation headline.
        s["dispatches"] = round(
            s["dispatches"] + float(r.get("dispatches", 0) or 0), 3)
        s["compile_secs"] = round(
            s["compile_secs"] + float(r.get("compile_secs", 0.0)
                                      or 0.0), 6)
        s["search_secs"] = round(
            s["search_secs"] + float(r.get("search_secs", 0.0) or 0.0),
            6)
        s["retries"] += int(r.get("retries", 0) or 0)
        s["failovers"] += int(r.get("failovers", 0) or 0)
        s["budget_spent"] = round(
            s["budget_spent"] + float(r.get("budget_units", 0.0)
                                      or 0.0), 6)
    for s in out.values():
        s["cost_per_unique"] = (
            round(s["device_secs"] / s["unique"], 9)
            if s["unique"] > 0 else None)
        # The lane-amortisation headline (ISSUE 14): mean dispatches
        # billed per job — batching drives this DOWN (`telemetry
        # compare` flags a rise as a regression).
        s["dispatches_per_job"] = (
            round(s["dispatches"] / s["jobs"], 3)
            if s["jobs"] > 0 else None)
    return out


# ------------------------------------------------------------- assembler

def _is_server_dir(path: str) -> bool:
    return os.path.isdir(path) and os.path.exists(
        os.path.join(path, "journal.jsonl"))


def _abs_ts(meta: dict, rel: float) -> Optional[float]:
    started = meta.get("started")
    if started is None:
        return None
    return float(started) + float(rel or 0.0)


def _segment_nodes(seg: dict, parent: str, prefix: str,
                   nodes: List[dict],
                   known: Optional[set] = None) -> dict:
    """One child segment -> trace nodes (run span + compile + levels +
    re-level events + the in-flight dispatch).  Returns the segment's
    phase totals {compile_secs, search_secs}.  The child's announced
    ``parent_span`` wins only when it names a node the assembler knows
    (``known``) — an env-inherited parent from OUTSIDE this trace tree
    falls back to ``parent`` so the chain never dangles."""
    meta = seg["meta"]
    run_id = meta.get("span_id") or f"{prefix}:run"
    run_parent = meta.get("parent_span")
    if not run_parent or (known is not None and run_parent not in known):
        run_parent = parent
    t0 = meta.get("started")
    recs = seg["records"]
    t1 = None
    if recs:
        t1 = _abs_ts(meta, max(float(r.get("ts", 0.0) or 0.0)
                               for r in recs))
    nodes.append({"span_id": run_id, "parent": run_parent,
                  "kind": "run", "name": meta.get("hint") or "run",
                  "pid": meta.get("pid"), "t0": t0, "t1": t1,
                  "trace_id": meta.get("trace_id")})
    compile_secs = 0.0
    search_secs = 0.0
    first_seen = set()
    n_level = 0
    for r in recs:
        t = r.get("t")
        ts = _abs_ts(meta, r.get("ts", 0.0))
        if t == "span":
            tag = r.get("tag", "?")
            if tag not in first_seen:
                # The first dispatch at a tag pays the (implicit) XLA
                # compile — the same attribution rule the report CLI's
                # compile-vs-search wall split uses.
                first_seen.add(tag)
                compile_secs += float(r.get("wall", 0.0) or 0.0)
        elif t == "level":
            n_level += 1
            wall = float(r.get("wall", 0.0) or 0.0)
            search_secs += wall
            nodes.append({
                "span_id": f"{run_id}:d{r.get('depth', n_level)}",
                "parent": run_id, "kind": "level",
                "name": f"level d{r.get('depth', '?')}",
                "t0": (ts - wall) if ts is not None else None,
                "t1": ts, "wall": wall,
                "engine": r.get("engine"),
                "explored": r.get("explored"),
                "unique": r.get("unique")})
        elif t == "event":
            kind = r.get("kind")
            if kind == "compile":
                compile_secs += float(r.get("secs", 0.0) or 0.0)
                nodes.append({
                    "span_id": f"{run_id}:compile", "parent": run_id,
                    "kind": "compile", "name": "aot compile",
                    "t0": (ts - float(r.get("secs", 0.0) or 0.0))
                    if ts is not None else None,
                    "t1": ts, "wall": r.get("secs"),
                    "engine": r.get("engine")})
            elif kind in ("rung", "mesh_shrunk", "knobs_shrunk",
                          "capacity_retry", "failover", "retry",
                          "wedged"):
                nodes.append({
                    "span_id": f"{run_id}:{kind}:{len(nodes)}",
                    "parent": run_id, "kind": "event", "name": kind,
                    "t0": ts, "t1": ts,
                    "detail": {k: v for k, v in r.items()
                               if k not in ("t", "ts", "kind",
                                            "trace")}})
        elif t == "outcome":
            nodes.append({
                "span_id": f"{run_id}:outcome", "parent": run_id,
                "kind": "outcome", "name": r.get("end_condition"),
                "t0": ts, "t1": ts,
                "engine": r.get("engine"),
                "explored": r.get("states_explored"),
                "unique": r.get("unique_states"),
                "compile_secs": r.get("compile_secs")})
    if seg["in_flight"] is not None:
        r = seg["in_flight"]
        ts = _abs_ts(meta, r.get("ts", 0.0))
        nodes.append({
            "span_id": f"{run_id}:inflight", "parent": run_id,
            "kind": "in_flight",
            "name": f"{r.get('tag')} i={r.get('i')}",
            "t0": ts, "t1": None, "tag": r.get("tag"),
            "i": r.get("i"), "depth": r.get("depth")})
    return {"compile_secs": compile_secs, "search_secs": search_secs}


def _assemble_job(root: str, rec: dict, journal: List[dict]) -> dict:
    """One journal job record + its run dir -> the causal tree."""
    job = rec["job"]
    job_id = job.get("job_id")
    trace_id = job.get("trace_id")
    submitted = float(job.get("submitted_at") or 0.0) or None
    starts = [r for r in journal
              if r.get("t") == "start" and r.get("job_id") == job_id]
    finish = next((r for r in journal
                   if r.get("t") in ("done", "failed")
                   and r.get("job_id") == job_id), None)
    admission = next((r for r in journal
                      if r.get("t") == "admission"
                      and trace_id
                      and r.get("trace_id") == trace_id), None)
    nodes: List[dict] = [{
        "span_id": trace_id or job_id, "parent": None,
        "kind": "submit", "name": f"submit {job_id}",
        "tenant": job.get("tenant"), "t0": submitted,
        "t1": submitted}]
    root_id = nodes[0]["span_id"]
    first_start = (float(starts[0]["ts"])
                   if starts and starts[0].get("ts") is not None
                   else None)
    queue_wait = (first_start - submitted
                  if first_start is not None and submitted is not None
                  else None)
    nodes.append({"span_id": f"{job_id}:queue", "parent": root_id,
                  "kind": "queue", "name": "queue-wait",
                  "t0": submitted, "t1": first_start,
                  "wall": queue_wait})
    adm_secs = 0.0
    if admission is not None:
        adm_secs = float(admission.get("secs", 0.0) or 0.0)
        adm_ts = admission.get("ts")
        nodes.append({
            "span_id": f"{job_id}:admission", "parent": root_id,
            "kind": "admission", "name": "admission",
            "t0": (float(adm_ts) - adm_secs)
            if adm_ts is not None else None,
            "t1": float(adm_ts) if adm_ts is not None else None,
            "wall": adm_secs,
            "skipped": bool(admission.get("skipped")),
            "cached": bool(admission.get("cached")),
            "findings": admission.get("findings", 0)})
    # Cross-job memoization (ISSUE 16): a memo_hit ends the tree right
    # here (no attempts, no flight log); a warm/incremental seed is a
    # zero-width annotation explaining why attempt 1 starts deep.
    memo = next(
        (r for r in journal if r.get("t") in ("memo_hit", "memo")
         and r.get("mode") != "introspect_failed"
         and (r.get("job_id") == job_id
              or (trace_id and r.get("trace_id") == trace_id))), None)
    if memo is not None:
        m_ts = memo.get("ts")
        nodes.append({
            "span_id": f"{job_id}:memo", "parent": root_id,
            "kind": "memo",
            "name": ("memo-hit" if memo.get("t") == "memo_hit"
                     else f"memo-{memo.get('mode')}"),
            "t0": float(m_ts) if m_ts is not None else None,
            "t1": float(m_ts) if m_ts is not None else None,
            "mode": ("hit" if memo.get("t") == "memo_hit"
                     else memo.get("mode")),
            "sig": memo.get("sig"),
            "seed_depth": memo.get("seed_depth"),
            "levels_skipped": memo.get("levels_skipped"),
            "device_secs_saved": memo.get("device_secs_saved")})
    # Attempt spans: one per journal `start`; its id is DERIVED
    # (attempt_span_id) so the child meta's parent_span links back.
    attempt_ids = {}
    for k, s in enumerate(starts):
        att = int(s.get("attempt", k + 1) or (k + 1))
        aid = attempt_span_id(job_id, att)
        attempt_ids[aid] = True
        t0 = float(s["ts"]) if s.get("ts") is not None else None
        if k + 1 < len(starts):
            t1 = (float(starts[k + 1]["ts"])
                  if starts[k + 1].get("ts") is not None else None)
        else:
            t1 = (float(finish["ts"])
                  if finish is not None and finish.get("ts") is not None
                  else None)
        nodes.append({"span_id": aid, "parent": root_id,
                      "kind": "attempt", "name": f"attempt {att}",
                      "attempt": att, "t0": t0, "t1": t1})
    # The run dir's flight log, segmented per child.
    flight = os.path.join(root, "jobs", job_id or "", "flight.jsonl")
    records, torn = read_flight_lax(flight)
    compile_secs = 0.0
    search_secs = 0.0
    in_flight = None
    known = set(attempt_ids) | {root_id}
    for si, seg in enumerate(segment_flight(records)):
        parent = next(iter(attempt_ids), root_id)
        ph = _segment_nodes(seg, parent, f"{job_id}:s{si}", nodes,
                            known=known)
        compile_secs += ph["compile_secs"]
        search_secs += ph["search_secs"]
        if seg["in_flight"] is not None:
            in_flight = dict(seg["in_flight"],
                             segment=si,
                             hint=seg["meta"].get("hint"))
    # Lane-batch attribution (ISSUE 14, tpu/lanes.py): a job that ran
    # in a batched lane has no flight log of its own — the journal's
    # ``lane_batch`` events name the resident jobs and the batch run
    # dir, and the batch's SHARED flight log is attributed to every
    # resident job's causal tree (marked shared, so a reader knows the
    # spans were amortised across lanes, not exclusive).
    for ev in journal:
        if ev.get("t") != "lane_batch" or not ev.get("run_dir"):
            continue
        if job_id not in (ev.get("jobs") or []):
            continue
        bid = ev.get("batch") or os.path.basename(ev["run_dir"])
        brecords, btorn = read_flight_lax(
            os.path.join(ev["run_dir"], "flight.jsonl"))
        torn += btorn
        parent = next(iter(attempt_ids), root_id)
        lane_root = f"{job_id}:lane:{bid}"
        nodes.append({"span_id": lane_root, "parent": parent,
                      "kind": "lane_batch",
                      "name": f"lane batch {bid} (shared)",
                      "shared": True,
                      "lanes": len(ev.get("jobs") or ()),
                      "t0": ev.get("ts"), "t1": None})
        known.add(lane_root)
        for si, seg in enumerate(segment_flight(brecords)):
            ph = _segment_nodes(seg, lane_root, f"{job_id}:lb{si}",
                                nodes, known=known)
            compile_secs += ph["compile_secs"]
            search_secs += ph["search_secs"]
            if seg["in_flight"] is not None and in_flight is None:
                in_flight = dict(seg["in_flight"], segment=si,
                                 hint=seg["meta"].get("hint"),
                                 shared=True)
    status = rec.get("status")
    verdict = rec.get("verdict") or rec.get("failure")
    total = None
    if finish is not None and finish.get("ts") is not None \
            and submitted is not None:
        total = float(finish["ts"]) - submitted
    return {
        "job_id": job_id, "tenant": job.get("tenant"),
        "trace_id": trace_id, "status": status,
        "submitted_at": submitted,
        "attempts": len(starts),
        "phases": {
            "queue_wait_secs": round(queue_wait, 3)
            if queue_wait is not None else None,
            "admission_secs": round(adm_secs, 3),
            "compile_secs": round(compile_secs, 3),
            "search_secs": round(search_secs, 3),
            "total_secs": round(total, 3) if total is not None else None,
        },
        "nodes": nodes, "in_flight": in_flight, "verdict": verdict,
        "torn_lines": torn, "flight_log": flight
        if os.path.exists(flight) else None,
    }


def assemble(path: str, job: Optional[str] = None) -> dict:
    """Stitch a causal trace FROM DISK ALONE.

    ``path`` is either a SERVICE root (contains ``journal.jsonl`` —
    every job becomes one tree, ``job`` filters to one) or a plain run
    dir / flight log (one tree from the flight records alone).  All
    readers are torn-tolerant: a mid-write SERVER_STATUS snapshot, a
    torn COSTS/journal tail, and mid-file torn flight lines (a
    SIGKILL'd child with a successor appending after it) are expected
    crash shapes, never assembly failures."""
    from dslabs_tpu.tpu import telemetry as tel_mod

    if _is_server_dir(path):
        journal, _ = read_flight_lax(os.path.join(path, "journal.jsonl"))
        submits = [r for r in journal
                   if r.get("t") == "submit"
                   and isinstance(r.get("job"), dict)]
        # Journal replay gives per-job status without re-walking events.
        from dslabs_tpu.service.queue import replay_journal

        try:
            _, records, _ = replay_journal(
                os.path.join(path, "journal.jsonl"))
        except ValueError:
            records = {}
        jobs = []
        for rec in submits:
            jid = rec["job"].get("job_id")
            if job is not None and jid != job:
                continue
            merged = dict(records.get(jid, {}), job=rec["job"])
            jobs.append(_assemble_job(path, merged, journal))
        server = load_json_tolerant(
            os.path.join(path, "SERVER_STATUS.json"))
        costs_recs, _ = read_flight_lax(os.path.join(path, COSTS_NAME))
        return {"source": path, "mode": "service", "jobs": jobs,
                "server": server,
                "costs": aggregate_costs(costs_recs)}
    # Plain run dir / flight log: one pseudo-job from the records.
    flight = tel_mod._resolve_flight(path)
    records, torn = read_flight_lax(flight)
    nodes: List[dict] = []
    meta0 = next((r for r in records if r.get("t") == "meta"), {})
    trace_id = meta0.get("trace_id")
    root_id = trace_id or meta0.get("span_id") or "run"
    nodes.append({"span_id": root_id, "parent": None, "kind": "submit",
                  "name": os.path.basename(flight),
                  "t0": meta0.get("started"), "t1": None})
    compile_secs = search_secs = 0.0
    in_flight = None
    for si, seg in enumerate(segment_flight(records)):
        ph = _segment_nodes(seg, root_id, f"run:s{si}", nodes,
                            known={root_id})
        compile_secs += ph["compile_secs"]
        search_secs += ph["search_secs"]
        if seg["in_flight"] is not None:
            in_flight = dict(seg["in_flight"], segment=si,
                             hint=seg["meta"].get("hint"))
    jobd = {"job_id": os.path.basename(os.path.dirname(flight)) or
            flight, "tenant": None, "trace_id": trace_id,
            "status": None, "submitted_at": meta0.get("started"),
            "attempts": 1,
            "phases": {"queue_wait_secs": None, "admission_secs": 0.0,
                       "compile_secs": round(compile_secs, 3),
                       "search_secs": round(search_secs, 3),
                       "total_secs": None},
            "nodes": nodes, "in_flight": in_flight, "verdict": None,
            "torn_lines": torn, "flight_log": flight}
    return {"source": path, "mode": "run", "jobs": [jobd],
            "server": None, "costs": {}}


# -------------------------------------------------------------- renderer

def _fmt_t(t0, base) -> str:
    if t0 is None or base is None:
        return "      ? "
    return f"+{t0 - base:7.3f}s"


def render_trace(tr: dict) -> str:
    """The human timeline (sections pinned by tests/test_tracing.py):
    one causal tree per job — submit, queue-wait, admission, attempts,
    child runs (indented under their parent attempt), compile, level
    summary, re-level events, the in-flight dispatch of a torn tail —
    plus the phase latency breakdown and, in service mode, the
    per-tenant cost ledger."""
    out: List[str] = [f"== dslabs causal trace: {tr.get('source')} =="]
    if not tr.get("jobs"):
        out.append("(no jobs found)")
        return "\n".join(out)
    for j in tr["jobs"]:
        base = j.get("submitted_at")
        out.append("")
        out.append(f"trace {j.get('trace_id') or '?'} "
                   f"job {j.get('job_id')} "
                   f"tenant {j.get('tenant') or '-'} "
                   f"status {j.get('status') or '?'}")
        ph = j["phases"]

        def _p(v):
            return "?" if v is None else f"{v:.3f}s"

        out.append(f"  phases: queue {_p(ph['queue_wait_secs'])} | "
                   f"admission {_p(ph['admission_secs'])} | "
                   f"compile {_p(ph['compile_secs'])} | "
                   f"search {_p(ph['search_secs'])} | "
                   f"total {_p(ph['total_secs'])}")
        if j.get("torn_lines"):
            out.append(f"  (flight log: {j['torn_lines']} torn "
                       "line(s) skipped — SIGKILL shape)")
        by_parent: Dict[Optional[str], List[dict]] = {}
        for n in j["nodes"]:
            by_parent.setdefault(n.get("parent"), []).append(n)

        def walk(span_id: str, indent: int) -> None:
            for n in by_parent.get(span_id, ()):
                pad = "  " * indent
                kind = n["kind"]
                if kind == "level":
                    continue             # summarised on the run line
                line = (f"  {_fmt_t(n.get('t0'), base)} {pad}"
                        f"{kind}: {n.get('name')}")
                if kind == "run":
                    levels = [c for c in by_parent.get(n["span_id"], ())
                              if c["kind"] == "level"]
                    if levels:
                        walls = sum(float(c.get("wall", 0.0) or 0.0)
                                    for c in levels)
                        line += (f" [{len(levels)} level(s), "
                                 f"{walls:.3f}s search]")
                if kind == "in_flight":
                    line = (f"  {_fmt_t(n.get('t0'), base)} {pad}"
                            f"!! in-flight: {n.get('name')} "
                            f"depth={n.get('depth')} — the child died "
                            "or wedged inside this dispatch")
                if kind == "outcome":
                    line += (f" unique={n.get('unique')} "
                             f"explored={n.get('explored')}")
                if kind == "event" and n.get("detail"):
                    line += f" {n['detail']}"
                if kind == "admission":
                    if n.get("skipped"):
                        line += " (skipped)"
                    elif n.get("cached"):
                        line += " (cached)"
                if kind == "memo":
                    if n.get("mode") == "hit":
                        saved = n.get("device_secs_saved")
                        line += (f" sig={n.get('sig')} "
                                 f"saved~{saved}s" if saved is not None
                                 else f" sig={n.get('sig')}")
                    else:
                        line += (f" seed_depth={n.get('seed_depth')} "
                                 f"levels_skipped="
                                 f"{n.get('levels_skipped')}")
                out.append(line)
                walk(n["span_id"], indent + 1)

        roots = [n for n in j["nodes"] if n.get("parent") is None]
        for r in roots:
            out.append(f"  {_fmt_t(r.get('t0'), base)} "
                       f"{r['kind']}: {r.get('name')}")
            walk(r["span_id"], 1)
        if j.get("verdict"):
            v = j["verdict"]
            out.append("  verdict: " + " ".join(
                f"{k}={v[k]}" for k in ("end", "unique", "explored",
                                        "depth", "kind")
                if k in v))
    costs = tr.get("costs") or {}
    if costs:
        out.append("")
        out.append("-- per-tenant cost ledger --")
        out.append(f"{'tenant':12s} {'jobs':>5s} {'unique':>9s} "
                   f"{'explored':>9s} {'dev_s':>8s} {'disp':>6s} "
                   f"{'compile_s':>9s} {'retries':>7s} "
                   f"{'cost/unique':>12s}")
        for t in sorted(costs):
            s = costs[t]
            cpu = s.get("cost_per_unique")
            out.append(
                f"{t:12s} {s['jobs']:5d} {s['unique']:9d} "
                f"{s['explored']:9d} {s['device_secs']:8.3f} "
                f"{s['dispatches']:6.1f} {s['compile_secs']:9.3f} "
                f"{s['retries']:7d} "
                f"{cpu if cpu is not None else '-':>12}")
    return "\n".join(out)


# ------------------------------------------------------- perfetto export

def to_perfetto(tr: dict) -> dict:
    """Chrome/Perfetto trace-event JSON (``chrome://tracing`` /
    https://ui.perfetto.dev import): every trace node becomes a
    complete ``X`` event on its job's track (``pid`` = job index,
    ``tid`` = tree depth), timestamps in microseconds of wall-clock
    time; an in-flight dispatch becomes an instant ``i`` event so the
    kill point is visible on the timeline."""
    events: List[dict] = []
    for pi, j in enumerate(tr.get("jobs", ())):
        events.append({"ph": "M", "pid": pi, "name": "process_name",
                       "args": {"name": f"{j.get('tenant') or 'run'}/"
                                        f"{j.get('job_id')}"}})
        depth_of: Dict[str, int] = {}
        for n in j["nodes"]:
            parent = n.get("parent")
            depth_of[n["span_id"]] = (depth_of.get(parent, -1) + 1
                                      if parent else 0)
            t0, t1 = n.get("t0"), n.get("t1")
            if t0 is None:
                continue
            args = {k: v for k, v in n.items()
                    if k not in ("span_id", "parent", "t0", "t1")
                    and v is not None}
            if n["kind"] == "in_flight":
                events.append({"ph": "i", "s": "p", "pid": pi,
                               "tid": depth_of[n["span_id"]],
                               "name": f"in-flight {n.get('name')}",
                               "ts": int(t0 * 1e6), "cat": "trace",
                               "args": args})
                continue
            dur = max(0.0, (t1 - t0)) if t1 is not None else 0.0
            events.append({"ph": "X", "pid": pi,
                           "tid": depth_of[n["span_id"]],
                           "name": f"{n['kind']}:{n.get('name')}",
                           "ts": int(t0 * 1e6),
                           "dur": max(1, int(dur * 1e6)),
                           "cat": "trace", "args": args})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# ------------------------------------------------------------------- CLI

_USAGE = """usage: python -m dslabs_tpu.tpu.telemetry trace \
<run-dir|server-dir> [--job ID] [--json] [--perfetto out.json]
"""


def main(argv: List[str]) -> int:
    """The ``telemetry trace`` subcommand body (telemetry.main
    delegates here)."""
    import sys

    if not argv:
        print(_USAGE, file=sys.stderr)
        return 2
    path = argv[0]
    flags = argv[1:]
    job = None
    if "--job" in flags:
        job = flags[flags.index("--job") + 1]
    tr = assemble(path, job=job)
    if "--perfetto" in flags:
        out_path = flags[flags.index("--perfetto") + 1]
        with open(out_path, "w") as f:
            json.dump(to_perfetto(tr), f)
        print(f"perfetto trace written: {out_path}", file=sys.stderr)
    if "--json" in flags:
        print(json.dumps(tr))
    else:
        print(render_trace(tr))
    return 0
