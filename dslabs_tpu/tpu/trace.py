"""TPU trace reconstruction: from a violating/goal row in the tensor
search back to a minimized, human-readable OBJECT trace.

Pipeline (SURVEY §8.1 "trace reconstruction"; SearchState.java:361-474,
TraceMinimizer.java:33-61):

1. The engine spills (parent frontier row, event id) per level when
   ``record_trace=True``; ``SearchOutcome.trace`` is the root-first event-id
   list for the terminal row (engine._reconstruct).
2. :func:`decode_trace` replays that list in TENSOR space one state at a
   time, reading each step's concrete message/timer lanes *before*
   stepping — event ids alone are meaningless without the parent state's
   canonical network/timer contents.
3. :func:`replay_on_object` maps each record through the protocol's
   ``decode_message``/``decode_timer`` and replays the resulting envelopes
   on the object-twin SearchState, rebuilding the parent chain the
   existing minimizer and human-readable printer consume.

The result: a TPU INVARIANT_VIOLATED/GOAL_FOUND outcome yields the same
trace artifact (minimizable, printable, saveable) as the object backend.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import numpy as np

from dslabs_tpu.testing.events import MessageEnvelope, TimerEnvelope
from dslabs_tpu.tpu.engine import SearchOutcome, TensorSearch

__all__ = ["decode_trace", "replay_on_object", "reconstruct_object_trace",
           "MessageTemplate"]


class MessageTemplate:
    """A decoded message whose full payload the twin does not model (e.g.
    a PaxosReply's application result value).  At replay time the
    template resolves against the object state's OWN network — the object
    execution that produced the network is the source of truth for
    application-level values — falling back to ``fallback`` only when no
    network message matches (e.g. the message was constructed but its
    object counterpart was GC'd; ambiguity is a loud error, never a
    guess)."""

    def __init__(self, cls, fallback, match):
        self.cls = cls
        self.fallback = fallback
        self.match = match

    def resolve(self, state, frm, to):
        cands = {m.message for m in state.network()
                 if m.frm.root_address() == frm.root_address()
                 and m.to.root_address() == to.root_address()
                 and isinstance(m.message, self.cls)
                 and self.match(m.message)}
        if len(cands) == 1:
            return next(iter(cands))
        if not cands:
            if self.fallback is None:
                # A None fallback means the binding has no way to build
                # this message without an object-side candidate — failing
                # here keeps "ambiguity is a loud error, never a guess"
                # (a None message would fail far away with an obscure
                # handler error; ADVICE r4).
                raise ValueError(
                    f"template resolution found no {self.cls.__name__} "
                    f"candidate from {frm} to {to} in the object network "
                    "and the binding provides no fallback")
            return self.fallback
        raise ValueError(
            f"ambiguous template resolution: {len(cands)} distinct "
            f"{self.cls.__name__} candidates from {frm} to {to}")


def decode_trace(search: TensorSearch,
                 outcome: SearchOutcome) -> List[Tuple[str, tuple]]:
    """Replay ``outcome.trace`` (event-id list) in tensor space; return
    root-first records ``("message", lanes)`` / ``("timer", node, lanes)``."""
    if outcome.trace is None:
        raise ValueError("outcome has no trace "
                         "(run the search with record_trace=True)")
    p = search.p
    # Replay from the root the trace was recorded against — for staged
    # searches (run(initial=...)) that is NOT the protocol initial state.
    root = getattr(search, "_trace_root", None)
    if root is None:
        root = jax.tree.map(np.asarray, search.initial_state())
    from dslabs_tpu.tpu.engine import flatten_state
    row = np.asarray(flatten_state(
        jax.tree.map(jax.numpy.asarray, root)))[0]
    step = jax.jit(search._step_one)
    records: List[Tuple[str, tuple]] = []
    tgrid = p.n_nodes * p.timer_cap
    for ev in outcome.trace:
        state = search._slice_state(row)       # numpy views
        if ev < p.net_cap:
            rec = np.asarray(state["net"][ev]).copy()
            records.append(("message", (rec,)))
        elif ev < p.net_cap + tgrid:
            t_idx = ev - p.net_cap
            node, slot = t_idx // p.timer_cap, t_idx % p.timer_cap
            rec = np.asarray(state["timers"][node, slot]).copy()
            records.append(("timer", (node, rec)))
        else:
            # Fault-segment event (ISSUE 19): record the controller's
            # human-readable label (CUT / HEAL / CRASH(kind[i]) / ...)
            # so witness traces NAME the fault that enabled them.
            f_idx = ev - p.net_cap - tgrid
            records.append(("fault", (p.fault.event_label(f_idx),)))
        succ_row, valid, _ = step(jax.numpy.asarray(row),
                                  jax.numpy.asarray(ev))
        assert bool(valid), (
            f"trace replay hit an undeliverable event {ev} — "
            "reconstruction mapping is corrupt")
        row = np.asarray(succ_row)
    return records


def replay_on_object(search: TensorSearch, outcome: SearchOutcome,
                     initial_object_state,
                     settings=None):
    """Replay the reconstructed record list on the object twin, returning
    the final object SearchState (whose parent chain IS the trace)."""
    p = search.p
    if p.decode_message is None or p.decode_timer is None:
        raise ValueError(f"{p.name}: protocol has no object-twin decoders")
    state = initial_object_state
    for kind, payload in decode_trace(search, outcome):
        if kind == "fault":
            # The object twin has no fault controller — a scenario
            # witness replays in tensor space only (decode_trace's
            # per-step validity asserts are the replay verification).
            raise NotImplementedError(
                f"{p.name}: trace contains fault event "
                f"{payload[0]!r}; object-twin replay does not model "
                "fault scenarios — verify the witness with "
                "decode_trace instead")
        if kind == "message":
            frm, to, msg = p.decode_message(payload[0])
            if isinstance(msg, MessageTemplate):
                msg = msg.resolve(state, frm, to)
            event = MessageEnvelope(frm, to, msg)
        else:
            node, rec = payload
            to, timer, mn, mx = p.decode_timer(node, rec)
            event = TimerEnvelope(to, timer, mn, mx)
        nxt = state.step_event(event, settings, skip_checks=True)
        assert nxt is not None, (
            f"object twin rejected reconstructed event {event!r} — "
            "tensor/object divergence")
        state = nxt
    return state


def reconstruct_object_trace(search: TensorSearch, outcome: SearchOutcome,
                             initial_object_state, predicate=None,
                             settings=None, minimize: bool = True):
    """Full pipeline: tensor outcome -> replayed object state ->
    (optionally) minimized against ``predicate`` (the object analog of the
    violated invariant / matched goal).  Returns the final SearchState;
    ``.print_trace()`` gives the human-readable causal trace."""
    end = replay_on_object(search, outcome, initial_object_state, settings)
    if minimize and predicate is not None:
        from dslabs_tpu.search.minimize import minimize_trace

        result = predicate.check(end)
        end = minimize_trace(end, result)
    return end
