"""Declarative protocol specs for the schema compiler (tpu/compiler.py):
lab 0 ping-pong and lab 1 exactly-once client/server, written as bounded
field/message/handler declarations — no jax, no lane arithmetic — and
compiled mechanically to TensorProtocols.

These are the "schema compiler first cut" deliverable (SURVEY §8.1
Protocol IR): the generated twins explore state spaces ISOMORPHIC to the
hand-written twins in tpu/protocols/ (tests/test_compiler.py pins the
unique-state counts and verdicts against both the hand twins and the
object oracle; lane layouts differ — e.g. the compiler's uniform
[tag, frm, to, payload] message records — which changes fingerprints
but not the state graph)."""

from __future__ import annotations

from dslabs_tpu.tpu.compiler import (Field, MessageType, NodeKind,
                                     ProtocolSpec, TimerType)

__all__ = ["pingpong_spec", "clientserver_spec"]


def pingpong_spec(workload_size: int = 2,
                  never_done: bool = False) -> ProtocolSpec:
    """Lab 0: a stateless echo server + one ClientWorker-collapsed
    client walking W commands (the same state collapse as the hand twin,
    tpu/protocols/pingpong.py: one k lane, 'waiting on command k').
    ``never_done`` adds the NONE_DECIDED invariant (the violation-probe
    configuration)."""
    w = workload_size
    spec = ProtocolSpec(
        "pingpong-gen",
        nodes=[NodeKind("server", 1, ()),
               NodeKind("client", 1, (Field("k", init=1),))],
        messages=[MessageType("REQ", ("i",)),
                  MessageType("REPLY", ("i",))],
        timers=[TimerType("PING", ("i",), 10, 10)],
        net_cap=8, timer_cap=4)

    @spec.on("server", "REQ")
    def srv_req(ctx, m):
        ctx.send("REPLY", 1, i=m["i"])

    @spec.on("client", "REPLY")
    def cli_reply(ctx, m):
        k = ctx.get("k")
        match = (m["i"] == k) & (k <= w)
        ctx.put("k", k + 1, when=match)
        k2 = ctx.get("k")
        nxt = match & (k2 <= w)
        ctx.send("REQ", 0, when=nxt, i=k2)
        ctx.set_timer("PING", when=nxt, i=k2)

    @spec.on_timer("client", "PING")
    def cli_timer(ctx, t):
        k = ctx.get("k")
        live = (t["i"] == k) & (k <= w)
        ctx.send("REQ", 0, when=live, i=k)
        ctx.set_timer("PING", when=live, i=k)

    spec.initial_messages.append(("REQ", 1, 0, {"i": 1}))
    spec.initial_timers.append(("PING", 1, {"i": 1}))

    def clients_done(v):
        return v.get("client", 0, "k") == w + 1

    def none_decided(v):
        return v.get("client", 0, "k") == 1

    spec.goals["CLIENTS_DONE"] = clients_done
    if never_done:
        spec.invariants["NONE_DECIDED"] = none_decided
    return spec


def clientserver_spec(n_clients: int = 1, w: int = 1) -> ProtocolSpec:
    """Lab 1: AMO server + NC clients, the hand twin's collapse
    (tpu/protocols/clientserver.py): server state = per-client
    last-executed seq, client state = seq in flight."""
    nc = n_clients
    spec = ProtocolSpec(
        "clientserver-gen",
        nodes=[NodeKind("server", 1, (Field("a", size=nc),)),
               NodeKind("client", nc, (Field("k", init=1),))],
        messages=[MessageType("REQ", ("c", "s")),
                  MessageType("REPLY", ("c", "s"))],
        timers=[TimerType("RETRY", ("s",), 100, 100)],
        net_cap=16, timer_cap=4)

    @spec.on("server", "REQ")
    def srv_req(ctx, m):
        c, s = m["c"], m["s"]
        a = ctx.get_at("a", c)
        ctx.put_at("a", c, s, when=s > a)
        # fresh -> execute + reply; s == a -> cached reply; older -> drop
        ctx.send("REPLY", 1 + c, when=s >= a, c=c, s=s)

    @spec.on("client", "REPLY")
    def cli_reply(ctx, m):
        c, s = m["c"], m["s"]
        k = ctx.get("k")
        mine = c == (ctx.node_index() - 1)
        match = mine & (s == k) & (k <= w)
        ctx.put("k", k + 1, when=match)
        k2 = ctx.get("k")
        nxt = match & (k2 <= w)
        ctx.send("REQ", 0, when=nxt, c=c, s=k2)
        ctx.set_timer("RETRY", when=nxt, s=k2)

    @spec.on_timer("client", "RETRY")
    def cli_timer(ctx, t):
        k = ctx.get("k")
        c = ctx.node_index() - 1
        live = (t["s"] == k) & (k <= w)
        ctx.send("REQ", 0, when=live, c=c, s=k)
        ctx.set_timer("RETRY", when=live, s=k)

    for c in range(nc):
        spec.initial_messages.append(("REQ", 1 + c, 0, {"c": c, "s": 1}))
        spec.initial_timers.append(("RETRY", 1 + c, {"s": 1}))

    def clients_done(v):
        done = True
        for c in range(nc):
            done = done & (v.get("client", c, "k") == w + 1)
        return done

    spec.goals["CLIENTS_DONE"] = clients_done
    return spec
