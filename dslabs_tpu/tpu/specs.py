"""Declarative protocol specs for the schema compiler (tpu/compiler.py):
lab 0 ping-pong and lab 1 exactly-once client/server, written as bounded
field/message/handler declarations — no jax, no lane arithmetic — and
compiled mechanically to TensorProtocols.

These are the "schema compiler first cut" deliverable (SURVEY §8.1
Protocol IR): the generated twins explore state spaces ISOMORPHIC to the
hand-written twins in tpu/protocols/ (tests/test_compiler.py pins the
unique-state counts and verdicts against both the hand twins and the
object oracle; lane layouts differ — e.g. the compiler's uniform
[tag, frm, to, payload] message records — which changes fingerprints
but not the state graph).

Conformance contract (ISSUE 10): every spec in this module is
sanitizer-clean — ``python -m dslabs_tpu.analysis conformance`` lints
the handlers (purity / determinism / spec hygiene, rules C1-C4 in
docs/analysis.md) and ``ProtocolSpec.compile()`` raises a structured
``SpecError`` on hygiene violations, so a handler that mutates its
payload or reads an undeclared field fails HERE, at the compile gate,
not as a silent generated-vs-hand parity break deep in a search
(tests/test_analysis.py pins the clean pass)."""

from __future__ import annotations

from dslabs_tpu.tpu.compiler import (Field, MessageType, NodeKind,
                                     ProtocolSpec, TimerType)

__all__ = ["pingpong_spec", "clientserver_spec", "pb_spec",
           "paxos_spec", "paxos_partition_spec", "pb_crash_spec"]


def pingpong_spec(workload_size: int = 2,
                  never_done: bool = False) -> ProtocolSpec:
    """Lab 0: a stateless echo server + one ClientWorker-collapsed
    client walking W commands (the same state collapse as the hand twin,
    tpu/protocols/pingpong.py: one k lane, 'waiting on command k').
    ``never_done`` adds the NONE_DECIDED invariant (the violation-probe
    configuration)."""
    w = workload_size
    # Declared domains (ISSUE 15, tpu/packing.py): k walks 1..w+1, the
    # command index i walks 1..w — the packed frontier stores each in
    # a few bits instead of a full int32 lane.
    spec = ProtocolSpec(
        "pingpong-gen",
        nodes=[NodeKind("server", 1, ()),
               NodeKind("client", 1, (Field("k", init=1, hi=w + 1),))],
        messages=[MessageType("REQ", ("i",), bounds={"i": (0, w)}),
                  MessageType("REPLY", ("i",), bounds={"i": (0, w)})],
        timers=[TimerType("PING", ("i",), 10, 10,
                          bounds={"i": (0, w)})],
        net_cap=8, timer_cap=4)

    @spec.on("server", "REQ")
    def srv_req(ctx, m):
        ctx.send("REPLY", 1, i=m["i"])

    @spec.on("client", "REPLY")
    def cli_reply(ctx, m):
        k = ctx.get("k")
        match = (m["i"] == k) & (k <= w)
        ctx.put("k", k + 1, when=match)
        k2 = ctx.get("k")
        nxt = match & (k2 <= w)
        ctx.send("REQ", 0, when=nxt, i=k2)
        ctx.set_timer("PING", when=nxt, i=k2)

    @spec.on_timer("client", "PING")
    def cli_timer(ctx, t):
        k = ctx.get("k")
        live = (t["i"] == k) & (k <= w)
        ctx.send("REQ", 0, when=live, i=k)
        ctx.set_timer("PING", when=live, i=k)

    spec.initial_messages.append(("REQ", 1, 0, {"i": 1}))
    spec.initial_timers.append(("PING", 1, {"i": 1}))

    def clients_done(v):
        return v.get("client", 0, "k") == w + 1

    def none_decided(v):
        return v.get("client", 0, "k") == 1

    spec.goals["CLIENTS_DONE"] = clients_done
    if never_done:
        spec.invariants["NONE_DECIDED"] = none_decided
    return spec


def clientserver_spec(n_clients: int = 1, w: int = 1) -> ProtocolSpec:
    """Lab 1: AMO server + NC clients, the hand twin's collapse
    (tpu/protocols/clientserver.py): server state = per-client
    last-executed seq, client state = seq in flight."""
    nc = n_clients
    # Declared domains (ISSUE 15): per-client last-executed seq a and
    # in-flight seq k are bounded by the workload, client ids by NC —
    # the packed frontier encoding derives its lane widths from these.
    cb, sb = (0, max(nc - 1, 0)), (0, w)
    spec = ProtocolSpec(
        "clientserver-gen",
        nodes=[NodeKind("server", 1, (Field("a", size=nc, hi=w),)),
               NodeKind("client", nc, (Field("k", init=1, hi=w + 1),))],
        messages=[MessageType("REQ", ("c", "s"),
                              bounds={"c": cb, "s": sb}),
                  MessageType("REPLY", ("c", "s"),
                              bounds={"c": cb, "s": sb})],
        timers=[TimerType("RETRY", ("s",), 100, 100,
                          bounds={"s": sb})],
        net_cap=16, timer_cap=4)

    @spec.on("server", "REQ")
    def srv_req(ctx, m):
        c, s = m["c"], m["s"]
        a = ctx.get_at("a", c)
        ctx.put_at("a", c, s, when=s > a)
        # fresh -> execute + reply; s == a -> cached reply; older -> drop
        ctx.send("REPLY", 1 + c, when=s >= a, c=c, s=s)

    @spec.on("client", "REPLY")
    def cli_reply(ctx, m):
        c, s = m["c"], m["s"]
        k = ctx.get("k")
        mine = c == (ctx.node_index() - 1)
        match = mine & (s == k) & (k <= w)
        ctx.put("k", k + 1, when=match)
        k2 = ctx.get("k")
        nxt = match & (k2 <= w)
        ctx.send("REQ", 0, when=nxt, c=c, s=k2)
        ctx.set_timer("RETRY", when=nxt, s=k2)

    @spec.on_timer("client", "RETRY")
    def cli_timer(ctx, t):
        k = ctx.get("k")
        c = ctx.node_index() - 1
        live = (t["s"] == k) & (k <= w)
        ctx.send("REQ", 0, when=live, c=c, s=k)
        ctx.set_timer("RETRY", when=live, s=k)

    for c in range(nc):
        spec.initial_messages.append(("REQ", 1 + c, 0, {"c": c, "s": 1}))
        spec.initial_timers.append(("RETRY", 1 + c, {"s": 1}))

    def clients_done(v):
        done = True
        for c in range(nc):
            done = done & (v.get("client", c, "k") == w + 1)
        return done

    spec.goals["CLIENTS_DONE"] = clients_done
    return spec


def pb_spec(ns: int = 2, n_clients: int = 1, w: int = 1,
            fault=None) -> ProtocolSpec:
    """Lab 2 primary-backup: ViewServer + PBServers + clients — the
    first STATEFUL multi-role protocol through the compiler (round-4
    verdict item 7: "a new protocol becomes searchable without
    twin-authoring expertise" is unproven until lab2's view-change /
    state-transfer compiles from a spec).  Handler-for-handler mirror of
    the hand twin (tpu/protocols/primarybackup.py), which itself mirrors
    labs/primarybackup/{viewserver,pb}.py: first-ping-rank idle
    selection, ack-before-view-change, primary state transfer with
    refusal to serve until acked, one-outstanding-op forwarding, and the
    client's view re-poll on every retry."""
    NS, NC = ns, n_clients
    DEAD = 2
    amo_fields = tuple(f"a{c}" for c in range(NC))
    # Declared domains (ISSUE 15): server/client ids, sync/acked bits,
    # amo seqs, and rank are all tiny.  View numbers (vn/svn/cvn)
    # genuinely grow with depth and defeat a static hi= — they carry
    # the delta-from-level-base annotation instead (ISSUE 18 leg (b)):
    # the mesh engine packs them as 8-bit offsets from the per-level
    # minimum, the single-device engine keeps them as full int32
    # lanes.  Liveness ticks stay raw: a dead server's ticks diverge
    # from the level base without bound, so a delta window would
    # overflow (loudly) on exactly the executions lab2 must explore.
    sid, cid, seq = (0, NS), (0, max(NC - 1, 0)), (0, w)
    amo_b = {f: seq for f in amo_fields}
    spec = ProtocolSpec(
        "pb-gen",
        nodes=[NodeKind("vs", 1, (
                   Field("vn", delta=8), Field("prim", hi=NS),
                   Field("back", hi=NS),
                   Field("acked", hi=1), Field("nextrank", hi=NS),
                   Field("rank", size=NS, hi=NS),
                   Field("ticks", size=NS))),
               NodeKind("server", NS, (
                   Field("svn", init=-1, delta=8), Field("sp", hi=NS),
                   Field("sb", hi=NS),
                   Field("sync", init=1, hi=1), Field("pc", hi=NC),
                   Field("ps", hi=w),
                   Field("amo", size=NC, hi=w))),
               NodeKind("client", NC, (
                   Field("k", init=1, hi=w + 1),
                   Field("cvn", init=-1, delta=8),
                   Field("cp", hi=NS), Field("cb", hi=NS)))],
        messages=[MessageType("PING", ("vn",)),
                  MessageType("GETVIEW", ()),
                  MessageType("VIEWREPLY", ("vn", "prim", "back"),
                              bounds={"prim": sid, "back": sid}),
                  MessageType("REQ", ("c", "s"),
                              bounds={"c": cid, "s": seq}),
                  MessageType("REPLY", ("c", "s"),
                              bounds={"c": cid, "s": seq}),
                  MessageType("FWD", ("vn", "c", "s"),
                              bounds={"c": cid, "s": seq}),
                  MessageType("FWDACK", ("vn", "c", "s"),
                              bounds={"c": cid, "s": seq}),
                  MessageType("XFER", ("vn", "prim", "back")
                              + amo_fields,
                              bounds={"prim": sid, "back": sid,
                                      **amo_b}),
                  MessageType("XFERACK", ("vn",))],
        timers=[TimerType("PINGCHECK", (), 100, 100),
                TimerType("PING", (), 25, 25),
                TimerType("CLIENT", ("s",), 100, 100,
                          bounds={"s": seq})],
        net_cap=32, timer_cap=4, fault=fault)

    # ------------------------------------------------ ViewServer helpers

    def vs_alive(ctx, a):
        ai = (a - 1).clip(0, NS - 1)
        return ((a > 0) & (ctx.get_at("rank", ai) > 0)
                & (ctx.get_at("ticks", ai) < DEAD))

    def vs_idle(ctx):
        """First alive non-primary/backup server in first-ping (rank)
        order; 0 if none (viewserver.py:112-116)."""
        import jax.numpy as jnp

        rank, ticks = ctx.get("rank"), ctx.get("ticks")
        prim, back = ctx.get("prim"), ctx.get("back")
        best_rank = jnp.full((), 1 << 30, jnp.int32)
        best = jnp.zeros((), jnp.int32)
        for s in range(NS):
            sid = s + 1
            ok = ((rank[s] > 0) & (ticks[s] < DEAD) & (prim != sid)
                  & (back != sid) & (rank[s] < best_rank))
            best_rank = jnp.where(ok, rank[s], best_rank)
            best = jnp.where(ok, sid, best)
        return best

    def vs_evaluate(ctx):
        """The view-change rules (viewserver.py:118-139) under the
        ctx's guard, as sequential conditional puts."""
        prim, back, acked = ctx.get("prim"), ctx.get("back"), \
            ctx.get("acked")
        idle = vs_idle(ctx)
        ap, ab = vs_alive(ctx, prim), vs_alive(ctx, back)
        c0 = (prim == 0) & (idle > 0)                  # startup
        guard = (prim != 0) & (acked == 1)
        c1 = guard & ~ap & ab                          # promote backup
        c2 = guard & ~ap & (back == 0) & (idle > 0)    # dead solo prim
        c3 = guard & ap & (back != 0) & ~ab            # replace backup
        c4 = guard & ap & (back == 0) & (idle > 0)     # fill backup
        did = c0 | c1 | c2 | c3 | c4
        ctx.put("vn", ctx.get("vn") + 1, when=did)
        ctx.put("acked", 0, when=did)
        ctx.put("prim", idle, when=c0)
        ctx.put("prim", back, when=c1)
        ctx.put("back", 0, when=c0)
        ctx.put("back", idle, when=c1 | c2 | c3 | c4)

    def vs_reply(ctx, to):
        ctx.send("VIEWREPLY", to, vn=ctx.get("vn"),
                 prim=ctx.get("prim"), back=ctx.get("back"))

    @spec.on("vs", "PING")
    def vs_ping(ctx, m):
        frm = m["_from"]
        si = (frm - 1).clip(0, NS - 1)
        newcomer = ctx.get_at("rank", si) == 0
        nv = ctx.get("nextrank") + 1
        ctx.put("nextrank", nv, when=newcomer)
        ctx.put_at("rank", si, nv, when=newcomer)
        ctx.put_at("ticks", si, 0)
        ctx.put("acked", 1, when=(frm == ctx.get("prim"))
                & (m["vn"] == ctx.get("vn")))
        vs_evaluate(ctx)
        vs_reply(ctx, frm)

    @spec.on("vs", "GETVIEW")
    def vs_getview(ctx, m):
        vs_reply(ctx, m["_from"])

    @spec.on_timer("vs", "PINGCHECK")
    def vs_pingcheck(ctx, t):
        for s in range(NS):
            ctx.put_at("ticks", s, ctx.get_at("ticks", s) + 1,
                       when=ctx.get_at("rank", s) > 0)
        vs_evaluate(ctx)
        ctx.set_timer("PINGCHECK")

    # -------------------------------------------------- PBServer helpers

    def srv_adopt(ctx, vn, prim, back, can_send):
        """_adopt (pb.py:123-137); mutations ride ``vn > svn``."""
        sid = ctx.node_index()
        do = vn > ctx.get("svn")
        ctx.put("svn", vn, when=do)
        ctx.put("sp", prim, when=do)
        ctx.put("sb", back, when=do)
        ctx.put("pc", 0, when=do)
        ctx.put("ps", 0, when=do)
        is_p, is_b = do & (prim == sid), do & (back == sid)
        ctx.put("sync", 1, when=do)
        ctx.put("sync", 0, when=(is_p & (back != 0)) | is_b)
        if can_send:
            ctx.send("XFER", back, when=is_p & (back != 0), vn=vn,
                     prim=prim, back=back,
                     **{f"a{c}": ctx.get_at("amo", c)
                        for c in range(NC)})

    @spec.on("server", "VIEWREPLY")
    def srv_viewreply(ctx, m):
        srv_adopt(ctx, m["vn"], m["prim"], m["back"], can_send=True)

    @spec.on("server", "REQ")
    def srv_req(ctx, m):
        sid = ctx.node_index()
        c, sq = m["c"], m["s"]
        serving = (ctx.get("sp") == sid) & (ctx.get("sync") == 1)
        amo_c = ctx.get_at("amo", c)
        already = serving & (sq <= amo_c)
        reply_cached = already & (sq == amo_c)
        solo = serving & ~already & (ctx.get("sb") == 0)
        ctx.put_at("amo", c, sq, when=solo)
        can_fwd = (serving & ~already & (ctx.get("sb") != 0)
                   & (ctx.get("pc") == 0))
        ctx.put("pc", c + 1, when=can_fwd)
        ctx.put("ps", sq, when=can_fwd)
        ctx.send("REPLY", 1 + NS + c, when=reply_cached | solo, c=c,
                 s=sq)
        ctx.send("FWD", ctx.get("sb"), when=can_fwd,
                 vn=ctx.get("svn"), c=c, s=sq)

    @spec.on("server", "FWD")
    def srv_fwd(ctx, m):
        sid = ctx.node_index()
        ok = ((ctx.get("sb") == sid) & (m["vn"] == ctx.get("svn"))
              & (ctx.get("sync") == 1))
        fc, fs = m["c"], m["s"]
        ctx.put_at("amo", fc, fs,
                   when=ok & (fs > ctx.get_at("amo", fc)))
        ctx.send("FWDACK", m["_from"], when=ok, vn=m["vn"], c=fc, s=fs)

    @spec.on("server", "FWDACK")
    def srv_fwdack(ctx, m):
        sid = ctx.node_index()
        ok = ((ctx.get("sp") == sid) & (m["vn"] == ctx.get("svn"))
              & (ctx.get("pc") == m["c"] + 1) & (ctx.get("ps") == m["s"]))
        ac, asq = m["c"], m["s"]
        ctx.put("pc", 0, when=ok)
        ctx.put("ps", 0, when=ok)
        reply = ok & (asq >= ctx.get_at("amo", ac))
        ctx.put_at("amo", ac, asq,
                   when=ok & (asq > ctx.get_at("amo", ac)))
        ctx.send("REPLY", 1 + NS + ac, when=reply, c=ac, s=asq)

    @spec.on("server", "XFER")
    def srv_xfer(ctx, m):
        sid = ctx.node_index()
        mine = m["back"] == sid
        c2 = ctx.cond(mine)
        srv_adopt(c2, m["vn"], m["prim"], m["back"], can_send=False)
        cur = mine & (ctx.get("svn") == m["vn"])
        install = cur & (ctx.get("sync") == 0)
        for c in range(NC):
            ctx.put_at("amo", c, m[f"a{c}"], when=install)
        ctx.put("sync", 1, when=install)
        ctx.send("XFERACK", m["_from"], when=cur, vn=m["vn"])

    @spec.on("server", "XFERACK")
    def srv_xferack(ctx, m):
        sid = ctx.node_index()
        ok = (ctx.get("sp") == sid) & (ctx.get("svn") == m["vn"])
        ctx.put("sync", 1, when=ok)

    @spec.on_timer("server", "PING")
    def srv_ping(ctx, t):
        import jax.numpy as jnp

        sid = ctx.node_index()
        svn, sync = ctx.get("svn"), ctx.get("sync")
        is_p = ctx.get("sp") == sid
        has_b = ctx.get("sb") != 0
        # view=None pings 0; an unsynced primary acks the PREVIOUS view
        # (pb.py:114-121)
        acked_vn = jnp.where(
            svn == -1, 0,
            jnp.where(is_p & has_b & (sync == 0), svn - 1, svn))
        ctx.send("PING", 0, vn=acked_vn)
        ctx.send("XFER", ctx.get("sb"),
                 when=is_p & has_b & (sync == 0), vn=svn,
                 prim=ctx.get("sp"), back=ctx.get("sb"),
                 **{f"a{c}": ctx.get_at("amo", c) for c in range(NC)})
        ctx.send("FWD", ctx.get("sb"),
                 when=is_p & has_b & (sync == 1) & (ctx.get("pc") > 0),
                 vn=svn, c=ctx.get("pc") - 1, s=ctx.get("ps"))
        ctx.set_timer("PING")

    # ------------------------------------------------------------ clients

    @spec.on("client", "VIEWREPLY")
    def cli_viewreply(ctx, m):
        cvn = ctx.get("cvn")
        newer = (cvn == -1) | (m["vn"] > cvn)
        ctx.put("cvn", m["vn"], when=newer)
        ctx.put("cp", m["prim"], when=newer)
        ctx.put("cb", m["back"], when=newer)
        k = ctx.get("k")
        waiting = k <= w
        cp = ctx.get("cp")
        c = ctx.node_index() - 1 - NS
        ctx.send("REQ", cp, when=newer & waiting & (cp > 0), c=c, s=k)
        ctx.send("GETVIEW", 0, when=newer & waiting & (cp == 0))

    @spec.on("client", "REPLY")
    def cli_reply(ctx, m):
        c = ctx.node_index() - 1 - NS
        k = ctx.get("k")
        match = (m["c"] == c) & (m["s"] == k) & (k <= w)
        ctx.put("k", k + 1, when=match)
        k2 = ctx.get("k")
        has_next = match & (k2 <= w)
        cp = ctx.get("cp")
        ctx.send("REQ", cp, when=has_next & (cp > 0), c=c, s=k2)
        ctx.send("GETVIEW", 0, when=has_next & (cp == 0))
        ctx.set_timer("CLIENT", when=has_next, s=k2)

    @spec.on_timer("client", "CLIENT")
    def cli_timer(ctx, t):
        c = ctx.node_index() - 1 - NS
        k = ctx.get("k")
        live = (t["s"] == k) & (k <= w)
        ctx.send("GETVIEW", 0, when=live)
        ctx.send("REQ", ctx.get("cp"), when=live & (ctx.get("cp") > 0),
                 c=c, s=k)
        ctx.set_timer("CLIENT", when=live, s=k)

    # ----------------------------------------------------------- initials

    for s in range(NS):
        spec.initial_messages.append(("PING", 1 + s, 0, {"vn": 0}))
        spec.initial_timers.append(("PING", 1 + s, {}))
    for c in range(NC):
        spec.initial_messages.append(("GETVIEW", 1 + NS + c, 0, {}))
        spec.initial_timers.append(("CLIENT", 1 + NS + c, {"s": 1}))
    spec.initial_timers.insert(0, ("PINGCHECK", 0, {}))

    def clients_done(v):
        done = True
        for c in range(NC):
            done = done & (v.get("client", c, "k") == w + 1)
        return done

    spec.goals["CLIENTS_DONE"] = clients_done
    return spec


def paxos_spec(n_acceptors: int = 3, quorum: int = 0,
               never_decided: bool = False,
               fault=None) -> ProtocolSpec:
    """Single-decree Paxos (one ballot, one proposer, ``n_acceptors``
    INTERCHANGEABLE acceptors) — the symmetry-reduction flagship
    (ISSUE 15, tpu/symmetry.py): the acceptors are declared a
    ``symmetry`` group, so states that differ only in WHICH acceptors
    have promised/accepted collapse to one canonical orbit
    representative when the reduction is on (engines' ``symmetry=True``
    knob; default OFF keeps raw counts).

    The spec is written in the symmetry-safe style the C5 conformance
    rule enforces: the proposer identifies responders by ``_from``
    (relabeled by the canonicalize pass) and tracks per-acceptor
    promise/accept bits in ``index_group`` arrays (permuted WITH the
    group); no handler compares ``node_index()`` against a constant.
    Every lane is domain-bounded, so the packed frontier encoding
    (tpu/packing.py) compresses it well past the 2x acceptance bar.

    Flow: initial PREPAREs fan out; acceptors PROMISE; at quorum the
    proposer broadcasts ACCEPT; acceptors reply ACCEPTED; at quorum
    the proposer decides (goal DECIDED).  ``never_decided`` installs
    the violation-probe invariant instead (witness tests)."""
    NA = n_acceptors
    Q = quorum or NA // 2 + 1
    spec = ProtocolSpec(
        "paxos-gen",
        nodes=[NodeKind("proposer", 1, (
                   Field("ph", hi=2),
                   Field("prom", size=NA, hi=1,
                         index_group="acceptor"),
                   Field("accs", size=NA, hi=1,
                         index_group="acceptor"),
                   Field("dec", hi=1))),
               NodeKind("acceptor", NA, (
                   Field("bal", hi=1), Field("acc", hi=1)))],
        messages=[MessageType("PREPARE", ()),
                  MessageType("PROMISE", ()),
                  MessageType("ACCEPT", ()),
                  MessageType("ACCEPTED", ())],
        timers=[],
        net_cap=4 * NA + 2, timer_cap=2,
        symmetry=("acceptor",), fault=fault)

    @spec.on("acceptor", "PREPARE")
    def acc_prepare(ctx, m):
        ctx.put("bal", 1)
        ctx.send("PROMISE", 0)

    @spec.on("proposer", "PROMISE")
    def prop_promise(ctx, m):
        ai = m["_from"] - 1
        ctx.put_at("prom", ai, 1)
        cnt = 0
        for a in range(NA):
            cnt = cnt + ctx.get_at("prom", a)
        go = (ctx.get("ph") == 0) & (cnt >= Q)
        ctx.put("ph", 1, when=go)
        for a in range(NA):
            ctx.send("ACCEPT", 1 + a, when=go)

    @spec.on("acceptor", "ACCEPT")
    def acc_accept(ctx, m):
        ctx.put("acc", 1)
        ctx.send("ACCEPTED", 0)

    @spec.on("proposer", "ACCEPTED")
    def prop_accepted(ctx, m):
        ai = m["_from"] - 1
        ctx.put_at("accs", ai, 1)
        cnt = 0
        for a in range(NA):
            cnt = cnt + ctx.get_at("accs", a)
        win = (ctx.get("ph") >= 1) & (cnt >= Q)
        ctx.put("dec", 1, when=win)
        ctx.put("ph", 2, when=win)

    for a in range(NA):
        spec.initial_messages.append(("PREPARE", 0, 1 + a, {}))

    def decided(v):
        return v.get("proposer", 0, "dec") == 1

    def none_decided(v):
        return v.get("proposer", 0, "dec") == 0

    if never_decided:
        spec.invariants["NONE_DECIDED"] = none_decided
    else:
        spec.goals["DECIDED"] = decided
    return spec


def paxos_partition_spec(n_acceptors: int = 3,
                         broken: bool = False) -> ProtocolSpec:
    """Single-decree Paxos under a checkable partition scenario
    (ISSUE 19 acceptance workload): the proposer and the acceptors sit
    in separate partition blocks, and the fault controller may CUT the
    link between them once (``max_eras=1``) and HEAL it again — the
    search explores every interleaving of the cut with the protocol's
    own messages.

    Two modes share one invariant, DECIDE_HAS_QUORUM (``dec == 1``
    implies a true majority of ACCEPTED bits):

    * ``broken=False`` — honest majority quorum.  The invariant holds
      on every reachable state: a decision needs ``NA//2+1`` ACCEPTED
      messages through the (possibly cut-then-healed) link, and each
      carries a real acceptor bit.  Exhaustive search (goal pruned to
      a prune by the scenario tests) proves safety with exact counts.

    * ``broken=True`` — quorum deliberately lowered to 1 AND the
      partition starts cut (``initial_cut=True``): the initial
      PREPAREs are frozen in flight until the controller fires HEAL,
      so every path to the (unsafe, single-vote) decision contains the
      HEAL fault event — the violation witness must name it.  The
      DECIDED goal is removed so the search runs to the violation."""
    from dslabs_tpu.tpu.faults import FaultModel, Partition

    NA = n_acceptors
    maj = NA // 2 + 1
    fm = FaultModel(partition=Partition(
        blocks=(("proposer",), ("acceptor",)),
        max_eras=1, initial_cut=broken))
    spec = paxos_spec(n_acceptors=NA, quorum=1 if broken else 0,
                      fault=fm)
    spec.name = "paxos-part-broken" if broken else "paxos-part"
    if broken:
        del spec.goals["DECIDED"]

    def decide_has_quorum(v):
        import jax.numpy as jnp

        return ((v.get("proposer", 0, "dec") == 0)
                | (jnp.sum(v.get("proposer", 0, "accs")) >= maj))

    spec.invariants["DECIDE_HAS_QUORUM"] = decide_has_quorum
    return spec


def pb_crash_spec(ns: int = 2, n_clients: int = 1,
                  w: int = 1) -> ProtocolSpec:
    """Primary-backup under a crash-recovery scenario (ISSUE 19): any
    server may crash once and restart.  The per-client ``amo``
    (at-most-once) table is declared DURABLE — it survives the crash —
    while the rest of the server state (view number, sync/primary
    bits, pending op) is volatile and resets to field inits on
    restart, forcing re-sync through the view service.  The protocol
    observes the crash only as message loss and timer silence; the
    exactly-once obligation must hold across it."""
    from dslabs_tpu.tpu.faults import Crash, FaultModel

    fm = FaultModel(crash=Crash(durable={"server": ("amo",)},
                                max_crashes=1))
    spec = pb_spec(ns=ns, n_clients=n_clients, w=w, fault=fm)
    spec.name = "pb-crash"
    return spec
