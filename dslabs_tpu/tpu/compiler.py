"""Protocol schema compiler: declarative bounded-state specs -> tensor
twins (SURVEY §8.1 "Protocol IR ... schema compiler for bounded protocol
state").

The hand-written twins in ``tpu/protocols/`` are expert artifacts: lane
layouts, one-hot muxing, send/set row budgeting, SENTINEL discipline.
This module mechanises exactly that layer.  A :class:`ProtocolSpec`
declares what the reference framework gets from a ``Node`` subclass —
node kinds with bounded integer fields, message/timer types with
payload fields, and handlers — and ``compile()`` derives the
:class:`~dslabs_tpu.tpu.engine.TensorProtocol`:

- fields -> packed node lanes (layout, offsets, init vector),
- message/timer enums -> tags + fixed-width records,
- handlers -> the engine's ``step_message``/``step_timer`` contract,
  with per-(kind, instance, type) guard conditions, jnp.where field
  merges, and exact send/set row budgets counted from the handler's
  ``ctx.send``/``ctx.set_timer`` calls (finalize-style loud assertion,
  never truncation).

Handlers are plain Python functions written against the tiny
:class:`Ctx` combinator API — reads, conditional writes, sends, timer
sets, and integer arithmetic on traced scalars — NOT raw jax: the
compiler owns every tensor-shape decision, which is what makes a new
protocol searchable without twin-authoring expertise (the reference
analog: any Node subclass is searchable for free,
framework/src/dslabs/framework/Node.java:106-602 + Search.java:405-505).

First-cut scope (deliberate): single-instance node kinds with scalar
or small-array int fields, handlers without cross-node reads (exactly
the Node contract — nodes communicate only by messages/timers).  The
lab 0 and lab 1 specs in ``tpu/specs.py`` compile to twins that match
the hand-written ones state-for-state (tests/test_compiler.py).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["Field", "MessageType", "TimerType", "NodeKind",
           "ProtocolSpec", "Ctx", "SpecError"]


class SpecError(Exception):
    """A structured spec-conformance failure raised at
    :meth:`ProtocolSpec.compile` time (ISSUE 10 satellite: malformed
    specs used to surface as bare KeyError/shape errors deep inside the
    engine; now the offending handler and field are named at the
    compile gate, which is what lets the conformance linter —
    ``python -m dslabs_tpu.analysis conformance`` — treat compile as
    the C4 spec-hygiene authority for generated twins, ROADMAP #3).

    ``handler``/``kind``/``field``/``line`` carry the structured
    location; ``code`` is the sanitizer rule that owns the failure
    (C4 unless stated otherwise)."""

    def __init__(self, message: str, *, spec: Optional[str] = None,
                 handler: Optional[str] = None,
                 kind: Optional[str] = None,
                 field: Optional[str] = None,
                 line: Optional[int] = None,
                 code: str = "C4"):
        self.spec = spec
        self.handler = handler
        self.kind = kind
        self.field = field
        self.line = line
        self.code = code
        loc = ""
        if handler:
            loc = f" [handler {handler}" + (
                f" @ line {line}]" if line else "]")
        super().__init__(f"{code}: {message}{loc}")


@dataclasses.dataclass(frozen=True)
class Field:
    """A bounded int field of a node: scalar (size 1) or a small int
    array (size > 1).  ``init`` is an int or a per-instance callable
    ``(instance_index) -> int | list``."""

    name: str
    size: int = 1
    init: object = 0


@dataclasses.dataclass(frozen=True)
class MessageType:
    name: str
    fields: Tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class TimerType:
    name: str
    fields: Tuple[str, ...] = ()
    min_ms: int = 10
    max_ms: int = 10


@dataclasses.dataclass(frozen=True)
class NodeKind:
    """``count`` instances of a node kind, each with the same fields.
    Twin node indices are assigned kind-by-kind in declaration order."""

    name: str
    count: int
    fields: Tuple[Field, ...]


class Ctx:
    """Handler combinator context for ONE (kind, instance) under ONE
    guard condition.  All mutation is conditional on the guard (and any
    ``when`` refinement): the compiler merges every branch with
    jnp.where, exactly the hand-twin discipline."""

    def __init__(self, spec, st, kind, idx, cond, sends, sets,
                 handler=None):
        self._spec = spec
        self._st = st
        self._kind = kind
        self._idx = idx
        self._cond = cond
        self._sends = sends
        self._sets = sets
        self._handler = handler        # (name, firstlineno) or None

    def _err(self, message: str, field: Optional[str] = None):
        name, line = self._handler or (None, None)
        return SpecError(message, spec=self._spec.name, handler=name,
                         kind=self._kind, field=field, line=line)

    def _key(self, field: str, op: str):
        key = (self._kind, self._idx, field)
        if key not in self._st:
            declared = sorted({f for k, _, f in self._st
                               if k == self._kind})
            raise self._err(
                f"{op} of undeclared field {field!r} on kind "
                f"{self._kind!r} (declared: {declared})", field=field)
        return key

    # ---------------------------------------------------------- accessors

    def get(self, field: str):
        """Current value of ``field`` (scalar, or [size] vector)."""
        return self._st[self._key(field, "get")]

    def put(self, field: str, value, when=True):
        """Conditionally set ``field`` (guard & when)."""
        import jax.numpy as jnp

        key = self._key(field, "put")
        cur = self._st[key]
        val = jnp.asarray(value, jnp.int32)
        self._st[key] = jnp.where(self._cond & when, val, cur).astype(
            jnp.int32)

    def get_at(self, field: str, i):
        """Dynamic element read of an array field — one-hot select, the
        engine's static-indexing rule (traced-index gathers are the
        measured vmap pathology).  Size-1 array fields unpack as
        scalars; treat them as one-element vectors."""
        import jax.numpy as jnp

        vec = jnp.atleast_1d(self._st[self._key(field, "get_at")])
        oh = jnp.arange(vec.shape[0]) == i
        return jnp.sum(jnp.where(oh, vec, 0))

    def put_at(self, field: str, i, value, when=True):
        import jax.numpy as jnp

        key = self._key(field, "put_at")
        cur = self._st[key]
        vec = jnp.atleast_1d(cur)
        oh = (jnp.arange(vec.shape[0]) == i) & self._cond & when
        out = jnp.where(oh, jnp.asarray(value, jnp.int32), vec).astype(
            jnp.int32)
        self._st[key] = out if cur.ndim else out[0]

    def cond(self, extra):
        """A refined child context (guard & extra) for nested logic."""
        return Ctx(self._spec, self._st, self._kind, self._idx,
                   self._cond & extra, self._sends, self._sets,
                   handler=self._handler)

    # ------------------------------------------------------------ effects

    def send(self, msg: str, to, when=True, **fields):
        m = self._spec._mspec.get(msg)
        if m is None:
            raise self._err(
                f"send of undeclared message {msg!r} (declared: "
                f"{sorted(self._spec._mspec)})", field=msg)
        unknown = sorted(set(fields) - set(m.fields))
        missing = sorted(set(m.fields) - set(fields))
        if unknown or missing:
            raise self._err(
                f"send({msg!r}): "
                + (f"unknown fields {unknown}" if unknown else "")
                + (" and " if unknown and missing else "")
                + (f"missing fields {missing}" if missing else ""),
                field=(unknown or missing)[0])
        self._sends.append(
            (self._spec._msg_row(msg, self.node_index(), to, fields),
             self._cond & when))

    def set_timer(self, timer: str, when=True, **fields):
        t = self._spec._tspec.get(timer)
        if t is None:
            raise self._err(
                f"set_timer of undeclared timer {timer!r} (declared: "
                f"{sorted(self._spec._tspec)})", field=timer)
        unknown = sorted(set(fields) - set(t.fields))
        missing = sorted(set(t.fields) - set(fields))
        if unknown or missing:
            raise self._err(
                f"set_timer({timer!r}): "
                + (f"unknown fields {unknown}" if unknown else "")
                + (" and " if unknown and missing else "")
                + (f"missing fields {missing}" if missing else ""),
                field=(unknown or missing)[0])
        self._sets.append(
            (self._spec._timer_row(timer, self.node_index(), fields),
             self._cond & when))

    def node_index(self):
        return self._spec._node_index(self._kind, self._idx)


class ProtocolSpec:

    def __init__(self, name: str,
                 nodes: Sequence[NodeKind],
                 messages: Sequence[MessageType],
                 timers: Sequence[TimerType],
                 net_cap: int = 16,
                 timer_cap: int = 4):
        self.name = name
        self.nodes = list(nodes)
        self.messages = list(messages)
        self.timers = list(timers)
        self.net_cap = net_cap
        self.timer_cap = timer_cap
        # (kind, message/timer name) -> handler(ctx, payload dict)
        self.handlers: Dict[Tuple[str, str], Callable] = {}
        self.timer_handlers: Dict[Tuple[str, str], Callable] = {}
        self.initial_messages: List[tuple] = []   # (msg, frm, to, fields)
        self.initial_timers: List[tuple] = []     # (timer, node, fields)
        self.goals: Dict[str, Callable] = {}      # name -> fn(view)
        self.invariants: Dict[str, Callable] = {}
        self.decode_message: Optional[Callable] = None
        self.decode_timer: Optional[Callable] = None
        self._mtag = {m.name: i for i, m in enumerate(self.messages)}
        self._mspec = {m.name: m for m in self.messages}
        # Timer tag 0 is reserved (SENTINEL-adjacent "no tag") to keep
        # records visibly distinct from zeroed lanes.
        self._ttag = {t.name: 1 + i for i, t in enumerate(self.timers)}
        self._tspec = {t.name: t for t in self.timers}
        self._mw = 3 + max((len(m.fields) for m in self.messages),
                           default=0)
        self._tw = 3 + max((len(t.fields) for t in self.timers),
                           default=0)       # [tag, min, max, fields...]

    # ------------------------------------------------------------- layout

    def on(self, kind: str, msg: str):
        def reg(fn):
            self.handlers[(kind, msg)] = fn
            return fn
        return reg

    def on_timer(self, kind: str, timer: str):
        def reg(fn):
            self.timer_handlers[(kind, timer)] = fn
            return fn
        return reg

    def _instances(self):
        for kind in self.nodes:
            for i in range(kind.count):
                yield kind, i

    def _node_index(self, kind_name: str, idx: int) -> int:
        base = 0
        for kind in self.nodes:
            if kind.name == kind_name:
                return base + idx
            base += kind.count
        raise KeyError(kind_name)

    def _layout(self):
        """(kind, idx, field) -> (offset, size); total width."""
        off = 0
        table = {}
        for kind, i in self._instances():
            for f in kind.fields:
                table[(kind.name, i, f.name)] = (off, f.size)
                off += f.size
        return table, off

    def _msg_row(self, name, frm, to, fields):
        import jax.numpy as jnp

        m = self._mspec[name]
        vals = dict(fields)
        lanes = [jnp.asarray(self._mtag[name], jnp.int32),
                 jnp.asarray(frm, jnp.int32), jnp.asarray(to, jnp.int32)]
        for f in m.fields:
            lanes.append(jnp.asarray(vals.pop(f), jnp.int32))
        assert not vals, f"{name}: unknown fields {sorted(vals)}"
        while len(lanes) < self._mw:
            lanes.append(jnp.zeros((), jnp.int32))
        return jnp.stack(lanes)

    def _timer_row(self, name, node, fields):
        import jax.numpy as jnp

        t = self._tspec[name]
        vals = dict(fields)
        lanes = [jnp.asarray(node, jnp.int32),
                 jnp.asarray(self._ttag[name], jnp.int32),
                 jnp.asarray(t.min_ms, jnp.int32),
                 jnp.asarray(t.max_ms, jnp.int32)]
        for f in t.fields:
            lanes.append(jnp.asarray(vals.pop(f), jnp.int32))
        assert not vals, f"{name}: unknown fields {sorted(vals)}"
        while len(lanes) < 1 + self._tw:
            lanes.append(jnp.zeros((), jnp.int32))
        return jnp.stack(lanes)

    # ----------------------------------------------------------- validate

    def _handler_id(self, fn):
        try:
            return (fn.__name__, fn.__code__.co_firstlineno)
        except AttributeError:
            return (getattr(fn, "__name__", repr(fn)), None)

    def validate(self) -> None:
        """The C4 spec-hygiene compile gate (ISSUE 10): handler
        registrations must reference declared node kinds and declared
        message/timer types, and initial messages/timers must name
        declared types — raised as structured :class:`SpecError`
        instead of the bare KeyError/shape errors malformed specs used
        to die with deep inside the engine.  Run automatically at the
        top of :meth:`compile`; the conformance linter
        (dslabs_tpu/analysis/conformance.py) reports the same failures
        as findings without raising."""
        kinds = {k.name for k in self.nodes}
        for (kind, msg), fn in self.handlers.items():
            name, line = self._handler_id(fn)
            if kind not in kinds:
                raise SpecError(
                    f"handler registered for unknown node kind "
                    f"{kind!r} (declared: {sorted(kinds)})",
                    spec=self.name, handler=name, kind=kind, line=line)
            if msg not in self._mtag:
                raise SpecError(
                    f"handler registered for unknown message {msg!r} "
                    f"(declared: {sorted(self._mtag)})",
                    spec=self.name, handler=name, kind=kind, field=msg,
                    line=line)
        for (kind, timer), fn in self.timer_handlers.items():
            name, line = self._handler_id(fn)
            if kind not in kinds:
                raise SpecError(
                    f"timer handler registered for unknown node kind "
                    f"{kind!r} (declared: {sorted(kinds)})",
                    spec=self.name, handler=name, kind=kind, line=line)
            if timer not in self._ttag:
                raise SpecError(
                    f"timer handler registered for unknown timer "
                    f"{timer!r} (declared: {sorted(self._ttag)})",
                    spec=self.name, handler=name, kind=kind,
                    field=timer, line=line)
        for name, *_ in self.initial_messages:
            if name not in self._mspec:
                raise SpecError(
                    f"initial message of undeclared type {name!r}",
                    spec=self.name, field=name)
        for name, *_ in self.initial_timers:
            if name not in self._tspec:
                raise SpecError(
                    f"initial timer of undeclared type {name!r}",
                    spec=self.name, field=name)

    # ------------------------------------------------------------ compile

    def compile(self):
        """-> TensorProtocol (the engine contract, engine.py:94-146)."""
        import jax.numpy as jnp

        from dslabs_tpu.tpu.engine import SENTINEL, TensorProtocol

        self.validate()
        table, nw = self._layout()
        n_nodes = sum(k.count for k in self.nodes)
        spec = self

        def unpack(nodes):
            st = {}
            for key, (off, size) in table.items():
                st[key] = (nodes[off] if size == 1
                           else nodes[off:off + size])
            return st

        def repack(st):
            parts = []
            for key, (off, size) in table.items():
                v = st[key]
                parts.append(v[None] if size == 1 else v)
            return jnp.concatenate(parts).astype(jnp.int32)

        # Static send/set budgets: trace each handler once with a dummy
        # context to COUNT its effect rows (the finalize-assert
        # discipline of the hand twins, without the hand counting).
        max_sends, max_sets = self._count_budgets()

        def _finalize(rows, budget, width):
            blank = jnp.full((width,), SENTINEL, jnp.int32)
            out = []
            for rec, cond in rows:
                out.append(jnp.where(cond, rec, blank))
            assert len(out) <= budget, (len(out), budget)
            while len(out) < budget:
                out.append(blank)
            return jnp.stack(out) if out else jnp.zeros((0, width),
                                                        jnp.int32)

        def step_message(nodes, msg):
            st = unpack(nodes)
            sends, sets = [], []
            tag, frm, to = msg[0], msg[1], msg[2]
            for kind, i in spec._instances():
                here = to == spec._node_index(kind.name, i)
                for m in spec.messages:
                    fn = spec.handlers.get((kind.name, m.name))
                    if fn is None:
                        continue
                    cond = here & (tag == spec._mtag[m.name])
                    payload = {f: msg[3 + j]
                               for j, f in enumerate(m.fields)}
                    payload["_from"] = frm
                    ctx = Ctx(spec, st, kind.name, i, cond, sends, sets,
                              handler=spec._handler_id(fn))
                    spec._invoke(fn, ctx, payload, m.name)
            return (repack(st), _finalize(sends, max_sends, spec._mw),
                    _finalize(sets, max_sets, 1 + spec._tw))

        def step_timer(nodes, node_idx, timer):
            st = unpack(nodes)
            sends, sets = [], []
            tag = timer[0]
            for kind, i in spec._instances():
                here = node_idx == spec._node_index(kind.name, i)
                for t in spec.timers:
                    fn = spec.timer_handlers.get((kind.name, t.name))
                    if fn is None:
                        continue
                    cond = here & (tag == spec._ttag[t.name])
                    payload = {f: timer[3 + j]
                               for j, f in enumerate(t.fields)}
                    ctx = Ctx(spec, st, kind.name, i, cond, sends, sets,
                              handler=spec._handler_id(fn))
                    spec._invoke(fn, ctx, payload, t.name)
            return (repack(st), _finalize(sends, max_sends, spec._mw),
                    _finalize(sets, max_sets, 1 + spec._tw))

        def init_nodes():
            out = np.zeros((nw,), np.int32)
            for (kind_name, i, fname), (off, size) in table.items():
                kind = next(k for k in self.nodes if k.name == kind_name)
                f = next(x for x in kind.fields if x.name == fname)
                v = f.init(i) if callable(f.init) else f.init
                out[off:off + size] = v
            return out

        def init_messages():
            rows = []
            for name, frm, to, fields in self.initial_messages:
                m = self._mspec[name]
                rec = np.zeros((self._mw,), np.int32)
                rec[0:3] = [self._mtag[name], frm, to]
                for j, f in enumerate(m.fields):
                    rec[3 + j] = fields[f]
                rows.append(rec)
            return (np.stack(rows) if rows
                    else np.zeros((0, self._mw), np.int32))

        def init_timers():
            rows = []
            for name, node, fields in self.initial_timers:
                t = self._tspec[name]
                rec = np.zeros((1 + self._tw,), np.int32)
                rec[0:4] = [node, self._ttag[name], t.min_ms, t.max_ms]
                for j, f in enumerate(t.fields):
                    rec[4 + j] = fields[f]
                rows.append(rec)
            return (np.stack(rows) if rows
                    else np.zeros((0, 1 + self._tw), np.int32))

        def _pred(fn):
            def wrapped(state):
                return fn(_View(spec, table, state["nodes"]))
            return wrapped

        return TensorProtocol(
            name=self.name,
            n_nodes=n_nodes,
            node_width=nw,
            msg_width=self._mw,
            timer_width=self._tw,
            net_cap=self.net_cap,
            timer_cap=self.timer_cap,
            max_sends=max(max_sends, 1),
            max_sets=max(max_sets, 1),
            init_nodes=init_nodes,
            init_messages=init_messages,
            init_timers=init_timers,
            step_message=step_message,
            step_timer=step_timer,
            msg_dest=lambda msg: msg[2],
            goals={k: _pred(v) for k, v in self.goals.items()},
            invariants={k: _pred(v) for k, v in self.invariants.items()},
            decode_message=self.decode_message,
            decode_timer=self.decode_timer,
        )

    def _invoke(self, fn, ctx: "Ctx", payload: dict, typ: str):
        """Run one handler under the compile gate: a KeyError on the
        payload dict (reading a field the message/timer type does not
        declare) surfaces as a structured SpecError naming the handler
        — the bare-KeyError shape this satellite retires."""
        try:
            return fn(ctx, payload)
        except KeyError as e:
            name, line = self._handler_id(fn)
            missing = e.args[0] if e.args else "?"
            raise SpecError(
                f"read of field {missing!r} not declared by "
                f"{typ!r} (payload fields: "
                f"{sorted(k for k in payload if k != '_from')})",
                spec=self.name, handler=name, field=str(missing),
                line=line) from e

    def _count_budgets(self) -> Tuple[int, int]:
        """Count worst-case send/set rows by running every handler once
        with a counting context (handlers are straight-line over the
        combinators, so one run = its static row count).  The compiled
        step accumulates ALL handlers' rows into one block per step
        kind, so the budget is the larger of the message-step and
        timer-step TOTALS."""
        import jax.numpy as jnp

        table, _ = self._layout()

        def dummy_state():
            return {key: (jnp.zeros((), jnp.int32) if size == 1
                          else jnp.zeros((size,), jnp.int32))
                    for key, (_, size) in table.items()}

        false = jnp.asarray(False)
        msg_sends = msg_sets = tmr_sends = tmr_sets = 0
        for kind, i in self._instances():
            for m in self.messages:
                fn = self.handlers.get((kind.name, m.name))
                if fn is None:
                    continue
                sends, sets = [], []
                ctx = Ctx(self, dummy_state(), kind.name, i, false,
                          sends, sets, handler=self._handler_id(fn))
                self._invoke(
                    fn, ctx, {f: jnp.zeros((), jnp.int32)
                              for f in m.fields} | {"_from": jnp.zeros(
                                  (), jnp.int32)}, m.name)
                msg_sends += len(sends)
                msg_sets += len(sets)
            for t in self.timers:
                fn = self.timer_handlers.get((kind.name, t.name))
                if fn is None:
                    continue
                sends, sets = [], []
                ctx = Ctx(self, dummy_state(), kind.name, i, false,
                          sends, sets, handler=self._handler_id(fn))
                self._invoke(
                    fn, ctx,
                    {f: jnp.zeros((), jnp.int32) for f in t.fields},
                    t.name)
                tmr_sends += len(sends)
                tmr_sets += len(sets)
        return (max(msg_sends, tmr_sends), max(msg_sets, tmr_sets))


class _View:
    """Read-only predicate view over the packed lanes of one state."""

    def __init__(self, spec, table, nodes):
        self._table = table
        self._nodes = nodes

    def get(self, kind: str, idx: int, field: str):
        off, size = self._table[(kind, idx, field)]
        return (self._nodes[off] if size == 1
                else self._nodes[off:off + size])
