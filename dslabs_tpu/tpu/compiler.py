"""Protocol schema compiler: declarative bounded-state specs -> tensor
twins (SURVEY §8.1 "Protocol IR ... schema compiler for bounded protocol
state").

The hand-written twins in ``tpu/protocols/`` are expert artifacts: lane
layouts, one-hot muxing, send/set row budgeting, SENTINEL discipline.
This module mechanises exactly that layer.  A :class:`ProtocolSpec`
declares what the reference framework gets from a ``Node`` subclass —
node kinds with bounded integer fields, message/timer types with
payload fields, and handlers — and ``compile()`` derives the
:class:`~dslabs_tpu.tpu.engine.TensorProtocol`:

- fields -> packed node lanes (layout, offsets, init vector),
- message/timer enums -> tags + fixed-width records,
- handlers -> the engine's ``step_message``/``step_timer`` contract,
  with per-(kind, instance, type) guard conditions, jnp.where field
  merges, and exact send/set row budgets counted from the handler's
  ``ctx.send``/``ctx.set_timer`` calls (finalize-style loud assertion,
  never truncation).

Handlers are plain Python functions written against the tiny
:class:`Ctx` combinator API — reads, conditional writes, sends, timer
sets, and integer arithmetic on traced scalars — NOT raw jax: the
compiler owns every tensor-shape decision, which is what makes a new
protocol searchable without twin-authoring expertise (the reference
analog: any Node subclass is searchable for free,
framework/src/dslabs/framework/Node.java:106-602 + Search.java:405-505).

First-cut scope (deliberate): single-instance node kinds with scalar
or small-array int fields, handlers without cross-node reads (exactly
the Node contract — nodes communicate only by messages/timers).  The
lab 0 and lab 1 specs in ``tpu/specs.py`` compile to twins that match
the hand-written ones state-for-state (tests/test_compiler.py).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["Field", "MessageType", "TimerType", "NodeKind",
           "ProtocolSpec", "Ctx", "SpecError", "Fragment"]


class SpecError(Exception):
    """A structured spec-conformance failure raised at
    :meth:`ProtocolSpec.compile` time (ISSUE 10 satellite: malformed
    specs used to surface as bare KeyError/shape errors deep inside the
    engine; now the offending handler and field are named at the
    compile gate, which is what lets the conformance linter —
    ``python -m dslabs_tpu.analysis conformance`` — treat compile as
    the C4 spec-hygiene authority for generated twins, ROADMAP #3).

    ``handler``/``kind``/``field``/``line`` carry the structured
    location; ``code`` is the sanitizer rule that owns the failure
    (C4 unless stated otherwise)."""

    def __init__(self, message: str, *, spec: Optional[str] = None,
                 handler: Optional[str] = None,
                 kind: Optional[str] = None,
                 field: Optional[str] = None,
                 line: Optional[int] = None,
                 code: str = "C4"):
        self.spec = spec
        self.handler = handler
        self.kind = kind
        self.field = field
        self.line = line
        self.code = code
        loc = ""
        if handler:
            loc = f" [handler {handler}" + (
                f" @ line {line}]" if line else "]")
        super().__init__(f"{code}: {message}{loc}")


@dataclasses.dataclass(frozen=True)
class Field:
    """A bounded int field of a node: scalar (size 1) or a small int
    array (size > 1).  ``init`` is an int or a per-instance callable
    ``(instance_index) -> int | list``.

    ``lo``/``hi`` declare the field's value DOMAIN — the input to the
    bit-packed frontier encoding (ISSUE 15, tpu/packing.py): a field
    with ``hi`` set is stored in ``ceil(log2(hi - lo + 1))`` bits on
    the packed frontier; ``hi=None`` (the default) keeps the full
    int32 lane.  Domains are enforced loudly: an out-of-domain live
    value is a CapacityOverflow, never silent corruption, and init
    values are range-checked at compile time.

    ``delta`` declares an UNBOUNDED monotone-ish counter (view
    numbers, liveness ticks — fields a static ``hi`` cannot cap) for
    the delta-from-level-base encoding (ISSUE 18, tpu/packing.py):
    the mesh engine stores ``v - base`` in ``delta`` bits, carrying
    the per-level base alongside the frontier; engines that do not
    track a base (the single-device path) keep the full int32 lane.
    ``delta`` and ``hi`` are mutually exclusive.

    ``index_group`` names a node KIND whose instances index this array
    field (size must equal that kind's count): when the kind is
    declared in the spec's ``symmetry`` groups, the canonicalize pass
    permutes this array's elements together with the node ids
    (tpu/symmetry.py) — per-member bitmaps/counters stay coherent
    under relabeling."""

    name: str
    size: int = 1
    init: object = 0
    lo: int = 0
    hi: Optional[int] = None
    index_group: Optional[str] = None
    delta: Optional[int] = None


@dataclasses.dataclass(frozen=True)
class MessageType:
    """``bounds`` maps payload field name -> (lo, hi) domain for the
    packed encoding (tpu/packing.py); undeclared fields keep full
    int32 lanes.  Tag/from/to lanes derive their domains from the
    spec itself (tag cardinality, node count)."""

    name: str
    fields: Tuple[str, ...] = ()
    bounds: Optional[Dict[str, Tuple[int, int]]] = None


@dataclasses.dataclass(frozen=True)
class TimerType:
    name: str
    fields: Tuple[str, ...] = ()
    min_ms: int = 10
    max_ms: int = 10
    bounds: Optional[Dict[str, Tuple[int, int]]] = None


@dataclasses.dataclass(frozen=True)
class NodeKind:
    """``count`` instances of a node kind, each with the same fields.
    Twin node indices are assigned kind-by-kind in declaration order.
    ``fields`` may mix plain :class:`Field`s with
    :class:`~dslabs_tpu.tpu.slots.Slots` blocks (ISSUE 20) — the spec
    expands each block to its struct-of-arrays lanes at construction
    and remembers the declaration for the Ctx slot ops."""

    name: str
    count: int
    fields: Tuple[Field, ...]


class Fragment:
    """A composable sub-state-machine (ISSUE 20): a named bundle of
    fields (plain or :class:`~dslabs_tpu.tpu.slots.Slots`), message and
    timer types, and handlers, attached to a node kind with
    :meth:`ProtocolSpec.include`.  This is how lab4's shardstore spec
    states its shape — a per-group Paxos fragment + a reconfiguration-
    epoch fragment + a 2PC vote fragment composed onto the server kind
    — instead of one monolithic handler set.  Inclusion is structural:
    fields append to the kind's layout, types merge into the spec's
    enums (same-name re-declarations must be identical), handlers
    register under the including kind, and the (kind, fragment) pair is
    recorded on ``spec.fragments`` so the memo fingerprint and the
    conformance linter see the composition."""

    def __init__(self, name: str, fields: Sequence[object] = (),
                 messages: Sequence[MessageType] = (),
                 timers: Sequence[TimerType] = ()):
        self.name = name
        self.fields = tuple(fields)
        self.messages = tuple(messages)
        self.timers = tuple(timers)
        self.handlers: Dict[str, Callable] = {}
        self.timer_handlers: Dict[str, Callable] = {}

    def on(self, msg: str):
        def reg(fn):
            self.handlers[msg] = fn
            return fn
        return reg

    def on_timer(self, timer: str):
        def reg(fn):
            self.timer_handlers[timer] = fn
            return fn
        return reg


class Ctx:
    """Handler combinator context for ONE (kind, instance) under ONE
    guard condition.  All mutation is conditional on the guard (and any
    ``when`` refinement): the compiler merges every branch with
    jnp.where, exactly the hand-twin discipline."""

    def __init__(self, spec, st, kind, idx, cond, sends, sets,
                 handler=None, excs=None):
        self._spec = spec
        self._st = st
        self._kind = kind
        self._idx = idx
        self._cond = cond
        self._sends = sends
        self._sets = sets
        self._excs = excs if excs is not None else []
        self._handler = handler        # (name, firstlineno) or None

    def _err(self, message: str, field: Optional[str] = None):
        name, line = self._handler or (None, None)
        return SpecError(message, spec=self._spec.name, handler=name,
                         kind=self._kind, field=field, line=line)

    def _key(self, field: str, op: str):
        key = (self._kind, self._idx, field)
        if key not in self._st:
            declared = sorted({f for k, _, f in self._st
                               if k == self._kind})
            raise self._err(
                f"{op} of undeclared field {field!r} on kind "
                f"{self._kind!r} (declared: {declared})", field=field)
        return key

    # ---------------------------------------------------------- accessors

    def get(self, field: str):
        """Current value of ``field`` (scalar, or [size] vector)."""
        return self._st[self._key(field, "get")]

    def put(self, field: str, value, when=True):
        """Conditionally set ``field`` (guard & when)."""
        import jax.numpy as jnp

        key = self._key(field, "put")
        cur = self._st[key]
        val = jnp.asarray(value, jnp.int32)
        self._st[key] = jnp.where(self._cond & when, val, cur).astype(
            jnp.int32)

    def _check_static_index(self, field: str, i, size: int, op: str):
        """A STATIC index outside the declared range is a loud
        compile-gate error (ISSUE 20): the one-hot mux would otherwise
        return a silent 0 / drop the write — exactly the class of bug
        the slot layer exists to retire.  Traced indices pass through
        (the mux masks them, matching the hand twins)."""
        if isinstance(i, (int, np.integer)) and not 0 <= int(i) < size:
            raise self._err(
                f"{op} of field {field!r}: static index {int(i)} "
                f"outside declared range [0, {size})", field=field)

    def get_at(self, field: str, i):
        """Dynamic element read of an array field — one-hot select, the
        engine's static-indexing rule (traced-index gathers are the
        measured vmap pathology).  Size-1 array fields unpack as
        scalars; treat them as one-element vectors."""
        import jax.numpy as jnp

        vec = jnp.atleast_1d(self._st[self._key(field, "get_at")])
        self._check_static_index(field, i, vec.shape[0], "get_at")
        oh = jnp.arange(vec.shape[0]) == i
        return jnp.sum(jnp.where(oh, vec, 0))

    def put_at(self, field: str, i, value, when=True):
        import jax.numpy as jnp

        key = self._key(field, "put_at")
        cur = self._st[key]
        vec = jnp.atleast_1d(cur)
        self._check_static_index(field, i, vec.shape[0], "put_at")
        oh = (jnp.arange(vec.shape[0]) == i) & self._cond & when
        out = jnp.where(oh, jnp.asarray(value, jnp.int32), vec).astype(
            jnp.int32)
        self._st[key] = out if cur.ndim else out[0]

    def cond(self, extra):
        """A refined child context (guard & extra) for nested logic."""
        return Ctx(self._spec, self._st, self._kind, self._idx,
                   self._cond & extra, self._sends, self._sets,
                   handler=self._handler, excs=self._excs)

    # ------------------------------------------------------------- slots

    def _slot_block(self, block: str, op: str):
        decl = self._spec.slot_blocks.get((self._kind, block))
        if decl is None:
            declared = sorted(b for k, b in self._spec.slot_blocks
                              if k == self._kind)
            raise self._err(
                f"{op} of undeclared Slots block {block!r} on kind "
                f"{self._kind!r} (declared: {declared})", field=block)
        touched = getattr(self._spec, "_touched_slots", None)
        if touched is not None:
            touched.add((self._kind, block))
        return decl

    def slot_get(self, block: str, field: str, i):
        """Read one record field of LOGICAL slot ``i`` (the block's
        ``base`` offset is spec data, not handler arithmetic)."""
        decl = self._slot_block(block, "slot_get")
        if isinstance(i, (int, np.integer)) and not (
                decl.base <= int(i) < decl.base + decl.n):
            raise self._err(
                f"slot_get of block {block!r}: static slot index "
                f"{int(i)} outside declared range "
                f"[{decl.base}, {decl.base + decl.n})", field=field)
        return self.get_at(decl.lane(field), i - decl.base)

    def slot_put(self, block: str, field: str, i, value, when=True):
        decl = self._slot_block(block, "slot_put")
        if isinstance(i, (int, np.integer)) and not (
                decl.base <= int(i) < decl.base + decl.n):
            raise self._err(
                f"slot_put of block {block!r}: static slot index "
                f"{int(i)} outside declared range "
                f"[{decl.base}, {decl.base + decl.n})", field=field)
        self.put_at(decl.lane(field), i - decl.base, value, when=when)

    def slot_clear_upto(self, block: str, upto, when=True):
        """Slot-windowed garbage bound: every slot with logical index
        STRICTLY below ``upto`` resets to its declared ``clear`` value
        (all record fields) — the lab3 log-GC pattern as one lowering.
        ``upto`` may be traced; the window mask rides the guard."""
        import jax.numpy as jnp

        decl = self._slot_block(block, "slot_clear_upto")
        idx = jnp.arange(decl.n) + decl.base
        win = (idx < upto) & self._cond & when
        for sf in decl.fields:
            key = self._key(decl.lane(sf.name), "slot_clear_upto")
            cur = jnp.atleast_1d(self._st[key])
            self._st[key] = jnp.where(win, sf.clear, cur).astype(
                jnp.int32)

    # ------------------------------------------------------------ quorum

    def quorum(self, name: str):
        """The spec-declared quorum ``name`` in resolved form
        (tpu/quorum.py Quorum: group size, vote threshold, reducers)."""
        q = self._spec.resolved_quorums().get(name)
        if q is None:
            raise self._err(
                f"read of undeclared quorum {name!r} (declared: "
                f"{sorted(self._spec.resolved_quorums())})", field=name)
        touched = getattr(self._spec, "_touched_quorums", None)
        if touched is not None:
            touched.add(name)
        return q

    def fail(self, code: int, when=True):
        """Raise the tensor analog of a handler exception: the step's
        ``exc`` lane becomes ``code`` when the guard (and ``when``)
        holds — the hand twins' pack-width guard discipline, now a
        combinator.  ``code`` must be a static positive int so the
        packed exc lane's domain is known at compile time."""
        if not isinstance(code, (int, np.integer)) or int(code) <= 0:
            raise self._err(
                f"fail() code must be a static positive int, got "
                f"{code!r}")
        self._excs.append((int(code), self._cond & when))

    # ------------------------------------------------------------ effects

    def send(self, msg: str, to, when=True, **fields):
        m = self._spec._mspec.get(msg)
        if m is None:
            raise self._err(
                f"send of undeclared message {msg!r} (declared: "
                f"{sorted(self._spec._mspec)})", field=msg)
        sent = getattr(self._spec, "_touched_sends", None)
        if sent is not None:
            sent.add(msg)
        unknown = sorted(set(fields) - set(m.fields))
        missing = sorted(set(m.fields) - set(fields))
        if unknown or missing:
            raise self._err(
                f"send({msg!r}): "
                + (f"unknown fields {unknown}" if unknown else "")
                + (" and " if unknown and missing else "")
                + (f"missing fields {missing}" if missing else ""),
                field=(unknown or missing)[0])
        self._sends.append(
            (self._spec._msg_row(msg, self.node_index(), to, fields),
             self._cond & when))

    def set_timer(self, timer: str, when=True, **fields):
        t = self._spec._tspec.get(timer)
        if t is None:
            raise self._err(
                f"set_timer of undeclared timer {timer!r} (declared: "
                f"{sorted(self._spec._tspec)})", field=timer)
        unknown = sorted(set(fields) - set(t.fields))
        missing = sorted(set(t.fields) - set(fields))
        if unknown or missing:
            raise self._err(
                f"set_timer({timer!r}): "
                + (f"unknown fields {unknown}" if unknown else "")
                + (" and " if unknown and missing else "")
                + (f"missing fields {missing}" if missing else ""),
                field=(unknown or missing)[0])
        self._sets.append(
            (self._spec._timer_row(timer, self.node_index(), fields),
             self._cond & when))

    def node_index(self):
        return self._spec._node_index(self._kind, self._idx)


class ProtocolSpec:

    def __init__(self, name: str,
                 nodes: Sequence[NodeKind],
                 messages: Sequence[MessageType],
                 timers: Sequence[TimerType],
                 net_cap: int = 16,
                 timer_cap: int = 4,
                 symmetry: Sequence[str] = (),
                 fault: Optional[object] = None,
                 quorums: Sequence[object] = (),
                 max_live_sends: Optional[int] = None):
        self.name = name
        # Multi-instance slot blocks (ISSUE 20, tpu/slots.py): each
        # Slots declaration inside NodeKind.fields expands to its
        # struct-of-arrays lanes here; the declaration itself is kept
        # for Ctx slot ops, fingerprinting, and conformance.
        self.slot_blocks: Dict[Tuple[str, str], object] = {}
        self.nodes = [self._expand_kind(k) for k in nodes]
        # Quorum declarations (ISSUE 20, tpu/quorum.py): resolved (and
        # refused when empty/unknown) at validate(); handlers reach
        # them via ctx.quorum(name).
        self.quorums = tuple(quorums)
        self._quorums_resolved: Optional[Dict[str, object]] = None
        # Composed sub-state machines: (kind, fragment name) pairs in
        # inclusion order — structural identity for the memo
        # fingerprint (service/memo.py).
        self.fragments: List[Tuple[str, str]] = []
        self.max_live_sends = max_live_sends
        # Declarative fault model (ISSUE 19, tpu/faults.py): when set,
        # a hidden controller node kind ("$fault") is appended LAST so
        # partition/crash/drop/dup budgets live in ordinary bounded
        # Fields — packing, symmetry, spill and checkpoints carry them
        # with zero special cases.  compile() attaches the lowered
        # FaultLanes descriptor to TensorProtocol.fault; fault=None
        # specs lower byte-identically to the pre-fault program.
        self.fault = fault
        if fault is not None:
            from dslabs_tpu.tpu.faults import controller_kind
            self.nodes.append(controller_kind(fault, self.nodes))
        self.messages = list(messages)
        self.timers = list(timers)
        self.net_cap = net_cap
        self.timer_cap = timer_cap
        # Symmetry groups (ISSUE 15, tpu/symmetry.py): names of node
        # KINDS whose instances are interchangeable — handlers must
        # treat every member identically (the C5 conformance rule).
        # compile() emits the canonical-relabeling permutation tables;
        # the engines' opt-in canonicalize pass (default OFF) dedups
        # symmetric twins to one representative.
        self.symmetry = tuple(symmetry)
        # (kind, message/timer name) -> handler(ctx, payload dict)
        self.handlers: Dict[Tuple[str, str], Callable] = {}
        self.timer_handlers: Dict[Tuple[str, str], Callable] = {}
        self.initial_messages: List[tuple] = []   # (msg, frm, to, fields)
        self.initial_timers: List[tuple] = []     # (timer, node, fields)
        self.goals: Dict[str, Callable] = {}      # name -> fn(view)
        self.invariants: Dict[str, Callable] = {}
        self.decode_message: Optional[Callable] = None
        self.decode_timer: Optional[Callable] = None
        self._reindex_types()

    def _reindex_types(self) -> None:
        """(Re)build the tag/spec/width tables — called at construction
        and after a :meth:`include` merges fragment types in."""
        self._mtag = {m.name: i for i, m in enumerate(self.messages)}
        self._mspec = {m.name: m for m in self.messages}
        # Timer tag 0 is reserved (SENTINEL-adjacent "no tag") to keep
        # records visibly distinct from zeroed lanes.
        self._ttag = {t.name: 1 + i for i, t in enumerate(self.timers)}
        self._tspec = {t.name: t for t in self.timers}
        self._mw = 3 + max((len(m.fields) for m in self.messages),
                           default=0)
        self._tw = 3 + max((len(t.fields) for t in self.timers),
                           default=0)       # [tag, min, max, fields...]

    def _expand_kind(self, kind: NodeKind) -> NodeKind:
        """Expand Slots blocks inside a kind's fields to their lowered
        array Fields, recording each declaration for the Ctx slot
        ops."""
        from dslabs_tpu.tpu.slots import Slots, expand_slots

        if not any(isinstance(f, Slots) for f in kind.fields):
            return kind
        out: List[Field] = []
        for f in kind.fields:
            if isinstance(f, Slots):
                if (kind.name, f.name) in self.slot_blocks:
                    raise SpecError(
                        f"duplicate Slots block {f.name!r} on kind "
                        f"{kind.name!r}", spec=self.name,
                        kind=kind.name, field=f.name)
                self.slot_blocks[(kind.name, f.name)] = f
                out.extend(expand_slots(f, Field))
            else:
                out.append(f)
        return dataclasses.replace(kind, fields=tuple(out))

    def include(self, kind: str, fragment: "Fragment") -> None:
        """Compose a :class:`Fragment` onto a declared node kind: its
        fields append to the kind's layout, its message/timer types
        merge into the spec enums (identical re-declaration tolerated,
        conflicting redefinition refused), and its handlers register
        under the kind.  Must run before :meth:`compile`."""
        for pos, k in enumerate(self.nodes):
            if k.name == kind:
                break
        else:
            raise SpecError(
                f"include of fragment {fragment.name!r} on unknown "
                f"node kind {kind!r} (declared: "
                f"{sorted(x.name for x in self.nodes)})",
                spec=self.name, kind=kind, field=fragment.name)
        if (kind, fragment.name) in self.fragments:
            raise SpecError(
                f"fragment {fragment.name!r} included twice on kind "
                f"{kind!r}", spec=self.name, kind=kind,
                field=fragment.name)
        ext = self._expand_kind(dataclasses.replace(
            self.nodes[pos],
            fields=self.nodes[pos].fields + tuple(fragment.fields)))
        self.nodes[pos] = ext
        for m in fragment.messages:
            cur = next((x for x in self.messages if x.name == m.name),
                       None)
            if cur is None:
                self.messages.append(m)
            elif cur != m:
                raise SpecError(
                    f"fragment {fragment.name!r} redeclares message "
                    f"{m.name!r} with a different shape",
                    spec=self.name, kind=kind, field=m.name)
        for t in fragment.timers:
            cur = next((x for x in self.timers if x.name == t.name),
                       None)
            if cur is None:
                self.timers.append(t)
            elif cur != t:
                raise SpecError(
                    f"fragment {fragment.name!r} redeclares timer "
                    f"{t.name!r} with a different shape",
                    spec=self.name, kind=kind, field=t.name)
        for msg, fn in fragment.handlers.items():
            if (kind, msg) in self.handlers:
                raise SpecError(
                    f"fragment {fragment.name!r} handler for "
                    f"{msg!r} collides with an existing handler on "
                    f"kind {kind!r}", spec=self.name, kind=kind,
                    field=msg)
            self.handlers[(kind, msg)] = fn
        for tmr, fn in fragment.timer_handlers.items():
            if (kind, tmr) in self.timer_handlers:
                raise SpecError(
                    f"fragment {fragment.name!r} timer handler for "
                    f"{tmr!r} collides with an existing handler on "
                    f"kind {kind!r}", spec=self.name, kind=kind,
                    field=tmr)
            self.timer_handlers[(kind, tmr)] = fn
        self.fragments.append((kind, fragment.name))
        self._reindex_types()

    def resolved_quorums(self) -> Dict[str, object]:
        """Declared quorums resolved against the node kinds (cached);
        raises the structured refusal for empty/unknown groups."""
        if self._quorums_resolved is None:
            from dslabs_tpu.tpu.quorum import resolve_quorums
            self._quorums_resolved = resolve_quorums(self)
        return self._quorums_resolved

    # ------------------------------------------------------------- layout

    def on(self, kind: str, msg: str):
        def reg(fn):
            self.handlers[(kind, msg)] = fn
            return fn
        return reg

    def on_timer(self, kind: str, timer: str):
        def reg(fn):
            self.timer_handlers[(kind, timer)] = fn
            return fn
        return reg

    def _instances(self):
        for kind in self.nodes:
            for i in range(kind.count):
                yield kind, i

    def _node_index(self, kind_name: str, idx: int) -> int:
        base = 0
        for kind in self.nodes:
            if kind.name == kind_name:
                return base + idx
            base += kind.count
        raise KeyError(kind_name)

    def _layout(self):
        """(kind, idx, field) -> (offset, size); total width."""
        off = 0
        table = {}
        for kind, i in self._instances():
            for f in kind.fields:
                table[(kind.name, i, f.name)] = (off, f.size)
                off += f.size
        return table, off

    def _msg_row(self, name, frm, to, fields):
        import jax.numpy as jnp

        m = self._mspec[name]
        vals = dict(fields)
        lanes = [jnp.asarray(self._mtag[name], jnp.int32),
                 jnp.asarray(frm, jnp.int32), jnp.asarray(to, jnp.int32)]
        for f in m.fields:
            lanes.append(jnp.asarray(vals.pop(f), jnp.int32))
        assert not vals, f"{name}: unknown fields {sorted(vals)}"
        while len(lanes) < self._mw:
            lanes.append(jnp.zeros((), jnp.int32))
        return jnp.stack(lanes)

    def _timer_row(self, name, node, fields):
        import jax.numpy as jnp

        t = self._tspec[name]
        vals = dict(fields)
        lanes = [jnp.asarray(node, jnp.int32),
                 jnp.asarray(self._ttag[name], jnp.int32),
                 jnp.asarray(t.min_ms, jnp.int32),
                 jnp.asarray(t.max_ms, jnp.int32)]
        for f in t.fields:
            lanes.append(jnp.asarray(vals.pop(f), jnp.int32))
        assert not vals, f"{name}: unknown fields {sorted(vals)}"
        while len(lanes) < 1 + self._tw:
            lanes.append(jnp.zeros((), jnp.int32))
        return jnp.stack(lanes)

    # ----------------------------------------------------------- validate

    def _handler_id(self, fn):
        try:
            return (fn.__name__, fn.__code__.co_firstlineno)
        except AttributeError:
            return (getattr(fn, "__name__", repr(fn)), None)

    def validate(self) -> None:
        """The C4 spec-hygiene compile gate (ISSUE 10): handler
        registrations must reference declared node kinds and declared
        message/timer types, and initial messages/timers must name
        declared types — raised as structured :class:`SpecError`
        instead of the bare KeyError/shape errors malformed specs used
        to die with deep inside the engine.  Run automatically at the
        top of :meth:`compile`; the conformance linter
        (dslabs_tpu/analysis/conformance.py) reports the same failures
        as findings without raising."""
        from dslabs_tpu.tpu.faults import FAULT_KIND, validate_fault
        n_ctrl = sum(1 for k in self.nodes if k.name == FAULT_KIND)
        if n_ctrl != (1 if self.fault is not None else 0):
            raise SpecError(
                f"node kind name {FAULT_KIND!r} is reserved for the "
                "fault controller (declare faults via fault=FaultModel"
                "(...), not as a node kind)",
                spec=self.name, kind=FAULT_KIND, code="C6")
        if self.fault is not None:
            for (kind, _msg) in list(self.handlers) + \
                    list(self.timer_handlers):
                if kind == FAULT_KIND:
                    raise SpecError(
                        "handlers may not be registered on the fault "
                        "controller kind — protocols observe faults "
                        "only through message loss and timer silence",
                        spec=self.name, kind=FAULT_KIND, code="C6")
            validate_fault(self)
        # Quorum declarations resolve (and refuse empty/unknown
        # groups) at the same gate (ISSUE 20, tpu/quorum.py).
        self._quorums_resolved = None
        self.resolved_quorums()
        kinds = {k.name for k in self.nodes}
        for (kind, msg), fn in self.handlers.items():
            name, line = self._handler_id(fn)
            if kind not in kinds:
                raise SpecError(
                    f"handler registered for unknown node kind "
                    f"{kind!r} (declared: {sorted(kinds)})",
                    spec=self.name, handler=name, kind=kind, line=line)
            if msg not in self._mtag:
                raise SpecError(
                    f"handler registered for unknown message {msg!r} "
                    f"(declared: {sorted(self._mtag)})",
                    spec=self.name, handler=name, kind=kind, field=msg,
                    line=line)
        for (kind, timer), fn in self.timer_handlers.items():
            name, line = self._handler_id(fn)
            if kind not in kinds:
                raise SpecError(
                    f"timer handler registered for unknown node kind "
                    f"{kind!r} (declared: {sorted(kinds)})",
                    spec=self.name, handler=name, kind=kind, line=line)
            if timer not in self._ttag:
                raise SpecError(
                    f"timer handler registered for unknown timer "
                    f"{timer!r} (declared: {sorted(self._ttag)})",
                    spec=self.name, handler=name, kind=kind,
                    field=timer, line=line)
        for name, *_ in self.initial_messages:
            if name not in self._mspec:
                raise SpecError(
                    f"initial message of undeclared type {name!r}",
                    spec=self.name, field=name)
        for name, *_ in self.initial_timers:
            if name not in self._tspec:
                raise SpecError(
                    f"initial timer of undeclared type {name!r}",
                    spec=self.name, field=name)
        kind_counts = {k.name: k.count for k in self.nodes}
        for g in self.symmetry:
            if g not in kinds:
                raise SpecError(
                    f"symmetry group names unknown node kind {g!r} "
                    f"(declared: {sorted(kinds)})",
                    spec=self.name, kind=g, code="C5")
        for kind in self.nodes:
            for f in kind.fields:
                if f.hi is not None and f.hi < f.lo:
                    raise SpecError(
                        f"field {f.name!r} on kind {kind.name!r} has "
                        f"empty domain [{f.lo}, {f.hi}]",
                        spec=self.name, kind=kind.name, field=f.name)
                if f.index_group is not None:
                    if f.index_group not in kind_counts:
                        raise SpecError(
                            f"field {f.name!r} on kind {kind.name!r} "
                            f"declares index_group for unknown kind "
                            f"{f.index_group!r}",
                            spec=self.name, kind=kind.name,
                            field=f.name, code="C5")
                    if f.size != kind_counts[f.index_group]:
                        raise SpecError(
                            f"field {f.name!r} on kind {kind.name!r} "
                            f"has size {f.size} but index_group "
                            f"{f.index_group!r} has "
                            f"{kind_counts[f.index_group]} instances",
                            spec=self.name, kind=kind.name,
                            field=f.name, code="C5")
                if f.delta is not None and f.hi is not None:
                    raise SpecError(
                        f"field {f.name!r} on kind {kind.name!r} "
                        "declares both hi= and delta= — a bounded "
                        "domain and the delta-from-base lane are "
                        "mutually exclusive", spec=self.name,
                        kind=kind.name, field=f.name, code="C5")
                # Init values must sit inside the declared domain —
                # the packed encoding would otherwise corrupt the root
                # state silently (tpu/packing.py).
                if f.hi is not None:
                    for i in range(kind.count):
                        v = f.init(i) if callable(f.init) else f.init
                        vals = np.atleast_1d(np.asarray(v)).tolist()
                        for x in vals:
                            if not (f.lo <= int(x) <= f.hi):
                                raise SpecError(
                                    f"init value {x} of field "
                                    f"{f.name!r} on kind {kind.name!r} "
                                    f"outside declared domain "
                                    f"[{f.lo}, {f.hi}]",
                                    spec=self.name, kind=kind.name,
                                    field=f.name)

    # -------------------------------------------- packing / symmetry

    def _lane_domains(self) -> dict:
        """Per-lane value domains for the bit-packed frontier encoding
        (tpu/packing.py): the structural lanes (message/timer tags,
        from/to node indices, timer min/max) derive from the spec
        itself; field/payload lanes from the declared ``lo``/``hi``
        bounds, ``None`` (full int32) where undeclared."""
        n_nodes = sum(k.count for k in self.nodes)
        nodes = []
        for kind, _i in self._instances():
            for f in kind.fields:
                if f.hi is not None:
                    dom = (f.lo, f.hi)
                elif f.delta is not None:
                    # Delta-from-base lane (ISSUE 18): engines that
                    # carry a level base pack this in f.delta bits;
                    # others derive it as raw (packing.derive_packing).
                    dom = ("delta", int(f.delta))
                else:
                    dom = None
                nodes += [dom] * f.size
        node_dom = (0, max(n_nodes - 1, 0))

        def _merge(entries):
            """Union of (lo, hi) domains; None poisons."""
            lo = hi = None
            for e in entries:
                if e is None:
                    return None
                lo = e[0] if lo is None else min(lo, e[0])
                hi = e[1] if hi is None else max(hi, e[1])
            return (0, 0) if lo is None else (lo, hi)

        msg = [(0, max(len(self.messages) - 1, 0)), node_dom, node_dom]
        for j in range(self._mw - 3):
            entries = []
            for m in self.messages:
                if j < len(m.fields):
                    entries.append((m.bounds or {}).get(m.fields[j]))
                else:
                    entries.append((0, 0))      # zero-padded lane
            msg.append(_merge(entries))
        tmr = [(0, len(self.timers)),
               _merge([(t.min_ms, t.min_ms) for t in self.timers]),
               _merge([(t.max_ms, t.max_ms) for t in self.timers])]
        for j in range(self._tw - 3):
            entries = []
            for t in self.timers:
                if j < len(t.fields):
                    entries.append((t.bounds or {}).get(t.fields[j]))
                else:
                    entries.append((0, 0))
            tmr.append(_merge(entries))
        # The exc lane spans the declared ctx.fail codes; without any
        # the compiled steps never set it (_normalize_step pads exc=0)
        # and the lane is a constant.
        return {"nodes": nodes, "msg": msg, "timer": tmr,
                "exc": (0, getattr(self, "_exc_hi", 0))}

    def _symmetry_spec(self, table):
        """Build the canonical-relabeling permutation tables for the
        declared symmetry groups (tpu/symmetry.py SymmetrySpec), or
        None when no groups are declared."""
        if not self.symmetry:
            return None
        import itertools

        from dslabs_tpu.tpu.symmetry import SymmetrySpec

        n_nodes = sum(k.count for k in self.nodes)
        _, nw = self._layout()
        bases = {}
        off = 0
        for kind in self.nodes:
            bases[kind.name] = off
            off += kind.count
        groups = []
        total = 1
        for g in self.symmetry:
            count = next(k.count for k in self.nodes if k.name == g)
            groups.append((g, bases[g], count))
            for i in range(2, count + 1):
                total *= i
        if total > 720:
            raise SpecError(
                f"symmetry groups expand to {total} permutations "
                "(> 720) — the fused canonicalize pass enumerates "
                "them; shrink the groups", spec=self.name, code="C5")
        per_group = [list(itertools.permutations(range(c)))
                     for _g, _b, c in groups]
        relabs, lane_srcs = [], []
        for combo in itertools.product(*per_group):
            relab = np.arange(n_nodes, dtype=np.int64)
            lane_src = np.arange(nw, dtype=np.int64)
            for (g, base, count), sigma in zip(groups, combo):
                # new position j holds old member sigma[j]
                for j in range(count):
                    relab[base + sigma[j]] = base + j
                kind = next(k for k in self.nodes if k.name == g)
                for j in range(count):
                    for f in kind.fields:
                        dst, size = table[(g, j, f.name)]
                        src, _ = table[(g, sigma[j], f.name)]
                        lane_src[dst:dst + size] = np.arange(
                            src, src + size)
                # Group-indexed array fields permute their ELEMENTS
                # with the group (per-member bitmaps stay coherent).
                # Restricted to fields on kinds OUTSIDE the group
                # itself (validated below), so every assignment reads
                # original (identity) positions — no composition.
                for kind2, i2 in self._instances():
                    for f in kind2.fields:
                        if f.index_group != g:
                            continue
                        if kind2.name == g:
                            raise SpecError(
                                f"field {f.name!r}: index_group on a "
                                f"kind inside its own symmetry group "
                                f"{g!r} is unsupported",
                                spec=self.name, kind=kind2.name,
                                field=f.name, code="C5")
                        o2, _ = table[(kind2.name, i2, f.name)]
                        for j in range(count):
                            lane_src[o2 + j] = o2 + sigma[j]
            relabs.append(relab)
            lane_srcs.append(lane_src)
        # Identity permutation first (the canonicalizer's cheap first
        # candidate); itertools.product with sorted permutations
        # yields it first already, but pin it explicitly.
        order = sorted(range(len(relabs)),
                       key=lambda i: 0 if (relabs[i]
                                           == np.arange(n_nodes)).all()
                       else 1)
        return SymmetrySpec(
            relab=np.stack([relabs[i] for i in order]),
            lane_src=np.stack([lane_srcs[i] for i in order]),
            groups=tuple((g, b, c) for g, b, c in groups))

    # ------------------------------------------------------------ compile

    def compile(self):
        """-> TensorProtocol (the engine contract, engine.py:94-146)."""
        import jax.numpy as jnp

        from dslabs_tpu.tpu.engine import SENTINEL, TensorProtocol

        self.validate()
        table, nw = self._layout()
        n_nodes = sum(k.count for k in self.nodes)
        spec = self

        def unpack(nodes):
            st = {}
            for key, (off, size) in table.items():
                st[key] = (nodes[off] if size == 1
                           else nodes[off:off + size])
            return st

        def repack(st):
            parts = []
            for key, (off, size) in table.items():
                v = st[key]
                parts.append(v[None] if size == 1 else v)
            return jnp.concatenate(parts).astype(jnp.int32)

        # Static send/set budgets: trace each handler once with a dummy
        # context to COUNT its effect rows (the finalize-assert
        # discipline of the hand twins, without the hand counting).
        max_sends, max_sets = self._count_budgets()

        uses_exc = self._exc_hi > 0

        def _finalize(groups, budget, width):
            """Merge per-invocation row groups into one [budget, width]
            block.  Invocations are pairwise mutually exclusive (see
            _count_budgets), so row j of the step is jnp.minimum over
            every group's SENTINEL-blanked row j: at most one group
            contributes live rows, SENTINEL (int32 max) loses every
            minimum, and an all-false step yields an all-blank block —
            exactly the hand twins' jnp.minimum merge discipline."""
            blank = jnp.full((width,), SENTINEL, jnp.int32)
            merged = [blank] * budget
            for rows in groups:
                assert len(rows) <= budget, (len(rows), budget)
                for j, (rec, cond) in enumerate(rows):
                    merged[j] = jnp.minimum(
                        merged[j], jnp.where(cond, rec, blank))
            return (jnp.stack(merged) if merged
                    else jnp.zeros((0, width), jnp.int32))

        def _exc_lane(excs):
            out = jnp.zeros((), jnp.int32)
            for code, cond in excs:
                out = jnp.maximum(out, jnp.where(cond, code, 0))
            return out

        def step_message(nodes, msg):
            st = unpack(nodes)
            send_groups, set_groups, excs = [], [], []
            tag, frm, to = msg[0], msg[1], msg[2]
            for kind, i in spec._instances():
                here = to == spec._node_index(kind.name, i)
                for m in spec.messages:
                    fn = spec.handlers.get((kind.name, m.name))
                    if fn is None:
                        continue
                    cond = here & (tag == spec._mtag[m.name])
                    payload = {f: msg[3 + j]
                               for j, f in enumerate(m.fields)}
                    payload["_from"] = frm
                    sends, sets = [], []
                    ctx = Ctx(spec, st, kind.name, i, cond, sends, sets,
                              handler=spec._handler_id(fn), excs=excs)
                    spec._invoke(fn, ctx, payload, m.name)
                    send_groups.append(sends)
                    set_groups.append(sets)
            out = (repack(st),
                   _finalize(send_groups, max_sends, spec._mw),
                   _finalize(set_groups, max_sets, 1 + spec._tw))
            return out + ((_exc_lane(excs),) if uses_exc else ())

        def step_timer(nodes, node_idx, timer):
            st = unpack(nodes)
            send_groups, set_groups, excs = [], [], []
            tag = timer[0]
            for kind, i in spec._instances():
                here = node_idx == spec._node_index(kind.name, i)
                for t in spec.timers:
                    fn = spec.timer_handlers.get((kind.name, t.name))
                    if fn is None:
                        continue
                    cond = here & (tag == spec._ttag[t.name])
                    payload = {f: timer[3 + j]
                               for j, f in enumerate(t.fields)}
                    sends, sets = [], []
                    ctx = Ctx(spec, st, kind.name, i, cond, sends, sets,
                              handler=spec._handler_id(fn), excs=excs)
                    spec._invoke(fn, ctx, payload, t.name)
                    send_groups.append(sends)
                    set_groups.append(sets)
            out = (repack(st),
                   _finalize(send_groups, max_sends, spec._mw),
                   _finalize(set_groups, max_sets, 1 + spec._tw))
            return out + ((_exc_lane(excs),) if uses_exc else ())

        def init_nodes():
            out = np.zeros((nw,), np.int32)
            for (kind_name, i, fname), (off, size) in table.items():
                kind = next(k for k in self.nodes if k.name == kind_name)
                f = next(x for x in kind.fields if x.name == fname)
                v = f.init(i) if callable(f.init) else f.init
                out[off:off + size] = v
            return out

        def init_messages():
            rows = []
            for name, frm, to, fields in self.initial_messages:
                m = self._mspec[name]
                rec = np.zeros((self._mw,), np.int32)
                rec[0:3] = [self._mtag[name], frm, to]
                for j, f in enumerate(m.fields):
                    rec[3 + j] = fields[f]
                rows.append(rec)
            return (np.stack(rows) if rows
                    else np.zeros((0, self._mw), np.int32))

        def init_timers():
            rows = []
            for name, node, fields in self.initial_timers:
                t = self._tspec[name]
                rec = np.zeros((1 + self._tw,), np.int32)
                rec[0:4] = [node, self._ttag[name], t.min_ms, t.max_ms]
                for j, f in enumerate(t.fields):
                    rec[4 + j] = fields[f]
                rows.append(rec)
            return (np.stack(rows) if rows
                    else np.zeros((0, 1 + self._tw), np.int32))

        def _pred(fn):
            def wrapped(state):
                return fn(_View(spec, table, state["nodes"]))
            return wrapped

        fault_lanes = None
        if self.fault is not None:
            from dslabs_tpu.tpu.faults import compile_fault_lanes
            fault_lanes = compile_fault_lanes(self, table, nw,
                                              init_nodes())

        return TensorProtocol(
            name=self.name,
            n_nodes=n_nodes,
            node_width=nw,
            lane_domains=self._lane_domains(),
            symmetry=self._symmetry_spec(table),
            fault=fault_lanes,
            msg_width=self._mw,
            timer_width=self._tw,
            net_cap=self.net_cap,
            timer_cap=self.timer_cap,
            max_sends=max(max_sends, 1),
            max_sets=max(max_sets, 1),
            max_live_sends=self.max_live_sends,
            init_nodes=init_nodes,
            init_messages=init_messages,
            init_timers=init_timers,
            step_message=step_message,
            step_timer=step_timer,
            msg_dest=lambda msg: msg[2],
            goals={k: _pred(v) for k, v in self.goals.items()},
            invariants={k: _pred(v) for k, v in self.invariants.items()},
            decode_message=self.decode_message,
            decode_timer=self.decode_timer,
        )

    def _invoke(self, fn, ctx: "Ctx", payload: dict, typ: str):
        """Run one handler under the compile gate: a KeyError on the
        payload dict (reading a field the message/timer type does not
        declare) surfaces as a structured SpecError naming the handler
        — the bare-KeyError shape this satellite retires."""
        try:
            return fn(ctx, payload)
        except KeyError as e:
            name, line = self._handler_id(fn)
            missing = e.args[0] if e.args else "?"
            raise SpecError(
                f"read of field {missing!r} not declared by "
                f"{typ!r} (payload fields: "
                f"{sorted(k for k in payload if k != '_from')})",
                spec=self.name, handler=name, field=str(missing),
                line=line) from e

    def _count_budgets(self) -> Tuple[int, int]:
        """Count worst-case send/set rows by running every handler once
        with a counting context (handlers are straight-line over the
        combinators, so one run = its static row count).

        Handler invocations within one step are pairwise mutually
        exclusive — each is guarded by ``(to == node_idx) & (tag ==
        mtag)`` and at most one (node, type) pair matches a delivered
        record — so the compiled step MERGES their row groups
        (jnp.minimum over SENTINEL-blanked rows) instead of
        concatenating them.  The budget is therefore the MAX single
        invocation's row count, not the sum: this is what keeps
        MAX_SENDS at hand-twin scale for lab3/lab4, where summing
        across ~40 handler instances would explode the send block
        (ISSUE 20).

        Also records exc-lane usage for :meth:`_lane_domains`:
        ``self._exc_hi`` is the largest static ``ctx.fail`` code (0
        when no handler fails)."""
        import jax.numpy as jnp

        table, _ = self._layout()

        def dummy_state():
            return {key: (jnp.zeros((), jnp.int32) if size == 1
                          else jnp.zeros((size,), jnp.int32))
                    for key, (_, size) in table.items()}

        false = jnp.asarray(False)
        max_sends = max_sets = 0
        self._exc_hi = 0
        # Coverage record for the conformance linter's soft C4 half:
        # which Slots blocks and quorums the dry-run actually touched.
        self._touched_slots = set()
        self._touched_quorums = set()
        self._touched_sends = set()
        for kind, i in self._instances():
            for m in self.messages:
                fn = self.handlers.get((kind.name, m.name))
                if fn is None:
                    continue
                sends, sets, excs = [], [], []
                ctx = Ctx(self, dummy_state(), kind.name, i, false,
                          sends, sets, handler=self._handler_id(fn),
                          excs=excs)
                self._invoke(
                    fn, ctx, {f: jnp.zeros((), jnp.int32)
                              for f in m.fields} | {"_from": jnp.zeros(
                                  (), jnp.int32)}, m.name)
                max_sends = max(max_sends, len(sends))
                max_sets = max(max_sets, len(sets))
                for code, _c in excs:
                    self._exc_hi = max(self._exc_hi, code)
            for t in self.timers:
                fn = self.timer_handlers.get((kind.name, t.name))
                if fn is None:
                    continue
                sends, sets, excs = [], [], []
                ctx = Ctx(self, dummy_state(), kind.name, i, false,
                          sends, sets, handler=self._handler_id(fn),
                          excs=excs)
                self._invoke(
                    fn, ctx,
                    {f: jnp.zeros((), jnp.int32) for f in t.fields},
                    t.name)
                max_sends = max(max_sends, len(sends))
                max_sets = max(max_sets, len(sets))
                for code, _c in excs:
                    self._exc_hi = max(self._exc_hi, code)
        return (max_sends, max_sets)


class _View:
    """Read-only predicate view over the packed lanes of one state."""

    def __init__(self, spec, table, nodes):
        self._table = table
        self._nodes = nodes

    def get(self, kind: str, idx: int, field: str):
        off, size = self._table[(kind, idx, field)]
        return (self._nodes[off] if size == 1
                else self._nodes[off:off + size])
