"""Protocol schema compiler: declarative bounded-state specs -> tensor
twins (SURVEY §8.1 "Protocol IR ... schema compiler for bounded protocol
state").

The hand-written twins in ``tpu/protocols/`` are expert artifacts: lane
layouts, one-hot muxing, send/set row budgeting, SENTINEL discipline.
This module mechanises exactly that layer.  A :class:`ProtocolSpec`
declares what the reference framework gets from a ``Node`` subclass —
node kinds with bounded integer fields, message/timer types with
payload fields, and handlers — and ``compile()`` derives the
:class:`~dslabs_tpu.tpu.engine.TensorProtocol`:

- fields -> packed node lanes (layout, offsets, init vector),
- message/timer enums -> tags + fixed-width records,
- handlers -> the engine's ``step_message``/``step_timer`` contract,
  with per-(kind, instance, type) guard conditions, jnp.where field
  merges, and exact send/set row budgets counted from the handler's
  ``ctx.send``/``ctx.set_timer`` calls (finalize-style loud assertion,
  never truncation).

Handlers are plain Python functions written against the tiny
:class:`Ctx` combinator API — reads, conditional writes, sends, timer
sets, and integer arithmetic on traced scalars — NOT raw jax: the
compiler owns every tensor-shape decision, which is what makes a new
protocol searchable without twin-authoring expertise (the reference
analog: any Node subclass is searchable for free,
framework/src/dslabs/framework/Node.java:106-602 + Search.java:405-505).

First-cut scope (deliberate): single-instance node kinds with scalar
or small-array int fields, handlers without cross-node reads (exactly
the Node contract — nodes communicate only by messages/timers).  The
lab 0 and lab 1 specs in ``tpu/specs.py`` compile to twins that match
the hand-written ones state-for-state (tests/test_compiler.py).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["Field", "MessageType", "TimerType", "NodeKind",
           "ProtocolSpec", "Ctx", "SpecError"]


class SpecError(Exception):
    """A structured spec-conformance failure raised at
    :meth:`ProtocolSpec.compile` time (ISSUE 10 satellite: malformed
    specs used to surface as bare KeyError/shape errors deep inside the
    engine; now the offending handler and field are named at the
    compile gate, which is what lets the conformance linter —
    ``python -m dslabs_tpu.analysis conformance`` — treat compile as
    the C4 spec-hygiene authority for generated twins, ROADMAP #3).

    ``handler``/``kind``/``field``/``line`` carry the structured
    location; ``code`` is the sanitizer rule that owns the failure
    (C4 unless stated otherwise)."""

    def __init__(self, message: str, *, spec: Optional[str] = None,
                 handler: Optional[str] = None,
                 kind: Optional[str] = None,
                 field: Optional[str] = None,
                 line: Optional[int] = None,
                 code: str = "C4"):
        self.spec = spec
        self.handler = handler
        self.kind = kind
        self.field = field
        self.line = line
        self.code = code
        loc = ""
        if handler:
            loc = f" [handler {handler}" + (
                f" @ line {line}]" if line else "]")
        super().__init__(f"{code}: {message}{loc}")


@dataclasses.dataclass(frozen=True)
class Field:
    """A bounded int field of a node: scalar (size 1) or a small int
    array (size > 1).  ``init`` is an int or a per-instance callable
    ``(instance_index) -> int | list``.

    ``lo``/``hi`` declare the field's value DOMAIN — the input to the
    bit-packed frontier encoding (ISSUE 15, tpu/packing.py): a field
    with ``hi`` set is stored in ``ceil(log2(hi - lo + 1))`` bits on
    the packed frontier; ``hi=None`` (the default) keeps the full
    int32 lane.  Domains are enforced loudly: an out-of-domain live
    value is a CapacityOverflow, never silent corruption, and init
    values are range-checked at compile time.

    ``delta`` declares an UNBOUNDED monotone-ish counter (view
    numbers, liveness ticks — fields a static ``hi`` cannot cap) for
    the delta-from-level-base encoding (ISSUE 18, tpu/packing.py):
    the mesh engine stores ``v - base`` in ``delta`` bits, carrying
    the per-level base alongside the frontier; engines that do not
    track a base (the single-device path) keep the full int32 lane.
    ``delta`` and ``hi`` are mutually exclusive.

    ``index_group`` names a node KIND whose instances index this array
    field (size must equal that kind's count): when the kind is
    declared in the spec's ``symmetry`` groups, the canonicalize pass
    permutes this array's elements together with the node ids
    (tpu/symmetry.py) — per-member bitmaps/counters stay coherent
    under relabeling."""

    name: str
    size: int = 1
    init: object = 0
    lo: int = 0
    hi: Optional[int] = None
    index_group: Optional[str] = None
    delta: Optional[int] = None


@dataclasses.dataclass(frozen=True)
class MessageType:
    """``bounds`` maps payload field name -> (lo, hi) domain for the
    packed encoding (tpu/packing.py); undeclared fields keep full
    int32 lanes.  Tag/from/to lanes derive their domains from the
    spec itself (tag cardinality, node count)."""

    name: str
    fields: Tuple[str, ...] = ()
    bounds: Optional[Dict[str, Tuple[int, int]]] = None


@dataclasses.dataclass(frozen=True)
class TimerType:
    name: str
    fields: Tuple[str, ...] = ()
    min_ms: int = 10
    max_ms: int = 10
    bounds: Optional[Dict[str, Tuple[int, int]]] = None


@dataclasses.dataclass(frozen=True)
class NodeKind:
    """``count`` instances of a node kind, each with the same fields.
    Twin node indices are assigned kind-by-kind in declaration order."""

    name: str
    count: int
    fields: Tuple[Field, ...]


class Ctx:
    """Handler combinator context for ONE (kind, instance) under ONE
    guard condition.  All mutation is conditional on the guard (and any
    ``when`` refinement): the compiler merges every branch with
    jnp.where, exactly the hand-twin discipline."""

    def __init__(self, spec, st, kind, idx, cond, sends, sets,
                 handler=None):
        self._spec = spec
        self._st = st
        self._kind = kind
        self._idx = idx
        self._cond = cond
        self._sends = sends
        self._sets = sets
        self._handler = handler        # (name, firstlineno) or None

    def _err(self, message: str, field: Optional[str] = None):
        name, line = self._handler or (None, None)
        return SpecError(message, spec=self._spec.name, handler=name,
                         kind=self._kind, field=field, line=line)

    def _key(self, field: str, op: str):
        key = (self._kind, self._idx, field)
        if key not in self._st:
            declared = sorted({f for k, _, f in self._st
                               if k == self._kind})
            raise self._err(
                f"{op} of undeclared field {field!r} on kind "
                f"{self._kind!r} (declared: {declared})", field=field)
        return key

    # ---------------------------------------------------------- accessors

    def get(self, field: str):
        """Current value of ``field`` (scalar, or [size] vector)."""
        return self._st[self._key(field, "get")]

    def put(self, field: str, value, when=True):
        """Conditionally set ``field`` (guard & when)."""
        import jax.numpy as jnp

        key = self._key(field, "put")
        cur = self._st[key]
        val = jnp.asarray(value, jnp.int32)
        self._st[key] = jnp.where(self._cond & when, val, cur).astype(
            jnp.int32)

    def get_at(self, field: str, i):
        """Dynamic element read of an array field — one-hot select, the
        engine's static-indexing rule (traced-index gathers are the
        measured vmap pathology).  Size-1 array fields unpack as
        scalars; treat them as one-element vectors."""
        import jax.numpy as jnp

        vec = jnp.atleast_1d(self._st[self._key(field, "get_at")])
        oh = jnp.arange(vec.shape[0]) == i
        return jnp.sum(jnp.where(oh, vec, 0))

    def put_at(self, field: str, i, value, when=True):
        import jax.numpy as jnp

        key = self._key(field, "put_at")
        cur = self._st[key]
        vec = jnp.atleast_1d(cur)
        oh = (jnp.arange(vec.shape[0]) == i) & self._cond & when
        out = jnp.where(oh, jnp.asarray(value, jnp.int32), vec).astype(
            jnp.int32)
        self._st[key] = out if cur.ndim else out[0]

    def cond(self, extra):
        """A refined child context (guard & extra) for nested logic."""
        return Ctx(self._spec, self._st, self._kind, self._idx,
                   self._cond & extra, self._sends, self._sets,
                   handler=self._handler)

    # ------------------------------------------------------------ effects

    def send(self, msg: str, to, when=True, **fields):
        m = self._spec._mspec.get(msg)
        if m is None:
            raise self._err(
                f"send of undeclared message {msg!r} (declared: "
                f"{sorted(self._spec._mspec)})", field=msg)
        unknown = sorted(set(fields) - set(m.fields))
        missing = sorted(set(m.fields) - set(fields))
        if unknown or missing:
            raise self._err(
                f"send({msg!r}): "
                + (f"unknown fields {unknown}" if unknown else "")
                + (" and " if unknown and missing else "")
                + (f"missing fields {missing}" if missing else ""),
                field=(unknown or missing)[0])
        self._sends.append(
            (self._spec._msg_row(msg, self.node_index(), to, fields),
             self._cond & when))

    def set_timer(self, timer: str, when=True, **fields):
        t = self._spec._tspec.get(timer)
        if t is None:
            raise self._err(
                f"set_timer of undeclared timer {timer!r} (declared: "
                f"{sorted(self._spec._tspec)})", field=timer)
        unknown = sorted(set(fields) - set(t.fields))
        missing = sorted(set(t.fields) - set(fields))
        if unknown or missing:
            raise self._err(
                f"set_timer({timer!r}): "
                + (f"unknown fields {unknown}" if unknown else "")
                + (" and " if unknown and missing else "")
                + (f"missing fields {missing}" if missing else ""),
                field=(unknown or missing)[0])
        self._sets.append(
            (self._spec._timer_row(timer, self.node_index(), fields),
             self._cond & when))

    def node_index(self):
        return self._spec._node_index(self._kind, self._idx)


class ProtocolSpec:

    def __init__(self, name: str,
                 nodes: Sequence[NodeKind],
                 messages: Sequence[MessageType],
                 timers: Sequence[TimerType],
                 net_cap: int = 16,
                 timer_cap: int = 4,
                 symmetry: Sequence[str] = (),
                 fault: Optional[object] = None):
        self.name = name
        self.nodes = list(nodes)
        # Declarative fault model (ISSUE 19, tpu/faults.py): when set,
        # a hidden controller node kind ("$fault") is appended LAST so
        # partition/crash/drop/dup budgets live in ordinary bounded
        # Fields — packing, symmetry, spill and checkpoints carry them
        # with zero special cases.  compile() attaches the lowered
        # FaultLanes descriptor to TensorProtocol.fault; fault=None
        # specs lower byte-identically to the pre-fault program.
        self.fault = fault
        if fault is not None:
            from dslabs_tpu.tpu.faults import controller_kind
            self.nodes.append(controller_kind(fault, self.nodes))
        self.messages = list(messages)
        self.timers = list(timers)
        self.net_cap = net_cap
        self.timer_cap = timer_cap
        # Symmetry groups (ISSUE 15, tpu/symmetry.py): names of node
        # KINDS whose instances are interchangeable — handlers must
        # treat every member identically (the C5 conformance rule).
        # compile() emits the canonical-relabeling permutation tables;
        # the engines' opt-in canonicalize pass (default OFF) dedups
        # symmetric twins to one representative.
        self.symmetry = tuple(symmetry)
        # (kind, message/timer name) -> handler(ctx, payload dict)
        self.handlers: Dict[Tuple[str, str], Callable] = {}
        self.timer_handlers: Dict[Tuple[str, str], Callable] = {}
        self.initial_messages: List[tuple] = []   # (msg, frm, to, fields)
        self.initial_timers: List[tuple] = []     # (timer, node, fields)
        self.goals: Dict[str, Callable] = {}      # name -> fn(view)
        self.invariants: Dict[str, Callable] = {}
        self.decode_message: Optional[Callable] = None
        self.decode_timer: Optional[Callable] = None
        self._mtag = {m.name: i for i, m in enumerate(self.messages)}
        self._mspec = {m.name: m for m in self.messages}
        # Timer tag 0 is reserved (SENTINEL-adjacent "no tag") to keep
        # records visibly distinct from zeroed lanes.
        self._ttag = {t.name: 1 + i for i, t in enumerate(self.timers)}
        self._tspec = {t.name: t for t in self.timers}
        self._mw = 3 + max((len(m.fields) for m in self.messages),
                           default=0)
        self._tw = 3 + max((len(t.fields) for t in self.timers),
                           default=0)       # [tag, min, max, fields...]

    # ------------------------------------------------------------- layout

    def on(self, kind: str, msg: str):
        def reg(fn):
            self.handlers[(kind, msg)] = fn
            return fn
        return reg

    def on_timer(self, kind: str, timer: str):
        def reg(fn):
            self.timer_handlers[(kind, timer)] = fn
            return fn
        return reg

    def _instances(self):
        for kind in self.nodes:
            for i in range(kind.count):
                yield kind, i

    def _node_index(self, kind_name: str, idx: int) -> int:
        base = 0
        for kind in self.nodes:
            if kind.name == kind_name:
                return base + idx
            base += kind.count
        raise KeyError(kind_name)

    def _layout(self):
        """(kind, idx, field) -> (offset, size); total width."""
        off = 0
        table = {}
        for kind, i in self._instances():
            for f in kind.fields:
                table[(kind.name, i, f.name)] = (off, f.size)
                off += f.size
        return table, off

    def _msg_row(self, name, frm, to, fields):
        import jax.numpy as jnp

        m = self._mspec[name]
        vals = dict(fields)
        lanes = [jnp.asarray(self._mtag[name], jnp.int32),
                 jnp.asarray(frm, jnp.int32), jnp.asarray(to, jnp.int32)]
        for f in m.fields:
            lanes.append(jnp.asarray(vals.pop(f), jnp.int32))
        assert not vals, f"{name}: unknown fields {sorted(vals)}"
        while len(lanes) < self._mw:
            lanes.append(jnp.zeros((), jnp.int32))
        return jnp.stack(lanes)

    def _timer_row(self, name, node, fields):
        import jax.numpy as jnp

        t = self._tspec[name]
        vals = dict(fields)
        lanes = [jnp.asarray(node, jnp.int32),
                 jnp.asarray(self._ttag[name], jnp.int32),
                 jnp.asarray(t.min_ms, jnp.int32),
                 jnp.asarray(t.max_ms, jnp.int32)]
        for f in t.fields:
            lanes.append(jnp.asarray(vals.pop(f), jnp.int32))
        assert not vals, f"{name}: unknown fields {sorted(vals)}"
        while len(lanes) < 1 + self._tw:
            lanes.append(jnp.zeros((), jnp.int32))
        return jnp.stack(lanes)

    # ----------------------------------------------------------- validate

    def _handler_id(self, fn):
        try:
            return (fn.__name__, fn.__code__.co_firstlineno)
        except AttributeError:
            return (getattr(fn, "__name__", repr(fn)), None)

    def validate(self) -> None:
        """The C4 spec-hygiene compile gate (ISSUE 10): handler
        registrations must reference declared node kinds and declared
        message/timer types, and initial messages/timers must name
        declared types — raised as structured :class:`SpecError`
        instead of the bare KeyError/shape errors malformed specs used
        to die with deep inside the engine.  Run automatically at the
        top of :meth:`compile`; the conformance linter
        (dslabs_tpu/analysis/conformance.py) reports the same failures
        as findings without raising."""
        from dslabs_tpu.tpu.faults import FAULT_KIND, validate_fault
        n_ctrl = sum(1 for k in self.nodes if k.name == FAULT_KIND)
        if n_ctrl != (1 if self.fault is not None else 0):
            raise SpecError(
                f"node kind name {FAULT_KIND!r} is reserved for the "
                "fault controller (declare faults via fault=FaultModel"
                "(...), not as a node kind)",
                spec=self.name, kind=FAULT_KIND, code="C6")
        if self.fault is not None:
            for (kind, _msg) in list(self.handlers) + \
                    list(self.timer_handlers):
                if kind == FAULT_KIND:
                    raise SpecError(
                        "handlers may not be registered on the fault "
                        "controller kind — protocols observe faults "
                        "only through message loss and timer silence",
                        spec=self.name, kind=FAULT_KIND, code="C6")
            validate_fault(self)
        kinds = {k.name for k in self.nodes}
        for (kind, msg), fn in self.handlers.items():
            name, line = self._handler_id(fn)
            if kind not in kinds:
                raise SpecError(
                    f"handler registered for unknown node kind "
                    f"{kind!r} (declared: {sorted(kinds)})",
                    spec=self.name, handler=name, kind=kind, line=line)
            if msg not in self._mtag:
                raise SpecError(
                    f"handler registered for unknown message {msg!r} "
                    f"(declared: {sorted(self._mtag)})",
                    spec=self.name, handler=name, kind=kind, field=msg,
                    line=line)
        for (kind, timer), fn in self.timer_handlers.items():
            name, line = self._handler_id(fn)
            if kind not in kinds:
                raise SpecError(
                    f"timer handler registered for unknown node kind "
                    f"{kind!r} (declared: {sorted(kinds)})",
                    spec=self.name, handler=name, kind=kind, line=line)
            if timer not in self._ttag:
                raise SpecError(
                    f"timer handler registered for unknown timer "
                    f"{timer!r} (declared: {sorted(self._ttag)})",
                    spec=self.name, handler=name, kind=kind,
                    field=timer, line=line)
        for name, *_ in self.initial_messages:
            if name not in self._mspec:
                raise SpecError(
                    f"initial message of undeclared type {name!r}",
                    spec=self.name, field=name)
        for name, *_ in self.initial_timers:
            if name not in self._tspec:
                raise SpecError(
                    f"initial timer of undeclared type {name!r}",
                    spec=self.name, field=name)
        kind_counts = {k.name: k.count for k in self.nodes}
        for g in self.symmetry:
            if g not in kinds:
                raise SpecError(
                    f"symmetry group names unknown node kind {g!r} "
                    f"(declared: {sorted(kinds)})",
                    spec=self.name, kind=g, code="C5")
        for kind in self.nodes:
            for f in kind.fields:
                if f.hi is not None and f.hi < f.lo:
                    raise SpecError(
                        f"field {f.name!r} on kind {kind.name!r} has "
                        f"empty domain [{f.lo}, {f.hi}]",
                        spec=self.name, kind=kind.name, field=f.name)
                if f.index_group is not None:
                    if f.index_group not in kind_counts:
                        raise SpecError(
                            f"field {f.name!r} on kind {kind.name!r} "
                            f"declares index_group for unknown kind "
                            f"{f.index_group!r}",
                            spec=self.name, kind=kind.name,
                            field=f.name, code="C5")
                    if f.size != kind_counts[f.index_group]:
                        raise SpecError(
                            f"field {f.name!r} on kind {kind.name!r} "
                            f"has size {f.size} but index_group "
                            f"{f.index_group!r} has "
                            f"{kind_counts[f.index_group]} instances",
                            spec=self.name, kind=kind.name,
                            field=f.name, code="C5")
                if f.delta is not None and f.hi is not None:
                    raise SpecError(
                        f"field {f.name!r} on kind {kind.name!r} "
                        "declares both hi= and delta= — a bounded "
                        "domain and the delta-from-base lane are "
                        "mutually exclusive", spec=self.name,
                        kind=kind.name, field=f.name, code="C5")
                # Init values must sit inside the declared domain —
                # the packed encoding would otherwise corrupt the root
                # state silently (tpu/packing.py).
                if f.hi is not None:
                    for i in range(kind.count):
                        v = f.init(i) if callable(f.init) else f.init
                        vals = np.atleast_1d(np.asarray(v)).tolist()
                        for x in vals:
                            if not (f.lo <= int(x) <= f.hi):
                                raise SpecError(
                                    f"init value {x} of field "
                                    f"{f.name!r} on kind {kind.name!r} "
                                    f"outside declared domain "
                                    f"[{f.lo}, {f.hi}]",
                                    spec=self.name, kind=kind.name,
                                    field=f.name)

    # -------------------------------------------- packing / symmetry

    def _lane_domains(self) -> dict:
        """Per-lane value domains for the bit-packed frontier encoding
        (tpu/packing.py): the structural lanes (message/timer tags,
        from/to node indices, timer min/max) derive from the spec
        itself; field/payload lanes from the declared ``lo``/``hi``
        bounds, ``None`` (full int32) where undeclared."""
        n_nodes = sum(k.count for k in self.nodes)
        nodes = []
        for kind, _i in self._instances():
            for f in kind.fields:
                if f.hi is not None:
                    dom = (f.lo, f.hi)
                elif f.delta is not None:
                    # Delta-from-base lane (ISSUE 18): engines that
                    # carry a level base pack this in f.delta bits;
                    # others derive it as raw (packing.derive_packing).
                    dom = ("delta", int(f.delta))
                else:
                    dom = None
                nodes += [dom] * f.size
        node_dom = (0, max(n_nodes - 1, 0))

        def _merge(entries):
            """Union of (lo, hi) domains; None poisons."""
            lo = hi = None
            for e in entries:
                if e is None:
                    return None
                lo = e[0] if lo is None else min(lo, e[0])
                hi = e[1] if hi is None else max(hi, e[1])
            return (0, 0) if lo is None else (lo, hi)

        msg = [(0, max(len(self.messages) - 1, 0)), node_dom, node_dom]
        for j in range(self._mw - 3):
            entries = []
            for m in self.messages:
                if j < len(m.fields):
                    entries.append((m.bounds or {}).get(m.fields[j]))
                else:
                    entries.append((0, 0))      # zero-padded lane
            msg.append(_merge(entries))
        tmr = [(0, len(self.timers)),
               _merge([(t.min_ms, t.min_ms) for t in self.timers]),
               _merge([(t.max_ms, t.max_ms) for t in self.timers])]
        for j in range(self._tw - 3):
            entries = []
            for t in self.timers:
                if j < len(t.fields):
                    entries.append((t.bounds or {}).get(t.fields[j]))
                else:
                    entries.append((0, 0))
            tmr.append(_merge(entries))
        # Compiled handlers never set an exception code
        # (_normalize_step pads exc=0), so the lane is a constant.
        return {"nodes": nodes, "msg": msg, "timer": tmr,
                "exc": (0, 0)}

    def _symmetry_spec(self, table):
        """Build the canonical-relabeling permutation tables for the
        declared symmetry groups (tpu/symmetry.py SymmetrySpec), or
        None when no groups are declared."""
        if not self.symmetry:
            return None
        import itertools

        from dslabs_tpu.tpu.symmetry import SymmetrySpec

        n_nodes = sum(k.count for k in self.nodes)
        _, nw = self._layout()
        bases = {}
        off = 0
        for kind in self.nodes:
            bases[kind.name] = off
            off += kind.count
        groups = []
        total = 1
        for g in self.symmetry:
            count = next(k.count for k in self.nodes if k.name == g)
            groups.append((g, bases[g], count))
            for i in range(2, count + 1):
                total *= i
        if total > 720:
            raise SpecError(
                f"symmetry groups expand to {total} permutations "
                "(> 720) — the fused canonicalize pass enumerates "
                "them; shrink the groups", spec=self.name, code="C5")
        per_group = [list(itertools.permutations(range(c)))
                     for _g, _b, c in groups]
        relabs, lane_srcs = [], []
        for combo in itertools.product(*per_group):
            relab = np.arange(n_nodes, dtype=np.int64)
            lane_src = np.arange(nw, dtype=np.int64)
            for (g, base, count), sigma in zip(groups, combo):
                # new position j holds old member sigma[j]
                for j in range(count):
                    relab[base + sigma[j]] = base + j
                kind = next(k for k in self.nodes if k.name == g)
                for j in range(count):
                    for f in kind.fields:
                        dst, size = table[(g, j, f.name)]
                        src, _ = table[(g, sigma[j], f.name)]
                        lane_src[dst:dst + size] = np.arange(
                            src, src + size)
                # Group-indexed array fields permute their ELEMENTS
                # with the group (per-member bitmaps stay coherent).
                # Restricted to fields on kinds OUTSIDE the group
                # itself (validated below), so every assignment reads
                # original (identity) positions — no composition.
                for kind2, i2 in self._instances():
                    for f in kind2.fields:
                        if f.index_group != g:
                            continue
                        if kind2.name == g:
                            raise SpecError(
                                f"field {f.name!r}: index_group on a "
                                f"kind inside its own symmetry group "
                                f"{g!r} is unsupported",
                                spec=self.name, kind=kind2.name,
                                field=f.name, code="C5")
                        o2, _ = table[(kind2.name, i2, f.name)]
                        for j in range(count):
                            lane_src[o2 + j] = o2 + sigma[j]
            relabs.append(relab)
            lane_srcs.append(lane_src)
        # Identity permutation first (the canonicalizer's cheap first
        # candidate); itertools.product with sorted permutations
        # yields it first already, but pin it explicitly.
        order = sorted(range(len(relabs)),
                       key=lambda i: 0 if (relabs[i]
                                           == np.arange(n_nodes)).all()
                       else 1)
        return SymmetrySpec(
            relab=np.stack([relabs[i] for i in order]),
            lane_src=np.stack([lane_srcs[i] for i in order]),
            groups=tuple((g, b, c) for g, b, c in groups))

    # ------------------------------------------------------------ compile

    def compile(self):
        """-> TensorProtocol (the engine contract, engine.py:94-146)."""
        import jax.numpy as jnp

        from dslabs_tpu.tpu.engine import SENTINEL, TensorProtocol

        self.validate()
        table, nw = self._layout()
        n_nodes = sum(k.count for k in self.nodes)
        spec = self

        def unpack(nodes):
            st = {}
            for key, (off, size) in table.items():
                st[key] = (nodes[off] if size == 1
                           else nodes[off:off + size])
            return st

        def repack(st):
            parts = []
            for key, (off, size) in table.items():
                v = st[key]
                parts.append(v[None] if size == 1 else v)
            return jnp.concatenate(parts).astype(jnp.int32)

        # Static send/set budgets: trace each handler once with a dummy
        # context to COUNT its effect rows (the finalize-assert
        # discipline of the hand twins, without the hand counting).
        max_sends, max_sets = self._count_budgets()

        def _finalize(rows, budget, width):
            blank = jnp.full((width,), SENTINEL, jnp.int32)
            out = []
            for rec, cond in rows:
                out.append(jnp.where(cond, rec, blank))
            assert len(out) <= budget, (len(out), budget)
            while len(out) < budget:
                out.append(blank)
            return jnp.stack(out) if out else jnp.zeros((0, width),
                                                        jnp.int32)

        def step_message(nodes, msg):
            st = unpack(nodes)
            sends, sets = [], []
            tag, frm, to = msg[0], msg[1], msg[2]
            for kind, i in spec._instances():
                here = to == spec._node_index(kind.name, i)
                for m in spec.messages:
                    fn = spec.handlers.get((kind.name, m.name))
                    if fn is None:
                        continue
                    cond = here & (tag == spec._mtag[m.name])
                    payload = {f: msg[3 + j]
                               for j, f in enumerate(m.fields)}
                    payload["_from"] = frm
                    ctx = Ctx(spec, st, kind.name, i, cond, sends, sets,
                              handler=spec._handler_id(fn))
                    spec._invoke(fn, ctx, payload, m.name)
            return (repack(st), _finalize(sends, max_sends, spec._mw),
                    _finalize(sets, max_sets, 1 + spec._tw))

        def step_timer(nodes, node_idx, timer):
            st = unpack(nodes)
            sends, sets = [], []
            tag = timer[0]
            for kind, i in spec._instances():
                here = node_idx == spec._node_index(kind.name, i)
                for t in spec.timers:
                    fn = spec.timer_handlers.get((kind.name, t.name))
                    if fn is None:
                        continue
                    cond = here & (tag == spec._ttag[t.name])
                    payload = {f: timer[3 + j]
                               for j, f in enumerate(t.fields)}
                    ctx = Ctx(spec, st, kind.name, i, cond, sends, sets,
                              handler=spec._handler_id(fn))
                    spec._invoke(fn, ctx, payload, t.name)
            return (repack(st), _finalize(sends, max_sends, spec._mw),
                    _finalize(sets, max_sets, 1 + spec._tw))

        def init_nodes():
            out = np.zeros((nw,), np.int32)
            for (kind_name, i, fname), (off, size) in table.items():
                kind = next(k for k in self.nodes if k.name == kind_name)
                f = next(x for x in kind.fields if x.name == fname)
                v = f.init(i) if callable(f.init) else f.init
                out[off:off + size] = v
            return out

        def init_messages():
            rows = []
            for name, frm, to, fields in self.initial_messages:
                m = self._mspec[name]
                rec = np.zeros((self._mw,), np.int32)
                rec[0:3] = [self._mtag[name], frm, to]
                for j, f in enumerate(m.fields):
                    rec[3 + j] = fields[f]
                rows.append(rec)
            return (np.stack(rows) if rows
                    else np.zeros((0, self._mw), np.int32))

        def init_timers():
            rows = []
            for name, node, fields in self.initial_timers:
                t = self._tspec[name]
                rec = np.zeros((1 + self._tw,), np.int32)
                rec[0:4] = [node, self._ttag[name], t.min_ms, t.max_ms]
                for j, f in enumerate(t.fields):
                    rec[4 + j] = fields[f]
                rows.append(rec)
            return (np.stack(rows) if rows
                    else np.zeros((0, 1 + self._tw), np.int32))

        def _pred(fn):
            def wrapped(state):
                return fn(_View(spec, table, state["nodes"]))
            return wrapped

        fault_lanes = None
        if self.fault is not None:
            from dslabs_tpu.tpu.faults import compile_fault_lanes
            fault_lanes = compile_fault_lanes(self, table, nw,
                                              init_nodes())

        return TensorProtocol(
            name=self.name,
            n_nodes=n_nodes,
            node_width=nw,
            lane_domains=self._lane_domains(),
            symmetry=self._symmetry_spec(table),
            fault=fault_lanes,
            msg_width=self._mw,
            timer_width=self._tw,
            net_cap=self.net_cap,
            timer_cap=self.timer_cap,
            max_sends=max(max_sends, 1),
            max_sets=max(max_sets, 1),
            init_nodes=init_nodes,
            init_messages=init_messages,
            init_timers=init_timers,
            step_message=step_message,
            step_timer=step_timer,
            msg_dest=lambda msg: msg[2],
            goals={k: _pred(v) for k, v in self.goals.items()},
            invariants={k: _pred(v) for k, v in self.invariants.items()},
            decode_message=self.decode_message,
            decode_timer=self.decode_timer,
        )

    def _invoke(self, fn, ctx: "Ctx", payload: dict, typ: str):
        """Run one handler under the compile gate: a KeyError on the
        payload dict (reading a field the message/timer type does not
        declare) surfaces as a structured SpecError naming the handler
        — the bare-KeyError shape this satellite retires."""
        try:
            return fn(ctx, payload)
        except KeyError as e:
            name, line = self._handler_id(fn)
            missing = e.args[0] if e.args else "?"
            raise SpecError(
                f"read of field {missing!r} not declared by "
                f"{typ!r} (payload fields: "
                f"{sorted(k for k in payload if k != '_from')})",
                spec=self.name, handler=name, field=str(missing),
                line=line) from e

    def _count_budgets(self) -> Tuple[int, int]:
        """Count worst-case send/set rows by running every handler once
        with a counting context (handlers are straight-line over the
        combinators, so one run = its static row count).  The compiled
        step accumulates ALL handlers' rows into one block per step
        kind, so the budget is the larger of the message-step and
        timer-step TOTALS."""
        import jax.numpy as jnp

        table, _ = self._layout()

        def dummy_state():
            return {key: (jnp.zeros((), jnp.int32) if size == 1
                          else jnp.zeros((size,), jnp.int32))
                    for key, (_, size) in table.items()}

        false = jnp.asarray(False)
        msg_sends = msg_sets = tmr_sends = tmr_sets = 0
        for kind, i in self._instances():
            for m in self.messages:
                fn = self.handlers.get((kind.name, m.name))
                if fn is None:
                    continue
                sends, sets = [], []
                ctx = Ctx(self, dummy_state(), kind.name, i, false,
                          sends, sets, handler=self._handler_id(fn))
                self._invoke(
                    fn, ctx, {f: jnp.zeros((), jnp.int32)
                              for f in m.fields} | {"_from": jnp.zeros(
                                  (), jnp.int32)}, m.name)
                msg_sends += len(sends)
                msg_sets += len(sets)
            for t in self.timers:
                fn = self.timer_handlers.get((kind.name, t.name))
                if fn is None:
                    continue
                sends, sets = [], []
                ctx = Ctx(self, dummy_state(), kind.name, i, false,
                          sends, sets, handler=self._handler_id(fn))
                self._invoke(
                    fn, ctx,
                    {f: jnp.zeros((), jnp.int32) for f in t.fields},
                    t.name)
                tmr_sends += len(sends)
                tmr_sets += len(sets)
        return (max(msg_sends, tmr_sends), max(msg_sets, tmr_sets))


class _View:
    """Read-only predicate view over the packed lanes of one state."""

    def __init__(self, spec, table, nodes):
        self._table = table
        self._nodes = nodes

    def get(self, kind: str, idx: int, field: str):
        off, size = self._table[(kind, idx, field)]
        return (self._nodes[off] if size == 1
                else self._nodes[off:off + size])
