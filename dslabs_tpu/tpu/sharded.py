"""Multi-chip sharded BFS: the full device-resident search loop (SPMD).

Scaling design (SURVEY §2.10, §5): the frontier, the visited set, and the
next-frontier accumulator all live in device HBM, sharded over the
``search`` mesh axis.  Each BFS level is a sequence of chunk steps — every
device expands a chunk of its frontier shard with the same vmapped
transition the single-chip engine uses, then successor FINGERPRINTS
(16 bytes each — state rows never ride the interconnect per chunk) are
exchanged by **key ownership** (device = key_hi mod D) with
``lax.all_to_all`` over ICI.  Each owner deduplicates the keys it owns
against its **open-addressing hash table in HBM** — 8-slot buckets read
as one aligned 128-byte line, membership and insert in one bounded probe
loop (the Pallas bucket kernel / jnp oracle in tpu/visited.py), claim
conflicts serialised by a per-bucket min-index reservation.  Under the
default **fused row exchange** (ISSUE 12, ``DSLABS_SHARDED_EXCHANGE``)
the successor rows ride the same owner buckets as their keys, so fresh
states land on their OWNER's frontier shard as they are produced and
the between-level promote is a local buffer swap — no reverse
fresh-flag exchange, no boundary rebalance, no wide compaction.  The
round-5 promote-boundary exchange (fresh flags returned to the
producer via a reverse all_to_all, frontier REBALANCED between levels
with contiguous shares + one all_to_all + one compaction) survives in
the legacy per-chunk driver as the width-parity oracle.  This is the
classic hash-partitioned distributed BFS,
mapped onto XLA collectives instead of the reference's shared-memory
ConcurrentHashMap (Search.java:405-505); with a 1-device mesh the
collectives are identities, which is how the TPU bench runs.

Host involvement per level: ONE on-device **superstep** dispatch — a
``lax.while_loop`` of chunk steps inside a single ``shard_map`` program
that drains every device's own frontier shard (occupancy-driven trip
count read from the carry, not the host's worst-case bound) and returns
the fused scalar stats vector — plus the between-level promote, so at
most two host dispatches per level where the round-5 driver issued
``n_chunks + 1`` (one jitted dispatch per chunk plus the stats sync).
The legacy host-driven per-chunk driver survives behind
``DSLABS_SHARDED_SUPERSTEP=0`` as the parity oracle (docs/perf.md).  No
state rows cross the host boundary until a terminal state must be
reported; even the initial carry is built on device.

Everything on device is int32/uint32 (TPU-native dtypes; no x64).  All
fixed-capacity structures (routing buckets, frontier shards, visited
shards) count their drops and the driver raises
:class:`~dslabs_tpu.tpu.engine.CapacityOverflow` — never a silent
undercount (round-1 advisor findings: validity rides an explicit mask
through the all_to_all, not a reserved fingerprint value).
"""

from __future__ import annotations

import math
import os
import re
import time
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dslabs_tpu.tpu import visited as visited_mod
from dslabs_tpu.tpu.engine import (CapacityOverflow, SearchOutcome,
                                   TensorProtocol, TensorSearch,
                                   device_get, flatten_state,
                                   row_fingerprints, state_fingerprints)
from dslabs_tpu.tpu.spill import (dropped_warn_threshold as
                                  _DROPPED_WARN,
                                  visited_warn_threshold as
                                  _VISITED_WARN)

__all__ = ["ShardedTensorSearch", "make_mesh",
           "CARRY_PARTITION_RULES", "match_partition_rules"]


def _env_on(name: str, default: bool = True) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.strip().lower() not in ("0", "", "off", "false", "no")

OVERFLOW_FACTOR = 2
# The visited hash table itself lives in dslabs_tpu/tpu/visited.py — ONE
# implementation shared with the single-device engine's device-resident
# wave loop (engine.py _run_device).
MAXU32 = visited_mod.MAXU32
BKT = visited_mod.BKT
# Dev: print per-level wall time / chunk rate from run().
_LEVEL_TIMING = bool(os.environ.get("DSLABS_LEVEL_TIMING"))


# ------------------------------------------------------- carry placement
#
# First-class NamedSharding/PartitionSpec placement of the search carry
# (ISSUE 12, following the SNIPPETS [1] regex-partition-rule pattern):
# ONE rule table maps carry leaf names to PartitionSpecs over the named
# mesh axis, and every placement consumer — the shard_map in/out specs,
# the hot programs' jit in/out shardings, the carry initialiser's
# out_shardings, the AOT ShapeDtypeStructs, and the resume/spill
# device_puts — derives from it.  Width-free by construction: the
# elastic ladder (tpu/supervisor.py) re-derives the identical layout on
# any narrower mesh, and XLA sees one consistent placement end to end
# instead of inferring (and defensively resharding) between dispatches.

CARRY_PARTITION_RULES = (
    # Wide SoA buffers: frontier shards, next-frontier accumulator,
    # per-row trace meta — row-sharded over the search axis.  Under
    # the packed wire format (ISSUE 18) cur/nxt hold PACKED words
    # (width = descriptor.words), same placement.
    (r"^(cur|nxt|tmeta)$", lambda ax: P(ax)),
    # The owner-sharded visited hash table (one [V+1, 4] shard per
    # device; owner = key lane 0 mod D picks the shard).
    (r"^visited$", lambda ax: P(ax)),
    # Terminal-flag rows/meta/counters: one n_flags block per device.
    (r"^(flag_rows|flag_meta|flag_cnt)$", lambda ax: P(ax)),
    # Delta-encoding level bases (ISSUE 18 leg (b)): one [n_delta]
    # int32 vector per device, value-replicated by construction (the
    # chunk step pmin's them) but stored per-device so the carry stays
    # uniformly sharded and donation-friendly.
    (r"^(pb_cur|pb_nxt)$", lambda ax: P(ax)),
    # Per-device scalar lanes: occupancies, loop counters, stats.
    (r"^(cur_n|nxt_n|vis_n|j|evp|noapp|explored|overflow|vis_over"
     r"|drops|f_full)$", lambda ax: P(ax)),
)


def match_partition_rules(rules, names, axis):
    """SNIPPETS [1]'s regex-rules -> PartitionSpec mapping, applied to
    carry leaf NAMES: the first matching rule wins; an unmatched leaf
    is a loud error (a new carry entry must declare its placement, not
    inherit one by accident)."""
    out = {}
    for name in names:
        for pat, spec in rules:
            if re.search(pat, name):
                out[name] = spec(axis) if callable(spec) else spec
                break
        else:
            raise ValueError(
                f"no partition rule for carry leaf {name!r} — add it "
                "to CARRY_PARTITION_RULES")
    return out


def make_mesh(n_devices: int = None, axis: str = "search") -> Mesh:
    devs = jax.devices()
    if n_devices is not None and len(devs) < n_devices:
        # Fewer accelerators than requested: use the virtual host-CPU
        # devices (--xla_force_host_platform_device_count) — the dry-run
        # path for multi-chip shardings on single-chip machines.
        devs = jax.devices("cpu")
    if n_devices is not None:
        if len(devs) < n_devices:
            raise RuntimeError(
                f"need {n_devices} devices, have {len(devs)} "
                "(set --xla_force_host_platform_device_count)")
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


class ShardedTensorSearch(TensorSearch):
    """BFS driver whose frontier, visited set, and expansion all live
    sharded on a device mesh; ``run()`` executes the full multi-level
    search with one scalar sync per level.

    Per-device carry (global shapes have a leading D factor):
      cur      [F, lanes] int32   current frontier shard (owned states)
      cur_n    [1]        int32   occupancy of cur
      nxt      [F+1, lanes]       next-frontier accumulator (+1 dump row)
      nxt_n    [1]                occupancy of nxt
      visited  [V+1, 4]   uint32  open-addressing hash table of 128-bit
                                  keys (+1 dump row); EMPTY = all-MAX
      vis_n    [1]                number of keys inserted
      counters: explored / overflow / routed-drop / frontier-drop
      flag_cnt [n_flags], flag_rows [n_flags, lanes]: terminal detection
        (exception -> invariant -> goal, checkState order
        Search.java:162-231) — first-hit successor row kept per flag.
    """

    def __init__(self, protocol: TensorProtocol, mesh: Mesh,
                 chunk_per_device: int = 1 << 10,
                 frontier_cap: int = 1 << 14,
                 visited_cap: int = 1 << 20,
                 max_depth: Optional[int] = None,
                 max_secs: Optional[float] = None,
                 strict: bool = True,
                 ev_budget: Optional[int] = None,
                 ev_spill: Optional[bool] = None,
                 record_trace: bool = False,
                 checkpoint_path: Optional[str] = None,
                 checkpoint_every: int = 0,
                 superstep: Optional[bool] = None,
                 superstep_chunks: Optional[int] = None,
                 row_exchange: Optional[bool] = None,
                 aot_warmup: Optional[bool] = None,
                 spill=None,
                 telemetry=None,
                 symmetry: Optional[bool] = None,
                 mesh_pack: Optional[bool] = None,
                 steal_threshold: Optional[float] = None):
        # Frontier checkpointing (SURVEY §5 "dump SoA tensors"): every
        # ``checkpoint_every`` levels the live carry — the OCCUPIED
        # frontier prefix, the occupied visited-table lines, and the
        # counters; never the empty accumulators or f_cap padding — is
        # snapshotted into fresh device buffers and drained to
        # ``checkpoint_path`` (atomic .npz rename) by a background
        # thread while the next levels compute (see the checkpointing
        # section below).  The dump is the UNIFIED engine-agnostic
        # format (tpu/checkpoint.py) — the single-device and host
        # engines resume the same file, which is what makes supervisor
        # failover (tpu/supervisor.py) resumable.  ``run(resume=True)``
        # continues a killed search from the last dump with identical
        # final verdict and unique count.  0 = off.
        self.mesh = mesh
        self.axis = mesh.axis_names[0]
        self.n_devices = int(mesh.devices.size)
        # strict=True (search tests): ANY capacity drop is fatal — verdicts
        # must be exact.  strict=False (throughput benches): routing-bucket
        # and frontier-cap drops truncate expansion coverage beam-style and
        # are reported via SearchOutcome.dropped; semantic overflow
        # (net/timer caps) stays fatal either way.  A FULL visited table
        # degrades to treat-as-fresh (visited.py contract): fatal in
        # strict (unique counts must be exact), counted and reported via
        # SearchOutcome.visited_overflow in beam.
        # F must divide evenly by the chunk (chunk-loop slicing) AND the
        # device count (level-rebalance shares); pad to the lcm so neither
        # pad breaks the other's invariant.
        quantum = math.lcm(chunk_per_device, self.n_devices)
        if frontier_cap % quantum:
            frontier_cap += quantum - frontier_cap % quantum
        if visited_cap & (visited_cap - 1):
            raise ValueError("visited_cap must be a power of two "
                             "(hash-table slot arithmetic)")
        self.f_cap = frontier_cap          # per device
        self.v_cap = visited_cap           # per device
        self.cpd = chunk_per_device
        # Event-window spill (round-4): when a chunk has valid events past
        # the ev_budget window, re-step it at the next window instead of
        # dropping (beam) / aborting (strict) — a finite budget then costs
        # extra passes on rare over-budget chunks, never coverage.
        # Default: on for strict (exactness), off for beam (the re-pass
        # of a whole chunk for a few tail events is the wrong throughput
        # trade; drops are counted as before).
        self.ev_spill = strict if ev_spill is None else ev_spill
        # The owner-side hash table is the dedup authority, so the
        # engine's in-chunk sort-unique prefilter is redundant work — but
        # without it, duplicate successors (all sharing one fingerprint,
        # hence one owner) can pile onto a single fixed-size routing
        # bucket.  On ONE device the bucket holds the entire successor
        # batch exactly (bucket = C * ne below), so pileup cannot
        # overflow and even strict runs skip the prefilter (it measured
        # ~60% of a loaded chunk step).  Multi-device strict keeps it:
        # per-owner buckets have only 2x-mean headroom.
        # Packed wire format (ISSUE 18): the sharded carry — frontier
        # shards, routing buckets, the fused row-exchange payload — is
        # re-typed to the spec-derived bit-packed encoding, so the
        # owner-hashed all_to_all ships descriptor.words int32 words per
        # state instead of ``lanes``.  super() still gets packed=False:
        # the base engine's OWN packing paths (device wave loop, its
        # checkpoint writer) are not on the sharded hot path, and the
        # sharded descriptor is derived separately below WITH the
        # delta-lane extension (delta=True) so view-number-style fields
        # pack here even though the single-device engine keeps them
        # raw.  Symmetry DOES ride along: the canonicalize pass lives
        # in the shared _expand_chunk hash step, so the owner-hash keys
        # on canonical fingerprints and symmetric twins dedup on one
        # owner.
        super().__init__(protocol, frontier_cap=frontier_cap,
                         chunk=chunk_per_device, max_depth=max_depth,
                         max_secs=max_secs,
                         in_chunk_dedup=strict and self.n_devices > 1,
                         ev_budget=ev_budget, record_trace=record_trace,
                         visited_cap=visited_cap, strict=strict,
                         checkpoint_path=checkpoint_path,
                         checkpoint_every=checkpoint_every,
                         spill=spill, telemetry=telemetry,
                         packed=False, symmetry=symmetry)
        # Mesh wire codec: DSLABS_MESH_PACK=0 (or mesh_pack=False) keeps
        # the legacy raw int32 exchange as the parity oracle.  Identity
        # descriptors (hand twins without domain metadata) fall back to
        # the raw wire — loudly, via the run()-time telemetry event.
        self.mesh_pack = (_env_on("DSLABS_MESH_PACK", True)
                          if mesh_pack is None else bool(mesh_pack))
        if self.mesh_pack:
            from dslabs_tpu.tpu.packing import derive_packing
            pk = derive_packing(protocol, self.lanes, delta=True)
            self._pk = None if pk.identity else pk
        else:
            self._pk = None
        self.plane = self._pk.words if self._pk is not None else self.lanes
        self._mesh_delta = (self._pk is not None and self._pk.has_delta)
        if self._mesh_delta:
            self._delta_lanes = np.asarray(self._pk.delta_lanes, np.int32)
        # Chunk-granular work stealing at level boundaries (ISSUE 18
        # leg (c)): when the per-owner frontier occupancy skew exceeds
        # the threshold, overfull owners donate packed rows through one
        # extra all_to_all; dedup ownership (visited shards) never
        # moves, only expand work, so counts stay bit-identical.
        # Threshold <= 0 / unset = off (the default keeps today's
        # dispatch counts byte-identical).  Only meaningful under the
        # fused row exchange: the legacy promote already rebalances.
        if steal_threshold is None:
            _st = os.environ.get("DSLABS_MESH_STEAL_THRESHOLD", "")
            steal_threshold = float(_st) if _st.strip() else 0.0
        self._steal_threshold = float(steal_threshold)
        self._steal_prog_cache = None
        self._steal_events = 0
        self._steal_moved = 0
        # Host-RAM spill tier (tpu/spill.py, docs/capacity.md): the
        # carry gains an ``f_full`` abort-code lane, the chunk step
        # aborts-and-reverts GLOBALLY (a psum'd decision — owner-side
        # inserts for a retried chunk must revert on every device) on
        # frontier/table exhaustion, and level boundaries refilter the
        # would-be frontier against the host tier.  All of it is
        # conditional on the knob so non-spill programs stay
        # byte-identical (warm compile caches keep hitting).
        self._spill_on = self._spill is not None
        # Trace mode: each level spills (child_fp, parent_fp, event_id)
        # for every appended successor; reconstruction walks fingerprints
        # back to the root on the HOST (fps are stable identities, so the
        # level rebalance needs no permutation bookkeeping) and replays
        # the grid event ids on the object twin via tpu/trace.py.
        self._fp_map = {}                  # child fp bytes -> (parent, ev)
        # On-device level superstep (default; DSLABS_SHARDED_SUPERSTEP=0
        # keeps the legacy host-driven per-chunk driver as the parity
        # oracle).  The superstep fuses each level's whole chunk loop —
        # lax.while_loop of chunk steps until every device's OWN frontier
        # shard is drained — into ONE dispatch that also returns the
        # fused stats vector, so host involvement per level drops from
        # n_chunks + 1 dispatches to superstep + promote.
        self.use_superstep = (_env_on("DSLABS_SHARDED_SUPERSTEP", True)
                              if superstep is None else bool(superstep))
        if self._spill_on:
            # The spill abort protocol rides the superstep's drain
            # condition; the legacy per-chunk parity driver stays the
            # oracle for UNCAPPED runs only.
            self.use_superstep = True
        # In-superstep owner-routed row exchange (ISSUE 12): the fused
        # chunk body routes the successor ROWS through the same
        # owner-hashed all_to_all as their keys, so fresh states land
        # on their owner's frontier shard as they are produced — the
        # promote-boundary rebalance (one wide all_to_all + compaction
        # per level) and the reverse fresh-flag exchange both
        # disappear, and the level promote shrinks to a local buffer
        # swap.  Default ON under the superstep driver;
        # DSLABS_SHARDED_EXCHANGE=0 (or the legacy per-chunk driver,
        # which IS the promote-boundary oracle) keeps the round-5
        # exchange for the width-parity matrix.
        self.row_exchange = (_env_on("DSLABS_SHARDED_EXCHANGE", True)
                             if row_exchange is None
                             else bool(row_exchange))
        if not self.use_superstep:
            self.row_exchange = False
        # Steal rides the fused row exchange only (the legacy promote
        # already rebalances evenly, so stealing there is redundant).
        self._steal_on = (self._steal_threshold > 0.0
                          and self.n_devices > 1 and self.row_exchange)
        # _flag_names is set by super().__init__ (shared with the
        # single-device device-resident loop).  Hot programs are jitted
        # with the rule-derived carry shardings pinned on BOTH sides
        # (in_shardings/out_shardings): placement is an explicit
        # contract, not an inference XLA re-derives per dispatch.
        self._chunk_step = self._chunk_jit()
        self._finish_level = self._sharded_jit(self._build_finish())
        self._superstep = self._superstep_jit()
        # Chunk-step budget per superstep dispatch when a wall-clock
        # budget is active: bounds device work between host clock checks
        # so mid-level TIME_EXHAUSTED keeps its round-3 granularity (the
        # legacy driver blocked every 16 chunks for the same reason).
        # First-class constructor knob since ISSUE 9: the supervisor's
        # adaptive OOM backoff halves it per knob-shrink re-level
        # (docs/resilience.md "knob-shrink ladder").
        self._superstep_chunks = (
            int(superstep_chunks) if superstep_chunks is not None
            else int(os.environ.get("DSLABS_SUPERSTEP_CHUNKS", "16")
                     or "16"))

        # ONE fused scalar vector per host sync: each device->host readback
        # over the runtime tunnel costs ~25 ms, and the naive sync did six
        # (round-2 profile: 152 ms/level of pure readback latency).
        nf = len(self._flag_names)

        def stats(carry):
            return jnp.concatenate([
                jnp.asarray([
                    jnp.sum(carry["overflow"]),
                    jnp.sum(carry["drops"]),
                    jnp.sum(carry["vis_over"]),
                    jnp.sum(carry["explored"]),
                    jnp.max(carry["vis_n"]),
                    jnp.sum(carry["vis_n"]),
                    jnp.max(carry["nxt_n"]),
                    # Slowest device's completed-chunk count: the spill
                    # re-dispatch loop reads it from the SAME readback as
                    # the level sync (no extra host round-trips when no
                    # chunk spilled).
                    jnp.min(carry["j"]),
                ], jnp.int32),
                jnp.sum(carry["flag_cnt"].reshape(self.n_devices, nf),
                        axis=0).astype(jnp.int32),
                # Per-device stats lanes (ISSUE 8): the pre-reduction
                # per-device scalars ride the SAME readback vector —
                # [explored×D, vis_n×D, nxt_n×D, drops×D], always the
                # LAST 4D slots of either driver's layout — so shard
                # skew / table load / frontier occupancy per device
                # cost zero extra transfers.
                carry["explored"].astype(jnp.int32),
                carry["vis_n"].astype(jnp.int32),
                carry["nxt_n"].astype(jnp.int32),
                carry["drops"].astype(jnp.int32),
            ])

        self._stats = jax.jit(stats)

        # Explicit AOT warm-up (ISSUE 3): .lower().compile() the hot
        # programs at construction so compile wall-time is measured
        # separately from search wall-time (SearchOutcome.compile_secs)
        # and — with the persistent compile cache wired — a second run of
        # the same config pays near-zero compile.
        self.compile_secs = 0.0
        if (_env_on("DSLABS_AOT_WARMUP", False)
                if aot_warmup is None else bool(aot_warmup)):
            self.aot_warmup()
        # Soundness sanitizer (ISSUE 10): audit the freshly-built
        # superstep/promote/init programs when DSLABS_SANITIZE is on.
        self._maybe_sanitize()

    # ------------------------------------------------- placement helpers

    def _carry_names(self) -> list:
        keys = ["cur", "cur_n", "j", "evp", "noapp", "nxt", "nxt_n",
                "visited", "vis_n", "explored", "overflow", "vis_over",
                "drops", "flag_cnt", "flag_rows"]
        if self.record_trace:
            keys += ["tmeta", "flag_meta"]
        if self._spill_on:
            keys += ["f_full"]
        if self._mesh_delta:
            keys += ["pb_cur", "pb_nxt"]
        return keys

    # Delta-lane level bases (ISSUE 18 leg (b)).  pb_cur/pb_nxt are
    # [n_delta] int32 per device: the per-lane minimum over the live
    # frontier / accumulating next frontier of every ("delta", bits)
    # lane.  The chunk step pmin's candidate minima across devices, so
    # the per-device copies are value-identical by construction and the
    # promote's re-encode needs no collective.
    _PB_EMPTY = np.int32(2 ** 31 - 1)

    def _base_vec(self, pb):
        """[n_delta] per-device base -> [lanes] base vector for the
        codec (non-delta lanes read their static lo; the scatter value
        for them is ignored by LanePacking)."""
        didx = jnp.asarray(self._delta_lanes)
        return (jnp.zeros((self.lanes,), jnp.int32)
                .at[didx].set(pb.astype(jnp.int32)))

    def _carry_shardings(self) -> dict:
        """Rule-derived NamedSharding per carry leaf — the ONE
        placement authority (CARRY_PARTITION_RULES) every consumer
        shares; rebuilt per mesh so the elastic ladder's narrower
        rungs get the identical layout."""
        return {k: NamedSharding(self.mesh, s)
                for k, s in self._carry_specs().items()}

    def _sharded_jit(self, fn, extra_in=(), extra_out=None):
        """jit a carry-first program with the rule-derived placement
        pinned on both sides and the carry donated.  ``extra_in`` /
        ``extra_out`` list the shardings of any non-carry operands
        (replicated scalars/masks) after the carry."""
        cs = self._carry_shardings()
        ins = (cs,) + tuple(extra_in)
        outs = cs if extra_out is None else (cs,) + tuple(extra_out)
        return jax.jit(fn, donate_argnums=0, in_shardings=ins,
                       out_shardings=outs)

    def _replicated(self):
        return NamedSharding(self.mesh, P())

    def _superstep_jit(self):
        rep = self._replicated()
        extra = ((rep, (rep, rep)) if self._has_rt_masks()
                 else (rep,))
        return self._sharded_jit(self._build_superstep(),
                                 extra_in=extra, extra_out=(rep,))

    def _chunk_jit(self):
        rep = self._replicated()
        extra = (((rep, rep),) if self._has_rt_masks() else ())
        return self._sharded_jit(self._build_chunk_step(),
                                 extra_in=extra)

    # --------------------------------------------------------- level chunk

    def _make_local_step(self, route_rows: bool = False):
        """The per-device chunk-step body (runs INSIDE shard_map): one
        chunk expand + key routing + owner dedup + frontier append.
        Shared by the legacy per-chunk program (_build_chunk_step, one
        shard_map dispatch per chunk) and the fused level superstep
        (_build_superstep, a lax.while_loop of these bodies in one
        dispatch)."""
        p = self.p
        D = self.n_devices
        C = self.cpd
        F = self.f_cap
        V = self.v_cap
        ne = self._num_events()
        ax = self.axis
        lanes = self.lanes
        # Packed wire format (ISSUE 18): frontier shards and the fused
        # row-exchange payload hold PACKED words; owners decode
        # in-register at expand time (unpack below), producers encode
        # each successor batch ONCE and both the wire and the nxt store
        # reuse the same packed rows.  plane == lanes when the codec is
        # identity / disabled — every shape below degenerates to the
        # legacy raw layout.
        pk = self._pk
        plane = self.plane
        delta = self._mesh_delta
        # On one device every successor routes to the sole owner, so the
        # bucket can hold the whole batch exactly (no overflow headroom
        # needed) — halving the rows the probe loop and flag exchange
        # touch.  Multi-device buckets keep 2x-mean headroom for skew.
        bucket = (C * ne if D == 1
                  else (C * ne // D + 1) * OVERFLOW_FACTOR)
        nf = len(self._flag_names)
        # Dev bisect hook (tools/profile_sharded2.py): truncate the step
        # after a named stage, folding that stage's outputs into the
        # explored counter so XLA cannot DCE the work under test.  None in
        # production; the bisect tool measures the REAL step this way
        # instead of maintaining a drifting copy.
        stop_after = getattr(self, "_stop_after", None)
        # Spill mode (tpu/spill.py): frontier/table exhaustion ABORTS
        # the chunk step GLOBALLY — the decision is psum'd and every
        # device reverts its whole update (owner-side inserts included:
        # a producer may have kept rows whose keys live only in another
        # device's reverted table, so all-or-nothing is the only sound
        # retry unit) — and an abort code lands on the carry's f_full
        # lane (bit 0 frontier full, bit 1 table full) for the host to
        # answer with a drain/evict before re-dispatching.
        spill_on = self._spill is not None

        def _stopped(carry, *live):
            out = dict(carry)
            acc = carry["explored"][0]
            for x in live:
                acc = acc + jnp.sum(x).astype(jnp.int32)
            out["explored"] = carry["explored"].at[0].set(acc)
            out["j"] = carry["j"] + 1
            return out

        def local(carry, masks=None):
            # The chunk index lives IN the carry (device-resident,
            # self-incrementing): passing it as a per-call jnp scalar cost
            # a fresh host->device transfer per chunk step, which on the
            # tunnelled runtime is the same ~25 ms latency class as a
            # readback.
            cur, cur_n = carry["cur"], carry["cur_n"][0]
            j = carry["j"][0]
            start = j * C
            rows_chunk = jax.lax.dynamic_slice(cur, (start, 0), (C, plane))
            base_cur = self._base_vec(carry["pb_cur"]) if delta else None
            if pk is not None:
                # In-register decode at expand time: the frontier shard
                # stores packed words, the expansion grid wants lanes.
                rows_chunk = pk.unpack_jnp(rows_chunk, base_cur)
            valid = (start + jnp.arange(C)) < cur_n
            ev_pass = carry["evp"][0]
            (rows, valids, fp, unique, overflow, ev_rem, event_ids,
             flags) = self._expand_chunk(rows_chunk, valid, ev_pass, masks)
            # Spill: valid events past this pass's window mean the SAME
            # chunk must re-step at the next window before j advances
            # (run() re-dispatches until every device's j reaches its
            # chunk count).  Without spill, the remainder is a counted
            # beam-style drop exactly as in round 3.
            if self.ev_spill:
                spill = ev_rem > 0
                j_next = carry["j"] + jnp.where(spill, 0, 1)
                evp_next = jnp.where(spill, carry["evp"] + 1, 0)
                ev_drops = jnp.int32(0)
            else:
                j_next = carry["j"] + 1
                evp_next = carry["evp"]
                ev_drops = ev_rem
            if self.record_trace:
                # [C*B, 9] uint32 trace meta: child fp, parent fp, grid
                # event id — spilled to host per level for fp-chain
                # reconstruction (the sharded analog of the base
                # engine's per-level (parent, event) spill).
                fp_par = row_fingerprints(rows_chunk)          # [C, 4]
                ne_slots = self._num_events()
                meta = jnp.concatenate([
                    fp,
                    jnp.repeat(fp_par, ne_slots, axis=0),
                    event_ids.reshape(-1, 1).astype(jnp.uint32),
                ], axis=1)                                     # [C*B, 9]
            if stop_after in ("events", "handlers", "tail", "fp",
                              "expand"):
                # The engine-internal stages already truncated inside
                # _expand_chunk (dummy outputs, live sums folded into
                # `overflow`); fold here and skip the rest of the step.
                return _stopped(carry, rows, fp, unique,
                                jnp.asarray([overflow]))

            # ---- terminal flags, checkState order (exception first)
            hit_list = [valids & (rows[:, -1] != 0)]
            for n in p.invariants:
                hit_list.append(valids & ~flags[f"inv:{n}"])
            for n in p.goals:
                hit_list.append(flags[f"goal:{n}"])
            hits = jnp.stack(hit_list)                       # [nf, C*E]
            cnts = jnp.sum(hits, axis=1).astype(jnp.int32)
            idxs = jnp.argmax(hits, axis=1)
            new_rows_f = rows[idxs]                          # [nf, lanes]
            fresh_flag = (carry["flag_cnt"] == 0) & (cnts > 0)
            flag_rows = jnp.where(fresh_flag[:, None], new_rows_f,
                                  carry["flag_rows"])
            flag_cnt = carry["flag_cnt"] + cnts
            if self.record_trace:
                flag_meta = jnp.where(fresh_flag[:, None], meta[idxs],
                                      carry["flag_meta"])

            pruned = rows[:, -1] != 0
            for n in p.prunes:
                pruned = pruned | flags[f"prune:{n}"]

            # ---- encode the successor batch ONCE: the same packed rows
            # ride the owner-hashed all_to_all (the ~pack_ratio x ICI
            # cut) AND the nxt store.  Out-of-domain values (a wrong
            # Field bound, or a delta value past its window) are counted
            # on LIVE rows only and folded into the semantic-overflow
            # counter — _sync_checks raises the loud CapacityOverflow.
            pack_bad = jnp.int32(0)
            if pk is not None:
                rows_store, bad = pk.pack_jnp(rows, base_cur,
                                              count_bad=True)
                pack_bad = jnp.sum(
                    jnp.where(valids, bad, 0)).astype(jnp.int32)
            else:
                rows_store = rows
            if delta:
                # Candidate next-level base: per-lane min of the live
                # successors' delta values, pmin'd across the mesh so
                # every device carries the identical base and the
                # promote re-encode needs no collective.  The min over
                # ALL live successors (pruned included) is a lower
                # bound of the stored subset — a valid (just possibly
                # looser) base.
                dvals = rows[:, jnp.asarray(self._delta_lanes)]
                dvals = jnp.where(valids[:, None], dvals,
                                  jnp.int32(self._PB_EMPTY))
                cand = jnp.min(dvals, axis=0).astype(jnp.int32)
                pb_nxt = jax.lax.pmin(
                    jnp.minimum(carry["pb_nxt"], cand), ax)

            # ---- ownership routing: exchange FINGERPRINTS ONLY, never
            # state rows.  Successor rows stay on the device that produced
            # them; owners deduplicate the 16-byte keys and return a fresh
            # flag via a second (reverse) all_to_all.  Any cross-row
            # permutation of the [B, lanes] successor matrix — gather or
            # scatter — measured ~2 GB/s effective (137 ms per chunk, 80%
            # of the level step) in the round-2 bisection, and the key
            # exchange also cuts ICI traffic by the full lane width
            # (1354 lanes -> 4).  Successors sorted by owner form
            # contiguous segments, so the [D, bucket] key buckets are
            # narrow gathers at segment offsets.
            owner = (fp[:, 0] % jnp.uint32(D)).astype(jnp.int32)
            owner = jnp.where(unique, owner, D)     # non-unique -> nowhere
            order = jnp.argsort(owner, stable=True)
            owner_s = owner[order]
            dev = jnp.arange(D)
            starts = jnp.searchsorted(owner_s, dev, side="left")
            ends = jnp.searchsorted(owner_s, dev, side="right")
            src = starts[:, None] + jnp.arange(bucket)[None, :]  # [D, bkt]
            send_valid = src < ends[:, None]
            gidx = order[src.clip(0, owner.shape[0] - 1)]  # [D, bkt] row idx
            send_keys = fp[gidx.reshape(-1)].reshape(D, bucket, 4)
            counts = ends - starts
            route_drop = jnp.sum(jnp.maximum(counts - bucket, 0)).astype(
                jnp.int32)
            if route_rows:
                # Fused row exchange (ISSUE 12): the successor ROW,
                # its pruned flag, and (in trace mode) its meta ride
                # the SAME owner buckets as the keys — one extra
                # all_to_all per chunk lands every fresh state on its
                # OWNER's frontier shard as it is produced.  The
                # reverse fresh-flag exchange and the promote-boundary
                # rebalance (the per-level wide row movement + its
                # compaction scatter) both disappear; the level
                # promote shrinks to a local buffer swap
                # (_build_finish).
                parts = [rows_store, pruned[:, None].astype(jnp.int32)]
                if self.record_trace:
                    parts.append(jax.lax.bitcast_convert_type(
                        meta, jnp.int32))
                payload = jnp.concatenate(parts, axis=1)
                send_rows = payload[gidx.reshape(-1)].reshape(
                    D, bucket, payload.shape[1])
            if stop_after == "route":
                return _stopped(carry, rows, send_keys, send_valid)

            # ---- the exchange: every device receives the key bucket
            # destined to it from every other device (ICI all_to_all)
            recv_keys = jax.lax.all_to_all(send_keys, ax, 0, 0)
            recv_valid = jax.lax.all_to_all(send_valid, ax, 0, 0)
            rb = D * bucket
            recv_keys = jnp.where(recv_valid.reshape(rb, 1),
                                  recv_keys.reshape(rb, 4), MAXU32)
            recv_valid = recv_valid.reshape(rb)
            if route_rows:
                recv_rows = jax.lax.all_to_all(
                    send_rows, ax, 0, 0).reshape(rb, -1)
            if stop_after == "a2a":
                return _stopped(carry, rows, recv_keys, recv_valid)

            # ---- owner-side dedup via the SHARED open-addressing hash
            # table (dslabs_tpu/tpu/visited.py — one implementation for
            # this driver and the single-device device-resident loop).
            # The recv batch may hold the same key several times (from
            # different producers, or in-chunk duplicates when the
            # prefilter is off); the table's per-bucket reservation
            # guarantees exactly one copy ever inserts.  Bucket index
            # comes from lane 2 (b_hi), NOT lane 0: ownership routing
            # already fixed lane0 ≡ device (mod D), so a lane0-derived
            # home bucket would cluster every owned key into 1/D of the
            # table (visited.py keys buckets by lane 2 for this reason).
            #
            # Probe exhaustion (table effectively full) leaves keys
            # UNRESOLVED: per the visited.py contract they are treated
            # as FRESH (sound — re-explored, never silently dropped) and
            # counted into the vis_over flag, which _sync_checks raises
            # on in strict mode and reports via
            # SearchOutcome.visited_overflow in beam mode.
            new_visited, ins_s, unres_s = visited_mod.insert(
                carry["visited"], recv_keys, recv_valid)
            fresh_s = ins_s | unres_s
            vis_over = jnp.sum(unres_s).astype(jnp.int32)
            n_fresh = jnp.sum(ins_s).astype(jnp.int32)
            if stop_after == "probe":
                out = _stopped(carry, rows, fresh_s, unres_s)
                out["visited"] = new_visited
                return out

            if route_rows:
                # Owner-side append: the received rows ARE this
                # device's share of the next frontier (owner-hashed
                # placement — the distribution the per-device skew
                # lanes judge).  No flag needs to travel back to the
                # producer, so the reverse all_to_all is gone.
                app_rows = recv_rows[:, :plane]
                app_pruned = recv_rows[:, plane] != 0
                app_fresh = fresh_s            # implies recv_valid
                if self.record_trace:
                    app_meta = jax.lax.bitcast_convert_type(
                        recv_rows[:, plane + 1:], jnp.uint32)
                if stop_after == "back":
                    out = _stopped(carry, rows, app_fresh, app_pruned)
                    out["visited"] = new_visited
                    return out
            else:
                # ---- return each key's fresh flag to its producer
                # (reverse all_to_all — an involution on the leading
                # axis; recv order was never permuted) and map it back
                # onto the producer's local successor rows.  Narrow
                # bool scatters only; `.max` (boolean or) so the
                # clipped dump writes of invalid slots can never
                # clobber a true flag.
                fresh_back = jax.lax.all_to_all(
                    fresh_s.reshape(D, bucket), ax, 0, 0)
                fresh_rows = jnp.zeros(owner.shape[0], bool).at[
                    gidx.reshape(-1)].max(
                    fresh_back.reshape(-1) & send_valid.reshape(-1))
                if stop_after == "back":
                    out = _stopped(carry, rows, fresh_rows)
                    out["visited"] = new_visited
                    return out
                app_rows = rows_store
                app_pruned = pruned
                app_fresh = fresh_rows
                if self.record_trace:
                    app_meta = meta

            # ---- append fresh, un-pruned successors (producer order
            # under the legacy exchange, owner-received order under the
            # fused row exchange — BFS level semantics are order-free)
            # to the local next frontier.
            # noapp (set by run() for the FINAL depth-limited level):
            # fresh states still count into vis_n/flags — discovered,
            # checked, never expanded — but skip the frontier append, so
            # a last level D times larger than frontier_cap needs no
            # frontier memory (the depth limit ends the search exactly as
            # DEPTH_EXHAUSTED would; the reference's BFS likewise never
            # queues states at the cutoff depth).
            noapp = carry["noapp"][0] == 1
            sel_would = app_fresh & ~app_pruned
            # Spill mode appends pruned-but-fresh rows too: every fresh
            # insert must reach the host refilter (the drain recomputes
            # the prune/exception mask before anything re-expands), or
            # a post-eviction re-discovery of a pruned state would
            # double-count.  noapp counting stays on sel_would — the
            # DEPTH-vs-SPACE decision is about expandable successors.
            sel = (app_fresh if spill_on else sel_would) & ~noapp
            spos = jnp.cumsum(sel) - 1
            nxt, nxt_n = carry["nxt"], carry["nxt_n"][0]
            sdst = jnp.where(sel & (nxt_n + spos < F), nxt_n + spos, F)
            nxt = nxt.at[sdst].set(app_rows)
            n_sel = jnp.sum(sel).astype(jnp.int32)
            frontier_drop = jnp.maximum(nxt_n + n_sel - F, 0)
            # Occupancy counts only rows that actually landed (<= F), else
            # the next level's chunk loop would re-expand the tail.
            n_sel = n_sel - frontier_drop

            out = {
                "cur": cur, "cur_n": carry["cur_n"],
                "j": j_next, "evp": evp_next, "noapp": carry["noapp"],
                # On a noapp level nxt_n counts the WOULD-BE appends
                # (rows themselves are skipped, no frontier-cap drops):
                # run() reads it to tell DEPTH_EXHAUSTED (successors
                # remained) from SPACE_EXHAUSTED (space ended exactly at
                # the depth limit) — the base engine's verdict for the
                # same boundary (engine.py run(): not lvl_keys).
                "nxt": nxt, "nxt_n": carry["nxt_n"].at[0].add(
                    jnp.where(noapp,
                              jnp.sum(sel_would).astype(jnp.int32),
                              n_sel)),
                "visited": new_visited,
                "vis_n": carry["vis_n"].at[0].add(n_fresh),
                "explored": carry["explored"].at[0].add(
                    jnp.sum(valids).astype(jnp.int32)),
                # Semantic overflow (net/timer caps) corrupts state
                # contents — always fatal.  Capacity drops (routing
                # bucket, frontier cap) only truncate *expansion
                # coverage* (beam-style) and are tolerable when the
                # caller opts in (bench throughput runs).  A full
                # visited table is its own flag (vis_over): sound
                # treat-as-fresh degradation, fatal only in strict.
                "overflow": carry["overflow"].at[0].add(
                    overflow + pack_bad),
                "vis_over": carry["vis_over"].at[0].add(vis_over),
                # ev_drops (valid events past the ev_budget) truncate
                # expansion coverage like a routing/frontier drop: fatal
                # in strict mode (via _sync_checks), beam-tolerable else.
                "drops": carry["drops"].at[0].add(
                    route_drop + frontier_drop + ev_drops),
                "flag_cnt": flag_cnt, "flag_rows": flag_rows,
            }
            if self.record_trace:
                # Trace meta rides the SAME append scatter as the rows.
                out["tmeta"] = carry["tmeta"].at[sdst].set(app_meta)
                out["flag_meta"] = flag_meta
            if delta:
                out["pb_cur"] = carry["pb_cur"]
                out["pb_nxt"] = pb_nxt
            if spill_on:
                front_full = (nxt_n + jnp.sum(sel).astype(jnp.int32)
                              ) > F
                tbl_full = jnp.any(unres_s)
                fa = jax.lax.psum(front_full.astype(jnp.int32), ax) > 0
                tb = jax.lax.psum(tbl_full.astype(jnp.int32), ax) > 0
                abort = fa | tb
                code = fa.astype(jnp.int32) + 2 * tb.astype(jnp.int32)
                revert = ["j", "evp", "nxt", "nxt_n", "visited",
                          "vis_n", "explored", "overflow", "vis_over",
                          "drops", "flag_cnt", "flag_rows"]
                if delta:
                    revert.append("pb_nxt")
                for k in revert:
                    out[k] = jnp.where(abort, carry[k], out[k])
                out["f_full"] = jnp.where(abort, code,
                                          jnp.int32(0))[None]
            return out

        return local

    def _has_rt_masks(self) -> bool:
        return (self.p.deliver_message_rt is not None
                or self.p.deliver_timer_rt is not None)

    def _build_chunk_step(self):
        # The legacy per-chunk driver IS the promote-boundary exchange
        # oracle: rows stay with their producer, the rebalance moves
        # them between levels (route_rows never applies here).
        local = self._make_local_step(route_rows=False)
        spec = self._carry_specs()
        if self._has_rt_masks():
            # Runtime delivery masks ride as a replicated ARGUMENT: every
            # staged phase (different partition/timer gating, same
            # protocol shape) shares one compiled program.
            return shard_map(local, mesh=self.mesh,
                             in_specs=(spec, (P(), P())), out_specs=spec,
                             check_rep=False)
        return shard_map(lambda c: local(c), mesh=self.mesh,
                         in_specs=(spec,), out_specs=spec,
                         check_rep=False)

    # ---------------------------------------------------- level superstep

    def _build_superstep(self):
        """The fused LEVEL superstep: one shard_map program whose
        ``lax.while_loop`` iterates chunk steps until every device's OWN
        frontier shard is drained (including event-window spill passes —
        a spilled chunk holds its ``j`` back, so the drain condition
        covers re-passes), bounded by a replicated ``budget`` scalar so
        a host wall-clock budget keeps mid-level granularity.

        The trip count is occupancy-driven FROM THE CARRY: device d runs
        ``ceil(cur_n_d / C)`` chunk steps (its actual post-rebalance
        share) instead of the host's pre-rebalance ``max_n + D - 1``
        worst case, and the loop condition is the psum of the per-device
        "still draining" flags — every device executes the same trip
        count (the body contains collectives) but that count is the max
        of the ACTUAL needs, not the host's bound.

        Returns ``(carry', stats)`` where ``stats`` is the fused scalar
        vector — the legacy 8 + n_flags layout (_sync_checks parses both
        drivers identically) plus two superstep-only slots:
        ``[..., remaining_devices, steps_taken]``.  Computing the stats
        in-program (psum/pmax over the mesh axis) folds the level sync
        into the same dispatch: host involvement per level becomes
        superstep + promote."""
        local = self._make_local_step(route_rows=self.row_exchange)
        C = self.cpd
        ax = self.axis

        def _psum(x):
            return jax.lax.psum(x, ax)

        spill_on = self._spill is not None

        def stats_local(c, steps):
            core = jnp.stack([
                _psum(c["overflow"][0]),
                _psum(c["drops"][0]),
                _psum(c["vis_over"][0]),
                _psum(c["explored"][0]),
                jax.lax.pmax(c["vis_n"][0], ax),
                _psum(c["vis_n"][0]),
                jax.lax.pmax(c["nxt_n"][0], ax),
                jax.lax.pmin(c["j"][0], ax),
            ]).astype(jnp.int32)
            flags = _psum(c["flag_cnt"]).astype(jnp.int32)
            remaining = _psum(
                (c["j"][0] * C < c["cur_n"][0]).astype(jnp.int32))
            tail = jnp.stack([remaining, steps]).astype(jnp.int32)
            parts = [core, flags, tail]
            if spill_on:
                # Spill abort code after the tail so every legacy index
                # parse is untouched; the abort is global, so any
                # device's copy is the fleet's (pmax for robustness).
                parts.append(jax.lax.pmax(
                    c["f_full"], ax).astype(jnp.int32))
            # Per-device stats lanes (ISSUE 8), LAST so all absolute
            # index parses above stay valid: one all_gather inside the
            # SAME fused program — the replicated stats vector simply
            # grows by 4D int32s, never an extra dispatch or readback.
            per_dev = jnp.stack([c["explored"][0], c["vis_n"][0],
                                 c["nxt_n"][0], c["drops"][0]])
            parts.append(jax.lax.all_gather(
                per_dev, ax).T.reshape(-1).astype(jnp.int32))
            return jnp.concatenate(parts)

        def super_local(carry, budget, masks=None):
            def cond(st):
                c, k = st
                own = c["j"][0] * C < c["cur_n"][0]
                keep = (jax.lax.psum(own.astype(jnp.int32), ax) > 0) & (
                    k < budget)
                if spill_on:
                    # A spill abort (frontier/table full) suspends the
                    # drain loop: the host must evict/spool before the
                    # held-back chunk can be re-stepped.
                    keep = keep & (c["f_full"][0] == 0)
                return keep

            def body(st):
                c, k = st
                return local(c, masks), k + 1

            carry, k = jax.lax.while_loop(cond, body,
                                          (carry, jnp.int32(0)))
            return carry, stats_local(carry, k)

        spec = self._carry_specs()
        if self._has_rt_masks():
            return shard_map(
                lambda c, b, m: super_local(c, b, m), mesh=self.mesh,
                in_specs=(spec, P(), (P(), P())),
                out_specs=(spec, P()), check_rep=False)
        return shard_map(
            lambda c, b: super_local(c, b), mesh=self.mesh,
            in_specs=(spec, P()), out_specs=(spec, P()),
            check_rep=False)

    def _superstep_call(self, carry, budget: int):
        """Dispatch one superstep through the supervisor boundary.  The
        dispatched callable BLOCKS on the stats readback (the tiny
        replicated vector, never rows), so the watchdog bounds the whole
        fused level step and the per-level host transfers stay scalar."""
        if budget >= (1 << 30):
            b = getattr(self, "_budget_full", None)
            if b is None:
                b = self._budget_full = jnp.asarray(1 << 30, jnp.int32)
        else:
            b = jnp.asarray(budget, jnp.int32)
        rt = getattr(self, "_rt_masks", None)

        prog = self._prog("superstep", self._superstep)

        def run(c, bb, *masks):
            c2, stats = (prog(c, bb, masks[0]) if masks
                         else prog(c, bb))
            return c2, device_get(stats)

        if rt is not None:
            return self._dispatch("sharded.superstep", run, carry, b, rt)
        return self._dispatch("sharded.superstep", run, carry, b)

    def _step(self, carry):
        """Dispatch one chunk step, passing the runtime masks when the
        protocol declares them.  Routed through the supervisor's
        dispatch boundary (engine._dispatch) like every hot-loop
        dispatch."""
        rt = getattr(self, "_rt_masks", None)
        prog = self._prog("step", self._chunk_step)
        if rt is not None:
            return self._dispatch("sharded.step", prog, carry, rt)
        return self._dispatch("sharded.step", prog, carry)

    def _build_finish(self):
        """Promote nxt -> cur between levels, REBALANCING the frontier
        across the mesh: successors accumulate on the device that produced
        them (the chunk step exchanges only fingerprints, never rows —
        see _build_chunk_step), so without this every reachable state
        would descend through the initial state's device alone and D-1
        devices would expand empty chunks.  Each device splits its
        occupied prefix into D equal contiguous shares (dynamic slices at
        traced offsets — no computed-index row permutation), one
        all_to_all moves the shares, and a single compaction scatter per
        LEVEL re-densifies — wide row movement at level granularity is
        ~1% of the level's chunk work."""
        D = self.n_devices
        F, lanes = self.f_cap, self.lanes
        plane = self.plane
        pk = self._pk
        delta = self._mesh_delta
        ax = self.axis
        share = F // D

        def local(carry):
            carry = dict(carry)
            nxt, nxt_n = carry["nxt"], carry["nxt_n"][0]
            if D == 1 or self.row_exchange:
                # Fused row exchange (ISSUE 12): successors already
                # landed on their owner's shard inside the superstep,
                # so the promote is a LOCAL buffer swap — zero ICI
                # traffic, zero wide compaction; on one device the
                # round-5 rebalance was an identity anyway.
                carry["cur"] = nxt[:F]
                carry["cur_n"] = carry["nxt_n"]
            else:
                per = (nxt_n + D - 1) // D          # rows per share
                send = jnp.stack([
                    jax.lax.dynamic_slice(nxt, (s * per, 0), (share, plane))
                    for s in range(D)])             # [D, share, plane]
                r = jnp.arange(share)
                send_valid = jnp.stack([
                    (r < per) & (s * per + r < nxt_n) for s in range(D)])
                recv = jax.lax.all_to_all(send, ax, 0, 0)
                recv_valid = jax.lax.all_to_all(send_valid, ax, 0, 0)
                rows = recv.reshape(D * share, plane)
                v = recv_valid.reshape(-1)
                pos = jnp.cumsum(v) - 1
                dst = jnp.where(v, pos, F)
                carry["cur"] = jnp.zeros(
                    (F + 1, plane), jnp.int32).at[dst].set(rows)[:F]
                carry["cur_n"] = jnp.sum(v).astype(jnp.int32)[None]
            if delta:
                # Delta re-base (ISSUE 18 leg (b)): the promoted rows
                # were packed against the OLD level base; re-encode them
                # against the accumulated next-level base (pb_nxt, a
                # global pmin computed inside the chunk steps — already
                # value-identical on every device, so this stays
                # elementwise: the fused promote keeps ZERO collectives).
                pb_old = carry["pb_cur"]
                # A lane whose pb_nxt never saw a successor (empty next
                # frontier) keeps the old base so the (vacuous)
                # re-encode stays in-window.
                pb_new = jnp.where(
                    carry["pb_nxt"] == jnp.int32(self._PB_EMPTY),
                    pb_old, carry["pb_nxt"])
                raw_rows = pk.unpack_jnp(carry["cur"],
                                         self._base_vec(pb_old))
                repacked, bad = pk.pack_jnp(raw_rows,
                                            self._base_vec(pb_new),
                                            count_bad=True)
                occ = jnp.arange(F) < carry["cur_n"][0]
                carry["cur"] = jnp.where(occ[:, None], repacked,
                                         jnp.int32(0))
                carry["overflow"] = carry["overflow"].at[0].add(
                    jnp.sum(jnp.where(occ, bad, 0)).astype(jnp.int32))
                carry["pb_cur"] = pb_new
                carry["pb_nxt"] = jnp.full_like(
                    pb_old, jnp.int32(self._PB_EMPTY))
            carry["nxt"] = jnp.zeros((F + 1, plane), jnp.int32)
            carry["nxt_n"] = jnp.zeros((1,), jnp.int32)
            carry["j"] = jnp.zeros((1,), jnp.int32)
            carry["evp"] = jnp.zeros((1,), jnp.int32)
            if self.record_trace:
                # The level's meta was spilled to host before this runs.
                carry["tmeta"] = jnp.zeros((F + 1, 9), jnp.uint32)
            return carry

        spec = self._carry_specs()
        return shard_map(local, mesh=self.mesh,
                         in_specs=(spec,), out_specs=spec,
                         check_rep=False)

    def _carry_specs(self):
        """shard_map in/out specs for the carry — derived from the
        partition-rule table (CARRY_PARTITION_RULES), not hand-listed,
        so shard_map conventions and NamedSharding placement cannot
        drift apart."""
        return match_partition_rules(CARRY_PARTITION_RULES,
                                     self._carry_names(), self.axis)

    # ------------------------------------------- boundary work stealing

    def _build_steal(self):
        """Chunk-granular work-stealing rebalance (ISSUE 18 leg (c)):
        ONE extra all_to_all at a level boundary moves packed frontier
        rows from overfull owners to underfull ones per a replicated
        host-built [D, D] donation plan (plan[s, r] = rows device s
        donates to device r, each entry <= one chunk).  Only EXPAND
        work migrates — visited shards, and therefore dedup ownership
        and every count, are untouched; the donated rows were already
        deduplicated when they landed on their owner, so moving them
        is a pure relabeling of who expands what.  Donors give away
        their frontier TAIL (the suffix above the kept prefix), so the
        surviving prefix needs no compaction."""
        D = self.n_devices
        F = self.f_cap
        K = self.cpd
        plane = self.plane
        ax = self.axis

        def local(carry, plan):
            carry = dict(carry)
            cur, cur_n = carry["cur"], carry["cur_n"][0]
            s = jax.lax.axis_index(ax)
            give = plan[s]                          # [D] rows to donate
            cum = jnp.cumsum(give)
            tot = cum[-1]
            # Donation r occupies [cur_n - cum[r], cur_n - cum[r] +
            # give[r]) of the local frontier — disjoint tail slices.
            starts = jnp.maximum(cur_n - cum, 0)
            offs = jnp.arange(K)
            # Exact gather (not dynamic_slice: its out-of-bounds start
            # clamping would silently shift a tail window that sits
            # within K of the cap).
            send = jnp.stack([
                jnp.take(cur, (starts[r] + offs).clip(0, F - 1),
                         axis=0)
                for r in range(D)])                 # [D, K, plane]
            sv = offs[None, :] < give[:, None]
            recv = jax.lax.all_to_all(send, ax, 0, 0).reshape(
                D * K, plane)
            rv = jax.lax.all_to_all(sv, ax, 0, 0).reshape(-1)
            keep_n = cur_n - tot
            pos = jnp.cumsum(rv) - 1
            dst = jnp.where(rv, keep_n + pos, F)
            got = jnp.sum(rv).astype(jnp.int32)
            # A receiver past frontier_cap drops the excess — counted
            # loudly (strict runs raise at the next sync); the host
            # plan never builds one (targets <= total // D <= F).
            lost = jnp.sum(rv & (dst >= F)).astype(jnp.int32)
            carry["cur"] = cur.at[dst].set(recv, mode="drop")
            carry["cur_n"] = (keep_n + got - lost)[None]
            carry["drops"] = carry["drops"].at[0].add(lost)
            return carry

        spec = self._carry_specs()
        return self._sharded_jit(
            shard_map(local, mesh=self.mesh, in_specs=(spec, P()),
                      out_specs=spec, check_rep=False),
            extra_in=(self._replicated(),))

    def _steal_prog(self):
        if self._steal_prog_cache is None:
            self._steal_prog_cache = self._build_steal()
        return self._steal_prog_cache

    def _steal_plan(self, occ, depth):
        """Host-side donation planner over the per-device frontier
        occupancy lanes (read from the SAME fused stats vector as the
        level sync — zero extra readbacks).  Returns a [D, D] int32
        plan or None.  Two regimes:

        * ``depth == 1`` — root-fanout seeding: the level-1 frontier is
          the lone root's successor set; split it evenly across owners
          unconditionally (no threshold, no chunk rounding) so the
          early tree never serializes on one owner.
        * deeper levels — gated on ``imbalance_max >``
          DSLABS_MESH_STEAL_THRESHOLD, and donations move in WHOLE
          chunks (the superstep's work quantum: a partial chunk costs a
          full chunk step, so finer migration cannot help)."""
        D, K = self.n_devices, self.cpd
        occ = [int(x) for x in occ]
        total = sum(occ)
        if D == 1 or total < 2:
            return None
        mean = total / D
        imb = max(occ) / mean
        fanout = depth == 1
        if not fanout and imb <= self._steal_threshold:
            return None
        target = total // D
        if fanout:
            # A successor set smaller than the mesh still fans out: one
            # row per owner beats D-1 idle owners at level 2.
            target = max(1, target)
        donors = [[d, occ[d] - target] for d in range(D)
                  if occ[d] > target]
        recvs = [[d, target - occ[d]] for d in range(D)
                 if occ[d] < target]
        donors.sort(key=lambda x: -x[1])
        recvs.sort(key=lambda x: -x[1])
        plan = np.zeros((D, D), np.int32)
        for d, ex in donors:
            for r_ent in recvs:
                if ex <= 0:
                    break
                r, need = r_ent
                if need <= 0:
                    continue
                amt = min(ex, need, K)
                if not fanout:
                    amt = (amt // K) * K     # whole chunks only
                if amt <= 0:
                    continue
                plan[d, r] = amt
                ex -= amt
                r_ent[1] -= amt
        if not plan.any():
            return None
        return plan

    def _maybe_steal(self, carry, depth):
        """Boundary steal hook — runs right after the level promote,
        using the per-device nxt_n lanes (== the promoted frontier
        occupancy under the fused row exchange) from the level's stats
        readback.  Updates the level record and emits a telemetry
        event; counts stay bit-identical by construction (the visited
        shards never move)."""
        if not self._steal_on:
            return carry
        pdev = getattr(self, "_last_per_device", None)
        if not pdev:
            return carry
        occ = pdev.get("frontier")
        if occ is None:
            return carry
        plan = self._steal_plan(occ, depth)
        if plan is None:
            return carry
        prog = self._prog("steal", self._steal_prog())
        pl = jax.device_put(jnp.asarray(plan), self._replicated())
        carry = self._dispatch("sharded.steal", prog, carry, pl)
        moved = int(plan.sum())
        occ_after = [int(o) - int(plan[d].sum()) + int(plan[:, d].sum())
                     for d, o in enumerate(occ)]
        self._steal_events += 1
        self._steal_moved += moved
        from dslabs_tpu.tpu.telemetry import skew_metrics
        before = skew_metrics(occ)
        after = skew_metrics(occ_after)
        recs = getattr(self, "_level_records", None)
        if recs:
            recs[-1]["steal"] = {
                "moved": moved,
                "imbalance_before": before["imbalance"],
                "imbalance_after": after["imbalance"],
            }
            sk = recs[-1].setdefault("skew", {})
            sk["frontier_post_steal"] = after
        pdev["frontier"] = occ_after
        tel = getattr(self, "_telemetry", None)
        if tel is not None:
            tel.event("steal", engine="sharded", depth=depth,
                      moved=moved,
                      imbalance_before=round(before["imbalance"], 3),
                      imbalance_after=round(after["imbalance"], 3))
        return carry

    # ----------------------------------------------------------------- run

    def _root_ids(self, state):
        """Root row + sanitized key + its owner device and home slot —
        shared by _init_carry and the AOT warm-up."""
        rows0 = flatten_state(state)                     # [1, lanes] device
        # Root key through the same canonicalize-then-hash step the
        # expand programs use (symmetry reduction, ISSUE 15b).
        fp0 = np.asarray(self._canonical_root_fp(state),
                         np.uint32)                      # [1, 4]
        owner = int(fp0[0, 0]) % self.n_devices
        key0 = visited_mod.host_sanitize_key(fp0[0])
        # The root key sits in slot 0 of its home BUCKET — addressing
        # mirrored from visited.py (bucket keyed by lane 2).
        home = visited_mod.host_home_slot(key0, self.v_cap)
        return rows0, key0, owner, home

    def _init_carry(self, state) -> dict:
        """Build the sharded carry ON DEVICE: the big buffers (frontier,
        next-frontier, visited table — hundreds of MB) are jnp
        allocations inside a jitted initializer, with only the root row
        and its key crossing the host boundary.  A host-numpy build +
        device_put shipped ~750 MB through the runtime tunnel and cost
        15-50 s per run() — charged to the bench's measured window."""
        rows0, key0, owner, home = self._root_ids(state)
        init = self._prog(("init", owner, home),
                          self._init_prog(owner, home))
        return self._dispatch("sharded.init", init, rows0[0],
                              jnp.asarray(key0))

    def _init_prog(self, owner: int, home: int):
        """The jitted carry initializer for a given root owner/home slot
        (both are baked into the traced program).  Cached so the AOT
        warm-up's compiled program is the one run() actually uses."""
        cache = getattr(self, "_init_progs", None)
        if cache is None:
            cache = self._init_progs = {}
        fn = cache.get((owner, home))
        if fn is not None:
            return fn
        D, F, V, lanes = self.n_devices, self.f_cap, self.v_cap, self.lanes
        plane, pk, delta = self.plane, self._pk, self._mesh_delta
        nf = len(self._flag_names)

        def build(row0, k0):
            onehot_d = jnp.arange(D) == owner
            if delta:
                # Level-0 base = the root row's own delta values (the
                # min over a one-row frontier).
                pb0 = row0[jnp.asarray(self._delta_lanes)].astype(
                    jnp.int32)
                row0s = pk.pack_jnp(row0[None], self._base_vec(pb0))[0]
            elif pk is not None:
                row0s = pk.pack_jnp(row0[None])[0]
            else:
                row0s = row0
            out = {
                "cur": jnp.zeros((D * F, plane), jnp.int32).at[
                    owner * F].set(row0s),
                "cur_n": onehot_d.astype(jnp.int32),
                "j": jnp.zeros((D,), jnp.int32),
                "evp": jnp.zeros((D,), jnp.int32),
                "noapp": jnp.zeros((D,), jnp.int32),
                "nxt": jnp.zeros((D * (F + 1), plane), jnp.int32),
                "nxt_n": jnp.zeros((D,), jnp.int32),
                "visited": jnp.full((D * (V + 1), 4), MAXU32,
                                    jnp.uint32).at[
                    owner * (V + 1) + home].set(k0),
                "vis_n": onehot_d.astype(jnp.int32),
                "explored": jnp.zeros((D,), jnp.int32),
                "overflow": jnp.zeros((D,), jnp.int32),
                "vis_over": jnp.zeros((D,), jnp.int32),
                "drops": jnp.zeros((D,), jnp.int32),
                "flag_cnt": jnp.zeros((D * nf,), jnp.int32),
                "flag_rows": jnp.zeros((D * nf, lanes), jnp.int32),
            }
            if self.record_trace:
                out["tmeta"] = jnp.zeros((D * (F + 1), 9), jnp.uint32)
                out["flag_meta"] = jnp.zeros((D * nf, 9), jnp.uint32)
            if self._spill_on:
                out["f_full"] = jnp.zeros((D,), jnp.int32)
            if delta:
                out["pb_cur"] = jnp.tile(pb0, D)
                out["pb_nxt"] = jnp.full(
                    (D * pb0.shape[0],), jnp.int32(self._PB_EMPTY))
            return out

        fn = jax.jit(build, out_shardings=self._carry_shardings())
        cache[(owner, home)] = fn
        return fn

    # ------------------------------------------------------- AOT warm-up

    def _carry_sds(self):
        """Abstract (ShapeDtypeStruct + NamedSharding) carry pytree for
        AOT lowering — shapes mirror _init_prog's builds, shardings come
        from the SAME partition-rule table every dispatch uses."""
        D, F, V, lanes = self.n_devices, self.f_cap, self.v_cap, self.lanes
        nf = len(self._flag_names)
        shards = self._carry_shardings()

        def sd(name, shape, dtype=jnp.int32):
            return jax.ShapeDtypeStruct(shape, dtype,
                                        sharding=shards[name])

        out = {
            "cur": sd("cur", (D * F, self.plane)),
            "cur_n": sd("cur_n", (D,)),
            "j": sd("j", (D,)), "evp": sd("evp", (D,)),
            "noapp": sd("noapp", (D,)),
            "nxt": sd("nxt", (D * (F + 1), self.plane)),
            "nxt_n": sd("nxt_n", (D,)),
            "visited": sd("visited", (D * (V + 1), 4), jnp.uint32),
            "vis_n": sd("vis_n", (D,)),
            "explored": sd("explored", (D,)),
            "overflow": sd("overflow", (D,)),
            "vis_over": sd("vis_over", (D,)),
            "drops": sd("drops", (D,)),
            "flag_cnt": sd("flag_cnt", (D * nf,)),
            "flag_rows": sd("flag_rows", (D * nf, lanes)),
        }
        if self.record_trace:
            out["tmeta"] = sd("tmeta", (D * (F + 1), 9), jnp.uint32)
            out["flag_meta"] = sd("flag_meta", (D * nf, 9), jnp.uint32)
        if self._spill_on:
            out["f_full"] = sd("f_full", (D,))
        if self._mesh_delta:
            nd = len(self._delta_lanes)
            out["pb_cur"] = sd("pb_cur", (D * nd,))
            out["pb_nxt"] = sd("pb_nxt", (D * nd,))
        return out

    def aot_warmup(self) -> float:
        """Ahead-of-time compile the hot programs (superstep or legacy
        chunk step + stats, the level promote, and the default root's
        carry initializer) via ``.lower().compile()``, so compile cost
        is paid — and MEASURED — at construction instead of inside the
        first run's search window.  With the persistent compile cache
        (DSLABS_COMPILE_CACHE / tpu/compile_cache.py) the second
        construction of any config hits the cache and this drops to
        near-zero.  Returns the wall seconds spent; also accumulated on
        ``self.compile_secs`` and surfaced as
        ``SearchOutcome.compile_secs``."""
        import sys

        t0 = time.time()
        exes = self._aot_exes = getattr(self, "_aot_exes", {})
        try:
            sds = self._carry_sds()
            rt = getattr(self, "_rt_masks", None)
            if self._has_rt_masks() and rt is None:
                raise RuntimeError(
                    "runtime-mask protocol: call set_runtime_masks() "
                    "before aot_warmup()")
            mask_args = (rt,) if rt is not None else ()
            b = jnp.asarray(1 << 30, jnp.int32)
            # The compiled executables are KEPT and invoked directly by
            # the dispatch paths (_prog): jit.__call__ does not reuse
            # .lower().compile() results in this JAX, so calling the jit
            # again would re-trace and re-compile (the persistent cache
            # would absorb the XLA half, but not the tracing).
            if self.use_superstep:
                exes["superstep"] = self._superstep.lower(
                    sds, b, *mask_args).compile()
            else:
                exes["step"] = self._chunk_step.lower(
                    sds, *mask_args).compile()
                exes["stats"] = self._stats.lower(sds).compile()
            exes["promote"] = self._finish_level.lower(sds).compile()
            rows0, key0, owner, home = self._root_ids(
                self.initial_state())
            exes[("init", owner, home)] = self._init_prog(
                owner, home).lower(rows0[0], jnp.asarray(key0)).compile()
        except Exception as e:  # noqa: BLE001 — warm-up must never kill
            # a run; a cold first dispatch is the graceful fallback.
            exes.clear()
            print(f"[dslabs] AOT warm-up skipped: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
        secs = time.time() - t0
        self.compile_secs = getattr(self, "compile_secs", 0.0) + secs
        tel = getattr(self, "_telemetry", None)
        if tel is not None:
            # The explicit AOT warm-up as a first-class trace node
            # (ISSUE 13): the causal timeline shows compile as its own
            # phase instead of folding it into the first dispatch.
            # An event, not a span — span counts stay equal to
            # dispatch counts (the obs-suite parity pin).
            tel.event("compile", engine="sharded",
                      secs=round(secs, 4), aot=True)
        return secs

    def _prog(self, name, default):
        """The AOT-compiled executable for a program when the warm-up
        built one (invoked directly — zero retrace), else the lazy jit."""
        return getattr(self, "_aot_exes", {}).get(name) or default

    def lane_signature(self):
        """Sharded searches are NOT lane-packable (ISSUE 14,
        tpu/lanes.py): the superstep is already one whole-mesh program
        whose dispatch cost is amortised across devices, and stacking
        a lane axis on top of shard_map would multiply the carry's HBM
        footprint by L on every chip.  The service's lane packer reads
        ``None`` as "run solo" — a mesh-sized job keeps its own
        dispatch stream."""
        return None

    def dispatch_site_programs(self):
        """Sanitizer site registry (ISSUE 10; see the base-class
        docstring): the ACTIVE driver's programs — the fused superstep
        by default, the legacy per-chunk step + stats pair under
        DSLABS_SHARDED_SUPERSTEP=0 — plus the level promote, the root
        carry initializer, and the spill reset/evict shard_map programs
        when the host tier is wired.  Args are the same abstract carry
        (ShapeDtypeStruct + NamedSharding) the AOT warm-up lowers, so
        the audit sees byte-identical programs to the ones dispatched."""
        sds = self._carry_sds()
        rt = getattr(self, "_rt_masks", None)
        if self._has_rt_masks() and rt is None:
            raise RuntimeError(
                "runtime-mask protocol: call set_runtime_masks() "
                "before dispatch_site_programs()")
        mask_args = (rt,) if rt is not None else ()
        b = jnp.asarray(1 << 30, jnp.int32)
        sites = {}
        if self.use_superstep:
            sites["sharded.superstep"] = dict(
                fn=self._superstep, args=(sds, b, *mask_args),
                donate=(0,), multi=True,
                builder=self._superstep_jit)
        else:
            sites["sharded.step"] = dict(
                fn=self._chunk_step, args=(sds, *mask_args),
                donate=(0,), multi=True,
                builder=self._chunk_jit)
            sites["sharded.sync"] = dict(
                fn=self._stats, args=(sds,), donate=(), multi=False,
                builder=None)
        sites["sharded.promote"] = dict(
            fn=self._finish_level, args=(sds,), donate=(0,),
            multi=True,
            builder=lambda: self._sharded_jit(self._build_finish()))
        # The bucket-probe kernel (ISSUE 12): the ACTIVE visited.insert
        # variant (Pallas or jnp per DSLABS_VISITED_PALLAS) as a
        # standalone single-device program over one owner-side dedup
        # batch — the profiler's hot-site table and the J1/J2/J4 audit
        # cover the kernel itself, not just the superstep it inlines
        # into.
        ne = self._num_events()
        bucket = (self.cpd * ne if self.n_devices == 1
                  else (self.cpd * ne // self.n_devices + 1)
                  * OVERFLOW_FACTOR)
        sites["visited.insert"] = visited_mod.dispatch_site_program(
            self.v_cap, self.n_devices * bucket)
        rows0, key0, owner, home = self._root_ids(self.initial_state())
        sites["sharded.init"] = dict(
            fn=self._init_prog(owner, home),
            args=(rows0[0], jnp.asarray(key0)), donate=(),
            multi=True, builder=None)
        if self._spill_on:
            progs = self._sh_spill_progs()
            sites["sharded.spill_drain"] = dict(
                fn=progs["reset"], args=(sds,), donate=(0,),
                multi=True, builder=None)
            sites["sharded.spill_evict"] = dict(
                fn=progs["evict"], args=(sds,), donate=(0,),
                multi=True, builder=None)
        # Packed-wire codec lowerings (ISSUE 18): the sharded engine's
        # own pack/decode over one chunk batch, so J1-J5 cover the
        # codec the superstep inlines (delta descriptors take the base
        # vector argument).
        if self._pk is not None:
            pk = self._pk
            rows_sds = jax.ShapeDtypeStruct((self.cpd, self.lanes),
                                            jnp.int32)
            packed_sds = jax.ShapeDtypeStruct((self.cpd, self.plane),
                                              jnp.int32)
            if pk.has_delta:
                base_sds = jax.ShapeDtypeStruct((self.lanes,),
                                                jnp.int32)
                mk_p = lambda: jax.jit(lambda r, b: pk.pack_jnp(r, b))
                mk_u = lambda: jax.jit(lambda r, b: pk.unpack_jnp(r, b))
                sites["packing.pack"] = dict(
                    fn=mk_p(), args=(rows_sds, base_sds), donate=(),
                    multi=False, builder=mk_p)
                sites["packing.unpack"] = dict(
                    fn=mk_u(), args=(packed_sds, base_sds), donate=(),
                    multi=False, builder=mk_u)
            else:
                sites["packing.pack"] = dict(
                    fn=jax.jit(pk.pack_jnp), args=(rows_sds,),
                    donate=(), multi=False,
                    builder=lambda: jax.jit(pk.pack_jnp))
                sites["packing.unpack"] = dict(
                    fn=jax.jit(pk.unpack_jnp), args=(packed_sds,),
                    donate=(), multi=False,
                    builder=lambda: jax.jit(pk.unpack_jnp))
        if self._steal_on:
            plan_sds = jax.ShapeDtypeStruct(
                (self.n_devices, self.n_devices), jnp.int32)
            sites["sharded.steal"] = dict(
                fn=self._steal_prog(), args=(sds, plan_sds),
                donate=(0,), multi=True, builder=self._build_steal)
        return sites

    def _terminal_from_flags(self, carry, explored, vis_total, depth, t0):
        """Resolve the first terminal flag (checkState order) from the
        per-device counters; returns a SearchOutcome or None."""
        nf = len(self._flag_names)
        cnts = np.asarray(carry["flag_cnt"]).reshape(self.n_devices, nf)
        if not cnts.any():
            return None
        rows = np.asarray(carry["flag_rows"]).reshape(
            self.n_devices, nf, self.lanes)
        metas = (np.asarray(carry["flag_meta"]).reshape(
            self.n_devices, nf, 9) if self.record_trace else None)
        for fi, fname in enumerate(self._flag_names):
            devs = np.nonzero(cnts[:, fi])[0]
            if not len(devs):
                continue
            row = rows[devs[0], fi]
            st = jax.tree.map(np.asarray,
                              self.unflatten_rows(row[None]))
            trace = None
            if metas is not None:
                m = metas[devs[0], fi]
                trace = self._walk_fp_chain(
                    tuple(int(x) for x in m[4:8]), int(m[8]))
            elapsed = time.time() - t0
            if fname == "exc":
                return SearchOutcome(
                    "EXCEPTION_THROWN", explored, vis_total, depth, elapsed,
                    violating_state=st, exception_code=int(st["exc"][0]),
                    trace=trace)
            kind, pname = fname.split(":", 1)
            if kind == "inv":
                return SearchOutcome(
                    "INVARIANT_VIOLATED", explored, vis_total, depth,
                    elapsed, violating_state=st, predicate_name=pname,
                    trace=trace)
            return SearchOutcome(
                "GOAL_FOUND", explored, vis_total, depth, elapsed,
                goal_state=st, predicate_name=pname, trace=trace)
        return None

    # ------------------------------------------------------- checkpointing
    #
    # Round-4 redesign: the round-3 dump was a synchronous full-carry
    # readback — MINUTES for a GB-scale carry over the tunnelled runtime,
    # which is why bench.py banned it inside measured windows.  Now the
    # dump (a) slices only the LIVE state — the occupied frontier prefix
    # (bounded by the level sync's max_n, not f_cap) + the visited table
    # + counters; the empty nxt, the f_cap padding, and tmeta are never
    # read back — and (b) runs ASYNChronously: device-side slices are
    # snapshotted into fresh buffers in the level gap, then a background
    # thread drains them host-side and writes the atomic .npz while the
    # next levels compute.  A snapshot still in flight skips the next
    # checkpoint tick (never queues).  Kill mid-write leaves the previous
    # complete dump (tmp + rename).

    def _snapshot_checkpoint(self, carry, max_n: int):
        """Device-side snapshot (fresh buffers — the live carry is
        donated to the next chunk step, so the dump thread must never
        alias it)."""
        # Post-rebalance occupancy bound: ceil-split can give one device
        # up to max_n + D - 1 rows (run()'s chunk-grid bound) — but on a
        # 1-device mesh the rebalance is an identity, so no slack.
        # Rounded UP to a power of two so the per-shape jitted snapshot
        # programs number O(log f_cap), not one per frontier size (each
        # is a synchronous shard_map compile in the level gap).
        need = min(max_n + self._rebalance_slack(), self.f_cap)
        m = self.cpd
        while m < need:
            m <<= 1
        m = max(min(m, self.f_cap), 1)
        plane = self.plane
        cache = getattr(self, "_snap_fns", None)
        if cache is None:
            cache = self._snap_fns = {}
        if m in cache:
            with self.mesh:
                return cache[m](carry)

        def local(c):
            out = {
                "cur": jax.lax.dynamic_slice(
                    c["cur"], (0, 0), (m, plane)),
                "cur_n": c["cur_n"] + 0,
                "visited": c["visited"] + jnp.uint32(0),
                "vis_n": c["vis_n"] + 0,
                "explored": c["explored"] + 0,
                "overflow": c["overflow"] + 0,
                "vis_over": c["vis_over"] + 0,
                "drops": c["drops"] + 0,
                "flag_cnt": c["flag_cnt"] + 0,
                "flag_rows": c["flag_rows"] + 0,
            }
            if self._mesh_delta:
                out["pb_cur"] = c["pb_cur"] + 0
            return out

        spec = self._carry_specs()
        keys = ["cur", "cur_n", "visited", "vis_n", "explored",
                "overflow", "vis_over", "drops", "flag_cnt", "flag_rows"]
        if self._mesh_delta:
            keys.append("pb_cur")
        snap_spec = {k: spec[k] for k in keys}
        fn = jax.jit(shard_map(local, mesh=self.mesh, in_specs=(spec,),
                               out_specs=snap_spec, check_rep=False))
        cache[m] = fn
        with self.mesh:
            return fn(carry)

    def _write_checkpoint(self, snap, depth: int, elapsed: float) -> None:
        """Background-thread half: host readback + conversion to the
        UNIFIED engine-agnostic format (tpu/checkpoint.py) + atomic npz
        write.  The dump stores the semantic search state — live
        frontier rows (all shards concatenated) and the occupied
        visited-table lines — not this engine's carry layout, so any
        ladder rung can resume it."""
        from dslabs_tpu.tpu import checkpoint as ckpt_mod

        D = self.n_devices
        cur = np.asarray(snap["cur"]).reshape(D, -1, self.plane)
        cur_n = np.asarray(snap["cur_n"]).reshape(-1)
        parts = [cur[d, :cur_n[d]] for d in range(D)]
        frontier = (np.concatenate(parts) if cur_n.sum()
                    else np.zeros((0, self.plane), np.int32))
        vis = np.asarray(snap["visited"]).reshape(
            D, self.v_cap + 1, 4)[:, :-1]
        occ = ~(vis == MAXU32).all(axis=2)
        fp_map = None
        if self.record_trace and self._fp_map:
            fp_map = np.asarray(
                [(k + v[0] + (v[1],)) for k, v in self._fp_map.items()],
                dtype=np.int64)
        # Frontier rows ride in the mesh engine's NATIVE encoding
        # (packed when the descriptor is non-identity) with the marker
        # — and, for delta descriptors, the level base — so any ladder
        # rung converts on resume (engine.py _normalize_ckpt_frontier;
        # loud, never silent).
        extra = None
        if self._pk is not None:
            extra = {"frontier_encoding": np.bytes_(
                self._pk.signature().encode())}
            if self._mesh_delta:
                pb = np.asarray(snap["pb_cur"]).reshape(
                    D, -1)[0].astype(np.int32)
                base = np.zeros((self.lanes,), np.int32)
                base[self._delta_lanes] = pb
                extra["pack_base"] = base
        ckpt_mod.save(self.checkpoint_path, ckpt_mod.SearchCheckpoint(
            fingerprint=self._ckpt_fingerprint(), depth=depth,
            explored=int(np.asarray(snap["explored"]).sum()),
            elapsed=elapsed, frontier=frontier, visited_keys=vis[occ],
            vis_over=int(np.asarray(snap["vis_over"]).sum()),
            dropped=int(np.asarray(snap["drops"]).sum()),
            fp_map=fp_map, extra=extra))

    def _save_checkpoint(self, carry, depth: int, elapsed: float,
                         max_n: int = None) -> None:
        """Kick an async checkpoint; skipped (not queued) while a prior
        dump is still draining (checkpoint.AsyncCheckpointWriter)."""
        if self._ckpt_writer.busy():
            return
        snap = self._snapshot_checkpoint(
            carry, max_n if max_n is not None else self.f_cap)
        self._ckpt_writer.kick(
            lambda: self._write_checkpoint(snap, depth, elapsed))

    def _join_checkpoint(self) -> None:
        self._ckpt_writer.join()

    def _load_checkpoint(self):
        """-> (carry on device, depth, elapsed) or None (no dump).  A
        dump from a DIFFERENT protocol/capacity configuration raises a
        loud :class:`~dslabs_tpu.tpu.checkpoint.CheckpointMismatch`
        naming both fingerprints — never resumed (or skipped) silently.
        Rebuilds the full sharded carry from the unified dump: frontier
        rows re-split into contiguous per-device shares, visited keys
        RE-INSERTED into each owner's shard table (owner = key lane 0
        mod D — the same routing the chunk step uses), and the
        never-dumped parts (nxt, loop counters, trace meta) rebuilt
        empty — exactly their state at a level boundary."""
        ck = self._load_ckpt()
        if ck is None:
            return None
        if ck.fp_map is not None:
            self._fp_map = {tuple(r[:4]): (tuple(r[4:8]), int(r[8]))
                            for r in ck.fp_map.tolist()}
        if self._spill_on:
            # Spill-mode resume: every dumped key loads into the host
            # tier and the device tables restart empty (a fresh epoch
            # — the refilter makes that exact); the dumped frontier
            # spools in mesh-sized segments, the first injected via
            # the normal resume path.
            import dataclasses as _dc

            sp = self._spill
            sp.restore(ck.visited_keys, ck.extra)
            # ck.frontier was normalized to RAW lanes by the loader;
            # the spool's steady-state encoding is packed for
            # non-delta descriptors — re-encode the deferred segments
            # to match (_sh_spill_drain's contract), keep raw for
            # delta (re-based per inject) and identity codecs.
            rows = np.asarray(ck.frontier, np.int32)
            spool_rows = rows
            if self._pk is not None and not self._mesh_delta:
                spool_rows = self._pk.pack_np(rows)
            segcap = self.n_devices * self.f_cap
            for i in range(segcap, len(rows), segcap):
                sp.spool_cur.push(spool_rows[i:i + segcap])
            ck = _dc.replace(ck, frontier=rows[:segcap],
                             visited_keys=np.zeros((0, 4), np.uint32))
        return self._resume_carry(ck), ck.depth, ck.elapsed

    def _resume_carry(self, ck):
        D, F, V, lanes = self.n_devices, self.f_cap, self.v_cap, self.lanes
        plane = self.plane
        nf = len(self._flag_names)
        n = len(ck.frontier)
        if -(-n // D) > F:
            raise CapacityOverflow(
                f"{self.p.name}: frontier_cap {F}/device too small to "
                f"resume {n} checkpointed frontier rows on {D} devices")
        # The loader normalized the dump's frontier to RAW lanes
        # (engine.py _normalize_ckpt_frontier) — re-encode to this
        # engine's native packed storage here, with a fresh level base
        # (the per-lane min over the resumed rows) when the descriptor
        # has delta lanes.
        frontier = np.asarray(ck.frontier, np.int32).reshape(-1, lanes)
        pb0 = None
        if self._mesh_delta:
            didx = self._delta_lanes
            pb0 = (frontier[:, didx].min(axis=0).astype(np.int32)
                   if n else np.zeros((len(didx),), np.int32))
            base = np.zeros((lanes,), np.int32)
            base[didx] = pb0
            spans = frontier[:, didx].astype(np.int64) - pb0
            # Max in-window span: the lane mask, minus the reserved
            # all-ones sentinel code where one exists.
            win = ((1 << self._pk.width[didx].astype(np.int64)) - 1
                   - self._pk.sent[didx].astype(np.int64))
            if n and (spans > win[None, :]).any():
                raise CapacityOverflow(
                    f"{self.p.name}: resumed frontier spans a delta "
                    "window wider than the declared Field(delta=) "
                    "bits — raise the delta bits on the offending "
                    "field")
            frontier = self._pk.pack_np(frontier, base)
        elif self._pk is not None:
            frontier = self._pk.pack_np(frontier)
        per = max(1, -(-n // D))
        cur = np.zeros((D, per, plane), np.int32)
        cur_n = np.zeros((D,), np.int32)
        for d in range(D):
            rows = frontier[d * per:(d + 1) * per]
            cur[d, :len(rows)] = rows
            cur_n[d] = len(rows)
        keys = ck.visited_keys
        owner = (keys[:, 0].astype(np.uint64)
                 % np.uint64(D)).astype(np.int64)
        groups = [keys[owner == d] for d in range(D)]
        kmax = max([len(g) for g in groups] + [1])
        kbuf = np.zeros((D, kmax, 4), np.uint32)
        kval = np.zeros((D, kmax), bool)
        for d, g in enumerate(groups):
            kbuf[d, :len(g)] = g
            kval[d, :len(g)] = True

        def spread0(v):
            a = np.zeros((D,), np.int32)
            a[0] = v
            return a

        shard = NamedSharding(self.mesh, P(self.axis))
        dev_in = {k: jax.device_put(v, shard) for k, v in {
            "cur0": cur.reshape(D * per, plane),
            "cur_n": cur_n,
            "keys": kbuf.reshape(D * kmax, 4),
            "kval": kval.reshape(D * kmax),
            "explored": spread0(ck.explored),
            "vis_over": spread0(ck.vis_over),
            "drops": spread0(ck.dropped),
        }.items()}

        def local(s):
            table, ins, unres = visited_mod.insert(
                visited_mod.empty_table(V), s["keys"], s["kval"])
            out = {
                "cur": jnp.zeros((F, plane), jnp.int32).at[:per].set(
                    s["cur0"]),
                "cur_n": s["cur_n"],
                "j": jnp.zeros((1,), jnp.int32),
                "evp": jnp.zeros((1,), jnp.int32),
                "noapp": jnp.zeros((1,), jnp.int32),
                "nxt": jnp.zeros((F + 1, plane), jnp.int32),
                "nxt_n": jnp.zeros((1,), jnp.int32),
                "visited": table,
                "vis_n": jnp.sum(ins).astype(jnp.int32)[None],
                "explored": s["explored"],
                "overflow": jnp.zeros((1,), jnp.int32),
                "vis_over": s["vis_over"],
                "drops": s["drops"],
                "flag_cnt": jnp.zeros((nf,), jnp.int32),
                "flag_rows": jnp.zeros((nf, lanes), jnp.int32),
            }
            if self.record_trace:
                out["tmeta"] = jnp.zeros((F + 1, 9), jnp.uint32)
                out["flag_meta"] = jnp.zeros((nf, 9), jnp.uint32)
            if self._spill_on:
                out["f_full"] = jnp.zeros((1,), jnp.int32)
            if self._mesh_delta:
                out["pb_cur"] = jnp.asarray(pb0, jnp.int32)
                out["pb_nxt"] = jnp.full((len(pb0),), jnp.int32(
                    self._PB_EMPTY))
            return out, jnp.sum(unres).astype(jnp.int32)[None]

        ax = self.axis
        in_spec = {k: P(ax) for k in dev_in}
        fn = jax.jit(shard_map(
            local, mesh=self.mesh, in_specs=(in_spec,),
            out_specs=(self._carry_specs(), P(ax)), check_rep=False))
        with self.mesh:
            carry, unres = fn(dev_in)
        n_unres = int(np.asarray(unres).sum())
        if n_unres:
            raise CapacityOverflow(
                f"{self.p.name}: visited_cap={V}/device too small to "
                f"rebuild the checkpoint's visited set ({n_unres} keys "
                "unresolved); raise visited_cap")
        return carry

    # ------------------------------------------- host-RAM spill tier
    #
    # The sharded half of tpu/spill.py (docs/capacity.md): same
    # drain/evict/refilter/reinject protocol as the single-device
    # engine, with the carry sharded over the mesh — readbacks gather
    # all shards, injections re-split into contiguous per-device
    # shares (the same discipline as _resume_carry).  Everything rides
    # the _dispatch seam (sharded.spill_* tags) so supervisor retry/
    # watchdog/FaultPlan and warden heartbeats cover the spill path.

    def _sh_spill_progs(self) -> dict:
        progs = getattr(self, "_sh_spill_prog_cache", None)
        if progs is not None:
            return progs
        F, V, lanes = self.f_cap, self.v_cap, self.lanes
        spec = self._carry_specs()

        def reset(c):
            out = dict(c)
            out["nxt"] = jnp.zeros((F + 1, self.plane), jnp.int32)
            out["nxt_n"] = jnp.zeros((1,), jnp.int32)
            out["f_full"] = jnp.zeros((1,), jnp.int32)
            return out

        def evict(c):
            out = dict(c)
            out["visited"] = jnp.full((V + 1, 4), MAXU32, jnp.uint32)
            out["vis_n"] = jnp.zeros((1,), jnp.int32)
            out["f_full"] = jnp.zeros((1,), jnp.int32)
            return out

        progs = self._sh_spill_prog_cache = {
            "reset": self._sharded_jit(shard_map(
                reset, mesh=self.mesh, in_specs=(spec,),
                out_specs=spec, check_rep=False)),
            "evict": self._sharded_jit(shard_map(
                evict, mesh=self.mesh, in_specs=(spec,),
                out_specs=spec, check_rep=False)),
            "inject": {},
        }
        return progs

    def _sh_spill_drain(self, carry):
        """Gather every device's occupied nxt prefix (ONE batched
        readback), refilter against the host tier, drop exception/
        pruned rows, spool the keepers, and reset nxt on device.

        Spool encoding (ISSUE 18): PACKED rows when the descriptor has
        no delta lanes (the host tier holds pack_ratio x more states at
        fixed RAM — keys/refilter masks come from a host-side unpack);
        RAW rows under a delta descriptor (the level base changes at
        each re-inject, so a fixed-encoding spool would go stale)."""
        sp = self._spill
        D, F = self.n_devices, self.f_cap
        pk, plane = self._pk, self.plane
        spool_packed = pk is not None and not self._mesh_delta

        def fetch():
            nxt = np.asarray(carry["nxt"]).reshape(D, F + 1, plane)
            counts = np.asarray(carry["nxt_n"]).reshape(-1)
            if counts.sum():
                rows = np.concatenate(
                    [nxt[d, :counts[d]] for d in range(D)])
            else:
                rows = np.zeros((0, plane), np.int32)
            if pk is None:
                raw = rows
            elif self._mesh_delta:
                pb = np.asarray(carry["pb_cur"]).reshape(D, -1)[0]
                base = np.zeros((self.lanes,), np.int32)
                base[self._delta_lanes] = pb
                rows = raw = pk.unpack_np(rows, base)
            else:
                raw = pk.unpack_np(rows)
            return rows, raw, self._spill_keys_of(raw, F)

        rows, raw, keys = self._dispatch("sharded.spill_drain", fetch)
        if len(rows):
            # Async drain (ISSUE 15c): the host half rides the ordered
            # worker while the mesh re-dispatches — see engine.py
            # _spill_drain for the exactness argument.
            def host_half():
                kept = sp.refilter(rows, keys)
                if len(kept):
                    ku = pk.unpack_np(kept) if spool_packed else kept
                    kept = kept[self._spill_keep_mask(ku, F)]
                sp.spool(kept)

            sp.submit_drain(host_half)
        return self._dispatch("sharded.spill_drain",
                              self._sh_spill_progs()["reset"], carry)

    def _sh_spill_evict(self, carry):
        """Bulk eviction: every shard's occupied table lines -> the
        (global) host tier; all tables restart empty."""
        sp = self._spill
        D, V = self.n_devices, self.v_cap

        def fetch():
            vis = np.asarray(carry["visited"]).reshape(D, V + 1, 4)
            return np.concatenate(
                [visited_mod.host_occupied(vis[d]) for d in range(D)])

        occ = self._dispatch("sharded.spill_evict", fetch)
        sp.submit_drain(lambda: sp.evict(occ), evict=True)
        self._last_vis_max = 0
        return self._dispatch("sharded.spill_evict",
                              self._sh_spill_progs()["evict"], carry)

    def _sh_spill_inject(self, carry, rows: np.ndarray):
        """(Re-)inject a host frontier segment: contiguous per-device
        shares (ceil split), zero-padded to a pow2 per-device width so
        the jitted set programs stay O(log f_cap).  Returns
        ``(carry, per_device_max)`` — the chunk-grid bound."""
        D, F, lanes = self.n_devices, self.f_cap, self.lanes
        plane = self.plane
        n = len(rows)
        per = max(1, -(-n // D))
        if per > F:
            raise CapacityOverflow(
                f"{self.p.name}: spool segment of {n} rows exceeds "
                f"frontier_cap {F}/device on {D} devices")
        if self._mesh_delta and n:
            # Delta spools hold RAW rows (_sh_spill_drain): re-encode
            # the segment against the CURRENT level base — pb_cur only
            # moves at promote, and the level's nxt rows all pack
            # against one base, so this stays consistent with what the
            # chunk step decodes.  A value outside the window from the
            # current base is the declared-bits contract being
            # exceeded: loud, with the fix named.
            rows = np.asarray(rows, np.int32).reshape(-1, lanes)
            pb = np.asarray(carry["pb_cur"]).reshape(D, -1)[0]
            base = np.zeros((lanes,), np.int32)
            base[self._delta_lanes] = pb
            spans = (rows[:, self._delta_lanes].astype(np.int64)
                     - pb.astype(np.int64))
            win = ((1 << self._pk.width[self._delta_lanes].astype(
                np.int64)) - 1
                - self._pk.sent[self._delta_lanes].astype(np.int64))
            if (spans < 0).any() or (spans > win[None, :]).any():
                raise CapacityOverflow(
                    f"{self.p.name}: spill re-inject found delta-lane "
                    "values outside the window from the current level "
                    "base — raise the Field(delta=) bits (spill defers "
                    "re-basing, so deep spilled runs need wider "
                    "windows)")
            rows = self._pk.pack_np(rows, base)
        m = self.cpd
        while m < per:
            m <<= 1
        m = max(min(m, F), 1)
        progs = self._sh_spill_progs()
        fn = progs["inject"].get(m)
        if fn is None:
            spec = self._carry_specs()
            ax = self.axis

            def inject(c, seg, nn):
                out = dict(c)
                out["cur"] = jnp.zeros((F, plane),
                                       jnp.int32).at[:m].set(seg)
                out["cur_n"] = nn
                out["j"] = jnp.zeros((1,), jnp.int32)
                out["evp"] = jnp.zeros((1,), jnp.int32)
                out["f_full"] = jnp.zeros((1,), jnp.int32)
                return out

            seg_shard = NamedSharding(self.mesh, P(ax))
            fn = progs["inject"][m] = self._sharded_jit(shard_map(
                inject, mesh=self.mesh,
                in_specs=(spec, P(ax), P(ax)), out_specs=spec,
                check_rep=False), extra_in=(seg_shard, seg_shard))
        buf = np.zeros((D, m, plane), np.int32)
        counts = np.zeros((D,), np.int32)
        for d in range(D):
            part = rows[d * per:(d + 1) * per]
            buf[d, :len(part)] = part
            counts[d] = len(part)
        shard = NamedSharding(self.mesh, P(self.axis))
        seg = jax.device_put(buf.reshape(D * m, plane), shard)
        nn = jax.device_put(counts, shard)
        carry = self._dispatch("sharded.spill_reinject", fn, carry,
                               seg, nn)
        return carry, int(counts.max())

    def _sh_spill_ckpt(self, carry, depth: int, explored: int,
                       elapsed: float) -> None:
        """Synchronous spill-mode unified dump: visited_keys = all
        shard tables ∪ host tier (exact-deduped), frontier = the
        spooled next level, counters on extra__spill_stats.  Any rung
        — spill or not, sharded or not — resumes it (docs/capacity.md)."""
        from dslabs_tpu.tpu import checkpoint as ckpt_mod

        sp = self._spill
        D, V = self.n_devices, self.v_cap
        vis = np.asarray(carry["visited"]).reshape(D, V + 1, 4)
        occ = np.concatenate(
            [visited_mod.host_occupied(vis[d]) for d in range(D)])
        # The spool holds packed rows for non-delta descriptors
        # (_sh_spill_drain) — the dump then carries the encoding
        # marker; delta spools are raw, so their dump is raw too.
        spool_packed = self._pk is not None and not self._mesh_delta
        extra = sp.checkpoint_extra() or {}
        if spool_packed:
            extra["frontier_encoding"] = np.bytes_(
                self._pk.signature().encode())
        ckpt_mod.save(self.checkpoint_path, ckpt_mod.SearchCheckpoint(
            fingerprint=self._ckpt_fingerprint(), depth=depth,
            explored=explored, elapsed=elapsed,
            frontier=sp.spool_cur.concat(
                self.plane if spool_packed else self.lanes),
            visited_keys=sp.checkpoint_keys(occ),
            extra=extra or None))

    def run(self, check_initial: bool = True,
            initial: Optional[dict] = None,
            resume: bool = False) -> SearchOutcome:
        """Run the sharded BFS.  ``initial`` (a batch-1 state pytree,
        e.g. a prior outcome's ``goal_state``) starts from an arbitrary
        state — the staged-search pattern (PaxosTest.java:886-1096),
        same contract as the single-device engine.  ``resume=True``
        continues from ``checkpoint_path`` if a dump exists (a killed
        search restarts at its last checkpointed level with identical
        final verdict and unique count)."""
        t0 = time.time()
        state = (jax.tree.map(jnp.asarray, initial) if initial is not None
                 else self.initial_state())
        # Root of this run's trace (tpu/trace.py replays from here).
        self._trace_root = jax.tree.map(np.asarray, state)
        self._fp_map = {}
        self._deep_samples = None
        # Structured per-level throughput records (depth, chunks, wall,
        # explored, unique, next_frontier) — attached to the outcome as
        # SearchOutcome.levels; DSLABS_LEVEL_TIMING pretty-prints the
        # same records to stderr as they land.
        self._level_records: List[dict] = []
        self._pd_prev_explored = [0] * self.n_devices
        self._root_fp = tuple(np.asarray(
            self._canonical_root_fp(state), np.uint32)[0].tolist())
        if check_initial:
            out = self._check_initial(state, t0)
            if out is not None:
                return out

        tel = getattr(self, "_telemetry", None)
        if tel is not None and self._spill is not None:
            self._spill.telemetry = tel
        try:
            out = self._run_levels(t0, state, resume)
            out.levels = self._level_records or None
            out.compile_secs = round(getattr(self, "compile_secs", 0.0), 3)
            self._stamp_capacity(out)
            if self._spill_on:
                self._spill.attach(out)
            if tel is not None:
                # Trace stamp at span emission (ISSUE 13): host string
                # copy off the recorder's context, zero device work.
                if out.trace_id is None:
                    out.trace_id = tel.trace_id
                tel.on_outcome(out, engine="sharded")
                if self.n_devices > 1 and self._pk is None:
                    # Identity-codec fallback on a real mesh (ISSUE 18
                    # satellite): the exchange shipped RAW lanes — hand
                    # twins without domain declarations, or the
                    # DSLABS_MESH_PACK=0 parity oracle.  Loud until
                    # ROADMAP #1 deletes the hand twins.
                    tel.event(
                        "mesh_unpacked", engine="sharded",
                        protocol=self.p.name,
                        mesh_width=self.n_devices,
                        reason=("knob" if not self.mesh_pack
                                else "identity descriptor"),
                        wire_lanes=self.lanes)
            if out.dropped and out.dropped >= _DROPPED_WARN():
                # The BENCH_r03 shape (5.8M beam drops, one flag to
                # show for it) must be LOUD — dropped_states is also a
                # first-class bench JSON field now.
                import warnings

                warnings.warn(
                    f"{self.p.name}: beam truncation dropped "
                    f"{out.dropped} states (>= DSLABS_DROPPED_WARN="
                    f"{_DROPPED_WARN()}); the verdict covers a "
                    "narrowed space — raise frontier_cap or enable "
                    "the spill tier for zero-drop coverage",
                    RuntimeWarning, stacklevel=2)
            return out
        finally:
            # An async checkpoint still draining must complete before the
            # caller sees the outcome (kill-resume tests depend on the
            # dump landing; the thread holds device snapshots alive).
            self._join_checkpoint()

    def _run_levels(self, t0, state, resume) -> SearchOutcome:
        with self.mesh:
            resumed = self._load_checkpoint() if resume else None
            if resumed is not None:
                carry, depth, prev_elapsed = resumed
                t0 = time.time() - prev_elapsed
                max_n = int(np.asarray(carry["cur_n"]).max())
                # Pre-loop totals: a checkpoint saved after the FINAL
                # level has an empty frontier, so the while body (which
                # normally binds these) never runs.
                explored = int(np.asarray(carry["explored"]).sum())
                vis_total = int(np.asarray(carry["vis_n"]).sum())
                if self._spill_on:
                    vis_total = self._spill.unique(vis_total)
                drops = int(np.asarray(carry["drops"]).sum())
            else:
                if self._spill_on:
                    # Fresh start: run N must not refilter against run
                    # N-1's tier (engine-reuse pattern; the resumed
                    # branch restores the tier from the dump instead).
                    self._spill.reset_run()
                carry = self._init_carry(state)
                depth = 0
                max_n = 1
                explored, vis_total, drops = 0, 1, 0   # the root state
            while max_n > 0:
                if self.max_depth is not None and depth >= self.max_depth:
                    return self._limit_outcome("DEPTH_EXHAUSTED", carry,
                                               depth, t0)
                if (self.max_secs is not None
                        and time.time() - t0 > self.max_secs) \
                        or self._cancelled():
                    out = self._limit_outcome("TIME_EXHAUSTED", carry,
                                              depth, t0)
                    out.cancelled = self._cancelled()
                    return out
                depth += 1
                # Live depth for supervision heartbeats (tpu/warden.py).
                self._current_depth = depth
                t_lvl = time.time()
                # Final depth-limited level: count/check fresh successors
                # without building the next frontier (it would never be
                # expanded — and at bench scale it would not even FIT:
                # the depth-10 strict probe's last level is ~4x the
                # frontier cap).  The explicit DEPTH_EXHAUSTED return
                # below replaces the loop-top check for this level.
                noapp_level = (self.max_depth is not None
                               and depth >= self.max_depth)
                if noapp_level and not self._spill_on:
                    # Spill mode keeps appends ON for the final level:
                    # the host spool absorbs an over-cap last level
                    # (noapp's reason to exist), and every fresh insert
                    # must reach the boundary refilter or a tier
                    # re-discovery would double-count (exact unique
                    # parity is the whole point of the tier).
                    shard = NamedSharding(self.mesh, P(self.axis))
                    carry["noapp"] = jax.device_put(
                        np.ones(self.n_devices, np.int32), shard)
                if self.use_superstep:
                    (carry, out, explored, vis_total, drops, max_n,
                     chunks) = self._level_superstep(carry, depth, t0,
                                                     max_n)
                else:
                    (carry, out, explored, vis_total, drops, max_n,
                     chunks) = self._level_chunks(carry, depth, t0, max_n)
                if out is not None:
                    return out
                if self._spill_on:
                    # Deferred re-expansion waves: spooled segments of
                    # THIS level (frontier rows that outgrew the device
                    # buffer, or a resumed dump's tail) run at the same
                    # depth before the level closes — depth accounting,
                    # and therefore DEPTH_EXHAUSTED soundness, is
                    # preserved exactly.
                    while True:
                        seg = self._spill.pop_current()
                        if seg is None:
                            break
                        carry, per = self._sh_spill_inject(carry, seg)
                        (carry, out, explored, vis_total, drops, max_n,
                         ch2) = self._level_superstep(carry, depth, t0,
                                                      per)
                        chunks += ch2
                        if out is not None:
                            return out
                rec = {
                    "depth": depth, "chunks": int(chunks),
                    "wall": round(time.time() - t_lvl, 4),
                    "explored": int(explored), "unique": int(vis_total),
                    "next_frontier": int(max_n),
                    # Per-level visited-table load factor (ISSUE 6
                    # satellite): pressure is visible in bench JSON
                    # before the overflow contract can fire.
                    "load_factor": round(
                        getattr(self, "_last_load", 0.0), 4),
                    # Wire/storage codec this level ran under (ISSUE
                    # 18): 1.0 = raw exchange — the identity-fallback
                    # gap the run()-level telemetry event makes loud.
                    "pack_ratio": (round(self._pk.pack_ratio, 3)
                                   if self._pk is not None else 1.0)}
                # Mesh-scope lanes (ISSUE 8): the pre-psum per-device
                # scalars the fused stats vector already carried, plus
                # skew metrics — what the owner-hashed all_to_all
                # design is decided on (ROADMAP #1).  Explored is
                # cumulative per device, so the level's work share is
                # the delta against the previous level sync.
                pdev = getattr(self, "_last_per_device", None)
                if pdev is not None:
                    from dslabs_tpu.tpu import telemetry as tel_mod

                    prev = getattr(self, "_pd_prev_explored",
                                   [0] * self.n_devices)
                    delta = [e - p for e, p in zip(pdev["explored"],
                                                   prev)]
                    self._pd_prev_explored = list(pdev["explored"])
                    rec["per_device"] = {
                        "explored": delta,
                        "frontier": pdev["frontier"],
                        "load_factor": [round(v / self.v_cap, 4)
                                        for v in pdev["vis_n"]],
                        "drops": pdev["drops"]}
                    rec["skew"] = {
                        "explored": tel_mod.skew_metrics(delta),
                        "frontier": tel_mod.skew_metrics(
                            pdev["frontier"])}
                tel = getattr(self, "_telemetry", None)
                if tel is not None:
                    # Host-side HBM high-water per device, polled via
                    # the runtime's memory stats at level boundaries
                    # ONLY (a host syscall — never a device dispatch
                    # or readback; CPU meshes report nothing and the
                    # lane is omitted).
                    from dslabs_tpu.tpu import telemetry as tel_mod

                    hbm = tel_mod.device_memory_stats(
                        self.mesh.devices.flat)
                    if hbm is not None:
                        rec["hbm_peak"] = hbm
                self._level_records.append(rec)
                if tel is not None:
                    # The SAME host scalars the fused stats readback
                    # already delivered — telemetry adds no transfers.
                    tel.on_level("sharded", self._level_records[-1])
                if _LEVEL_TIMING:
                    import sys as _sys
                    r = self._level_records[-1]
                    print(f"[level {r['depth']}] chunks={r['chunks']} "
                          f"dt={r['wall']:.2f}s "
                          f"chunk={r['wall']/max(r['chunks'],1)*1e3:.1f}ms "
                          f"explored={r['explored']} "
                          f"unique={r['unique']} "
                          f"next={r['next_frontier']}",
                          flush=True, file=_sys.stderr)
                if noapp_level and self._spill_on:
                    # Final level, spill mode: drain through the
                    # refilter for the exact dedup accounting, then
                    # decide DEPTH vs SPACE on the refiltered,
                    # prune-filtered remainder — the same "expandable
                    # successors remained" question noapp's would-be
                    # count answers in the uncapped run.
                    carry = self._sh_spill_drain(carry)
                    vis_total = self._spill.unique(
                        int(np.asarray(carry["vis_n"]).sum()))
                    remained = self._spill.spool_next.rows()
                    out = SearchOutcome(
                        "DEPTH_EXHAUSTED" if remained > 0
                        else "SPACE_EXHAUSTED",
                        explored, vis_total, depth,
                        time.time() - t0, dropped=drops,
                        samples=getattr(self, "_deep_samples", None))
                    return out
                if noapp_level:
                    # max_n counted the final level's would-be appends:
                    # zero means the space ended exactly at the depth
                    # limit — SPACE_EXHAUSTED, matching the base engine
                    # and the pre-noapp loop's verdict at this boundary.
                    return SearchOutcome(
                        "DEPTH_EXHAUSTED" if max_n > 0
                        else "SPACE_EXHAUSTED",
                        explored, vis_total, depth,
                        time.time() - t0, dropped=drops,
                        samples=getattr(self, "_deep_samples", None),
                        visited_overflow=getattr(self, "_vis_over", 0))
                if self.record_trace:
                    self._spill_tmeta(carry)
                sp = self._spill
                if self._spill_on and (sp.active or sp.should_evict(
                        getattr(self, "_last_vis_max", 0), self.v_cap)):
                    # Spill boundary: drain nxt through the refilter
                    # (the corrected promote mask — one batched
                    # readback against the PRE-eviction tier), evict at
                    # high water, swap spools, re-inject the next
                    # level's first segment.  Replaces the on-device
                    # promote until the pressure clears.
                    carry = self._sh_spill_drain(carry)
                    if sp.should_evict(
                            getattr(self, "_last_vis_max", 0),
                            self.v_cap):
                        carry = self._sh_spill_evict(carry)
                    vis_total = sp.unique(
                        int(np.asarray(carry["vis_n"]).sum()))
                    sp.advance_level()
                    if not sp.spool_cur.segments:
                        return SearchOutcome(
                            "SPACE_EXHAUSTED", explored, vis_total,
                            depth, time.time() - t0, dropped=drops,
                            samples=getattr(self, "_deep_samples",
                                            None))
                    if (self.checkpoint_every and self.checkpoint_path
                            and depth % self.checkpoint_every == 0):
                        self._sh_spill_ckpt(carry, depth, explored,
                                            time.time() - t0)
                    seg = sp.spool_cur.pop()
                    carry, max_n = self._sh_spill_inject(carry, seg)
                    continue
                carry = self._dispatch(
                    "sharded.promote",
                    self._prog("promote", self._finish_level), carry)
                # Boundary work stealing (ISSUE 18 leg (c)): root-fanout
                # at depth 1 (split the lone root's successor set), the
                # threshold-gated chunk-granular rebalance at deeper
                # boundaries.  max_n stays the (safe, pre-steal) bound.
                carry = self._maybe_steal(carry, depth)
                if (self.checkpoint_every and self.checkpoint_path
                        and depth % self.checkpoint_every == 0):
                    self._save_checkpoint(carry, depth, time.time() - t0,
                                          max_n=max_n)

            return SearchOutcome(
                "SPACE_EXHAUSTED", explored, vis_total, depth,
                time.time() - t0, dropped=drops,
                samples=getattr(self, "_deep_samples", None),
                visited_overflow=getattr(self, "_vis_over", 0))

    def _rebalance_slack(self) -> int:
        """Post-rebalance occupancy slack over the pre-rebalance max_n:
        ceil-split can hand one device up to ``max_n + D - 1`` rows — but
        a 1-device mesh's rebalance is an identity, so the extra
        (mostly-invalid) chunk the slack would force is pure waste on
        the TPU bench path and is skipped.  The fused row exchange has
        no rebalance at all (owner-side appends ARE the placement, and
        the level sync's nxt_max is already the exact per-device
        bound), so it needs no slack either."""
        if self.n_devices == 1 or self.row_exchange:
            return 0
        return self.n_devices - 1

    def _level_superstep(self, carry, depth, t0, max_n):
        """One BFS level via the fused on-device superstep: each
        dispatch drains up to ``budget`` chunk steps (unbounded when no
        wall-clock budget is set — the whole level in ONE dispatch) and
        returns the fused stats in the same program.  Returns
        ``(carry, outcome_or_none, explored, vis_total, drops, nxt_max,
        chunk_steps_run)``."""
        budget = ((1 << 30) if self.max_secs is None
                  else max(1, self._superstep_chunks))
        # Watchdog granularity (tpu/supervisor.py): a superstep
        # legitimately runs a whole level's chunk work in one dispatch,
        # so the per-dispatch deadline scales by the expected trip count
        # (2x for event-window spill re-passes).
        est = -(-(max_n + self._rebalance_slack()) // self.cpd)
        self._dispatch_deadline_scales = {
            "superstep": float(max(1, min(budget, 2 * est)))}
        nf = len(self._flag_names)
        chunks = 0
        while True:
            carry, stats = self._superstep_call(carry, budget)
            chunks += int(stats[9 + nf])
            # The checks run BEFORE any time-budget return: a violation
            # or capacity loss in the chunks already completed is never
            # masked by TIME_EXHAUSTED (same contract as the legacy
            # driver's mid-level clock check).
            (out, explored, vis_total, drops, nxt_max,
             _j) = self._sync_checks(carry, depth, t0, stats=stats)
            if out is not None:
                return (carry, out, explored, vis_total, drops, nxt_max,
                        chunks)
            if self._spill_on and int(stats[10 + nf]):
                # Spill abort: the superstep suspended on a frontier-
                # full (bit 0) / table-full (bit 1) chunk, reverted
                # wholesale.  Drain nxt through the refilter to the
                # host spool, evict the tables if they were the wall,
                # and re-enter the drain loop — the held-back chunk
                # re-steps against recovered capacity.
                code = int(stats[10 + nf])
                if (code & 1) and nxt_max == 0:
                    raise CapacityOverflow(
                        f"{self.p.name}: one chunk's fresh successors "
                        f"exceed frontier_cap={self.f_cap}/device even "
                        f"with spill; lower chunk_per_device "
                        f"({self.cpd}) or raise frontier_cap")
                if (code & 2) and int(stats[4]) == 0:
                    raise CapacityOverflow(
                        f"{self.p.name}: one chunk's unique successors "
                        f"exceed visited_cap={self.v_cap}/device even "
                        f"from empty tables; lower chunk_per_device "
                        f"({self.cpd}) or raise visited_cap")
                carry = self._sh_spill_drain(carry)
                if code & 2:
                    carry = self._sh_spill_evict(carry)
                continue
            if int(stats[8 + nf]) == 0:     # every device's shard drained
                return (carry, None, explored, vis_total, drops, nxt_max,
                        chunks)
            if (self.max_secs is not None
                    and time.time() - t0 > self.max_secs) \
                    or self._cancelled():
                out = self._limit_outcome("TIME_EXHAUSTED", carry,
                                          depth, t0)
                out.cancelled = self._cancelled()
                return (carry, out,
                        explored, vis_total, drops, nxt_max, chunks)

    def _level_chunks(self, carry, depth, t0, max_n):
        """The legacy host-driven per-chunk level driver (one jitted
        dispatch per chunk + one stats sync) — kept behind
        ``DSLABS_SHARDED_SUPERSTEP=0`` as the parity oracle the fused
        superstep is tested against.  Same return contract as
        :meth:`_level_superstep`."""
        # max_n was read BEFORE the rebalance: a device can end up with
        # ceil(total/D) <= max_n + D - 1 rows afterwards, so widen the
        # chunk grid by that bound (at most one extra, mostly-invalid
        # chunk; never silently skips rows).  1-device meshes skip the
        # slack — the rebalance is an identity there.
        n_chunks = -(-(max_n + self._rebalance_slack()) // self.cpd)
        chunks = n_chunks
        for j in range(n_chunks):
            carry = self._step(carry)
            # Respect the time budget inside long levels too.  The
            # partial level runs the same overflow/terminal-flag
            # checks as a full level before reporting, so a
            # violation or capacity loss in the chunks already
            # processed is never masked by TIME_EXHAUSTED.
            # Dispatch is async — without the periodic block the
            # whole level enqueues in milliseconds and the clock
            # check below can never fire mid-level (round-3: a
            # 120 s budget overran to 153 s, and the overrun runs
            # the SLOWEST, highest-table-load chunks).
            if (self.max_secs is not None and j % 16 == 15):
                jax.block_until_ready(carry["j"])
            if (self.max_secs is not None and j + 1 < n_chunks
                    and time.time() - t0 > self.max_secs):
                (out, explored, vis_total, drops, nxt_max,
                 _j) = self._sync_checks(carry, depth, t0)
                if out is None:
                    out = self._limit_outcome("TIME_EXHAUSTED", carry,
                                              depth, t0)
                return (carry, out, explored, vis_total, drops, nxt_max,
                        j + 1)
        # ---- the one host sync per level.  With event-window spill, a
        # chunk that had valid events past its window held j back —
        # re-dispatch until the slowest device has completed all its
        # chunks (no extra readbacks when nothing spilled: j_done rides
        # the same stats vector).
        while True:
            (out, explored, vis_total, drops, nxt_max,
             j_done) = self._sync_checks(carry, depth, t0)
            if out is not None:
                return (carry, out, explored, vis_total, drops, nxt_max,
                        chunks)
            if not self.ev_spill or j_done >= n_chunks:
                return (carry, None, explored, vis_total, drops, nxt_max,
                        chunks)
            # Spill rounds respect the time budget too (the checks above
            # already ran, so a verdict in the completed chunks is never
            # masked).
            if (self.max_secs is not None
                    and time.time() - t0 > self.max_secs):
                return (carry,
                        self._limit_outcome("TIME_EXHAUSTED", carry,
                                            depth, t0),
                        explored, vis_total, drops, nxt_max, chunks)
            for _ in range(n_chunks - j_done):
                carry = self._step(carry)
                chunks += 1

    def _spill_tmeta(self, carry) -> None:
        """Fold this level's appended (child_fp, parent_fp, event) rows
        into the host-side fingerprint chain map (trace mode only).
        Vectorised: a per-row Python loop at frontier scale would dwarf
        the device time per level."""
        F = self.f_cap
        meta = np.asarray(carry["tmeta"]).reshape(
            self.n_devices, F + 1, 9)
        counts = np.asarray(carry["nxt_n"]).reshape(-1)
        rows = np.concatenate([meta[d, :counts[d]]
                               for d in range(self.n_devices)])
        if not len(rows):
            return
        children = list(map(tuple, rows[:, :4].tolist()))
        parents = list(map(tuple, rows[:, 4:8].tolist()))
        events = rows[:, 8].tolist()
        # Keep FIRST occurrence (BFS parent) both within the level's batch
        # (reversed zip: earlier rows overwrite later duplicates — today
        # owner-side dedup already makes within-level children unique, but
        # first-wins must not depend on that) and across levels (existing
        # entries win via the update order below).
        new = dict(zip(reversed(children),
                       zip(reversed(parents), reversed(events))))
        new.update(self._fp_map)
        self._fp_map = new
        # Sample a few of this level's children (spread across the batch)
        # and keep their root-first traces; at an exhaust verdict these
        # are the deepest states available for the object-side
        # value-invariant re-check (ADVICE r4).  The rows are already on
        # the host — only K short chain walks per level.
        k = min(3, len(rows))
        picks = {0, len(rows) // 2, len(rows) - 1}
        samples = []
        for i in sorted(picks)[:k]:
            tr = self._walk_fp_chain(parents[i], int(events[i]))
            if tr is not None:
                samples.append(tr)
        if samples:
            self._deep_samples = samples

    def _walk_fp_chain(self, parent_fp, event_id) -> Optional[list]:
        """flag_meta (parent fp, event) -> grid event ids root-first, by
        walking the host fp map back to the run's root state."""
        events = [event_id]
        fp = parent_fp
        seen = 0
        while fp != self._root_fp:
            ent = self._fp_map.get(fp)
            if ent is None:
                return None     # chain broken (shouldn't happen)
            fp, ev = ent
            events.append(ev)
            seen += 1
            if seen > 10 ** 6:
                return None
        events.reverse()
        return events

    def _sync_checks(self, carry, depth, t0, stats=None):
        """The per-sync check pipeline: semantic overflow (raise) ->
        strict-mode drops (raise) -> terminal flags (checkState order) ->
        visited load factor (raise).  ONE device->host readback (the fused
        ``_stats`` vector) — or zero when the superstep already returned
        the vector in-program (``stats``); the expensive flag-row
        readback happens only when a terminal flag actually fired.
        Returns (outcome_or_none, explored, vis_total, drops, nxt_max,
        j_done) where j_done is the slowest device's completed-chunk
        count (the spill re-dispatch signal)."""
        if stats is None:
            s = np.asarray(self._dispatch(
                "sharded.sync", self._prog("stats", self._stats), carry))
        else:
            s = np.asarray(stats)
        nf = len(self._flag_names)
        (overflow, drops, vis_over, explored, vis_max, vis_total, nxt_max,
         j_done) = (int(x) for x in s[:8])
        flag_counts = s[8:8 + nf]
        # Per-device stats lanes: the LAST 4D slots of either driver's
        # layout (superstep appends them after the tail/f_full slots,
        # the legacy stats program after the flags) — stashed for the
        # level record's skew derivation, same readback as everything
        # above.
        D = self.n_devices
        pd = [int(x) for x in s[len(s) - 4 * D:]]
        self._last_per_device = {
            "explored": pd[:D], "vis_n": pd[D:2 * D],
            "frontier": pd[2 * D:3 * D], "drops": pd[3 * D:]}
        # Running total for outcome plumbing (SearchOutcome
        # .visited_overflow): keys the full table degraded to
        # treat-as-fresh — sound, but unique counts may over-report.
        self._vis_over = vis_over
        # Early-warning instrumentation (ISSUE 6 satellite): surface
        # table pressure BEFORE the overflow contract fires.  The
        # effective ceiling is the strict 75% guard when it applies,
        # the raw capacity otherwise; load_factor also lands on the
        # per-level records (SearchOutcome.levels).
        limit = (3 * self.v_cap // 4
                 if self.strict and not self._spill_on else self.v_cap)
        self._last_load = vis_max / self.v_cap
        self._last_vis_max = vis_max
        if (vis_max >= int(_VISITED_WARN() * limit)
                and not getattr(self, "_warned_visited", False)):
            self._warned_visited = True
            import warnings

            warnings.warn(
                f"{self.p.name}: visited table at {vis_max}/"
                f"{self.v_cap} per device (load "
                f"{self._last_load:.0%}) at depth {depth} — capacity "
                "pressure; "
                + ("the spill tier will evict to host RAM"
                   if self._spill_on else
                   "raise visited_cap or enable the spill tier "
                   "(spill=True / DSLABS_SPILL=1) before this "
                   "becomes CapacityOverflow"),
                RuntimeWarning, stacklevel=2)
        if self._spill_on:
            # Exact unique count across tiers (tpu/spill.py): the
            # device total is one epoch's inserts; the host tier holds
            # the evicted epochs, minus refilter-corrected duplicates.
            vis_total = self._spill.unique(vis_total)
        if overflow:
            raise CapacityOverflow(
                f"{self.p.name}: {overflow} semantic drops at depth "
                f"{depth} (net_cap/timer_cap overflowed; raise the caps)")
        if drops and self.strict:
            raise CapacityOverflow(
                f"{self.p.name}: {drops} capacity drops at depth "
                f"{depth} (routing bucket or frontier cap "
                f"{self.f_cap}/device; raise caps or run "
                f"strict=False for beam-style truncation)")
        # Terminal flags before the table guards: a violation/goal found
        # this level is a valid verdict even if the table is full.
        if flag_counts.any():
            out = self._terminal_from_flags(carry, explored, vis_total,
                                            depth, t0)
            if out is not None:
                out.dropped = drops
                out.visited_overflow = vis_over
                return out, explored, vis_total, drops, nxt_max, j_done
        if self._spill_on:
            # The abort protocol reverts any chunk that would leave
            # keys unresolved, and eviction replaces the 75% guard.
            if vis_over:
                raise AssertionError(
                    "spill mode committed unresolved keys (abort "
                    "contract violated)")
            return None, explored, vis_total, drops, nxt_max, j_done
        if vis_over and self.strict:
            raise CapacityOverflow(
                f"{self.p.name}: visited hash table full at depth "
                f"{depth} ({vis_over} unresolved keys, cap "
                f"{self.v_cap}/device); raise visited_cap or run "
                "strict=False for sound treat-as-fresh degradation")
        if self.strict and vis_max > 3 * self.v_cap // 4:
            raise CapacityOverflow(
                f"{self.p.name}: visited hash table > 75% full "
                f"({vis_max}/{self.v_cap} per device) "
                f"at depth {depth}; raise visited_cap")
        return None, explored, vis_total, drops, nxt_max, j_done

    def _limit_outcome(self, cond, carry, depth, t0):
        unique = int(np.asarray(carry["vis_n"]).sum())
        if self._spill_on:
            unique = self._spill.unique(unique)
        return SearchOutcome(
            cond,
            int(np.asarray(carry["explored"]).sum()),
            unique,
            depth, time.time() - t0,
            dropped=int(np.asarray(carry["drops"]).sum()),
            samples=getattr(self, "_deep_samples", None),
            visited_overflow=int(np.asarray(carry["vis_over"]).sum()))
