"""Multi-chip sharded BFS level step (SPMD over a jax.sharding.Mesh).

Scaling design (SURVEY §2.10, §5): the frontier is data-parallel over the
``search`` mesh axis; every device expands its shard with the same vmapped
transition the single-chip engine uses, then successors are exchanged by
**fingerprint ownership** (device = h1 mod D) with ``lax.all_to_all`` over
ICI so each device deduplicates exactly the keys it owns against its own
visited shard.  Collectives: one all_to_all for the routed successor
records + fingerprints, and psums for the level statistics — the classic
hash-partitioned distributed BFS, mapped onto XLA collectives instead of
the reference's shared-memory ConcurrentHashMap (Search.java:405-505).

The routed exchange uses fixed-capacity buckets (OVERFLOW_FACTOR x the
balanced share) — hash partitioning balances well; overflowed records are
counted (psum) so callers can detect loss rather than silently undercount.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dslabs_tpu.tpu.engine import SENTINEL, TensorProtocol, TensorSearch

__all__ = ["ShardedTensorSearch", "make_mesh"]

OVERFLOW_FACTOR = 2


def make_mesh(n_devices: int = None, axis: str = "search") -> Mesh:
    devs = jax.devices()
    if n_devices is not None and len(devs) < n_devices:
        # Fewer accelerators than requested: use the virtual host-CPU
        # devices (--xla_force_host_platform_device_count) — the dry-run
        # path for multi-chip shardings on single-chip machines.
        devs = jax.devices("cpu")
    if n_devices is not None:
        if len(devs) < n_devices:
            raise RuntimeError(
                f"need {n_devices} devices, have {len(devs)} "
                "(set --xla_force_host_platform_device_count)")
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


class ShardedTensorSearch(TensorSearch):
    """BFS driver whose level expansion runs SPMD over a device mesh.

    The host loop (frontier compaction, visited merging, termination) is
    inherited; only the hot expand + ownership routing is sharded."""

    def __init__(self, protocol: TensorProtocol, mesh: Mesh,
                 chunk_per_device: int = 1 << 10, **kwargs):
        self.mesh = mesh
        self.axis = mesh.axis_names[0]
        self.n_devices = mesh.devices.size
        super().__init__(protocol, chunk=chunk_per_device * self.n_devices,
                         **kwargs)
        self._sharded_expand = self._build_sharded_expand(chunk_per_device)

    # ----------------------------------------------------------- level step

    def _build_sharded_expand(self, cpd: int):
        p = self.p
        ne = self._num_events()
        D = self.n_devices
        ax = self.axis
        bucket = (cpd * ne // D + 1) * OVERFLOW_FACTOR
        lanes = (p.node_width + p.net_cap * p.msg_width
                 + p.n_nodes * p.timer_cap * p.timer_width)

        def flatten_state(s):
            m = s["nodes"].shape[0]
            return jnp.concatenate(
                [s["nodes"].reshape(m, -1), s["net"].reshape(m, -1),
                 s["timers"].reshape(m, -1)], axis=1)

        def local_step(chunk_state, chunk_valid):
            """Runs on ONE device over its [cpd] shard of the chunk."""
            flat, valids, h1, h2, flags = self._expand_chunk(
                chunk_state, chunk_valid)
            rows = flatten_state(flat)

            # Ownership routing: bucket successors by h1 mod D.
            owner = (h1 % D).astype(jnp.int32)
            owner = jnp.where(valids, owner, D)  # invalid -> dropped
            # Stable sort by owner so each destination's records are
            # contiguous; then scatter into [D, bucket] send buffers.
            order = jnp.argsort(owner, stable=True)
            owner_s = owner[order]
            rows_s = rows[order]
            h1_s, h2_s = h1[order], h2[order]
            # Position of each record within its destination bucket.
            idx_in_bucket = jnp.arange(owner_s.shape[0]) - jnp.searchsorted(
                owner_s, owner_s, side="left")
            fits = (owner_s < D) & (idx_in_bucket < bucket)
            dropped = jnp.sum((owner_s < D) & ~fits)
            # Column `bucket` is a write-off slot for non-fitting rows so
            # they cannot clobber real records; it is dropped below.
            send_rows = jnp.full((D, bucket + 1, lanes), SENTINEL, rows.dtype)
            send_h1 = jnp.full((D, bucket + 1), jnp.int64(2 ** 62), jnp.int64)
            send_h2 = jnp.zeros((D, bucket + 1), jnp.int64)
            dst = owner_s.clip(0, D - 1)
            slot = jnp.where(fits, idx_in_bucket, bucket).clip(0, bucket)
            send_rows = send_rows.at[dst, slot].set(rows_s)
            send_h1 = send_h1.at[dst, slot].set(
                jnp.where(fits, h1_s, jnp.int64(2 ** 62)))
            send_h2 = send_h2.at[dst, slot].set(jnp.where(fits, h2_s, 0))
            send_rows = send_rows[:, :bucket]
            send_h1 = send_h1[:, :bucket]
            send_h2 = send_h2[:, :bucket]

            # The exchange: every device receives the bucket destined to it
            # from every other device (ICI all-to-all).
            recv_rows = jax.lax.all_to_all(send_rows, ax, 0, 0, tiled=False)
            recv_h1 = jax.lax.all_to_all(send_h1, ax, 0, 0, tiled=False)
            recv_h2 = jax.lax.all_to_all(send_h2, ax, 0, 0, tiled=False)
            recv_rows = recv_rows.reshape(D * bucket, lanes)
            recv_h1 = recv_h1.reshape(D * bucket)
            recv_h2 = recv_h2.reshape(D * bucket)

            # Local owner-side dedup: sort by key, keep first occurrences.
            o = jnp.lexsort((recv_h2, recv_h1))
            rh1, rh2 = recv_h1[o], recv_h2[o]
            first = jnp.ones(rh1.shape[0], bool).at[1:].set(
                (rh1[1:] != rh1[:-1]) | (rh2[1:] != rh2[:-1]))
            valid_recv = rh1 < jnp.int64(2 ** 62)
            unique = first & valid_recv
            n_explored = jnp.sum(valids)
            # Cross-device stats ride the ICI as psums.
            totals = {
                "explored": jax.lax.psum(n_explored, ax),
                "routed_unique": jax.lax.psum(jnp.sum(unique), ax),
                "dropped": jax.lax.psum(dropped, ax),
            }
            flag_any = {k: jax.lax.psum(jnp.sum(v), ax)
                        for k, v in flags.items()}
            return (recv_rows[o], rh1, rh2, unique, totals, flag_any)

        in_specs = (
            {"nodes": P(ax), "net": P(ax), "timers": P(ax)}, P(ax))
        out_specs = (P(ax), P(ax), P(ax), P(ax), P(), P())
        fn = shard_map(local_step, mesh=self.mesh,
                       in_specs=in_specs, out_specs=out_specs,
                       check_rep=False)
        return jax.jit(fn)

    def level_step(self, chunk_state, chunk_valid):
        """One sharded BFS level step over the mesh (the 'training step' of
        this framework: expand + route + dedup + reduce)."""
        with self.mesh:
            return self._sharded_expand(chunk_state, chunk_valid)
