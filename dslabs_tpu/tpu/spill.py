"""Host-RAM spill tier: the sound capacity ladder under every engine.

ROADMAP item #4 ("bigger-than-HBM searches").  Before this module the
device visited table and the frontier buffer were hard walls: a strict
search that crossed either raised :class:`CapacityOverflow` and the
failover ladder could not help (smaller rungs have LESS capacity), and
a beam search silently narrowed (BENCH_r03 dropped 5.8M states with
only a flag to show for it).  This module turns both walls into the
classic explicit-state tiering trick (disk-based / hash-compaction
checkers a la Stern & Dill): cold state moves OFF the fast device onto
host RAM, and "full" degrades to "slower, still exact".

Three cooperating pieces, all engine-agnostic (the drivers in
engine.py / sharded.py own the device half):

* :class:`HostVisitedTier` — the cold half of the visited set: an
  exact, sorted host-side store of 128-bit fingerprints (the same
  (h1, h2) uint64 representation the host parity loop uses).  When the
  device table crosses the load-factor high-water mark, its occupied
  key lines are EVICTED here in bulk and the table restarts empty; at
  every level boundary the batch of would-be-fresh states is
  RE-FILTERED against this tier (one batched readback + a corrected
  promote mask — never a per-state host sync), so a state discovered
  before an eviction is never re-expanded after one.

* :class:`FrontierSpool` — the overflow-safe frontier: rows that would
  be dropped (beam) or fatal (strict) at frontier capacity are spilled
  here and re-injected as deferred re-expansion waves AT THE SAME BFS
  DEPTH, so level/depth accounting — and therefore the soundness of a
  ``DEPTH_EXHAUSTED`` verdict — is preserved exactly.  Two spools
  (current level being consumed, next level being assembled) swap at
  each level boundary.

* :class:`SpillManager` — the bookkeeping that keeps strict counts
  EXACT across tiers.  Within one eviction epoch the device table
  dedups perfectly; across epochs a re-discovered state is counted
  once more by the device (``dup_epoch``) and the refilter both drops
  the duplicate row and subtracts the double count:

      unique = len(tier) + vis_n_device_epoch - dup_epoch

  The refilter invariants that make this exact (derived in
  docs/capacity.md):

  - every batch of rows leaving the device (a mid-level drain or the
    level-boundary promote) is refiltered against the tier BEFORE the
    next eviction can add its own keys to the tier — so a first
    discovery is never mistaken for a re-discovery;
  - each drained batch spans a single eviction epoch, so it is
    internally duplicate-free (the device table guaranteed that);
  - an aborted chunk step is reverted WHOLESALE on device (table
    included), so a retried chunk re-runs against exactly the state it
    first saw.

Checkpoints: the unified dump (tpu/checkpoint.py) stays engine- and
tier-agnostic — ``visited_keys`` stores the UNION of the device table
and the host tier (deduplicated), ``frontier`` stores the injected
rows plus every spooled segment, and the spill counters ride an
``extra__spill_stats`` array.  The host tier therefore inherits the
CRC32 checksum and ``.prev`` rotation like everything else, a non-
spill engine can resume a spill dump (if its table fits the key set),
and a spill engine resumes ANY dump by loading all keys into the tier
and starting the device table empty — which is why kill-mid-spill
resume is bit-exact.

Env knobs: ``DSLABS_SPILL`` (default engine opt-in), ``DSLABS_SPILL_
HIGH_WATER`` (eviction trigger, default 0.60 of visited_cap),
``DSLABS_SPILL_HOST_CAP`` (max keys the tier accepts before raising —
the supervisor's capacity ladder escalates it), ``DSLABS_VISITED_WARN``
(early-warning load factor, default 0.85), ``DSLABS_DROPPED_WARN``
(beam dropped-states warning threshold, default 1e6).
"""

from __future__ import annotations

import dataclasses
import os
import queue as queue_mod
import threading
import time
from typing import List, Optional, Tuple

import numpy as np

__all__ = ["SpillConfig", "SpillStats", "HostVisitedTier",
           "FrontierSpool", "SpillManager", "spill_env_default",
           "spill_manager_for_audit",
           "VISITED_WARN_DEFAULT", "DROPPED_WARN_DEFAULT",
           "visited_warn_threshold", "dropped_warn_threshold",
           "TIER_FORMAT", "TierMismatch", "TierCorrupt",
           "save_tier", "load_tier", "peek_tier_meta"]

VISITED_WARN_DEFAULT = 0.85
DROPPED_WARN_DEFAULT = 1_000_000


def spill_env_default() -> bool:
    v = os.environ.get("DSLABS_SPILL")
    if v is None:
        return False
    return v.strip().lower() not in ("0", "", "off", "false", "no")


def spill_manager_for_audit() -> "SpillManager":
    """A minimally-configured manager whose only job is flipping an
    engine into spill mode so the sanitizer's jaxpr audit
    (dslabs_tpu/analysis/jaxpr_audit.py) can lower and check the
    spill-variant step/drain/evict programs — the audit never runs a
    search, so the tier stays empty and the tiny host cap is free."""
    return SpillManager(SpillConfig(high_water=0.60, host_cap=1 << 16))


def visited_warn_threshold() -> float:
    """Load factor past which the early-warning fires (satellite:
    operators must see pressure BEFORE overflow)."""
    try:
        return float(os.environ.get("DSLABS_VISITED_WARN", "") or
                     VISITED_WARN_DEFAULT)
    except ValueError:
        return VISITED_WARN_DEFAULT


def dropped_warn_threshold() -> int:
    try:
        return int(os.environ.get("DSLABS_DROPPED_WARN", "") or
                   DROPPED_WARN_DEFAULT)
    except ValueError:
        return DROPPED_WARN_DEFAULT


def _async_env_default() -> bool:
    v = os.environ.get("DSLABS_SPILL_ASYNC")
    if v is None:
        return True
    return v.strip().lower() not in ("0", "", "off", "false", "no")


@dataclasses.dataclass(frozen=True)
class SpillConfig:
    """Spill-tier knobs.  ``high_water``: device-table load factor that
    triggers a bulk eviction at the next boundary (the abort-and-retry
    backstop in the step programs catches anything that outruns it).
    ``host_cap``: max keys the host tier accepts; crossing it raises
    CapacityOverflow (host RAM is large, not infinite) — the
    supervisor's capacity ladder retries with a bigger tier.
    ``async_drain`` (ISSUE 15c, default ON; DSLABS_SPILL_ASYNC=0 pins
    the legacy sync-per-chunk gear): the drain's host half — tier
    refilter, prune mask, spool, eviction absorb — runs on a single
    ordered worker while the device re-dispatches the next chunk, so
    host round-trips stop serializing against device compute.  The
    single ordered queue preserves every exactness invariant (each
    batch refilters against the pre-eviction tier; counts are read
    behind a barrier)."""

    high_water: float = float(
        os.environ.get("DSLABS_SPILL_HIGH_WATER", "") or 0.60)
    host_cap: int = int(
        os.environ.get("DSLABS_SPILL_HOST_CAP", "") or (1 << 26))
    async_drain: bool = dataclasses.field(
        default_factory=_async_env_default)


@dataclasses.dataclass
class SpillStats:
    """The accounting SearchOutcome surfaces (never a silent spill).

    ``drain_wall_ms``/``drain_wait_ms`` are the async-drain wall split
    (ISSUE 15c): total host milliseconds spent inside drain jobs vs
    milliseconds the driver actually BLOCKED at a barrier waiting for
    them — their difference is host work that overlapped device
    compute (the pipelining win; zero wait = full overlap)."""

    spilled_keys: int = 0        # keys evicted device -> host tier
    host_tier_hits: int = 0      # re-discoveries the refilter removed
    respilled_frontier: int = 0  # frontier rows through the host spool
    evictions: int = 0           # bulk table evictions
    reinjections: int = 0        # deferred re-expansion waves injected
    drain_wall_ms: int = 0       # host ms inside drain jobs
    drain_wait_ms: int = 0       # host ms blocked at drain barriers

    @property
    def overlap_ms(self) -> int:
        return max(0, self.drain_wall_ms - self.drain_wait_ms)

    @property
    def overlap_ratio(self) -> float:
        """Fraction of drain-host wall hidden behind device compute."""
        if self.drain_wall_ms <= 0:
            return 0.0
        return round(self.overlap_ms / self.drain_wall_ms, 4)

    def as_array(self) -> np.ndarray:
        return np.asarray([self.spilled_keys, self.host_tier_hits,
                           self.respilled_frontier, self.evictions,
                           self.reinjections, self.drain_wall_ms,
                           self.drain_wait_ms], np.int64)

    @classmethod
    def from_array(cls, a) -> "SpillStats":
        a = np.asarray(a, np.int64).reshape(-1)
        vals = [int(x) for x in a[:7]]
        vals += [0] * (7 - len(vals))     # pre-round-2 dumps: 5 slots
        return cls(*vals)


def _rows_to_u64(keys: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """[K, 4] uint32 device-format key rows -> (h1, h2) uint64 pairs —
    the host tier's native representation (same packing as
    engine.host_keys; duplicated here to keep spill.py import-light)."""
    keys = np.asarray(keys, np.uint64).reshape(-1, 4)
    h1 = (keys[:, 0] << np.uint64(32)) | keys[:, 1]
    h2 = (keys[:, 2] << np.uint64(32)) | keys[:, 3]
    return h1, h2


def _u64_to_rows(h1: np.ndarray, h2: np.ndarray) -> np.ndarray:
    rows = np.empty((len(h1), 4), np.uint32)
    rows[:, 0] = (h1 >> np.uint64(32)).astype(np.uint32)
    rows[:, 1] = (h1 & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    rows[:, 2] = (h2 >> np.uint64(32)).astype(np.uint32)
    rows[:, 3] = (h2 & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    return rows


class HostVisitedTier:
    """Exact host-RAM fingerprint set: sorted (h1, h2) uint64 arrays.

    Membership reuses the collision-safe forward scan of
    ``engine.sorted_member`` (imported lazily — engine imports nothing
    from this module at top level, so no cycle)."""

    def __init__(self, host_cap: int = 1 << 26):
        self.h1 = np.empty((0,), np.uint64)
        self.h2 = np.empty((0,), np.uint64)
        self.host_cap = host_cap

    def __len__(self) -> int:
        return len(self.h1)

    def nbytes(self) -> int:
        return int(self.h1.nbytes + self.h2.nbytes)

    def absorb(self, keys: np.ndarray) -> int:
        """Merge [K, 4] key rows into the tier (sorted-merge, exact
        dedup against the existing set AND within the batch).  Returns
        the number of NEW keys added; raises CapacityOverflow past
        ``host_cap`` (the ladder escalates the cap, never silently
        drops a key)."""
        if not len(keys):
            return 0
        h1, h2 = _rows_to_u64(keys)
        order = np.lexsort((h2, h1))
        h1, h2 = h1[order], h2[order]
        first = np.ones(len(h1), bool)
        first[1:] = (h1[1:] != h1[:-1]) | (h2[1:] != h2[:-1])
        h1, h2 = h1[first], h2[first]
        fresh = ~self._contains_u64(h1, h2)
        n_new = int(fresh.sum())
        if n_new == 0:
            return 0
        if len(self) + n_new > self.host_cap:
            from dslabs_tpu.tpu.engine import CapacityOverflow

            raise CapacityOverflow(
                f"host spill tier full: {len(self)} + {n_new} keys > "
                f"host_cap {self.host_cap} "
                "(raise DSLABS_SPILL_HOST_CAP or let the supervisor's "
                "capacity ladder escalate it)")
        mh1 = np.concatenate([self.h1, h1[fresh]])
        mh2 = np.concatenate([self.h2, h2[fresh]])
        mo = np.lexsort((mh2, mh1))
        self.h1, self.h2 = mh1[mo], mh2[mo]
        return n_new

    def _contains_u64(self, h1, h2) -> np.ndarray:
        from dslabs_tpu.tpu.engine import sorted_member

        if not len(self.h1) or not len(h1):
            return np.zeros(len(h1), bool)
        return sorted_member(self.h1, self.h2, h1, h2)

    def contains(self, keys: np.ndarray) -> np.ndarray:
        """[K, 4] key rows -> bool membership mask."""
        h1, h2 = _rows_to_u64(keys)
        return self._contains_u64(h1, h2)

    def key_rows(self) -> np.ndarray:
        """The whole tier as [K, 4] uint32 rows (checkpoint union)."""
        return _u64_to_rows(self.h1, self.h2)


# ------------------------------------------------- tier persistence
#
# Versioned on-disk format for the exact host tier (ISSUE 16 satellite:
# the cross-job memo store persists one tier per spec signature).  Same
# durability discipline as tpu/checkpoint.py: CRC32 content checksum,
# atomic tmp+replace with one-deep ``.prev`` rotation, and a LOUD
# refusal — never a silent empty tier — when the file is foreign (pack
# descriptor or symmetry flag differs from what the consumer expects)
# or torn (checksum mismatch on every candidate).

TIER_FORMAT = "dslabs-visited-tier-v1"


class TierMismatch(RuntimeError):
    """The tier on disk belongs to a different configuration (foreign
    pack descriptor, symmetry flag, or format version): its (h1, h2)
    fingerprints hash a DIFFERENT encoding of state, so absorbing them
    would silently corrupt exact-dedup counts."""


class TierCorrupt(RuntimeError):
    """No candidate tier file passed the content checksum."""


def _tier_checksum(h1: np.ndarray, h2: np.ndarray,
                   meta_blob: bytes) -> np.uint32:
    import zlib

    crc = zlib.crc32(meta_blob)
    crc = zlib.crc32(np.ascontiguousarray(h1).tobytes(), crc)
    crc = zlib.crc32(np.ascontiguousarray(h2).tobytes(), crc)
    return np.uint32(crc & 0xFFFFFFFF)


def save_tier(path: str, h1: np.ndarray, h2: np.ndarray,
              meta: Optional[dict] = None) -> None:
    """Atomic checksummed tier dump with one-deep rotation.  ``meta``
    pins the encoding identity (``pack`` descriptor signature,
    ``sym`` perm count, anything else the producer wants checked);
    :func:`load_tier` refuses a mismatch loudly."""
    import json

    full = {"fmt": TIER_FORMAT}
    full.update(meta or {})
    blob = json.dumps(full, sort_keys=True).encode()
    h1 = np.asarray(h1, np.uint64)
    h2 = np.asarray(h2, np.uint64)
    host = {"meta": np.bytes_(blob), "h1": h1, "h2": h2,
            "checksum": _tier_checksum(h1, h2, blob)}
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **host)
    if os.path.exists(path):
        os.replace(path, path + ".prev")
    os.replace(tmp, path)


def peek_tier_meta(path: str) -> Optional[dict]:
    """The tier's meta dict without loading the key arrays, or None
    when no readable candidate exists."""
    import json

    for cand in (path, path + ".prev"):
        if not os.path.exists(cand):
            continue
        try:
            with np.load(cand) as z:
                if "meta" in z.files:
                    return json.loads(z["meta"].item().decode())
        except Exception:  # noqa: BLE001 — torn file: try .prev
            continue
    return None


def load_tier(path: str, expect_meta: Optional[dict] = None
              ) -> Tuple[np.ndarray, np.ndarray, dict]:
    """Load and VERIFY a tier dump -> ``(h1, h2, meta)``.

    * A checksum-failing main file falls back to ``.prev`` with a
      warning; when every candidate fails, :class:`TierCorrupt`.
    * ``expect_meta``: every key the caller passes must match the
      stored meta EXACTLY (plus the format version, always checked) —
      a foreign pack descriptor or symmetry flag raises
      :class:`TierMismatch` naming both sides, never returns keys."""
    import json
    import warnings

    last_err: Optional[str] = None
    for cand in (path, path + ".prev"):
        if not os.path.exists(cand):
            continue
        try:
            with np.load(cand) as z:
                data = {k: z[k] for k in z.files}
        except Exception as e:  # noqa: BLE001 — torn zip: try .prev
            last_err = f"{cand}: unreadable ({type(e).__name__}: {e})"
            continue
        if not all(k in data for k in ("meta", "h1", "h2", "checksum")):
            last_err = f"{cand}: not a tier dump (missing entries)"
            continue
        blob = data["meta"].item()
        h1 = np.asarray(data["h1"], np.uint64)
        h2 = np.asarray(data["h2"], np.uint64)
        want = int(np.uint32(data["checksum"]))
        got = int(_tier_checksum(h1, h2, blob))
        if want != got:
            last_err = (f"{cand}: tier checksum mismatch "
                        f"(stored {want:#010x}, computed {got:#010x})")
            continue
        if cand.endswith(".prev") and last_err:
            warnings.warn(f"tier {path}: main dump unusable "
                          f"({last_err}); resuming from .prev",
                          RuntimeWarning, stacklevel=2)
        meta = json.loads(blob.decode())
        if meta.get("fmt") != TIER_FORMAT:
            raise TierMismatch(
                f"{cand}: tier format {meta.get('fmt')!r} != expected "
                f"{TIER_FORMAT!r} — refusing a cross-version tier")
        for k, v in (expect_meta or {}).items():
            if meta.get(k) != v:
                raise TierMismatch(
                    f"{cand}: tier {k!r} mismatch — stored "
                    f"{meta.get(k)!r}, expected {v!r} (a foreign "
                    "encoding must never seed exact-dedup state)")
        return h1, h2, meta
    raise TierCorrupt(
        f"{path}: no loadable tier candidate "
        f"({last_err or 'no file exists'})")


class FrontierSpool:
    """Host-side queue of frontier row segments for ONE BFS level."""

    def __init__(self):
        self.segments: List[np.ndarray] = []

    def push(self, rows: np.ndarray) -> None:
        if len(rows):
            self.segments.append(np.asarray(rows, np.int32))

    def pop(self) -> Optional[np.ndarray]:
        return self.segments.pop(0) if self.segments else None

    def rows(self) -> int:
        return sum(len(s) for s in self.segments)

    def concat(self, lanes: int) -> np.ndarray:
        if not self.segments:
            return np.zeros((0, lanes), np.int32)
        return np.concatenate(self.segments, axis=0)


class _DrainWorker:
    """The async drain's single ordered worker (ISSUE 15c): jobs run
    strictly in submission order on one daemon thread, so a refilter
    submitted before an eviction always sees the pre-eviction tier —
    the exactness invariant needs ORDER, not synchrony.  A job that
    raises (e.g. the tier's CapacityOverflow) parks the exception and
    skips the rest of the queue; the next :meth:`barrier` re-raises it
    on the driver thread — loud, never swallowed."""

    def __init__(self):
        self._q: "queue_mod.Queue" = queue_mod.Queue()
        self._thread: Optional[threading.Thread] = None
        self._exc: Optional[BaseException] = None
        self.busy_secs = 0.0

    def _loop(self) -> None:
        while True:
            fn = self._q.get()
            try:
                if fn is not None and self._exc is None:
                    t0 = time.time()
                    fn()
                    self.busy_secs += time.time() - t0
            except BaseException as e:  # noqa: BLE001 — re-raised at
                self._exc = e           # the next barrier
            finally:
                self._q.task_done()

    def submit(self, fn) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name="dslabs-spill-drain")
            self._thread.start()
        self._q.put(fn)

    def pending(self) -> bool:
        return self._q.unfinished_tasks > 0

    def barrier(self) -> None:
        self._q.join()
        if self._exc is not None:
            e, self._exc = self._exc, None
            raise e


class SpillManager:
    """Per-run spill state shared by a driver's device half.

    The driver owns WHEN (load-factor checks, abort codes from the
    step program); this object owns the host tier, the two spools, the
    exact-count bookkeeping, the refilter math, and — since ISSUE 15c
    — the async drain queue that overlaps all of that host work with
    the next device chunk."""

    def __init__(self, config: Optional[SpillConfig] = None):
        self.config = config or SpillConfig()
        self.tier = HostVisitedTier(host_cap=self.config.host_cap)
        self.spool_cur = FrontierSpool()    # level being consumed
        self.spool_next = FrontierSpool()   # level being assembled
        self.stats = SpillStats()
        self._worker: Optional[_DrainWorker] = None
        self._walls_reported = (0.0, 0.0)   # (busy, wait) last snapshot
        # Optional telemetry recorder (tpu/telemetry.py), set by the
        # owning engine at run start: evictions and reinjections become
        # flight-recorder events (host bookkeeping only — the device
        # round-trips themselves are already spans via _dispatch).
        self.telemetry = None
        # Device-table inserts THIS EPOCH that duplicate a tier key
        # (refilter hits); reset at each eviction — see the module
        # docstring's unique formula.
        self.dup_epoch = 0

    def reset_run(self) -> None:
        """Fresh-run reset: tier, spools, counters, and epoch all
        restart empty (the worker thread survives).  Called by the
        drivers at the top of every NON-resume run — an engine reused
        across runs (the bench's warm-up-then-measure pattern) must
        not refilter run 2 against run 1's tier: that dropped live
        states as 're-discoveries' and corrupted counts (the latent
        reuse bug ISSUE 15's capacity2 phase exposed).  Resume paths
        call :meth:`restore` instead, which rebuilds the tier from the
        dump."""
        self.barrier()
        self.tier = HostVisitedTier(host_cap=self.config.host_cap)
        self.spool_cur = FrontierSpool()
        self.spool_next = FrontierSpool()
        self.stats = SpillStats()
        self.dup_epoch = 0
        if self._worker is not None:
            self._worker.busy_secs = 0.0
        self._walls_reported = (0.0, 0.0)

    # ----------------------------------------------------- async drain

    def submit_drain(self, fn, evict: bool = False) -> None:
        """Queue one drain job (refilter+spool, or an eviction
        absorb).  Async gear: runs on the ordered worker while the
        device continues; sync gear (async_drain=False): runs inline
        — byte-identical semantics, the legacy timing."""
        if not self.config.async_drain:
            fn()
            return
        if self._worker is None:
            self._worker = _DrainWorker()
        self._worker.submit(fn)

    def barrier(self) -> None:
        """Wait for every queued drain job; re-raises a parked job
        exception.  Every count/spool READ goes behind this — the
        driver blocks only when it actually needs the numbers, which
        is what turns the drain wall into overlap."""
        w = self._worker
        if w is None:
            return
        if not w.pending():
            # Queue already drained — but a parked exception from a
            # completed job must STILL surface here (losing it would
            # be the silent-swallow this class exists to prevent).
            w.barrier()
            return
        t0 = time.time()
        try:
            w.barrier()
        finally:
            self.stats.drain_wait_ms += int(
                (time.time() - t0) * 1000)
            self.stats.drain_wall_ms = int(w.busy_secs * 1000)

    def level_walls(self) -> dict:
        """Drain wall split SINCE THE LAST CALL — the per-level
        spill-overlap numbers the drivers attach to their level
        records (telemetry satellite)."""
        busy = (self._worker.busy_secs if self._worker is not None
                else 0.0)
        self.stats.drain_wall_ms = int(busy * 1000)
        wait = self.stats.drain_wait_ms / 1000.0
        pb, pw = self._walls_reported
        self._walls_reported = (busy, wait)
        return {"drain_wall": round(busy - pb, 4),
                "drain_wait": round(wait - pw, 4),
                "drain_overlap": round(max(0.0, (busy - pb)
                                           - (wait - pw)), 4)}

    # ------------------------------------------------------------ state

    @property
    def active(self) -> bool:
        """Spill machinery engaged: once anything has been tiered or
        spooled, level boundaries must run the refilter path.  Until
        then the driver keeps its fast on-device promote."""
        self.barrier()
        return (len(self.tier) > 0 or bool(self.spool_cur.segments)
                or bool(self.spool_next.segments))

    def should_evict(self, vis_n: int, cap: int) -> bool:
        return vis_n >= int(self.config.high_water * cap)

    def unique(self, vis_n_device: int) -> int:
        """Exact distinct-state count across tiers (module docstring).
        Reads behind the drain barrier: pending refilters still owe
        their dup_epoch corrections."""
        self.barrier()
        return len(self.tier) + int(vis_n_device) - self.dup_epoch

    # ------------------------------------------------------- operations

    def evict(self, occupied_keys: np.ndarray) -> int:
        """Bulk-absorb the device table's occupied key lines; the
        caller clears the device table (and its vis_n) right after.
        Returns keys newly tiered."""
        n_new = self.tier.absorb(occupied_keys)
        self.stats.spilled_keys += n_new
        self.stats.evictions += 1
        self.dup_epoch = 0
        if self.telemetry is not None:
            self.telemetry.event("spill_evict", keys=n_new,
                                 tier=len(self.tier))
        return n_new

    def refilter(self, rows: np.ndarray,
                 keys: np.ndarray) -> np.ndarray:
        """The corrected promote mask: drop rows whose key is already
        in the host tier (a re-discovery of a pre-eviction state) and
        charge the duplicate device-table insert to ``dup_epoch``.
        Returns the kept rows."""
        if not len(rows) or not len(self.tier):
            return np.asarray(rows, np.int32)
        hit = self.tier.contains(keys)
        n_hit = int(hit.sum())
        if n_hit:
            self.stats.host_tier_hits += n_hit
            self.dup_epoch += n_hit
            rows = np.asarray(rows)[~hit]
        return np.asarray(rows, np.int32)

    def spool(self, rows: np.ndarray) -> None:
        """Queue refiltered NEXT-level rows for deferred re-expansion."""
        if len(rows):
            self.stats.respilled_frontier += len(rows)
            self.spool_next.push(rows)
            if self.telemetry is not None:
                # Live-monitor feed (STATUS.json "spill" block): the
                # tier/spool sizes a watcher reads to see how deep the
                # capacity detour currently is.
                self.telemetry.event(
                    "spill_spool", rows=len(rows),
                    spool_rows=self.spool_next.rows(),
                    tier=len(self.tier))

    def pop_current(self) -> Optional[np.ndarray]:
        self.barrier()
        seg = self.spool_cur.pop()
        if seg is not None:
            self.stats.reinjections += 1
            if self.telemetry is not None:
                self.telemetry.event("spill_reinject", rows=len(seg),
                                     tier=len(self.tier))
        return seg

    def advance_level(self) -> None:
        """Level boundary: the assembled next level becomes current."""
        self.barrier()
        assert not self.spool_cur.segments, \
            "advance_level with unconsumed current-level segments"
        self.spool_cur, self.spool_next = (self.spool_next,
                                           FrontierSpool())

    # ------------------------------------------------------ checkpoints

    def checkpoint_keys(self, device_keys: np.ndarray) -> np.ndarray:
        """visited_keys for the unified dump: device ∪ tier, exact-
        deduplicated (the resumer's unique base is len(keys))."""
        self.barrier()
        parts = [np.asarray(device_keys, np.uint32).reshape(-1, 4),
                 self.tier.key_rows()]
        allk = np.concatenate(parts, axis=0)
        if not len(allk):
            return allk
        h1, h2 = _rows_to_u64(allk)
        order = np.lexsort((h2, h1))
        h1, h2 = h1[order], h2[order]
        first = np.ones(len(h1), bool)
        first[1:] = (h1[1:] != h1[:-1]) | (h2[1:] != h2[:-1])
        return _u64_to_rows(h1[first], h2[first])

    def checkpoint_extra(self) -> dict:
        return {"spill_stats": self.stats.as_array()}

    def restore(self, visited_keys: np.ndarray,
                extra: Optional[dict] = None) -> None:
        """Resume-from-dump: ALL dumped keys load into the host tier
        and the device epoch restarts empty — bit-exact by the unique
        formula (len(tier) + 0 - 0 = the dump's distinct count)."""
        self.barrier()
        self.tier = HostVisitedTier(host_cap=self.config.host_cap)
        self.spool_cur = FrontierSpool()
        self.spool_next = FrontierSpool()
        self.dup_epoch = 0
        self.tier.absorb(visited_keys)
        if extra and "spill_stats" in extra:
            self.stats = SpillStats.from_array(extra["spill_stats"])

    def attach(self, outcome) -> None:
        """Surface the accounting on a SearchOutcome (never silent)."""
        self.barrier()
        if self._worker is not None:
            self.stats.drain_wall_ms = int(
                self._worker.busy_secs * 1000)
        outcome.spilled_keys = self.stats.spilled_keys
        outcome.host_tier_hits = self.stats.host_tier_hits
        outcome.respilled_frontier = self.stats.respilled_frontier
        outcome.spill_drain_ms = self.stats.drain_wall_ms
        outcome.spill_wait_ms = self.stats.drain_wait_ms
