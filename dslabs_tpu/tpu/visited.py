"""Device-resident visited set: an open-addressing hash table in HBM.

ONE implementation of the 128-bit-key dedup table shared by both search
drivers — the sharded engine's owner-side dedup (sharded.py) and the
single-device engine's device-resident wave loop (engine.py run()).
Extracted from sharded.py so the probe/insert machinery exists exactly
once (hash compaction after Stern & Dill; the GPUexplore-style BFS table
in PAPERS.md).

Layout: ``[V + 1, 4]`` uint32 where V (a power of two) is the slot
count, viewed as ``[V/8, 8]``-slot buckets so one probe iteration reads
a whole aligned 128-byte line; the trailing row is the scatter dump for
clipped writes.  EMPTY slots are all-MAX (a real all-MAX key — the
2^-128 collider — is remapped by :func:`sanitize_keys`).  Membership and
insert happen in one bounded probe loop; claim conflicts (equal keys or
distinct keys hashing to one bucket) are serialised by a hashed
per-bucket min-index reservation, so no sort of the batch is needed.
After ~2 full-batch iterations only deep bucket chains remain; those are
compacted into a small tail so late iterations stop re-scanning the
whole batch (the measured high-load pathology in round 3).

Overflow contract (ISSUE 1): a key whose probe exhausts (table
effectively full) is **unresolved** — it is NOT inserted, and the caller
must treat it as FRESH (sound: the state may be re-explored; never a
silent drop) while surfacing the count as a visible overflow flag.
Strict drivers raise :class:`~dslabs_tpu.tpu.engine.CapacityOverflow`
on a nonzero count (exact unique counts would otherwise drift); beam
drivers report it via ``SearchOutcome.visited_overflow``.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["BKT", "MAXU32", "empty_table", "sanitize_keys",
           "host_sanitize_key", "host_home_slot", "host_occupied",
           "insert", "build_table"]

# Slots per bucket: the probe loop reads whole buckets (one aligned
# 128-byte line of 8 x 16-byte keys).
BKT = 8
MAXU32 = np.uint32(0xFFFFFFFF)


def check_cap(cap: int) -> None:
    if cap & (cap - 1) or cap < BKT:
        raise ValueError(
            f"visited cap must be a power of two >= {BKT} "
            f"(hash-table slot arithmetic), got {cap}")


def empty_table(cap: int) -> jnp.ndarray:
    """A fresh ``[cap + 1, 4]`` all-EMPTY table (+1 scatter-dump row)."""
    check_cap(cap)
    return jnp.full((cap + 1, 4), MAXU32, jnp.uint32)


def sanitize_keys(keys: jnp.ndarray, valid: jnp.ndarray) -> jnp.ndarray:
    """Remap the all-MAX key (would alias the EMPTY marker) on valid
    rows; [N, 4] uint32 -> [N, 4] uint32."""
    all_max = jnp.all(keys == MAXU32, axis=1)
    return keys.at[:, 3].set(
        jnp.where(all_max & valid, MAXU32 - 1, keys[:, 3]))


def host_sanitize_key(key: np.ndarray) -> np.ndarray:
    """Host-side :func:`sanitize_keys` for a single [4] uint32 key (carry
    initialisers place the root key without a device round-trip)."""
    key = key.copy()
    if (key == MAXU32).all():
        key[3] = np.uint32(MAXU32 - 1)
    return key


def host_home_slot(key: np.ndarray, cap: int) -> int:
    """Slot index of a [4] key's home bucket's first slot — MUST mirror
    :func:`insert`'s addressing (bucket keyed by lane 2: lane 0 is
    owner-routing-biased in the sharded engine, see sharded.py)."""
    check_cap(cap)
    return (int(key[2]) & (cap // BKT - 1)) * BKT


def host_occupied(table: np.ndarray) -> np.ndarray:
    """Occupied key lines of a HOST copy of a ``[V + 1, 4]`` table (the
    trailing scatter-dump row excluded) — the bulk-eviction readback of
    the spill tier (tpu/spill.py) and the checkpoint writers share this
    one definition of "occupied" (any lane != EMPTY's all-MAX)."""
    table = np.asarray(table)[:-1]
    occ = ~(table == MAXU32).all(axis=1)
    return table[occ]


def build_table(cap: int, keys) -> Tuple[jnp.ndarray, int, int]:
    """A fresh table with ``keys`` ([K, 4] uint32) pre-inserted — the
    HOST-SIDE rebuild/pre-seed entry point (engine.py
    ``_carry_from_ckpt``; the sharded and swarm drivers re-insert
    inside their shard_map initialisers instead, where the table must
    be built per device).  Returns ``(table, n_inserted,
    n_unresolved)``; callers treat a nonzero unresolved count as
    CapacityOverflow (the table cannot hold the key set)."""
    keys = jnp.asarray(keys, jnp.uint32).reshape(-1, 4)
    table, ins, unres = insert(empty_table(cap), keys,
                               jnp.ones((keys.shape[0],), bool))
    return (table, int(np.asarray(jnp.sum(ins))),
            int(np.asarray(jnp.sum(unres))))


def _probe_iter(table, keys, bkt_i, ps, unres, idx, V, RT, batch_n):
    """One probe iteration over any batch (idx = each row's identity for
    reservation tie-breaks; rows with unres=False are inert).  Reads each
    key's whole bucket, resolves membership across its BKT slots, and
    lets the minimum-index contender of each bucket claim the first
    empty slot; losers re-read the same bucket next iteration, full
    buckets advance by the key's double-hash step."""
    VB = V // BKT
    bkt = table[:V].reshape(VB, BKT, 4)[bkt_i]
    eq = jnp.any(jnp.all(bkt == keys[:, None, :], axis=2), axis=1)
    empty = jnp.all(bkt == MAXU32, axis=2)
    has_empty = jnp.any(empty, axis=1)
    first_empty = jnp.argmax(empty, axis=1)
    want = unres & ~eq & has_empty
    rcell = bkt_i & (RT - 1)
    res = jnp.full((RT + 1,), batch_n, jnp.int32).at[
        jnp.where(want, rcell, RT)].min(idx)
    winner = want & (res[rcell] == idx)
    dst = jnp.where(winner, bkt_i * BKT + first_empty, V)
    table = table.at[dst].set(keys)
    newly = eq | winner
    nb = (bkt_i.astype(jnp.uint32) + ps).astype(jnp.int32) & (VB - 1)
    bkt_i = jnp.where(unres & ~newly & ~has_empty, nb, bkt_i)
    return table, bkt_i, newly & unres, winner & unres


def insert(table: jnp.ndarray, keys: jnp.ndarray, valid: jnp.ndarray,
           max_iters: int = 64,
           ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Membership + insert of a key batch in one bounded probe.

    ``table`` [V+1, 4] uint32 (V a power of two; last row = scatter
    dump), ``keys`` [N, 4] uint32 (pre-:func:`sanitize_keys`-ed or raw —
    sanitisation is applied here), ``valid`` [N] bool.

    Returns ``(table', inserted, unresolved)`` where ``inserted[i]`` is
    True iff key i claimed a slot this call (exactly one copy of each
    distinct key ever wins, even with in-batch duplicates) and
    ``unresolved[i]`` is True iff the probe exhausted before key i
    resolved — the table-full overflow case.  Callers MUST treat
    unresolved keys as fresh (sound re-exploration, never a silent
    drop) and surface ``sum(unresolved)`` as a visible overflow flag.
    Pure jnp — usable under jit and inside shard_map bodies.
    """
    V = table.shape[0] - 1
    check_cap(V)
    VB = V // BKT
    n = keys.shape[0]
    skeys = sanitize_keys(keys, valid)
    slot0 = (skeys[:, 2] & jnp.uint32(VB - 1)).astype(jnp.int32)
    pstep = (skeys[:, 1] | jnp.uint32(1)).astype(jnp.uint32)
    # Reservations go through a small HASHED table (bkt_i mod RT): a
    # collision between two DISTINCT buckets just makes one contender
    # retry next iteration — a winner must still re-win its own cell.
    RT = 1 << max((n * 2 - 1).bit_length(), 10)
    # Tail threshold: once fewer than T keys remain unresolved, compact
    # them so late iterations stop re-scanning the whole batch.
    T = max(n // 8, min(256, n))
    ridx = jnp.arange(n, dtype=jnp.int32)

    def full_cond(st):
        _, _, resolved, _, it = st
        # ONE guaranteed full-batch iteration: below 50% table load the
        # first bucket read resolves all but the full-bucket collisions,
        # which fit the tail buffer.
        return ((it < 1) | (jnp.sum(~resolved) > T)) & (
            it < max_iters) & jnp.any(~resolved)

    def full_body(st):
        tbl, bkt_i, resolved, ins, it = st
        tbl, bkt_i, newly, winner = _probe_iter(
            tbl, skeys, bkt_i, pstep, ~resolved, ridx, V, RT, n)
        return tbl, bkt_i, resolved | newly, ins | winner, it + 1

    table, bkt_i, resolved, inserted, _ = jax.lax.while_loop(
        full_cond, full_body,
        (table, slot0, ~valid, jnp.zeros(n, bool), jnp.int32(0)))

    # ---- tail phase: compact the unresolved few into [T] slots.
    tail_idx = jnp.nonzero(~resolved, size=T, fill_value=n)[0]
    tclip = tail_idx.clip(0, n - 1)
    tval = tail_idx < n
    t_keys = skeys[tclip]
    t_bkt = bkt_i[tclip]
    t_ps = pstep[tclip]
    t_id = jnp.arange(T, dtype=jnp.int32)

    def tail_cond(st):
        _, _, t_unres, _, it = st
        return (it < max_iters) & jnp.any(t_unres)

    def tail_body(st):
        tbl, tb, t_unres, t_ins, it = st
        tbl, tb, newly, winner = _probe_iter(
            tbl, t_keys, tb, t_ps, t_unres, t_id, V, RT, n)
        return tbl, tb, t_unres & ~newly, t_ins | winner, it + 1

    table, _, t_unres, t_ins, _ = jax.lax.while_loop(
        tail_cond, tail_body,
        (table, t_bkt, tval, jnp.zeros(T, bool), jnp.int32(0)))
    resolved = resolved.at[tclip].max(tval & ~t_unres)
    inserted = inserted.at[tclip].max(t_ins & tval)
    return table, inserted, ~resolved
