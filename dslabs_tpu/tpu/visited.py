"""Device-resident visited set: an open-addressing hash table in HBM.

ONE implementation of the 128-bit-key dedup table shared by both search
drivers — the sharded engine's owner-side dedup (sharded.py) and the
single-device engine's device-resident wave loop (engine.py run()).
Extracted from sharded.py so the probe/insert machinery exists exactly
once (hash compaction after Stern & Dill; the GPUexplore-style BFS table
in PAPERS.md).

Layout: ``[V + 1, 4]`` uint32 where V (a power of two) is the slot
count, viewed as ``[V/8, 8]``-slot buckets so one probe iteration reads
a whole aligned 128-byte line; the trailing row is the scatter dump for
clipped writes.  EMPTY slots are all-MAX (a real all-MAX key — the
2^-128 collider — is remapped by :func:`sanitize_keys`).  Membership and
insert happen in one bounded probe loop; claim conflicts (equal keys or
distinct keys hashing to one bucket) are serialised by a hashed
per-bucket min-index reservation, so no sort of the batch is needed.
After ~2 full-batch iterations only deep bucket chains remain; those are
compacted into a small tail so late iterations stop re-scanning the
whole batch (the measured high-load pathology in round 3).

Overflow contract (ISSUE 1): a key whose probe exhausts (table
effectively full) is **unresolved** — it is NOT inserted, and the caller
must treat it as FRESH (sound: the state may be re-explored; never a
silent drop) while surfacing the count as a visible overflow flag.
Strict drivers raise :class:`~dslabs_tpu.tpu.engine.CapacityOverflow`
on a nonzero count (exact unique counts would otherwise drift); beam
drivers report it via ``SearchOutcome.visited_overflow``.

Pallas kernel (ISSUE 12): the probe/insert — the hot instruction on
every expanded state — also exists as a Pallas TPU kernel
(:func:`pallas_insert`) whose body is the SAME traced algorithm as the
jnp path (:func:`insert_jnp`), so the two are bit-identical by
construction: same probe order, same reservation tie-breaks, same
unresolved set.  :func:`insert` dispatches between them by the
``DSLABS_VISITED_PALLAS`` knob (``auto`` compiles the kernel on TPU
when the table fits the VMEM budget; ``interpret`` runs the Mosaic
interpreter — the CPU/test path; ``0`` pins the jnp oracle).  The
kernel is a canonical dispatch site (``visited.insert`` in
``telemetry.DISPATCH_SITES``) so the profiler's hot-site selection and
the jaxpr auditor cover it; :func:`dispatch_site_program` builds the
audit entry.
"""

from __future__ import annotations

import contextlib
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["BKT", "MAXU32", "empty_table", "sanitize_keys",
           "host_sanitize_key", "host_home_slot", "host_occupied",
           "insert", "insert_jnp", "pallas_insert", "pallas_mode",
           "force_jnp", "dispatch_site_program", "build_table"]

# Trace-time override depth for :func:`force_jnp` — engines that trace
# the probe loop under a batching transform (the lane engine vmaps the
# whole step body over stacked jobs, tpu/lanes.py) pin the jnp oracle
# here: ``pallas_call`` has no batching rule for this kernel, and the
# two variants are bit-identical by construction, so the override is a
# lowering choice, never a semantic one.
_FORCE_JNP = 0


@contextlib.contextmanager
def force_jnp():
    """Pin :func:`insert` to the jnp oracle for programs traced inside
    this context (nested use is fine; trace-time only — already-compiled
    programs are unaffected)."""
    global _FORCE_JNP
    _FORCE_JNP += 1
    try:
        yield
    finally:
        _FORCE_JNP -= 1

# Slots per bucket: the probe loop reads whole buckets (one aligned
# 128-byte line of 8 x 16-byte keys).
BKT = 8
MAXU32 = np.uint32(0xFFFFFFFF)


def check_cap(cap: int) -> None:
    if cap & (cap - 1) or cap < BKT:
        raise ValueError(
            f"visited cap must be a power of two >= {BKT} "
            f"(hash-table slot arithmetic), got {cap}")


def empty_table(cap: int) -> jnp.ndarray:
    """A fresh ``[cap + 1, 4]`` all-EMPTY table (+1 scatter-dump row)."""
    check_cap(cap)
    return jnp.full((cap + 1, 4), MAXU32, jnp.uint32)


def sanitize_keys(keys: jnp.ndarray, valid: jnp.ndarray) -> jnp.ndarray:
    """Remap the all-MAX key (would alias the EMPTY marker) on valid
    rows; [N, 4] uint32 -> [N, 4] uint32."""
    all_max = jnp.all(keys == MAXU32, axis=1)
    return keys.at[:, 3].set(
        jnp.where(all_max & valid, MAXU32 - 1, keys[:, 3]))


def host_sanitize_key(key: np.ndarray) -> np.ndarray:
    """Host-side :func:`sanitize_keys` for a single [4] uint32 key (carry
    initialisers place the root key without a device round-trip)."""
    key = key.copy()
    if (key == MAXU32).all():
        key[3] = np.uint32(MAXU32 - 1)
    return key


def host_home_slot(key: np.ndarray, cap: int) -> int:
    """Slot index of a [4] key's home bucket's first slot — MUST mirror
    :func:`insert`'s addressing (bucket keyed by lane 2: lane 0 is
    owner-routing-biased in the sharded engine, see sharded.py)."""
    check_cap(cap)
    return (int(key[2]) & (cap // BKT - 1)) * BKT


def host_occupied(table: np.ndarray) -> np.ndarray:
    """Occupied key lines of a HOST copy of a ``[V + 1, 4]`` table (the
    trailing scatter-dump row excluded) — the bulk-eviction readback of
    the spill tier (tpu/spill.py) and the checkpoint writers share this
    one definition of "occupied" (any lane != EMPTY's all-MAX)."""
    table = np.asarray(table)[:-1]
    occ = ~(table == MAXU32).all(axis=1)
    return table[occ]


def build_table(cap: int, keys) -> Tuple[jnp.ndarray, int, int]:
    """A fresh table with ``keys`` ([K, 4] uint32) pre-inserted — the
    HOST-SIDE rebuild/pre-seed entry point (engine.py
    ``_carry_from_ckpt``; the sharded and swarm drivers re-insert
    inside their shard_map initialisers instead, where the table must
    be built per device).  Returns ``(table, n_inserted,
    n_unresolved)``; callers treat a nonzero unresolved count as
    CapacityOverflow (the table cannot hold the key set)."""
    keys = jnp.asarray(keys, jnp.uint32).reshape(-1, 4)
    table, ins, unres = insert(empty_table(cap), keys,
                               jnp.ones((keys.shape[0],), bool))
    return (table, int(np.asarray(jnp.sum(ins))),
            int(np.asarray(jnp.sum(unres))))


def _probe_iter(table, keys, bkt_i, ps, unres, idx, V, RT, batch_n):
    """One probe iteration over any batch (idx = each row's identity for
    reservation tie-breaks; rows with unres=False are inert).  Reads each
    key's whole bucket, resolves membership across its BKT slots, and
    lets the minimum-index contender of each bucket claim the first
    empty slot; losers re-read the same bucket next iteration, full
    buckets advance by the key's double-hash step."""
    VB = V // BKT
    bkt = table[:V].reshape(VB, BKT, 4)[bkt_i]
    eq = jnp.any(jnp.all(bkt == keys[:, None, :], axis=2), axis=1)
    empty = jnp.all(bkt == MAXU32, axis=2)
    has_empty = jnp.any(empty, axis=1)
    first_empty = jnp.argmax(empty, axis=1)
    want = unres & ~eq & has_empty
    rcell = bkt_i & (RT - 1)
    res = jnp.full((RT + 1,), batch_n, jnp.int32).at[
        jnp.where(want, rcell, RT)].min(idx)
    winner = want & (res[rcell] == idx)
    dst = jnp.where(winner, bkt_i * BKT + first_empty, V)
    table = table.at[dst].set(keys)
    newly = eq | winner
    nb = (bkt_i.astype(jnp.uint32) + ps).astype(jnp.int32) & (VB - 1)
    bkt_i = jnp.where(unres & ~newly & ~has_empty, nb, bkt_i)
    return table, bkt_i, newly & unres, winner & unres


def insert_jnp(table: jnp.ndarray, keys: jnp.ndarray, valid: jnp.ndarray,
               max_iters: int = 64,
               ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Membership + insert of a key batch in one bounded probe — the
    pure-jnp reference implementation (the Pallas kernel's parity
    oracle AND the CPU/interpret fallback; :func:`insert` dispatches).

    ``table`` [V+1, 4] uint32 (V a power of two; last row = scatter
    dump), ``keys`` [N, 4] uint32 (pre-:func:`sanitize_keys`-ed or raw —
    sanitisation is applied here), ``valid`` [N] bool.

    Returns ``(table', inserted, unresolved)`` where ``inserted[i]`` is
    True iff key i claimed a slot this call (exactly one copy of each
    distinct key ever wins, even with in-batch duplicates) and
    ``unresolved[i]`` is True iff the probe exhausted before key i
    resolved — the table-full overflow case.  Callers MUST treat
    unresolved keys as fresh (sound re-exploration, never a silent
    drop) and surface ``sum(unresolved)`` as a visible overflow flag.
    Pure jnp — usable under jit, inside shard_map bodies, and inside
    the Pallas kernel body.
    """
    V = table.shape[0] - 1
    check_cap(V)
    VB = V // BKT
    n = keys.shape[0]
    skeys = sanitize_keys(keys, valid)
    slot0 = (skeys[:, 2] & jnp.uint32(VB - 1)).astype(jnp.int32)
    pstep = (skeys[:, 1] | jnp.uint32(1)).astype(jnp.uint32)
    # Reservations go through a small HASHED table (bkt_i mod RT): a
    # collision between two DISTINCT buckets just makes one contender
    # retry next iteration — a winner must still re-win its own cell.
    RT = 1 << max((n * 2 - 1).bit_length(), 10)
    # Tail threshold: once fewer than T keys remain unresolved, compact
    # them so late iterations stop re-scanning the whole batch.
    T = max(n // 8, min(256, n))
    ridx = jnp.arange(n, dtype=jnp.int32)

    def full_cond(st):
        _, _, resolved, _, it = st
        # ONE guaranteed full-batch iteration: below 50% table load the
        # first bucket read resolves all but the full-bucket collisions,
        # which fit the tail buffer.
        return ((it < 1) | (jnp.sum(~resolved) > T)) & (
            it < max_iters) & jnp.any(~resolved)

    def full_body(st):
        tbl, bkt_i, resolved, ins, it = st
        tbl, bkt_i, newly, winner = _probe_iter(
            tbl, skeys, bkt_i, pstep, ~resolved, ridx, V, RT, n)
        return tbl, bkt_i, resolved | newly, ins | winner, it + 1

    table, bkt_i, resolved, inserted, _ = jax.lax.while_loop(
        full_cond, full_body,
        (table, slot0, ~valid, jnp.zeros(n, bool), jnp.int32(0)))

    # ---- tail phase: compact the unresolved few into [T] slots.
    tail_idx = jnp.nonzero(~resolved, size=T, fill_value=n)[0]
    tclip = tail_idx.clip(0, n - 1)
    tval = tail_idx < n
    t_keys = skeys[tclip]
    t_bkt = bkt_i[tclip]
    t_ps = pstep[tclip]
    t_id = jnp.arange(T, dtype=jnp.int32)

    def tail_cond(st):
        _, _, t_unres, _, it = st
        return (it < max_iters) & jnp.any(t_unres)

    def tail_body(st):
        tbl, tb, t_unres, t_ins, it = st
        tbl, tb, newly, winner = _probe_iter(
            tbl, t_keys, tb, t_ps, t_unres, t_id, V, RT, n)
        return tbl, tb, t_unres & ~newly, t_ins | winner, it + 1

    table, _, t_unres, t_ins, _ = jax.lax.while_loop(
        tail_cond, tail_body,
        (table, t_bkt, tval, jnp.zeros(T, bool), jnp.int32(0)))
    resolved = resolved.at[tclip].max(tval & ~t_unres)
    inserted = inserted.at[tclip].max(t_ins & tval)
    return table, inserted, ~resolved


# ------------------------------------------------- Pallas bucket kernel
#
# ISSUE 12 leg (c): the probe/insert as a Pallas TPU kernel.  The body
# runs the SAME traced algorithm as insert_jnp over the table resident
# in VMEM (one load, the whole bounded probe on-chip, one aliased
# store), so jnp-vs-Pallas parity is bit-exact by construction and the
# jnp path stays the oracle.  Compiled Mosaic only makes sense when the
# table fits the VMEM budget; bigger tables and non-TPU backends keep
# the jnp path (interpret mode exists for parity tests and debugging).

def pallas_mode() -> str:
    """Resolved DSLABS_VISITED_PALLAS knob: ``off`` (jnp oracle) |
    ``on`` (compiled on TPU, interpreter elsewhere) | ``interpret``
    (force the Mosaic interpreter — the CPU parity/test path) |
    ``auto`` (default: compiled on TPU when the table fits the VMEM
    budget, jnp everywhere else)."""
    v = os.environ.get("DSLABS_VISITED_PALLAS", "auto").strip().lower()
    if v in ("0", "off", "false", "no", ""):
        return "off"
    if v == "interpret":
        return "interpret"
    if v in ("1", "on", "true", "yes", "pallas"):
        return "on"
    return "auto"


def _pallas_vmem_budget() -> int:
    """Table-bytes ceiling for the compiled kernel (the table must sit
    in VMEM beside the key batch); ~half a v5e core's 16 MB."""
    try:
        return int(os.environ.get("DSLABS_VISITED_PALLAS_VMEM", "")
                   or (8 << 20))
    except ValueError:
        return 8 << 20


def _pallas_interpret(table_bytes: int) -> Optional[bool]:
    """None = use the jnp path; True/False = pallas_call's interpret
    flag.  Decided at TRACE time (env + backend are trace-stable, so
    rebuilt programs lower identically — the J5 retrace contract)."""
    mode = pallas_mode()
    if mode == "off":
        return None
    if mode == "interpret":
        return True
    on_tpu = jax.default_backend() == "tpu"
    fits = table_bytes <= _pallas_vmem_budget()
    if mode == "on":
        if not on_tpu:
            return True          # no Mosaic backend: interpreter
        return False if fits else None   # over-VMEM tables: jnp path
    # auto: the compiled kernel only where it is actually the win.
    if on_tpu and fits:
        return False
    return None


def pallas_insert(table: jnp.ndarray, keys: jnp.ndarray,
                  valid: jnp.ndarray, max_iters: int = 64,
                  interpret: Optional[bool] = None,
                  ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """:func:`insert_jnp` as one Pallas kernel: table + key batch load
    into VMEM, the bounded probe runs on-chip, and the table writes
    back through an input/output alias (the in-place update the
    engines' donated carries rely on).  Same signature and bit-exact
    results as the jnp path; ``interpret=True`` runs the Mosaic
    interpreter (the CPU parity path — no TPU hardware needed)."""
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n = keys.shape[0]

    def kernel(table_ref, keys_ref, valid_ref, out_table_ref,
               ins_ref, unres_ref):
        tbl, ins, unres = insert_jnp(
            table_ref[...], keys_ref[...], valid_ref[...] != 0,
            max_iters)
        out_table_ref[...] = tbl
        ins_ref[...] = ins.astype(jnp.int32)
        unres_ref[...] = unres.astype(jnp.int32)

    kwargs = {}
    if not interpret:
        # Compiled Mosaic: pin everything to VMEM (the default ANY can
        # land the table in slow HBM) and let in-batch claim conflicts
        # serialise exactly as traced.
        from jax.experimental.pallas import tpu as pltpu

        vmem = pl.BlockSpec(memory_space=pltpu.VMEM)
        kwargs = dict(in_specs=[vmem, vmem, vmem],
                      out_specs=(vmem, vmem, vmem))
    table2, ins, unres = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct(table.shape, table.dtype),
                   jax.ShapeDtypeStruct((n,), jnp.int32),
                   jax.ShapeDtypeStruct((n,), jnp.int32)),
        input_output_aliases={0: 0},
        interpret=bool(interpret), **kwargs)(
            table, keys, valid.astype(jnp.int32))
    return table2, ins != 0, unres != 0


def insert(table: jnp.ndarray, keys: jnp.ndarray, valid: jnp.ndarray,
           max_iters: int = 64,
           ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """THE probe/insert entry point both engines trace: dispatches to
    the Pallas kernel per :func:`pallas_mode` (compiled on TPU when the
    table fits VMEM; interpreter when forced) with :func:`insert_jnp`
    as the everywhere-else fallback and parity oracle.  Contract and
    return values are identical across paths (see ``insert_jnp``)."""
    if _FORCE_JNP:
        return insert_jnp(table, keys, valid, max_iters)
    interp = _pallas_interpret(int(table.shape[0]) * 16)
    if interp is None:
        return insert_jnp(table, keys, valid, max_iters)
    return pallas_insert(table, keys, valid, max_iters,
                         interpret=interp)


def dispatch_site_program(cap: int, batch: int):
    """The ``visited.insert`` audit-site entry (ISSUE 12): the ACTIVE
    probe/insert variant as a standalone jitted program over abstract
    args, shaped like one owner-side dedup call — what the jaxpr
    auditor lowers (J1/J2/J4: no callbacks, no f64, no collectives in
    the single-device kernel) and the profiler's hot-site table counts
    via ``telemetry.DISPATCH_SITES``."""
    check_cap(cap)
    args = (jax.ShapeDtypeStruct((cap + 1, 4), jnp.uint32),
            jax.ShapeDtypeStruct((batch, 4), jnp.uint32),
            jax.ShapeDtypeStruct((batch,), jnp.bool_))

    def build():
        return jax.jit(lambda t, k, v: insert(t, k, v),
                       donate_argnums=0)

    return dict(fn=build(), args=args, donate=(0,), multi=False,
                builder=build)
