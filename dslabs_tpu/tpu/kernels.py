"""Pallas TPU kernels for the tensor-search engine.

The one genuinely kernel-shaped op in the BFS pipeline is the full-state
fingerprint: a [B, L] int32 -> [B, 4] uint32 blocked reduction (L ~ 1300
lanes for the lab3 bench protocol).  The Pallas version tiles rows into
VMEM blocks and reuses the ENGINE's own mixing math on each block, so its
output is bit-identical to the jnp reference path by construction
(SURVEY §2.10 "state fingerprinting as a Pallas hash kernel").

Row tiles are processed by a 1-D grid; the full lane width rides in one
VMEM block (a [128, 1354] int32 block is ~0.7 MB — comfortably inside
VMEM).  ``mode="interpret"`` runs the kernel through the Pallas
interpreter for CPU testing.

MEASURED OUTCOME (v5e, round 2): in the engine's expand program the
Pallas kernel is bit-identical but ~2x SLOWER end-to-end than the jnp
path — the pallas_call boundary forces the [B, ~1300-lane] flattened
state to materialise in HBM, where XLA otherwise fuses the hashing into
the successor computation and never writes the preimage out.  The engine
therefore defaults to the fused jnp path; the kernel remains available
(``mode="pallas"`` / env DSLABS_PALLAS_FP=1) for workloads whose
fingerprint input is already materialised.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["fingerprint_rows"]

TILE = 128

# DSLABS_PALLAS_FP opt-in, resolved once: fingerprint_rows is traced
# inside the engine's hottest jitted programs (the expand pipeline and
# the device-resident dedup loop's carry initialiser — the fingerprints
# it emits feed dslabs_tpu/tpu/visited.py's hash table directly on
# device), and the mode decision must be stable across retraces.
_PALLAS_OPT_IN: bool = None


def _pallas_opt_in() -> bool:
    global _PALLAS_OPT_IN
    if _PALLAS_OPT_IN is None:
        import os

        _PALLAS_OPT_IN = os.environ.get(
            "DSLABS_PALLAS_FP", "").lower() in ("1", "true", "yes")
    return _PALLAS_OPT_IN


def _kernel(in_ref, out_ref):
    # The engine's mixing math (single source of truth: _fingerprint32),
    # with one Mosaic accommodation: reductions over unsigned ints are
    # unsupported, so the lane sums run on an int32 bitcast view —
    # two's-complement wrapping addition is bit-identical to uint32
    # wrapping addition, so the output matches engine.row_fingerprints
    # exactly.
    from dslabs_tpu.tpu.engine import _fingerprint32

    flat = in_ref[:]

    def u32sum(x):
        s = jnp.sum(jax.lax.bitcast_convert_type(x, jnp.int32), axis=1,
                    dtype=jnp.int32)
        return jax.lax.bitcast_convert_type(s, jnp.uint32)

    a_hi, a_lo = _fingerprint32(flat, 1, sum_fn=u32sum)
    b_hi, b_lo = _fingerprint32(flat, 2, sum_fn=u32sum)
    out_ref[:] = jnp.stack([a_hi, a_lo, b_hi, b_lo], axis=1)


def _pallas_call(flat: jnp.ndarray, interpret: bool) -> jnp.ndarray:
    b, l = flat.shape
    return pl.pallas_call(
        _kernel,
        grid=(b // TILE,),
        in_specs=[pl.BlockSpec((TILE, l), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((TILE, 4), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((b, 4), jnp.uint32),
        interpret=interpret,
    )(flat)


def fingerprint_rows(flat: jnp.ndarray, mode: str = "auto") -> jnp.ndarray:
    """[B, L] int32 rows -> [B, 4] uint32 128-bit fingerprints.

    mode: "auto" (fused jnp unless DSLABS_PALLAS_FP=1 on TPU — see the
    module docstring for the measurement behind the default), "jnp",
    "pallas", or "interpret" (Pallas interpreter — CPU parity tests)."""
    from dslabs_tpu.tpu.engine import row_fingerprints

    b = flat.shape[0]
    if mode == "auto":
        on_tpu = jax.default_backend() == "tpu"
        mode = "pallas" if on_tpu and _pallas_opt_in() else "jnp"
    if mode == "jnp":
        return row_fingerprints(flat)
    pad = (-b) % TILE
    if pad:
        flat = jnp.concatenate(
            [flat, jnp.zeros((pad, flat.shape[1]), flat.dtype)])
    return _pallas_call(flat, interpret=(mode == "interpret"))[:b]
