"""Seeded chaos harness: deterministic multi-site fault schedules + soak.

The supervisor's recovery paths (tpu/supervisor.py) are each proven by a
hand-placed :class:`~dslabs_tpu.tpu.supervisor.FaultPlan` rule; what none
of those tests prove is the COMPOSITION — a long run absorbing transient
storms, OOM re-levels, wedges, and fatal rung burns all in one search
and still landing the exact fault-free verdict.  That is the contract a
checking SERVICE sells (ROADMAP #2: a long-lived multi-tenant process
must degrade by one chip, not by a whole mesh), and this module makes it
a one-call CI assertion:

* **ChaosSpec / build_plan** — a seeded random schedule of faults over
  the dispatch sites of a real run: site x kind x dispatch-index, drawn
  from a :class:`random.Random(seed)` so every soak is bit-reproducible.
  Kinds map onto the supervisor's failure taxonomy:

  - ``transient``  retryable raise (TransientDeviceError) — absorbed by
    in-place backoff retry;
  - ``oom``        :class:`ChaosOOM` (a MemoryError) — classified
    OOM-like, answered by the adaptive knob-shrink re-level;
  - ``fatal``      :class:`ChaosError` — burns the rung, the elastic
    ladder rebuilds a smaller mesh from the checkpoint
    (``mesh_shrunk``);
  - ``hang``       an injected wedge — the watchdog abandons the
    dispatch and the ladder fails over.

  Transient/oom/fatal faults are scheduled as BURSTS of consecutive
  site-local dispatch indices anchored near the start of each site's
  stream: a raise consumes its index and the retry occupies the next,
  so every scheduled fault is GUARANTEED to fire on any run that
  reaches the anchor — no dead rules, and the soak can assert its
  injection count exactly.

* **soak()** — run the fault-free baseline (which also measures each
  site's dispatch budget), build the plan from those budgets, run the
  SAME search under sustained injection on the elastic ladder with
  per-level checkpoints, and assert exact verdict/unique/explored
  parity plus ``dropped_states == 0``.  Returns an attributable report
  (fired count, per-site coverage, mesh_shrinks / knob_retries /
  failovers / retries absorbed).

CLI: ``python -m dslabs_tpu.tpu.chaos --protocol lab1 --seed 3`` prints
the soak report as one JSON line (``make chaos-smoke`` runs the pytest
suite; the CLI is the by-hand entry point).
"""

from __future__ import annotations

import dataclasses
import os
import random
import tempfile
import time
from typing import Dict, List, Optional, Tuple

from dslabs_tpu.tpu.supervisor import (FaultPlan, RetryPolicy,
                                       SearchSupervisor,
                                       TransientDeviceError)

__all__ = ["ChaosError", "ChaosOOM", "ChaosSpec", "ChaosPlan",
           "build_plan", "chaos_policy", "soak", "DEFAULT_SITES"]


class ChaosError(RuntimeError):
    """An injected NON-transient fault: classified fatal, burns the
    rung — the elastic ladder's mesh_shrunk path."""


class ChaosOOM(MemoryError):
    """An injected OOM-shaped fault (a MemoryError, no transient
    marker): classified fatal + OOM-like, answered by the supervisor's
    in-place knob-shrink re-level."""


# The first rung's dispatch sites (the superstep driver's vocabulary):
# one one-shot site (init) + the two per-level sites.  Chaos targets
# the FIRST rung's engine name — the elastic ladder keeps the name
# "sharded" for every width, so injection persists across shrinks.
DEFAULT_SITES = (("sharded", "init"), ("sharded", "superstep"),
                 ("sharded", "promote"))


@dataclasses.dataclass(frozen=True)
class ChaosSpec:
    """A deterministic chaos schedule's knobs.  ``faults`` is the TOTAL
    injection count; ``oom/fatal/hang`` carve special kinds out of it
    (the remainder is transient).  Keep ``fatal_faults + hang_faults``
    at least two below the rung count — each burns a rung, and the
    soak's parity assertion needs a surviving rung to land on."""

    seed: int = 0
    faults: int = 24
    oom_faults: int = 2
    fatal_faults: int = 1
    hang_faults: int = 1
    sites: tuple = DEFAULT_SITES
    hang_secs: float = 3600.0
    burst: int = 4                  # max consecutive faults per burst


class ChaosPlan(FaultPlan):
    """A FaultPlan generated from a seed.  ``chaos = True`` tags the
    boundary's injection events ``chaos_inject`` on the flight log;
    ``schedule`` keeps the full (engine, site, index, kind) list for
    the report."""

    chaos = True

    def __init__(self, spec: ChaosSpec,
                 schedule: List[Tuple[str, str, int, str]]):
        super().__init__()
        self.spec = spec
        self.schedule = schedule

    def sites_fired(self):
        return {(e, s) for (e, s, _k, _i) in self.fired_log}


def build_plan(spec: ChaosSpec,
               site_counts: Dict[tuple, int]) -> ChaosPlan:
    """Generate the seeded schedule over the observed dispatch budgets
    (``site_counts``: the fault-free run's per-(engine, site) dispatch
    counts, e.g. ``supervisor.boundary.site_counts``).  One-shot sites
    (a single dispatch per rung, like ``init``) get a transient at
    index 0; multi-dispatch sites get bursts of consecutive indices
    anchored within the first few real dispatches."""
    rng = random.Random(spec.seed)
    sites = [(e, s, int(site_counts.get((e, s), 0)))
             for (e, s) in spec.sites]
    one_shot = [(e, s) for e, s, n in sites if n == 1]
    multi = [(e, s, n) for e, s, n in sites if n > 1]
    if not multi:
        raise ValueError(
            "chaos needs at least one multi-dispatch site; observed "
            f"counts: {dict(site_counts)}")

    schedule: List[Tuple[str, str, int, str]] = []
    for e, s in one_shot:
        schedule.append((e, s, 0, "transient"))

    remaining = max(0, spec.faults - len(schedule))
    specials = (["oom"] * min(spec.oom_faults, remaining)
                + ["fatal"] * min(spec.fatal_faults, remaining))
    hangs = ["hang"] * min(spec.hang_faults, remaining)
    n_transient = max(0, remaining - len(specials) - len(hangs))
    kinds = ["transient"] * n_transient + specials
    rng.shuffle(kinds)

    # Round-robin the kinds over the multi sites; hangs pin to the
    # lowest-deadline-scale site (promote — a superstep hang waits the
    # trip-count-stretched deadline, a promote hang only the base one)
    # and go FIRST there, so the wedge lands while plenty of run
    # remains for the faults scheduled behind it.
    per_site: Dict[int, List[str]] = {i: [] for i in range(len(multi))}
    for j, kind in enumerate(kinds):
        per_site[j % len(multi)].append(kind)
    hang_site = next((i for i, (_e, s, _n) in enumerate(multi)
                      if s == "promote"), 0)
    per_site[hang_site] = hangs + per_site[hang_site]

    for i, (e, s, _n) in enumerate(multi):
        ks = per_site[i]
        if not ks:
            continue
        # Firing guarantee: a raise consumes its index and the retry
        # occupies the next, so a CONSECUTIVE burst fires end-to-end
        # once its anchor is reached — only the anchor and the
        # one-dispatch gaps between bursts consume REAL dispatches.
        # The burst length scales with the site's load so a heavy
        # schedule never needs more real dispatches than a short run
        # has (the seed-13 lesson: fixed short bursts + wide gaps
        # outran a depth-5 space).
        burst_len = max(spec.burst, -(-len(ks) // 3))
        idx = rng.randint(1, 2)
        burst = 0
        for kind in ks:
            schedule.append((e, s, idx, kind))
            idx += 1
            burst += 1
            if burst >= burst_len:
                burst = 0
                idx += 1                   # one real dispatch between

    plan = ChaosPlan(spec, schedule)
    for e, s, idx, kind in schedule:
        if kind == "transient":
            plan.raise_at(idx, engine=e, site=s,
                          error=TransientDeviceError,
                          message="chaos transient")
        elif kind == "oom":
            plan.raise_at(idx, engine=e, site=s, error=ChaosOOM,
                          message="chaos injected allocation failure")
        elif kind == "fatal":
            plan.raise_at(idx, engine=e, site=s, error=ChaosError,
                          message="chaos fatal")
        else:
            plan.hang_at(idx, engine=e, site=s, secs=spec.hang_secs)
    return plan


def chaos_policy(spec: ChaosSpec,
                 deadline_secs: Optional[float] = None) -> RetryPolicy:
    """The soak's retry policy: a budget big enough that transient
    bursts never starve a rung (the soak measures recovery, not budget
    arithmetic), near-zero backoff, and a watchdog so injected hangs
    cost seconds.  The first-dispatch grace stays compile-sized."""
    if deadline_secs is None:
        deadline_secs = float(
            os.environ.get("DSLABS_CHAOS_DEADLINE", "12") or "12")
    return RetryPolicy(max_retries=spec.faults + 8,
                       backoff_base=0.005, backoff_factor=1.5,
                       backoff_max=0.05,
                       deadline_secs=deadline_secs,
                       deadline_first_secs=900.0, seed=spec.seed)


def soak(protocol, spec: Optional[ChaosSpec] = None,
         supervisor_kwargs: Optional[dict] = None,
         checkpoint_path: Optional[str] = None,
         telemetry=None, min_fired: int = 0, min_sites: int = 0) -> dict:
    """Run a strict search under sustained seeded injection and assert
    exact parity against the fault-free run.

    1. the fault-free BASELINE runs first (same supervisor config, no
       plan) — its verdict/counts are the oracle AND its per-site
       dispatch counts are the budgets the plan is drawn from;
    2. the CHAOS run executes with the seeded plan on the elastic
       ladder, checkpointing every level so burned rungs resume;
    3. parity (verdict / unique / explored), ``dropped_states == 0``,
       and the requested injection/site coverage are ASSERTED — a soak
       that silently under-injects is a failed soak.

    Returns the report dict (also what the CLI prints)."""
    spec = spec or ChaosSpec()
    kw = dict(supervisor_kwargs or {})
    kw.setdefault("strict", True)
    kw.setdefault("elastic", True)

    base_sup = SearchSupervisor(protocol, **kw)
    base = base_sup.run()
    site_counts = dict(base_sup.boundary.site_counts)
    plan = build_plan(spec, site_counts)

    if checkpoint_path is None:
        checkpoint_path = os.path.join(
            tempfile.mkdtemp(prefix="dslabs-chaos-"), "soak.ckpt")
    kw2 = dict(kw)
    kw2.setdefault("checkpoint_every", 1)
    kw2["checkpoint_path"] = checkpoint_path
    kw2.setdefault("policy", chaos_policy(spec))
    sup = SearchSupervisor(protocol, fault_plan=plan,
                           telemetry=telemetry, **kw2)
    t0 = time.time()
    out = sup.run()

    fired_sites = sorted(f"{e}.{s}" for e, s in plan.sites_fired())
    parity = (out.end_condition == base.end_condition
              and out.unique_states == base.unique_states
              and out.states_explored == base.states_explored)
    report = {
        "seed": spec.seed,
        "scheduled": len(plan.schedule),
        "fired": plan.fired,
        "sites_fired": fired_sites,
        "kinds_fired": sorted({k for (_e, _s, k, _i)
                               in plan.fired_log}),
        "parity": bool(parity),
        "verdict": out.end_condition,
        "base": {"verdict": base.end_condition,
                 "unique": base.unique_states,
                 "explored": base.states_explored,
                 "depth": base.depth},
        "chaos": {"unique": out.unique_states,
                  "explored": out.states_explored,
                  "depth": out.depth,
                  "engine": out.engine,
                  "mesh_width": out.mesh_width,
                  "mesh_shrinks": out.mesh_shrinks,
                  "knob_retries": out.knob_retries,
                  "failovers": out.failovers,
                  "retries": out.retries,
                  "resumed_from_depth": out.resumed_from_depth,
                  "dropped_states": out.dropped_states},
        "wall_secs": round(time.time() - t0, 2),
        "checkpoint": checkpoint_path,
    }
    if plan.fired < min_fired:
        raise AssertionError(
            f"chaos soak under-injected: {plan.fired} faults fired "
            f"(wanted >= {min_fired}); report: {report}")
    if len(fired_sites) < min_sites:
        raise AssertionError(
            f"chaos soak covered {len(fired_sites)} sites "
            f"({fired_sites}), wanted >= {min_sites}; report: {report}")
    if not parity:
        raise AssertionError(
            f"chaos soak broke parity: {report}")
    if out.dropped_states:
        raise AssertionError(
            f"chaos soak dropped {out.dropped_states} states: {report}")
    return report


# ------------------------------------------------------------------ CLI

def _protocol(name: str):
    import dataclasses as _dc

    if name == "pingpong":
        from dslabs_tpu.tpu.protocols.pingpong import \
            make_pingpong_protocol

        p = make_pingpong_protocol(2)
    elif name == "lab1":
        from dslabs_tpu.tpu.protocols.clientserver import \
            make_clientserver_protocol

        p = make_clientserver_protocol(n_clients=1, w=2)
    elif name == "paxos-partition":
        # Scenario-protocol leg (ISSUE 19): a job whose MODEL carries
        # fault events (partition cut/heal lanes) soaked under the
        # supervisor's own orthogonal fault injection — the scenario's
        # search-level faults and the infrastructure's chaos faults
        # compose without disturbing the verdict.
        from dslabs_tpu.tpu.specs import paxos_partition_spec

        p = paxos_partition_spec().compile()
        return _dc.replace(p, goals={},
                           prunes={"DECIDED": p.goals["DECIDED"]})
    else:
        raise SystemExit(f"unknown --protocol {name!r} "
                         "(pingpong | lab1 | paxos-partition)")
    # Exhaustive shape: the goal becomes a prune so the soak measures
    # full-space parity, not a first-goal race.
    return _dc.replace(p, goals={},
                       prunes={"CLIENTS_DONE": p.goals["CLIENTS_DONE"]})


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import json
    import sys

    ap = argparse.ArgumentParser(
        prog="python -m dslabs_tpu.tpu.chaos",
        description="seeded chaos soak: strict search under sustained "
                    "fault injection, exact parity asserted")
    ap.add_argument("--protocol", default="lab1",
                    choices=("pingpong", "lab1", "paxos-partition"))
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--faults", type=int, default=24)
    ap.add_argument("--mesh", type=int, default=None,
                    help="mesh width (default: all devices)")
    args = ap.parse_args(argv)

    from dslabs_tpu.tpu.sharded import make_mesh

    kw = {"chunk": 64, "frontier_cap": 1 << 9, "visited_cap": 1 << 12}
    if args.mesh:
        kw["mesh"] = make_mesh(args.mesh)
    report = soak(_protocol(args.protocol),
                  spec=ChaosSpec(seed=args.seed, faults=args.faults),
                  supervisor_kwargs=kw,
                  min_fired=min(args.faults, 20), min_sites=3)
    print(json.dumps(report))
    return 0 if report["parity"] else 1


if __name__ == "__main__":
    import sys

    sys.exit(main())
