"""Node addresses.

Re-design of the reference's Address hierarchy (framework/src/dslabs/framework/
Address.java:41-104): an opaque, totally-ordered, immutable identifier.  Tests
use string-named LocalAddress; node hierarchies (lab4 sub-nodes) use SubAddress
printed ``parent/id``.
"""

from __future__ import annotations

import functools
from typing import Optional

from dslabs_tpu.utils.structural import ImmutableMarker

__all__ = ["Address", "LocalAddress", "SubAddress", "sub_address", "root_address"]


@functools.total_ordering
class Address(ImmutableMarker):
    """Base address.  Compares by string representation, like the reference's
    ``compareTo`` over ``toString`` ordering (Address.java:47-56)."""

    __slots__ = ()

    def root_address(self) -> "Address":
        return self

    def __lt__(self, other: "Address") -> bool:
        return str(self) < str(other)

    def __eq__(self, other) -> bool:
        return isinstance(other, Address) and str(self) == str(other)

    def __hash__(self) -> int:
        return hash(str(self))

    def __deepcopy__(self, memo):
        return self  # immutable

    def __sfreeze__(self):
        # Canonical frozen form for structural hashing: the printed name is
        # the identity (equality/ordering are string-based above).
        return str(self)

    def __repr__(self) -> str:
        return str(self)


class LocalAddress(Address):
    """String-named address used by tests (testing/LocalAddress.java:33-54)."""

    __slots__ = ("_name",)

    def __init__(self, name: str):
        self._name = name

    def __str__(self) -> str:
        return self._name

    # pickle support despite __slots__
    def __getstate__(self):
        return self._name

    def __setstate__(self, state):
        self._name = state


class SubAddress(Address):
    """Address of a sub-node: ``parent/id`` (Address.java:60-104)."""

    __slots__ = ("_parent", "_id")

    def __init__(self, parent: Address, sub_id: str):
        self._parent = parent
        self._id = sub_id

    @property
    def parent(self) -> Address:
        return self._parent

    @property
    def sub_id(self) -> str:
        return self._id

    def root_address(self) -> Address:
        return self._parent.root_address()

    def __str__(self) -> str:
        return f"{self._parent}/{self._id}"

    def __getstate__(self):
        return (self._parent, self._id)

    def __setstate__(self, state):
        self._parent, self._id = state


def sub_address(parent: Address, sub_id: str) -> SubAddress:
    return SubAddress(parent, sub_id)


def root_address(address: Optional[Address]) -> Optional[Address]:
    return None if address is None else address.root_address()
