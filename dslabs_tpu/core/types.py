"""Marker types of the public protocol API.

Mirrors framework/src/dslabs/framework/{Message,Timer,Command,Result,
Application,Client}.java.  Messages/timers/commands/results are plain data;
protocol code typically declares them as frozen dataclasses.
"""

from __future__ import annotations

import abc
from typing import Optional

__all__ = ["Message", "Timer", "Command", "Result", "Application", "Client"]


class Message:
    """Marker base for protocol messages (Message.java:34)."""
    __slots__ = ()


class Timer:
    """Marker base for protocol timers (Timer.java:37)."""
    __slots__ = ()


class Command:
    """Marker base for application commands (Command.java:28-35)."""
    __slots__ = ()

    def read_only(self) -> bool:
        """Commands default to read-write; read-only commands may skip
        replication (used by lab3/lab4)."""
        return False


class Result:
    """Marker base for application results (Result.java:28)."""
    __slots__ = ()


class Application(abc.ABC):
    """A deterministic state machine (Application.java:33-42).

    ``execute`` must be a pure function of (state, command): same command on
    equal states yields equal results and equal successor states.
    """

    @abc.abstractmethod
    def execute(self, command: Command) -> Result:
        ...


class Client(abc.ABC):
    """Interface implemented by client *nodes* (Client.java:41-71).

    Contract: ``send_command`` and ``has_result`` are non-blocking;
    ``get_result`` blocks until the result of the most recently sent command is
    available (real-time runner only — the model checker drives clients through
    the non-blocking half).
    """

    @abc.abstractmethod
    def send_command(self, command: Command) -> None:
        ...

    @abc.abstractmethod
    def has_result(self) -> bool:
        ...

    @abc.abstractmethod
    def get_result(self, timeout: Optional[float] = None) -> Result:
        ...
