"""Helper mixin giving client nodes a blocking ``get_result``.

The reference Client contract (Client.java:41-71) requires ``getResult`` to
block until the most recent command's result arrives (releasing monitors while
waiting).  Protocol client nodes mix this in and call ``_notify_result()``
from the handler that records a result; the search engines only ever use the
non-blocking half (``has_result`` + immediate ``get_result``).
"""

from __future__ import annotations

import threading
import time
from typing import Optional

__all__ = ["SyncClientMixin"]

# Guards lazy Condition creation: a waiter and a notifier racing through
# _result_cond must agree on a single Condition object.
_COND_CREATE_LOCK = threading.Lock()


class SyncClientMixin:

    # The condition is runtime wiring: excluded from equality (underscore) and
    # from cloning/pickling (it is not copyable and a clone gets a fresh one).
    __deepcopy_skip__ = ("_config", "_client_sync")

    def _result_cond(self) -> threading.Condition:
        cond = getattr(self, "_client_sync", None)
        if cond is None:
            with _COND_CREATE_LOCK:
                cond = getattr(self, "_client_sync", None)
                if cond is None:
                    cond = threading.Condition()
                    self._client_sync = cond
        return cond

    def _notify_result(self) -> None:
        cond = self._result_cond()
        with cond:
            cond.notify_all()

    def __getstate__(self):
        d = dict(self.__dict__)
        d.pop("_client_sync", None)
        d["_config"] = None
        return d

    def __setstate__(self, state):
        self.__dict__.update(state)

    def get_result(self, timeout: Optional[float] = None):
        """Block until ``has_result()``; subclasses implement
        ``_take_result()`` to consume and return the pending result."""
        cond = self._result_cond()
        deadline = None if timeout is None else time.monotonic() + timeout
        with cond:
            while not self.has_result():
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    raise TimeoutError("Timed out waiting for result")
                cond.wait(remaining)
            return self._take_result()
