"""The Node base class — the core of the public protocol API.

Re-design of framework/src/dslabs/framework/Node.java:106-602 for Python:

  * Handlers are resolved **by method name from the message/timer class name**:
    a message of class ``Foo`` is delivered to ``handle_Foo(message, sender)``;
    a timer of class ``Bar`` fires ``on_Bar(timer)`` (reference: reflective
    lookup of ``handleFoo``/``onBar``, Node.java:372-373, 449-450).  Lookup is
    cached per (class, name).
  * ``send``/``broadcast``/``set_timer`` go through configured hooks wired in
    by the execution engine (``config``, Node.java:582-601); sub-nodes route
    through their parent (Node.java:264-268, 307-310, 335-339).
  * Sub-node hierarchy via ``add_sub_node`` (Node.java:149-171); delivery to a
    ``SubAddress`` walks the path from the root node (Node.java:484-503).
  * Local immediate delivery between nodes of one hierarchy:
    ``handle_message_local`` (no cloning, exceptions propagate —
    Node.java:391-427).

Contract for protocol authors (Node.java:50-101): handlers are sequential,
deterministic, non-blocking; node state must be structurally comparable and
deep-clonable — inherited here from :class:`StructEq`.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, Iterable, Optional, Tuple

from dslabs_tpu.core.address import Address, SubAddress
from dslabs_tpu.core.types import Message, Timer
from dslabs_tpu.utils.structural import StructEq

LOG = logging.getLogger("dslabs.node")

__all__ = ["Node", "NodeConfig"]

# Handler method cache: (class, handler_name) -> bound-method-name or None
_HANDLER_CACHE: Dict[Tuple[type, str], Optional[str]] = {}


class NodeConfig:
    """Hooks wired into a root node by the execution engine.

    Mirrors the five config parameters of Node.config (Node.java:582-601).
    ``message_adder(from, to, message)``, ``batch_message_adder(from, tos,
    message)``, ``timer_adder(from, timer, min_ms, max_ms)``,
    ``throwable_catcher(exc)``.
    """

    __slots__ = ("message_adder", "batch_message_adder", "timer_adder",
                 "throwable_catcher", "log_exceptions")

    def __init__(self,
                 message_adder: Optional[Callable[[Address, Address, Message], None]],
                 timer_adder: Callable[[Address, Timer, int, int], None],
                 throwable_catcher: Optional[Callable[[BaseException], None]] = None,
                 batch_message_adder: Optional[
                     Callable[[Address, Tuple[Address, ...], Message], None]] = None,
                 log_exceptions: bool = True):
        self.message_adder = message_adder
        self.batch_message_adder = batch_message_adder
        self.timer_adder = timer_adder
        self.throwable_catcher = throwable_catcher
        self.log_exceptions = log_exceptions


class Node(StructEq):
    """Base class of every protocol actor."""

    # Config hooks are runtime wiring, not state: dropped on clone
    # (the engine re-configures each cloned node), excluded from equality.
    __deepcopy_skip__ = ("_config",)

    def __init__(self, address: Address):
        self.address = address
        self.sub_nodes: Dict[str, "Node"] = {}
        self._parent: Optional["Node"] = None
        self._config: Optional[NodeConfig] = None

    # -- StructEq: exclude the (immutable) address from the hashed field set is
    #    unnecessary; it is constant per node slot.  _parent/_config excluded
    #    automatically (underscore prefix).

    def init(self) -> None:
        """Initialization hook; may send messages and set timers."""
        raise NotImplementedError

    # ------------------------------------------------------------------ sends

    def send(self, message: Message, to: Address) -> None:
        self._send(message, self.address, to)

    def broadcast(self, message: Message, to: Iterable[Address]) -> None:
        tos = tuple(to)
        if not tos:
            return
        self._broadcast(message, self.address, tos)

    def set_timer(self, timer: Timer, min_ms: int, max_ms: Optional[int] = None) -> None:
        """Set a timer to fire between min_ms and max_ms (inclusive), chosen
        uniformly at random by the real-time runner; the model checker treats
        the bounds as a partial order (Node.java:218-248)."""
        if max_ms is None:
            max_ms = min_ms
        if min_ms > max_ms:
            raise ValueError("Minimum timer length greater than maximum")
        if min_ms < 1:
            raise ValueError("Minimum timer length < 1ms")
        self._set(timer, min_ms, max_ms, self.address)

    def _send(self, message: Message, frm: Address, to: Address) -> None:
        if message is None or to is None:
            LOG.error("Attempted to send null message/address from %s", frm)
            return
        if self._parent is not None and self._config is None:
            self._parent._send(message, frm, to)
            return
        cfg = self._config
        if cfg is None:
            LOG.error("Send before node configured: %s -> %s", frm, to)
            return
        if cfg.message_adder is not None:
            cfg.message_adder(frm, to, message)
        elif cfg.batch_message_adder is not None:
            cfg.batch_message_adder(frm, (to,), message)
        else:
            LOG.error("Node configured without message adder")

    def _broadcast(self, message: Message, frm: Address, tos: Tuple[Address, ...]) -> None:
        if message is None or any(a is None for a in tos):
            LOG.error("Attempted to broadcast null from %s", frm)
            return
        if self._parent is not None and self._config is None:
            self._parent._broadcast(message, frm, tos)
            return
        cfg = self._config
        if cfg is None:
            LOG.error("Broadcast before node configured from %s", frm)
            return
        if cfg.batch_message_adder is not None:
            cfg.batch_message_adder(frm, tos, message)
        elif cfg.message_adder is not None:
            for a in tos:
                cfg.message_adder(frm, a, message)
        else:
            LOG.error("Node configured without message adder")

    def _set(self, timer: Timer, min_ms: int, max_ms: int, frm: Address) -> None:
        if timer is None:
            LOG.error("Attempted to set null timer for %s", frm)
            return
        if self._parent is not None and self._config is None:
            self._parent._set(timer, min_ms, max_ms, frm)
            return
        cfg = self._config
        if cfg is None:
            LOG.error("Timer set before node configured for %s", frm)
            return
        cfg.timer_adder(frm, timer, min_ms, max_ms)

    # -------------------------------------------------------------- hierarchy

    def add_sub_node(self, sub_node: "Node") -> None:
        sa = sub_node.address
        if not (isinstance(sa, SubAddress) and sa.parent == self.address):
            raise ValueError(
                "Sub-node address must be a sub-address of this node's address")
        if sub_node._config is not None:
            raise ValueError("Cannot add node already configured as stand-alone")
        if sa.sub_id in self.sub_nodes:
            raise ValueError(f"Node already has sub-node with id {sa.sub_id}")
        sub_node._parent = self
        self.sub_nodes[sa.sub_id] = sub_node

    def _resolve(self, destination: Address) -> Optional["Node"]:
        """Walk from the hierarchy root to the node owning ``destination``."""
        n: Node = self
        while n._parent is not None:
            n = n._parent
        path = []
        d = destination
        while isinstance(d, SubAddress):
            path.append(d.sub_id)
            d = d.parent
        for sub_id in reversed(path):
            child = n.sub_nodes.get(sub_id)
            if child is None:
                LOG.error("Could not find sub-node %s of %s", sub_id, n.address)
                return None
            n = child
        return n

    # --------------------------------------------------------------- delivery

    def deliver_message(self, message: Message, sender: Address,
                        destination: Optional[Address] = None) -> None:
        """Framework entry point: dispatch a message to its handler.

        Exceptions from the handler are caught and routed to the configured
        throwable catcher (Node.java:387-389, 546-560)."""
        self._handle_message_internal(message, sender,
                                      destination or self.address,
                                      handle_exceptions=True)

    def handle_message_local(self, message: Message,
                             destination: Optional[Address] = None) -> Any:
        """Immediate local delivery within one root hierarchy (parent <->
        sub-node communication).  NOT cloned; exceptions propagate; the
        handler's return value is passed back (Node.java:391-427)."""
        return self._handle_message_internal(
            message, self.address, destination or self.address,
            handle_exceptions=False)

    def deliver_timer(self, timer: Timer,
                      destination: Optional[Address] = None) -> None:
        """Framework entry point: fire a timer handler."""
        self._on_timer_internal(timer, destination or self.address,
                                handle_exceptions=True)

    def on_timer_local(self, timer: Timer,
                       destination: Optional[Address] = None) -> None:
        """Invoke a timer handler immediately (Node.java:467-476)."""
        self._on_timer_internal(timer, destination or self.address,
                                handle_exceptions=False)

    def _handle_message_internal(self, message: Message, sender: Address,
                                 destination: Address, handle_exceptions: bool) -> Any:
        if message is None:
            LOG.error("Null message to %s", destination)
            return None
        if self.address.root_address() != destination.root_address():
            LOG.error("Message destined to %s delivered to %s; dropping",
                      destination, self.address)
            return None
        handler = "handle_" + type(message).__name__
        return self._call(destination, handler, handle_exceptions,
                          message, sender)

    def _on_timer_internal(self, timer: Timer, destination: Address,
                           handle_exceptions: bool) -> None:
        if timer is None:
            LOG.error("Null timer to %s", destination)
            return
        if self.address.root_address() != destination.root_address():
            LOG.error("Timer destined to %s delivered to %s; dropping",
                      destination, self.address)
            return
        handler = "on_" + type(timer).__name__
        self._call(destination, handler, handle_exceptions, timer)

    def _call(self, destination: Address, name: str, handle_exceptions: bool,
              *args: Any) -> Any:
        n = self._resolve(destination)
        if n is None:
            return None
        cls = type(n)
        key = (cls, name)
        if key not in _HANDLER_CACHE:
            _HANDLER_CACHE[key] = name if hasattr(n, name) else None
        resolved = _HANDLER_CACHE[key]
        if resolved is None:
            LOG.error("No handler %s on %s", name, cls.__name__)
            return None
        try:
            return getattr(n, resolved)(*args)
        except Exception as e:  # noqa: BLE001 — framework boundary
            if not handle_exceptions:
                raise
            # Route to the root's throwable catcher (the engine's hook).
            root: Node = self
            while root._parent is not None:
                root = root._parent
            cfg = root._config
            if cfg is not None and cfg.log_exceptions:
                LOG.exception("Error invoking %s on %s", name, cls.__name__)
            if cfg is not None and cfg.throwable_catcher is not None:
                cfg.throwable_catcher(e)
            return None

    # ------------------------------------------------------------ configuring

    def config(self, cfg: NodeConfig) -> None:
        """Wire engine hooks into this (root) node (Node.java:582-601)."""
        if self._parent is not None:
            LOG.error("Cannot configure node already configured as sub-node")
        if cfg.message_adder is None and cfg.batch_message_adder is None:
            LOG.error("Config must include a message adder")
        self._config = cfg

    @property
    def configured(self) -> bool:
        return self._config is not None
