"""Per-test stdout/stderr capture with size caps — TeeStdOutErr
(utils/TeeStdOutErr.java:34-134) re-designed as a context manager.

Output still flows to the real streams (the console reporter interleaves
with test output, as in the reference); the captured copy (truncated at
``max_bytes`` with a flag) feeds the JSON results log
(TestResults.java:86-97)."""

from __future__ import annotations

import io
import sys

__all__ = ["TeeStdOutErr"]


class _TeeWriter(io.TextIOBase):
    def __init__(self, real, cap: int):
        self.real = real
        self.cap = cap
        self.buf = io.StringIO()
        self.truncated = False

    def write(self, s):
        self.real.write(s)
        room = self.cap - self.buf.tell()
        if room > 0:
            self.buf.write(s[:room])
        if s and len(s) > max(room, 0):
            self.truncated = True
        return len(s)

    def flush(self):
        self.real.flush()

    def captured(self) -> str:
        return self.buf.getvalue()


class ThreadRouter(io.TextIOBase):
    """Routes writes by thread: threads registered via :meth:`route` write
    to their own `_TeeWriter`; everything else goes to the real stream.

    The CLI runner installs one router per stream for the whole run so a
    timed-out test's orphaned thread keeps writing to ITS OWN (abandoned)
    capture buffer instead of contaminating the next test's capture."""

    def __init__(self, real):
        self.real = real
        self.routes = {}

    def route(self, thread_ident, writer) -> None:
        self.routes[thread_ident] = writer

    def unroute(self, thread_ident) -> None:
        self.routes.pop(thread_ident, None)

    def write(self, s):
        import threading

        w = self.routes.get(threading.get_ident())
        if w is not None:
            return w.write(s)
        return self.real.write(s)

    def flush(self):
        self.real.flush()


class TeeStdOutErr:
    """``with TeeStdOutErr() as tee: ...`` then ``tee.stdout``/``tee.stderr``
    hold the captured (possibly truncated) copies."""

    def __init__(self, max_bytes: int = 1 << 20):
        self.max_bytes = max_bytes
        self.stdout = ""
        self.stderr = ""
        self.stdout_truncated = False
        self.stderr_truncated = False

    def __enter__(self):
        self._out = _TeeWriter(sys.stdout, self.max_bytes)
        self._err = _TeeWriter(sys.stderr, self.max_bytes)
        self._saved = (sys.stdout, sys.stderr)
        sys.stdout, sys.stderr = self._out, self._err
        return self

    def __exit__(self, *exc):
        sys.stdout, sys.stderr = self._saved
        self.stdout = self._out.captured()
        self.stderr = self._err.captured()
        self.stdout_truncated = self._out.truncated
        self.stderr_truncated = self._err.truncated
        return False
