"""Per-test stdout/stderr capture with size caps — TeeStdOutErr
(utils/TeeStdOutErr.java:34-134) re-designed as a context manager.

Output still flows to the real streams (the console reporter interleaves
with test output, as in the reference); the captured copy (truncated at
``max_bytes`` with a flag) feeds the JSON results log
(TestResults.java:86-97)."""

from __future__ import annotations

import io
import sys

__all__ = ["TeeStdOutErr"]


class _TeeWriter(io.TextIOBase):
    def __init__(self, real, cap: int):
        self.real = real
        self.cap = cap
        self.buf = io.StringIO()
        self.truncated = False

    def write(self, s):
        self.real.write(s)
        if self.buf.tell() < self.cap:
            self.buf.write(s[:self.cap - self.buf.tell()])
        elif s:
            self.truncated = True
        return len(s)

    def flush(self):
        self.real.flush()

    def captured(self) -> str:
        return self.buf.getvalue()


class TeeStdOutErr:
    """``with TeeStdOutErr() as tee: ...`` then ``tee.stdout``/``tee.stderr``
    hold the captured (possibly truncated) copies."""

    def __init__(self, max_bytes: int = 1 << 20):
        self.max_bytes = max_bytes
        self.stdout = ""
        self.stderr = ""
        self.stdout_truncated = False
        self.stderr_truncated = False

    def __enter__(self):
        self._out = _TeeWriter(sys.stdout, self.max_bytes)
        self._err = _TeeWriter(sys.stderr, self.max_bytes)
        self._saved = (sys.stdout, sys.stderr)
        sys.stdout, sys.stderr = self._out, self._err
        return self

    def __exit__(self, *exc):
        sys.stdout, sys.stderr = self._saved
        self.stdout = self._out.captured()
        self.stderr = self._err.captured()
        self.stdout_truncated = self._out.truncated
        self.stderr_truncated = self._err.truncated
        return False
