"""Test registry + annotations — the @Lab/@Part/@TestDescription/
@TestPointValue/@Category system (junit/Lab.java:35, Part.java:33,
TestDescription.java:32, TestPointValue.java:32, RunTests.java:25,
SearchTests.java:25, UnreliableTests.java:25) re-designed as one function
decorator.

A lab test is an ordinary pytest function decorated with
:func:`lab_test`; the decorator registers it (module import populates the
registry, like the reference's classpath scan in utils/ClassSearch.java:35)
and leaves the function itself untouched, so the same test runs under
pytest and under the CLI driver (`run_tests.py`).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional, Tuple

__all__ = ["RUN_TESTS", "SEARCH_TESTS", "UNRELIABLE_TESTS", "TestEntry",
           "lab_test", "registry", "clear_registry"]

# Category markers (reference: JUnit @Category classes).
RUN_TESTS = "RunTests"
SEARCH_TESTS = "SearchTests"
UNRELIABLE_TESTS = "UnreliableTests"


@dataclasses.dataclass(frozen=True)
class TestEntry:
    __test__ = False          # not itself a pytest collectable

    fn: Callable
    lab: str                       # "0".."4" (string, like @Lab)
    num: int                       # test number (test01Foo -> 1)
    description: str
    points: int = 0
    part: Optional[int] = None
    categories: Tuple[str, ...] = ()
    timeout_secs: Optional[float] = None

    @property
    def full_number(self) -> str:
        """DSLabsTestCore's part-qualified number ("2.1" / "7")."""
        if self.part is not None:
            return f"{self.part}.{self.num}"
        return str(self.num)

    @property
    def name(self) -> str:
        return self.fn.__name__

    def sort_key(self):
        return (self.lab, self.part or 0, self.num, self.name)


_REGISTRY: List[TestEntry] = []


def lab_test(lab: str, num: int, description: str, points: int = 0,
             part: Optional[int] = None,
             categories: Tuple[str, ...] = (RUN_TESTS,),
             timeout_secs: Optional[float] = None):
    """Register a lab test with its reference metadata.

    Numbers, descriptions, and point values mirror the reference lab test
    suites (cited per test at the use sites), so `run_tests.py --lab N`
    reproduces the reference's selection and scoring shape."""

    def deco(fn):
        entry = TestEntry(fn=fn, lab=str(lab), num=num,
                          description=description, points=points, part=part,
                          categories=tuple(categories),
                          timeout_secs=timeout_secs)
        _REGISTRY.append(entry)
        fn._dslabs_test_entry = entry
        return fn

    return deco


def registry() -> List[TestEntry]:
    return sorted(_REGISTRY, key=TestEntry.sort_key)


def clear_registry() -> None:
    _REGISTRY.clear()
