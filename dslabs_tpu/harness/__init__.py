"""Test harness: registry/annotations, assertion helpers, runner, capture.

Re-design of the reference's `junit/` layer (BaseJUnitTest.java:70,
DSLabsTestCore.java:49, TestResultsPrinter.java:39) for plain-Python lab
tests driven either by pytest or by the `run_tests.py` CLI."""

from dslabs_tpu.harness.annotations import (RUN_TESTS, SEARCH_TESTS,
                                            UNRELIABLE_TESTS, TestEntry,
                                            clear_registry, lab_test,
                                            registry)
from dslabs_tpu.harness.junit import (FailureAccumulator, TestFailure,
                                      assert_end_condition_valid,
                                      assert_goal_found,
                                      assert_space_exhausted,
                                      goal_matching_state)
from dslabs_tpu.harness.runner import (RunReport, TestResult, run_tests,
                                       select_tests)
from dslabs_tpu.harness.tee import TeeStdOutErr

__all__ = [
    "RUN_TESTS", "SEARCH_TESTS", "UNRELIABLE_TESTS", "TestEntry",
    "lab_test", "registry", "clear_registry",
    "FailureAccumulator", "TestFailure", "assert_end_condition_valid",
    "assert_goal_found", "assert_space_exhausted", "goal_matching_state",
    "RunReport", "TestResult", "run_tests", "select_tests", "TeeStdOutErr",
]
