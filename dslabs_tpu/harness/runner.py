"""Test selection, execution, and reporting — DSLabsTestCore +
TestResultsPrinter + TestResultsLogger re-designed
(junit/DSLabsTestCore.java:49-289, TestResultsPrinter.java:39-170,
TestResults.java:49-98).

Output mirrors the reference's console shape:

    --------------------------------------------------
    TEST 2.1: Startup view (5pts)
      START [2026-07-30 12:00:00.00]...

    ...PASS [2026-07-30 12:00:01.10] (1.1s)
    ==================================================

    Tests passed: 11/12
    Points: 55/60
    Total time: 12.3s

    ALL PASS / FAIL
"""

from __future__ import annotations

import dataclasses
import json
import logging
import threading
import time
import traceback
from typing import List, Optional, Sequence

LOG = logging.getLogger("dslabs.harness")

from dslabs_tpu.harness.annotations import TestEntry
from dslabs_tpu.harness.tee import TeeStdOutErr
from dslabs_tpu.utils.flags import GlobalSettings

__all__ = ["select_tests", "run_tests", "TestResult", "RunReport"]

SMALL_SEP = "-" * 50
LARGE_SEP = "=" * 50


def _now() -> str:
    ms = int((time.time() % 1) * 100)
    return time.strftime("%Y-%m-%d %H:%M:%S") + f".{ms:02d}"


@dataclasses.dataclass
class TestResult:
    entry: TestEntry
    passed: bool
    elapsed_secs: float
    error: Optional[str] = None
    timed_out: bool = False
    stdout: str = ""
    stderr: str = ""
    stdout_truncated: bool = False
    stderr_truncated: bool = False
    start_time: float = 0.0
    end_time: float = 0.0


@dataclasses.dataclass
class RunReport:
    results: List[TestResult]
    total_secs: float

    @property
    def num_passed(self) -> int:
        return sum(r.passed for r in self.results)

    @property
    def points_earned(self) -> int:
        return sum(r.entry.points for r in self.results if r.passed)

    @property
    def points_available(self) -> int:
        return sum(r.entry.points for r in self.results)

    @property
    def all_passed(self) -> bool:
        return self.num_passed == len(self.results)


def select_tests(entries: Sequence[TestEntry],
                 lab: Optional[str] = None,
                 part: Optional[int] = None,
                 nums: Optional[Sequence[int]] = None,
                 exclude_run: bool = False,
                 exclude_search: bool = False,
                 exclude_unreliable: bool = False) -> List[TestEntry]:
    """Lab/part/test-number/category selection
    (DSLabsTestCore.java:56-70, 186-232)."""
    from dslabs_tpu.harness.annotations import (RUN_TESTS, SEARCH_TESTS,
                                                UNRELIABLE_TESTS)
    out = []
    for e in sorted(entries, key=TestEntry.sort_key):
        if lab is not None and e.lab != str(lab):
            continue
        if part is not None and e.part != part:
            continue
        if nums and e.num not in nums:
            continue
        cats = set(e.categories)
        is_search = SEARCH_TESTS in cats
        is_run = RUN_TESTS in cats or not is_search
        if exclude_run and is_run and not is_search:
            continue
        if exclude_search and is_search and not is_run:
            continue
        if exclude_unreliable and UNRELIABLE_TESTS in cats:
            continue
        out.append(e)
    return out


def _run_one(entry: TestEntry, routers=None) -> TestResult:
    from dslabs_tpu.harness.tee import _TeeWriter

    start = time.time()
    err_box: List[Optional[BaseException]] = [None]
    out_router, err_router = routers
    out_w = _TeeWriter(out_router.real, 1 << 20)
    err_w = _TeeWriter(err_router.real, 1 << 20)

    def target():
        ident = threading.get_ident()
        out_router.route(ident, out_w)
        err_router.route(ident, err_w)
        try:
            entry.fn()
        except BaseException as e:  # noqa: BLE001 — reported, not swallowed
            err_box[0] = e
        finally:
            # A thread that outlives its timeout stays routed to its own
            # abandoned buffer until the function finally returns — its
            # late output can never land in a later test's capture.
            out_router.unroute(ident)
            err_router.unroute(ident)

    timeout = entry.timeout_secs
    if GlobalSettings.test_timeouts_disabled:
        timeout = None
    if timeout is None:
        target()
        timed_out = False
    else:
        th = threading.Thread(target=target, daemon=True)
        th.start()
        th.join(timeout)
        timed_out = th.is_alive()
        if timed_out:
            # Cooperative stop of everything the abandoned test thread
            # started: node threads exit, single-threaded run loops
            # break, and a brief grace join keeps late output out of the
            # next test (the reference interrupts + joins,
            # RunState.java:340-383).
            from dslabs_tpu.runner.run_state import stop_active_run_states
            stopped, stuck = stop_active_run_states()
            if stopped:
                LOG.warning(
                    "timeout: stopped %d leaked RunState(s)%s", stopped,
                    (f", {stuck} node thread(s) stuck past their join "
                     "timeout (wedged handlers — names/addresses logged "
                     "above)") if stuck else "")
            th.join(2.0)
    end = time.time()
    err = err_box[0]
    error_text = None
    if timed_out:
        error_text = f"TIMEOUT after {timeout}s"
    elif err is not None:
        error_text = "".join(traceback.format_exception(
            type(err), err, err.__traceback__))
    return TestResult(
        entry=entry, passed=error_text is None,
        elapsed_secs=end - start, error=error_text, timed_out=timed_out,
        stdout=out_w.captured(), stderr=err_w.captured(),
        stdout_truncated=out_w.truncated,
        stderr_truncated=err_w.truncated,
        start_time=start, end_time=end)


def _run_all(entries, out_router, err_router):
    import gc

    results = []
    for e in entries:
        # Inter-test isolation (BaseJUnitTest.java:111-191: GC + settle
        # between tests): a collector pause or the previous test's
        # late-stopping threads must not land inside the next test's
        # wall-clock window (the lab run tests assert sub-second client
        # wait bounds).
        gc.collect()
        time.sleep(0.05)
        print(SMALL_SEP)
        print(f"TEST {e.full_number}: {e.description} ({e.points}pts)")
        print(f"  START [{_now()}]...\n")
        r = _run_one(e, routers=(out_router, err_router))
        results.append(r)
        if r.error is not None:
            print(r.error)
        verdict = "...PASS" if r.passed else "...FAIL"
        print(f"{verdict} [{_now()}] ({r.elapsed_secs:.2f}s)")
    return results


def run_tests(entries: Sequence[TestEntry],
              results_output_file: Optional[str] = None) -> RunReport:
    import sys

    from dslabs_tpu.harness.tee import ThreadRouter

    t0 = time.time()
    results: List[TestResult] = []
    out_router = ThreadRouter(sys.stdout)
    err_router = ThreadRouter(sys.stderr)
    saved = (sys.stdout, sys.stderr)
    sys.stdout, sys.stderr = out_router, err_router
    try:
        results.extend(_run_all(entries, out_router, err_router))
    finally:
        sys.stdout, sys.stderr = saved
    report = RunReport(results=results, total_secs=time.time() - t0)

    print(LARGE_SEP)
    print()
    print(f"Tests passed: {report.num_passed}/{len(results)}")
    print(f"Points: {report.points_earned}/{report.points_available}")
    print(f"Total time: {report.total_secs:.3f}s")
    print("\nALL PASS" if report.all_passed else "\nFAIL")
    print(LARGE_SEP)

    out_file = results_output_file or GlobalSettings.results_output_file
    if out_file:
        _write_json(report, out_file)
    return report


def _write_json(report: RunReport, path: str) -> None:
    """JSON results log (TestResultsLogger.java:41, TestResults.java:49-98)."""
    payload = {
        "num_passed": report.num_passed,
        "num_tests": len(report.results),
        "points_earned": report.points_earned,
        "points_available": report.points_available,
        "total_secs": report.total_secs,
        "tests": [{
            "lab": r.entry.lab,
            "part": r.entry.part,
            "number": r.entry.num,
            "name": r.entry.name,
            "description": r.entry.description,
            "categories": list(r.entry.categories),
            "points_earned": r.entry.points if r.passed else 0,
            "points_available": r.entry.points,
            "passed": r.passed,
            "timed_out": r.timed_out,
            "error": r.error,
            "stdout": r.stdout,
            "stdout_truncated": r.stdout_truncated,
            "stderr": r.stderr,
            "stderr_truncated": r.stderr_truncated,
            "start_time": r.start_time,
            "end_time": r.end_time,
        } for r in report.results],
    }
    with open(path, "w") as f:
        json.dump(payload, f, indent=2)
    print(f"Wrote JSON results to {path}")
