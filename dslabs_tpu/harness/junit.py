"""BaseJUnitTest-analog assertion helpers (junit/BaseJUnitTest.java:70-492).

These are plain functions usable from pytest tests and from the CLI
driver alike:

* :func:`assert_end_condition_valid` — the workhorse: on an invariant
  violation / unexpected exception it prints the human-readable minimized
  trace (BaseJUnitTest.java:286-330), saves it to ``traces/`` when trace
  saving is enabled (GlobalSettings.save_traces, `-s` in run-tests.py),
  then fails.
* goal/space assertions (BaseJUnitTest.java:361-444).
* :class:`FailureAccumulator` — fail-and-continue with a final
  MultipleFailureException analog (DSLabsJUnitTest.java:118-143).
"""

from __future__ import annotations

from typing import List, Optional

from dslabs_tpu.search.results import EndCondition, SearchResults
from dslabs_tpu.search.trace import save_trace
from dslabs_tpu.utils.flags import GlobalSettings

__all__ = ["assert_end_condition_valid", "assert_goal_found",
           "assert_space_exhausted", "goal_matching_state",
           "FailureAccumulator", "TestFailure"]


class TestFailure(AssertionError):
    """A lab-test failure (assertion with harness context attached)."""


def _report_violation(state, header: str, lab: Optional[str] = None,
                      part: Optional[int] = None,
                      test_name: Optional[str] = None,
                      invariants=()) -> None:
    print(f"\n{header}")
    if state is not None:
        state.print_trace()
        if GlobalSettings.save_traces:
            path = save_trace(state, list(invariants), lab_id=lab or "?",
                              lab_part=part, test_class_name="",
                              test_method_name=test_name or "")
            print(f"Saved trace to {path}")
        if GlobalSettings.start_viz:
            # -z: launch the branch-exploring debugger on the violating
            # trace and halt the run there — the BaseJUnitTest startViz /
            # VizStarted behavior (BaseJUnitTest.java:286-355).
            from dslabs_tpu.viz.debugger import serve_debugger

            events = [e.previous_event for e in state.trace()
                      if e.previous_event is not None]
            root = state.trace()[0]
            serve_debugger(root, preload_events=events)


def assert_end_condition_valid(results: SearchResults,
                               lab: Optional[str] = None,
                               part: Optional[int] = None,
                               test_name: Optional[str] = None) -> None:
    """Fail (with trace printing/saving) unless the search ended without
    finding a violation or exception — BaseJUnitTest.assertEndConditionValid
    (junit/BaseJUnitTest.java:286-355)."""
    if results.end_condition == EndCondition.INVARIANT_VIOLATED:
        r = results.invariant_violated_result
        _report_violation(results.invariant_violating_state,
                          "Invariant violated; trace:", lab, part, test_name,
                          results.invariants)
        raise TestFailure(
            f"Invariant violated: "
            f"{r.error_message() if r is not None else 'unknown'}")
    if results.end_condition == EndCondition.EXCEPTION_THROWN:
        state = results.exceptional_state
        _report_violation(state, "Exception thrown by a handler; trace:",
                          lab, part, test_name, results.invariants)
        exc = getattr(state, "thrown_exception", None)
        raise TestFailure(f"Exception thrown by a node handler: {exc!r}")


def assert_goal_found(results: SearchResults, **ctx) -> None:
    """assertEndConditionValid + the goal must have matched
    (BaseJUnitTest.java:361-384)."""
    assert_end_condition_valid(results, **ctx)
    if results.end_condition != EndCondition.GOAL_FOUND:
        raise TestFailure(
            f"Goal not found (end condition: {results.end_condition}; "
            f"goals: {[str(g) for g in results.goals]})")


def goal_matching_state(results: SearchResults, **ctx):
    """The state matching the goal, for staged searches
    (BaseJUnitTest.java:398-409; PaxosTest.java:898-902)."""
    assert_goal_found(results, **ctx)
    return results.goal_matching_state


def assert_space_exhausted(results: SearchResults, **ctx) -> None:
    """assertEndConditionValid + full exploration (BaseJUnitTest.java:
    411-444) — the pruned subspace must have been exhausted, not timed out."""
    assert_end_condition_valid(results, **ctx)
    if results.end_condition != EndCondition.SPACE_EXHAUSTED:
        raise TestFailure(
            f"Search space not exhausted ({results.end_condition}); "
            "increase the time limit or narrow the search")


class FailureAccumulator:
    """failAndContinue + MultipleFailureException analog
    (DSLabsJUnitTest.java:118-143)."""

    def __init__(self):
        self.failures: List[str] = []

    def fail_and_continue(self, message: str) -> None:
        self.failures.append(message)
        print(f"FAILURE (continuing): {message}")

    def check(self, condition: bool, message: str) -> None:
        if not condition:
            self.fail_and_continue(message)

    def assert_no_failures(self) -> None:
        if self.failures:
            raise TestFailure(
                f"{len(self.failures)} accumulated failure(s):\n  " +
                "\n  ".join(self.failures))
