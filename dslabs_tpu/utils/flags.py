"""Global flag system.

Re-design of framework/tst/.../utils/GlobalSettings.java:37-143.  Flags come
from environment variables (``DSLABS_<NAME>``) or are set programmatically;
the test harness maps CLI options onto them the way run-tests.py maps flags to
JVM properties.
"""

from __future__ import annotations

import os
from typing import Optional

__all__ = ["GlobalSettings"]


def _env_bool(name: str, default: bool = False) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.lower() in ("1", "true", "yes", "on")


class _GlobalSettings:
    def __init__(self):
        self.verbose: bool = _env_bool("DSLABS_VERBOSE", True)
        self.single_threaded: bool = _env_bool("DSLABS_SINGLE_THREADED")
        self.start_viz: bool = _env_bool("DSLABS_START_VIZ")
        self.save_traces: bool = _env_bool("DSLABS_SAVE_TRACES")
        self.do_checks: bool = _env_bool("DSLABS_DO_CHECKS")
        self.do_all_checks: bool = _env_bool("DSLABS_DO_ALL_CHECKS")
        self.test_timeouts_disabled: bool = _env_bool("DSLABS_NO_TIMEOUTS")
        self.results_output_file: Optional[str] = os.environ.get(
            "DSLABS_RESULTS_OUTPUT_FILE")
        self.log_level: str = os.environ.get("DSLABS_LOG_LEVEL", "WARNING")
        # Search strategy: "object" (the Python graph checker) or
        # "tensor" (the TPU engine via protocol twins, tpu/backend.py).
        self.search_backend: str = os.environ.get(
            "DSLABS_SEARCH_BACKEND", "object")
        # Multiplier on every search max-time budget (the reference
        # grader's timeout-multiplier analog): batch runs under compile
        # or CPU contention can set e.g. 2.0 so a directed staged phase
        # that needs 10s solo doesn't TIME_EXHAUST at a nominal 60s
        # budget that contention stretched past (the round-4 "test23
        # passes standalone, fails in batch" margin).
        self.time_scale: float = float(
            os.environ.get("DSLABS_TIME_SCALE", "1.0"))
        # Temporarily-enabled error checks (@ChecksEnabled rule analog)
        self.error_checks_temporarily_enabled: bool = False

    def do_error_checks(self) -> bool:
        return self.do_checks or self.error_checks_temporarily_enabled

    def do_all_error_checks(self) -> bool:
        return self.do_all_checks


GlobalSettings = _GlobalSettings()
