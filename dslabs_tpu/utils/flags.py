"""Global flag system.

Re-design of framework/tst/.../utils/GlobalSettings.java:37-143.  Flags come
from environment variables (``DSLABS_<NAME>``) or are set programmatically;
the test harness maps CLI options onto them the way run-tests.py maps flags to
JVM properties.
"""

from __future__ import annotations

import os
from typing import Optional

__all__ = ["GlobalSettings"]


def _env_bool(name: str, default: bool = False) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.lower() in ("1", "true", "yes", "on")


class _GlobalSettings:
    def __init__(self):
        self.verbose: bool = _env_bool("DSLABS_VERBOSE", True)
        self.single_threaded: bool = _env_bool("DSLABS_SINGLE_THREADED")
        self.start_viz: bool = _env_bool("DSLABS_START_VIZ")
        self.save_traces: bool = _env_bool("DSLABS_SAVE_TRACES")
        self.do_checks: bool = _env_bool("DSLABS_DO_CHECKS")
        self.do_all_checks: bool = _env_bool("DSLABS_DO_ALL_CHECKS")
        self.test_timeouts_disabled: bool = _env_bool("DSLABS_NO_TIMEOUTS")
        self.results_output_file: Optional[str] = os.environ.get(
            "DSLABS_RESULTS_OUTPUT_FILE")
        self.log_level: str = os.environ.get("DSLABS_LOG_LEVEL", "WARNING")
        # Search strategy: "object" (the Python graph checker) or
        # "tensor" (the TPU engine via protocol twins, tpu/backend.py).
        self.search_backend: str = os.environ.get(
            "DSLABS_SEARCH_BACKEND", "object")
        # Temporarily-enabled error checks (@ChecksEnabled rule analog)
        self.error_checks_temporarily_enabled: bool = False

    def do_error_checks(self) -> bool:
        return self.do_checks or self.error_checks_temporarily_enabled

    def do_all_error_checks(self) -> bool:
        return self.do_all_checks


GlobalSettings = _GlobalSettings()
