"""Structural equality, hashing, and cloning for protocol state.

The reference framework (dslabs, Java) requires every piece of node state to
implement equals/hashCode and be deep-clonable (framework/src/dslabs/framework/
Node.java:50-101, framework/tst/.../utils/Cloning.java:64-159).  The model
checker's visited set keys on that equality.

In this rebuild, protocol objects are ordinary Python objects; this module
supplies the structural primitives:

  * ``sfreeze(obj)``   -> a canonical, hashable "frozen" form of an object graph
                          (order-insensitive for dicts/sets, order-sensitive for
                          lists/tuples).  Two objects are search-equivalent iff
                          their frozen forms are equal.
  * ``shash(obj)``     -> hash of the frozen form (memoised per call tree).
  * ``clone(obj)``     -> deep clone (copy.deepcopy with a shared memo guard);
                          fields named with a leading underscore on framework
                          classes are treated like Java ``transient`` fields and
                          excluded from equality/hash (but still deep-copied
                          unless the class opts out via ``__deepcopy_skip__``).

Classes participate by inheriting :class:`StructEq`, which derives
``__eq__``/``__hash__`` from the public instance ``__dict__`` (every attribute
whose name does not start with ``_``).  This mirrors Lombok's
``@EqualsAndHashCode`` used pervasively in the reference.
"""

from __future__ import annotations

import copy
from typing import Any

from dslabs_tpu.utils.flags import GlobalSettings

__all__ = ["sfreeze", "shash", "clone", "StructEq", "ImmutableMarker"]


class ImmutableMarker:
    """Mix-in marking a class as immutable: clone() returns it unchanged.

    Mirrors the reference's ``@Immutable`` short-circuit in its cloning layer
    (framework/tst/.../utils/Cloning.java:64-141), used by e.g. LocalAddress.
    """


def _public_items(obj: Any):
    d = obj.__dict__
    return [(k, v) for k, v in d.items() if not k.startswith("_")]


def _slot_items(obj: Any):
    """Public values stored in __slots__ across the MRO (objects like Address
    keep their state in slots, not __dict__)."""
    items = []
    for cls in type(obj).__mro__:
        for name in getattr(cls, "__slots__", ()):
            if name.startswith("__"):
                continue
            try:
                items.append((name, getattr(obj, name)))
            except AttributeError:
                pass
    return items


def sfreeze(obj: Any) -> Any:
    """Return a canonical hashable representation of ``obj``.

    dicts and sets freeze order-insensitively (like Java HashMap/HashSet
    hashCodes); lists/tuples keep order.  Objects with ``StructEq`` freeze as
    (class, frozen public fields).  A class may define ``__sfreeze__`` to
    supply its own canonical form.
    """
    if obj is None or isinstance(obj, (bool, int, float, str, bytes)):
        return obj
    custom = getattr(obj, "__sfreeze__", None)
    if custom is not None:
        return (type(obj).__qualname__, custom())
    if isinstance(obj, (list, tuple)):
        return ("#l", tuple(sfreeze(x) for x in obj))
    if isinstance(obj, dict):
        return ("#d", frozenset((sfreeze(k), sfreeze(v)) for k, v in obj.items()))
    if isinstance(obj, (set, frozenset)):
        return ("#s", frozenset(sfreeze(x) for x in obj))
    if isinstance(obj, StructEq):
        # Use the class's equality fields so customised equality (e.g.
        # ClientWorker's (client, results)) shapes nested hashing too.
        return (type(obj).__qualname__, ("#d", frozenset(
            (k, sfreeze(v)) for k, v in obj._eq_fields().items())))
    if hasattr(obj, "__dict__") or hasattr(type(obj), "__slots__"):
        # Plain objects (e.g. dataclasses, slotted classes): structural over
        # public __dict__ entries plus public slot values.
        fields = _public_items(obj) if hasattr(obj, "__dict__") else []
        fields += _slot_items(obj)
        return (type(obj).__qualname__, ("#d", frozenset(
            (k, sfreeze(v)) for k, v in fields)))
    # Fall back to the object's own hashability (enums, etc).
    return obj


def shash(obj: Any) -> int:
    return hash(sfreeze(obj))


def clone(obj: Any):
    """Deep-clone an object graph.

    Equivalent role to the reference's Cloning.clone (utils/Cloning.java:109-141):
    used for clone-on-send and copy-on-write successor states.  Immutable-marked
    objects are returned as-is.  Under ``do_error_checks`` every clone is
    verified equal-and-hash-consistent with its original and failures are
    routed to the CheckLogger (Cloning.java:130-138).
    """
    if obj is None or isinstance(obj, (bool, int, float, str, bytes, ImmutableMarker)):
        return obj
    out = copy.deepcopy(obj)
    if GlobalSettings.do_error_checks():
        from dslabs_tpu.utils.check_logger import CheckLogger

        try:
            eq = bool(out == obj)
        except Exception:  # noqa: BLE001 — incomparable (e.g. array-valued
            eq = None      # __eq__); cannot judge, not a conformance finding
        if eq is False:
            CheckLogger.clone_not_equal(obj)
        elif eq:
            try:
                if shash(out) != shash(obj):
                    CheckLogger.hash_inconsistent(obj)
            except Exception:  # noqa: BLE001 — unhashable: nothing to check
                pass
    return out


class StructEq:
    """Structural equality/hash over public instance attributes.

    Attributes starting with ``_`` are excluded (Java ``transient`` analog: the
    reference nulls transient fields before comparing/cloning,
    utils/Cloning.java:80-104).  Subclasses may extend/override
    ``_eq_fields()`` to customise (e.g. ClientWorker compares only
    (client, results), ClientWorker.java:49-52).
    """

    def _eq_fields(self):
        return {k: v for k, v in self.__dict__.items() if not k.startswith("_")}

    def __eq__(self, other: Any) -> bool:
        if self is other:
            return True
        if type(self) is not type(other):
            return NotImplemented
        return self._eq_fields() == other._eq_fields()

    def __ne__(self, other: Any) -> bool:
        r = self.__eq__(other)
        return r if r is NotImplemented else not r

    def __hash__(self) -> int:
        return hash((type(self).__qualname__, frozenset(
            (k, sfreeze(v)) for k, v in self._eq_fields().items())))

    def __deepcopy__(self, memo):
        cls = self.__class__
        new = cls.__new__(cls)
        memo[id(self)] = new
        skip = getattr(self, "__deepcopy_skip__", ())
        for k, v in self.__dict__.items():
            if k in skip:
                setattr(new, k, None)
            else:
                setattr(new, k, copy.deepcopy(v, memo))
        return new

    def __repr__(self) -> str:  # debugger-friendly default
        fields = ", ".join(f"{k}={v!r}" for k, v in self._eq_fields().items())
        return f"{type(self).__name__}({fields})"
