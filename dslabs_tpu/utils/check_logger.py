"""Conformance-violation aggregator.

Re-design of framework/tst/.../utils/CheckLogger.java:40-185: collects
witnesses of non-deterministic handlers, non-idempotent message handlers, and
clone/equality inconsistencies; printed once at interpreter exit.  These
checks are what make student-style state machines safe to hash and vectorize
(SURVEY §4.2).
"""

from __future__ import annotations

import atexit
import sys
import threading
from typing import Dict, Tuple

__all__ = ["CheckLogger"]


class _CheckLogger:
    def __init__(self):
        self._lock = threading.Lock()
        # kind -> witness description (first witness wins per kind+location)
        self._findings: Dict[Tuple[str, str], str] = {}
        self._registered = False

    def _record(self, kind: str, location: str, detail: str) -> None:
        with self._lock:
            key = (kind, location)
            if key not in self._findings:
                self._findings[key] = detail
            if not self._registered:
                atexit.register(self.print_report)
                self._registered = True

    def not_deterministic(self, event, state) -> None:
        self._record("NON_DETERMINISTIC_HANDLER", repr(event),
                     f"Re-executing {event!r} on {state!r} gave a different state")

    def not_idempotent(self, event, state) -> None:
        self._record("NON_IDEMPOTENT_HANDLER", repr(event),
                     f"Re-delivering {event!r} changed the state again")

    def clone_not_equal(self, obj) -> None:
        self._record("CLONE_NOT_EQUAL", type(obj).__qualname__,
                     f"Object not equal to its clone: {obj!r}")

    def hash_inconsistent(self, obj) -> None:
        self._record("HASHCODE_INCONSISTENT", type(obj).__qualname__,
                     f"Clone hash differs: {obj!r}")

    @property
    def findings(self):
        return dict(self._findings)

    def clear(self) -> None:
        with self._lock:
            self._findings.clear()

    def print_report(self, out=None) -> None:
        out = out or sys.stderr
        if not self._findings:
            return
        print("\n=== dslabs conformance check findings ===", file=out)
        for (kind, loc), detail in self._findings.items():
            print(f"[{kind}] at {loc}: {detail}", file=out)


CheckLogger = _CheckLogger()
