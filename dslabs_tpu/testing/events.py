"""Events: message and timer envelopes.

Re-design of framework/tst/dslabs/framework/testing/{Event,MessageEnvelope,
TimerEnvelope}.java.

Key semantics (SURVEY §7):
  * ``MessageEnvelope`` has value equality over (from, to, message) — the
    search network is a *set*, so identical sends collapse
    (MessageEnvelope.java:29-41).
  * ``TimerEnvelope`` equality EXCLUDES the concretely sampled duration and
    wall-clock bookkeeping (TimerEnvelope.java:39-40) so search states hash
    identically regardless of real-time sampling.
"""

from __future__ import annotations

import random
import time
from typing import Optional, Union

from dslabs_tpu.core.address import Address
from dslabs_tpu.core.types import Message, Timer
from dslabs_tpu.utils.structural import StructEq

__all__ = ["Event", "MessageEnvelope", "TimerEnvelope"]


class MessageEnvelope(StructEq):
    """(from, to, message) with structural value equality."""

    def __init__(self, frm: Address, to: Address, message: Message):
        self.frm = frm
        self.to = to
        self.message = message

    def location_root_address(self) -> Address:
        """The root node this event applies to (Event.java:34-49)."""
        return self.to.root_address()

    def __repr__(self) -> str:
        return f"Message({self.frm} -> {self.to}, {self.message!r})"


class TimerEnvelope(StructEq):
    """A set timer: (to, timer, min_ms, max_ms).

    The real-time runner draws a concrete ``length_ms`` uniformly from
    [min, max] and tracks wall-clock deadlines (TimerEnvelope.java:50-99);
    those fields are underscore-private and therefore excluded from structural
    equality/hash.
    """

    def __init__(self, to: Address, timer: Timer, min_ms: int, max_ms: int):
        self.to = to
        self.timer = timer
        self.min_ms = min_ms
        self.max_ms = max_ms
        self._length_ms: Optional[int] = None
        self._start_ns: Optional[int] = None

    # --- real-time half (runner only) ---

    @property
    def length_ms(self) -> int:
        if self._length_ms is None:
            self._length_ms = (self.min_ms if self.min_ms == self.max_ms
                               else random.randint(self.min_ms, self.max_ms))
        return self._length_ms

    def start(self) -> None:
        self._start_ns = time.monotonic_ns()

    @property
    def end_ns(self) -> int:
        assert self._start_ns is not None, "timer not started"
        return self._start_ns + self.length_ms * 1_000_000

    def is_due(self) -> bool:
        return time.monotonic_ns() >= self.end_ns

    def location_root_address(self) -> Address:
        return self.to.root_address()

    def __repr__(self) -> str:
        return f"Timer(-> {self.to}, {self.timer!r})"


Event = Union[MessageEnvelope, TimerEnvelope]
