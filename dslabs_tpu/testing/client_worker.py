"""ClientWorker: the workload-driving wrapper node around a student Client.

Re-design of framework/tst/.../ClientWorker.java:53-310.  The worker *is* a
Node at the client's address; it interposes on the framework delivery entry
points, forwards them to the wrapped client node, and after every delivery
pumps ``send_next_command_while_possible``: collect an available result, check
it against the workload's expected result, and send the next command.

Critical semantics (SURVEY §7.8): **equality and hashing cover only
(client, results)** so that search states differing merely in bookkeeping
(sent-command lists, waiting flags) hash identically
(ClientWorker.java:49-52).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import List, Optional, Tuple

from dslabs_tpu.core.address import Address
from dslabs_tpu.core.node import Node, NodeConfig
from dslabs_tpu.core.types import Client, Command, Message, Result, Timer
from dslabs_tpu.testing.workload import Workload
from dslabs_tpu.utils.structural import clone

__all__ = ["ClientWorker", "InterRequestTimer"]


@dataclass(frozen=True)
class InterRequestTimer(Timer):
    """Private rate-limiting timer (ClientWorker.java:55)."""


class ClientWorker(Node):

    __deepcopy_skip__ = ("_config", "_sync", "_last_send_time", "_max_wait")

    def __init__(self, client, workload: Workload,
                 record_commands_and_results: bool = True):
        assert isinstance(client, Node) and isinstance(client, Client)
        super().__init__(client.address)
        self.client = client
        self.results: List[Result] = []
        # Clone the workload on creation to avoid sharing across workers
        # (ClientWorker.java:94-96).
        self._workload: Workload = clone(workload)
        self._workload.reset()
        self._record = record_commands_and_results
        self._initialized = False
        self._waiting_on_result = False
        self._waiting_to_send = False
        self._last_command: Optional[Command] = None
        self._expected_result: Optional[Result] = None
        self._sent_commands: List[Command] = []
        self._results_ok = True
        self._expected_and_received: Optional[Tuple[Result, Result]] = None
        self._last_send_time: Optional[float] = None
        self._max_wait: Optional[Tuple[float, float]] = None  # (duration_s, send_time)
        self._sync: Optional[threading.Condition] = None

    # Equality = (client, results) ONLY (ClientWorker.java:49-52).
    def _eq_fields(self):
        return {"client": self.client, "results": self.results}

    # ------------------------------------------------------------- threading

    def _cond(self) -> threading.Condition:
        if self._sync is None:
            self._sync = threading.Condition()
        return self._sync

    def __getstate__(self):
        d = dict(self.__dict__)
        d["_sync"] = None
        d["_config"] = None
        return d

    def __setstate__(self, state):
        self.__dict__.update(state)

    # ------------------------------------------------------------ properties

    @property
    def workload(self) -> Workload:
        return self._workload

    @property
    def sent_commands(self) -> List[Command]:
        return self._sent_commands

    @property
    def record_commands_and_results(self) -> bool:
        return self._record

    def results_ok(self) -> Tuple[bool, Optional[str]]:
        if self._results_ok:
            return True, None
        exp, got = self._expected_and_received
        return False, f"expected {exp!r}, received {got!r}"

    @property
    def expected_and_received(self):
        return self._expected_and_received

    def add_command(self, command, result=None) -> None:
        with self._cond():
            self._workload.add(command, result)
            self._pump()

    # ------------------------------------------------------------- wait stats

    def max_wait(self, stop_time: Optional[float] = None) -> Optional[Tuple[float, float]]:
        """Longest observed wait (seconds) and the send time it corresponds
        to; includes the currently outstanding command up to ``stop_time``
        (ClientWorker.java:144-172)."""
        with self._cond():
            return self._max_wait_internal(stop_time if stop_time is not None
                                           else time.monotonic())

    def _max_wait_internal(self, ref: float):
        if not self._waiting_on_result or self._last_send_time is None:
            return self._max_wait
        current = ref - self._last_send_time
        if self._max_wait is not None and self._max_wait[0] >= current:
            return self._max_wait
        return (current, self._last_send_time)

    # ------------------------------------------------------------- the pump

    def _pump(self) -> None:
        """sendNextCommandWhilePossible (ClientWorker.java:174-235)."""
        if not self._initialized:
            return
        while True:
            if self._waiting_on_result and self.client.has_result():
                result = self.client.get_result()
                self._max_wait = self._max_wait_internal(time.monotonic())
                if self._record:
                    self._sent_commands.append(self._last_command)
                    self.results.append(result)
                if self._workload.has_results() and self._expected_result != result:
                    self._results_ok = False
                    if self._expected_and_received is None:
                        self._expected_and_received = (self._expected_result, result)
                self._waiting_on_result = False
                self._last_command = None
                self._expected_result = None

            if (self._waiting_on_result or self._waiting_to_send
                    or not self._workload.has_next()):
                break

            if self._workload.millis_between_requests > 0:
                self.set_timer(InterRequestTimer(),
                               self._workload.millis_between_requests)
                self._waiting_to_send = True
                break

            self._send_next_command()

        if self.done():
            self._cond().notify_all()

    def _send_next_command(self) -> None:
        if self._workload.has_results():
            cmd, res = self._workload.next_command_and_result(self.client.address)
            self._last_command, self._expected_result = cmd, res
        else:
            self._last_command = self._workload.next_command(self.client.address)
        self.client.send_command(self._last_command)
        self._waiting_to_send = False
        self._waiting_on_result = True
        self._last_send_time = time.monotonic()

    def done(self) -> bool:
        return not self._waiting_on_result and not self._workload.has_next()

    def wait_until_done(self, timeout_s: Optional[float] = None) -> None:
        deadline = None if timeout_s is None else time.monotonic() + timeout_s
        with self._cond():
            while not self.done():
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return
                self._cond().wait(remaining)

    # --------------------------------------------------- Node entry overrides

    def init(self) -> None:
        with self._cond():
            self._initialized = True
            self.client.init()
            self._pump()

    def deliver_message(self, message: Message, sender: Address,
                        destination: Optional[Address] = None) -> None:
        with self._cond():
            self.client.deliver_message(message, sender, destination)
            self._pump()

    def deliver_timer(self, timer: Timer,
                      destination: Optional[Address] = None) -> None:
        with self._cond():
            if isinstance(timer, InterRequestTimer):
                self._send_next_command()
            else:
                self.client.deliver_timer(timer, destination)
            self._pump()

    def config(self, cfg: NodeConfig) -> None:
        # Both the worker (for InterRequestTimer) and the wrapped client share
        # the engine hooks (ClientWorker.java:293-309).
        super().config(cfg)
        self.client.config(cfg)
