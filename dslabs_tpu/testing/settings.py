"""Base test settings: invariants, time limits, network connectivity matrix.

Re-design of framework/tst/.../TestSettings.java:46-269.  Settings *gate
events*, never mutate state (SURVEY §7.7): the same state can be re-searched
under different settings (staged search).

``should_deliver`` resolution priority (TestSettings.java:224-245):
  per-link override  >  sender override  >  receiver override  >  global flag.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from dslabs_tpu.core.address import Address
from dslabs_tpu.testing.predicates import PredicateResult, StatePredicate

__all__ = ["TestSettings"]


class TestSettings:
    """Fluent, self-typed settings base shared by run and search settings."""

    def __init__(self):
        self.invariants: List[StatePredicate] = []
        self.max_time_secs: Optional[float] = None
        self.single_threaded: bool = False
        self.deliver_timers_default: bool = True
        self._timer_delivery: Dict[Address, bool] = {}
        # Connectivity: None = unset at that level
        self._link_active: Dict[Tuple[Address, Address], bool] = {}
        self._sender_active: Dict[Address, bool] = {}
        self._receiver_active: Dict[Address, bool] = {}
        self._network_active: bool = True

    # ------------------------------------------------------------- invariants

    def add_invariant(self, predicate: StatePredicate) -> "TestSettings":
        self.invariants.append(predicate)
        return self

    def clear_invariants(self) -> "TestSettings":
        self.invariants.clear()
        return self

    def invariants_hold(self, state) -> Optional[PredicateResult]:
        """Return None if all invariants hold, else the first failure.
        Invariant exceptions count as violations (TestSettings.java:130-138)."""
        for inv in self.invariants:
            r = inv.test(state, expected=True)
            if r is not None:
                return r
        return None

    def invariant_violated(self, state) -> Optional[PredicateResult]:
        return self.invariants_hold(state)

    # ------------------------------------------------------------------- time

    def max_time(self, secs: float) -> "TestSettings":
        self.max_time_secs = secs
        return self

    def set_single_threaded(self, value: bool = True) -> "TestSettings":
        self.single_threaded = value
        return self

    # ------------------------------------------------------------------ timers

    def deliver_timers(self, address_or_flag, value: Optional[bool] = None) -> "TestSettings":
        """``deliver_timers(False)`` gates all timers; ``deliver_timers(addr,
        False)`` gates one node's timers (TestSettings.java:76-94)."""
        if isinstance(address_or_flag, bool):
            self.deliver_timers_default = address_or_flag
            self._timer_delivery.clear()
        else:
            assert value is not None
            self._timer_delivery[address_or_flag] = value
        return self

    def clear_deliver_timers(self) -> "TestSettings":
        """Reset all per-address timer gating (TestSettings.java:94)."""
        self.deliver_timers_default = True
        self._timer_delivery.clear()
        return self

    def should_deliver_timer(self, to: Address) -> bool:
        return self._timer_delivery.get(to.root_address(),
                                        self.deliver_timers_default)

    # ---------------------------------------------------------------- network

    def network_active(self, active: bool = True) -> "TestSettings":
        self._network_active = active
        return self

    def link_active(self, frm: Address, to: Address, active: bool) -> "TestSettings":
        self._link_active[(frm.root_address(), to.root_address())] = active
        return self

    def sender_active(self, frm: Address, active: bool) -> "TestSettings":
        self._sender_active[frm.root_address()] = active
        return self

    def receiver_active(self, to: Address, active: bool) -> "TestSettings":
        self._receiver_active[to.root_address()] = active
        return self

    def node_active(self, address: Address, active: bool) -> "TestSettings":
        """Convenience: gate a node both as sender and receiver."""
        return self.sender_active(address, active).receiver_active(address, active)

    def partition(self, *addresses) -> "TestSettings":
        """Keep only links internal to the given partition: every node is
        deactivated as sender+receiver, then intra-partition links are
        re-activated (TestSettings.java:181-198)."""
        if len(addresses) == 1 and isinstance(addresses[0], (list, tuple, set)):
            addresses = tuple(addresses[0])
        part = [a.root_address() for a in addresses]
        self._network_active = False
        self._link_active.clear()
        self._sender_active.clear()
        self._receiver_active.clear()
        for a in part:
            for b in part:
                if a != b:
                    self._link_active[(a, b)] = True
        return self

    def reconnect(self) -> "TestSettings":
        """Clear all connectivity overrides (TestSettings.java:204-210)."""
        self._network_active = True
        self._link_active.clear()
        self._sender_active.clear()
        self._receiver_active.clear()
        return self

    def should_deliver(self, envelope) -> bool:
        """Connectivity check for a message envelope (TestSettings.java:224-245)."""
        frm = envelope.frm.root_address()
        to = envelope.to.root_address()
        link = self._link_active.get((frm, to))
        if link is not None:
            return link
        sender = self._sender_active.get(frm)
        if sender is not None:
            return sender
        receiver = self._receiver_active.get(to)
        if receiver is not None:
            return receiver
        return self._network_active
